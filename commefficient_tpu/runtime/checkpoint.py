"""Full-state checkpoint/resume for the federated runtime.

The reference only saves final weights (``torch.save(state_dict)``,
cv_train.py:420-423) and never optimizer/error state (SURVEY.md §5
"Checkpoint / resume: save-only"). Here a checkpoint captures the
complete round state:

- flat ``ps_weights``
- per-client ``ClientStates`` (velocities / errors / stale weights)
- server ``ServerState`` (virtual momentum + error, dense or
  sketch-shaped)
- round / update counters, byte-accounting state, optimizer step
  count, LR-scheduler position
- optionally the ``FedSampler``'s RNG state, so a resumed run
  continues the exact data order of an uninterrupted one

Format: a single ``np.savez_compressed`` archive with a JSON ``meta``
entry, written atomically (tmp + rename). Resume is bit-exact:
tests/test_checkpoint.py checks interrupted-and-resumed training
reproduces the uninterrupted run's weights exactly.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Optional

import jax
import numpy as np

from commefficient_tpu.core.rounds import ClientStates
from commefficient_tpu.core.server import ServerState

_FMT = 1


def checkpoint_file(directory: str, tag: str = "state") -> str:
    return os.path.join(directory, f"ckpt_{tag}.npz")


def _shard_file(path: str, process_index: int) -> str:
    """Side file holding a non-zero process's client-store shard."""
    return f"{path}.shard{int(process_index)}.npz"


def _atomic_savez(path: str, **arrays):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def save_checkpoint(path: str, model, opt, scheduler=None,
                    sampler=None, epoch: int = 0,
                    extra: Optional[dict] = None,
                    loader=None, mid_epoch: bool = False) -> str:
    """Serialise the full runtime state to ``path`` (.npz).

    ``mid_epoch=True`` (the round-cadence autosaver) additionally
    captures the sampler's LIVE epoch state — permutation, per-client
    cursors, the lookahead's buffered round spec and the post-draw
    RNG — so a resumed run continues the interrupted epoch's
    remaining rounds bit-exactly instead of restarting the epoch.
    Epoch-boundary saves must NOT set it: their exhausted iterator
    state would make the resumed epoch yield zero rounds."""
    if getattr(model, "_inflight", None):
        # flushing here would drop the flushed rounds' metrics and
        # desync the trainer's pending queue — the caller must drain
        raise RuntimeError("checkpoint requested with pipelined rounds "
                           "inflight; drain with model.flush(force="
                           "True) (the trainers do this at epoch end)")
    # _host, not device_get: on a multi-process mesh the per-client
    # state rows are sharded across processes and not fully addressable
    # — process_allgather (a collective every process must reach)
    # reassembles the global rows; replicated arrays pass through
    from commefficient_tpu.runtime.fed_model import _host

    if getattr(model, "client_store", None) is not None:
        # host client store: land any round still awaiting write-back
        # so the store snapshot below is complete
        model._store_writeback()

    # checkpoint save is a deliberate full sync OFF the round hot
    # path (epoch cadence): materialising state here is the point,
    # and no telemetry round record is open to attribute it to
    arrays = {"ps_weights": _host(model.ps_weights)}  # audit: allow(host-sync)
    cs = model.client_states
    for name, val in (("cs_velocities", cs.velocities),
                      ("cs_errors", cs.errors),
                      ("cs_weights", cs.weights)):
        if val is not None:
            arrays[name] = _host(val)  # audit: allow(host-sync)
    ss = opt.server_state
    arrays["ss_Vvelocity"] = _host(ss.Vvelocity)  # audit: allow(host-sync)
    arrays["ss_Verror"] = _host(ss.Verror)  # audit: allow(host-sync)
    arrays["last_updated"] = model.last_updated
    arrays["client_last_seen"] = model.client_last_seen
    if getattr(model, "model_state", None) is not None:
        # BatchNorm running stats: flatten the pytree with stable,
        # path-derived keys
        from jax.tree_util import keystr, tree_flatten_with_path
        leaves, _ = tree_flatten_with_path(model.model_state)
        for leaf_path, leaf in leaves:
            # audit: allow(host-sync) — same checkpoint-save sync
            arrays["bnstats:" + keystr(leaf_path)] = _host(leaf)

    meta = {
        "format": _FMT,
        "epoch": int(epoch),
        "round_index": int(model.round_index),
        "update_round": int(model._update_round),
        "fedavg_lr": float(model.fedavg_lr),
        "opt_step_count": int(opt._step_count),
        "mode": model.args.mode,
        "grad_size": int(model.args.grad_size),
        "num_clients": int(model.num_clients),
        "transmit_shape": list(model.args.transmit_shape),
        "error_type": model.args.error_type,
        "extra": extra or {},
    }
    if model.args.mode == "sketch":
        # the RESOLVED rotation granularity, not the -1 sentinel: a
        # sketch-space error table decoded under a different rotation
        # stream is silent corruption, and auto (-1) re-resolves per
        # platform — so resume validates the resolved value
        from commefficient_tpu.core.rounds import resolve_rot_lanes
        meta["rot_lanes"] = int(resolve_rot_lanes(model.args))
    store = getattr(model, "client_store", None)
    if store is not None:
        # sparse store snapshot: only the rows clients actually wrote
        # (plus each field's init row, so never-seen clients replay the
        # ORIGINAL run's init on resume). Process 0's shard rides in
        # the main archive; every other process writes its own side
        # file next to it (its rows are not addressable from here).
        meta["clientstore"] = {"fields": list(store.field_names),
                               "processes": int(jax.process_count())}
        shard = store.export_shard()
        if jax.process_index() == 0:
            for k, v in shard.items():
                arrays["store:" + k] = v
        else:
            _atomic_savez(_shard_file(path, jax.process_index()),
                          **shard)
    if scheduler is not None:
        meta["scheduler_step"] = int(scheduler._step)
    if sampler is not None and hasattr(sampler.rng, "get_state"):
        state = sampler.rng.get_state()
        meta["sampler_rng"] = [state[0], None, int(state[2]),
                               int(state[3]), float(state[4])]
        arrays["sampler_rng_keys"] = np.asarray(state[1])
    # datasets with stateful per-item RNG (e.g. FedPERSONA's
    # personality shuffles) advance it on every access — capture it or
    # a resumed epoch sees different records than the uninterrupted run
    ds = getattr(sampler, "dataset", None)
    ds_rng = getattr(ds, "_rng", None)
    if ds_rng is not None and hasattr(ds_rng, "getstate"):
        version, internal, gauss = ds_rng.getstate()
        meta["dataset_rng"] = [int(version), gauss]
        arrays["dataset_rng_state"] = np.asarray(internal, np.int64)
    # the CV transform stacks draw from the GLOBAL numpy RNG — capture
    # it too, or augmentation replays from the re-seeded stream after
    # resume while the uninterrupted run's stream had advanced
    g = np.random.get_state()
    meta["np_global_rng"] = [g[0], None, int(g[2]), int(g[3]),
                             float(g[4])]
    arrays["np_global_rng_keys"] = np.asarray(g[1])
    # the native data-plane derives per-round augmentation seeds from
    # its round counter
    if loader is not None and hasattr(loader, "_round_counter"):
        meta["loader_round_counter"] = int(loader._round_counter)
    # --dropout_prob draws from the loader's own RNG stream every
    # round — capture it or a resumed run replays drops from the
    # re-seeded stream while the uninterrupted run's had advanced
    dr = getattr(loader, "_dropout_rng", None)
    if dr is not None and hasattr(dr, "get_state"):
        g = dr.get_state()
        meta["dropout_rng"] = [g[0], None, int(g[2]), int(g[3]),
                               float(g[4])]
        arrays["dropout_rng_keys"] = np.asarray(g[1])
    if mid_epoch and sampler is not None \
            and hasattr(sampler, "export_state"):
        st = sampler.export_state()
        if st is not None:
            meta["sampler_mid_epoch"] = True
            arrays["sampler_mid_permuted"] = np.asarray(st["permuted"])
            arrays["sampler_mid_cur"] = np.asarray(st["cur"])
            if st.get("rng_state") is not None:
                rs = st["rng_state"]
                meta["sampler_mid_rng"] = [rs[0], None, int(rs[2]),
                                           int(rs[3]), float(rs[4])]
                arrays["sampler_mid_rng_keys"] = np.asarray(rs[1])
            if st.get("spec_workers") is not None:
                arrays["sampler_mid_spec_workers"] = st["spec_workers"]
                arrays["sampler_mid_spec_sizes"] = st["spec_sizes"]
                arrays["sampler_mid_spec_idx"] = st["spec_idx"]

    # every process gathered (the allgathers above are collectives)
    # but exactly one writes — concurrent writers on a shared
    # filesystem would corrupt the archive
    err = None
    if jax.process_index() == 0:
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(path) or ".", suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    np.savez_compressed(f, meta=json.dumps(meta),
                                        **arrays)
                os.replace(tmp, path)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
        except BaseException as e:
            # don't raise yet: the peers are headed into the barrier
            # below, and abandoning it would turn a local I/O error
            # into a pod-wide hang
            err = e
    if jax.process_count() > 1:
        # barrier + failure broadcast: nobody proceeds (or resumes
        # from this path) until the writer finished, and a write
        # failure on process 0 fails every process with the real
        # reason instead of a heartbeat timeout. The broadcast is
        # one-sided: if a NON-zero process dies before reaching it
        # (e.g. in its local gather/serialization above), process 0
        # blocks here until the distributed runtime's collective
        # timeout fires — the general failure mode of any collective,
        # bounded and attributed by that timeout rather than by this
        # layer
        from jax.experimental import multihost_utils
        ok = multihost_utils.broadcast_one_to_all(
            np.int32(0 if err is None else 1))
        if int(ok) and err is None:
            raise RuntimeError(
                f"checkpoint write failed on process 0 ({path})")
    if err is not None:
        raise err
    return path


def load_checkpoint(path: str, model, opt, scheduler=None,
                    sampler=None, loader=None) -> dict:
    """Restore runtime state in place; returns the meta dict (use
    ``meta["epoch"]`` as the resume epoch)."""
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        checks = [("format", _FMT),
                  ("grad_size", int(model.args.grad_size)),
                  ("mode", model.args.mode),
                  ("num_clients", int(model.num_clients))]
        if "transmit_shape" in meta:  # sketch geometry etc.
            checks.append(("transmit_shape",
                           list(model.args.transmit_shape)))
            checks.append(("error_type", model.args.error_type))
        if model.args.mode == "sketch":
            # an absent key is a pre-round-5 checkpoint, written when
            # the default was 0 (full granularity) — it must still
            # refuse a run whose auto default now resolves nonzero,
            # not skip the check
            from commefficient_tpu.core.rounds import resolve_rot_lanes
            got = int(meta.get("rot_lanes", 0))
            want = int(resolve_rot_lanes(model.args))
            if got != want:
                raise ValueError(
                    f"checkpoint rot_lanes={got} does not match "
                    f"this run's {want} ({path})")
        for key, want in checks:
            if meta[key] != want:
                raise ValueError(
                    f"checkpoint {key}={meta[key]!r} does not match "
                    f"this run's {want!r} ({path})")
        # the set of client-state buffers is determined by the config
        # (local momentum / local error / topk_down) — a presence
        # mismatch means the hyperparameters changed, and silently
        # keeping fresh zeros would diverge from the saved trajectory.
        # Derived from the CONFIG (not model.client_states) so it
        # holds for both placements: a host-store run keeps the device
        # arrays None and records its fields in meta instead.
        ck_store = meta.get("clientstore")
        ck_fields = set((ck_store or {}).get("fields", []))
        uses = {
            "velocities": model.args.local_momentum > 0,
            "errors": model.args.error_type == "local",
            "weights": bool(getattr(model.args, "do_topk_down",
                                    False)),
        }
        for field, used in uses.items():
            has = ("cs_" + field in z.files) or (field in ck_fields)
            if has != used:
                raise ValueError(
                    f"checkpoint {'has' if has else 'lacks'} "
                    f"client {field} but this run "
                    f"{'does not use' if not used else 'needs'} them "
                    "— momentum/error/topk_down flags differ")

        import jax.numpy as jnp

        from commefficient_tpu.parallel.mesh import client_sharding

        # per-client state rows were sharded over the clients axis at
        # init (FedModel.__init__) — restore with the same placement.
        # Row padding depends on the mesh size, so a checkpoint taken
        # on a different device count is repadded here (padded rows
        # hold no information: client ids never index them).
        from commefficient_tpu.parallel.mesh import padded_rows

        csh = client_sharding(model.mesh)
        nc = int(model.num_clients)
        rows = padded_rows(nc, model.mesh)

        def put_client_rows(arr):
            arr = np.asarray(arr)[:nc]
            if arr.shape[0] < rows:
                pad = np.zeros((rows - arr.shape[0],) + arr.shape[1:],
                               arr.dtype)
                arr = np.concatenate([arr, pad])
            # device_put straight from host numpy: transfers each
            # shard to its device without a replicated stopover
            return jax.device_put(arr, csh)

        model.ps_weights = jnp.asarray(z["ps_weights"])
        store = getattr(model, "client_store", None)
        if store is not None:
            # this run keeps client state in the host store
            if ck_store is not None:
                if int(ck_store.get("processes", 1)) != \
                        jax.process_count():
                    raise ValueError(
                        "clientstore checkpoint written by "
                        f"{ck_store.get('processes')} processes; this "
                        f"run has {jax.process_count()} — shard "
                        "ownership would not line up")
                if jax.process_index() == 0:
                    shard = {k[len("store:"):]: np.asarray(z[k])
                             for k in z.files if k.startswith("store:")}
                else:
                    with np.load(_shard_file(path, jax.process_index()),
                                 allow_pickle=False) as sz:
                        shard = {k: np.asarray(sz[k])
                                 for k in sz.files}
                store.import_shard(shard)
            else:
                # dense (device-placement) checkpoint: import every
                # client's row into the store
                nc0 = int(model.num_clients)
                shard = {"ids": np.arange(nc0, dtype=np.int64)}
                for field in store.field_names:
                    shard[field] = np.asarray(z["cs_" + field])[:nc0]
                store.import_shard(shard)
            model.client_states = ClientStates(None, None, None)
        elif ck_fields:
            # host-store checkpoint into a device-placement run:
            # densify each shard over the init rows
            if int(ck_store.get("processes", 1)) != 1:
                raise ValueError(
                    "cannot densify a multi-process clientstore "
                    "checkpoint into device placement")

            def densify(field):
                if field not in ck_fields:
                    return None
                ids = np.asarray(z["store:ids"], np.int64)
                rows_f = np.asarray(z["store:" + field])
                shape = (int(model.num_clients),) + rows_f.shape[1:]
                init_key = "store:init:" + field
                if init_key in z.files:
                    base = np.broadcast_to(np.asarray(z[init_key]),
                                           shape).copy()
                else:
                    base = np.zeros(shape, np.float32)
                base[ids] = rows_f
                return put_client_rows(base)

            model.client_states = ClientStates(densify("velocities"),
                                               densify("errors"),
                                               densify("weights"))
        else:
            cs = model.client_states
            model.client_states = ClientStates(
                put_client_rows(z["cs_velocities"])
                if "cs_velocities" in z else cs.velocities,
                put_client_rows(z["cs_errors"])
                if "cs_errors" in z else cs.errors,
                put_client_rows(z["cs_weights"])
                if "cs_weights" in z else cs.weights,
            )
        opt.server_state = ServerState(jnp.asarray(z["ss_Vvelocity"]),
                                       jnp.asarray(z["ss_Verror"]))
        model.last_updated = np.asarray(z["last_updated"])
        model.client_last_seen = np.asarray(z["client_last_seen"])
        if getattr(model, "model_state", None) is not None:
            from jax.tree_util import keystr, tree_flatten_with_path
            leaves, treedef = tree_flatten_with_path(model.model_state)
            if not any(k.startswith("bnstats:") for k in z.files):
                # checkpoint written by a BN-free build (or before
                # running stats existed): keep the fresh init stats
                # rather than refusing the whole restore — weights and
                # optimizer state are still bit-exact, only the running
                # statistics restart their blend
                import warnings
                warnings.warn(
                    "checkpoint has no BN running stats "
                    "(pre-batchnorm format); resuming with freshly "
                    "initialised statistics")
            else:
                restored = []
                for path, leaf in leaves:
                    key = "bnstats:" + keystr(path)
                    if key not in z.files:
                        raise ValueError(
                            f"checkpoint lacks BN running stats {key} "
                            "but this run tracks them")
                    restored.append(jnp.asarray(z[key]))
                model.model_state = jax.tree_util.tree_unflatten(
                    treedef, restored)
        model.round_index = meta["round_index"]
        model._update_round = meta["update_round"]
        model._rebuild_round_counts()
        model.fedavg_lr = meta["fedavg_lr"]
        opt._step_count = meta["opt_step_count"]
        if scheduler is not None and "scheduler_step" in meta:
            scheduler._step = meta["scheduler_step"]
        if sampler is not None and "sampler_rng" in meta:
            s = meta["sampler_rng"]
            sampler.rng.set_state((s[0], np.asarray(z["sampler_rng_keys"]),
                                   s[2], s[3], s[4]))
        ds = getattr(sampler, "dataset", None)
        ds_rng = getattr(ds, "_rng", None)
        if ds_rng is not None and "dataset_rng" in meta:
            version, gauss = meta["dataset_rng"]
            internal = tuple(int(v) for v in z["dataset_rng_state"])
            ds_rng.setstate((version, internal, gauss))
        if "np_global_rng" in meta:
            g = meta["np_global_rng"]
            np.random.set_state((g[0],
                                 np.asarray(z["np_global_rng_keys"]),
                                 g[2], g[3], g[4]))
        if loader is not None and "loader_round_counter" in meta \
                and hasattr(loader, "_round_counter"):
            loader._round_counter = meta["loader_round_counter"]
        dr = getattr(loader, "_dropout_rng", None)
        if dr is not None and "dropout_rng" in meta \
                and hasattr(dr, "set_state"):
            g = meta["dropout_rng"]
            dr.set_state((g[0], np.asarray(z["dropout_rng_keys"]),
                          g[2], g[3], g[4]))
        if sampler is not None and meta.get("sampler_mid_epoch") \
                and hasattr(sampler, "import_state"):
            st = {"permuted": np.asarray(z["sampler_mid_permuted"]),
                  "cur": np.asarray(z["sampler_mid_cur"])}
            if "sampler_mid_rng" in meta:
                r = meta["sampler_mid_rng"]
                st["rng_state"] = (
                    r[0], np.asarray(z["sampler_mid_rng_keys"]),
                    r[2], r[3], r[4])
            if "sampler_mid_spec_workers" in z.files:
                st["spec_workers"] = np.asarray(
                    z["sampler_mid_spec_workers"])
                st["spec_sizes"] = np.asarray(
                    z["sampler_mid_spec_sizes"])
                st["spec_idx"] = np.asarray(z["sampler_mid_spec_idx"])
            sampler.import_state(st)
    return meta


def history_file(directory: str, tag: str, round_index: int) -> str:
    """A retained autosave snapshot's path (round-stamped)."""
    return os.path.join(directory,
                        f"ckpt_{tag}_r{int(round_index):08d}.npz")


class RoundAutosaver:
    """``--checkpoint_every_rounds`` round-cadence autosave.

    Called from the trainers' round loop after every completed round.
    Saves a ``mid_epoch`` checkpoint at the configured cadence —
    skipping rounds whose pipelined dispatches are still inflight
    (forcing a drain on the hot path would serialise the pipeline;
    the next eligible round retries) — then retains up to
    ``--checkpoint_keep`` round-stamped history snapshots via
    hardlinks to the just-written archive (zero copy cost; falls
    back to a copy on link-hostile filesystems) and prunes the
    oldest beyond the budget. A SIGTERM at any point leaves either
    the previous or the new checkpoint intact — never a torn one
    (the save itself is tmp+rename atomic)."""

    def __init__(self, args, model, opt, scheduler, sampler, loader,
                 tag: str):
        self.every = int(getattr(args, "checkpoint_every_rounds", 0)
                         or 0)
        self.keep = int(getattr(args, "checkpoint_keep", 0) or 0)
        self.args = args
        self.model, self.opt, self.scheduler = model, opt, scheduler
        self.sampler, self.loader, self.tag = sampler, loader, tag
        self.path = checkpoint_file(args.checkpoint_path, tag)
        self._last_saved = -1

    def __call__(self, epoch: int):
        """``epoch``: the 0-based epoch currently in progress (a
        mid-epoch resume re-enters this same epoch)."""
        if self.every <= 0:
            return
        r = int(self.model.round_index)
        if r <= 0 or r % self.every or r == self._last_saved:
            return
        if getattr(self.model, "_inflight", None):
            return
        save_checkpoint(self.path, self.model, self.opt,
                        self.scheduler, self.sampler, epoch=int(epoch),
                        loader=self.loader, mid_epoch=True)
        self._last_saved = r
        if self.keep > 0 and jax.process_index() == 0:
            self._retain(r)

    def _retain(self, round_index: int):
        import re
        import shutil
        hist = history_file(self.args.checkpoint_path, self.tag,
                            round_index)
        if not os.path.exists(hist):
            try:
                os.link(self.path, hist)
            except OSError:
                shutil.copy2(self.path, hist)
        pat = re.compile(
            rf"^ckpt_{re.escape(self.tag)}_r(\d+)\.npz$")
        snaps = sorted(
            (int(m.group(1)), m.group(0))
            for m in (pat.match(n) for n in
                      os.listdir(self.args.checkpoint_path))
            if m)
        for _, name in snaps[:-self.keep]:
            try:
                os.unlink(os.path.join(self.args.checkpoint_path,
                                       name))
            except OSError:
                pass


def setup_resume(args, model, opt, scheduler, loader, tag: str):
    """Shared trainer wiring: returns
    ``(start_epoch, epoch_hook, round_hook)``.

    - ``--resume`` requires ``--checkpoint`` and an existing file —
      anything else raises instead of silently training from scratch
      (and then overwriting the directory's checkpoints).
    - ``epoch_hook`` saves every ``--checkpoint_every`` epochs and at
      the end of training.
    - ``round_hook(epoch)`` is the :class:`RoundAutosaver` when
      ``--checkpoint_every_rounds`` is set (None otherwise); the
      trainers call it after every completed round.
    """
    import math

    if not (args.do_checkpoint or args.do_resume):
        return 0, None, None
    if args.do_resume and not args.do_checkpoint:
        raise ValueError("--resume requires --checkpoint")
    path = checkpoint_file(args.checkpoint_path, tag)
    sampler = getattr(loader, "sampler", None)
    start_epoch = 0
    if args.do_resume:
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"--resume: no checkpoint at {path}")
        meta = load_checkpoint(path, model, opt, scheduler, sampler,
                               loader)
        start_epoch = meta["epoch"]
        print(f"resumed from {path} at epoch {start_epoch}"
              + (" (mid-epoch)" if meta.get("sampler_mid_epoch")
                 else ""))

    def epoch_hook(ep):
        if (args.checkpoint_every
                and ep % args.checkpoint_every == 0) \
                or ep >= math.ceil(args.num_epochs):
            save_checkpoint(path, model, opt, scheduler, sampler,
                            epoch=ep, loader=loader)

    round_hook = None
    if int(getattr(args, "checkpoint_every_rounds", 0) or 0) > 0:
        round_hook = RoundAutosaver(args, model, opt, scheduler,
                                    sampler, loader, tag)
    return start_epoch, epoch_hook, round_hook
