"""Full-state checkpoint/resume for the federated runtime.

The reference only saves final weights (``torch.save(state_dict)``,
cv_train.py:420-423) and never optimizer/error state (SURVEY.md §5
"Checkpoint / resume: save-only"). Here a checkpoint captures the
complete round state:

- flat ``ps_weights``
- per-client ``ClientStates`` (velocities / errors / stale weights)
- server ``ServerState`` (virtual momentum + error, dense or
  sketch-shaped)
- round / update counters, byte-accounting state, optimizer step
  count, LR-scheduler position
- optionally the ``FedSampler``'s RNG state, so a resumed run
  continues the exact data order of an uninterrupted one

Format: a single ``np.savez_compressed`` archive with a JSON ``meta``
entry, written atomically (tmp + rename). Resume is bit-exact:
tests/test_checkpoint.py checks interrupted-and-resumed training
reproduces the uninterrupted run's weights exactly.

Checkpoints are TOPOLOGY-PORTABLE (the elastic-pod contract,
tests/test_elastic.py): every state buffer is saved as the full host
array, so restore re-places it under the CURRENT run's mesh and
process count — server momentum/EF columns reshard through
``parallel/mesh.server_state_sharding``, client rows repad through
``padded_rows``, multi-process clientstore side shards merge and
re-split by the new ownership ranges, and the asyncfed arrival
backlog is rebuilt entry for entry. ``meta["topology"]`` /
``meta["segments"]`` record the lineage so manifests (and the perf
gate) can tell a resized run from an unbroken one.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import warnings
import zipfile
from typing import Optional

import jax
import numpy as np

from commefficient_tpu.core.rounds import ClientStates
from commefficient_tpu.core.server import ServerState
from commefficient_tpu.parallel.mesh import mesh_shape_dict

_FMT = 1


class TornCheckpointError(ValueError):
    """A checkpoint archive (main or side shard) is missing,
    truncated or otherwise unreadable. Carries the offending file's
    path in the message so an operator knows exactly which shard to
    recover; ``setup_resume`` catches it and falls back to the newest
    retained autosave that still validates."""


def checkpoint_file(directory: str, tag: str = "state") -> str:
    return os.path.join(directory, f"ckpt_{tag}.npz")


def _shard_file(path: str, process_index: int) -> str:
    """Side file holding a non-zero process's client-store shard."""
    return f"{path}.shard{int(process_index)}.npz"


def _atomic_savez(path: str, **arrays):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _verify_archive(path: str) -> None:
    """Refuse a torn/truncated .npz with an error NAMING the file.
    The atomic tmp+rename write means a torn archive normally cannot
    exist, but a shared filesystem hiccup, a partial copy, or a side
    shard orphaned by a dead process can still leave one — and
    np.load's failure mode on those is an opaque zipfile traceback
    halfway through restore."""
    if not os.path.exists(path):
        raise TornCheckpointError(
            f"checkpoint shard missing: {path}")
    try:
        with zipfile.ZipFile(path) as zf:
            bad = zf.testzip()  # CRC-checks every member
        if bad is not None:
            raise TornCheckpointError(
                f"checkpoint shard {path} is torn: member {bad!r} "
                "fails its CRC")
    except TornCheckpointError:
        raise
    except (zipfile.BadZipFile, OSError, EOFError) as e:
        raise TornCheckpointError(
            f"checkpoint shard {path} is torn/truncated: {e}") from e


def validate_checkpoint(path: str) -> dict:
    """Verify the main archive AND every side shard its meta records,
    returning the meta dict. Restore calls this first so a torn shard
    is reported by name before any state is touched, instead of
    crashing mid-resume with half the model restored."""
    _verify_archive(path)
    try:
        with np.load(path, allow_pickle=False) as z:
            if "meta" not in z.files:
                raise TornCheckpointError(
                    f"checkpoint {path} has no meta entry — torn or "
                    "not a checkpoint archive")
            meta = json.loads(str(z["meta"]))
    except TornCheckpointError:
        raise
    except (ValueError, OSError, EOFError) as e:
        raise TornCheckpointError(
            f"checkpoint {path} is unreadable: {e}") from e
    procs = int((meta.get("clientstore") or {}).get("processes", 1))
    for k in range(1, procs):
        _verify_archive(_shard_file(path, k))
    return meta


def current_topology(mesh=None) -> dict:
    """This run's restore-relevant topology, stamped into checkpoint
    meta segments and registry manifests: the counts whose change
    triggers the migration paths in load_checkpoint."""
    topo = {"device_count": int(jax.device_count()),
            "process_count": int(jax.process_count())}
    ms = mesh_shape_dict(mesh)
    if ms is not None:
        topo["mesh_shape"] = ms
    return topo


def resume_manifest_extra(model) -> dict:
    """Registry stamps for a resumed run: ``resumed_from`` (the
    checkpoint this run restored) and ``topology_segments`` (one
    entry per topology the lineage has run under, the restored chain
    plus the current segment). Empty for unresumed runs, so trainers
    can unconditionally splat it into ``maybe_write_manifest``'s
    extra. The perf gate refuses to resolve a pin when the segments
    span more than one topology (telemetry/registry.py
    run_topology_changed)."""
    info = getattr(model, "_resume_info", None)
    if not info:
        return {}
    segments = list(getattr(model, "_restored_segments", []))
    segments.append({**current_topology(model.mesh),
                     "round_index": int(model.round_index)})
    return {"resumed_from": dict(info),
            "topology_segments": segments}


def _prune_stale_shards(path: str, processes: int) -> None:
    """Drop side shard files whose index is >= the writing process
    count: they were left by a LARGER previous topology, the meta
    just written no longer records them, and a later resume on yet
    another process count must not merge rows from the dead layout."""
    base = os.path.basename(path)
    pat = re.compile(re.escape(base) + r"\.shard(\d+)\.npz$")
    d = os.path.dirname(path) or "."
    for name in os.listdir(d):
        m = pat.fullmatch(name)
        if m and int(m.group(1)) >= int(processes):
            try:
                os.unlink(os.path.join(d, name))
            except OSError:
                pass


def _merged_store_shard(path: str, z, processes: int) -> dict:
    """Merge every writing process's sparse clientstore shard into
    one global shard: process 0's rows from the main archive plus
    each side file's. Ids are disjoint across shards (contiguous
    ownership ranges), so the merge is a concatenation; init rows are
    identical everywhere and taken first-seen. This is the
    topology-migration path — ``import_shard`` on the restoring side
    then keeps only the rows each NEW process owns."""
    shards = [{k[len("store:"):]: np.asarray(z[k])
               for k in z.files if k.startswith("store:")}]
    for k in range(1, int(processes)):
        sp = _shard_file(path, k)
        _verify_archive(sp)
        with np.load(sp, allow_pickle=False) as sz:
            shards.append({n: np.asarray(sz[n]) for n in sz.files})
    merged: dict = {}
    for sh in shards:
        for n, v in sh.items():
            if n.startswith("init:") and n not in merged:
                merged[n] = v
    merged["ids"] = np.concatenate(
        [np.asarray(sh.get("ids", np.zeros((0,), np.int64)), np.int64)
         for sh in shards])
    fields = sorted({n for sh in shards for n in sh
                     if n != "ids" and not n.startswith("init:")})
    for f in fields:
        parts = []
        for i, sh in enumerate(shards):
            if f not in sh:
                raise TornCheckpointError(
                    f"clientstore shard {i} of {path} lacks field "
                    f"{f!r} — partial shard set")
            parts.append(np.asarray(sh[f]))
        merged[f] = np.concatenate(parts)
    return merged


def save_checkpoint(path: str, model, opt, scheduler=None,
                    sampler=None, epoch: int = 0,
                    extra: Optional[dict] = None,
                    loader=None, mid_epoch: bool = False) -> str:
    """Serialise the full runtime state to ``path`` (.npz).

    ``mid_epoch=True`` (the round-cadence autosaver) additionally
    captures the sampler's LIVE epoch state — permutation, per-client
    cursors, the lookahead's buffered round spec and the post-draw
    RNG — so a resumed run continues the interrupted epoch's
    remaining rounds bit-exactly instead of restarting the epoch.
    Epoch-boundary saves must NOT set it: their exhausted iterator
    state would make the resumed epoch yield zero rounds."""
    if getattr(model, "_inflight", None):
        # flushing here would drop the flushed rounds' metrics and
        # desync the trainer's pending queue — the caller must drain
        raise RuntimeError("checkpoint requested with pipelined rounds "
                           "inflight; drain with model.flush(force="
                           "True) (the trainers do this at epoch end)")
    # _host, not device_get: on a multi-process mesh the per-client
    # state rows are sharded across processes and not fully addressable
    # — process_allgather (a collective every process must reach)
    # reassembles the global rows; replicated arrays pass through
    from commefficient_tpu.runtime.fed_model import _host

    if getattr(model, "client_store", None) is not None:
        # host client store: land any round still awaiting write-back
        # so the store snapshot below is complete
        model._store_writeback()

    # checkpoint save is a deliberate full sync OFF the round hot
    # path (epoch cadence): materialising state here is the point,
    # and no telemetry round record is open to attribute it to
    arrays = {"ps_weights": _host(model.ps_weights)}  # audit: allow(host-sync)
    cs = model.client_states
    for name, val in (("cs_velocities", cs.velocities),
                      ("cs_errors", cs.errors),
                      ("cs_weights", cs.weights)):
        if val is not None:
            arrays[name] = _host(val)  # audit: allow(host-sync)
    ss = opt.server_state
    arrays["ss_Vvelocity"] = _host(ss.Vvelocity)  # audit: allow(host-sync)
    arrays["ss_Verror"] = _host(ss.Verror)  # audit: allow(host-sync)
    arrays["last_updated"] = model.last_updated
    arrays["client_last_seen"] = model.client_last_seen
    if getattr(model, "model_state", None) is not None:
        # BatchNorm running stats: flatten the pytree with stable,
        # path-derived keys
        from jax.tree_util import keystr, tree_flatten_with_path
        leaves, _ = tree_flatten_with_path(model.model_state)
        for leaf_path, leaf in leaves:
            # audit: allow(host-sync) — same checkpoint-save sync
            arrays["bnstats:" + keystr(leaf_path)] = _host(leaf)

    meta = {
        "format": _FMT,
        "epoch": int(epoch),
        "round_index": int(model.round_index),
        "update_round": int(model._update_round),
        "fedavg_lr": float(model.fedavg_lr),
        "opt_step_count": int(opt._step_count),
        "mode": model.args.mode,
        "grad_size": int(model.args.grad_size),
        "num_clients": int(model.num_clients),
        "transmit_shape": list(model.args.transmit_shape),
        "error_type": model.args.error_type,
        "extra": extra or {},
        # elastic-pod lineage: the topology this archive was written
        # under, plus the chain of earlier segments a resumed run
        # restored through — restore migrates placement whenever the
        # reader's topology differs, and manifests/perf-gate use the
        # segment list to refuse cross-topology pin resolution
        "topology": current_topology(getattr(model, "mesh", None)),
        "segments": (list(getattr(model, "_restored_segments", []))
                     + [{**current_topology(getattr(model, "mesh",
                                                    None)),
                         "round_index": int(model.round_index)}]),
    }
    if model.args.mode == "sketch":
        # the RESOLVED rotation granularity, not the -1 sentinel: a
        # sketch-space error table decoded under a different rotation
        # stream is silent corruption, and auto (-1) re-resolves per
        # platform — so resume validates the resolved value
        from commefficient_tpu.core.rounds import resolve_rot_lanes
        meta["rot_lanes"] = int(resolve_rot_lanes(model.args))
    store = getattr(model, "client_store", None)
    if store is not None:
        # sparse store snapshot: only the rows clients actually wrote
        # (plus each field's init row, so never-seen clients replay the
        # ORIGINAL run's init on resume). Process 0's shard rides in
        # the main archive; every other process writes its own side
        # file next to it (its rows are not addressable from here).
        meta["clientstore"] = {"fields": list(store.field_names),
                               "processes": int(jax.process_count())}
        shard = store.export_shard()
        if jax.process_index() == 0:
            for k, v in shard.items():
                arrays["store:" + k] = v
        else:
            _atomic_savez(_shard_file(path, jax.process_index()),
                          **shard)
        # asyncfed issue-round stamps: identical on every process
        # (stamp_rounds runs with the full cohort's ids everywhere),
        # so process 0's copy in the main archive covers the pod
        stamp_ids, stamp_rounds = store.export_stamps()
        if stamp_ids.size:
            arrays["store_stamp_ids"] = stamp_ids
            arrays["store_stamp_rounds"] = stamp_rounds
    drv = getattr(model, "_async_driver", None)
    if drv is not None:
        # the buffered-arrival backlog: without it a resumed async
        # run restarts with an empty queue and every in-flight
        # buffered round is silently dropped
        st = drv.export_state()
        meta["asyncfed"] = {
            "fold": st["fold"], "seq": st["seq"],
            "issued_total": st["issued_total"],
            "folded_total": st["folded_total"],
            "pending": int(st["arrive_at"].shape[0]),
            "slot_keys": list(st["slot_keys"]),
        }
        arrays["async_arrive_at"] = st["arrive_at"]
        arrays["async_issue_seq"] = st["issue_seq"]
        arrays["async_issue"] = st["issue"]
        for k, v in st["slots"].items():
            arrays["async:slot:" + k] = v
    acc = getattr(model, "_accountant", None)
    if acc is not None:
        # --dp sketch: the accountant's per-order RDP totals ride as
        # JSON floats (bit-exact round-trip), so a resumed run's ε
        # trajectory continues the unbroken run's exactly — the spent
        # budget survives preemption like every other piece of state
        meta["privacy"] = acc.state_dict()
    if scheduler is not None:
        meta["scheduler_step"] = int(scheduler._step)
    if sampler is not None and hasattr(sampler.rng, "get_state"):
        state = sampler.rng.get_state()
        meta["sampler_rng"] = [state[0], None, int(state[2]),
                               int(state[3]), float(state[4])]
        arrays["sampler_rng_keys"] = np.asarray(state[1])
    # datasets with stateful per-item RNG (e.g. FedPERSONA's
    # personality shuffles) advance it on every access — capture it or
    # a resumed epoch sees different records than the uninterrupted run
    ds = getattr(sampler, "dataset", None)
    ds_rng = getattr(ds, "_rng", None)
    if ds_rng is not None and hasattr(ds_rng, "getstate"):
        version, internal, gauss = ds_rng.getstate()
        meta["dataset_rng"] = [int(version), gauss]
        arrays["dataset_rng_state"] = np.asarray(internal, np.int64)
    # the CV transform stacks draw from the GLOBAL numpy RNG — capture
    # it too, or augmentation replays from the re-seeded stream after
    # resume while the uninterrupted run's stream had advanced
    g = np.random.get_state()
    meta["np_global_rng"] = [g[0], None, int(g[2]), int(g[3]),
                             float(g[4])]
    arrays["np_global_rng_keys"] = np.asarray(g[1])
    # the native data-plane derives per-round augmentation seeds from
    # its round counter
    if loader is not None and hasattr(loader, "_round_counter"):
        meta["loader_round_counter"] = int(loader._round_counter)
    # --dropout_prob draws from the loader's own RNG stream every
    # round — capture it or a resumed run replays drops from the
    # re-seeded stream while the uninterrupted run's had advanced
    dr = getattr(loader, "_dropout_rng", None)
    if dr is not None and hasattr(dr, "get_state"):
        g = dr.get_state()
        meta["dropout_rng"] = [g[0], None, int(g[2]), int(g[3]),
                               float(g[4])]
        arrays["dropout_rng_keys"] = np.asarray(g[1])
    if mid_epoch and sampler is not None \
            and hasattr(sampler, "export_state"):
        st = sampler.export_state()
        if st is not None:
            meta["sampler_mid_epoch"] = True
            arrays["sampler_mid_permuted"] = np.asarray(st["permuted"])
            arrays["sampler_mid_cur"] = np.asarray(st["cur"])
            if st.get("rng_state") is not None:
                rs = st["rng_state"]
                meta["sampler_mid_rng"] = [rs[0], None, int(rs[2]),
                                           int(rs[3]), float(rs[4])]
                arrays["sampler_mid_rng_keys"] = np.asarray(rs[1])
            if st.get("spec_workers") is not None:
                arrays["sampler_mid_spec_workers"] = st["spec_workers"]
                arrays["sampler_mid_spec_sizes"] = st["spec_sizes"]
                arrays["sampler_mid_spec_idx"] = st["spec_idx"]

    # every process gathered (the allgathers above are collectives)
    # but exactly one writes — concurrent writers on a shared
    # filesystem would corrupt the archive
    err = None
    if jax.process_index() == 0:
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(path) or ".", suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    np.savez_compressed(f, meta=json.dumps(meta),
                                        **arrays)
                os.replace(tmp, path)
                _prune_stale_shards(path, int(jax.process_count()))
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
        except BaseException as e:
            # don't raise yet: the peers are headed into the barrier
            # below, and abandoning it would turn a local I/O error
            # into a pod-wide hang
            err = e
    if jax.process_count() > 1:
        # barrier + failure broadcast: nobody proceeds (or resumes
        # from this path) until the writer finished, and a write
        # failure on process 0 fails every process with the real
        # reason instead of a heartbeat timeout. The broadcast is
        # one-sided: if a NON-zero process dies before reaching it
        # (e.g. in its local gather/serialization above), process 0
        # blocks here until the distributed runtime's collective
        # timeout fires — the general failure mode of any collective,
        # bounded and attributed by that timeout rather than by this
        # layer
        from jax.experimental import multihost_utils
        ok = multihost_utils.broadcast_one_to_all(
            np.int32(0 if err is None else 1))
        if int(ok) and err is None:
            raise RuntimeError(
                f"checkpoint write failed on process 0 ({path})")
    if err is not None:
        raise err
    return path


def load_checkpoint(path: str, model, opt, scheduler=None,
                    sampler=None, loader=None) -> dict:
    """Restore runtime state in place; returns the meta dict (use
    ``meta["epoch"]`` as the resume epoch).

    Topology-portable: the checkpoint may have been written on a
    different mesh shape, device count or process count — state is
    re-placed under THIS run's layout (values untouched, so a resized
    resume stays bit-exact against an unresized one)."""
    validate_checkpoint(path)
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        checks = [("format", _FMT),
                  ("grad_size", int(model.args.grad_size)),
                  ("mode", model.args.mode),
                  ("num_clients", int(model.num_clients))]
        if "transmit_shape" in meta:  # sketch geometry etc.
            checks.append(("transmit_shape",
                           list(model.args.transmit_shape)))
            checks.append(("error_type", model.args.error_type))
        if model.args.mode == "sketch":
            # an absent key is a pre-round-5 checkpoint, written when
            # the default was 0 (full granularity) — it must still
            # refuse a run whose auto default now resolves nonzero,
            # not skip the check
            from commefficient_tpu.core.rounds import resolve_rot_lanes
            got = int(meta.get("rot_lanes", 0))
            want = int(resolve_rot_lanes(model.args))
            if got != want:
                raise ValueError(
                    f"checkpoint rot_lanes={got} does not match "
                    f"this run's {want} ({path})")
        for key, want in checks:
            if meta[key] != want:
                raise ValueError(
                    f"checkpoint {key}={meta[key]!r} does not match "
                    f"this run's {want!r} ({path})")
        # the set of client-state buffers is determined by the config
        # (local momentum / local error / topk_down) — a presence
        # mismatch means the hyperparameters changed, and silently
        # keeping fresh zeros would diverge from the saved trajectory.
        # Derived from the CONFIG (not model.client_states) so it
        # holds for both placements: a host-store run keeps the device
        # arrays None and records its fields in meta instead.
        ck_store = meta.get("clientstore")
        ck_fields = set((ck_store or {}).get("fields", []))
        uses = {
            "velocities": model.args.local_momentum > 0,
            "errors": model.args.error_type == "local",
            "weights": bool(getattr(model.args, "do_topk_down",
                                    False)),
        }
        for field, used in uses.items():
            has = ("cs_" + field in z.files) or (field in ck_fields)
            if has != used:
                raise ValueError(
                    f"checkpoint {'has' if has else 'lacks'} "
                    f"client {field} but this run "
                    f"{'does not use' if not used else 'needs'} them "
                    "— momentum/error/topk_down flags differ")

        import jax.numpy as jnp

        from commefficient_tpu.parallel.mesh import (
            client_sharding, model_axis_size, padded_rows,
            server_state_sharding)

        # per-client state rows were sharded over the clients axis at
        # init (FedModel.__init__) — restore with the same placement.
        # Row padding depends on the mesh size, so a checkpoint taken
        # on a different device count is repadded here (padded rows
        # hold no information: client ids never index them).

        csh = client_sharding(model.mesh)
        nc = int(model.num_clients)
        rows = padded_rows(nc, model.mesh)

        def put_client_rows(arr):
            arr = np.asarray(arr)[:nc]
            if arr.shape[0] < rows:
                pad = np.zeros((rows - arr.shape[0],) + arr.shape[1:],
                               arr.dtype)
                arr = np.concatenate([arr, pad])
            # device_put straight from host numpy: transfers each
            # shard to its device without a replicated stopover
            return jax.device_put(arr, csh)

        model.ps_weights = jnp.asarray(z["ps_weights"])
        store = getattr(model, "client_store", None)
        if store is not None:
            # this run keeps client state in the host store
            if ck_store is not None:
                ck_procs = int(ck_store.get("processes", 1))
                if ck_procs == jax.process_count():
                    # same process count: shard files line up with
                    # ownership, each process imports exactly its own
                    if jax.process_index() == 0:
                        shard = {k[len("store:"):]: np.asarray(z[k])
                                 for k in z.files
                                 if k.startswith("store:")}
                    else:
                        sp = _shard_file(path, jax.process_index())
                        with np.load(sp, allow_pickle=False) as sz:
                            shard = {k: np.asarray(sz[k])
                                     for k in sz.files}
                else:
                    # topology-changing restore: the old shard split
                    # no longer matches this run's ownership ranges —
                    # merge every old process's sparse shard and let
                    # import_shard's write keep only the rows each
                    # NEW process owns (the placement-migration path)
                    shard = _merged_store_shard(path, z, ck_procs)
                store.import_shard(shard)
                if "store_stamp_ids" in z.files:
                    store.import_stamps(z["store_stamp_ids"],
                                        z["store_stamp_rounds"])
            else:
                # dense (device-placement) checkpoint: import every
                # client's row into the store
                nc0 = int(model.num_clients)
                shard = {"ids": np.arange(nc0, dtype=np.int64)}
                for field in store.field_names:
                    shard[field] = np.asarray(z["cs_" + field])[:nc0]
                store.import_shard(shard)
            model.client_states = ClientStates(None, None, None)
        elif ck_fields:
            # host-store checkpoint into a device-placement run:
            # merge all processes' sparse shards (the single-process
            # case merges trivially) and densify over the init rows
            merged = _merged_store_shard(
                path, z, int(ck_store.get("processes", 1)))

            def densify(field):
                if field not in ck_fields:
                    return None
                ids = np.asarray(merged["ids"], np.int64)
                rows_f = np.asarray(merged[field])
                shape = (int(model.num_clients),) + rows_f.shape[1:]
                init = merged.get("init:" + field)
                if init is not None:
                    base = np.broadcast_to(np.asarray(init),
                                           shape).copy()
                else:
                    base = np.zeros(shape, np.float32)
                base[ids] = rows_f
                return put_client_rows(base)

            model.client_states = ClientStates(densify("velocities"),
                                               densify("errors"),
                                               densify("weights"))
        else:
            cs = model.client_states
            model.client_states = ClientStates(
                put_client_rows(z["cs_velocities"])
                if "cs_velocities" in z else cs.velocities,
                put_client_rows(z["cs_errors"])
                if "cs_errors" in z else cs.errors,
                put_client_rows(z["cs_weights"])
                if "cs_weights" in z else cs.weights,
            )
        # server momentum/EF buffers: the archive holds the full host
        # table, so restoring onto a different CxM mesh is a pure
        # placement migration — device_put under the CURRENT mesh's
        # column sharding (values untouched, hence bit-exact vs an
        # unresized run). The <=1 model-axis case restores replicated,
        # exactly the layout FedOptimizer initialised.
        if model_axis_size(model.mesh) > 1:
            ssh = server_state_sharding(
                model.mesh, tuple(model.args.transmit_shape))
        else:
            ssh = None
        opt.server_state = ServerState.restore(
            np.asarray(z["ss_Vvelocity"]), np.asarray(z["ss_Verror"]),
            sharding=ssh)
        model.last_updated = np.asarray(z["last_updated"])
        model.client_last_seen = np.asarray(z["client_last_seen"])
        if getattr(model, "model_state", None) is not None:
            from jax.tree_util import keystr, tree_flatten_with_path
            leaves, treedef = tree_flatten_with_path(model.model_state)
            if not any(k.startswith("bnstats:") for k in z.files):
                # checkpoint written by a BN-free build (or before
                # running stats existed): keep the fresh init stats
                # rather than refusing the whole restore — weights and
                # optimizer state are still bit-exact, only the running
                # statistics restart their blend
                warnings.warn(
                    "checkpoint has no BN running stats "
                    "(pre-batchnorm format); resuming with freshly "
                    "initialised statistics")
            else:
                restored = []
                for leaf_path, leaf in leaves:
                    key = "bnstats:" + keystr(leaf_path)
                    if key not in z.files:
                        raise ValueError(
                            f"checkpoint lacks BN running stats {key} "
                            "but this run tracks them")
                    restored.append(jnp.asarray(z[key]))
                model.model_state = jax.tree_util.tree_unflatten(
                    treedef, restored)
        model.round_index = meta["round_index"]
        model._update_round = meta["update_round"]
        model._rebuild_round_counts()
        model.fedavg_lr = meta["fedavg_lr"]
        opt._step_count = meta["opt_step_count"]
        if scheduler is not None and "scheduler_step" in meta:
            scheduler._step = meta["scheduler_step"]
        if sampler is not None and "sampler_rng" in meta:
            s = meta["sampler_rng"]
            sampler.rng.set_state((s[0], np.asarray(z["sampler_rng_keys"]),
                                   s[2], s[3], s[4]))
        ds = getattr(sampler, "dataset", None)
        ds_rng = getattr(ds, "_rng", None)
        if ds_rng is not None and "dataset_rng" in meta:
            version, gauss = meta["dataset_rng"]
            internal = tuple(int(v) for v in z["dataset_rng_state"])
            ds_rng.setstate((version, internal, gauss))
        if "np_global_rng" in meta:
            g = meta["np_global_rng"]
            np.random.set_state((g[0],
                                 np.asarray(z["np_global_rng_keys"]),
                                 g[2], g[3], g[4]))
        if loader is not None and "loader_round_counter" in meta \
                and hasattr(loader, "_round_counter"):
            loader._round_counter = meta["loader_round_counter"]
        dr = getattr(loader, "_dropout_rng", None)
        if dr is not None and "dropout_rng" in meta \
                and hasattr(dr, "set_state"):
            g = meta["dropout_rng"]
            dr.set_state((g[0], np.asarray(z["dropout_rng_keys"]),
                          g[2], g[3], g[4]))
        if sampler is not None and meta.get("sampler_mid_epoch") \
                and hasattr(sampler, "import_state"):
            st = {"permuted": np.asarray(z["sampler_mid_permuted"]),
                  "cur": np.asarray(z["sampler_mid_cur"])}
            if "sampler_mid_rng" in meta:
                r = meta["sampler_mid_rng"]
                st["rng_state"] = (
                    r[0], np.asarray(z["sampler_mid_rng_keys"]),
                    r[2], r[3], r[4])
            if "sampler_mid_spec_workers" in z.files:
                st["spec_workers"] = np.asarray(
                    z["sampler_mid_spec_workers"])
                st["spec_sizes"] = np.asarray(
                    z["sampler_mid_spec_sizes"])
                st["spec_idx"] = np.asarray(z["sampler_mid_spec_idx"])
            sampler.import_state(st)

        # asyncfed backlog: rebuild the arrival heap + counters so
        # queued (in-flight) buffered rounds survive the resume
        drv = getattr(model, "_async_driver", None)
        ck_async = meta.get("asyncfed")
        if drv is not None and ck_async is not None:
            keys = list(ck_async.get("slot_keys", []))
            drv.import_state({
                "fold": ck_async["fold"], "seq": ck_async["seq"],
                "issued_total": ck_async["issued_total"],
                "folded_total": ck_async["folded_total"],
                "slot_keys": keys,
                "arrive_at": np.asarray(z["async_arrive_at"]),
                "issue_seq": np.asarray(z["async_issue_seq"]),
                "issue": np.asarray(z["async_issue"]),
                "slots": {k: np.asarray(z["async:slot:" + k])
                          for k in keys},
            })
        elif drv is not None:
            warnings.warn(
                "checkpoint has no asyncfed state (written by a "
                "synchronous or pre-elastic run); the arrival buffer "
                "resumes empty")
        elif ck_async is not None and int(ck_async.get("pending", 0)):
            raise ValueError(
                f"checkpoint holds {ck_async['pending']} queued async "
                "arrival(s) but this run is synchronous — resume with "
                "--async_buffer_size or the buffered rounds in flight "
                f"are dropped ({path})")

        # DP accountant: restore the spent-budget state bit-exactly.
        # Presence mismatches are hard decisions — a DP resume from a
        # DP-less checkpoint would silently RESET the spent ε to zero
        # (a privacy violation, not an inconvenience), so it refuses;
        # the reverse direction only drops observability and warns.
        ck_priv = meta.get("privacy")
        acc = getattr(model, "_accountant", None)
        if acc is not None and ck_priv is not None:
            from commefficient_tpu.privacy import PrivacyAccountant
            model._accountant = PrivacyAccountant.load_state(ck_priv)
        elif acc is not None:
            raise ValueError(
                "checkpoint has no privacy accountant state but this "
                "run is --dp sketch; resuming would reset the spent "
                f"ε budget to zero ({path})")
        elif ck_priv is not None:
            warnings.warn(
                "checkpoint carries a privacy accountant (written by "
                "a --dp sketch run) but this run has DP off; the "
                "spent-budget state is dropped")

        # lineage, for manifests (resume_manifest_extra) and the next
        # save's meta["segments"] chain
        model._restored_segments = list(
            meta.get("segments")
            or ([meta["topology"]] if meta.get("topology") else []))
        model._resume_info = {
            "checkpoint": os.path.abspath(path),
            "epoch": int(meta.get("epoch", 0)),
            "round_index": int(meta.get("round_index", 0)),
            "topology": meta.get("topology"),
        }
    return meta


def history_file(directory: str, tag: str, round_index: int) -> str:
    """A retained autosave snapshot's path (round-stamped)."""
    return os.path.join(directory,
                        f"ckpt_{tag}_r{int(round_index):08d}.npz")


class RoundAutosaver:
    """``--checkpoint_every_rounds`` round-cadence autosave.

    Called from the trainers' round loop after every completed round.
    Saves a ``mid_epoch`` checkpoint at the configured cadence —
    skipping rounds whose pipelined dispatches are still inflight
    (forcing a drain on the hot path would serialise the pipeline;
    the next eligible round retries) — then retains up to
    ``--checkpoint_keep`` round-stamped history snapshots via
    hardlinks to the just-written archive (zero copy cost; falls
    back to a copy on link-hostile filesystems) and prunes the
    oldest beyond the budget. A SIGTERM at any point leaves either
    the previous or the new checkpoint intact — never a torn one
    (the save itself is tmp+rename atomic)."""

    def __init__(self, args, model, opt, scheduler, sampler, loader,
                 tag: str):
        self.every = int(getattr(args, "checkpoint_every_rounds", 0)
                         or 0)
        self.keep = int(getattr(args, "checkpoint_keep", 0) or 0)
        self.args = args
        self.model, self.opt, self.scheduler = model, opt, scheduler
        self.sampler, self.loader, self.tag = sampler, loader, tag
        self.path = checkpoint_file(args.checkpoint_path, tag)
        self._last_saved = -1

    def __call__(self, epoch: int):
        """``epoch``: the 0-based epoch currently in progress (a
        mid-epoch resume re-enters this same epoch)."""
        if self.every <= 0:
            return
        r = int(self.model.round_index)
        if r <= 0 or r % self.every or r == self._last_saved:
            return
        if getattr(self.model, "_inflight", None):
            return
        save_checkpoint(self.path, self.model, self.opt,
                        self.scheduler, self.sampler, epoch=int(epoch),
                        loader=self.loader, mid_epoch=True)
        self._last_saved = r
        if self.keep > 0 and jax.process_index() == 0:
            self._retain(r)

    def _retain(self, round_index: int):
        import shutil

        def link(src, dst):
            if os.path.exists(dst) or not os.path.exists(src):
                return
            try:
                os.link(src, dst)
            except OSError:
                shutil.copy2(src, dst)

        hist = history_file(self.args.checkpoint_path, self.tag,
                            round_index)
        link(self.path, hist)
        # multi-process clientstore side shards retain WITH the main
        # archive — a fallback resume onto this snapshot must be able
        # to rebuild the store from the matching shard set
        for k in range(1, jax.process_count()):
            link(_shard_file(self.path, k), _shard_file(hist, k))
        pat = re.compile(
            rf"^ckpt_{re.escape(self.tag)}_r(\d+)\.npz$")
        snaps = sorted(
            (int(m.group(1)), m.group(0))
            for m in (pat.match(n) for n in
                      os.listdir(self.args.checkpoint_path))
            if m)
        for _, name in snaps[:-self.keep]:
            doomed = [name] + [
                n for n in os.listdir(self.args.checkpoint_path)
                if n.startswith(name + ".shard")]
            for victim in doomed:
                try:
                    os.unlink(os.path.join(self.args.checkpoint_path,
                                           victim))
                except OSError:
                    pass


def _resolve_resume_source(directory: str, path: str,
                           tag: str) -> str:
    """The archive ``--resume`` should actually restore: the
    canonical checkpoint when it validates, else the NEWEST retained
    autosave snapshot that does. A torn canonical (shared-fs hiccup,
    partial copy) therefore costs at most ``--checkpoint_every_rounds``
    rounds instead of crashing the resume; with no valid fallback the
    original TornCheckpointError (naming the bad shard) propagates."""
    try:
        validate_checkpoint(path)
        return path
    except TornCheckpointError as torn:
        pat = re.compile(rf"^ckpt_{re.escape(tag)}_r(\d+)\.npz$")
        snaps = sorted(
            ((int(m.group(1)), m.group(0))
             for m in (pat.match(n) for n in os.listdir(directory))
             if m), reverse=True)
        for _, name in snaps:
            hist = os.path.join(directory, name)
            try:
                validate_checkpoint(hist)
            except TornCheckpointError:
                continue
            print(f"WARNING: {torn} — falling back to retained "
                  f"autosave {hist}")
            return hist
        raise


def setup_resume(args, model, opt, scheduler, loader, tag: str):
    """Shared trainer wiring: returns
    ``(start_epoch, epoch_hook, round_hook)``.

    - ``--resume`` requires ``--checkpoint`` and an existing file —
      anything else raises instead of silently training from scratch
      (and then overwriting the directory's checkpoints).
    - a torn canonical checkpoint falls back to the newest retained
      autosave that still validates (``_resolve_resume_source``).
    - ``epoch_hook`` saves every ``--checkpoint_every`` epochs and at
      the end of training.
    - ``round_hook(epoch)`` is the :class:`RoundAutosaver` when
      ``--checkpoint_every_rounds`` is set (None otherwise); the
      trainers call it after every completed round.
    """
    import math

    if not (args.do_checkpoint or args.do_resume):
        return 0, None, None
    if args.do_resume and not args.do_checkpoint:
        raise ValueError("--resume requires --checkpoint")
    path = checkpoint_file(args.checkpoint_path, tag)
    sampler = getattr(loader, "sampler", None)
    start_epoch = 0
    if args.do_resume:
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"--resume: no checkpoint at {path}")
        src = _resolve_resume_source(args.checkpoint_path, path, tag)
        meta = load_checkpoint(src, model, opt, scheduler, sampler,
                               loader)
        start_epoch = meta["epoch"]
        print(f"resumed from {src} at epoch {start_epoch}"
              + (" (mid-epoch)" if meta.get("sampler_mid_epoch")
                 else ""))

    def epoch_hook(ep):
        if (args.checkpoint_every
                and ep % args.checkpoint_every == 0) \
                or ep >= math.ceil(args.num_epochs):
            save_checkpoint(path, model, opt, scheduler, sampler,
                            epoch=ep, loader=loader)

    round_hook = None
    if int(getattr(args, "checkpoint_every_rounds", 0) or 0) > 0:
        round_hook = RoundAutosaver(args, model, opt, scheduler,
                                    sampler, loader, tag)
    return start_epoch, epoch_hook, round_hook
