"""Sequence-parallel federated runtime for GPT-2 (``--seq_devices N``).

Drop-in FedModel variant whose TRAIN path runs the 2-D
clients x seq round (core/rounds_sp.py): each client's forward/backward
is sequence-sharded over ``seq_devices`` chips with ring (or Ulysses)
attention, so context length scales with chips — a capability the
reference lacks entirely (SURVEY.md §2.8). Validation and the
FedOptimizer server step are inherited unchanged.

Mode composition: the SP round produces the round's aggregated DENSE
gradient. ``uncompressed``/``true_topk`` consume it directly; for
``sketch`` it is table-ized once server-side — by sketch linearity
this equals the psum of per-client sketches, so the server math is
identical to the 1-D engine's. Modes needing per-client local state
(local momentum/error, local_topk, fedavg, topk_down) are rejected.

Objective notes (differences vs the 1-D engine, both deliberate):
- clients are weighted equally (per-client mean), vs datapoint-count
  weighting — the standard FedAvg-style choice for ragged clients;
- each client's LM loss is a token-mean over ALL its valid tokens,
  vs the 1-D path's mean of per-example token-means — longer examples
  weigh proportionally to their length. Toggling --seq_devices
  therefore changes training dynamics slightly at equal LR.
Weight decay is applied with the 1-D engine's effective coefficient
(weight_decay / num_workers, see core/grad.py). ``--max_grad_norm``
and ``--dp`` are per-client pre-aggregation operations that cannot be
recovered from the aggregated gradient — they are rejected rather than
silently dropped. Byte accounting is inherited.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import args2sketch
from commefficient_tpu.core.rounds_sp import (build_sp_gpt2_round,
                                              make_sp_mesh,
                                              shift_lm_labels)
from commefficient_tpu.runtime.fed_model import FedModel
from commefficient_tpu.telemetry import clock, trace


class SeqParallelFedModel(FedModel):
    def __init__(self, module, params, compute_loss, args: Config,
                 gpt2_cfg, compute_loss_val=None,
                 padded_batch_size=None):
        if args.mode not in ("uncompressed", "sketch", "true_topk"):
            raise ValueError(
                f"--seq_devices does not support mode={args.mode} "
                "(needs per-client local state)")
        if args.local_momentum > 0 or args.error_type == "local" \
                or args.do_topk_down:
            raise ValueError("--seq_devices requires local_momentum 0, "
                             "error_type none/virtual, no topk_down")
        if args.max_grad_norm is not None or args.do_dp:
            raise ValueError(
                "--seq_devices does not support --max_grad_norm/--dp "
                "(per-client clipping/noise happens before "
                "aggregation and cannot be applied afterwards)")
        n_dev = len(jax.devices())
        if n_dev % args.seq_devices != 0:
            raise ValueError(f"seq_devices={args.seq_devices} must "
                             f"divide device count {n_dev}")
        n_client_axis = n_dev // args.seq_devices

        super().__init__(module, params, compute_loss, args,
                         compute_loss_val=compute_loss_val,
                         padded_batch_size=padded_batch_size)
        # this subclass's _call_train accounts synchronously; keep the
        # base pipeline machinery off so the op ordering stays valid
        self.pipeline_depth = 1

        sp_cfg = dataclasses.replace(gpt2_cfg,
                                     seq_impl=args.seq_impl)
        self._sp_mesh = make_sp_mesh(n_client_axis, args.seq_devices)
        sp_round = build_sp_gpt2_round(
            sp_cfg, self._sp_mesh, self.unravel,
            lm_coef=args.lm_coef, mc_coef=args.mc_coef,
            ignore_index=-1, tokens_per_chunk=args.tokens_per_chunk)
        sketch = args2sketch(args)
        wd = args.weight_decay / max(args.num_workers, 1)
        probes_on = self.probe_period > 0

        def make_round(with_recovery):
            @jax.jit
            def round_and_compress(ps, batch):
                agg, loss = sp_round(ps, batch)
                if wd > 0:  # 1-D engine's effective decay (core/grad.py)
                    agg = agg + wd * ps
                dense = agg
                if sketch is not None:
                    # linearity: sketch(mean of grads) == mean of
                    # sketches
                    agg = sketch.sketch(dense)
                pr = None
                if probes_on:
                    from commefficient_tpu.core.rounds import _agg_probes
                    pr = _agg_probes(agg)
                    if with_recovery and sketch is not None:
                        # the dense aggregate exists pre-sketch on
                        # this path, so ground truth is free here
                        pr["recovery_error"] = sketch.recovery_error(
                            agg, dense, args.k)
                return agg, loss, pr
            return round_and_compress

        self._sp_round = make_round(False)
        self._sp_round_probed = (
            make_round(True)
            if probes_on and sketch is not None else None)

    def _call_train(self, batch):
        tel = self.telemetry
        ridx = self.round_index
        tel.begin_round(ridx)
        trace.begin_round_marker(ridx)
        eng = self.alarm_engine
        step_t0 = (clock.tick()
                   if eng is not None and eng.step_time_ratio > 0
                   else None)
        ids_np = np.asarray(batch["client_ids"])
        W = ids_np.shape[0]
        if W % self._sp_mesh.shape["clients"] != 0:
            raise ValueError(
                f"num_workers {W} must be divisible by the client "
                f"axis {self._sp_mesh.shape['clients']}")
        with tel.span("h2d"), trace.phase("h2d"):
            sp_batch = {
                "input_ids": jnp.asarray(batch["input_ids"]),
                "token_type_ids": jnp.asarray(batch["token_type_ids"]),
                "shifted_labels": shift_lm_labels(
                    jnp.asarray(batch["lm_labels"])),
                "mc_token_ids": jnp.asarray(batch["mc_token_ids"]),
                "mc_labels": jnp.asarray(batch["mc_labels"]),
                "mask": jnp.asarray(batch["mask"]),
            }
        round_fn = self._sp_round
        if (self._sp_round_probed is not None
                and ridx % self.probe_period == 0):
            round_fn = self._sp_round_probed
        if (self._cost_model is None and tel.enabled
                and getattr(self.args, "do_profile", False)):
            self._emit_cost_model(round_fn,
                                  (self.ps_weights, sp_batch))
        with tel.span("round_dispatch"), trace.phase("round_dispatch"):
            agg, per_client_loss, probes = round_fn(self.ps_weights,
                                                    sp_batch)
        self.pending_aggregated = agg
        self.pending_client_ids = jnp.asarray(ids_np, jnp.int32)
        self.round_index += 1

        # per-client losses, like the 1-D engine's metrics arrays —
        # the trainer weights them by real sample counts. _host, not
        # device_get: the (W,) vector is client-axis sharded and not
        # fully addressable on a multi-process mesh
        from commefficient_tpu.runtime.fed_model import _host
        with tel.span("metrics_host"), trace.phase("metrics_host"):
            metrics = [np.asarray(_host(per_client_loss), np.float64)]
            probe_vals = (None if probes is None else
                          {k: float(_host(v))
                           for k, v in probes.items()})
        if probe_vals is not None:
            tel.merge_round_probes(ridx, probe_vals)
            self._probe_host[ridx] = probe_vals
        if step_t0 is not None:
            eng.check_step_time(ridx, clock.tick() - step_t0)
        down, up = self._account_bytes(ids_np, batch["mask"])
        tel.set_round_bytes(ridx, float(down.sum()), float(up.sum()))
        return metrics + [down, up]
