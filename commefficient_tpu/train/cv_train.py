"""CV experiment driver — counterpart of reference cv_train.py.

Same CLI, same round loop structure (LR scheduler stepped *before*
the round, the LR==0 "HACK STEP" alignment quirk, NaN abort, fractional
epochs, byte-accounting totals, TableLogger rows), driving the SPMD
runtime instead of a process fleet.

Run e.g.:
    python -m commefficient_tpu.train.cv_train --dataset_name Synthetic \
        --mode sketch --error_type virtual --local_momentum 0 \
        --num_clients 10 --num_workers 2 --num_epochs 2
"""

from __future__ import annotations

import math
import re
import sys

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import (Config, num_classes_of_dataset,
                                      parse_args)
from commefficient_tpu.data import (FedLoader, FedSampler, ValLoader,
                                    get_dataset_cls)
from commefficient_tpu.data import transforms as T
from commefficient_tpu.models import get_model
from commefficient_tpu.runtime import (FedModel, FedOptimizer, LambdaLR,
                                       drain_rounds)
from commefficient_tpu.telemetry import clock
from commefficient_tpu.telemetry.alarms import DivergenceAbort
from commefficient_tpu.utils import (PiecewiseLinear, TableLogger,
                                     TSVLogger, Timer, steps_per_epoch)


def masked_mean(values, mask):
    return jnp.sum(values * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def make_compute_loss(module, init_stats=None):
    """CE loss + accuracy (reference compute_loss_ce,
    cv_train.py:32-50), masked-mean over real samples.

    Mixup support: when the batch carries ``y_b``/``lam`` (added by
    ``apply_mixup`` under ``--mixup``), the loss becomes
    lam*CE(y) + (1-lam)*CE(y_b) — the reference ships this as dead
    code (compute_loss_mixup is never wired and its mixup_data helper
    doesn't exist, SURVEY §2.7); here it works. Accuracy is reported
    against the dominant label."""

    def compute_loss(params, batch, args):
        variables = {"params": params}
        if init_stats is not None:
            # masked batch statistics: padded rows must not enter
            # (the reference's torch batches are dynamically sized,
            # so its BN only ever sees real samples)
            variables["batch_stats"] = init_stats
            logits, _ = module.apply(variables, batch["x"],
                                     mask=batch["mask"],
                                     mutable=["batch_stats"])
        else:
            logits = module.apply(variables, batch["x"])
        return _ce_loss_and_acc(logits, batch)

    return compute_loss


def _ce_loss_and_acc(logits, batch):
    labels = batch["y"]
    logp = jax.nn.log_softmax(logits)

    def nll_of(lab):
        return -jnp.take_along_axis(logp, lab[..., None],
                                    axis=-1)[..., 0]

    if "y_b" in batch:
        lam = batch["lam"]  # per-sample (broadcast of round lam)
        nll = lam * nll_of(labels) \
            + (1.0 - lam) * nll_of(batch["y_b"])
        dominant = jnp.where(lam >= 0.5, labels, batch["y_b"])
    else:
        nll = nll_of(labels)
        dominant = labels
    loss = masked_mean(nll, batch["mask"])
    acc = masked_mean(
        (jnp.argmax(logits, -1) == dominant).astype(jnp.float32),
        batch["mask"])
    return loss, (acc,)


def make_compute_loss_eval(module):
    """Eval loss for stateful-BN models: normalize by the server's
    running statistics (model_state), so metrics are invariant to the
    eval batch composition — the reference's torch BN eval behavior
    (models/resnet9.py:32-59 via nn.BatchNorm2d)."""

    def compute_loss(params, batch, args, model_state):
        logits = module.apply({"params": params,
                               "batch_stats": model_state},
                              batch["x"], train=False)
        return _ce_loss_and_acc(logits, batch)

    return compute_loss


def make_bn_stats_fn(module, init_stats):
    """One client's raw batch statistics: a train-mode forward with a
    mutable batch_stats collection (BatchStatNorm records the masked
    batch mean/var; the server does the running blend). This is a
    second forward per client on top of the gradient pass — accepted
    tradeoff: threading the stats out through the grad/metrics
    machinery would complicate every mode path, and --batchnorm is a
    parity mode, not the perf path (benches are BN-free)."""

    def stats_fn(params, batch):
        _, upd = module.apply({"params": params,
                               "batch_stats": init_stats},
                              batch["x"], mask=batch["mask"],
                              mutable=["batch_stats"])
        return upd["batch_stats"]

    return stats_fn


# Fixup scalar leaf names, matched as the EXACT final path segment —
# a bare substring test ('bias' in path) would silently sweep any
# future parameter whose path merely contains the string into the
# 0.1x group. The name sets cover every scalar the Fixup family
# declares (fixup_resnet9.py: bias1a/1b/2a/2b, bias1/bias2, scale;
# FixupBottleneck adds bias3a/3b; FixupResNet18: add1a/1b/2a/2b, mul)
# plus the Dense head's 'bias', which the reference's substring match
# also places at 0.1x (cv_train.py:366-376).
_FIXUP_BIAS_RE = re.compile(
    r"\['(?:bias(?:[123][ab]?)?|add[12][ab])'\]$")
_FIXUP_SCALE_RE = re.compile(r"\['(?:scale|mul)'\]$")


def fixup_bias_name(name: str) -> bool:
    """Fixup 0.1x 'bias' group membership by parameter-path name
    (reference cv_train.py:366-376 matches torch names by substring;
    here the final path segment must equal a known scalar name)."""
    return _FIXUP_BIAS_RE.search(name) is not None


def fixup_scale_name(name: str) -> bool:
    """Fixup 0.1x 'scale' group: 'mul.scale' in the reference; our
    FixupResNet18 names the multiplicative scalar 'mul'."""
    return _FIXUP_SCALE_RE.search(name) is not None


def apply_mixup(batch, alpha, rng):
    """Host-side mixup (the classic mixup_data recipe): one lambda ~
    Beta(alpha, alpha) per round; inputs are mixed with a permutation
    WITHIN each client's real rows (mixing across clients would leak
    data between federated clients)."""
    lam = float(rng.beta(alpha, alpha)) if alpha > 0 else 1.0
    x = np.asarray(batch["x"]).copy()
    y = np.asarray(batch["y"])
    mask = np.asarray(batch["mask"])
    y_b = y.copy()
    for w in range(x.shape[0]):
        real = np.nonzero(mask[w] > 0)[0]
        if len(real) < 2:
            continue
        perm = real[rng.permutation(len(real))]
        x[w, real] = lam * x[w, real] + (1 - lam) * x[w, perm]
        y_b[w, real] = y[w, perm]
    out = dict(batch)
    out["x"] = x
    out["y_b"] = y_b
    out["lam"] = np.full_like(mask, lam)
    return out


def run_batches(model, opt, lr_scheduler, loader, args, training,
                logger=None, epoch_fraction=1.0, mixup_rng=None,
                round_hook=None, epoch=0):
    """(reference cv_train.py:171-252). ``round_hook(epoch)`` runs
    after every completed round (round-cadence autosave,
    runtime/checkpoint.py RoundAutosaver; it skips itself while
    pipelined rounds are still in flight)."""
    if training:
        model.train(True)
        losses, accs = [], []
        download_total = np.zeros(model.num_clients)
        upload_total = np.zeros(model.num_clients)
        spe = len(loader)
        max_batches = max(1, int(spe * epoch_fraction))
        state = {"t0": clock.wall()}
        pending = []

        def process(metrics, i, w, lr):
            loss, acc, download, upload = (metrics[0], metrics[1],
                                           metrics[-2], metrics[-1])
            download_total[:] += download
            upload_total[:] += upload
            # weight per-client metrics by real sample counts so
            # dropped clients (--dropout_prob) and ragged batches
            # don't dilute the reported numbers; fully-dropped rounds
            # trained on nothing and are excluded from the epoch means
            if w.sum() == 0:
                return True
            losses.append(float(np.sum(loss * w) / w.sum()))
            accs.append(float(np.sum(acc * w) / w.sum()))
            if args.dataset_name == "EMNIST":
                # per-round progress line (reference cv_train.py:
                # 233-237); lr captured at dispatch time so pipelined
                # drains report each round's own LR (Time becomes
                # burst-shaped under pipelining — inherent)
                print("LR: {:0.5f}, Loss: {:0.5f}, Acc: {:0.5f}, "
                      "Time: {:0.2f}".format(
                          lr, losses[-1], accs[-1],
                          clock.wall() - state["t0"]))
                state["t0"] = clock.wall()
            if not math.isfinite(losses[-1]) or \
                    losses[-1] > args.nan_threshold:
                print(f"Stopping at batch {i}: diverged "
                      f"(loss {losses[-1]})")
                return False
            return True

        tel = model.telemetry
        it = enumerate(loader)
        try:
            while True:
                # manual pull so the sampler/loader wait is a ledger
                # span (lands on the previous round's record — it's
                # the inter-round host gap)
                with tel.span("sampler"):
                    nxt = next(it, None)
                if nxt is None:
                    break
                i, batch = nxt
                if i >= max_batches:
                    break
                if mixup_rng is not None:
                    batch = apply_mixup(batch, args.mixup_alpha,
                                        mixup_rng)
                lr_scheduler.step()
                if opt.param_groups[0]["lr"] == 0:
                    # "HACK STEP": keep FedAvg's schedule aligned when
                    # the triangular LR hits 0 (reference cv_train.py:
                    # 198-203); every group — schedule zeros hit them
                    # all at once
                    for g in opt.param_groups:
                        g["lr"] = 1e-10
                metrics = model(batch)
                opt.step()
                w = np.asarray(batch["mask"]).sum(axis=1)
                lr_now = float(opt.param_groups[0]["lr"])
                if metrics is None:
                    # pipelined (--pipeline_depth > 1): results arrive
                    # in batches; the device runs ahead of this loop
                    pending.append((i, w, lr_now))
                    if not drain_rounds(model, pending, process,
                                        force=False):
                        return None
                elif not process(metrics, i, w, lr_now):
                    return None
                if round_hook is not None:
                    round_hook(epoch)
                if args.do_test:
                    break
            if not drain_rounds(model, pending, process, force=True):
                return None
        except DivergenceAbort as e:
            # --on_divergence abort: a probe alarm fired (alarms are
            # already flagged on the round's ledger record, which
            # becomes the run's final record when telemetry closes)
            print(f"Stopping at round {e.round_index}: {e}")
            model.diverged = True
            return None
        if not losses:  # every round fully dropped
            return (float("nan"), float("nan"),
                    download_total, upload_total)
        return (np.mean(losses), np.mean(accs),
                download_total, upload_total)
    else:
        model.train(False)
        losses, accs, counts = [], [], []
        for i, batch in enumerate(loader):
            shard_metrics = model(batch)
            losses.extend(shard_metrics[0].tolist())
            accs.extend(shard_metrics[1].tolist())
            counts.extend(shard_metrics[-1].tolist())
            if args.do_test:
                break
        counts = np.asarray(counts)
        w = counts / max(counts.sum(), 1.0)
        return float(np.sum(losses * w)), float(np.sum(accs * w))


def train(model, opt, lr_scheduler, train_loader, val_loader, args,
          logger=None, timer=None, start_epoch=0, epoch_hook=None,
          round_hook=None):
    """Epoch loop (reference cv_train.py:85-168). ``epoch_hook(ep)``
    runs after each completed epoch and ``round_hook(epoch)`` after
    each completed round (checkpointing)."""
    from commefficient_tpu.telemetry.profiler import profile_epoch
    from commefficient_tpu.telemetry.sinks import TensorBoardSink
    from commefficient_tpu.utils import make_logdir
    timer = timer or Timer()
    logger = logger or TableLogger()
    tsv = TSVLogger()
    logdir = (make_logdir(args)
              if (args.use_tensorboard or args.do_profile) else None)
    tel = model.telemetry
    if args.use_tensorboard:
        # the trainer owns the run logdir, so the TB sink attaches
        # here rather than in build_telemetry
        tel.add_sink(TensorBoardSink(logdir))
    results = []
    num_epochs = args.num_epochs
    # one persistent mixup stream across epochs (fresh draws per round)
    mixup_rng = (np.random.RandomState(args.seed + 77)
                 if args.do_mixup else None)
    try:
        for epoch in range(start_epoch, math.ceil(num_epochs)):
            epoch_fraction = min(1.0, num_epochs - epoch)
            with profile_epoch(args, epoch, start_epoch, logdir,
                               telemetry=tel):
                out = run_batches(model, opt, lr_scheduler,
                                  train_loader, args, training=True,
                                  epoch_fraction=epoch_fraction,
                                  mixup_rng=mixup_rng,
                                  round_hook=round_hook, epoch=epoch)
            if out is None:
                print("NaN detected, aborting training")
                return results
            train_loss, train_acc, download, upload = out
            train_time = timer()
            val_loss, val_acc = run_batches(model, opt, lr_scheduler,
                                            val_loader, args,
                                            training=False)
            val_time = timer()
            row = {
                "epoch": epoch + 1,
                "lr": float(opt.param_groups[0]["lr"]),
                "train_time": train_time,
                "train_loss": float(train_loss),
                "train_acc": float(train_acc),
                "test_time": val_time,
                "test_loss": float(val_loss),
                "test_acc": float(val_acc),
                "down (MiB)": float(download.sum() / (1024 * 1024)),
                "up (MiB)": float(upload.sum() / (1024 * 1024)),
                "total_time": timer.total_time,
            }
            logger.append(row)
            tsv.append(row)
            results.append(row)
            tel.epoch(row, epoch + 1)
            if epoch_hook is not None:
                epoch_hook(epoch + 1)
    finally:
        # sinks flush/close here even on abort; finalize()'s close is
        # a no-op afterwards (idempotent)
        tel.close()
    return results


def get_data_loaders(args: Config):
    """(reference cv_train.py:254-287)"""
    name = args.dataset_name
    train_t, val_t = None, None
    if name in ("CIFAR10", "CIFAR100"):
        mean = T.CIFAR10_MEAN if name == "CIFAR10" else T.CIFAR100_MEAN
        std = T.CIFAR10_STD if name == "CIFAR10" else T.CIFAR100_STD
        train_t = T.cifar_train_transform(mean, std)
        val_t = T.cifar_val_transform(mean, std)
    elif name == "EMNIST":
        train_t = T.femnist_train_transform()
        val_t = T.femnist_val_transform()
    elif name == "ImageNet":
        train_t = T.imagenet_train_transform()
        val_t = T.imagenet_val_transform()

    cls = get_dataset_cls(name)
    common = dict(do_iid=args.do_iid, num_clients=args.num_clients,
                  seed=args.seed)
    if name == "Synthetic":
        common["classes_per_client"] = args.classes_per_client
        common["per_class"] = args.synthetic_per_class
        common["separation"] = args.synthetic_separation
        common["num_val"] = args.synthetic_num_val
    train_ds = cls(args.dataset_dir, name, transform=train_t,
                   train=True, **common)
    val_ds = cls(args.dataset_dir, name, transform=val_t, train=False,
                 **common)
    sampler = FedSampler(train_ds, args.num_workers,
                         args.local_batch_size,
                         seed=args.seed)
    # C++ data-plane with threaded prefetch when the transform stack
    # and toolchain allow; Python loader otherwise (same batch dict)
    from commefficient_tpu.data import make_fed_loader
    train_loader = make_fed_loader(train_ds, sampler, seed=args.seed,
                                   prefer_native=not args.do_test,
                                   dropout_prob=args.dropout_prob)
    val_loader = ValLoader(val_ds, args.valid_batch_size,
                           shards_per_step=max(1, args.num_workers))
    return train_loader, val_loader, train_ds


def build_model(args: Config, rng=None):
    num_classes = num_classes_of_dataset(args.dataset_name)
    model_cls = get_model(args.model)
    kw = dict(num_classes=num_classes)
    if args.model == "ResNet9":
        kw["do_batchnorm"] = args.do_batchnorm
    if args.do_bf16:
        if "dtype" in getattr(model_cls, "__dataclass_fields__", {}):
            kw["dtype"] = jnp.bfloat16
        else:
            import warnings
            warnings.warn(f"--bf16 not supported by {args.model}; "
                          "training in float32")
    if args.do_test and hasattr(model_cls, "test_config"):
        kw.update(model_cls.test_config(num_classes))
    module = model_cls(**kw)
    # model-init stream, not noise  # audit: allow(noise-confinement)
    rng = rng if rng is not None else jax.random.PRNGKey(args.seed)
    # EMNIST is 28x28 grayscale, ImageNet 224x224 (reference dataset
    # table at utils.py:37-41 + transforms.py)
    sample_shape = {"EMNIST": (1, 28, 28, 1),
                    "ImageNet": (1, 224, 224, 3)}.get(
        args.dataset_name, (1, 32, 32, 3))
    variables = module.init(rng, jnp.zeros(sample_shape), train=True)
    params = variables["params"]
    init_stats = variables.get("batch_stats")
    return module, params, init_stats


def merge_finetune_params(target, source):
    """Overlay ``source`` (a loaded checkpoint pytree) onto ``target``
    (freshly initialised for the new dataset) wherever leaf shapes
    match; leaves whose shapes differ — the classifier head when the
    class count changed — keep their fresh initialisation. The
    functional form of the reference's head-swap finetuning
    (cv_train.py:342-352, 377-384). Returns (merged, replaced_paths).
    """
    replaced = []

    def rec(t, s, path):
        if isinstance(t, dict):
            out = {}
            for k, v in t.items():
                if isinstance(s, dict) and k in s:
                    out[k] = rec(v, s[k], path + (k,))
                else:
                    replaced.append("/".join(path + (k,)))
                    out[k] = v
            return out
        if getattr(s, "shape", None) == getattr(t, "shape", None):
            return jnp.asarray(s)
        replaced.append("/".join(path))
        return t

    return rec(target, source, ()), replaced


def load_finetune_params(args, params):
    """Load finetune_path/<model>.pkl (trained on --finetuned_from)
    and merge it into the fresh params."""
    import os
    import pickle
    path = os.path.join(args.finetune_path, args.model + ".pkl")
    with open(path, "rb") as f:
        source = pickle.load(f)
    merged, replaced = merge_finetune_params(params, source)
    print(f"finetune: loaded {path}; reinitialised: "
          f"{replaced or 'nothing'}")
    return merged


DEFAULT_LR = 0.4


def main(argv=None):
    args = parse_args(default_lr=DEFAULT_LR, argv=argv)
    from commefficient_tpu.parallel.mesh import \
        maybe_initialize_multihost_cli
    maybe_initialize_multihost_cli(args)
    if args.seq_devices > 1:
        raise ValueError("--seq_devices is a GPT-2 trainer feature "
                         "(sequence parallelism); cv models have no "
                         "sequence axis")
    np.random.seed(args.seed)

    model_cfg = None
    if not args.do_test:
        # overlay per-model recommended hyperparameters onto fields
        # the user left at their defaults (models/configs.py)
        from commefficient_tpu.models.configs import get_model_config
        model_cfg = get_model_config(args.model)
        if model_cfg is not None:
            defaults = parse_args(default_lr=DEFAULT_LR,
                                  argv=[]).__dict__
            applied = model_cfg.set_args(args, defaults)
            if applied:
                print(f"model config {type(model_cfg).__name__}: "
                      f"{applied}")

    if args.do_test:
        # tiny sketch like the reference smoke mode (cv_train.py:329-336)
        # pre-run CLI override: no round program exists yet for a
        # knob move to diverge from, so the waivers below are safe
        args.k = 10  # audit: allow(knob-mutation)
        args.num_cols = 10  # audit: allow(knob-mutation)
        args.num_rows = 1
        args.num_blocks = 1

    train_loader, val_loader, train_ds = get_data_loaders(args)
    if args.num_clients is None:
        args.num_clients = int(train_ds.num_clients)

    module, params, init_stats = build_model(args)
    if args.do_finetune:
        params = load_finetune_params(args, params)
    compute_loss = make_compute_loss(module, init_stats)

    stats_fn = loss_val = None
    if init_stats:  # stateful BN (--batchnorm): running-stats eval
        stats_fn = make_bn_stats_fn(module, init_stats)
        loss_val = make_compute_loss_eval(module)
    model = FedModel(module, params, compute_loss, args,
                     compute_loss_val=loss_val,
                     padded_batch_size=train_loader.B,
                     stats_fn=stats_fn, init_model_state=init_stats)
    if hasattr(train_loader, "peek_next_client_ids"):
        # host client store: the loader's one-round lookahead feeds
        # the prefetch thread (no-op under --clientstore device)
        model.attach_participant_feed(
            train_loader.peek_next_client_ids)

    if args.model.startswith("Fixup") and args.mode != "fedavg":
        # Fixup LR groups (reference cv_train.py:366-376): bias and
        # scale parameters train at 0.1x; built as flat-vector index
        # groups so the per-coordinate LR lines up exactly. The
        # nominal-LR group comes first so logged LR is the schedule's.
        from commefficient_tpu.ops.vec import param_group_indices
        bias_idx, scale_idx, other_idx = param_group_indices(
            params, fixup_bias_name, fixup_scale_name)
        param_groups = [{"lr": 1.0, "index": other_idx},
                        {"lr": 0.1, "index": bias_idx},
                        {"lr": 0.1, "index": scale_idx}]
        print("using fixup learning rates")
    else:
        if args.model.startswith("Fixup") and args.mode == "fedavg":
            # fedavg's client local SGD uses one shared scalar LR
            # (reference g_lr shm, fed_worker.py:57), so per-group
            # Fixup LRs cannot apply — unlike the reference, which
            # also ignores them silently in this combination
            print("WARNING: fedavg uses a scalar LR; Fixup bias/scale "
                  "0.1x groups are not applied")
        param_groups = [{"lr": 1.0}]
    opt = FedOptimizer(param_groups, args)

    spe = steps_per_epoch(args.local_batch_size, train_ds,
                          args.num_workers)
    horizon = args.schedule_epochs or args.num_epochs
    if model_cfg is not None \
            and model_cfg.lr_schedule_shape is not None:
        # per-model epoch-indexed shape x args.lr_scale (the working
        # form of the reference's ModelConfig pattern) — an explicit
        # --lr_scale still takes effect
        shape = model_cfg.lr_schedule_shape
        lr_scheduler = LambdaLR(
            opt, lambda x: args.lr_scale * shape(x / spe))
    else:
        lambda_step = PiecewiseLinear(
            [0, args.pivot_epoch * spe, horizon * spe],
            [0, args.lr_scale, 0])
        lr_scheduler = LambdaLR(opt, lambda x: lambda_step(x))

    from commefficient_tpu.runtime.checkpoint import setup_resume
    start_epoch, epoch_hook, round_hook = setup_resume(
        args, model, opt, lr_scheduler, train_loader, tag=args.model)

    from commefficient_tpu.utils import GracefulShutdown, sigterm_raises
    interrupted = False
    try:
        with sigterm_raises():
            results = train(model, opt, lr_scheduler, train_loader,
                            val_loader, args, start_epoch=start_epoch,
                            epoch_hook=epoch_hook,
                            round_hook=round_hook)
    except GracefulShutdown as e:
        # crash safety: drop in-flight round state, close everything
        # cleanly, and save NOTHING here — the last round-cadence
        # autosave is the consistent resume point, and an end-of-run
        # save now would capture a mid-round server state
        print(f"interrupted ({e}); resume from the last autosave")
        interrupted = True
        results = []
        if model.flightrec is not None:
            # the postmortem preserves the rounds the ledger may not
            # have flushed — dumped before interrupted() discards the
            # in-flight host state it describes
            model.flightrec.dump("graceful_shutdown",
                                 context={"signal": str(e)})
        model.interrupted()
    model.finalize()
    from commefficient_tpu.runtime.checkpoint import \
        resume_manifest_extra
    from commefficient_tpu.telemetry import registry
    registry.maybe_write_manifest(
        args, mesh_shape=dict(model.mesh.shape),
        extra={"trainer": "cv_train", "epochs": len(results),
               "interrupted": interrupted,
               "diverged": bool(getattr(model, "diverged", False)),
               **resume_manifest_extra(model)})

    if args.do_checkpoint and not interrupted \
            and jax.process_index() == 0:
        # params are replicated — one writer on a shared filesystem
        import os
        import pickle
        os.makedirs(args.checkpoint_path, exist_ok=True)
        path = os.path.join(args.checkpoint_path, args.model + ".pkl")
        # audit: allow(host-sync) — end-of-run checkpoint write
        params = jax.device_get(model.params())
        with open(path, "wb") as f:
            pickle.dump(params, f)
        print(f"saved checkpoint to {path}")
        # the reference's exact artifact: torch.save(state_dict) named
        # <model>.pt (cv_train.py:420-423), reference torch key names
        from commefficient_tpu.models.torch_export import (
            save_torch_state_dict, supports_torch_export)
        if supports_torch_export(model.module):
            tpath = os.path.join(args.checkpoint_path,
                                 args.model + ".pt")
            save_torch_state_dict(model.module, params,
                                  getattr(model, "model_state", None),
                                  tpath)
            print(f"saved torch state_dict to {tpath}")
    return results


if __name__ == "__main__":
    main(sys.argv[1:])
