"""GPT-2 / PersonaChat federated fine-tuning driver — counterpart of
reference gpt2_train.py.

Same structure: double-heads loss (lm_coef*LM + mc_coef*MC) for
training (run with --num_results_train 1), NLL + multiple-choice
accuracy + PPL for validation, linear LR decay
PiecewiseLinear([0, epochs*spe], [lr_scale, 0]), same round loop.

Offline notes: the PersonaChat archive and GPT-2 vocab must be on disk
(zero egress); absent those, --test generates a synthetic archive and
uses the byte-level fallback tokenizer with a tiny GPT-2 config.
"""

from __future__ import annotations

import math
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import Config, parse_args
from commefficient_tpu.data.fed_persona import (FedPERSONA,
                                                generate_synthetic_personachat)
from commefficient_tpu.data.fed_sampler import FedSampler
from commefficient_tpu.data.loader import (PersonaFedLoader,
                                           PersonaValLoader)
from commefficient_tpu.data.tokenizer import (SPECIAL_TOKENS,
                                              load_tokenizer)
from commefficient_tpu.models.gpt2 import (GPT2Config, GPT2DoubleHeads,
                                           token_nll)
from commefficient_tpu.runtime import (FedModel, FedOptimizer, LambdaLR,
                                       drain_rounds)
from commefficient_tpu.telemetry.alarms import DivergenceAbort
from commefficient_tpu.utils import (PiecewiseLinear, TableLogger,
                                     Timer, steps_per_epoch)

MAX_SEQ_LEN = 256  # static pad length (persona sequences are short)


def _lm_nll_sums(module, params, batch, tokens_per_chunk=0,
                 fused=False, batch_mult=1):
    """Shared forward for the train and val losses: hidden states +
    MC logits from the module, then the tied-head cross-entropy — the
    (tokens, vocab) logits tensor never materialises: chunked
    (models/gpt2.py lm_nll_sums_chunked) by default, or the fused
    Pallas kernels (ops/flce_pallas.py, ``fused=True``) where even the
    per-chunk logits tiles stay in VMEM. Returns per-example
    ((B*N,) Σnll, (B*N,) Σvalid), mc_logits, B, N.
    ``tokens_per_chunk`` 0 = auto (1024 — throughput-flat 512-4096
    at the 8x geometry, BENCHMARKS.md)."""
    from commefficient_tpu.models.gpt2 import lm_nll_sums_chunked

    ids = batch["input_ids"]
    B, N, T = ids.shape
    h, wte, mc_logits = module.apply(
        {"params": params}, ids, batch["mc_token_ids"],
        batch["token_type_ids"], return_hidden=True)
    labels = batch["lm_labels"].reshape(B * N, T)
    if fused:
        from commefficient_tpu.ops.flce_pallas import lm_nll_sums_fused
        # batch_mult: this runs under the round's per-client vmap, so
        # the kernel's dX-partials OOM guard must see the vmapped
        # multiplicity — the buffer exists once PER CLIENT concurrently
        sn, sv = lm_nll_sums_fused(h[:, :-1], wte, labels[:, 1:],
                                   module.cfg.dtype, ignore_index=-1,
                                   tokens_per_chunk=tokens_per_chunk
                                   or 1024, batch_mult=batch_mult)
    else:
        sn, sv = lm_nll_sums_chunked(h[:, :-1], wte, labels[:, 1:],
                                     module.cfg.dtype, ignore_index=-1,
                                     tokens_per_chunk=tokens_per_chunk
                                     or 1024)
    return sn, sv, mc_logits, B, N


def _resolve_fused(args, module):
    from commefficient_tpu.ops.flce_pallas import resolve_fused_ce
    return resolve_fused_ce(getattr(args, "fused_ce", "off"),
                            module.cfg.n_embd)


def _token_nll(logits, labels, ignore_index=-1):
    """token_nll with the persona loaders' label padding default."""
    return token_nll(logits, labels, ignore_index)


def make_compute_loss_train(module, args):
    """(reference gpt2_train.py:88-99) — one result (the combined
    loss); run with --num_results_train 1. Batched formulation of
    gpt2_double_heads_loss applied per example: identical math to a
    per-example vmap (which XLA lowers to a serial scan over examples
    with a materialised f32 logits buffer — measured 10x the cost).
    The LM term is computed by the chunked tied-head cross-entropy
    (models/gpt2.py lm_nll_sums_chunked via _lm_nll_sums) — or the
    fused Pallas kernels (ops/flce_pallas.py) with --fused_ce — so
    the (tokens, vocab) logits tensor never materialises: its f32
    store/reload chain dominated the large-batch training profile."""

    def compute_loss(params, batch, cfg):
        # shift handled in _lm_nll_sums: position t predicts t+1;
        # per example i: token-mean over its valid positions
        sn, sv, mc_logits, B, N = _lm_nll_sums(
            module, params, batch,
            getattr(args, "tokens_per_chunk", 0),
            fused=_resolve_fused(args, module),
            batch_mult=max(1, getattr(args, "num_workers", 1)))
        lm_i = sn.reshape(B, N).sum(1) \
            / jnp.maximum(sv.reshape(B, N).sum(1), 1.0)

        mc_nll, _ = _token_nll(mc_logits[..., None, :],
                               batch["mc_labels"][..., None])
        mc_i = mc_nll[..., 0]

        m = batch["mask"]
        losses = cfg.lm_coef * lm_i + cfg.mc_coef * mc_i
        loss = jnp.sum(losses * m) / jnp.maximum(jnp.sum(m), 1.0)
        return loss, ()

    return compute_loss


def make_compute_loss_val(module, args):
    """(reference gpt2_train.py:55-86): token-mean NLL + MC accuracy.
    The NLL uses the chunked (or, with --fused_ce, the fused-kernel)
    tied-head cross-entropy: with
    full-candidate validation (N ~ 20) a materialised f32
    (B, N, T, V) logits tensor would be ~8 GB per val shard at the
    natural PersonaChat candidate count."""
    def compute_loss(params, batch, cfg):
        # val shards run under a vmap over shards_per_step =
        # max(1, num_workers) (get_data_loaders) — same multiplicity
        sn, sv, mc_logits, B, N = _lm_nll_sums(
            module, params, batch,
            getattr(args, "tokens_per_chunk", 0),
            fused=_resolve_fused(args, module),
            batch_mult=max(1, getattr(args, "num_workers", 1)))
        m = batch["mask"]
        w = jnp.broadcast_to(m[:, None], (B, N)).reshape(B * N)
        nll = jnp.sum(sn * w) / jnp.maximum(jnp.sum(sv * w), 1.0)

        # padded candidate slots (val items pad up to the loader's
        # static N) must never win the argmax
        cand = batch.get("cand_mask")
        if cand is not None:
            mc_logits = jnp.where(cand > 0, mc_logits, -jnp.inf)
        pred = jnp.argmax(mc_logits, axis=-1)
        acc = jnp.sum((pred == batch["mc_labels"]) * m) \
            / jnp.maximum(jnp.sum(m), 1.0)
        return nll, (acc,)

    return compute_loss


def run_batches(model, opt, lr_scheduler, loader, args, training,
                round_hook=None, epoch=0):
    """(reference gpt2_train.py:169-253). ``round_hook(epoch)`` runs
    after every completed round (round-cadence autosave)."""
    if training:
        model.train(True)
        losses = []
        pending = []

        def process(metrics, i, w):
            # sample-count weighting: see cv_train.run_batches;
            # fully-dropped rounds trained on nothing — excluded
            if w.sum() == 0:
                return True
            loss = float(np.sum(metrics[0] * w) / w.sum())
            losses.append(loss)
            if not math.isfinite(loss) or loss > args.nan_threshold:
                print(f"diverged at round {i} (loss {loss})")
                return False
            return True

        tel = model.telemetry
        it = enumerate(loader)
        try:
            while True:
                # manual pull so the loader wait is a ledger span
                # (lands on the previous round's record — the
                # inter-round gap)
                with tel.span("sampler"):
                    nxt = next(it, None)
                if nxt is None:
                    break
                i, batch = nxt
                lr_scheduler.step()
                metrics = model(batch)
                opt.step()
                w = np.asarray(batch["mask"]).sum(axis=1)
                if metrics is None:  # --pipeline_depth > 1
                    pending.append((i, w))
                    if not drain_rounds(model, pending, process,
                                        force=False):
                        return None
                elif not process(metrics, i, w):
                    return None
                if round_hook is not None:
                    round_hook(epoch)
                if args.do_test:
                    break
            if not drain_rounds(model, pending, process, force=True):
                return None
        except DivergenceAbort as e:
            # alarm engine (--on_divergence abort): the offending
            # round is already ledger-flagged; tel.close() in
            # train_gpt2's finally emits it
            print(f"Stopping at round {e.round_index}: {e}")
            model.diverged = True
            return None
        return float(np.mean(losses)) if losses else float("nan")
    else:
        model.train(False)
        nlls, accs, counts = [], [], []
        for i, batch in enumerate(loader):
            shard_metrics = model(batch)
            nlls.extend(shard_metrics[0].tolist())
            accs.extend(shard_metrics[1].tolist())
            counts.extend(shard_metrics[-1].tolist())
            if args.do_test:
                break
        counts = np.asarray(counts)
        w = counts / max(counts.sum(), 1.0)
        nll = float(np.sum(nlls * w))
        return nll, float(np.sum(accs * w)), float(np.exp(nll))


def train_gpt2(model, opt, lr_scheduler, train_loader, val_loader,
               args, logger=None, start_epoch=0, epoch_hook=None,
               round_hook=None, logdir=None):
    """(reference gpt2_train.py:115-147)"""
    from commefficient_tpu.telemetry.profiler import profile_epoch
    from commefficient_tpu.telemetry.sinks import TensorBoardSink
    from commefficient_tpu.utils import make_logdir
    logger = logger or TableLogger()
    timer = Timer()
    if logdir is None:
        logdir = (make_logdir(args)
                  if (args.use_tensorboard or args.do_profile) else None)
    tel = model.telemetry
    if args.use_tensorboard:
        # the trainer owns the run logdir, so the TB sink attaches
        # here rather than in build_telemetry
        tel.add_sink(TensorBoardSink(logdir))
    results = []
    try:
        for epoch in range(start_epoch, math.ceil(args.num_epochs)):
            with profile_epoch(args, epoch, start_epoch, logdir,
                               telemetry=tel):
                train_loss = run_batches(model, opt, lr_scheduler,
                                         train_loader, args,
                                         training=True,
                                         round_hook=round_hook,
                                         epoch=epoch)
            if train_loss is None:
                print("NaN detected, aborting")
                model.diverged = True
                return results
            train_time = timer()
            nll, acc, ppl = run_batches(model, opt, lr_scheduler,
                                        val_loader, args,
                                        training=False)
            val_time = timer()
            row = {"epoch": epoch + 1,
                   "lr": float(opt.param_groups[0]["lr"]),
                   "train_time": train_time, "train_loss": train_loss,
                   "val_time": val_time, "val_nll": nll, "val_acc": acc,
                   "val_ppl": ppl, "total_time": timer.total_time}
            logger.append(row)
            results.append(row)
            tel.epoch(row, epoch + 1)
            if epoch_hook is not None:
                epoch_hook(epoch + 1)
    finally:
        # sinks flush/close here even on abort; finalize()'s close is
        # a no-op afterwards (idempotent)
        tel.close()
    return results


def build_model_and_tokenizer(args: Config):
    import dataclasses

    import json

    tokenizer = load_tokenizer(args.model_checkpoint)
    tokenizer.add_special_tokens(SPECIAL_TOKENS)
    cfg_json = os.path.join(args.model_checkpoint, "config.json") \
        if os.path.isdir(args.model_checkpoint) else ""
    if os.path.exists(cfg_json):
        # a run dir saved by FedModel.save_pretrained: its config
        # defines the architecture the saved weights fit
        with open(cfg_json) as f:
            blob = json.load(f)
        fields = {f.name for f in dataclasses.fields(GPT2Config)}
        # attn_impl is a runtime lowering knob, not architecture: a
        # config saved from a flash-attention TPU run must not force
        # the Pallas kernel on whatever platform reloads it
        fields.discard("attn_impl")
        cfg = GPT2Config(**{k: v for k, v in blob.items()
                            if k in fields})
    elif args.do_test or tokenizer.__class__.__name__ == "ByteTokenizer":
        cfg = GPT2Config.tiny()
        cfg = dataclasses.replace(
            cfg,
            vocab_size=max(len(tokenizer), cfg.vocab_size),
            n_positions=max(MAX_SEQ_LEN, cfg.n_positions))
    else:
        cfg = GPT2Config(vocab_size=len(tokenizer),
                         n_positions=1024)
    if args.do_bf16:
        cfg = dataclasses.replace(cfg, dtype=jnp.bfloat16)
    if args.do_remat:
        cfg = dataclasses.replace(cfg, remat=True)
    if getattr(args, "attn_impl", "xla") != "xla":
        cfg = dataclasses.replace(cfg, attn_impl=args.attn_impl)
    module = GPT2DoubleHeads(cfg)
    dummy = jnp.zeros((1, args.num_candidates, 8), jnp.int32)
    # model-init stream, not noise  # audit: allow(noise-confinement)
    params = module.init(jax.random.PRNGKey(args.seed), dummy,
                         jnp.zeros((1, args.num_candidates),
                                   jnp.int32), dummy)["params"]

    if os.path.isdir(args.model_checkpoint):
        torch_ckpt = os.path.join(args.model_checkpoint,
                                  "pytorch_model.bin")
        flax_ckpt = os.path.join(args.model_checkpoint,
                                 "flax_model.msgpack")
        if os.path.exists(torch_ckpt):
            import torch
            from commefficient_tpu.models.gpt2 import convert_torch_gpt2
            sd = {k: v.numpy() for k, v in
                  torch.load(torch_ckpt, map_location="cpu").items()}
            params = convert_torch_gpt2(sd, cfg)
            print(f"loaded GPT-2 weights from {torch_ckpt}")
        elif os.path.exists(flax_ckpt):
            # a run dir saved by FedModel.save_pretrained; without its
            # config.json the module above was built from tokenizer
            # heuristics and the weights would mis-shape inside jit
            if not os.path.exists(cfg_json):
                raise FileNotFoundError(
                    f"{flax_ckpt} has no config.json beside it; "
                    "cannot reconstruct the saved architecture")
            from flax import serialization
            with open(flax_ckpt, "rb") as f:
                params = serialization.msgpack_restore(f.read())
            print(f"loaded GPT-2 weights from {flax_ckpt}")
    return module, params, tokenizer


def get_data_loaders(args: Config, tokenizer):
    """(reference gpt2_train.py:315-355)"""
    if args.do_test and not os.path.exists(
            os.path.join(args.dataset_dir,
                         "personachat_self_original.json")):
        if not os.path.exists(os.path.join(args.dataset_dir,
                                           "stats.json")):
            generate_synthetic_personachat(args.dataset_dir)

    common = dict(do_iid=args.do_iid, num_clients=args.num_clients,
                  seed=args.seed)
    train_ds = FedPERSONA(tokenizer, args.num_candidates,
                          args.max_history,
                          args.personality_permutations,
                          args.dataset_dir, "PERSONA", train=True,
                          **common)
    val_ds = FedPERSONA(tokenizer, -1, args.max_history, 1,
                        args.dataset_dir, "PERSONA", train=False,
                        **common)
    pad_id = tokenizer.convert_tokens_to_ids(["<pad>"])[0]
    sampler = FedSampler(train_ds, args.num_workers,
                         args.local_batch_size, seed=args.seed)
    train_loader = PersonaFedLoader(
        train_ds, sampler, args.num_candidates, MAX_SEQ_LEN, pad_id,
        dropout_prob=args.dropout_prob, dropout_seed=args.seed)
    # full-candidate validation (reference fed_persona.py:251-254
    # restricts candidates only for train items): evaluate MC accuracy
    # over every candidate the val item carries, not num_candidates
    n_val = args.val_candidates
    if n_val <= 0:
        # exact max over the raw val JSON (candidate counts can vary
        # per utterance) — no tokenization needed
        n_val = max((len(u["candidates"]) for d in val_ds.raw_val_set
                     for u in d["utterances"]), default=2)
    val_loader = PersonaValLoader(
        val_ds, args.valid_batch_size, max(n_val, 2),
        MAX_SEQ_LEN, pad_id,
        shards_per_step=max(1, args.num_workers))
    return train_loader, val_loader, train_ds


def main(argv=None):
    args = parse_args(default_lr=4e-2, argv=argv)
    from commefficient_tpu.parallel.mesh import \
        maybe_initialize_multihost_cli
    maybe_initialize_multihost_cli(args)
    np.random.seed(args.seed)
    args.num_results_train = 1

    if args.do_test:
        # pre-run CLI override: no round program exists yet for a
        # knob move to diverge from, so the waivers below are safe
        args.k = 10  # audit: allow(knob-mutation)
        args.num_cols = 100  # audit: allow(knob-mutation)
        args.num_rows = 1
        args.num_blocks = 1

    module, params, tokenizer = build_model_and_tokenizer(args)
    train_loader, val_loader, train_ds = get_data_loaders(args,
                                                          tokenizer)
    if args.num_clients is None:
        args.num_clients = int(train_ds.num_clients)

    if args.seq_devices > 1:
        from commefficient_tpu.runtime.fed_model_sp import (
            SeqParallelFedModel)
        model = SeqParallelFedModel(
            module, params, make_compute_loss_train(module, args),
            args, gpt2_cfg=module.cfg,
            compute_loss_val=make_compute_loss_val(module, args),
            padded_batch_size=train_loader.B)
    else:
        model = FedModel(module, params,
                         make_compute_loss_train(module, args), args,
                         compute_loss_val=make_compute_loss_val(module,
                                                                args),
                         padded_batch_size=train_loader.B)
    if hasattr(model, "attach_participant_feed") \
            and hasattr(train_loader, "peek_next_client_ids"):
        # host client store: one-round lookahead feeds the prefetcher
        model.attach_participant_feed(
            train_loader.peek_next_client_ids)
    opt = FedOptimizer([{"lr": 1.0}], args)

    spe = steps_per_epoch(args.local_batch_size, train_ds,
                          args.num_workers)
    horizon = args.schedule_epochs or args.num_epochs
    lambda_step = PiecewiseLinear([0, horizon * spe],
                                  [args.lr_scale, 0])
    lr_scheduler = LambdaLR(opt, lambda x: lambda_step(x))

    if args.do_finetune:
        # --finetune = eval only (reference gpt2_train.py:308-312)
        out = run_batches(model, opt, lr_scheduler, val_loader, args,
                          training=False)
        print({"val_nll": out[0], "val_acc": out[1], "val_ppl": out[2]})
        return out

    from commefficient_tpu.runtime.checkpoint import setup_resume
    start_epoch, epoch_hook, round_hook = setup_resume(
        args, model, opt, lr_scheduler, train_loader, tag="gpt2")

    if args.eval_before_start and start_epoch == 0:
        # (reference gpt2_train.py:207 via --eval_before_start);
        # skipped on resume — the restored model isn't "before start"
        out = run_batches(model, opt, lr_scheduler, val_loader, args,
                          training=False)
        print({"epoch": 0, "val_nll": out[0], "val_acc": out[1],
               "val_ppl": out[2]})

    # one logdir for the whole run: TB events, profiles, and the final
    # model/tokenizer save all land together (reference gpt2_train.py
    # computes log_dir once at startup, :278-283)
    from commefficient_tpu.utils import make_logdir
    logdir = make_logdir(args) if not args.do_test else None
    from commefficient_tpu.utils import GracefulShutdown, sigterm_raises
    interrupted = False
    try:
        with sigterm_raises():
            results = train_gpt2(model, opt, lr_scheduler,
                                 train_loader, val_loader, args,
                                 start_epoch=start_epoch,
                                 epoch_hook=epoch_hook,
                                 round_hook=round_hook, logdir=logdir)
    except GracefulShutdown as e:
        # crash safety: see cv_train.main — no save here; the last
        # round-cadence autosave is the consistent resume point
        print(f"interrupted ({e}); resume from the last autosave")
        interrupted = True
        results = []
        if model.flightrec is not None:
            # see cv_train.main — dump the postmortem before the
            # in-flight state it describes is discarded
            model.flightrec.dump("graceful_shutdown",
                                 context={"signal": str(e)})
        model.interrupted()
    model.finalize()
    from commefficient_tpu.runtime.checkpoint import \
        resume_manifest_extra
    from commefficient_tpu.telemetry import registry
    registry.maybe_write_manifest(
        args, mesh_shape=dict(model.mesh.shape),
        extra={"trainer": "gpt2_train", "epochs": len(results),
               "interrupted": interrupted,
               "diverged": bool(getattr(model, "diverged", False)),
               **resume_manifest_extra(model)})
    if logdir is not None and not getattr(model, "diverged", False) \
            and not interrupted and jax.process_index() == 0:
        # reference gpt2_train.py:146, 278-283: final model + tokenizer
        # saved HF-style into the run's logdir (skipped after a NaN
        # abort — diverged weights are not a final model)
        model.save_pretrained(logdir, hf_format=args.do_hf_export)
        tokenizer.save_pretrained(logdir)
        print(f"saved model + tokenizer to {logdir}"
              + (" (HF torch format)" if args.do_hf_export else ""))
    return results


if __name__ == "__main__":
    main(sys.argv[1:])
