"""CommEfficient-TPU: a TPU-native communication-efficient federated learning framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
amitport/CommEfficient (FetchSGD et al.): count-sketch gradient
compression, top-k sparsification, FedAvg local SGD, error feedback and
momentum (local or virtual), differential privacy, and per-client
communication accounting — built for SPMD execution over a TPU device
mesh rather than a parameter-server + NCCL worker topology.

Architecture (vs. the reference's process topology, see SURVEY.md §1):

- The reference runs 1 parameter-server process + N worker GPU
  processes connected by multiprocessing queues, host shared memory and
  one NCCL ``reduce`` per round.  Here a federated round is a single
  jitted SPMD program over a ``jax.sharding.Mesh``: participating
  clients are vmapped/sharded over the ``clients`` mesh axis, the
  gradient/sketch aggregation is a sum that XLA lowers to an ICI
  all-reduce, and the (deterministic) server step runs replicated on
  every device — no parameter-server rank exists.

- The entire model is a single flat f32 parameter vector (same
  invariant as reference fed_aggregator.py:81-97), produced by
  ``jax.flatten_util.ravel_pytree``; compression, error feedback,
  momentum and the server update all operate on this vector or on its
  ``(num_rows, num_cols)`` count-sketch.
"""

__version__ = "0.1.0"

from commefficient_tpu.config import Config, parse_args  # noqa: F401
