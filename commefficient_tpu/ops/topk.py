"""Magnitude top-k sparsification.

TPU-native counterpart of reference utils.py:232-252 (`_topk`): keep
the k largest-magnitude entries of a vector (or of each row of a
matrix), zeroing the rest. Uses `jax.lax.top_k`, which XLA lowers to a
fused partial sort — no NaN workarounds needed (the reference's
zero-initialised output dance at utils.py:239-244 is a CUDA quirk).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def topk(vec: jax.Array, k: int) -> jax.Array:
    """Return a copy of ``vec`` with everything but the ``k``
    largest-magnitude entries zeroed.

    1-D: global top-k. 2-D: row-wise top-k along the last axis
    (matching torch.topk's dim=-1 default used by the reference).
    """
    k = min(k, vec.shape[-1])
    if vec.ndim == 1:
        _, idx = jax.lax.top_k(jax.lax.square(vec), k)
        return jnp.zeros_like(vec).at[idx].set(vec[idx], mode="promise_in_bounds")
    elif vec.ndim == 2:
        _, idx = jax.lax.top_k(jax.lax.square(vec), k)
        rows = jnp.arange(vec.shape[0])[:, None]
        return jnp.zeros_like(vec).at[rows, idx].set(
            vec[rows, idx], mode="promise_in_bounds")
    raise ValueError(f"topk supports 1-D/2-D inputs, got ndim={vec.ndim}")


def topk_values_indices(vec: jax.Array, k: int):
    """(values, indices) of the k largest-magnitude entries of a 1-D
    vector — the sparse representation actually shipped over the wire
    when measuring upload bytes (k floats, fed_aggregator.py:296-297)."""
    _, idx = jax.lax.top_k(jax.lax.square(vec), min(k, vec.shape[-1]))
    return vec[idx], idx


def topk_with_support(vec: jax.Array, k: int):
    """``(dense, indices, values)`` top-k of a 1-D vector: the zeroed
    dense form plus its sparse support in one place (the canonical
    scatter lives here so sparse-support consumers don't re-derive it)."""
    vals, idx = topk_values_indices(vec, k)
    dense = jnp.zeros_like(vec).at[idx].set(vals,
                                            mode="promise_in_bounds")
    return dense, idx, vals
