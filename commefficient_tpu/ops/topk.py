"""Magnitude top-k sparsification.

TPU-native counterpart of reference utils.py:232-252 (`_topk`): keep
the k largest-magnitude entries of a vector (or of each row of a
matrix), zeroing the rest. Uses `jax.lax.top_k`, which XLA lowers to a
fused partial sort — no NaN workarounds needed (the reference's
zero-initialised output dance at utils.py:239-244 is a CUDA quirk).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Row size at/above which exact selection leaves lax.top_k (a full
# sort at large d on TPU). Current routing: DENSE selections use the
# threshold MASK + where (~3x at d = 6.6M, k = 50k on v5e); 1-D exact
# INDEX selection (unsketch recovery) uses the mask + hierarchical
# extraction (461.9 -> 103.2 ms at d = 124M — a naive jnp.nonzero
# compaction would be a d-sized scatter and lose to the sort, the
# blocked-cumsum extraction does not). Only batched index selections
# and approx_max_k requests remain on the XLA primitives. Numbers:
# BENCHMARKS.md, runs/exact_select.log.
_THRESHOLD_SELECT_MIN_D = 1 << 20
_approx_override_logged = False


def use_threshold_select(k: int, d: int, approx: bool) -> bool:
    """The ONE gating predicate for the exact threshold-select path
    (shared by the dense ``topk`` here, the server helpers and
    ``CountSketch.prefer_threshold_unsketch`` — keep them from
    drifting): exact selection, genuine selection (k < d), and a row
    large enough that lax.top_k's sort lowering loses."""
    return not approx and k < d and d >= _THRESHOLD_SELECT_MIN_D


def selection_may_duplicate(d: int, approx: bool) -> bool:
    """The ONE predicate for "can a k-selection's index vector carry
    duplicates": only the big-d approx path (``CountSketch.unsketch``'s
    degenerate-tie guard clamps approx_max_k's out-of-range zero-tie
    picks to duplicate (d-1, 0) pairs). Consumers scattering from such
    a selection must use ADD semantics and must NOT assert
    unique_indices (core/rounds.py server scatter, unsketch's dense
    form) — both derive from here so the big-d gate cannot drift."""
    return approx and d >= _THRESHOLD_SELECT_MIN_D


def _blocked_cumsum(x: jax.Array, block: int = 1024) -> jax.Array:
    """Inclusive cumsum along the last axis via intra-block scans plus
    block-offset scans. XLA's flat cumsum over tens of millions of
    elements lowers to a multi-pass scan (~60 ms at d = 124M on v5e);
    the blocked form runs one short vectorized scan over (B, block)
    plus a tiny scan over B (~6 ms). Exact same values."""
    *lead, d = x.shape
    pad = (-d) % block
    xp = jnp.pad(x, [(0, 0)] * len(lead) + [(0, pad)])
    xb = xp.reshape(tuple(lead) + (-1, block))
    intra = jnp.cumsum(xb, axis=-1)
    offs = jnp.cumsum(intra[..., -1], axis=-1)
    offs = jnp.concatenate(
        [jnp.zeros_like(offs[..., :1]), offs[..., :-1]], axis=-1)
    out = (intra + offs[..., None]).reshape(
        tuple(lead) + (d + pad,))
    return out[..., :d]


def _threshold_topk_mask(sq: jax.Array, k: int) -> jax.Array:
    """Exact top-k selection MASK of non-negative ``sq`` along the
    last axis without sorting: binary-search the k-th largest value
    one bit at a time (non-negative f32 order == unsigned-int order on
    the bit pattern; 32 masked count-reductions stream the row instead
    of sorting it), then tie-break equal values by lowest index — the
    same selected set as ``lax.top_k`` (which also prefers lower
    indices on ties). Batched over leading axes; returns a boolean
    mask with exactly k True per row."""
    shape = sq.shape
    d = shape[-1]
    rows = 1
    for s in shape[:-1]:
        rows *= s
    keys = jax.lax.bitcast_convert_type(
        sq.astype(jnp.float32), jnp.uint32).reshape(rows, d)

    # 32 single-bit passes, NOT the nibble search: under vmap (the
    # local_topk per-client masking) the batched nibble histogram
    # lowers worse than this simple loop (29.2 vs 20.3 ms/round
    # measured at ResNet9 scale); the nibble search wins only on the
    # 1-D fast path (threshold_topk_mask_1d)
    def body(i, thresh):
        bit = jnp.uint32(31) - i.astype(jnp.uint32)
        cand = thresh | (jnp.uint32(1) << bit)  # (rows,)
        cnt = jnp.sum((keys >= cand[:, None]).astype(jnp.int32),
                      axis=-1)
        return jnp.where(cnt >= k, cand, thresh)

    t = jax.lax.fori_loop(0, 32, body,
                          jnp.zeros((rows,), jnp.uint32))
    gt = keys > t[:, None]
    eq = keys == t[:, None]
    need = k - jnp.sum(gt.astype(jnp.int32), -1, keepdims=True)
    take = gt | (eq & (_blocked_cumsum(eq.astype(jnp.int32))
                       <= need))
    return take.reshape(shape)


def _nibble_threshold_key(keys: jax.Array, k: int,
                          axis_name: str = None,
                          valid: jax.Array = None) -> jax.Array:
    """k-th largest uint32 key of 1-D ``keys`` by an 8-pass 4-bit
    radix search (vs 32 single-bit passes): each pass histograms the
    current nibble among prefix-matching elements in one streamed
    read — same T as a single-bit binary search (tested), ~40% less
    search traffic at d = 124M. 1-D only: the batched variant was
    measured SLOWER than the single-bit loop under vmap (see
    _threshold_topk_mask).

    ``axis_name``: sum each pass's 16-bucket histogram over that mesh
    axis (``jax.lax.psum``) — the k-th key of the GLOBAL key
    population when ``keys`` is one shard of a vector distributed
    along the axis. Eight tiny (16,) all-reduces; every shard agrees
    on the same threshold. ``valid``: boolean mask excluding padding
    slots from the population (a zero key is a legitimate candidate —
    padding must be masked, not zeroed). Both default to None, which
    keeps the emitted single-device program byte-identical to before
    the parameters existed."""
    assert keys.ndim == 1

    def body(i, carry):
        t, remaining = carry
        shift = jnp.uint32(28) - 4 * i.astype(jnp.uint32)
        # prefix compare as two shifts of <= 28 and 4 bits — a single
        # shift by (shift + 4) would be a shift-by-32 on pass 0,
        # implementation-defined; this form is well-defined and yields
        # the correct all-match on the empty pass-0 prefix
        match = (((keys ^ t) >> shift) >> 4) == 0
        if valid is not None:
            match = match & valid
        nib = (keys >> shift) & 15
        counts = jnp.stack([
            jnp.sum((match & (nib == b)).astype(jnp.int32))
            for b in range(16)])
        if axis_name is not None:
            counts = jax.lax.psum(counts, axis_name)
        suffix = jnp.cumsum(counts[::-1])[::-1]  # count(nib >= b)
        ge = suffix >= remaining
        b = jnp.max(jnp.where(ge, jnp.arange(16), 0)).astype(jnp.uint32)
        above = jnp.where(b < 15, suffix[jnp.minimum(b + 1, 15)], 0)
        return (t | (b << shift), remaining - above)

    t, _ = jax.lax.fori_loop(0, 8, body,
                             (jnp.uint32(0), jnp.int32(k)))
    return t


def _take_from_threshold_1d(keys: jax.Array, t: jax.Array,
                            need) -> jax.Array:
    """take = (> t) ∪ (first ``need`` == t in index order) — the ONE
    XLA construction of the tie-broken mask (the Pallas kernel and
    the batched mask implement the same rule; equivalence-tested)."""
    gt = keys > t
    eq = keys == t
    return gt | (eq & (_blocked_cumsum(eq.astype(jnp.int32))
                       <= need))


def distributed_threshold_mask_1d(sq: jax.Array, k: int,
                                  axis_name: str,
                                  valid: jax.Array = None) -> jax.Array:
    """Exact global top-k selection MASK over non-negative values
    sharded along mesh axis ``axis_name``, where shard p holds the
    coordinates of a contiguous ascending slice (slices ordered by
    ``axis_index``). Runs inside shard_map: the nibble radix search
    agrees the global k-th key via psum'd histograms, then threshold
    ties are taken in GLOBAL lowest-index order — an exclusive
    cross-shard prefix of per-shard tie counts (one (1,) all-gather)
    tells each shard how many of its own ties survive. ``valid``
    masks padding slots out of the population entirely. The union of
    the returned local masks has exactly min(k, #valid) True bits and
    is the same selected set as the single-device threshold select /
    ``lax.top_k`` (lowest-index tie-break)."""
    assert sq.ndim == 1
    keys = jax.lax.bitcast_convert_type(
        sq.astype(jnp.float32), jnp.uint32)
    t = _nibble_threshold_key(keys, k, axis_name=axis_name,
                              valid=valid)
    gt = keys > t
    eq = keys == t
    if valid is not None:
        gt = gt & valid
        eq = eq & valid
    need = k - jax.lax.psum(jnp.sum(gt.astype(jnp.int32)), axis_name)
    eq_counts = jax.lax.all_gather(
        jnp.sum(eq.astype(jnp.int32)), axis_name)  # (n_shards,)
    p = jax.lax.axis_index(axis_name)
    before = jnp.sum(jnp.where(
        jnp.arange(eq_counts.shape[0]) < p, eq_counts, 0))
    local_need = need - before  # <= 0: this shard takes no ties
    return gt | (eq & (_blocked_cumsum(eq.astype(jnp.int32))
                       <= local_need))


def threshold_topk_mask_1d(sq: jax.Array, k: int, *,
                           interpret: bool = False,
                           force_xla: bool = False) -> jax.Array:
    """Fast 1-D exact threshold mask for the server-side selections
    (never vmapped): nibble radix search for the k-th largest key,
    then — on TPU — the fused Pallas take-mask kernel (one streamed
    read + int8 write instead of the XLA path's several (d,)-sized
    intermediates; ops/topk_pallas.py). Falls back to the generic
    XLA mask elsewhere. Same exactly-k, lowest-index-tie-break
    semantics (equivalence-tested; ``interpret``/``force_xla`` are
    test hooks selecting the branch explicitly)."""
    assert sq.ndim == 1
    d = sq.shape[0]
    keys = jax.lax.bitcast_convert_type(
        sq.astype(jnp.float32), jnp.uint32)
    t = _nibble_threshold_key(keys, k)
    from commefficient_tpu.ops import topk_pallas
    need = k - jnp.sum((keys > t).astype(jnp.int32))
    if force_xla or not topk_pallas.supported(d):
        return _take_from_threshold_1d(keys, t, need)
    if interpret:  # test hook: Pallas interpreter on any backend
        return topk_pallas.take_mask_pallas(
            sq.astype(jnp.float32), t.reshape(1), need.reshape(1),
            interpret=True)

    # branch selected at LOWERING time per platform (lax.platform_
    # dependent), not from jax.default_backend() at trace time: a
    # jit(..., backend="cpu") on a TPU-initialized process — or any
    # multi-backend embedder — gets the XLA mask, while tpu/axon
    # lowerings get the fused Pallas take-mask kernel. Both branches
    # compute the identical exactly-k, lowest-index-tie-break mask
    # (equivalence-tested).
    def _pallas(sqf, t, need):
        return topk_pallas.take_mask_pallas(
            sqf, t.reshape(1), need.reshape(1))

    def _xla(sqf, t, need):
        return _take_from_threshold_1d(
            jax.lax.bitcast_convert_type(sqf, jnp.uint32), t, need)

    return jax.lax.platform_dependent(
        sq.astype(jnp.float32), t, need,
        tpu=_pallas, axon=_pallas, default=_xla)


def _threshold_topk_idx(sq: jax.Array, k: int) -> jax.Array:
    """Indices (ascending) of the threshold-select mask — used by
    tests to check set equivalence with lax.top_k; the hot paths use
    the mask directly (``jnp.nonzero`` compaction is a d-sized
    scatter) or the hierarchical extraction below."""
    take = _threshold_topk_mask(sq, k)

    def row_nonzero(m):
        return jnp.nonzero(m, size=k, fill_value=0)[0]

    if take.ndim == 1:
        return row_nonzero(take)
    flat = take.reshape(-1, take.shape[-1])
    return jax.vmap(row_nonzero)(flat).reshape(
        take.shape[:-1] + (k,))


def threshold_topk_indices(sq: jax.Array, k: int,
                           block: int = 1024) -> jax.Array:
    """Exact top-k INDICES (ascending) of non-negative 1-D ``sq``
    without sorting and without a d-sized scatter: the threshold mask
    (32 streaming count passes) followed by hierarchical compaction —
    blockwise cumsums locate each output slot's block (searchsorted
    over block totals) and its column (argmax over the gathered block
    cumsum row). O(d) streaming + O(k·block) gather work, vs
    lax.top_k's full sort: 461.9 -> 103.2 ms at d = 124M, k = 50k on
    v5e — the selection behind exact unsketch recovery at GPT-2
    scale (BENCHMARKS.md, runs/exact_select.log). Same selected set
    as lax.top_k, including the lowest-index tie-break."""
    assert sq.ndim == 1, "hierarchical extraction is 1-D"
    d = sq.shape[0]
    take = threshold_topk_mask_1d(sq, k)  # exactly k set bits
    pad = (-d) % block
    bits = jnp.pad(take, (0, pad)).reshape(-1, block)
    intra = jnp.cumsum(bits.astype(jnp.int32), axis=-1)  # (B, block)
    cum = jnp.cumsum(intra[:, -1])  # inclusive block totals (B,)
    slots = jnp.arange(k, dtype=jnp.int32)
    b = jnp.searchsorted(cum, slots, side="right").astype(jnp.int32)
    offs = cum[b] - intra[b, -1]  # exclusive offset of block b
    j = slots - offs  # rank within block, 0-based
    rows = intra[b]  # (k, block) gather
    col = jnp.argmax(rows > j[:, None], axis=1).astype(jnp.int32)
    return b * block + col


def _select_idx(vec: jax.Array, k: int, approx: bool,
                recall: float) -> jax.Array:
    """Indices of the k largest-magnitude entries along the last axis
    — the ONE place that chooses exact ``top_k`` vs
    ``approx_max_k`` (see ``topk`` for the tradeoff)."""
    if approx and k < vec.shape[-1]:
        _, idx = jax.lax.approx_max_k(jax.lax.square(vec), k,
                                      recall_target=recall)
    else:
        _, idx = jax.lax.top_k(jax.lax.square(vec), k)
    return idx


def topk(vec: jax.Array, k: int, approx: bool = False,
         recall: float = 0.95) -> jax.Array:
    """Return a copy of ``vec`` with everything but the ``k``
    largest-magnitude entries zeroed.

    1-D: global top-k. 2-D: row-wise top-k along the last axis
    (matching torch.topk's dim=-1 default used by the reference).

    ``approx``: use ``lax.approx_max_k`` at the given recall — the
    same --approx_topk tradeoff as unsketch recovery (missed
    coordinates stay in the error accumulator and resurface next
    round).

    At large rows (>= _THRESHOLD_SELECT_MIN_D) the DENSE selection
    always uses the exact threshold path — the mask (32 streaming
    count passes) feeds a ``where``, no sort and no gather/scatter —
    which measures faster than even ``approx_max_k`` + scatter while
    being exact (127 → 20 ms for the full local_topk round at ResNet9
    scale, BENCHMARKS.md). ``approx`` therefore only affects dense
    selections below the threshold size; the index-producing
    selections (unsketch recovery) still honor it everywhere."""
    k = min(k, vec.shape[-1])
    if vec.ndim not in (1, 2):
        raise ValueError(
            f"topk supports 1-D/2-D inputs, got ndim={vec.ndim}")
    if k < vec.shape[-1] \
            and vec.shape[-1] >= _THRESHOLD_SELECT_MIN_D:
        if approx:
            # once per process: --approx_topk runs at this size now
            # select a (different, exact) set than pre-round-3 builds
            # did — surface why comparisons against older runs moved
            global _approx_override_logged
            if not _approx_override_logged:
                _approx_override_logged = True
                import logging
                logging.getLogger(__name__).info(
                    "approx=True ignored for dense selection at d=%d "
                    ">= %d: the exact threshold-select path is faster "
                    "than the approximate sort (BENCHMARKS.md); "
                    "selected sets differ from pre-threshold-select "
                    "builds", vec.shape[-1], _THRESHOLD_SELECT_MIN_D)
        take = _threshold_topk_mask(jax.lax.square(vec), k)
        return jnp.where(take, vec, jnp.zeros_like(vec))
    idx = _select_idx(vec, k, approx, recall)
    if vec.ndim == 1:
        return jnp.zeros_like(vec).at[idx].set(vec[idx], mode="promise_in_bounds")
    rows = jnp.arange(vec.shape[0])[:, None]
    return jnp.zeros_like(vec).at[rows, idx].set(
        vec[rows, idx], mode="promise_in_bounds")


def topk_values_indices(vec: jax.Array, k: int, approx: bool = False,
                        recall: float = 0.95):
    """(values, indices) of the k largest-magnitude entries of a 1-D
    vector — the sparse representation actually shipped over the wire
    when measuring upload bytes (k floats, fed_aggregator.py:296-297)."""
    idx = _select_idx(vec, min(k, vec.shape[-1]), approx, recall)
    return vec[idx], idx


def topk_with_support(vec: jax.Array, k: int, approx: bool = False,
                      recall: float = 0.95):
    """``(dense, indices, values)`` top-k of a 1-D vector: the zeroed
    dense form plus its sparse support in one place (the canonical
    scatter lives here so sparse-support consumers don't re-derive
    it). ``approx``: lax.approx_max_k selection (see ``topk``)."""
    vals, idx = topk_values_indices(vec, k, approx, recall)
    dense = jnp.zeros_like(vec).at[idx].set(vals,
                                            mode="promise_in_bounds")
    return dense, idx, vals
