"""Magnitude top-k sparsification.

TPU-native counterpart of reference utils.py:232-252 (`_topk`): keep
the k largest-magnitude entries of a vector (or of each row of a
matrix), zeroing the rest. Uses `jax.lax.top_k`, which XLA lowers to a
fused partial sort — no NaN workarounds needed (the reference's
zero-initialised output dance at utils.py:239-244 is a CUDA quirk).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _select_idx(vec: jax.Array, k: int, approx: bool,
                recall: float) -> jax.Array:
    """Indices of the k largest-magnitude entries along the last axis
    — the ONE place that chooses exact ``top_k`` vs
    ``approx_max_k`` (see ``topk`` for the tradeoff)."""
    if approx and k < vec.shape[-1]:
        _, idx = jax.lax.approx_max_k(jax.lax.square(vec), k,
                                      recall_target=recall)
    else:
        _, idx = jax.lax.top_k(jax.lax.square(vec), k)
    return idx


def topk(vec: jax.Array, k: int, approx: bool = False,
         recall: float = 0.95) -> jax.Array:
    """Return a copy of ``vec`` with everything but the ``k``
    largest-magnitude entries zeroed.

    1-D: global top-k. 2-D: row-wise top-k along the last axis
    (matching torch.topk's dim=-1 default used by the reference).

    ``approx``: use ``lax.approx_max_k`` at the given recall — exact
    ``top_k`` at k=50k over millions of coords lowers to a full sort
    on TPU (~88 ms at d=6.6M, the dominant cost of a local_topk
    round); the approximate selection is the same --approx_topk
    tradeoff as unsketch recovery (missed coordinates stay in the
    error accumulator and resurface next round)."""
    k = min(k, vec.shape[-1])
    idx = _select_idx(vec, k, approx, recall)
    if vec.ndim == 1:
        return jnp.zeros_like(vec).at[idx].set(vec[idx], mode="promise_in_bounds")
    elif vec.ndim == 2:
        rows = jnp.arange(vec.shape[0])[:, None]
        return jnp.zeros_like(vec).at[rows, idx].set(
            vec[rows, idx], mode="promise_in_bounds")
    raise ValueError(f"topk supports 1-D/2-D inputs, got ndim={vec.ndim}")


def topk_values_indices(vec: jax.Array, k: int, approx: bool = False,
                        recall: float = 0.95):
    """(values, indices) of the k largest-magnitude entries of a 1-D
    vector — the sparse representation actually shipped over the wire
    when measuring upload bytes (k floats, fed_aggregator.py:296-297)."""
    idx = _select_idx(vec, min(k, vec.shape[-1]), approx, recall)
    return vec[idx], idx


def topk_with_support(vec: jax.Array, k: int, approx: bool = False,
                      recall: float = 0.95):
    """``(dense, indices, values)`` top-k of a 1-D vector: the zeroed
    dense form plus its sparse support in one place (the canonical
    scatter lives here so sparse-support consumers don't re-derive
    it). ``approx``: lax.approx_max_k selection (see ``topk``)."""
    vals, idx = topk_values_indices(vec, k, approx, recall)
    dense = jnp.zeros_like(vec).at[idx].set(vals,
                                            mode="promise_in_bounds")
    return dense, idx, vals
