"""Fused tied-head cross-entropy (fused-linear-CE) Pallas kernels.

The GPT-2 training loss computes ``CE(h @ wte.T, labels)`` where the
(tokens, vocab) logits tensor is ~200 MB f32 per 1k tokens at GPT-2
vocab. The chunked formulation (models/gpt2.py lm_nll_sums_chunked,
the reference loss is gpt2_train.py:88-99) bounds *peak memory* to one
chunk, but each chunk's logits still round-trip HBM up to three times
(forward store+load, checkpointed-backward recompute), and the
backward re-derives the logsumexp it already computed.

These kernels never write logits to HBM at all:

- ``_flce_fwd``: grid (token-blocks, vocab-blocks), vocab inner. Each
  step computes one (BM, BV) logits tile on the MXU and folds it into
  running online-softmax stats (max, sumexp) plus the label-logit
  gather, all VMEM-resident; per-token (lse, tok) vectors are the only
  HBM writes.
- ``_flce_bwd``: grid (vocab-blocks, token-blocks), token inner. One
  logits-tile recompute feeds BOTH gradient products:
  ``dW[j] += d_logitsᵀ @ x`` accumulates f32 in VMEM across the inner
  token loop (written once per vocab block), while ``d_logits @ W[j]``
  lands as a per-vocab-block partial of dX, summed by one cheap XLA
  reduction outside. Total backward matmul work equals the
  checkpointed chunked path (recompute + two products); the logits /
  d_logits HBM round-trips and the duplicate logsumexp pass are gone.

``lm_nll_sums_fused`` is a drop-in for ``lm_nll_sums_chunked`` (same
(Σ nll, Σ valid) per-example contract, same masking semantics) and
falls back to it off-TPU or at unsupported geometries. Gradients are
wired with jax.custom_vjp; vmap (the per-client axis in the federated
round) batches the pallas_call with a leading grid dimension as usual.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the params class was renamed TPUCompilerParams -> CompilerParams
# across JAX releases; accept either so the kernels (and their
# interpret-mode tests) run on both
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

# Default tiles: (1024, 2048) keeps the weight-streaming traffic low
# (W is re-read once per token block: M/BM * |W|) while the f32
# logits tile (8 MB) and the backward's f32 dW accumulator (6.3 MB)
# stay comfortably inside VMEM. _STATS_LANES follows the TPU
# flash-attention convention: per-row running stats live in a
# (BM, 128) scratch (one full vreg lane-width) rather than a (BM, 1)
# column, which Mosaic lays out poorly.
_BLOCK_M = 1024
_BLOCK_V = 2048
_STATS_LANES = 128
_VMEM_LIMIT = 100 * 1024 * 1024
# The backward's dX comes out as per-vocab-block partials (nv, M, C)
# summed by one XLA reduction — 4x cheaper than the alternatives (an
# i-outer grid's dW partials are (nm, V, C) f32, ~4x larger at every
# M; a second dX kernel pass re-pays the full logits recompute,
# ~9x the partials' HBM traffic at GPT-2 vocab/width). The buffer is
# transient but real: nv * M * C * 2 bytes per call (times the client
# axis under vmap), so calls whose partials would exceed this cap
# fall back to the chunked path instead of risking an HBM OOM the
# chunked path doesn't have. 512 MB admits the T=1024 long-context
# geometry (M=8184 -> 315 MB/client) with an order of magnitude of
# HBM headroom at the benched client counts.
_DXP_LIMIT = 512 * 1024 * 1024


def supported(c: int) -> bool:
    """Pallas path requires a lane-aligned embedding width, and the
    backward's VMEM residents must fit the compiler budget: the f32
    dW accumulator (BV, C) + double-buffered w/x tiles + the f32
    logits/d_logits temporaries ((BM, BV), C-independent). Token and
    vocab counts are padded to tile multiples internally."""
    if c % 128 != 0:
        return False
    acc = _BLOCK_V * c * 4
    tiles = 2 * (_BLOCK_V * c * 2 + _BLOCK_M * c * 2)
    temps = 3 * _BLOCK_M * _BLOCK_V * 4
    return acc + tiles + temps <= _VMEM_LIMIT


def _fwd_kernel(lab_ref, x_ref, w_ref, lse_ref, tok_ref, m_s, s_s, t_s,
                *, nv, v_actual, block_v):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s[...], -jnp.inf)
        s_s[...] = jnp.zeros_like(s_s[...])
        t_s[...] = jnp.zeros_like(t_s[...])

    x = x_ref[...]                                    # (BM, C)
    w = w_ref[...]                                    # (BV, C)
    logits = jax.lax.dot_general(
        x, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # (BM, BV)
    vid = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1)
    logits = jnp.where(vid < v_actual, logits, -jnp.inf)

    lab = lab_ref[...]                                # (BM, 1)
    m_prev = m_s[...][:, :1]
    bmax = jnp.max(logits, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, bmax)
    # first block: exp(-inf - finite) == 0 folds the empty carry in
    s_new = (s_s[...][:, :1] * jnp.exp(m_prev - m_new)
             + jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True))
    # at most one vocab block contains the (in-range) label; the
    # where() keeps padded-vocab -inf out of the 0-weighted sum
    t_new = t_s[...][:, :1] + jnp.sum(
        jnp.where(vid == lab, logits, 0.0), axis=1, keepdims=True)

    m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
    s_s[...] = jnp.broadcast_to(s_new, s_s.shape)
    t_s[...] = jnp.broadcast_to(t_new, t_s.shape)

    @pl.when(j == nv - 1)
    def _write():
        lse_ref[...] = m_new + jnp.log(s_new)
        tok_ref[...] = t_new


def _bwd_kernel(lab_ref, x_ref, w_ref, lse_ref, gl_ref, gt_ref,
                dxp_ref, dw_ref, acc, *, nm, v_actual, block_v,
                compute_dtype):
    j = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc[...])

    x = x_ref[...]                                    # (BM, C)
    w = w_ref[...]                                    # (BV, C)
    logits = jax.lax.dot_general(
        x, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # (BM, BV)
    vid = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1)
    lse = lse_ref[...]                                # (BM, 1)
    # padded-vocab columns (w rows are zero-padded, so logits there
    # are 0, not -inf as in the forward) must not leak into p
    p = jnp.where(vid < v_actual, jnp.exp(logits - lse), 0.0)
    d = gl_ref[...] * p + gt_ref[...] * (vid == lab_ref[...]).astype(
        jnp.float32)                                  # (BM, BV) f32
    dc = d.astype(compute_dtype)
    dxp_ref[...] = jax.lax.dot_general(
        dc, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(
            dxp_ref.dtype)[None]                      # (1, BM, C)
    acc[...] += jax.lax.dot_general(
        dc, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # (BV, C)

    @pl.when(i == nm - 1)
    def _write():
        dw_ref[...] = acc[...]


def _pad_rows(a, rows):
    return jnp.pad(a, ((0, rows - a.shape[0]),) + ((0, 0),) * (a.ndim - 1))


def _tile_geometry(m, v, block_m, block_v):
    """Shared fwd/bwd tiling: the custom_vjp backward MUST reproduce
    the forward's padding exactly for the residuals to line up, so
    both sides derive it here. Returns (bm, mp, vp, nm, nv)."""
    bm = min(block_m, max(8, -(-m // 8) * 8))
    mp = -(-m // bm) * bm
    vp = -(-v // block_v) * block_v
    return bm, mp, vp, mp // bm, vp // block_v


def _pad_operands(x, w, labels, mp, vp):
    """Zero-pad x/w to tile multiples; padded token rows get label -1
    (never matches a vocab id, and their cotangents are zero)."""
    xp = _pad_rows(x, mp)
    wp = _pad_rows(w, vp)
    lp = jnp.pad(labels.astype(jnp.int32), (0, mp - x.shape[0]),
                 constant_values=-1).reshape(mp, 1)
    return xp, wp, lp


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flce_lse_tok(x, w, labels, block_m=_BLOCK_M, block_v=_BLOCK_V,
                 interpret=False):
    """Per-token (logsumexp, label-logit) of ``x @ w.T`` without
    materialising the (M, V) logits. ``labels`` must be in-range
    (callers substitute 0 for ignored positions and mask outside).
    Differentiable in x and w; nll = lse - tok."""
    lse, tok = _flce_fwd_impl(x, w, labels, block_m, block_v, interpret)
    return lse, tok


def _flce_fwd_impl(x, w, labels, block_m, block_v, interpret):
    m, c = x.shape
    v = w.shape[0]
    bm, mp, vp, nm, nv = _tile_geometry(m, v, block_m, block_v)
    xp, wp, lp = _pad_operands(x, w, labels, mp, vp)

    lse, tok = pl.pallas_call(
        partial(_fwd_kernel, nv=nv, v_actual=v, block_v=block_v),
        grid=(nm, nv),
        in_specs=[
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, c), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_v, c), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, 1), jnp.float32),
            jax.ShapeDtypeStruct((mp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bm, _STATS_LANES), jnp.float32),
            pltpu.VMEM((bm, _STATS_LANES), jnp.float32),
            pltpu.VMEM((bm, _STATS_LANES), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(lp, xp, wp)
    return lse[:m, 0], tok[:m, 0]


def _flce_vjp_fwd(x, w, labels, block_m, block_v, interpret):
    lse, tok = _flce_fwd_impl(x, w, labels, block_m, block_v, interpret)
    return (lse, tok), (x, w, labels, lse)


def _flce_vjp_bwd(block_m, block_v, interpret, res, g):
    x, w, labels, lse = res
    g_lse, g_tok = g
    m, c = x.shape
    v = w.shape[0]
    bm, mp, vp, nm, nv = _tile_geometry(m, v, block_m, block_v)
    xp, wp, lp = _pad_operands(x, w, labels, mp, vp)
    # padded token rows carry zero cotangent, so their (garbage) lse
    # rows and p values contribute nothing to either product
    lsep = jnp.pad(lse, (0, mp - m)).reshape(mp, 1)
    glp = jnp.pad(g_lse.astype(jnp.float32), (0, mp - m)).reshape(mp, 1)
    gtp = jnp.pad(g_tok.astype(jnp.float32), (0, mp - m)).reshape(mp, 1)

    dxp, dw = pl.pallas_call(
        partial(_bwd_kernel, nm=nm, v_actual=v, block_v=block_v,
                compute_dtype=x.dtype),
        grid=(nv, nm),
        in_specs=[
            pl.BlockSpec((bm, 1), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, c), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_v, c), lambda j, i: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, 1), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, 1), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, 1), lambda j, i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, bm, c), lambda j, i: (j, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_v, c), lambda j, i: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nv, mp, c), x.dtype),
            jax.ShapeDtypeStruct((vp, c), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_v, c), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            vmem_limit_bytes=_VMEM_LIMIT),
        interpret=interpret,
    )(lp, xp, wp, lsep, glp, gtp)

    # f32 partials reduction: the nv per-vocab-block dX contributions
    # are near-cancelling around softmax mass, so a bf16 tree-sum
    # loses mantissa exactly where the gradient is smallest — bound
    # the rounding to the final cast
    dx = jnp.sum(dxp, axis=0, dtype=jnp.float32)[:m].astype(x.dtype)
    dwo = dw[:v].astype(w.dtype)
    return dx, dwo, np.zeros(labels.shape, jax.dtypes.float0)


flce_lse_tok.defvjp(_flce_vjp_fwd, _flce_vjp_bwd)


def resolve_fused_ce(flag: str, n_embd: int) -> bool:
    """Build-time resolution of --fused_ce (same pattern as
    core.rounds.resolve_rot_lanes): "auto" engages the Pallas path
    only when the process's default backend is TPU and the width is
    lane-aligned — programs built here then jitted onto another
    backend should pass "off"/"on" explicitly."""
    if flag == "on":
        return True
    if flag == "off":
        return False
    return jax.default_backend() == "tpu" and supported(n_embd)


_warned_fallbacks: set = set()


def fused_fallback_reason(e, tm, c, v, dtype, interpret=False,
                          batch_mult=1):
    """Why ``lm_nll_sums_fused`` would take the chunked path for this
    geometry — None when the fused kernels engage.

    ``batch_mult`` is the caller's vmapped multiplicity (the round's
    client axis): the dX-partials buffer exists once PER mapped call
    concurrently, so the OOM guard must scale by it — 8 clients x
    315 MB must not pass a 512 MB per-call check."""
    if not supported(c):
        return (f"embedding width {c} is not lane-aligned / "
                "VMEM-admissible")
    _, mp, _, _, nv = _tile_geometry(e * tm, v, _BLOCK_M, _BLOCK_V)
    dxp_bytes = max(1, int(batch_mult)) * nv * mp * c \
        * jnp.dtype(dtype).itemsize
    if dxp_bytes > _DXP_LIMIT:
        return (f"dX partials would be {dxp_bytes >> 20} MB "
                f"(x{max(1, int(batch_mult))} vmapped calls) — over "
                f"the {_DXP_LIMIT >> 20} MB cap")
    if not interpret and jax.default_backend() != "tpu":
        return (f"default backend is {jax.default_backend()!r}, "
                "not tpu (Mosaic kernels cannot lower)")
    return None


def lm_nll_sums_fused(h, wte, labels, dtype, ignore_index=-100,
                      tokens_per_chunk=1024, interpret=False,
                      batch_mult=1):
    """Drop-in for models.gpt2.lm_nll_sums_chunked backed by the
    fused kernels: per-example (Σ nll, Σ valid) of the tied-head LM
    cross-entropy, logits never materialised even per chunk. Falls
    back to the chunked path (honoring ``tokens_per_chunk``) at
    non-lane-aligned widths, when the backward's dX partials would
    exceed _DXP_LIMIT across ``batch_mult`` concurrent vmapped calls,
    and — unless ``interpret`` — on non-TPU default backends, where
    the Mosaic kernels cannot lower. The fallback warns once per
    reason: it used to be silent, so flce_bench could 'measure' the
    chunked path against itself."""
    e, tm, c = h.shape
    reason = fused_fallback_reason(e, tm, c, wte.shape[0], dtype,
                                   interpret=interpret,
                                   batch_mult=batch_mult)
    if reason is not None:
        if reason not in _warned_fallbacks:
            _warned_fallbacks.add(reason)
            import warnings
            warnings.warn("lm_nll_sums_fused falling back to the "
                          "chunked path: " + reason)
        from commefficient_tpu.models.gpt2 import lm_nll_sums_chunked
        return lm_nll_sums_chunked(h, wte, labels, dtype,
                                   ignore_index=ignore_index,
                                   tokens_per_chunk=tokens_per_chunk)
    x = h.astype(dtype).reshape(e * tm, c)
    w = wte.astype(dtype)
    lab = labels.reshape(e * tm)
    valid = lab != ignore_index
    safe = jnp.where(valid, lab, 0)
    lse, tok = flce_lse_tok(x, w, safe, _BLOCK_M, _BLOCK_V, interpret)
    nll = jnp.where(valid, lse - tok, 0.0).reshape(e, tm)
    sv = valid.reshape(e, tm).astype(jnp.float32)
    return jnp.sum(nll, axis=1), jnp.sum(sv, axis=1)
