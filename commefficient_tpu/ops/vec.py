"""Flat parameter-vector utilities.

The framework keeps the reference's core invariant — the whole model is
one flat f32 vector (reference fed_aggregator.py:81-97,
utils.py:254-297) — via `jax.flatten_util.ravel_pytree`: flatten once
at init, unravel (a cheap reshape/slice fusion under jit) inside every
forward pass.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def flatten_params(params: Any) -> Tuple[jax.Array, Callable[[jax.Array], Any]]:
    """pytree -> (flat f32 vector, unravel_fn).

    Counterpart of reference get_param_vec/set_param_vec
    (utils.py:281-297); unlike the reference there is no mutable module
    to scatter back into — ``unravel_fn`` reconstitutes the pytree
    functionally inside the jitted step.
    """
    flat, unravel = ravel_pytree(params)
    return flat.astype(jnp.float32), unravel


def param_group_indices(params: Any, *predicates):
    """Flat-vector index arrays grouping leaves by parameter-path name.

    TPU-native form of the reference's named-parameter param groups
    (cv_train.py:366-376: Fixup bias/scale/other LR groups). Each
    predicate receives the leaf's path string (e.g.
    ``['FixupLayer_0']['bias1a']``); a leaf joins the first predicate
    that matches, unmatched leaves join a final catch-all group.
    Indices are positions in the ``flatten_params`` vector (leaf order
    of ``ravel_pytree`` == ``tree_flatten_with_path``), so unlike the
    reference's concatenated-in-group-order LR vector
    (fed_aggregator.py:413-429) the resulting per-coordinate LRs are
    exactly aligned with the flat gradient.
    """
    import numpy as np
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(params)
    spans = [[] for _ in range(len(predicates) + 1)]
    offset = 0
    for path, leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        name = keystr(path)
        for i, pred in enumerate(predicates):
            if pred(name):
                spans[i].append((offset, n))
                break
        else:
            spans[-1].append((offset, n))
        offset += n
    return [np.concatenate([np.arange(o, o + n) for o, n in s])
            if s else np.empty(0, np.int64) for s in spans]


def global_norm(vec: jax.Array) -> jax.Array:
    return jnp.sqrt(jnp.sum(jax.lax.square(vec)))


def clip_by_l2(vec: jax.Array, clip: float) -> jax.Array:
    """L2-clip to norm ``clip`` — only shrinks, never grows
    (reference utils.py:305-313 ``clip_grad`` dense branch)."""
    norm = global_norm(vec)
    scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    return vec * scale


def clip_by_global_norm_tree(tree: Any, max_norm: float) -> Any:
    """torch.nn.utils.clip_grad_norm_ analogue for pytrees
    (used pre-weight-decay, reference fed_worker.py:292-294)."""
    leaves = jax.tree_util.tree_leaves(tree)
    norm = jnp.sqrt(sum(jnp.sum(jax.lax.square(l)) for l in leaves))
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(lambda l: l * scale, tree)
