"""Flat parameter-vector utilities.

The framework keeps the reference's core invariant — the whole model is
one flat f32 vector (reference fed_aggregator.py:81-97,
utils.py:254-297) — via `jax.flatten_util.ravel_pytree`: flatten once
at init, unravel (a cheap reshape/slice fusion under jit) inside every
forward pass.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def flatten_params(params: Any) -> Tuple[jax.Array, Callable[[jax.Array], Any]]:
    """pytree -> (flat f32 vector, unravel_fn).

    Counterpart of reference get_param_vec/set_param_vec
    (utils.py:281-297); unlike the reference there is no mutable module
    to scatter back into — ``unravel_fn`` reconstitutes the pytree
    functionally inside the jitted step.
    """
    flat, unravel = ravel_pytree(params)
    return flat.astype(jnp.float32), unravel


def global_norm(vec: jax.Array) -> jax.Array:
    return jnp.sqrt(jnp.sum(jax.lax.square(vec)))


def clip_by_l2(vec: jax.Array, clip: float) -> jax.Array:
    """L2-clip to norm ``clip`` — only shrinks, never grows
    (reference utils.py:305-313 ``clip_grad`` dense branch)."""
    norm = global_norm(vec)
    scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    return vec * scale


def clip_by_global_norm_tree(tree: Any, max_norm: float) -> Any:
    """torch.nn.utils.clip_grad_norm_ analogue for pytrees
    (used pre-weight-decay, reference fed_worker.py:292-294)."""
    leaves = jax.tree_util.tree_leaves(tree)
    norm = jnp.sqrt(sum(jnp.sum(jax.lax.square(l)) for l in leaves))
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return jax.tree_util.tree_map(lambda l: l * scale, tree)
