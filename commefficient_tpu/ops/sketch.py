"""Count-sketch (CSVec) — the FetchSGD compression operator, TPU-native.

In-tree replacement for the reference's external CUDA `csvec` library
(used at fed_aggregator.py:5,466-469,586-597 and fed_worker.py:315-322;
API surface documented in SURVEY.md §2.9). Semantics:

- An ``(r, c)`` table of buckets. Coordinate ``i`` of a d-dim vector is
  hashed by each of the r rows to a column ``h_r(i)`` and a sign
  ``s_r(i) ∈ {±1}``; sketching scatter-adds ``s_r(i)·v[i]`` into
  ``table[r, h_r(i)]``.
- Recovery estimates ``v[i] ≈ median_r(s_r(i)·table[r, h_r(i)])``;
  ``unsketch(k)`` returns a dense vector keeping only the k
  largest-magnitude estimates (heavy hitters).
- ``l2estimate() = sqrt(median_r ‖table[r]‖²)``.

Design notes (TPU-first, not a CUDA translation):

- Hashes/signs are **counter-based**: a murmur3-style integer mixer of
  (coordinate index XOR per-row seed), computed in-register. No stored
  hash tables, so the operator has zero state to ship across devices
  and is bit-deterministic on every replica — which makes
  ``psum(table)`` over the mesh exactly equal to the sketch of the
  summed vector (sketching is linear in v for *fixed* hashes).
- Both sketching and recovery stream over fixed-size coordinate blocks
  with ``lax.scan`` so peak memory is O(block + r·c), never O(r·d).
  ``num_blocks`` (same flag as the reference's CUDA memory knob) sets
  the block count.
- All shapes are static; everything here is jit/vmap/pjit-compatible.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)


def _mix(x: jax.Array) -> jax.Array:
    """murmur3 fmix32 finalizer — a cheap, well-dispersed bijection on
    uint32, vectorisable on the VPU."""
    x = x ^ (x >> 16)
    x = x * _M1
    x = x ^ (x >> 13)
    x = x * _M2
    x = x ^ (x >> 16)
    return x


@dataclasses.dataclass(frozen=True)
class CountSketch:
    """Static description of a sketch operator (d, c, r, seed).

    Mirrors ``CSVec(d, c, r, numBlocks)`` (reference
    fed_aggregator.py:466-469) minus the device argument — placement is
    the mesh's job. Instances are hashable and static under jit.
    """

    d: int
    c: int
    r: int
    num_blocks: int = 20
    seed: int = 42

    def __post_init__(self):
        assert self.d > 0 and self.c > 0 and self.r > 0

    # --- hashing ---------------------------------------------------------

    @property
    def _block(self) -> int:
        return -(-self.d // max(self.num_blocks, 1))  # ceil

    @property
    def _padded_d(self) -> int:
        return self._block * max(self.num_blocks, 1)

    def _row_seeds(self):
        """Two distinct uint32 seeds per row (bucket and sign)."""
        rows = np.arange(self.r, dtype=np.uint64)
        base = self.seed & 0xFFFFFFFF
        mask = np.uint64(0xFFFFFFFF)
        bucket_seed = ((base * 0x9E3779B9 + rows * 0x7FEB352D + 1) & mask)
        sign_seed = ((base * 0x6C62272E + rows * 0x846CA68B + 2) & mask)
        return (jnp.asarray(bucket_seed.astype(np.uint32)),
                jnp.asarray(sign_seed.astype(np.uint32)))

    def hashes(self, idx: jax.Array):
        """(buckets, signs) for int32 coordinate indices ``idx``:
        buckets uint32 (r, n) in [0, c); signs float32 (r, n) in {±1}."""
        bucket_seed, sign_seed = self._row_seeds()
        x = idx.astype(jnp.uint32)[None, :]
        b = _mix(x ^ bucket_seed[:, None]) % jnp.uint32(self.c)
        s = 1.0 - 2.0 * ((_mix(x ^ sign_seed[:, None]) >> 16) & 1).astype(
            jnp.float32)
        return b, s

    # --- sketching (accumulateVec) --------------------------------------

    def sketch(self, v: jax.Array) -> jax.Array:
        """Dense (d,) vector -> (r, c) sketch table.

        Blocked scatter-add: scan over coordinate blocks; within a
        block, each row's signed values are summed into a flattened
        (r·c,) table with one scatter-add.
        """
        assert v.shape == (self.d,), v.shape
        block, nblocks = self._block, max(self.num_blocks, 1)
        v = jnp.pad(v.astype(jnp.float32), (0, self._padded_d - self.d))
        vb = v.reshape(nblocks, block)
        offs = jnp.arange(nblocks, dtype=jnp.int32) * block
        row_base = jnp.arange(self.r, dtype=jnp.uint32)[:, None] * jnp.uint32(self.c)

        def body(table, inp):
            off, vals = inp
            idx = off + jnp.arange(block, dtype=jnp.int32)
            buckets, signs = self.hashes(idx)
            flat_idx = (row_base + buckets).reshape(-1)
            contrib = (signs * vals[None, :]).reshape(-1)
            table = table.at[flat_idx].add(contrib, mode="promise_in_bounds")
            return table, None

        table, _ = jax.lax.scan(
            body, jnp.zeros(self.r * self.c, jnp.float32), (offs, vb))
        return table.reshape(self.r, self.c)

    # --- recovery --------------------------------------------------------

    def _estimate_block(self, table: jax.Array, idx: jax.Array) -> jax.Array:
        """Median-of-rows estimates for coordinate indices ``idx``."""
        buckets, signs = self.hashes(idx)
        ests = signs * table[jnp.arange(self.r)[:, None],
                             buckets.astype(jnp.int32)]
        return jnp.median(ests, axis=0)

    def estimates(self, table: jax.Array) -> jax.Array:
        """All-coordinate estimates (d,). O(r·d) memory — use only for
        small d (tests); ``unsketch`` streams instead."""
        return self._estimate_block(
            table, jnp.arange(self.d, dtype=jnp.int32))

    @partial(jax.jit, static_argnums=(0, 2))
    def unsketch(self, table: jax.Array, k: int) -> jax.Array:
        """(r, c) table -> dense (d,) vector containing only the k
        largest-magnitude estimated coordinates (reference
        ``CSVec.unSketch(k)``; server use at fed_aggregator.py:592).

        Streams blocks, carrying a running top-k: per block, merge the
        block's estimates with the carry and re-select top-k, so peak
        memory is O(k + block) instead of O(d).
        """
        assert table.shape == (self.r, self.c), table.shape
        k = min(k, self.d)
        block, nblocks = self._block, max(self.num_blocks, 1)
        offs = jnp.arange(nblocks, dtype=jnp.int32) * block

        def body(carry, off):
            top_vals, top_idx = carry
            idx = off + jnp.arange(block, dtype=jnp.int32)
            est = self._estimate_block(table, idx)
            # padded coords (>= d) must never win
            est = jnp.where(idx < self.d, est, 0.0)
            cand_vals = jnp.concatenate([top_vals, est])
            cand_idx = jnp.concatenate([top_idx, idx])
            _, sel = jax.lax.top_k(jax.lax.square(cand_vals), k)
            return (cand_vals[sel], cand_idx[sel]), None

        init = (jnp.zeros(k, jnp.float32),
                jnp.full(k, self.d, dtype=jnp.int32))  # sentinel idx
        (top_vals, top_idx), _ = jax.lax.scan(body, init, offs)

        out = jnp.zeros(self.d + 1, jnp.float32)  # slot d absorbs sentinels
        out = out.at[top_idx].set(top_vals, mode="promise_in_bounds")
        return out[: self.d]

    # --- norms -----------------------------------------------------------

    @staticmethod
    def l2estimate(table: jax.Array) -> jax.Array:
        """sqrt(median over rows of per-row sum of squares) — the sketch
        estimate of ‖v‖₂ (reference utils.py:309 via CSVec.l2estimate)."""
        return jnp.sqrt(jnp.median(jnp.sum(jax.lax.square(table), axis=1)))


def clip_record(record: jax.Array, clip: float, *, is_sketch: bool) -> jax.Array:
    """Reference ``clip_grad`` (utils.py:305-313): L2-clip a dense
    vector, or a sketch table by its l2estimate. Only ever shrinks."""
    if not is_sketch:
        from commefficient_tpu.ops.vec import clip_by_l2
        return clip_by_l2(record, clip)
    norm = CountSketch.l2estimate(record)
    scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    return record * scale
