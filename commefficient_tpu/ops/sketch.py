"""Count-sketch (CSVec) — the FetchSGD compression operator, TPU-native.

In-tree replacement for the reference's external CUDA `csvec` library
(used at fed_aggregator.py:5,466-469,586-597 and fed_worker.py:315-322;
API surface documented in SURVEY.md §2.9). Semantics:

- An ``(r, c)`` table of buckets. Coordinate ``i`` is hashed by each of
  the r rows to a column ``h_r(i)`` and a sign ``s_r(i) ∈ {±1}``;
  sketching adds ``s_r(i)·v[i]`` into ``table[r, h_r(i)]``.
- Recovery estimates ``v[i] ≈ median_r(s_r(i)·table[r, h_r(i)])``;
  ``unsketch(k)`` returns a dense vector keeping only the k
  largest-magnitude estimates (heavy hitters).
- ``l2estimate() = sqrt(median_r ‖table[r]‖²)``.

**TPU-first hash design — the rotation (circulant) sketch.** A CUDA
count-sketch scatter-adds to random buckets; random scatter/gather is
the worst workload for a TPU's vector units (measured: >200 ms for the
ResNet9-sized sketch via XLA scatter). Instead, the padded coordinate
space is split into ``m = ceil(d/c)`` contiguous chunks of width c,
and row r assigns coordinate ``i`` (chunk ``t = i // c``, offset
``j = i % c``) the bucket

    h_r(i) = (j + o[r, t]) mod c

with a pseudorandom per-(row, chunk) rotation ``o[r, t]`` and
per-coordinate murmur signs. Then:

- sketching row r = sign-multiply + per-chunk ``roll`` + chunk-sum —
  aligned VPU ops, zero scatter;
- recovery row r = per-chunk inverse ``roll`` of the table row —
  zero gather.

Collision analysis (why CS guarantees survive): two coords in the same
chunk keep their offset distance under rotation, so they **never**
collide (better than the classic 1/c); coords in chunks t ≠ t' collide
iff ``o[r,t] - o[r,t'] ≡ j' - j (mod c)`` — probability 1/c,
independent across rows. Per-pair collision probability ≤ 1/c
throughout, which is the only property the count-sketch variance bound
uses; signs are iid per coordinate, so estimates stay unbiased.

Rotations and signs are counter-based (murmur3 mixer of the seed), so
the operator is stateless and bit-deterministic on every replica —
``psum(table)`` over the mesh equals the sketch of the summed vector
exactly (linearity + fixed hashes).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_M1 = np.uint32(0x85EBCA6B)
_M2 = np.uint32(0xC2B2AE35)

# chunk counts up to this get fully unrolled static-shift rolls (fast
# path); above it, a scan with dynamic shifts keeps the emitted XLA
# program constant-size (tiny-c configs like --num_cols 1000 at
# grad_size 1e6 would otherwise unroll thousands of ops)
_UNROLL_LIMIT = 128


def _mix(x: jax.Array) -> jax.Array:
    """murmur3 fmix32 finalizer — cheap, well-dispersed, VPU-friendly."""
    x = x ^ (x >> 16)
    x = x * _M1
    x = x ^ (x >> 13)
    x = x * _M2
    x = x ^ (x >> 16)
    return x


def _np_mix(x: np.ndarray) -> np.ndarray:
    """numpy twin of _mix (identical uint32 wraparound semantics)."""
    x = np.asarray(x, np.uint32)
    x = x ^ (x >> np.uint32(16))
    x = x * _M1
    x = x ^ (x >> np.uint32(13))
    x = x * _M2
    x = x ^ (x >> np.uint32(16))
    return x


@dataclasses.dataclass(frozen=True)
class CountSketch:
    """Static description of a sketch operator (d, c, r, seed).

    Mirrors ``CSVec(d, c, r, numBlocks)`` (reference
    fed_aggregator.py:466-469) minus the device argument — placement is
    the mesh's job. ``num_blocks`` is accepted for CLI parity (it was
    the reference CUDA library's memory knob) but unused: the rotation
    formulation has no memory blow-up to manage. Instances are
    hashable and static under jit.
    """

    d: int
    c: int
    r: int
    num_blocks: int = 20
    seed: int = 42
    # TPU-native approximate top-k for recovery (lax.approx_max_k,
    # ~3x faster at recall 0.95). Algorithmically safe for FetchSGD —
    # error feedback re-surfaces missed heavy hitters next round — but
    # off by default for exact reference parity.
    approx_topk: bool = False
    # recall target for approx_topk: lower = smaller internal sort =
    # faster (measured ~2x at 0.85, which still selects ~94% of the
    # true top-k on gaussian data); missed coordinates stay in the
    # error accumulator and resurface next round
    approx_recall: float = 0.95
    # "auto" | "xla" | "pallas" | "pallas_interpret": auto picks the
    # fused Pallas kernels (ops/sketch_pallas.py) on TPU when the
    # geometry supports them (c lane-aligned, table VMEM-resident) and
    # the roll-based XLA path otherwise. Identical hash streams; sketch
    # tables agree to ULP-level summation-order tolerance, recovery
    # from a given table is bit-exact.
    backend: str = "auto"
    # > 0: quantize rotations to multiples of this lane width, so the
    # Pallas kernels' per-(row, chunk) circular shift becomes a SINGLE
    # sublane roll instead of the 5-op arbitrary-shift decomposition
    # (the kernels are VPU-bound on rolls at large d). Collision
    # tradeoff: coords in chunks t != t' with equal lane offset
    # (j ≡ j' mod rot_lanes, a 1/rot_lanes fraction of pairs) collide
    # with probability rot_lanes/c instead of 1/c; all other cross-
    # chunk pairs never collide. The AVERAGE per-pair collision rate
    # stays 1/c, so expected recovery error is unchanged while the
    # tail is heavier — quality measured in scripts/rot_quality.py
    # and BENCHMARKS.md before any default changes. 0 = off (full-
    # granularity rotations, the reference-quality default).
    rot_lanes: int = 0
    # stream precomputed packed sign bits ((padded_d,) uint8, bit row
    # = hash bit 16+row) into the Pallas kernels instead of hashing
    # in-kernel. The murmur mix is two u32 multiplies per element —
    # emulated multi-op on the VPU and the largest r-independent ALU
    # block in both kernels; the table costs ~1 byte/element of HBM
    # traffic (~0.15 ms at d=124M vs ~2-3 ms of hashing per kernel
    # call) and is computed ON-DEVICE inside the round program (a
    # closed-over 125 MB host constant measured 11.7 s lowering +
    # 27.6 s compile + a 250 MB HLO — never do that), where XLA CSE
    # shares one materialisation across the clients vmap and the
    # sketch/estimates pair. Eligible when one-mix signs apply and
    # r <= 8 (u8 holds 8 row bits); ineligible geometries hash
    # in-kernel as before. Sign VALUES are identical either way.
    packed_signs: bool = True

    def __post_init__(self):
        assert self.d > 0 and self.c > 0 and self.r > 0
        self._check_rot_lanes_engage()

    # --- hashing ---------------------------------------------------------

    @property
    def _m(self) -> int:
        """number of coordinate chunks"""
        return -(-self.d // self.c)  # ceil

    @property
    def _padded_d(self) -> int:
        return self._m * self.c

    def _seeds(self):
        base = np.uint64(self.seed & 0xFFFFFFFF)
        mask = np.uint64(0xFFFFFFFF)
        rot = np.uint32((base * np.uint64(0x9E3779B9) + np.uint64(1)) & mask)
        sign = np.uint32((base * np.uint64(0x6C62272E) + np.uint64(2)) & mask)
        return rot, sign

    def _rotations(self) -> np.ndarray:
        """(r, m) rotations in [0, c) — computed host-side in numpy so
        the rolls below get *static* shifts (XLA lowers them to plain
        slice+concat instead of dynamic-slice chains). With
        ``rot_lanes`` set, rotations are drawn uniformly from the
        c/rot_lanes multiples of rot_lanes (see the field comment)."""
        rot_seed, _ = self._seeds()
        rows = np.arange(self.r, dtype=np.uint32)[:, None]
        chunks = np.arange(self._m, dtype=np.uint32)[None, :]
        with np.errstate(over="ignore"):
            h = _np_mix(rows * np.uint32(0x7FEB352D)
                        ^ chunks * np.uint32(0x846CA68B)
                        ^ rot_seed)
        if self.rot_lanes > 0:
            assert self.c % self.rot_lanes == 0, (self.c, self.rot_lanes)
            # the rotation space must stay large: c/rot_lanes distinct
            # rotations bound the same-lane-offset collision rate at
            # rot_lanes/c per row. At c == rot_lanes every rotation is
            # zero and stride-c pairs collide in EVERY row — degenerate
            assert self.c // self.rot_lanes >= 8, \
                f"rot_lanes {self.rot_lanes} too coarse for c={self.c}"
            s = np.uint32(self.c // self.rot_lanes)
            return ((h % s) * np.uint32(self.rot_lanes)).astype(np.int64)
        return (h % np.uint32(self.c)).astype(np.int64)

    @property
    def _one_mix_signs(self) -> bool:
        """r <= 16: all rows' signs come from distinct high bits of a
        SINGLE murmur mix per coordinate (bits are independent after
        fmix32) — 1/r the hashing cost, the dominant cost of the fused
        kernels. Larger r falls back to one mix per (row, coord)."""
        return self.r <= 16

    def _sign_hash(self, idx: jax.Array) -> jax.Array:
        """uint32 per-coordinate sign hash (one-mix scheme)."""
        _, sign_seed = self._seeds()
        return _mix(idx ^ sign_seed)

    def _signs_row(self, row: int | jax.Array) -> jax.Array:
        """(padded_d,) float32 signs for one row."""
        _, sign_seed = self._seeds()
        idx = jnp.arange(self._padded_d, dtype=jnp.uint32)
        if self._one_mix_signs:
            h = self._sign_hash(idx)
            bit = (h >> (jnp.uint32(16) + jnp.uint32(row))) & 1
        else:
            h = _mix(idx ^ (jnp.uint32(row) * jnp.uint32(0x9E3779B9))
                     ^ sign_seed)
            bit = (h >> 16) & 1
        return 1.0 - 2.0 * bit.astype(jnp.float32)

    @property
    def _packed_sign_kernels(self) -> bool:
        """Whether the Pallas kernels stream precomputed sign bits
        (see the ``packed_signs`` field comment)."""
        return self.packed_signs and self._one_mix_signs and self.r <= 8

    def _packed_signs_traced(self) -> jax.Array:
        """(padded_d,) uint8 packed sign bits — bit ``row`` is the
        one-mix hash bit 16+row, i.e. exactly the bit
        ``_signs_row(row)`` reads. Built from jnp ops INSIDE the
        caller's trace (never a host-side constant; see the field
        comment for why), so XLA CSEs the subgraph wherever it
        appears more than once in a program."""
        idx = jnp.arange(self._padded_d, dtype=jnp.uint32)
        h = self._sign_hash(idx)
        mask = jnp.uint32((1 << self.r) - 1)
        return ((h >> 16) & mask).astype(jnp.uint8)

    def hashes(self, idx: jax.Array):
        """(buckets, signs) for int32 coordinate indices: buckets
        uint32 (r, n) in [0, c); signs float32 (r, n) in {±1}."""
        rot = jnp.asarray(self._rotations(), jnp.uint32)
        _, sign_seed = self._seeds()
        i = idx.astype(jnp.uint32)[None, :]
        t = (i // jnp.uint32(self.c)).astype(jnp.int32)
        j = i % jnp.uint32(self.c)
        rows = jnp.arange(self.r, dtype=jnp.uint32)[:, None]
        buckets = (j + jnp.take_along_axis(
            jnp.broadcast_to(rot, (self.r, self._m)), t, axis=1)) \
            % jnp.uint32(self.c)
        if self._one_mix_signs:
            h = self._sign_hash(i)
            bit = (h >> (jnp.uint32(16) + rows)) & 1
        else:
            h = _mix(i ^ (rows * jnp.uint32(0x9E3779B9)) ^ sign_seed)
            bit = (h >> 16) & 1
        signs = 1.0 - 2.0 * bit.astype(jnp.float32)
        return buckets, signs

    # --- sketching (accumulateVec) --------------------------------------

    def _check_rot_lanes_engage(self):
        """rot_lanes only pays off when the kernels' roll collapses to
        a sublane roll, i.e. rot_lanes is a multiple of the lane width
        the kernel picks for this c. Otherwise the user eats the
        heavier collision tail for zero speedup — warn once."""
        if self.rot_lanes <= 0:
            return
        import logging
        log = logging.getLogger(__name__)
        # construction stays JAX-runtime-free: probing the backend here
        # would call jax.devices() inside __post_init__, locking in a
        # backend before a multi-host embedder's
        # jax.distributed.initialize() / platform override runs. The
        # resolved-backend warning fires lazily from _resolve_backend
        # at first use instead; only the explicit backend="xla" case is
        # knowable (and warned) now.
        if self.backend == "xla":
            self._warn_rot_lanes_no_pallas("xla")
            return
        from commefficient_tpu.ops.sketch_pallas import _pick_lanes
        L = _pick_lanes(self.c)
        if L is not None and self.rot_lanes % L != 0:
            log.warning(
                "rot_lanes=%d is not a multiple of the kernel lane "
                "width %d for c=%d: rotations are quantized (heavier "
                "collision tail) but the sublane fast path does NOT "
                "engage — use rot_lanes=%d",
                self.rot_lanes, L, self.c, L)

    def _warn_rot_lanes_no_pallas(self, resolved: str):
        """Quantized rotations pay their collision tail only to buy
        the Pallas sublane roll; any non-pallas lowering (unsupported
        geometry, non-TPU platform, explicit backend="xla") gains
        nothing from them — warn once per instance."""
        if getattr(self, "_rot_lanes_warned", False):
            return
        object.__setattr__(self, "_rot_lanes_warned", True)
        import logging
        logging.getLogger(__name__).warning(
            "sketch_rot_lanes=%d with backend %r: the sublane fast "
            "path only exists in the Pallas TPU kernels — rotations "
            "are quantized (heavier collision tail) for zero speedup "
            "here; use rot_lanes=0", self.rot_lanes, resolved)

    def _resolve_backend(self) -> str:
        resolved = self.backend
        if resolved == "auto":
            from commefficient_tpu.ops.sketch_pallas import supported
            if not supported(self.d, self.c, self.r):
                resolved = "xla"
            else:
                # allowlist: Mosaic kernels only lower on TPU ("axon"
                # is the tunneled-TPU platform under the remote relay)
                platform = jax.devices()[0].platform
                resolved = ("pallas" if platform in ("tpu", "axon")
                            else "xla")
        if resolved != "pallas" and self.rot_lanes > 0:
            self._warn_rot_lanes_no_pallas(resolved)
        return resolved

    def sketch(self, v: jax.Array) -> jax.Array:
        """Dense (d,) vector -> (r, c) sketch table, scatter-free."""
        assert v.shape == (self.d,), v.shape
        vp = jnp.pad(v.astype(jnp.float32), (0, self._padded_d - self.d))
        return self._sketch_padded(vp)

    def sketch_from_leaves(self, leaves) -> jax.Array:
        """Gradient-pytree leaves -> (r, c) table, bit-identical to
        ``sketch`` of their ``ravel_pytree`` concatenation.

        The flat-primal fused round pays two d-sized copies between
        the model backward and the kernel: autodiff's
        transpose-of-unravel concatenates the leaf cotangents into the
        (d,) flat gradient, then ``sketch`` pads it to padded_d. With
        tree-space gradients this assembles the kernel input in ONE
        concatenate (leaves + zero tail) — XLA lowers it to parallel
        writes into the padded buffer, and the flat (d,) gradient never
        exists (the concat/pad item in the round-3 xplane breakdown,
        VERDICT round 3 weak #5)."""
        parts = [jnp.ravel(l).astype(jnp.float32) for l in leaves]
        total = sum(p.size for p in parts)
        assert total == self.d, (total, self.d)
        pad = self._padded_d - self.d
        if pad:
            parts.append(jnp.zeros((pad,), jnp.float32))
        return self._sketch_padded(jnp.concatenate(parts))

    def _sketch_padded(self, vp: jax.Array) -> jax.Array:
        """(padded_d,) pre-padded vector -> (r, c) table."""
        assert vp.shape == (self._padded_d,), vp.shape
        m, c = self._m, self.c
        backend = self._resolve_backend()
        if backend in ("pallas", "pallas_interpret"):
            from commefficient_tpu.ops.sketch_pallas import sketch_pallas
            _, sign_seed = self._seeds()
            sgn = (self._packed_signs_traced()
                   if self._packed_sign_kernels else None)
            return sketch_pallas(vp, jnp.asarray(self._rotations()),
                                 c, self.r, int(sign_seed),
                                 backend == "pallas_interpret",
                                 one_mix=self._one_mix_signs,
                                 rot_step=self.rot_lanes, sgn=sgn)
        rot = self._rotations()  # host constants -> static rolls

        if m <= _UNROLL_LIMIT:
            rows = []
            for row in range(self.r):
                signed = (vp * self._signs_row(row)).reshape(m, c)
                rolled = jnp.stack([
                    jnp.roll(signed[t], int(rot[row, t]))
                    for t in range(m)])
                rows.append(jnp.sum(rolled, axis=0))
            return jnp.stack(rows)

        # many-chunk regime (small c): scan over chunks with dynamic
        # rolls to keep the emitted program constant-size
        rot_dev = jnp.asarray(rot, jnp.int32)

        def one_row(row, rots):
            signed = (vp * self._signs_row(row)).reshape(m, c)

            def body(acc, inp):
                chunk, o = inp
                return acc + jnp.roll(chunk, o), None

            # zero init derived from the input (x*0), not jnp.zeros:
            # under shard_map (a per-client sketch inside a spanning
            # mesh) a plain-zeros carry lacks the body output's
            # varying mesh axes and trips the scan carry-type check
            out, _ = jax.lax.scan(body, signed[0] * 0.0,
                                  (signed, rots))
            return out

        return jax.vmap(one_row)(jnp.arange(self.r, dtype=jnp.uint32),
                                 rot_dev)

    def sketch_quantized(self, v: jax.Array, wire: str, rows=None):
        """Dense (d,) vector -> (wire-dtype (r, c) table, (r, 1) f32
        rowmax): the fused emit + local-quantize wire path. On the
        Pallas backend the f32 table only ever exists in the kernel's
        VMEM scratch (ops/sketch_pallas.sketch_quant_pallas); other
        backends sketch then quantize (same algebra, ops/quant.py
        quantize_local), so the two paths agree exactly on a given
        table. Callers harmonize the result onto the shared global
        scale before the wire collective (core/rounds.py).

        ``rows`` — optional ``(offset, count)`` row chunk
        (--overlap_depth chunked emission): emit + quantize ONLY those
        table rows. The Pallas kernel then runs with a chunk-sized
        VMEM scratch, the chunk's rotation-row slice and sign streams
        keyed by the absolute row, so the chunk is bit-identical to
        the same rows of a whole-table call (per-row scales make the
        quantization algebra row-separable)."""
        from commefficient_tpu.ops.quant import quantize_local
        off, cnt = rows if rows is not None else (0, self.r)
        assert 0 <= off and off + cnt <= self.r, (off, cnt, self.r)
        if wire == "bf16":
            # scale-free cast — nothing to fuse
            q, rm = quantize_local(self.sketch(v), wire)
            if rows is not None:
                q = jax.lax.slice_in_dim(q, off, off + cnt, axis=0)
            return q, rm
        backend = self._resolve_backend()
        if backend in ("pallas", "pallas_interpret"):
            from commefficient_tpu.ops.sketch_pallas import \
                sketch_quant_pallas
            assert v.shape == (self.d,), v.shape
            vp = jnp.pad(v.astype(jnp.float32),
                         (0, self._padded_d - self.d))
            _, sign_seed = self._seeds()
            sgn = (self._packed_signs_traced()
                   if self._packed_sign_kernels else None)
            rot = self._rotations()
            if rows is not None:
                rot = rot[off:off + cnt]
            return sketch_quant_pallas(
                vp, jnp.asarray(rot), self.c, cnt,
                int(sign_seed), wire,
                backend == "pallas_interpret",
                one_mix=self._one_mix_signs,
                rot_step=self.rot_lanes, sgn=sgn,
                row_offset=off)
        table = self.sketch(v)
        if rows is not None:
            table = jax.lax.slice_in_dim(table, off, off + cnt,
                                         axis=0)
        return quantize_local(table, wire)

    # --- recovery --------------------------------------------------------

    def estimates(self, table: jax.Array,
                  padded: bool = False) -> jax.Array:
        """Median-of-rows estimates for all d coordinates — gather-free
        (per-chunk inverse rolls of the table rows). Materialises
        (r, padded_d): fine up to tens of millions of coords.

        ``padded=True`` returns the full (padded_d,) vector with the
        tail coordinates (>= d) zeroed instead of slicing to (d,):
        ``est[:d]`` is a d-sized prefix copy (~2 ms at GPT-2's d=124M)
        that the index-selection consumers never need — zeros lose
        every magnitude comparison, so selection over the padded
        vector picks the identical set (indices stay < d as long as
        the vector has >= k nonzero estimates, which any real gradient
        table does)."""
        assert table.shape == (self.r, self.c), table.shape
        m, c = self._m, self.c
        backend = self._resolve_backend()
        if backend in ("pallas", "pallas_interpret"):
            from commefficient_tpu.ops.sketch_pallas import estimates_pallas
            _, sign_seed = self._seeds()
            sgn = (self._packed_signs_traced()
                   if self._packed_sign_kernels else None)
            est = estimates_pallas(table, jnp.asarray(self._rotations()),
                                   c, self.r, int(sign_seed),
                                   backend == "pallas_interpret",
                                   one_mix=self._one_mix_signs,
                                   valid=self.d if padded else None,
                                   rot_step=self.rot_lanes, sgn=sgn)
            return est if padded else est[: self.d]
        rot = self._rotations()

        if m <= _UNROLL_LIMIT:
            ests = []
            for row in range(self.r):
                unrolled = jnp.stack([
                    jnp.roll(table[row], -int(rot[row, t]))
                    for t in range(m)])  # (m, c): chunk t's table view
                ests.append(unrolled.reshape(-1) * self._signs_row(row))
            return self._finish_estimates(
                jnp.median(jnp.stack(ests), axis=0), padded)

        rot_dev = jnp.asarray(rot, jnp.int32)

        def one_row(row, trow, rots):
            unrolled = jax.lax.map(lambda o: jnp.roll(trow, -o), rots)
            return unrolled.reshape(-1) * self._signs_row(row)

        ests = jax.vmap(one_row)(jnp.arange(self.r, dtype=jnp.uint32),
                                 table, rot_dev)
        return self._finish_estimates(jnp.median(ests, axis=0), padded)

    def _finish_estimates(self, est_full: jax.Array,
                          padded: bool) -> jax.Array:
        if not padded:
            return est_full[: self.d]
        if self._padded_d == self.d:
            return est_full
        # zero the tail in place of the slice; the iota compare fuses
        # into the median's elementwise epilogue
        pos = jnp.arange(self._padded_d, dtype=jnp.int32)
        return jnp.where(pos < self.d, est_full, 0.0)

    def estimates_at(self, table: jax.Array,
                     idx: jax.Array) -> jax.Array:
        """Median-of-rows estimates for an arbitrary int32 index
        vector — the gather-based dual of ``estimates()``. Element i
        of row ``row`` in the rolled path reads
        ``table[row, (i % c + rot[row, i // c]) % c]`` times the sign
        bit, which is exactly the (bucket, sign) pair ``hashes()``
        produces, so this is bit-identical per coordinate to
        ``estimates(table)[idx]`` (same float32 products, same
        median) while doing O(r·n) work instead of O(r·d). Used by
        the 2D server round, where each model peer estimates only its
        own coordinate slice of the gathered table. Indices must be
        in [0, padded_d); padded-tail indices return garbage, so
        callers mask them out themselves."""
        assert table.shape == (self.r, self.c), table.shape
        buckets, signs = self.hashes(idx)
        vals = jnp.take_along_axis(
            table, buckets.astype(jnp.int32), axis=1) * signs
        return jnp.median(vals, axis=0)

    @partial(jax.jit, static_argnums=(0, 2, 3, 4))
    def unsketch(self, table: jax.Array, k: int,
                 with_support: bool = False,
                 with_dense: bool = True):
        """(r, c) table -> dense (d,) vector keeping only the k
        largest-magnitude estimated coordinates (reference
        ``CSVec.unSketch(k)``; server use at fed_aggregator.py:592).

        ``with_support=True`` additionally returns the (k,) selected
        indices and their values — the sparse form of the update, used
        so downstream consumers (download-byte accounting) never need
        the dense vector on the host."""
        k = min(k, self.d)
        # the big-d selections never need the (d,) prefix slice of the
        # estimates — selection over the tail-zeroed padded vector
        # picks the identical set (see ``estimates``); the small-d
        # lax.top_k path keeps the slice (d == padded_d there is
        # common, and the sort dominates anyway)
        from commefficient_tpu.ops.topk import (
            _THRESHOLD_SELECT_MIN_D, threshold_topk_indices,
            use_threshold_select)
        big_d = self.d >= _THRESHOLD_SELECT_MIN_D
        est = self.estimates(table, padded=big_d)
        if self.approx_topk:
            _, idx = jax.lax.approx_max_k(
                jax.lax.square(est), k,
                recall_target=self.approx_recall)
            if big_d:
                # degenerate guard (sub-k support): approx_max_k breaks
                # zero-ties in unspecified order and could pick a tail
                # slot; clamp it in range for the promise_in_bounds
                # scatters, and force the value to 0 below — est[d-1]
                # is generally nonzero, and a duplicated
                # (d-1, est[d-1]) pair would double-count under
                # sketch_sparse's scatter-ADD on the sparse-resketch
                # path. The threshold path needs no guard — its
                # lowest-index tie-break can't reach the tail while
                # k <= d
                oob = idx >= self.d
                idx = jnp.minimum(idx, self.d - 1)
        else:
            if use_threshold_select(k, self.d, False):
                # exact selection without the full sort: at GPT-2's
                # d=124M lax.top_k costs 461.9 ms vs 103.2 ms for the
                # threshold + hierarchical extraction (BENCHMARKS.md)
                idx = threshold_topk_indices(
                    jax.lax.square(est), k)
            else:
                _, idx = jax.lax.top_k(jax.lax.square(est), k)
            oob = None
        vals = est[idx]
        if self.approx_topk and big_d:
            vals = jnp.where(oob, 0.0, vals)
        if not with_dense:
            # support-only form: at large d the dense (d,) scatter is
            # the single most expensive piece of the server step —
            # callers on the sparse path never need it
            assert with_support
            return None, idx, vals
        # scatter-ADD, not set: the big-d approx guard above can leave
        # duplicate (d-1) slots whose vals are forced 0 — under .set a
        # legitimate (d-1, est[d-1]) pick could lose to a forced-0
        # duplicate (order-nondeterministic); under .add over a zero
        # init the zeros are inert and unique-index inputs are
        # unchanged. selection_may_duplicate (ops/topk.py) is the one
        # shared predicate for when duplicates are possible.
        from commefficient_tpu.ops.topk import selection_may_duplicate
        dense = jnp.zeros(self.d, jnp.float32).at[idx].add(
            vals, mode="promise_in_bounds",
            unique_indices=not selection_may_duplicate(
                self.d, self.approx_topk))
        if with_support:
            return dense, idx, vals
        return dense

    def unsketch_dense_mask(self, table: jax.Array, k: int):
        """Exact dense unsketch without the top-k sort: the
        threshold-select mask (ops/topk.py, 32 streaming count passes)
        keeps the k largest-magnitude estimates via a ``where`` — no
        sort, no index gather/scatter. Returns ``(dense, mask)``;
        use where the consumer never needs the (k,) index form (the
        dense-regime server step; download accounting takes the
        bit-packed mask). Selection set is identical to ``unsketch``'s
        exact path (lowest-index tie-break, tested)."""
        from commefficient_tpu.ops.topk import threshold_topk_mask_1d
        k = min(k, self.d)
        est = self.estimates(table)
        mask = threshold_topk_mask_1d(jax.lax.square(est), k)
        return jnp.where(mask, est, 0.0), mask

    def prefer_threshold_unsketch(self, k: int) -> bool:
        """Dense-regime exact recovery via the threshold mask: wins
        once d is large enough that lax.top_k lowers to an expensive
        full sort (~13 ms extra per round at ResNet9's d=6.6M,
        BENCHMARKS.md). Approximate recovery (approx_topk) stays on
        the index path — approx_max_k is cheaper than the 32 count
        passes; and the sparse-resketch regime needs indices anyway."""
        from commefficient_tpu.ops.topk import use_threshold_select
        return (use_threshold_select(k, self.d, self.approx_topk)
                and not self.prefer_sparse_resketch(k))

    def sketch_sparse(self, idx: jax.Array,
                      vals: jax.Array) -> jax.Array:
        """(n,) int32 indices + (n,) values -> (r, c) table, identical
        (to summation order) to ``sketch`` of the dense scatter of
        ``vals`` at ``idx``. Costs O(r*n) scatter-adds instead of O(d)
        kernel work — the winning form for re-sketching a k-sparse
        recovered update once d >> r*k (see ``prefer_sparse_resketch``;
        at GPT-2's d=124M the dense kernel costs ~8 ms while 5x50k
        scatter-adds cost ~1.5 ms)."""
        buckets, signs = self.hashes(idx.astype(jnp.int32))
        rows = jnp.broadcast_to(
            jnp.arange(self.r, dtype=jnp.int32)[:, None], buckets.shape)
        contrib = signs * vals[None, :].astype(jnp.float32)
        return jnp.zeros((self.r, self.c), jnp.float32) \
            .at[rows, buckets.astype(jnp.int32)] \
            .add(contrib, mode="promise_in_bounds")

    def prefer_sparse_resketch(self, k: int) -> bool:
        """Cost model from measured v5e numbers: the dense kernel runs
        ~14-15M coords/ms; TPU scatter-add ~6 us per 1k elements. The
        sparse path wins when d/14e6 > r*k*6e-6, i.e. d > ~90*r*k
        (GPT-2 124M with r=5, k=50k: yes; ResNet9 6.6M: no)."""
        return self.d > 90 * self.r * k

    # --- norms -----------------------------------------------------------

    @staticmethod
    def l2estimate(table: jax.Array) -> jax.Array:
        """sqrt(median over rows of per-row sum of squares) — the sketch
        estimate of ‖v‖₂ (reference utils.py:309 via CSVec.l2estimate)."""
        return jnp.sqrt(jnp.median(jnp.sum(jax.lax.square(table), axis=1)))

    def recovery_error(self, table: jax.Array, dense: jax.Array,
                       k: int) -> jax.Array:
        """Relative top-k recovery error ‖unsketch(S(v)) − v‖ / ‖v‖
        of this operator against the TRUE dense vector — the ground-
        truth fidelity probe (--probe_full). 0 would be lossless; the
        top-k floor is sqrt(1 − ‖v_topk‖²/‖v‖²) for an exact sketch,
        so values near 1 mean the recovered heavy hitters carry almost
        none of the vector's mass. A zero vector reports 0."""
        assert dense.shape == (self.d,), dense.shape
        est = self.unsketch(table, k)
        num = jnp.linalg.norm(est - dense.astype(jnp.float32))
        den = jnp.linalg.norm(dense.astype(jnp.float32))
        return jnp.where(den > 0, num / jnp.maximum(den, 1e-30), 0.0)


def clip_record(record: jax.Array, clip: float, *, is_sketch: bool) -> jax.Array:
    """Reference ``clip_grad`` (utils.py:305-313): L2-clip a dense
    vector, or a sketch table by its l2estimate. Only ever shrinks."""
    if not is_sketch:
        from commefficient_tpu.ops.vec import clip_by_l2
        return clip_by_l2(record, clip)
    norm = CountSketch.l2estimate(record)
    scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    return record * scale
