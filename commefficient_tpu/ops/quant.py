"""Sketch-table wire quantization (``--sketch_dtype``).

Count-sketch tables are mean-zero with iid-signed bucket sums
(FedSKETCH; sketched-SGD, arXiv:1903.04488), so coarse wire dtypes
cost recovery error gracefully — int8 cuts uplink ~4x while staying
inside the recovery-error alarm band on the reference config. The
scheme is **local-quantize then harmonize**:

1. ``quantize_local(table)``: each shard quantizes its f32 table
   against its own per-row maxabs at FULL wire range (int8: ±127,
   fp8 e4m3fn: ±448) — this step can run inside the Pallas emission
   kernel, where the global row maximum cannot exist yet.
2. ``harmonize(q, rowmax, global_rowmax, n_addends)``: an elementwise
   rescale onto the shared per-row scale ``global_rowmax / qeff``
   where ``qeff = qmax / n_addends`` — summation headroom, so the
   wire-dtype ``psum``/``psum_scatter`` of ``n_addends`` quantized
   shards can never overflow the wire range. ``global_rowmax`` is the
   ``pmax`` of the local rowmaxes over the participating mesh axes
   (an (r,) f32 collective the ledger counts). On a single shard
   (``n_addends == 1``, global == local) the ratio is exactly 1.0 and
   harmonize is the identity — the NumPy mirror matches bit-exact.
3. After the collective: ``dequantize(q, scale)`` back to f32, so
   server momentum / error feedback state never leaves f32.

``bf16`` is scale-free: a plain cast, summed in bf16 on the wire.
``f32`` never routes through here — the round program compiles
bit-identical to a build without the flag (HLO-fingerprint pinned).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from commefficient_tpu.accounting import WIRE_DTYPES, wire_has_scales

# full-range maxima of the scaled wire dtypes. fp8 e4m3fn's true max
# is 448; quantizing to +-448 exactly would round values within half
# a top-bin of the row max to inf-free saturation boundaries, so the
# headroom math below keeps qeff <= these.
QMAX = {"int8": 127.0, "fp8": 448.0}


def wire_jnp_dtype(wire: str):
    """jnp dtype object for a wire name."""
    return jnp.dtype(WIRE_DTYPES[wire][0])


def qeff(wire: str, n_addends: int) -> float:
    """Usable per-addend range under summation headroom: the shared
    scale maps each addend's row max to qeff so the wire-dtype sum of
    n_addends shards is bounded by qmax. int8 floors to an integer
    step (>= 1); fp8 divides exactly (its values are not integers)."""
    q = QMAX[wire]
    if wire == "int8":
        return float(max(1, int(q // max(1, n_addends))))
    return q / float(max(1, n_addends))


def local_rowmax(table: jax.Array) -> jax.Array:
    """Per-row maxabs over the trailing (column) axis, keepdims —
    the shard-local ingredient of the shared wire scale."""
    return jnp.max(jnp.abs(table.astype(jnp.float32)), axis=-1,
                   keepdims=True)


def _scale(rowmax: jax.Array, q: float) -> jax.Array:
    """rowmax/q with an all-zero-row guard (scale 1.0 dequantizes the
    zero row to exactly zero either way; the guard keeps 0/0 out)."""
    return jnp.where(rowmax > 0.0, rowmax / q, 1.0)


def _to_fp8(x: jax.Array, wire: str) -> jax.Array:
    """f32 -> fp8 through an EXPLICIT f16 intermediate. XLA's CPU
    lowering of the f32->f8 convert double-rounds via f16 anyway;
    spelling it out makes the quantization bit-reproducible across
    backends (TPU converts directly) and lets the NumPy mirror match
    bit-for-bit with np.float16. Costs at most 1 fp8 ULP vs a
    correctly-rounded convert, in near-tie cases only — noise next to
    the format's own quantization error."""
    return x.astype(jnp.float16).astype(wire_jnp_dtype(wire))


def quantize_local(table: jax.Array, wire: str):
    """f32 table -> (wire-dtype table, f32 rowmax). Full-range local
    quantization (step 1 above). bf16 is a cast with rowmax None."""
    if wire == "bf16":
        return table.astype(jnp.bfloat16), None
    rowmax = local_rowmax(table)
    s = _scale(rowmax, QMAX[wire])
    if wire == "int8":
        q = jnp.clip(jnp.round(table.astype(jnp.float32) / s),
                     -QMAX[wire], QMAX[wire])
        return q.astype(jnp.int8), rowmax
    return _to_fp8(table.astype(jnp.float32) / s, wire), rowmax


def harmonize(q: jax.Array, rowmax, global_rowmax,
              wire: str, n_addends: int):
    """Rescale a locally-quantized table onto the shared wire scale
    (step 2): returns ``(q', scale)`` where ``scale`` (f32, per-row
    keepdims) dequantizes the post-collective sum. Exact identity
    when ``n_addends == 1`` and global == local rowmax (IEEE x/x == 1
    and the re-round of integer-valued q is itself)."""
    if wire == "bf16":
        return q, None
    qe = qeff(wire, n_addends)
    s_local = _scale(rowmax, QMAX[wire])
    s_global = _scale(global_rowmax, qe)
    ratio = s_local / s_global
    if wire == "int8":
        qq = jnp.clip(jnp.round(q.astype(jnp.float32) * ratio),
                      -QMAX[wire], QMAX[wire]).astype(jnp.int8)
    else:
        qq = _to_fp8(q.astype(jnp.float32) * ratio, wire)
    return qq, s_global


def quantize_table(table: jax.Array, wire: str, n_addends: int = 1,
                   global_rowmax=None):
    """Convenience: local-quantize + harmonize in one call. With the
    default ``global_rowmax=None`` the local rowmax is the global one
    (single-shard semantics — what the NumPy mirror models)."""
    q, rowmax = quantize_local(table, wire)
    if global_rowmax is None:
        global_rowmax = rowmax
    return harmonize(q, rowmax, global_rowmax, wire, n_addends)


def dequantize(q: jax.Array, scale) -> jax.Array:
    """Wire-dtype table (post-collective) -> f32. ``scale`` is the
    shared per-row scale from harmonize (None for bf16/f32)."""
    t = q.astype(jnp.float32)
    if scale is None:
        return t
    return t * scale


def wire_psum(q: jax.Array, scale, axis_name):
    """The quantized wire crossing: psum the wire-dtype table over
    ``axis_name`` and max-combine nothing — the scale is already the
    shared global one, so only the table itself moves at wire width.
    Kept as a helper so the auditor has one spot to match collective
    dtypes against."""
    out = jax.lax.psum(q, axis_name)
    return out, scale


def global_rowmax_over(rowmax: jax.Array, axis_names) -> jax.Array:
    """pmax of the local rowmax over the participating mesh axes —
    the (r, 1) f32 side-channel collective that establishes the
    shared scale (counted by the ledger at r x 4 bytes)."""
    return jax.lax.pmax(rowmax, axis_names)
