"""Pallas TPU kernels for the count-sketch hot path.

The XLA formulation in :mod:`commefficient_tpu.ops.sketch` materialises
an ``(r, padded_d)`` intermediate for recovery (~140 MB at the flagship
ResNet9 geometry) and re-reads the signed vector once per row when
sketching. These kernels fuse sign application (streamed packed sign
bits by default, in-register murmur mix of the coordinate index for
r > 8), the per-(row, chunk) rotation, and the accumulate/median into
single passes:

- ``sketch_pallas``: one streamed read of the (padded) vector, table
  accumulated in VMEM across the chunk grid — HBM traffic ~= |v| + |table|
  instead of r·|v|.
- ``estimates_pallas``: table stays VMEM-resident across the chunk grid;
  the (r, padded_d) estimate tensor is never materialised — each chunk's
  r rolled/sign-corrected rows are medianed in-register (min/max
  selection network for the flagship r=5 and r=3; odd-even
  transposition sort for other r) and written once.

Hash-identity contract: identical rotation/sign streams to the XLA
path, so Pallas and XLA replicas can mix freely under ``psum``. Tables
match to ULP-level tolerance (chunk summation order differs); recovery
from a given table is bit-exact. Property-tested in
tests/test_pallas_sketch.py.

Rotation trick: a chunk of width c is viewed as a 2-D ``(S, L)`` tile
(L a multiple of 128, so lane-aligned). A 1-D circular shift by
``o = a·L + b`` decomposes into two sublane rolls (a, a+1), a lane roll
(b) of each, and a lane-index select — all supported by Mosaic's
``dynamic_rotate`` at any alignment, unlike a flat 1-D rotate of
unaligned width. Requires ``c % 128 == 0`` (the auto backend falls back
to XLA otherwise, e.g. for the reference's default c=500000).

Reference provenance: this implements the same operator as the
reference's external CUDA ``csvec`` library (fed_aggregator.py:466-469,
fed_worker.py:315-322) — see SURVEY.md §2.9.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from commefficient_tpu.ops.sketch import _mix as _mix_u32  # noqa: E402
# (single source of truth for the murmur mix: the psum-mixing contract
# requires the Pallas and XLA sign streams to stay bit-identical)

# table must stay VMEM-resident for the estimates kernel. The kernels
# raise the Mosaic scoped-VMEM budget (default 16 MB) via
# CompilerParams — v5e cores have headroom well past 64 MB (verified
# on hardware) — so the bound here is table + temporaries with margin.
_TABLE_VMEM_LIMIT = 20 * 1024 * 1024
_VMEM_CEILING = 64 * 1024 * 1024


# the params class was renamed TPUCompilerParams -> CompilerParams
# across JAX releases; accept either
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))


def _compiler_params(table_bytes: int):
    # table resident + r per-chunk temp rows (~table again) + double-
    # buffered chunk blocks + relayout scratch, with margin
    want = min(_VMEM_CEILING, max(32 * 1024 * 1024, 3 * table_bytes))
    return _CompilerParams(vmem_limit_bytes=want)


def _pick_lanes(c: int) -> int | None:
    """Widest lane-aligned factorisation of the chunk width."""
    for L in (1024, 512, 256, 128):
        if c % L == 0:
            return L
    return None


def supported(d: int, c: int, r: int) -> bool:
    """Whether the Pallas backend can run this geometry (else XLA).

    The table limit is empirical: the estimates kernel also streams r
    per-chunk value arrays through the median network, but Mosaic's
    scheduler handles the flagship r=5, c=2^19 case (10.5 MB table) on
    v5e. Geometries pushing right up to the limit may still OOM VMEM
    at compile — set backend="xla" explicitly there. The m bound keeps
    the (r, m) rotation table within SMEM."""
    L = _pick_lanes(c)
    if L is None or 4 * r * c > _TABLE_VMEM_LIMIT:
        return False
    m = -(-d // c)
    return r * m <= 2048


def _sign_hash_chunk(t, sign_seed: np.uint32, c: int, S: int, L: int,
                     r: int):
    """One-mix sign scheme (CountSketch._one_mix_signs, r <= 16): a
    single murmur mix of the global index per chunk element; row r's
    sign is bit 16+r. Hoisted out of the kernels' row loops — hashing
    was the dominant kernel cost at 1 mix per (row, coord)."""
    assert r <= 16
    s_idx = jax.lax.broadcasted_iota(jnp.uint32, (S, L), 0)
    l_idx = jax.lax.broadcasted_iota(jnp.uint32, (S, L), 1)
    g = t.astype(jnp.uint32) * jnp.uint32(c) + s_idx * jnp.uint32(L) + l_idx
    return _mix_u32(g ^ sign_seed)


def _flip_from_hash(h, row: int):
    """Sign-bit flip mask for row ``row`` from the one-mix hash: bit
    16+row of ``h`` moved to bit 31. XORing a float32 with this mask
    IS multiplication by the row's ±1 sign (IEEE sign-bit flip is
    exact, bit-identical to ``x * (1 - 2*bit)`` incl. ±0), at 2 VPU
    ops instead of the extract/convert/multiply chain (~7)."""
    assert 0 <= row <= 15
    return (h << (15 - row)) & jnp.uint32(0x80000000)


def _flip_chunk(t, row: int, sign_seed: np.uint32, c: int, S: int, L: int):
    """Per-(row, coord) mix fallback for r > 16 — replicates
    ops.sketch.CountSketch._signs_row on global indices
    ``t*c + s*L + l``, returned as a sign-bit flip mask (bit 16 of the
    row-salted mix moved to bit 31). ``row`` is a Python int; ``t`` is
    traced."""
    s_idx = jax.lax.broadcasted_iota(jnp.uint32, (S, L), 0)
    l_idx = jax.lax.broadcasted_iota(jnp.uint32, (S, L), 1)
    g = t.astype(jnp.uint32) * jnp.uint32(c) + s_idx * jnp.uint32(L) + l_idx
    row_const = (np.uint32((row * 0x9E3779B9) & 0xFFFFFFFF) ^ sign_seed)
    h = _mix_u32(g ^ jnp.uint32(row_const))
    return (h << 15) & jnp.uint32(0x80000000)


def _apply_flip(x, flip):
    """x * sign, as a sign-bit XOR (see _flip_from_hash)."""
    xb = jax.lax.bitcast_convert_type(x, jnp.uint32)
    return jax.lax.bitcast_convert_type(xb ^ flip, jnp.float32)


def _roll1d(x, o, S: int, L: int, lane=None):
    """Circular shift of the flattened (S, L) tile by traced ``o``
    (0 <= o < S*L). The lane roll (the expensive cross-lane permute)
    is computed ONCE and the two candidate sublane rolls (a, a+1)
    applied after — legal because rolls on distinct axes commute:
    ``lane_roll(sub_roll(x, a), b) == sub_roll(lane_roll(x, b), a)``.
    ``lane`` is the (S, L) lane iota, hoistable by the caller."""
    a = o // L
    b = o % L
    y = pltpu.roll(x, shift=b, axis=1)
    R1 = pltpu.roll(y, shift=a, axis=0)
    R2 = pltpu.roll(y, shift=a + 1, axis=0)
    if lane is None:
        lane = jax.lax.broadcasted_iota(jnp.int32, (S, L), 1)
    return jnp.where(lane < b, R2, R1)


def _median3(x, y, z):
    """max(min(x,y), min(max(x,y), z)) — 4 ops vs 6 for the sort."""
    lo = jnp.minimum(x, y)
    hi = jnp.maximum(x, y)
    return jnp.maximum(lo, jnp.minimum(hi, z))


def _median_network(vals):
    """Elementwise median of a list of same-shape arrays. Matches
    jnp.median: middle element for odd r, mean of the two middles for
    even r. min/max compositions are order-exact, so any correct
    network returns the identical value — the flagship r=5 uses the
    classic selection network (10 ops: median3 of the max-of-mins,
    min-of-maxes, and the odd element) instead of a full odd-even
    transposition sort (20 ops); other r fall back to the sort."""
    v = list(vals)
    n = len(v)
    if n == 1:
        return v[0]
    if n == 3:
        return _median3(v[0], v[1], v[2])
    if n == 5:
        f = jnp.maximum(jnp.minimum(v[0], v[1]), jnp.minimum(v[2], v[3]))
        g = jnp.minimum(jnp.maximum(v[0], v[1]), jnp.maximum(v[2], v[3]))
        return _median3(v[4], f, g)
    for rnd in range(n):
        start = rnd % 2
        for i in range(start, n - 1, 2):
            lo = jnp.minimum(v[i], v[i + 1])
            hi = jnp.maximum(v[i], v[i + 1])
            v[i], v[i + 1] = lo, hi
    if n % 2 == 1:
        return v[n // 2]
    return 0.5 * (v[n // 2 - 1] + v[n // 2])


def _flips_for_chunk(t, sgn_block, one_mix: bool, seed, c, S, L, r,
                     row_offset: int = 0):
    """Per-row sign-bit flip masks for chunk ``t``, cheapest source
    first: a streamed packed-sign block (bit ``row`` of a u8 per
    element — 2 shift/and ops per row, no hashing), else the in-kernel
    one-mix hash (r <= 16), else one mix per (row, coord).
    ``row_offset`` shifts every row index by the table-row offset of a
    chunked call (--overlap_depth): the sign stream is keyed by the
    ABSOLUTE table row, so a chunk's rows flip identically to the same
    rows of a whole-table call."""
    if sgn_block is not None:
        b32 = sgn_block.astype(jnp.uint32)
        return [(b32 << (31 - (row_offset + row)))
                & jnp.uint32(0x80000000) for row in range(r)]
    if one_mix:
        h = _sign_hash_chunk(t, seed, c, S, L, r)
        return [_flip_from_hash(h, row_offset + row)
                for row in range(r)]
    return [_flip_chunk(t, row_offset + row, seed, c, S, L)
            for row in range(r)]


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7, 8))
def sketch_pallas(vp, rot, c: int, r: int, sign_seed: int,
                  interpret: bool = False, lanes: int | None = None,
                  one_mix: bool = False, rot_step: int = 0, sgn=None):
    """(padded_d,) signed-rotate-accumulate -> (r, c) table.

    ``vp`` is the zero-padded flat vector (padded_d = m*c); ``rot`` is
    the (r, m) int32 host-derived rotation table (static per operator,
    passed as an array so the kernel is geometry-cached). ``rot_step``
    > 0 promises every rotation is a multiple of it; when that step is
    lane-aligned the 5-op arbitrary-shift roll collapses to a single
    sublane roll (CountSketch.rot_lanes). ``sgn`` (optional,
    (padded_d,) uint8): packed sign bits (bit row = hash bit 16+row,
    CountSketch._packed_signs_traced) streamed alongside the vector —
    removes the murmur mix (two emulated u32 multiplies per element,
    the largest r-independent ALU block) from the kernel for ~1 extra
    byte/element of HBM traffic."""
    L = lanes or _pick_lanes(c)
    assert L is not None and c % L == 0
    S = c // L
    m = vp.size // c
    seed = np.uint32(sign_seed)
    sublane = rot_step > 0 and rot_step % L == 0
    packed = sgn is not None

    def kernel(rot_ref, v_ref, *refs):
        (sgn_ref, out_ref) = refs if packed else (None, refs[0])
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        # NOTE: a 1-D (c,) input block with an in-kernel reshape was
        # measured WORSE (sketch 8.3 -> 13.4 ms at d=124M): Mosaic
        # relayouts every chunk inside the kernel, serialized with
        # compute, while the XLA-side 2-D relayout copy costs ~1.5 ms
        # once and overlaps. Keep the 2-D operand.
        chunk = v_ref[:]  # (S, L) chunk t, streamed
        flips = _flips_for_chunk(
            t, sgn_ref[:] if packed else None,
            one_mix, seed, c, S, L, r)
        lane = jax.lax.broadcasted_iota(jnp.int32, (S, L), 1)
        for row in range(r):
            signed = _apply_flip(chunk, flips[row])
            if sublane:
                rolled = pltpu.roll(signed, rot_ref[row, t] // L,
                                    axis=0)
            else:
                rolled = _roll1d(signed, rot_ref[row, t], S, L, lane)
            sl = slice(row * S, (row + 1) * S)
            out_ref[sl, :] = out_ref[sl, :] + rolled

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((S, L), lambda t: (t, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [rot.astype(jnp.int32),
                vp.astype(jnp.float32).reshape(m * S, L)]
    if packed:
        in_specs.append(pl.BlockSpec((S, L), lambda t: (t, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(sgn.reshape(m * S, L))
    out = pl.pallas_call(
        kernel,
        grid=(m,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((r * S, L), lambda t: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r * S, L), jnp.float32),
        compiler_params=_compiler_params(4 * r * c),
        interpret=interpret,
    )(*operands)
    return out.reshape(r, c)


@functools.partial(jax.jit,
                   static_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 11))
def sketch_quant_pallas(vp, rot, c: int, r: int, sign_seed: int,
                        wire: str = "int8", interpret: bool = False,
                        lanes: int | None = None, one_mix: bool = False,
                        rot_step: int = 0, sgn=None,
                        row_offset: int = 0):
    """Fused emit + quantize: ``sketch_pallas`` whose f32 table lives
    ONLY in a VMEM scratch accumulator — after the last chunk the
    kernel computes each row's maxabs, quantizes the row at full wire
    range against it (ops/quant.py ``quantize_local`` semantics,
    bit-identical math), and writes the wire-dtype table + per-row
    f32 maxabs. The full-width f32 table never reaches HBM: on the
    model-sharded 2D path the shard-local tile leaves the kernel at
    wire width, ready for the harmonize + reduce-scatter that follows
    (core/rounds.py ``_quantize_for_collective`` does the same
    harmonize on this kernel's outputs, so fused and unfused paths
    share one quantization algebra).

    Returns ``(q, rowmax)``: q (r, c) in the wire dtype, rowmax
    (r, 1) f32. ``wire`` is "int8" or "fp8" (bf16 has no scale and is
    a plain cast of ``sketch_pallas``'s output — nothing to fuse).

    ``row_offset`` (--overlap_depth chunked emission): ``r`` is then
    the CHUNK row count and ``rot`` the chunk's row slice of the
    rotation table; the sign streams key off the absolute row
    ``row_offset + row``, so each chunk's output is bit-identical to
    the same rows of a whole-table call. The VMEM scratch and the
    compiler's VMEM budget derive from the chunk row count — a
    depth-N pipeline holds one chunk-sized accumulator per in-flight
    chunk instead of N full-table scratches."""
    from commefficient_tpu.ops.quant import QMAX, wire_jnp_dtype
    assert wire in QMAX, wire
    qmax = QMAX[wire]
    out_dtype = wire_jnp_dtype(wire)
    L = lanes or _pick_lanes(c)
    assert L is not None and c % L == 0
    S = c // L
    m = vp.size // c
    seed = np.uint32(sign_seed)
    sublane = rot_step > 0 and rot_step % L == 0
    packed = sgn is not None
    assert row_offset >= 0
    if one_mix:
        # the one-mix hash carries 16 sign bits — absolute rows of a
        # chunked call must stay inside them
        assert row_offset + r <= 16, (row_offset, r)

    def kernel(rot_ref, v_ref, *refs):
        if packed:
            sgn_ref, q_ref, rm_ref, acc_ref = refs
        else:
            sgn_ref, (q_ref, rm_ref, acc_ref) = None, refs
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        chunk = v_ref[:]
        flips = _flips_for_chunk(
            t, sgn_ref[:] if packed else None,
            one_mix, seed, c, S, L, r, row_offset)
        lane = jax.lax.broadcasted_iota(jnp.int32, (S, L), 1)
        for row in range(r):
            signed = _apply_flip(chunk, flips[row])
            if sublane:
                rolled = pltpu.roll(signed, rot_ref[row, t] // L,
                                    axis=0)
            else:
                rolled = _roll1d(signed, rot_ref[row, t], S, L, lane)
            sl = slice(row * S, (row + 1) * S)
            acc_ref[sl, :] = acc_ref[sl, :] + rolled

        @pl.when(t == m - 1)
        def _():
            for row in range(r):
                sl = slice(row * S, (row + 1) * S)
                block = acc_ref[sl, :]
                rm = jnp.max(jnp.abs(block))
                # identical scale algebra to quantize_local: full
                # range against the local rowmax, zero-row guard 1.0
                s = jnp.where(rm > 0.0, rm / qmax, 1.0)
                if wire == "int8":
                    q = jnp.clip(jnp.round(block / s), -qmax, qmax)
                    q_ref[sl, :] = q.astype(out_dtype)
                else:
                    # explicit f16 intermediate, matching
                    # quant._to_fp8 bit-for-bit on every backend
                    q_ref[sl, :] = (block / s).astype(
                        jnp.float16).astype(out_dtype)
                rm_ref[row, :] = jnp.full((L,), rm, jnp.float32)

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((S, L), lambda t: (t, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [rot.astype(jnp.int32),
                vp.astype(jnp.float32).reshape(m * S, L)]
    if packed:
        in_specs.append(pl.BlockSpec((S, L), lambda t: (t, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(sgn.reshape(m * S, L))
    q, rm = pl.pallas_call(
        kernel,
        grid=(m,),
        in_specs=in_specs,
        out_specs=(pl.BlockSpec((r * S, L), lambda t: (0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((r, L), lambda t: (0, 0),
                                memory_space=pltpu.VMEM)),
        out_shape=(jax.ShapeDtypeStruct((r * S, L), out_dtype),
                   jax.ShapeDtypeStruct((r, L), jnp.float32)),
        scratch_shapes=[pltpu.VMEM((r * S, L), jnp.float32)],
        compiler_params=_compiler_params(4 * r * c),
        interpret=interpret,
    )(*operands)
    return q.reshape(r, c), rm[:, :1]


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7, 8, 9))
def estimates_pallas(table, rot, c: int, r: int, sign_seed: int,
                     interpret: bool = False, lanes: int | None = None,
                     one_mix: bool = False, valid: int | None = None,
                     rot_step: int = 0, sgn=None):
    """(r, c) table -> (padded_d,) median-of-rows estimates, fused
    (the (r, padded_d) intermediate of the XLA path never exists).

    ``valid``: zero estimates at positions >= valid in-kernel — lets
    callers consume the padded vector directly instead of paying the
    ``[:d]`` prefix-slice copy (CountSketch.estimates(padded=True)).
    ``sgn``: optional (padded_d,) packed sign bits, see
    ``sketch_pallas``."""
    L = lanes or _pick_lanes(c)
    assert L is not None and c % L == 0
    S = c // L
    m = rot.shape[1]
    seed = np.uint32(sign_seed)
    sublane = rot_step > 0 and rot_step % L == 0
    packed = sgn is not None

    def kernel(rot_ref, tab_ref, *refs):
        (sgn_ref, out_ref) = refs if packed else (None, refs[0])
        t = pl.program_id(0)
        flips = _flips_for_chunk(
            t, sgn_ref[:] if packed else None,
            one_mix, seed, c, S, L, r)
        lane = jax.lax.broadcasted_iota(jnp.int32, (S, L), 1)
        vals = []
        for row in range(r):
            trow = tab_ref[row * S:(row + 1) * S, :]
            o = rot_ref[row, t]
            back = (jnp.int32(c) - o) % jnp.int32(c)
            if sublane:
                unrolled = pltpu.roll(trow, back // L, axis=0)
            else:
                unrolled = _roll1d(trow, back, S, L, lane)
            vals.append(_apply_flip(unrolled, flips[row]))
        med = _median_network(vals)
        if valid is not None and valid < m * c:
            s_idx = jax.lax.broadcasted_iota(jnp.int32, (S, L), 0)
            l_idx = jax.lax.broadcasted_iota(jnp.int32, (S, L), 1)
            g = t * c + s_idx * L + l_idx
            med = jnp.where(g < valid, med, 0.0)
        # 1-D output block: the (padded_d,) estimates leave in their
        # consumers' native linear layout (the 2-D (m*S, L) out_shape
        # cost a d-sized relayout on the way to selection)
        out_ref[:] = med.reshape(c)

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        # table resident in VMEM across all chunk steps
        pl.BlockSpec((r * S, L), lambda t: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    operands = [rot.astype(jnp.int32),
                table.astype(jnp.float32).reshape(r * S, L)]
    if packed:
        in_specs.append(pl.BlockSpec((S, L), lambda t: (t, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(sgn.reshape(m * S, L))
    out = pl.pallas_call(
        kernel,
        grid=(m,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((c,), lambda t: (t,),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m * c,), jnp.float32),
        compiler_params=_compiler_params(4 * r * c),
        interpret=interpret,
    )(*operands)
    return out
