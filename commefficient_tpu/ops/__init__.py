from commefficient_tpu.ops.topk import topk as topk  # noqa: F401
from commefficient_tpu.ops.vec import (  # noqa: F401
    clip_by_l2,
    flatten_params,
    global_norm,
)
from commefficient_tpu.ops.sketch import CountSketch  # noqa: F401
