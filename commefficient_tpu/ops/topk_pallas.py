"""Pallas TPU kernel for the exact threshold-select mask.

The XLA formulation in :mod:`commefficient_tpu.ops.topk`
(`_threshold_topk_mask`) materialises several (d,)-sized
intermediates after the bit search — keys, gt/eq masks, the int32
tie-rank cumsum and the combined take mask — ~45 ms of HBM traffic at
GPT-2's d = 124M. This kernel fuses all of it into ONE streamed read
of the squared-magnitude vector and one int8 mask write: the grid
walks chunks sequentially (TPU grid order is sequential) carrying the
running equal-to-threshold count in SMEM, so the lowest-index
tie-break is computed exactly as the XLA path does.

Used by the 1-D, non-vmapped server-side selections (unsketch
recovery, true_topk). The generic batched mask in ops/topk.py stays
XLA — a vmapped pallas_call would batch the grid and break the
sequential-carry tie-break.

No reference counterpart: the reference's exact top-k is torch.topk
on GPU (utils.py:232-252); this is the TPU-native answer to its cost.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# chunk geometry: 512 x 128 = 64K f32 elements = 256 KB VMEM per
# buffered block — well within budget, big enough to amortise grid
# overhead at d ~ 1e8 (~1900 steps)
_S = 512
_L = 128
_CHUNK = _S * _L


def supported(d: int) -> bool:
    """Worth the kernel only when the XLA intermediates hurt."""
    return d >= _CHUNK


@functools.partial(jax.jit, static_argnums=(3,))
def take_mask_pallas(sq, t_key, need, interpret: bool = False):
    """``sq`` (d,) f32 non-negative keys (squared magnitudes),
    ``t_key`` (1,) uint32 — the k-th largest key's bit pattern from
    the threshold search, ``need`` (1,) int32 — how many
    equal-to-threshold elements to take (k − count(gt)).

    Returns a (d,) bool mask with exactly k True: every key > T plus
    the first ``need`` keys == T in index order."""
    d = sq.shape[0]
    pad = (-d) % _CHUNK
    # padded zeros: key 0 is only ever eq when T == 0, and then the
    # real elements' ranks all precede the pads', so need is exhausted
    # before any pad (count(real keys >= 0) = d >= k)
    sqp = jnp.pad(sq, (0, pad))
    m = (d + pad) // _CHUNK

    def kernel(t_ref, need_ref, x_ref, out_ref, cnt_ref):
        t = pl.program_id(0)

        @pl.when(t == 0)
        def _():
            cnt_ref[0] = 0

        keys = jax.lax.bitcast_convert_type(x_ref[:], jnp.uint32)
        T = t_ref[0]
        gt = keys > T
        eq = keys == T
        eqf = eq.astype(jnp.float32)
        # row-major rank of each eq element within the chunk, via
        # triangular matmuls (Mosaic has no cumsum primitive; the MXU
        # does prefix sums for free at tile scale, exact in f32 —
        # counts <= S*L = 64K << 2^24)
        li = jax.lax.broadcasted_iota(jnp.int32, (_L, _L), 0)
        lj = jax.lax.broadcasted_iota(jnp.int32, (_L, _L), 1)
        upper = (li <= lj).astype(jnp.float32)       # (L, L)
        lane_cum = jnp.dot(eqf, upper,
                           preferred_element_type=jnp.float32)
        row_tot = lane_cum[:, _L - 1:_L]             # (S, 1)
        si = jax.lax.broadcasted_iota(jnp.int32, (_S, _S), 0)
        sj = jax.lax.broadcasted_iota(jnp.int32, (_S, _S), 1)
        strict_lower = (sj < si).astype(jnp.float32)  # (S, S)
        row_off = jnp.dot(strict_lower, row_tot,
                          preferred_element_type=jnp.float32)
        rank = (lane_cum.astype(jnp.int32)
                + row_off.astype(jnp.int32) + cnt_ref[0])  # 1-based
        take = gt | (eq & (rank <= need_ref[0]))
        # int8 here is a kernel-local selection bitmap (VMEM out
        # buffer), not a wire format — it never crosses the ICI/host
        # boundary, so quant.py's byte accounting doesn't apply.
        out_ref[:] = take.astype(jnp.int8)  # audit: allow(wire-dtype-crossing)
        cnt_ref[0] = cnt_ref[0] + jnp.sum(eqf).astype(jnp.int32)

    out = pl.pallas_call(
        kernel,
        grid=(m,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_S, _L), lambda t: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_S, _L), lambda t: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m * _S, _L), jnp.int8),
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=interpret,
    )(t_key.astype(jnp.uint32).reshape(1),
      need.astype(jnp.int32).reshape(1),
      sqp.astype(jnp.float32).reshape(m * _S, _L))
    return out.reshape(-1)[:d].astype(bool)
