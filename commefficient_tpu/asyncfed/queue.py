"""Arrival queue: issued client updates ordered by arrival step.

A min-heap on ``(arrive_at, issue_seq)``: pops come out in arrival
order, and clients arriving at the same step come out in issue
order. That tiebreak is what makes the degenerate case exact — with
punctual arrival (all delays 0) and a buffer the size of the cohort,
``pop_arrived`` returns precisely the issued batch, slot for slot.
"""

from __future__ import annotations

import heapq
from typing import Any, List, Optional


class ArrivalQueue:
    """FIFO-within-arrival-step priority queue of issued updates."""

    def __init__(self):
        self._heap: list = []
        self._seq = 0

    def __len__(self) -> int:
        return len(self._heap)

    def push(self, arrive_at: int, entry: Any) -> None:
        heapq.heappush(self._heap, (int(arrive_at), self._seq, entry))
        self._seq += 1

    def pop_arrived(self, now: int, limit: int) -> List[Any]:
        """Up to ``limit`` entries with ``arrive_at <= now``, in
        (arrival, issue) order. Entries still in flight stay queued —
        their staleness grows until a later fold drains them."""
        out: List[Any] = []
        while self._heap and len(out) < limit \
                and self._heap[0][0] <= now:
            out.append(heapq.heappop(self._heap)[2])
        return out

    def peek_arrived(self, now: int,
                     limit: Optional[int] = None) -> List[Any]:
        """The entries ``pop_arrived(now, limit)`` would return,
        without consuming them (the prefetch-lookahead feed)."""
        out: List[Any] = []
        for t, _, e in sorted(self._heap):
            if t > now or (limit is not None and len(out) >= limit):
                break
            out.append(e)
        return out

    def snapshot(self):
        """``(entries, next_seq)`` — every queued ``(arrive_at, seq,
        entry)`` in (arrival, issue) order plus the running sequence
        counter: the checkpointable view of the backlog
        (runtime/checkpoint.py saves it so in-flight buffered rounds
        survive a resume)."""
        return (sorted(self._heap, key=lambda t: (t[0], t[1])),
                self._seq)

    def restore(self, entries, next_seq: int) -> None:
        """Inverse of :meth:`snapshot` — rebuilds the heap in place.
        Preserving the original seq values keeps the FIFO tiebreak
        (and therefore the fold order) identical to a run that was
        never interrupted."""
        self._heap = [(int(t), int(s), e) for t, s, e in entries]
        heapq.heapify(self._heap)
        self._seq = int(next_seq)
