"""Buffered asynchronous federated rounds (FedBuff-style serving).

The synchronous round is a barrier: every client in the cohort must
arrive before the fold runs, so at millions of clients the round
clock is the slowest arrival. This package replaces the barrier with
an ARRIVAL QUEUE and a buffered fold:

* each sampled cohort is *issued* at the current fold step and every
  client gets an arrival delay from an arrival process (default:
  punctual, delay 0 — schedules from ``data/chaos.py`` are injected
  only by tests/benches/scripts, per the arrival-confinement rule);
* arrived updates accumulate in the buffer and the server folds up
  to ``--async_buffer_size K`` of them per step, while the *next*
  cohort's rows are already warming on the clientstore prefetch
  lookahead (the driver, not the sampler, feeds the prefetcher —
  it knows what is queued);
* each folded update is weighted ``1/(1+staleness)^alpha``
  (``--async_staleness_weight``) inside the jitted round, on both
  the transmit and its datapoint count, so the fold stays a weighted
  per-datapoint mean and stale mass never corrupts the server's
  virtual momentum / error feedback.

Sketch linearity (FetchSGD) is what makes the buffer safe: stale
sketched updates merge by weighted addition, so the buffered fold is
algebraically testable against the NumPy mirror. The degenerate
configuration — ``K == cohort`` and ``alpha == 0`` under punctual
arrival — reduces bit-exactly to the synchronous round, and async-off
builds compile to an HLO-identical program (both pinned by tests).

The compiled cohort width never changes: a fold with fewer than
``num_workers`` arrivals pads dead slots (mask 0), reusing the
dead-slot machinery the dropout traces already exercise.
"""

from commefficient_tpu.asyncfed.queue import ArrivalQueue
from commefficient_tpu.asyncfed.driver import AsyncRoundDriver

__all__ = ["ArrivalQueue", "AsyncRoundDriver"]
