"""The async round driver: issue cohorts, fold what has arrived.

Host-side bookkeeping only — nothing here is traced. Each trainer
step the driver *issues* the sampled cohort (every slot gets an
arrival delay from the attached arrival process; default punctual),
then assembles the fold batch from up to ``K`` updates that have
actually arrived. The fold batch keeps the compiled cohort width:
arrived updates fill the leading slots, the rest are dead (mask 0),
so the jitted round program is the same one the dropout traces
already run. The per-slot staleness vector (fold step minus issue
step) rides along for the staleness-weighted fold inside the round.

Simulation model: a stale client's gradient is evaluated when its
fold runs (the standard simulated-staleness benchmarking model —
arrival timing, weighting and byte accounting are exact; the local
compute is replayed at fold time).
"""

from __future__ import annotations

import contextlib
from typing import Callable, Dict, List, Optional

import numpy as np

from commefficient_tpu.asyncfed.queue import ArrivalQueue

# delays(round_index, n) -> np.ndarray of per-slot arrival delays
ArrivalProcess = Callable[[int, int], np.ndarray]


class AsyncRoundDriver:
    """Buffered-arrival front end for ``FedModel.__call__``."""

    def __init__(self, cfg, stamp: Optional[Callable] = None):
        self.k = int(cfg.async_buffer_size)
        self.num_workers = int(cfg.num_workers)
        assert 0 < self.k <= self.num_workers
        self.queue = ArrivalQueue()
        self._arrival: Optional[ArrivalProcess] = None
        self._stamp = stamp  # (ids, issue_round) -> None
        # optional CausalTracer (--causal_trace), attached by
        # FedModel: cohort_issue / arrival_dequeue spans nest under
        # the enclosing async_fold telemetry span
        self.causal = None
        self._fold = 0
        self.issued_total = 0
        self.folded_total = 0
        self.last_stats: Dict[str, float] = {}

    def attach_arrival_process(self,
                               fn: Optional[ArrivalProcess]) -> None:
        """Inject a seeded arrival schedule (tests/benches/scripts
        only — production keeps the punctual default)."""
        self._arrival = fn

    # -- the per-step protocol --------------------------------------

    def step(self, batch: dict):
        """Issue ``batch``'s cohort, then assemble this fold's batch
        from up to K arrived updates. Returns
        ``(fold_batch, staleness)`` with ``staleness`` float32
        ``(num_workers,)`` (0 on dead pad slots)."""
        now = self._fold
        ids = np.asarray(batch["client_ids"])
        W = ids.shape[0]
        if self._arrival is not None:
            delays = np.maximum(
                np.asarray(self._arrival(now, W)), 0).astype(np.int64)
        else:
            delays = np.zeros((W,), np.int64)
        if self._stamp is not None:
            self._stamp(ids, now)
        causal = self.causal
        ctx = (causal.span("cohort_issue") if causal is not None
               else contextlib.nullcontext())
        with ctx:
            for i in range(W):
                self.queue.push(now + int(delays[i]), {
                    "issue": now,
                    "slot": {k: np.asarray(v)[i] for k, v in
                             batch.items()},
                })
            self.issued_total += W
        ctx = (causal.span("arrival_dequeue") if causal is not None
               else contextlib.nullcontext())
        with ctx:
            arrived = self.queue.pop_arrived(now, self.k)
        self.folded_total += len(arrived)
        fold_batch = self._assemble(arrived, batch)
        staleness = np.zeros((self.num_workers,), np.float32)
        for i, e in enumerate(arrived):
            staleness[i] = float(now - e["issue"])
        self._note_stats(arrived, staleness)
        self._fold = now + 1
        return fold_batch, staleness

    def _assemble(self, arrived: List[dict], template: dict) -> dict:
        """Width-``num_workers`` host batch: arrived slots first,
        then dead padding (mask 0, id 0 — the established dead-slot
        shape, skipped by state writeback and byte accounting)."""
        W = self.num_workers
        out = {}
        for key, v in template.items():
            v = np.asarray(v)
            rows = [np.asarray(e["slot"][key]) for e in arrived]
            pad = W - len(rows)
            if pad:
                zero = np.zeros_like(v[0])
                rows.extend([zero] * pad)
            out[key] = np.stack(rows).astype(v.dtype)
        if len(arrived) < W:
            # belt + braces: padding must be dead regardless of the
            # template's mask content
            mask = out["mask"].copy()
            mask[len(arrived):] = 0
            out["mask"] = mask
        return out

    # -- checkpoint/resume ------------------------------------------

    def export_state(self) -> dict:
        """Host-serialisable snapshot of the driver: the arrival heap
        in (arrive_at, seq) order — timing columns as int64 arrays,
        per-slot rows stacked per batch key — plus the fold/seq/total
        counters. Saved by runtime/checkpoint.py so a resumed async
        run rebuilds the exact backlog instead of silently restarting
        with an empty buffer."""
        entries, next_seq = self.queue.snapshot()
        keys = sorted(entries[0][2]["slot"]) if entries else []
        return {
            "fold": int(self._fold),
            "seq": int(next_seq),
            "issued_total": int(self.issued_total),
            "folded_total": int(self.folded_total),
            "slot_keys": keys,
            "arrive_at": np.asarray([t for t, _, _ in entries],
                                    np.int64),
            "issue_seq": np.asarray([s for _, s, _ in entries],
                                    np.int64),
            "issue": np.asarray([e["issue"] for _, _, e in entries],
                                np.int64),
            "slots": {k: np.stack([np.asarray(e["slot"][k])
                                   for _, _, e in entries])
                      for k in keys},
        }

    def import_state(self, state: dict) -> None:
        """Inverse of :meth:`export_state` — rebuilds the heap and
        counters in place. Entry order, seq values and staleness
        arithmetic come back exactly, so the resumed fold sequence is
        bit-identical to the uninterrupted run's."""
        self._fold = int(state["fold"])
        self.issued_total = int(state["issued_total"])
        self.folded_total = int(state["folded_total"])
        keys = list(state["slot_keys"])
        arrive_at = np.asarray(state["arrive_at"], np.int64)
        issue_seq = np.asarray(state["issue_seq"], np.int64)
        issue = np.asarray(state["issue"], np.int64)
        entries = []
        for i in range(arrive_at.shape[0]):
            entry = {
                "issue": int(issue[i]),
                "slot": {k: np.asarray(state["slots"][k][i])
                         for k in keys},
            }
            entries.append((int(arrive_at[i]), int(issue_seq[i]),
                            entry))
        self.queue.restore(entries, int(state["seq"]))

    # -- prefetch lookahead -----------------------------------------

    def peek_next_ids(self) -> Optional[np.ndarray]:
        """The next fold's exact gather ids (fold-slot order, dead
        slots padded with id 0) — the prefetch-lookahead feed. Only a
        backlog already holding a full buffer is predictable: the
        next issue cannot preempt entries that have already arrived
        (they sort first by (arrive_at, seq)), so the prediction is
        exact. An underfull backlog returns None and the caller falls
        back to the sampler lookahead; a wrong fallback guess is just
        a prefetch miss (synchronous gather)."""
        nxt = self.queue.peek_arrived(self._fold, self.k)
        if len(nxt) < self.k:
            return None
        ids = np.zeros((self.num_workers,), np.int64)
        for i, e in enumerate(nxt):
            ids[i] = int(e["slot"]["client_ids"])
        return ids

    # -- telemetry --------------------------------------------------

    def _note_stats(self, arrived: List[dict],
                    staleness: np.ndarray) -> None:
        n = len(arrived)
        s = staleness[:n] if n else np.zeros((0,), np.float32)
        hist = np.bincount(s.astype(np.int64),
                           minlength=1) if n else np.zeros(1, np.int64)
        self.last_stats = {
            "async_buffer_occupancy": n / float(self.k),
            "async_backlog": float(len(self.queue)),
            "async_staleness_mean": float(s.mean()) if n else 0.0,
            "async_staleness_max": float(s.max()) if n else 0.0,
            "async_staleness_hist": [int(c) for c in hist],
        }

    def round_stats(self) -> Dict[str, float]:
        """The last fold's probes (merged into the ledger round
        record and fed to the async_staleness alarm rule)."""
        return dict(self.last_stats)
