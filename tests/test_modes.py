"""End-to-end mode-lattice tests: the JAX round engine vs an
independent NumPy mirror of the reference semantics, plus closed-form
hand checks (reference unit_test.py:79-118 step-1 traces).

Why only the step-1 traces: the reference unit test's later expected
weights (w2=0.3808 one-param; the two-param k=1 trace ending at
(-0.3008, 0.34)) encode a pre-refactor optimizer — e.g. the k=1 trace
is true_topk + local momentum with NO server-side error accumulation,
a combination the current reference *asserts against*
(fed_aggregator.py:514 requires error_type=="virtual" for true_topk; a
virtual-error run double-counts the coord-0 residual and lands at
w2≈(-0.58, 0.34) instead). The current-semantics step-2 behaviour is
covered by the closed-form tests below and the NumPy mirror."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import (ClientStates, args2sketch,
                                           build_client_round,
                                           build_server_round)
from commefficient_tpu.core.server import ServerState

from reference_mirror import MirrorFed


def linear_loss(params_flat, batch):
    """Masked-mean MSE for y = w.x — the reference unit test's model
    (unit_test.py:16-17) with mean reduction."""
    pred = batch["x"] @ params_flat
    sq = (pred - batch["y"]) ** 2
    n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
    loss = jnp.sum(sq * batch["mask"]) / n
    return loss, (loss * 0.0 + 1.0,)  # dummy accuracy metric


def make_cfg(**kw):
    base = dict(mode="uncompressed", local_momentum=0.0,
                virtual_momentum=0.0, weight_decay=0.0,
                error_type="none", num_workers=2, k=2,
                num_rows=3, num_cols=8, num_blocks=1,
                local_batch_size=2, microbatch_size=-1, seed=21)
    base.update(kw)
    return Config(**base)


def run_engine(cfg, w0, rounds, lr, num_clients=4):
    """rounds: list of list of (client_id, X(np), y(np)); all client
    batches padded to the same B with masks."""
    d = len(w0)
    cfg = dataclasses.replace(cfg, grad_size=d)
    B = max(len(y) for rnd in rounds for _, _, y in rnd)
    client_round = jax.jit(build_client_round(cfg, linear_loss, B))
    server_round = jax.jit(build_server_round(cfg))

    ps = jnp.asarray(w0, jnp.float32)
    cs = ClientStates.init(cfg, num_clients, ps)
    ss = ServerState.init(cfg)
    rng = jax.random.PRNGKey(cfg.seed)
    traj = []
    for rnd_i, clients in enumerate(rounds):
        W = len(clients)
        x = np.zeros((W, B, d), np.float32)
        y = np.zeros((W, B), np.float32)
        mask = np.zeros((W, B), np.float32)
        ids = np.zeros((W,), np.int32)
        for i, (cid, X, Y) in enumerate(clients):
            n = len(Y)
            x[i, :n], y[i, :n], mask[i, :n], ids[i] = X, Y, 1.0, cid
        batch = {"x": jnp.asarray(x), "y": jnp.asarray(y),
                 "mask": jnp.asarray(mask)}
        res = client_round(ps, cs, batch, jnp.asarray(ids),
                           jax.random.fold_in(rng, rnd_i),
                           jnp.float32(lr))
        cs = res.client_states
        ps, ss, new_vel, _, _ = server_round(
            ps, ss, res.aggregated, jnp.float32(lr),
            cs.velocities, jnp.asarray(ids))
        if new_vel is not None:
            cs = cs._replace(velocities=new_vel)
        traj.append(np.asarray(ps, np.float64))
    return traj


def run_mirror(cfg, w0, rounds, lr, num_clients=4):
    d = len(w0)
    cfg = dataclasses.replace(cfg, grad_size=d)
    m = MirrorFed(cfg, w0, num_clients, sketch=args2sketch(cfg))
    if cfg.mode == "fedavg":
        return [m.round_fedavg(r, lr) for r in rounds]
    return [m.round(r, lr) for r in rounds]


def unit_test_data():
    """The reference unit test's 1-param dataset: x=[0..3], y=x
    (unit_test.py:23-26, 84-88), two clients with 2 points each."""
    X = np.arange(4, dtype=np.float32).reshape(4, 1)
    y = np.arange(4, dtype=np.float32)
    return [(0, X[:2], y[:2]), (1, X[2:], y[2:])]


def assert_traj_close(cfg, w0, rounds, lr, rtol=1e-4, atol=1e-5, **kw):
    got = run_engine(cfg, w0, rounds, lr, **kw)
    want = run_mirror(cfg, w0, rounds, lr, **kw)
    for r, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_allclose(g, w, rtol=rtol, atol=atol,
                                   err_msg=f"round {r}")


class TestHandDerived:
    """Closed-form checks on the 1-param linear regression."""

    def test_uncompressed_one_round(self):
        # mean-loss grad at w=0 over all 4 pts: (2/4)*sum(x^2)*(w-1)=-7
        # two clients of 2: g1_mean=-1, g2_mean=-13; transmit=bs*g;
        # agg=(-2-26)/4=-7; w1 = 0 + lr*7
        cfg = make_cfg()
        traj = run_engine(cfg, [0.0], [unit_test_data()], lr=0.005)
        np.testing.assert_allclose(traj[0], [0.035], rtol=1e-5)

    def test_uncompressed_two_rounds(self):
        # w2 = w1 + lr*7*(1-w1)
        cfg = make_cfg()
        traj = run_engine(cfg, [0.0], [unit_test_data()] * 2, lr=0.005)
        w1 = 0.035
        np.testing.assert_allclose(traj[1], [w1 + 0.005 * 7 * (1 - w1)],
                                   rtol=1e-5)

    def test_sum_loss_reproduces_reference_trace_step1(self):
        """With one client holding all 4 points, the round gradient is
        the batch-mean grad -7, matching the reference trace's -28
        sum-gradient scaled by its batch: w1 = 0.14 at 4x the lr."""
        cfg = make_cfg(num_workers=1)
        X = np.arange(4, dtype=np.float32).reshape(4, 1)
        y = np.arange(4, dtype=np.float32)
        traj = run_engine(cfg, [0.0], [[(0, X, y)]], lr=0.02)
        np.testing.assert_allclose(traj[0], [0.14], rtol=1e-5)


class TestModeLattice:
    """Engine vs NumPy reference-mirror across the mode/error/momentum
    combination lattice (the combos the reference permits,
    SURVEY.md §2.1-2.2)."""

    W0 = [0.0, 0.5, -0.3, 0.1, 0.0, 0.2, -0.1, 0.05]

    def rounds(self, seed=0, n_rounds=3, d=8, num_clients=4, W=2, B=3):
        rng = np.random.RandomState(seed)
        rounds = []
        for _ in range(n_rounds):
            ids = rng.choice(num_clients, W, replace=False)
            rounds.append([
                (int(cid),
                 rng.randn(B, d).astype(np.float32),
                 rng.randn(B).astype(np.float32))
                for cid in ids])
        return rounds

    def test_uncompressed_virtual_momentum(self):
        cfg = make_cfg(virtual_momentum=0.9)
        assert_traj_close(cfg, self.W0, self.rounds(), lr=0.01)

    def test_uncompressed_weight_decay(self):
        cfg = make_cfg(weight_decay=5e-4)
        assert_traj_close(cfg, self.W0, self.rounds(1), lr=0.01)

    def test_true_topk_virtual_error(self):
        cfg = make_cfg(mode="true_topk", error_type="virtual", k=3)
        assert_traj_close(cfg, self.W0, self.rounds(2), lr=0.01)

    def test_true_topk_virtual_error_momentum(self):
        cfg = make_cfg(mode="true_topk", error_type="virtual", k=3,
                       virtual_momentum=0.9)
        assert_traj_close(cfg, self.W0, self.rounds(3), lr=0.01)

    def test_true_topk_local_momentum_masking(self):
        """Server must zero participating clients' local velocities at
        the global top-k coords (fed_aggregator.py:530-535 — done
        right here, not the reference's unset-global bug)."""
        cfg = make_cfg(mode="true_topk", error_type="virtual", k=3,
                       local_momentum=0.9)
        assert_traj_close(cfg, self.W0, self.rounds(4, n_rounds=4),
                          lr=0.01)

    def test_local_topk_plain(self):
        cfg = make_cfg(mode="local_topk", k=3)
        assert_traj_close(cfg, self.W0, self.rounds(5), lr=0.01)

    def test_local_topk_local_error(self):
        cfg = make_cfg(mode="local_topk", error_type="local", k=3)
        assert_traj_close(cfg, self.W0, self.rounds(6, n_rounds=4),
                          lr=0.01)

    def test_local_topk_local_error_momentum(self):
        cfg = make_cfg(mode="local_topk", error_type="local", k=3,
                       local_momentum=0.9)
        assert_traj_close(cfg, self.W0, self.rounds(7, n_rounds=4),
                          lr=0.01)

    def test_local_topk_virtual_momentum(self):
        cfg = make_cfg(mode="local_topk", k=3, virtual_momentum=0.9)
        assert_traj_close(cfg, self.W0, self.rounds(8), lr=0.01)

    def test_sketch_virtual_error(self):
        cfg = make_cfg(mode="sketch", error_type="virtual", k=4,
                       num_rows=5, num_cols=64)
        assert_traj_close(cfg, self.W0, self.rounds(9), lr=0.01,
                          rtol=1e-3, atol=1e-4)

    def test_sketch_virtual_error_momentum(self):
        cfg = make_cfg(mode="sketch", error_type="virtual", k=4,
                       num_rows=5, num_cols=64, virtual_momentum=0.9)
        assert_traj_close(cfg, self.W0, self.rounds(10, n_rounds=4),
                          lr=0.01, rtol=1e-3, atol=1e-4)

    def test_fedavg(self):
        cfg = make_cfg(mode="fedavg", fedavg_batch_size=2,
                       local_batch_size=-1, num_fedavg_epochs=2,
                       fedavg_lr_decay=0.9)
        assert_traj_close(cfg, self.W0, self.rounds(11, B=5), lr=0.05)

    def test_fedavg_virtual_momentum(self):
        cfg = make_cfg(mode="fedavg", fedavg_batch_size=-1,
                       local_batch_size=-1, virtual_momentum=0.9)
        assert_traj_close(cfg, self.W0, self.rounds(12, B=4), lr=0.05)

    def test_ragged_batches_weighting(self):
        """Clients with different true batch sizes must be weighted by
        datapoint count (fed_worker.py:192, fed_aggregator.py:334)."""
        cfg = make_cfg()
        rng = np.random.RandomState(13)
        rounds = [[
            (0, rng.randn(1, 8).astype(np.float32),
             rng.randn(1).astype(np.float32)),
            (1, rng.randn(3, 8).astype(np.float32),
             rng.randn(3).astype(np.float32)),
        ]]
        assert_traj_close(cfg, self.W0, rounds, lr=0.01)

    def test_dp_worker_clip(self):
        """DP worker mode with noise_multiplier=0: pure per-client
        L2 clipping to l2_norm_clip (fed_worker.py:306-307)."""
        cfg = make_cfg(do_dp=True, dp_mode="worker", l2_norm_clip=0.5,
                       noise_multiplier=0.0)
        assert_traj_close(cfg, self.W0, self.rounds(15), lr=0.01)

    def test_dp_worker_noise_scale(self):
        """Worker-mode DP noise must have std noise_multiplier *
        sqrt(num_workers) per client (fed_worker.py:308-311)."""
        import dataclasses as dc
        d, W = 8, 4
        cfg = dc.replace(make_cfg(do_dp=True, dp_mode="worker",
                                  l2_norm_clip=1e9,
                                  noise_multiplier=0.1, num_workers=W),
                         grad_size=d)
        from commefficient_tpu.core.grad import make_forward_grad
        fg = make_forward_grad(cfg, linear_loss, None, 2)
        batch = {"x": jnp.zeros((2, d)), "y": jnp.zeros(2),
                 "mask": jnp.ones(2)}
        w = jnp.zeros(d)
        samples = np.stack([
            np.asarray(fg(w, batch, jax.random.PRNGKey(i))[0])
            for i in range(500)])
        # zero data + zero weights -> transmit is pure noise
        std = samples.std()
        np.testing.assert_allclose(std, 0.1 * np.sqrt(W), rtol=0.1)

    def test_dp_server_noise_zero_matches_uncompressed(self):
        cfg = make_cfg(do_dp=True, dp_mode="server",
                       noise_multiplier=0.0)
        got = run_engine(cfg, self.W0, self.rounds(16), lr=0.01)
        # server mode: no worker-side noise; clip still applies
        want = run_mirror(cfg, self.W0, self.rounds(16), lr=0.01)
        np.testing.assert_allclose(got[-1], want[-1], rtol=1e-4,
                                   atol=1e-5)

    def test_dp_server_noise_persists_in_momentum(self):
        """Reference aliasing (fed_aggregator.py:506-510): server-mode
        DP noise lands in Vvelocity and persists across rounds."""
        import dataclasses as dc
        import jax
        from commefficient_tpu.core.server import (ServerState,
                                                   server_update)
        cfg = dc.replace(make_cfg(do_dp=True, dp_mode="server",
                                  noise_multiplier=0.5,
                                  virtual_momentum=0.9), grad_size=8)
        state = ServerState.init(cfg)
        g = jnp.ones(8)
        res = server_update(cfg, g, state, 1.0,
                            noise_rng=jax.random.PRNGKey(0))
        # Vvelocity must include the noise (not just the update)
        assert not np.allclose(np.asarray(res.state.Vvelocity),
                               np.ones(8))
        np.testing.assert_allclose(np.asarray(res.weight_update),
                                   np.asarray(res.state.Vvelocity))

    def test_microbatched_grad_accumulation(self):
        """Sum-of-microbatch-mean-gradients semantics
        (fed_worker.py:268-289)."""
        cfg = make_cfg(microbatch_size=1)
        got = run_engine(cfg, self.W0, self.rounds(14, B=3), lr=0.01)
        # mirror: with B=3 equal microbatches of 1, sum of means =
        # 3 * batch-mean, so equals mirror with lr*3... compute directly:
        cfg_plain = make_cfg()
        want = run_mirror(cfg_plain, self.W0, self.rounds(14, B=3),
                          lr=0.03)
        np.testing.assert_allclose(got[0], want[0], rtol=1e-4, atol=1e-5)


class TestTopkDown:
    """--topk_down stale-client weight download (reference
    get_new_worker_weights, fed_worker.py:234-249)."""

    def test_stale_weight_download_applies_topk_of_diff(self):
        import jax.numpy as jnp
        from commefficient_tpu.core.client import stale_weight_download

        cfg = make_cfg(mode="true_topk", error_type="virtual",
                       do_topk_down=True, k=2)
        ps = jnp.asarray(np.array([1.0, 5.0, -3.0, 0.5, 0.1],
                                  np.float32))
        local = jnp.zeros(5, jnp.float32)
        out = np.asarray(stale_weight_download(cfg, ps, local))
        # only the two largest-|diff| coords (5.0 and -3.0) download
        np.testing.assert_array_equal(
            out, np.array([0.0, 5.0, -3.0, 0.0, 0.0], np.float32))

    def test_round_engine_tracks_client_weights(self):
        """Under --topk_down the engine keeps per-client stale weights
        and each participating client only catches up by top-k."""
        import jax
        import jax.numpy as jnp
        from commefficient_tpu.core.rounds import (ClientStates,
                                                   build_client_round)

        d, k, W = 12, 3, 2
        cfg = make_cfg(mode="true_topk", error_type="virtual",
                       local_momentum=0.0, do_topk_down=True, k=k,
                       num_workers=W, local_batch_size=2)
        cfg.grad_size = d

        def loss(p, batch):
            # quadratic -> grad = p - target rows
            t = jnp.sum(batch["x"], axis=0)
            return (0.5 * jnp.sum((p - t) ** 2), (jnp.float32(0.0),))

        round_fn = jax.jit(build_client_round(cfg, loss, 2))
        ps = jnp.asarray(np.linspace(1, 4, d).astype(np.float32))
        cs = ClientStates.init(cfg, 4, jnp.zeros(d, jnp.float32))
        assert cs.weights is not None and cs.weights.shape == (4, d)

        batch = {"x": jnp.zeros((W, 2, d), jnp.float32),
                 "mask": jnp.ones((W, 2), jnp.float32)}
        ids = jnp.asarray([0, 2], jnp.int32)
        res = round_fn(ps, cs, batch, ids, jax.random.PRNGKey(0), 1.0)

        new_w = np.asarray(res.client_states.weights)
        # participating clients moved by exactly k coords, others not
        assert (np.count_nonzero(new_w[0]) == k
                and np.count_nonzero(new_w[2]) == k)
        np.testing.assert_array_equal(new_w[1], np.zeros(d))
        np.testing.assert_array_equal(new_w[3], np.zeros(d))


class TestSparseServerUpdate:
    def test_sparse_resketch_path_equals_dense(self, monkeypatch):
        """The large-d sparse server path (sparse re-sketch + k-sized
        weight scatter) must produce the same new weights, server
        state, and support as the dense path it replaces."""
        import jax

        from commefficient_tpu.config import Config
        from commefficient_tpu.core.rounds import build_server_round
        from commefficient_tpu.core.server import ServerState
        from commefficient_tpu.ops.sketch import CountSketch

        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9,
                     num_workers=2, local_batch_size=2, num_clients=4,
                     dataset_name="CIFAR10", seed=0, k=16,
                     num_rows=3, num_cols=256, num_blocks=1,
                     grad_size=4096)
        rng = np.random.RandomState(0)
        ps = jnp.asarray(rng.randn(cfg.grad_size).astype(np.float32))
        table = jnp.asarray(
            rng.randn(cfg.num_rows, cfg.num_cols).astype(np.float32))
        ss = ServerState.init(cfg)

        def run(force_sparse):
            monkeypatch.setattr(
                CountSketch, "prefer_sparse_resketch",
                lambda self, k: force_sparse)
            fn = build_server_round(cfg)
            new_ps, new_ss, _, upd, support = fn(
                ps, ss, table, jnp.float32(0.05))
            return (np.asarray(new_ps),
                    np.asarray(new_ss.Vvelocity),
                    np.asarray(new_ss.Verror),
                    upd, support)

        ps_d, vv_d, ve_d, upd_d, sup_d = run(False)
        ps_s, vv_s, ve_s, upd_s, sup_s = run(True)
        assert upd_d is not None and upd_s is None
        np.testing.assert_allclose(ps_s, ps_d, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(vv_s, vv_d, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(ve_s, ve_d, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(sup_s[0]),
                                      np.asarray(sup_d[0]))
        np.testing.assert_allclose(np.asarray(sup_s[1]),
                                   np.asarray(sup_d[1]), rtol=1e-6)

    def test_sparse_path_with_lr_vector(self, monkeypatch):
        """Per-coordinate LR vectors must scale the sparse scatter the
        same way they scale the dense update."""
        import jax

        from commefficient_tpu.config import Config
        from commefficient_tpu.core.rounds import build_server_round
        from commefficient_tpu.core.server import ServerState
        from commefficient_tpu.ops.sketch import CountSketch

        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.0,
                     num_workers=2, local_batch_size=2, num_clients=4,
                     dataset_name="CIFAR10", seed=1, k=8,
                     num_rows=3, num_cols=128, num_blocks=1,
                     grad_size=1024)
        rng = np.random.RandomState(1)
        ps = jnp.asarray(rng.randn(cfg.grad_size).astype(np.float32))
        table = jnp.asarray(
            rng.randn(cfg.num_rows, cfg.num_cols).astype(np.float32))
        lr_vec = jnp.asarray(
            rng.rand(cfg.grad_size).astype(np.float32))
        ss = ServerState.init(cfg)

        def run(force_sparse):
            monkeypatch.setattr(
                CountSketch, "prefer_sparse_resketch",
                lambda self, k: force_sparse)
            fn = build_server_round(cfg)
            new_ps, *_ = fn(ps, ss, table, lr_vec)
            return np.asarray(new_ps)

        np.testing.assert_allclose(run(True), run(False),
                                   rtol=1e-5, atol=1e-6)


class TestThresholdServerSelect:
    """The exact large-d server selections (sketch dense-regime
    unsketch, true_topk) via the threshold mask: same weights, state
    and CHANGED-COORDS support as the lax.top_k index path they
    replace (the support switches form, (idx, vals) -> bitmap)."""

    def _support_set(self, support, d):
        if isinstance(support, dict):
            bits = np.unpackbits(np.asarray(support["bitmap"]))[:d]
            return set(np.nonzero(bits)[0].tolist())
        idx = np.asarray(support[0])
        vals = np.asarray(support[1])
        return set(idx[vals != 0].tolist())

    def test_sketched_threshold_equals_topk_path(self, monkeypatch):
        from commefficient_tpu.config import Config
        from commefficient_tpu.core.rounds import build_server_round
        from commefficient_tpu.core.server import ServerState
        import importlib
        topk_mod = importlib.import_module(
            "commefficient_tpu.ops.topk")

        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9,
                     num_workers=2, local_batch_size=2, num_clients=4,
                     dataset_name="CIFAR10", seed=0, k=16,
                     num_rows=3, num_cols=256, num_blocks=1,
                     grad_size=4096)
        rng = np.random.RandomState(3)
        ps = jnp.asarray(rng.randn(cfg.grad_size).astype(np.float32))
        table = jnp.asarray(
            rng.randn(cfg.num_rows, cfg.num_cols).astype(np.float32))
        ss = ServerState.init(cfg)

        def run(min_d):
            monkeypatch.setattr(topk_mod,
                                "_THRESHOLD_SELECT_MIN_D", min_d)
            fn = build_server_round(cfg)
            new_ps, new_ss, _, upd, support = fn(
                ps, ss, table, jnp.float32(0.05))
            return (np.asarray(new_ps), np.asarray(new_ss.Vvelocity),
                    np.asarray(new_ss.Verror), support)

        ps_t, vv_t, ve_t, sup_t = run(1)        # threshold engaged
        ps_s, vv_s, ve_s, sup_s = run(1 << 60)  # top_k path
        assert isinstance(sup_t, dict) and not isinstance(sup_s, dict)
        np.testing.assert_allclose(ps_t, ps_s, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(vv_t, vv_s, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(ve_t, ve_s, rtol=1e-6, atol=1e-7)
        assert self._support_set(sup_t, cfg.grad_size) \
            == self._support_set(sup_s, cfg.grad_size)

    def test_true_topk_threshold_equals_topk_path(self, monkeypatch):
        from commefficient_tpu.config import Config
        from commefficient_tpu.core.rounds import build_server_round
        from commefficient_tpu.core.server import ServerState
        import importlib
        topk_mod = importlib.import_module(
            "commefficient_tpu.ops.topk")

        cfg = Config(mode="true_topk", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9,
                     num_workers=2, local_batch_size=2, num_clients=4,
                     dataset_name="CIFAR10", seed=0, k=16,
                     grad_size=4096)
        rng = np.random.RandomState(4)
        ps = jnp.asarray(rng.randn(cfg.grad_size).astype(np.float32))
        grad = jnp.asarray(rng.randn(cfg.grad_size).astype(np.float32))
        ss = ServerState.init(cfg)

        def run(min_d):
            monkeypatch.setattr(topk_mod,
                                "_THRESHOLD_SELECT_MIN_D", min_d)
            fn = build_server_round(cfg)
            new_ps, new_ss, _, upd, support = fn(
                ps, ss, grad, jnp.float32(0.05))
            return (np.asarray(new_ps), np.asarray(new_ss.Vvelocity),
                    np.asarray(new_ss.Verror), support)

        ps_t, vv_t, ve_t, sup_t = run(1)
        ps_s, vv_s, ve_s, sup_s = run(1 << 60)
        assert isinstance(sup_t, dict) and not isinstance(sup_s, dict)
        np.testing.assert_allclose(ps_t, ps_s, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(vv_t, vv_s, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(ve_t, ve_s, rtol=1e-6, atol=1e-7)
        assert self._support_set(sup_t, cfg.grad_size) \
            == self._support_set(sup_s, cfg.grad_size)

    def test_zero_lr_bitmap_marks_nothing(self, monkeypatch):
        """lr == 0: the bit-packed support must read all-unchanged,
        matching the value-compare on update * lr."""
        from commefficient_tpu.config import Config
        from commefficient_tpu.core.rounds import build_server_round
        from commefficient_tpu.core.server import ServerState
        import importlib
        topk_mod = importlib.import_module(
            "commefficient_tpu.ops.topk")

        monkeypatch.setattr(topk_mod, "_THRESHOLD_SELECT_MIN_D", 1)
        cfg = Config(mode="true_topk", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.0,
                     num_workers=2, local_batch_size=2, num_clients=4,
                     dataset_name="CIFAR10", seed=0, k=16,
                     grad_size=1024)
        rng = np.random.RandomState(5)
        fn = build_server_round(cfg)
        *_, support = fn(
            jnp.asarray(rng.randn(1024).astype(np.float32)),
            ServerState.init(cfg),
            jnp.asarray(rng.randn(1024).astype(np.float32)),
            jnp.float32(0.0))
        assert self._support_set(support, 1024) == set()


class TestFedavgInitialLr:
    def test_round_before_first_step_transmits_nothing(self):
        """The fedavg local-SGD LR must start at ZERO like the
        reference's shared g_lr tensor (fed_aggregator.py:98-101):
        clients read the value set by the previous round's
        opt.step(), so a round dispatched before any step must
        transmit zero weight deltas. (Initialising to 1.0 made round
        0 take full-gradient local steps — instant divergence at
        ResNet9 scale.)"""
        import flax.linen as nn

        from commefficient_tpu.config import Config
        from commefficient_tpu.runtime import FedModel

        class Lin(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(4, use_bias=False)(x)

        module = Lin()
        params = module.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 3)))["params"]
        args = Config(mode="fedavg", error_type="none",
                      local_momentum=0.0, virtual_momentum=0.0,
                      num_workers=2, local_batch_size=-1,
                      fedavg_batch_size=2, num_clients=4,
                      dataset_name="CIFAR10", seed=0)

        def loss(p, batch, cfg):
            pred = module.apply({"params": p}, batch["x"])
            per = jnp.sum((pred - batch["y"][..., None]) ** 2, -1)
            n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
            return jnp.sum(per * batch["mask"]) / n, ()

        model = FedModel(module, params, loss, args,
                         padded_batch_size=4)
        assert model.fedavg_lr == 0.0
        rng = np.random.RandomState(0)
        batch = {"x": rng.randn(2, 4, 3).astype(np.float32),
                 "y": rng.randn(2, 4).astype(np.float32),
                 "mask": np.ones((2, 4), np.float32),
                 "client_ids": np.array([0, 1], np.int32)}
        model(batch)
        np.testing.assert_array_equal(
            np.asarray(model.pending_aggregated), 0.0)


class TestDeadSlotServerMasking:
    def test_true_topk_dead_client_velocity_untouched(self):
        """Regression (found by tests/test_fuzz_modes.py): true_topk's
        SERVER-side velocity masking scatters rows back at the round's
        client ids — a dead slot (dropout / loader padding, all-zero
        mask) must carry the out-of-range sentinel through
        ``FedModel.pending_client_ids`` so the dead client's momentum
        stays untouched, same state-untouched contract as the
        client-side states (core/rounds.py _state_ids)."""
        import flax.linen as nn

        from commefficient_tpu.config import Config
        from commefficient_tpu.runtime import FedModel, FedOptimizer

        class Lin(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(4, use_bias=False)(x)

        module = Lin()
        params = module.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 3)))["params"]
        args = Config(mode="true_topk", error_type="virtual",
                      local_momentum=0.9, virtual_momentum=0.9,
                      k=2, num_workers=2, local_batch_size=4,
                      num_clients=4, dataset_name="CIFAR10", seed=0)

        def loss(p, batch, cfg):
            pred = module.apply({"params": p}, batch["x"])
            per = jnp.sum((pred - batch["y"][..., None]) ** 2, -1)
            n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
            return jnp.sum(per * batch["mask"]) / n, ()

        model = FedModel(module, params, loss, args,
                         padded_batch_size=4)
        opt = FedOptimizer([{"lr": 0.1}], args)
        rng = np.random.RandomState(0)

        def round_with_mask(mask):
            batch = {"x": rng.randn(2, 4, 3).astype(np.float32),
                     "y": rng.randn(2, 4).astype(np.float32),
                     "mask": mask,
                     "client_ids": np.array([0, 1], np.int32)}
            model(batch)
            opt.step()

        # round 1: both alive — client 1 accumulates momentum
        round_with_mask(np.ones((2, 4), np.float32))
        vel_before = np.asarray(model.client_states.velocities[1])
        assert np.abs(vel_before).sum() > 0
        # round 2: client 1 is DEAD (all padding). Its velocity must
        # be bit-identical afterwards — in particular NOT masked at
        # the round's global top-k coordinates by the server scatter.
        dead = np.ones((2, 4), np.float32)
        dead[1] = 0.0
        round_with_mask(dead)
        np.testing.assert_array_equal(
            np.asarray(model.client_states.velocities[1]), vel_before)


class TestResolveRotLanes:
    """--sketch_rot_lanes -1 (auto) resolution — core/rounds.py
    resolve_rot_lanes engages 1024 only on a TPU default backend at a
    Pallas-supported, lane-aligned, large-d geometry; everywhere else
    (and for any explicit value) the sketch keeps what it was given."""

    FLAGSHIP = dict(mode="sketch", error_type="virtual",
                    virtual_momentum=0.9, k=100, num_rows=5,
                    num_cols=524288, grad_size=6_600_000)

    def _resolve(self, **kw):
        from commefficient_tpu.core.rounds import resolve_rot_lanes
        base = dict(self.FLAGSHIP)
        base.update(kw)
        return resolve_rot_lanes(make_cfg(**base))

    def test_config_default_is_auto(self):
        assert make_cfg(**self.FLAGSHIP).sketch_rot_lanes == -1

    def test_auto_is_off_on_cpu(self, monkeypatch):
        # on a CPU backend auto must keep full-granularity rotations
        # (quantization would pay its collision tail for zero speedup
        # — no sublane roll there); pinned via monkeypatch so the
        # test also passes when the suite runs on a TPU host
        monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
        assert self._resolve() == 0
        cs = args2sketch(make_cfg(**self.FLAGSHIP))
        assert cs.rot_lanes == 0

    def test_auto_engages_on_tpu_at_flagship_geometry(self, monkeypatch):
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert self._resolve() == 1024

    def test_auto_stays_off_for_small_d(self, monkeypatch):
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert self._resolve(grad_size=100_000) == 0

    def test_auto_stays_off_for_coarse_c(self, monkeypatch):
        # c // 1024 < 8: the rotation space would collapse
        # (CountSketch asserts the same bound for explicit values)
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert self._resolve(num_cols=4096) == 0

    def test_explicit_values_pass_through(self, monkeypatch):
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert self._resolve(sketch_rot_lanes=0) == 0
        assert self._resolve(sketch_rot_lanes=1024) == 1024
        # explicit quantization off-TPU passes through too (the
        # CountSketch-level warning covers the footgun)
        monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
        assert self._resolve(sketch_rot_lanes=1024) == 1024
