"""Host-resident client-state store (commefficient_tpu/clientstore/).

The contract under test: ``--clientstore host`` is a pure *placement*
change — same per-client math, same RNG streams, same aggregation
order — so at populations where both placements fit, every round's
weights, metrics and per-client state rows must be bit-identical to
the dense in-HBM path; checkpoints taken through the store must resume
bit-exactly (and migrate across placements); the arena must evict to
the mmap spill tier under a tiny budget without losing a row; and the
prefetch thread must shut down cleanly with jobs still staged.
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.clientstore import (HostClientStore,
                                           StorePrefetcher,
                                           resolve_clientstore,
                                           shard_range, state_fields)
from commefficient_tpu.config import Config

D = 6    # flat parameter dimension of the toy linear model
NC = 24  # simulated population
W = 8    # participants per round (== the 8 virtual devices)
B = 2    # examples per client


def _loss(params, batch, args):
    pred = batch["x"] @ params["w"]
    n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
    loss = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
    return loss, (loss * 0.0 + 1.0,)


def _make_rounds(n_rounds, seed=11, dead_round=2, num_clients=NC):
    """Deterministic (ids, data) per round, with client repeats across
    rounds (state reuse) and one fully-masked slot in ``dead_round``
    (a dropped-out / loader-padding client whose state must stay
    untouched in BOTH placements)."""
    rng = np.random.RandomState(seed)
    rounds = []
    for r in range(n_rounds):
        ids = rng.choice(num_clients, W, replace=False).astype(np.int32)
        x = rng.randn(W, B, D).astype(np.float32)
        y = rng.randn(W, B).astype(np.float32)
        mask = np.ones((W, B), np.float32)
        if r == dead_round:
            mask[-1] = 0.0
        rounds.append((ids, {"x": x, "y": y, "mask": mask}))
    return rounds


def _cfg(clientstore, **kw):
    base = dict(mode="local_topk", error_type="local",
                local_momentum=0.9, virtual_momentum=0.0,
                weight_decay=0.0, k=3, num_workers=W,
                local_batch_size=B, num_clients=NC, seed=5,
                clientstore=clientstore)
    base.update(kw)
    return Config(**base)


def _build(cfg, lr=0.25):
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)
    params = {"w": jnp.zeros((D,), jnp.float32)}
    model = FedModel(None, params, _loss, cfg, padded_batch_size=B)
    opt = FedOptimizer([{"lr": lr}], cfg, model=model)
    return model, opt


def _drive(model, opt, rounds, feed_ids=None):
    """Run ``rounds`` through model + opt; returns (weights trajectory,
    per-round metric arrays). ``feed_ids``: global round->ids list for
    the prefetch lookahead (indexed by model.round_index, so it works
    across a resume)."""
    if feed_ids is not None and model.client_store is not None:
        def peek():
            nxt = model.round_index + 1
            return feed_ids[nxt] if nxt < len(feed_ids) else None
        model.attach_participant_feed(peek)
    traj, metrics = [], []
    for ids, data in rounds:
        batch = {"client_ids": ids,
                 **{k: jnp.asarray(v) for k, v in data.items()}}
        out = model(batch)
        metrics.append([np.asarray(m) for m in out])
        opt.step()
        traj.append(np.asarray(model.ps_weights, np.float64))
    return traj, metrics


def _device_state_rows(model):
    cs = model.client_states
    out = {}
    for name, val in (("velocities", cs.velocities),
                      ("errors", cs.errors), ("weights", cs.weights)):
        if val is not None:
            out[name] = np.asarray(val)[:model.num_clients]
    return out


def _store_state_rows(model):
    rows, _ = model.client_store.gather(
        np.arange(model.num_clients, dtype=np.int64))
    return {k: np.asarray(v) for k, v in rows.items()}


def _assert_rows_equal(a, b):
    assert set(a) == set(b), (set(a), set(b))
    for name in a:
        np.testing.assert_array_equal(a[name], b[name], err_msg=name)


# ----------------------------------------------------------------------
# bit-equality: host placement vs the dense device placement


@pytest.mark.parametrize("mode_kw", [
    # stateful: per-client momentum + error rows through the store
    dict(),
    # stateless fedavg: empty store, but the full gather/round/
    # write-back loop (and accounting) must still match
    dict(mode="fedavg", error_type="none", local_momentum=0.0,
         local_batch_size=-1),
], ids=["local_topk", "fedavg"])
def test_host_bit_identical_to_device(mode_kw):
    rounds = _make_rounds(4)
    feed = [ids for ids, _ in rounds]

    md, od = _build(_cfg("device", **mode_kw))
    traj_d, met_d = _drive(md, od, rounds)

    mh, oh = _build(_cfg("host", clientstore_bytes=1 << 20, **mode_kw))
    assert mh.clientstore == "host" and mh.client_store is not None
    traj_h, met_h = _drive(mh, oh, rounds, feed_ids=feed)

    for r, (a, b) in enumerate(zip(traj_d, traj_h)):
        np.testing.assert_array_equal(a, b, err_msg=f"round {r}")
    for r, (ma, mb) in enumerate(zip(met_d, met_h)):
        assert len(ma) == len(mb)
        for x, y in zip(ma, mb):
            np.testing.assert_array_equal(x, y, err_msg=f"round {r}")

    # per-client state rows agree for the WHOLE population (incl. the
    # dead slot's untouched row and never-sampled clients)
    _assert_rows_equal(_device_state_rows(md), _store_state_rows(mh))
    if mh._prefetcher is not None:
        # the lookahead actually predicted rounds 1..3
        assert mh._prefetcher.hits >= len(rounds) - 1
    mh.finalize()
    assert mh.client_store is None and mh._prefetcher is None


def test_host_requires_unpipelined_rounds():
    with pytest.raises(ValueError, match="pipeline_depth"):
        _build(_cfg("host", pipeline_depth=2))


# ----------------------------------------------------------------------
# checkpoint/resume through the store


def test_checkpoint_resume_bit_exact(tmp_path):
    from commefficient_tpu.runtime.checkpoint import (load_checkpoint,
                                                      save_checkpoint)
    rounds = _make_rounds(6, seed=13)
    feed = [ids for ids, _ in rounds]
    cfg = _cfg("host", clientstore_bytes=1 << 20)

    # uninterrupted reference
    m0, o0 = _build(cfg)
    traj0, _ = _drive(m0, o0, rounds, feed_ids=feed)
    rows0 = _store_state_rows(m0)
    m0.finalize()

    # interrupted at round 3, "killed", resumed in a fresh process
    m1, o1 = _build(cfg)
    _drive(m1, o1, rounds[:3], feed_ids=feed)
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, m1, o1, epoch=1)
    m1.finalize()

    with np.load(path) as z:
        # sparse store snapshot, not dense cs_* arrays
        assert "store:ids" in z.files
        assert "store:velocities" in z.files
        assert not any(k.startswith("cs_") for k in z.files)

    m2, o2 = _build(cfg)
    load_checkpoint(path, m2, o2)
    assert m2.round_index == 3
    traj2, _ = _drive(m2, o2, rounds[3:], feed_ids=feed)
    np.testing.assert_array_equal(traj0[-1], traj2[-1])
    _assert_rows_equal(rows0, _store_state_rows(m2))
    m2.finalize()


def test_checkpoint_migrates_between_placements(tmp_path):
    """A checkpoint written through the store loads into a device-
    placement run (densified over the init rows) and vice versa, and
    continued training is bit-identical either way."""
    from commefficient_tpu.runtime.checkpoint import (load_checkpoint,
                                                      save_checkpoint)
    rounds = _make_rounds(4, seed=17)

    # host -> {host, device}
    mh, oh = _build(_cfg("host"))
    _drive(mh, oh, rounds[:2])
    p1 = str(tmp_path / "host.npz")
    save_checkpoint(p1, mh, oh, epoch=1)
    rows_h = _store_state_rows(mh)
    mh.finalize()

    md, od = _build(_cfg("device"))
    load_checkpoint(p1, md, od)
    _assert_rows_equal(rows_h, _device_state_rows(md))
    mh2, oh2 = _build(_cfg("host"))
    load_checkpoint(p1, mh2, oh2)
    td, _ = _drive(md, od, rounds[2:])
    th, _ = _drive(mh2, oh2, rounds[2:])
    np.testing.assert_array_equal(td[-1], th[-1])
    mh2.finalize()

    # device -> host
    md3, od3 = _build(_cfg("device"))
    _drive(md3, od3, rounds[:2])
    p2 = str(tmp_path / "dev.npz")
    save_checkpoint(p2, md3, od3, epoch=1)
    mh3, oh3 = _build(_cfg("host"))
    load_checkpoint(p2, mh3, oh3)
    _assert_rows_equal(_device_state_rows(md3), _store_state_rows(mh3))
    td3, _ = _drive(md3, od3, rounds[2:])
    th3, _ = _drive(mh3, oh3, rounds[2:])
    np.testing.assert_array_equal(td3[-1], th3[-1])
    mh3.finalize()


# ----------------------------------------------------------------------
# the store itself: budget, eviction, spill tier


def test_eviction_to_spill_tier(tmp_path):
    fields = {"v": ((4,), None)}
    row_bytes = 4 * 4
    spill_dir = str(tmp_path / "spill")
    st = HostClientStore(20, fields, budget_bytes=3 * row_bytes,
                         spill_dir=spill_dir)
    assert st.arena_rows == 3
    for cid in range(10):
        st.write([cid], {"v": np.full((1, 4), cid + 1.0, np.float32)})
    assert st.stats["resident_rows"] == 3
    assert st.stats["spill_rows"] == 7
    assert st.stats["evictions"] == 7
    assert st.stats["resident_rows_max"] == 3

    # every row reads back exactly, whichever tier holds it; unwritten
    # clients read the (zero) default
    rows, _ = st.gather(np.arange(20))
    for cid in range(10):
        np.testing.assert_array_equal(rows["v"][cid],
                                      np.full(4, cid + 1.0))
    np.testing.assert_array_equal(rows["v"][10:], 0.0)
    np.testing.assert_array_equal(st.written_ids(), np.arange(10))

    # rewriting a spilled row promotes it back to the arena
    st.write([0], {"v": np.full((1, 4), 99.0, np.float32)})
    rows, _ = st.gather([0])
    np.testing.assert_array_equal(rows["v"][0], np.full(4, 99.0))

    paths = [os.path.join(spill_dir, f) for f in os.listdir(spill_dir)]
    assert paths
    st.close()
    assert all(not os.path.exists(p) for p in paths)
    with pytest.raises(RuntimeError):
        st.gather([0])


def test_zero_budget_spills_everything():
    st = HostClientStore(5, {"v": ((2,), None)}, budget_bytes=0)
    st.write([3], {"v": np.array([[7.0, 8.0]], np.float32)})
    rows, _ = st.gather([3, 4])
    np.testing.assert_array_equal(rows["v"][0], [7.0, 8.0])
    np.testing.assert_array_equal(rows["v"][1], 0.0)
    assert st.stats["resident_rows"] == 0
    assert st.stats["spill_rows"] == 1
    st.close()


def test_init_row_and_ownership():
    init = np.arange(3, dtype=np.float32)
    st = HostClientStore(10, {"w": ((3,), init)}, budget_bytes=1 << 12,
                         owned=(2, 6))
    # unwritten owned clients read the init row; non-owned read zeros
    # (the multi-host allgather-sum counts each row exactly once)
    rows, _ = st.gather([2, 0])
    np.testing.assert_array_equal(rows["w"][0], init)
    np.testing.assert_array_equal(rows["w"][1], 0.0)
    # writes outside the owned shard are dropped
    st.write([0, 3], {"w": np.full((2, 3), 5.0, np.float32)})
    np.testing.assert_array_equal(st.written_ids(), [3])
    rows, _ = st.gather([0, 3])
    np.testing.assert_array_equal(rows["w"][0], 0.0)
    np.testing.assert_array_equal(rows["w"][1], np.full(3, 5.0))
    st.close()


# ----------------------------------------------------------------------
# prefetch thread


def _wait(pred, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.01)
    return False


def test_prefetcher_hit_miss_staleness_and_shutdown():
    st = HostClientStore(10, {"v": ((4,), None)}, budget_bytes=1 << 16)
    before = set(threading.enumerate())
    pf = StorePrefetcher(st)

    ids = np.array([1, 2, 3], np.int64)
    st.write(ids, {"v": np.eye(3, 4, dtype=np.float32)})

    # hit
    pf.submit(ids)
    rows = pf.take(ids)
    assert rows is not None and pf.hits == 1
    np.testing.assert_array_equal(rows["v"], np.eye(3, 4))

    # staleness: a row written AFTER the async gather snapshot must be
    # patched in by take()
    pf.submit(ids)
    assert _wait(lambda: pf._done.qsize() > 0)
    st.write([2], {"v": np.full((1, 4), 42.0, np.float32)})
    rows = pf.take(ids)
    np.testing.assert_array_equal(rows["v"][1], np.full(4, 42.0))

    # misprediction: staged ids don't match the round's -> None, and
    # the caller falls back to a synchronous gather
    pf.submit(np.array([7, 8], np.int64))
    assert pf.take(np.array([0, 1], np.int64)) is None
    assert pf.misses == 1

    # shutdown with a job still staged; idempotent; no leaked threads
    pf.submit(ids)
    pf.close()
    pf.close()
    assert not pf._thread.is_alive()
    assert set(threading.enumerate()) - before == set()

    # a worker exception surfaces in take(), not in the worker
    st2 = HostClientStore(4, {"v": ((2,), None)}, budget_bytes=1 << 12)
    pf2 = StorePrefetcher(st2)
    st2.close()
    pf2.submit(np.array([0], np.int64))
    with pytest.raises(RuntimeError):
        pf2.take(np.array([0], np.int64))
    pf2.close()
    st.close()


def test_prefetcher_out_of_order_consumption():
    """Buffered-async overlap (asyncfed) consumes staged gathers out
    of issue order: a ``take`` for the SECOND submit must drain the
    first staged job as a miss — no deadlock, no torn rows — and a
    row written after the async snapshot must still come back patched
    through the version check, never a silently-stale mix."""
    st = HostClientStore(12, {"v": ((4,), None)},
                         budget_bytes=1 << 16)
    pf = StorePrefetcher(st)
    ids1 = np.array([1, 2, 3], np.int64)
    ids2 = np.array([4, 5, 6], np.int64)
    st.write(ids1, {"v": np.ones((3, 4), np.float32)})
    st.write(ids2, {"v": np.full((3, 4), 2.0, np.float32)})
    pf.submit(ids1)
    pf.submit(ids2)
    assert _wait(lambda: pf._done.qsize() == 2)
    # a write landing between the snapshot and the take: version
    # patching must hand back the CURRENT row, not the staged one
    st.write([5], {"v": np.full((1, 4), 42.0, np.float32)})
    rows = pf.take(ids2)
    assert rows is not None
    assert pf.misses == 1 and pf.hits == 1
    np.testing.assert_array_equal(rows["v"][0], np.full(4, 2.0))
    np.testing.assert_array_equal(rows["v"][1], np.full(4, 42.0))
    # the backlog is drained: a further take must return fast with
    # None (synchronous-gather fallback), not wedge on the queue
    t0 = time.time()
    assert pf.take(ids1, timeout=0.5) is None
    assert time.time() - t0 < 5.0
    pf.close()
    st.close()


def test_prefetcher_worker_death_surfaces_out_of_order():
    """The chaos-harness kill hook marks the loop dead exactly like
    an escaped exception: the NEXT take()/submit — even one for a
    job staged before the death — raises the worker-died RuntimeError
    instead of stalling out its timeout."""
    st = HostClientStore(4, {"v": ((2,), None)}, budget_bytes=1 << 12)
    pf = StorePrefetcher(st)
    pf.submit(np.array([0], np.int64))
    assert pf.take(np.array([0], np.int64)) is not None
    pf._fail_for_test(ValueError("chaos"))
    with pytest.raises(RuntimeError, match="prefetch worker died"):
        pf.take(np.array([0], np.int64))
    with pytest.raises(RuntimeError, match="prefetch worker died"):
        pf.submit(np.array([1], np.int64))
    pf.close()
    st.close()


def test_store_issue_round_stamps():
    """asyncfed version stamps: bookkeeping-only per-client issue
    rounds, -1 for never-issued, last issue wins on re-issue."""
    st = HostClientStore(8, {"v": ((2,), None)}, budget_bytes=1 << 12)
    assert st.stamped_round(3) == -1
    st.stamp_rounds(np.array([1, 3], np.int64), 5)
    st.stamp_rounds(np.array([[3]], np.int64), 7)  # any shape of ids
    assert st.stamped_round(1) == 5
    assert st.stamped_round(3) == 7
    assert st.stamped_round(0) == -1
    st.close()


# ----------------------------------------------------------------------
# config plumbing


def test_resolve_clientstore_auto():
    cfg = _cfg("auto", clientstore_bytes=1 << 10).replace(grad_size=100)
    # local_topk + local error + momentum: 2 rows of grad_size f32
    # per client = 800 B; 24 clients = 19200 B > 1 KiB budget -> host
    assert resolve_clientstore(cfg, cfg.num_clients) == "host"
    assert resolve_clientstore(
        cfg.replace(clientstore_bytes=1 << 20), cfg.num_clients) \
        == "device"
    # stateless combo: nothing to store, stays on device at any budget
    fa = _cfg("auto", mode="fedavg", error_type="none",
              local_momentum=0.0, local_batch_size=-1,
              clientstore_bytes=0).replace(grad_size=100)
    assert resolve_clientstore(fa, fa.num_clients) == "device"
    # explicit flags resolve to themselves
    assert resolve_clientstore(_cfg("device"), NC) == "device"
    assert resolve_clientstore(_cfg("host"), NC) == "host"


def test_state_fields_follow_config():
    cfg = _cfg("host").replace(grad_size=7)
    f = state_fields(cfg)
    assert list(f) == ["velocities", "errors"]
    assert f["velocities"][0] == (7,)
    init = np.arange(7, dtype=np.float32)
    f2 = state_fields(cfg.replace(do_topk_down=True), init_weights=init)
    assert list(f2) == ["velocities", "errors", "weights"]
    np.testing.assert_array_equal(f2["weights"][1], init)
    fa = _cfg("host", mode="fedavg", error_type="none",
              local_momentum=0.0,
              local_batch_size=-1).replace(grad_size=7)
    assert state_fields(fa) == {}


def test_shard_range_partitions_population():
    assert shard_range(10, 0, 2) == (0, 5)
    assert shard_range(10, 1, 2) == (5, 10)
    assert shard_range(10, 2, 3) == (8, 10)
    assert shard_range(3, 3, 4) == (3, 3)  # empty trailing shard
    for nc, pc in ((10, 2), (10, 3), (3, 4), (1_000_000, 7)):
        spans = [shard_range(nc, i, pc) for i in range(pc)]
        assert spans[0][0] == 0 and spans[-1][1] == nc
        for (a, b), (c, d) in zip(spans, spans[1:]):
            assert b == c and a <= b and c <= d


# ----------------------------------------------------------------------
# scale: populations far beyond any dense-HBM placement


@pytest.mark.slow
@pytest.mark.parametrize("mode_kw", [
    dict(),
    dict(mode="fedavg", error_type="none", local_momentum=0.0,
         local_batch_size=-1),
], ids=["local_topk", "fedavg"])
def test_million_client_population(mode_kw):
    """1M simulated clients under a ~1000-row store budget: training
    proceeds, resident rows respect the budget, and state survives
    eviction round-trips (the dense device placement would need the
    full (1M, d) arrays resident)."""
    nc = 1_000_000
    budget = 1000 * 2 * D * 4  # ~1000 (velocities+errors) rows
    rounds = _make_rounds(3, seed=23, dead_round=-1, num_clients=nc)
    cfg = _cfg("host", num_clients=nc, clientstore_bytes=budget,
               **mode_kw)
    m, o = _build(cfg)
    traj, _ = _drive(m, o, rounds, feed_ids=[i for i, _ in rounds])
    assert np.all(np.isfinite(traj[-1]))
    st = m.client_store
    participants = {int(c) for ids, _ in rounds for c in ids}
    if st.fields:
        assert st.stats["resident_rows_max"] <= st.arena_rows
        assert set(st.written_ids()) <= participants
    m.finalize()
