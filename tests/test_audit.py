"""Static-analysis subsystem (analysis/): HLO text parsers, the AST
lint rules (each must trip on a seeded violation and respect waivers),
program-audit regressions (dropping donate_argnums must FAIL the
donation check), the collective-inventory <-> ledger byte cross-check
for all five modes, and the tier-1 baseline gate against the
committed audit_baseline.json."""

import json
import pathlib
import textwrap

import pytest

from commefficient_tpu.analysis import baseline as base_mod
from commefficient_tpu.analysis import hlo
from commefficient_tpu.analysis.lint import (RULES_BY_NAME, lint_report,
                                             run_lint,
                                             unwaived)
from commefficient_tpu.analysis.program import (SERVER_CFG_KW,
                                                ProgramSpec,
                                                audit_client_program,
                                                audit_server_program,
                                                make_cfg,
                                                run_program_audit)

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]


@pytest.fixture(scope="module")
def audit_report():
    """One full program audit per test module — every entry below
    reads from it instead of re-lowering the matrix."""
    return run_program_audit()


# --- HLO text parsers --------------------------------------------------


COMPILED_SNIPPET = """\
HloModule jit_f, input_output_alias={ {1}: (1, {}, may-alias), {3}: (2, {}, may-alias) }, entry_computation_layout=...
  %all-reduce.7 = f32[64]{0} all-reduce(f32[64]{0} %add.3), replica_groups={{0,1}}
  %ar2 = (f32[2,16]{1,0}, f32[]) all-reduce(%a, %b), channel_id=1
  %ag = bf16[8,64]{1,0} all-gather(bf16[1,64]{1,0} %x), dimensions={0}
  %ars = f32[8]{0} all-reduce-start(f32[8]{0} %y)
  %ard = f32[8]{0} all-reduce-done(f32[8]{0} %ars)
"""


def test_collective_inventory_parses_shapes_and_async():
    ops = hlo.collective_inventory(COMPILED_SNIPPET)
    kinds = sorted(o.kind for o in ops)
    # -done retires the -start; counting both would double the bytes
    assert kinds == ["all-gather", "all-reduce", "all-reduce",
                     "all-reduce"]
    summary = hlo.collective_summary(ops)
    assert summary["counts"] == {"all-reduce": 3, "all-gather": 1}
    # 64*4 + (2*16*4 + 4) + 8*4 for the reduces; 8*64*2 for the gather
    assert summary["bytes"]["all-reduce"] == 256 + 132 + 32
    assert summary["bytes"]["all-gather"] == 1024
    # variadic components match individually, scalars excluded
    assert hlo.matching_reduce_bytes(ops, "f32", (2, 16)) == 128
    assert hlo.matching_reduce_bytes(ops, "f32", (64,)) == 256


def test_matching_collective_bytes_keys_on_kind():
    text = ("  %rs = f32[2,8]{1,0} reduce-scatter(f32[2,16]{1,0} %t), "
            "dimensions={1}\n"
            "  %ar = f32[2,8]{1,0} all-reduce(f32[2,8]{1,0} %u)\n")
    ops = hlo.collective_inventory(text)
    assert hlo.matching_collective_bytes(
        ops, "reduce-scatter", "f32", (2, 8)) == 64
    assert hlo.matching_collective_bytes(
        ops, "all-reduce", "f32", (2, 8)) == 64
    assert hlo.matching_collective_bytes(
        ops, "reduce-scatter", "f32", (2, 16)) == 0


def test_compiled_alias_count_handles_nested_braces():
    assert hlo.compiled_alias_count(COMPILED_SNIPPET) == 2
    assert hlo.compiled_alias_count("HloModule jit_g, entry=...") == 0


def test_transfer_scan_flags_outfeed_not_substrings():
    text = ("  %o = token[] outfeed(f32[2]{0} %v, token[] %t)\n"
            "  %s = f32[2]{0} sort(%v), dimensions={0} "
            "is_stable=true descending\n")
    hits = hlo.host_transfer_lines(text)
    assert len(hits) == 1 and "outfeed" in hits[0]


def test_fingerprint_ignores_locations():
    a = 'module @jit_f {\n  %0 = stablehlo.add %a, %b loc("x.py":1:2)\n}'
    b = 'module @jit_f {\n  %0 = stablehlo.add %a, %b loc("y.py":9:9)\n}'
    c = 'module @jit_f {\n  %0 = stablehlo.mul %a, %b\n}'
    assert hlo.fingerprint(a) == hlo.fingerprint(b)
    assert hlo.fingerprint(a) != hlo.fingerprint(c)


# --- lint rules: each fires on a seeded violation ----------------------


SEEDED = {
    # path (under a fake package root) -> (source, rule that must fire)
    "runtime/clocky.py": ("""
        import time
        def f():
            t0 = time.perf_counter()
            return time.time() - t0
        """, "raw-clock"),
    "runtime/probey.py": ("""
        def flush(res):
            # probe scalars
            vals = [_host(v) for v in res.probes]
            return vals
        """, "probe-transfer-span"),
    "runtime/syncy.py": ("""
        import jax
        def step(x):
            jax.block_until_ready(x)
            return x.item()
        """, "host-sync"),
    "core/tracer_leak.py": ("""
        import numpy as np
        def build(cfg):
            def traced(x):
                return np.asarray(x) * 2
            return traced
        """, "np-on-tracer"),
    "ops/rngy.py": ("""
        import random
        import numpy as np
        def noise():
            return random.random() + np.random.randn()
        """, "python-rng"),
    "core/defaulty.py": ("""
        def accumulate(x, out=[]):
            out.append(x)
            return out
        """, "mutable-default-arg"),
    "telemetry/devicey.py": ("""
        import jax
        def lanes():
            return [d.id for d in jax.devices()] + jax.local_devices()
        """, "raw-devices"),
    "core/speccy.py": ("""
        from jax.sharding import PartitionSpec as P
        def layout(mesh):
            return P("clients")
        """, "inline-partition-spec"),
    "runtime/checkpoint.py": ("""
        import jax
        def restore(z, some_spec):
            return jax.device_put(z["x"], some_spec)
        """, "checkpoint-mesh-route"),
}


@pytest.fixture()
def seeded_root(tmp_path):
    for rel, (src, _rule) in SEEDED.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


@pytest.mark.parametrize("rel", sorted(SEEDED))
def test_each_rule_fires(seeded_root, rel):
    rule = SEEDED[rel][1]
    hits = unwaived(run_lint(root=seeded_root,
                             rules=[RULES_BY_NAME[rule]]))
    assert any(v.path == rel for v in hits), \
        f"rule {rule} did not fire on {rel}: {hits}"


def test_waiver_suppresses_and_is_recorded(tmp_path):
    p = tmp_path / "runtime" / "waived.py"
    p.parent.mkdir(parents=True)
    p.write_text("import time\n"
                 "def f():\n"
                 "    # audit: allow(raw-clock) — test fixture\n"
                 "    return time.time()\n")
    vs = run_lint(root=tmp_path, rules=[RULES_BY_NAME["raw-clock"]])
    assert len(vs) == 1 and vs[0].waived
    assert unwaived(vs) == []
    # a waiver for a DIFFERENT rule does not suppress
    p.write_text("import time\n"
                 "def f():\n"
                 "    # audit: allow(host-sync)\n"
                 "    return time.time()\n")
    vs = run_lint(root=tmp_path, rules=[RULES_BY_NAME["raw-clock"]])
    assert len(unwaived(vs)) == 1


def test_span_scoped_host_sync_passes(tmp_path):
    p = tmp_path / "runtime" / "ok.py"
    p.parent.mkdir(parents=True)
    p.write_text("def f(tel, x):\n"
                 '    with tel.span("metrics_host"):\n'
                 "        return _host(x)\n")
    assert run_lint(root=tmp_path,
                    rules=[RULES_BY_NAME["host-sync"]]) == []


def test_module_level_numpy_in_ops_is_fine(tmp_path):
    # hash-constant setup (ops/sketch.py idiom) must NOT be flagged:
    # only nested (traced) closures are in scope for np-on-tracer
    p = tmp_path / "ops" / "setup.py"
    p.parent.mkdir(parents=True)
    p.write_text("import numpy as np\n"
                 "TABLE = np.asarray([1, 2, 3])\n"
                 "def make(x):\n"
                 "    return np.asarray(x, np.uint32)\n")
    assert run_lint(root=tmp_path,
                    rules=[RULES_BY_NAME["np-on-tracer"]]) == []


def test_repo_lint_is_clean():
    assert unwaived(run_lint()) == [], \
        "unwaived lint violations in the package"


def test_partition_spec_attribute_form_fires(tmp_path):
    # the attribute spelling (jax.sharding.NamedSharding(...)) must be
    # caught too, not just the from-import
    p = tmp_path / "core" / "attr_spec.py"
    p.parent.mkdir(parents=True)
    p.write_text("import jax.sharding\n"
                 "def place(mesh, x):\n"
                 "    s = jax.sharding.NamedSharding(mesh, None)\n"
                 "    return s\n")
    hits = unwaived(run_lint(
        root=tmp_path, rules=[RULES_BY_NAME["inline-partition-spec"]]))
    assert len(hits) == 1 and hits[0].line == 3


def test_checkpoint_mesh_route_allows_constructor_specs(tmp_path):
    # placements built by parallel.mesh constructors — directly, via a
    # named intermediate, or via the conditional spec-or-None idiom —
    # are the sanctioned route; an inline sharding= is not
    p = tmp_path / "runtime" / "checkpoint.py"
    p.parent.mkdir(parents=True)
    p.write_text(
        "import jax\n"
        "from commefficient_tpu.parallel.mesh import (client_sharding,"
        " server_state_sharding, model_axis_size)\n"
        "def load(z, model):\n"
        "    csh = client_sharding(model.mesh)\n"
        "    ssh = server_state_sharding(model.mesh, (3, 8)) \\\n"
        "        if model_axis_size(model.mesh) > 1 else None\n"
        "    a = jax.device_put(z['rows'], csh)\n"
        "    return a, restore(z['ss'], sharding=ssh)\n")
    rule = RULES_BY_NAME["checkpoint-mesh-route"]
    assert run_lint(root=tmp_path, rules=[rule]) == []
    # the same file with a hand-built sharding= must fire
    p.write_text(
        "def load(z, model, mesh):\n"
        "    s = make_my_own_layout(mesh)\n"
        "    return restore(z['ss'], sharding=s)\n")
    hits = unwaived(run_lint(root=tmp_path, rules=[rule]))
    assert len(hits) == 1 and "sharding=" in hits[0].message


def test_partition_spec_allowed_in_parallel(tmp_path):
    p = tmp_path / "parallel" / "mesh.py"
    p.parent.mkdir(parents=True)
    p.write_text("from jax.sharding import NamedSharding, "
                 "PartitionSpec as P\n")
    assert run_lint(root=tmp_path,
                    rules=[RULES_BY_NAME["inline-partition-spec"]]) \
        == []


# --- program audit: regression fixtures --------------------------------


def test_dropping_donation_fails_the_check():
    """The audit's reason to exist: remove donate_argnums from a
    state-carrying round and the donation check must go red."""
    spec = ProgramSpec("uncompressed/per_client", "uncompressed",
                       "per_client",
                       dict(virtual_momentum=0.9, local_momentum=0.9))
    entry = audit_client_program(spec, donate=False)
    assert any("donation" in f for f in entry["failures"]), entry


def test_dropping_server_donation_fails_the_check():
    entry = audit_server_program("sketch", donate=False)
    assert any("donation" in f for f in entry["failures"]), entry


def test_program_audit_is_clean(audit_report):
    assert audit_report["failures"] == []


def test_fingerprints_are_retrace_stable(audit_report):
    unstable = [n for n, e in audit_report["programs"].items()
                if not e["retrace_stable"]]
    assert unstable == []


def test_round_programs_are_transfer_free(audit_report):
    leaky = {n: e["transfers"]
             for n, e in audit_report["programs"].items()
             if e.get("transfers")}
    assert leaky == {}


# --- collective inventory <-> ledger cross-check -----------------------


# same shapes as tests/test_accounting.py MODES: the static wire bytes
# must agree with the brute-force ledger accounting's
# 4 * upload_floats_per_client per participating client
@pytest.mark.parametrize("name", [
    "sketch/fused", "true_topk/fused", "uncompressed/fused",
    "sketch/per_client", "true_topk/per_client",
    "uncompressed/per_client", "fedavg/per_client",
])
def test_static_uplink_bytes_match_ledger_exactly(audit_report, name):
    up = audit_report["programs"][name]["uplink"]
    assert up["relation"] == "exact"
    assert up["aggregate_allreduce_bytes"] == \
        up["ledger_bytes_per_client"], up


def test_ledger_bytes_agree_with_accounting_formula(audit_report):
    """Anchor the cross-check to the same source of truth
    tests/test_accounting.py brute-forces: uplink bytes per client
    are ``accounting.bytes_of`` at the program's wire dtype (table at
    wire width + per-row f32 scales where the dtype carries them)."""
    from commefficient_tpu import accounting

    for name, entry in audit_report["programs"].items():
        if "uplink" not in entry:
            continue
        cfg = make_cfg(entry["mode"], 8,
                       **SERVER_CFG_KW[entry["mode"]])
        if entry["mode"] == "sketch":
            wire = entry["uplink"]["wire_dtype"]
            assert entry["uplink"]["ledger_bytes_per_client"] == \
                accounting.sketch_wire_bytes(cfg.num_rows,
                                             cfg.num_cols, wire)
        elif entry["mode"] == "local_topk":
            assert entry["uplink"]["ledger_bytes_per_client"] == \
                4 * cfg.k
        else:
            assert entry["uplink"]["ledger_bytes_per_client"] == \
                4 * cfg.grad_size


def test_2d_sketch_uplink_shards_by_model_axis(audit_report):
    """The pod-scale cross-check: on the clients x model mesh both the
    reduce-scatter (partial tables -> column shards) and the
    client-axis all-reduce carry exactly ledger/M bytes — the 2D round
    never moves the full table over a single link."""
    up = audit_report["programs"]["sketch/fused2d"]["uplink"]
    assert up["relation"] == "sharded"
    m = up["model_shards"]
    assert m > 1
    assert up["reduce_scatter_bytes"] * m == \
        up["ledger_bytes_per_client"]
    assert up["aggregate_allreduce_bytes"] * m == \
        up["ledger_bytes_per_client"]


def test_2d_server_gathers_table_once(audit_report):
    tt = audit_report["programs"]["sketch/server2d"]["table_traffic"]
    assert tt == {"all_gathers": 1, "allreduce_bytes": 0}


def test_mesh_1x1_is_hlo_identical_to_1d(audit_report):
    entry = audit_report["programs"]["sketch/mesh1x1"]
    assert entry["fingerprint"] == entry["mesh1x1_fingerprint"]


def test_local_topk_wire_bytes_bound_ledger(audit_report):
    """local_topk reduces the DENSE masked vector over the ICI: the
    4k logical uplink is a lower bound on the 4d wire bytes, not an
    equality — the documented exception."""
    up = audit_report["programs"]["local_topk/per_client"]["uplink"]
    assert up["relation"] == "bound"
    assert up["aggregate_allreduce_bytes"] >= \
        up["ledger_bytes_per_client"]
    assert up["aggregate_allreduce_bytes"] > 0


def test_chunked_and_server_programs_are_collective_free(audit_report):
    for name, entry in audit_report["programs"].items():
        if entry["path"] in ("chunked", "server"):
            assert entry["collectives"]["counts"] == {}, (name, entry)


# --- tier-1 baseline gate ----------------------------------------------


@pytest.fixture(scope="module")
def lint_summary(package_parse):
    # both lint tiers off the suite's one shared engine run
    # (conftest.package_parse) — the baseline tests only read this
    return lint_report(package_parse["violations"])


def test_report_matches_committed_baseline(audit_report, lint_summary):
    """The CI gate: a fresh audit must diff clean against the
    committed audit_baseline.json. Any new collective, lost donation,
    host transfer, fingerprint drift, or new lint waiver fails here
    until `python scripts/audit.py --write-baseline` re-pins it (and
    the diff is reviewed)."""
    baseline_path = REPO_ROOT / "audit_baseline.json"
    assert baseline_path.exists(), \
        "audit_baseline.json missing — run scripts/audit.py " \
        "--write-baseline"
    baseline = base_mod.load_baseline(baseline_path)
    # both lint tiers — the baseline pins flow-checker waivers too
    report = base_mod.build_report(audit_report, lint_summary)
    problems = base_mod.diff_against_baseline(report, baseline)
    assert problems == [], "\n".join(problems)


def test_baseline_roundtrip_and_diff_detects_drift(audit_report,
                                                   lint_summary):
    report = base_mod.build_report(audit_report, lint_summary)
    pinned = json.loads(json.dumps(base_mod.to_baseline(report)))
    assert base_mod.diff_against_baseline(report, pinned) == []
    # fingerprint drift is a visible failure
    name = next(iter(pinned["programs"]))
    pinned["programs"][name]["fingerprint"] = "0" * 64
    problems = base_mod.diff_against_baseline(report, pinned)
    assert any("fingerprint changed" in p for p in problems)
    # a fresh waiver is a visible failure too
    pinned2 = json.loads(json.dumps(base_mod.to_baseline(report)))
    report2 = json.loads(json.dumps(report))
    report2["lint"]["waived"].append("x.py:1: host-sync: new [waived]")
    problems = base_mod.diff_against_baseline(report2, pinned2)
    assert any("new lint waiver" in p for p in problems)
