"""--client_chunk: chunked client fan-out equals the full vmap.

The chunked scan (core/rounds.py _client_round_chunked) must be a pure
memory transformation — same aggregated transmit, same per-client
metrics, same updated per-client state, for every mode that carries
local state, including W not divisible by the chunk (tail padding).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import ClientStates, build_client_round
from commefficient_tpu.ops.vec import flatten_params


def _setup(mode, error_type, local_momentum, W=6, B=3, D=50,
           chunk=0, **extra):
    kw = dict(local_batch_size=B)
    kw.update(extra)
    cfg = Config(mode=mode, error_type=error_type,
                 local_momentum=local_momentum, virtual_momentum=0.0,
                 weight_decay=0.0, num_workers=W,
                 k=5, num_cols=32, num_rows=3,
                 dataset_name="CIFAR10", seed=0,
                 client_chunk=chunk, **kw)
    rng = np.random.RandomState(1)
    tree = {"w": jnp.asarray(rng.randn(D, 4), jnp.float32)}
    flat, unravel = flatten_params(tree)
    cfg.grad_size = int(flat.size)

    def loss(p, b):
        pred = b["x"] @ unravel(p)["w"]
        per = jnp.sum((pred - b["y"]) ** 2, -1)
        l = jnp.sum(per * b["mask"]) / jnp.maximum(
            jnp.sum(b["mask"]), 1.0)
        return l, (l * 2.0,)

    batch = {
        "x": jnp.asarray(rng.randn(W, B, D), jnp.float32),
        "y": jnp.asarray(rng.randn(W, B, 4), jnp.float32),
        "mask": jnp.ones((W, B), jnp.float32),
    }
    # one client padded out entirely: state-kept semantics must match
    batch["mask"] = batch["mask"].at[2].set(0.0)
    states = ClientStates.init(cfg, 10, flat)
    # make pre-existing state nonzero so "kept" vs "zeroed" differs
    states = ClientStates(
        jnp.ones_like(states.velocities) * 0.1
        if states.velocities is not None else None,
        jnp.ones_like(states.errors) * 0.2
        if states.errors is not None else None,
        states.weights)
    ids = jnp.asarray([0, 3, 5, 7, 1, 9, 2, 8], jnp.int32)[:W]
    return cfg, loss, flat, batch, states, ids


MODES = [
    ("local_topk", "local", 0.9, {}),
    ("uncompressed", "none", 0.9, {}),   # local momentum state path
    ("sketch", "virtual", 0.0, {"max_grad_norm": 1.0}),  # non-fused
    ("fedavg", "none", 0.0, {"local_batch_size": -1}),
]


@pytest.mark.parametrize("mode,etype,lmom,extra", MODES)
@pytest.mark.parametrize("chunk", [2, 4])  # 4 does not divide W=6
def test_chunked_equals_full(mode, etype, lmom, extra, chunk):
    cfg_f, loss, flat, batch, states, ids = _setup(
        mode, etype, lmom, **extra)
    cfg_c, *_ = _setup(mode, etype, lmom, chunk=chunk, **extra)

    key = jax.random.PRNGKey(0)
    full = build_client_round(cfg_f, loss, 3)(
        flat, states, batch, ids, key, 0.5)
    chunked = build_client_round(cfg_c, loss, 3)(
        flat, states, batch, ids, key, 0.5)

    np.testing.assert_allclose(np.asarray(full.aggregated),
                               np.asarray(chunked.aggregated),
                               rtol=1e-6, atol=1e-6)
    for mf, mc in zip(full.metrics, chunked.metrics):
        np.testing.assert_allclose(np.asarray(mf), np.asarray(mc),
                                   rtol=1e-6, atol=1e-7)
    for f, c in zip(full.client_states, chunked.client_states):
        if f is None:
            assert c is None
            continue
        np.testing.assert_allclose(np.asarray(f), np.asarray(c),
                                   rtol=1e-6, atol=1e-7)


def test_client_zero_in_padded_tail_chunk():
    # the pad slots must NOT touch client 0's state: pad with a real
    # id and (a) topk_down's unguarded new_wts writes advance client
    # 0's stale-download row, (b) a real client 0 sharing the padded
    # chunk races its own update against the pad's stale copy. The
    # sentinel-id fix drops pad scatters entirely.
    cfg_f, loss, flat, batch, states, ids = _setup(
        "local_topk", "local", 0.9)
    cfg_c, *_ = _setup("local_topk", "local", 0.9, chunk=4)
    # client 0 goes LAST: chunk 4 over W=6 puts it in the padded chunk
    ids = jnp.asarray([3, 5, 7, 1, 9, 0], jnp.int32)
    key = jax.random.PRNGKey(0)
    full = build_client_round(cfg_f, loss, 3)(
        flat, states, batch, ids, key, 0.5)
    chunked = build_client_round(cfg_c, loss, 3)(
        flat, states, batch, ids, key, 0.5)
    for f, c in zip(full.client_states, chunked.client_states):
        if f is not None:
            np.testing.assert_allclose(np.asarray(f), np.asarray(c),
                                       rtol=1e-6, atol=1e-7)


def test_topk_down_chunked_state_untouched_by_pads():
    cfg_f, loss, flat, batch, states, ids = _setup(
        "local_topk", "local", 0.0, do_topk_down=True)
    cfg_c, *_ = _setup("local_topk", "local", 0.0, chunk=4,
                       do_topk_down=True)
    states = ClientStates.init(cfg_f, 10, flat)
    ids = jnp.asarray([3, 5, 7, 1, 9, 0], jnp.int32)
    key = jax.random.PRNGKey(0)
    full = build_client_round(cfg_f, loss, 3)(
        flat, states, batch, ids, key, 0.5)
    chunked = build_client_round(cfg_c, loss, 3)(
        flat, states, batch, ids, key, 0.5)
    np.testing.assert_allclose(
        np.asarray(full.client_states.weights),
        np.asarray(chunked.client_states.weights),
        rtol=1e-6, atol=1e-7)


def test_chunked_ignored_on_mesh(devices):
    # the guard must SKIP chunking on a multi-device mesh (the client
    # axis is already divided): a chunk=2 round on the mesh must equal
    # the chunk=0 round on the same mesh exactly
    from jax.sharding import Mesh
    from commefficient_tpu.parallel.mesh import CLIENT_AXIS
    cfg0, loss, flat, batch, states, ids = _setup(
        "local_topk", "local", 0.9, W=8)
    cfg2, *_ = _setup("local_topk", "local", 0.9, W=8, chunk=2)
    mesh = Mesh(np.asarray(devices), (CLIENT_AXIS,))
    key = jax.random.PRNGKey(0)
    r0 = build_client_round(cfg0, loss, 3, mesh=mesh)(
        flat, states, batch, ids, key, 0.5)
    r2 = build_client_round(cfg2, loss, 3, mesh=mesh)(
        flat, states, batch, ids, key, 0.5)
    np.testing.assert_array_equal(np.asarray(r0.aggregated),
                                  np.asarray(r2.aggregated))
    for a, b in zip(r0.client_states, r2.client_states):
        if a is not None:
            np.testing.assert_array_equal(np.asarray(a),
                                          np.asarray(b))


def test_loader_zero_id_padding_cannot_touch_client_zero():
    # the loader pads ragged rounds with id 0 + all-zero mask; those
    # slots must leave client 0's state bit-identical — including
    # topk_down's download-state, and including when REAL client 0 is
    # in the same round (the duplicate-index scatter race) — on both
    # the full and chunked paths
    for chunk in (0, 2):
        cfg, loss, flat, batch, states, ids = _setup(
            "local_topk", "local", 0.9, chunk=chunk,
            do_topk_down=True)
        states = ClientStates.init(cfg, 10, flat)
        # slots: real clients [0, 3, 5, 1] + two id-0 pads (dead mask)
        ids = jnp.asarray([0, 3, 5, 1, 0, 0], jnp.int32)
        batch["mask"] = batch["mask"].at[4:].set(0.0)
        res = build_client_round(cfg, loss, 3)(
            flat, states, batch, ids, jax.random.PRNGKey(0), 0.5)
        # rows of clients NOT in the round are untouched
        for row in (2, 4, 6, 7, 8, 9):
            np.testing.assert_array_equal(
                np.asarray(res.client_states.weights[row]),
                np.asarray(states.weights[row]))
        # client 0's weights row reflects its REAL (alive) download —
        # deterministically, despite the dead duplicate id-0 slots
        cfg1, *_ = _setup("local_topk", "local", 0.9,
                          do_topk_down=True)
        states1 = ClientStates.init(cfg1, 10, flat)
        batch1 = {k: v[:4] for k, v in batch.items()}
        res1 = build_client_round(cfg1, loss, 3)(
            flat, states1, batch1, jnp.asarray([0, 3, 5, 1], jnp.int32),
            jax.random.PRNGKey(0), 0.5)
        np.testing.assert_allclose(
            np.asarray(res.client_states.weights[0]),
            np.asarray(res1.client_states.weights[0]),
            rtol=1e-6, atol=1e-7)
