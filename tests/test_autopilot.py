"""Adaptive compression autopilot: the knob lattice, the bounded
re-jit cache (isolation: LRU bound, hit/miss counters, eviction), the
deterministic band controller and its bit-exact replay, the perf-gate
band keying (no cross-band fallback), and the FedModel integration —
autopilot-off object identity, pinned-knob bit parity with the
equivalent static config, variant-switch bit parity with a fresh
jax.jit, and warm-ahead never compiling an unvisited lattice point."""

import dataclasses
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.autopilot import (AutopilotController,
                                         RoundVariantCache,
                                         VariantKey, apply_knobs,
                                         build_controller,
                                         build_ladder, key_of,
                                         key_str, parse_band,
                                         parse_key, replay_record,
                                         variant_bytes)
from commefficient_tpu.config import Config
from commefficient_tpu.telemetry import gate


def make_cfg(**kw):
    base = dict(mode="sketch", error_type="virtual",
                local_momentum=0.0, virtual_momentum=0.9,
                num_workers=2, k=16, num_rows=3, num_cols=128,
                num_blocks=1, local_batch_size=2, microbatch_size=-1,
                seed=21)
    base.update(kw)
    return Config(**base)


# --- lattice ------------------------------------------------------------


def test_key_roundtrip_and_apply_knobs_identity():
    cfg = make_cfg()
    key = key_of(cfg)
    assert parse_key(key_str(key)) == key
    # the sanctioned no-op: matching key returns the SAME object, so
    # the autopilot-off build path uses the identical Config instance
    assert apply_knobs(cfg, key) is cfg
    moved = apply_knobs(cfg, key._replace(dtype="int8"))
    assert moved is not cfg
    assert moved.sketch_dtype == "int8"
    assert moved.k == cfg.k and moved.num_cols == cfg.num_cols
    with pytest.raises(ValueError):
        parse_key("int8-k16")


def test_ladder_cost_monotone():
    ladder = build_ladder(make_cfg(sketch_dtype="f32"))
    assert [k.dtype for k in ladder] == ["f32", "bf16", "int8"]
    costs = [variant_bytes(k) for k in ladder]
    assert costs == sorted(costs, reverse=True)
    assert all(a > b for a, b in zip(costs, costs[1:]))
    # fp8 base: no cheaper dtype exists -> one-point ladder
    assert build_ladder(make_cfg(sketch_dtype="fp8")) == \
        [key_of(make_cfg(sketch_dtype="fp8"))]


def test_ladder_geometry_steps():
    cfg = make_cfg(num_cols=256, autopilot_geometry=True)
    ladder = build_ladder(cfg)
    tail = [k for k in ladder if k.dtype == "int8"]
    assert [k.cols for k in tail] == [256, 128, 64]
    costs = [variant_bytes(k) for k in ladder]
    assert all(a > b for a, b in zip(costs, costs[1:]))


def test_parse_band():
    assert parse_band("0.05:0.6") == (0.05, 0.6)
    for bad in ("0.6:0.05", "nope", "0.5"):
        with pytest.raises(ValueError):
            parse_band(bad)


# --- re-jit cache isolation ---------------------------------------------


def test_cache_bound_lru_eviction_counters():
    built, evicted = [], []
    cache = RoundVariantCache(lambda k: built.append(k) or f"v:{k}",
                              max_size=2,
                              on_evict=lambda k, e: evicted.append(k))
    assert cache.get("a") == "v:a" and cache.get("b") == "v:b"
    assert cache.counters() == {"hits": 0, "misses": 2,
                                "evictions": 0, "size": 2}
    assert cache.get("a") == "v:a"          # hit refreshes recency
    assert cache.keys() == ["b", "a"]
    cache.get("c")                          # evicts LRU ("b")
    assert evicted == ["b"] and "b" not in cache
    assert len(cache) == 2
    # re-visit after eviction is a rebuild (the recompile the ledger
    # stamp makes visible), never a stale entry
    cache.get("b")
    assert built == ["a", "b", "c", "b"]
    assert cache.counters() == {"hits": 1, "misses": 4,
                                "evictions": 2, "size": 2}


def test_cache_peek_is_side_effect_free():
    cache = RoundVariantCache(lambda k: f"v:{k}", max_size=2)
    assert cache.peek("a") is None          # no build on absence
    assert cache.misses == 0 and len(cache) == 0
    cache.get("a")
    cache.get("b")
    hits = cache.hits
    assert cache.peek("a") == "v:a"
    assert cache.hits == hits               # no recency touch either
    assert cache.keys() == ["a", "b"]


# --- controller policy --------------------------------------------------


def _ladder3():
    return build_ladder(make_cfg())


def test_controller_cheapen_cooldown_and_hold():
    ctl = AutopilotController(_ladder3(), (0.05, 0.6), cooldown=2)
    assert ctl.observe(0, {"recovery_error": 0.01}) == _ladder3()[1]
    # cooldown: two in-band/low observations must pass before the
    # next cheapen
    assert ctl.observe(1, {"recovery_error": 0.01}) is None
    assert ctl.observe(2, {"recovery_error": 0.01}) is None
    assert ctl.observe(3, {"recovery_error": 0.01}) == _ladder3()[2]
    # in-band at the cheapest point: hold forever
    for r in (4, 5, 6):
        assert ctl.observe(r, {"recovery_error": 0.3}) is None
    assert ctl.key == _ladder3()[2]
    acts = [t["action"] for t in ctl.trajectory]
    assert acts == ["cheapen", "hold", "hold", "cheapen",
                    "hold", "hold", "hold"]


def test_controller_backoff_never_oscillates():
    ctl = AutopilotController(_ladder3(), (0.05, 0.6), cooldown=0)
    ctl.observe(0, {"recovery_error": 0.01})
    ctl.observe(1, {"recovery_error": 0.01})
    assert ctl.key == _ladder3()[2]
    # breach: immediate backoff, and the offending point is fenced
    assert ctl.observe(2, {"recovery_error": 0.9}) == _ladder3()[1]
    # low error again — but the cheap limit is monotone: the breached
    # point is never re-entered, so the knobs cannot oscillate
    for r in range(3, 10):
        assert ctl.observe(r, {"recovery_error": 0.001}) is None
    assert ctl.key == _ladder3()[1]


def test_controller_panic_freezes_ladder():
    ctl = AutopilotController(_ladder3(), (0.05, 0.6), cooldown=0)
    ctl.observe(0, {"recovery_error": 0.01})
    assert ctl.idx == 1
    assert ctl.observe(1, {"recovery_error": 0.3,
                           "agg_nan": 1.0}) == _ladder3()[0]
    assert ctl.trajectory[-1]["action"] == "panic"
    # frozen for good: even a perfect error never cheapens again
    for r in range(2, 8):
        assert ctl.observe(r, {"recovery_error": 1e-4}) is None
    assert ctl.key == _ladder3()[0]


def test_controller_blind_rounds_do_not_pay_cooldown():
    ctl = AutopilotController(_ladder3(), (0.05, 0.6), cooldown=1)
    ctl.observe(0, {"recovery_error": 0.01})    # cheapen, cool=1
    # off-cadence rounds (no recovery observation) must not
    # fast-forward the cooldown
    for r in (1, 2, 3):
        assert ctl.observe(r, {}) is None
        assert ctl.trajectory[-1]["action"] == "blind"
    assert ctl.observe(4, {"recovery_error": 0.01}) is None  # pays
    assert ctl.observe(5, {"recovery_error": 0.01}) == _ladder3()[2]


def test_controller_pinned_holds():
    ctl = AutopilotController(_ladder3(), (0.05, 0.6), cooldown=0,
                              start=2, pinned=True)
    for r, err in enumerate((0.001, 0.9, float("nan"))):
        probes = {"recovery_error": err}
        if err != err:
            probes = {"agg_nan": 1.0}
        assert ctl.observe(r, probes) is None
    assert ctl.key == _ladder3()[2]
    assert all(t["action"] == "pinned" for t in ctl.trajectory)


def test_controller_deterministic_and_replay_exact():
    errs = [0.01, 0.01, 0.01, 0.2, 0.01, 0.9, 0.001, None, 0.3]

    def run():
        ctl = AutopilotController(_ladder3(), (0.05, 0.6), cooldown=1,
                                  seed=7)
        for r, e in enumerate(errs):
            ctl.observe(r, {} if e is None
                        else {"recovery_error": e})
        return ctl

    a, b = run(), run()
    assert a.trajectory == b.trajectory
    rec = a.record()
    assert rec["initial"] == key_str(_ladder3()[0])
    assert rec["final"] == key_str(a.key)
    assert rec["final_wire_bytes"] < rec["initial_wire_bytes"]
    # bit-exact replay from the manifest record alone
    assert replay_record(rec) == [t["key"] for t in rec["trajectory"]]


def test_build_controller_modes():
    assert build_controller(make_cfg()) is None
    cfg = make_cfg(autopilot="on", autopilot_band="0.05:0.6",
                   probe_every=1)
    ctl = build_controller(cfg)
    assert ctl is not None and not ctl.pinned
    assert ctl.key == key_of(cfg)
    # pin at an on-ladder point
    pin = key_str(build_ladder(cfg)[2])
    pinned = build_controller(dataclasses.replace(
        cfg, autopilot_pin=pin))
    assert pinned.pinned and key_str(pinned.key) == pin
    # pin OFF the automatic walk: appended as an extra lattice point
    off = build_controller(dataclasses.replace(
        cfg, autopilot_pin="int8-k8-r3-c128-re9500"))
    assert key_str(off.key) == "int8-k8-r3-c128-re9500"


# --- perf-gate band keying ----------------------------------------------


def test_band_suffix_forms():
    assert gate.band_suffix(None) == ""
    assert gate.band_suffix("") == ""
    assert gate.band_suffix("0.2:0.6") == "b0.2-0.6"
    assert gate.band_suffix("0.2-0.6") == "b0.2-0.6"
    assert gate.band_suffix((0.05, 0.6)) == "b0.05-0.6"
    assert gate.topology_key(8, 1, band="0.05:0.6") == "d8p1b0.05-0.6"
    assert gate.topology_key(8, 1, wire_dtype="int8",
                             band="0.05:0.6") == "d8p1qint8b0.05-0.6"


def test_no_cross_band_fallback():
    m = {"round_total": {"median": 1.0, "mad": 0.1, "n": 5,
                         "unit": "ms"}}
    base = gate.make_baseline(m, device_count=8, process_count=1)
    base = gate.update_baseline(base, m, device_count=8,
                                process_count=1, band="0.05:0.6")
    # banded run resolves ONLY its own band
    assert gate.baseline_entry(base, 8, 1, band="0.05:0.6") is not None
    assert gate.baseline_entry(base, 8, 1, band="0.2:0.6") is None
    # a banded run never resolves the static pin, and a static run
    # never resolves a banded one
    assert gate.baseline_entry(base, 8, 1) is not None
    assert gate.baseline_entry(base, 8, 1)\
        .get("autopilot_band") is None
    only_band = gate.make_baseline(m, device_count=8,
                                   process_count=1, band="0.05:0.6")
    assert gate.baseline_entry(only_band, 8, 1) is None
    with pytest.raises(ValueError):
        gate.compare(only_band, m, device_count=8, process_count=1)
    # mesh fallback keeps the band fragment (mesh is the ONLY
    # fragment with a migration fallback)
    assert gate.baseline_entry(
        base, 8, 1, mesh_shape={"clients": 4, "model": 2},
        band="0.05:0.6") is not None


def test_registry_band_and_final_dtype_keying():
    from commefficient_tpu.telemetry import registry
    man = {"config": {"autopilot": "on",
                      "autopilot_band": "0.05:0.6",
                      "sketch_dtype": "f32", "mode": "sketch"},
           "autopilot": {"final": "int8-k16-r3-c128-re9500"}}
    assert registry.run_band(man) == "0.05:0.6"
    # the converged point (not the launch dtype) keys the wire dtype,
    # so a walk that settled on int8 pins as qint8b<lo-hi>
    assert registry.run_wire_dtype(man) == "int8"
    static = {"config": {"autopilot": "off", "sketch_dtype": "bf16",
                         "mode": "sketch"}}
    assert registry.run_band(static) is None
    assert registry.run_wire_dtype(static) == "bf16"


# --- lint: knob mutation confined to the re-plan API --------------------


def test_knob_mutation_lint_rule():
    import ast

    from commefficient_tpu.analysis.lint import RULES_BY_NAME
    rule = RULES_BY_NAME["knob-mutation"]
    src = ("cfg.k = 3\n"
           "self.args.num_rows = 2\n"
           "x.sketch_dtype = 'int8'\n"
           "out = cfg.replace(k=4, num_cols=64)\n"
           "loop.k = 1\n"             # not a config receiver: legal
           "s = s.replace(':', '-')\n")  # positional replace: legal
    hits = rule.check(pathlib.PurePath("runtime/foo.py"),
                      src.splitlines(), ast.parse(src))
    assert sorted(h[0] for h in hits) == [1, 2, 3, 4]
    # autopilot/ IS the sanctioned re-plan API: exempt
    assert rule.check(pathlib.PurePath("autopilot/lattice.py"),
                      src.splitlines(), ast.parse(src)) == []


# --- round plan ---------------------------------------------------------


def test_round_plan_records_autopilot_block():
    from commefficient_tpu.core.rounds import round_plan
    cfg = dataclasses.replace(
        make_cfg(autopilot="on", autopilot_band="0.05:0.6",
                 probe_every=1), grad_size=64)
    plan = round_plan(cfg)
    ap = plan["autopilot"]
    assert ap["band"] == "0.05:0.6"
    assert ap["base"] == key_str(key_of(cfg))
    assert ap["ladder"][0] == ap["base"]
    assert len(ap["ladder"]) == 3
    assert "autopilot" not in round_plan(
        dataclasses.replace(make_cfg(), grad_size=64))


# --- dp budget constraint -----------------------------------------------


def dp_make_cfg(**kw):
    base = dict(dp="sketch", dp_clip=1.0, dp_noise_mult=1.0,
                dp_delta=1e-5, num_clients=8)
    base.update(kw)
    return make_cfg(**base)


def test_apply_knobs_rescales_noise_on_rows_move():
    """A rows-changing knob move recalibrates dp_noise_mult so the
    ABSOLUTE table noise stays at the launch calibration."""
    import math

    from commefficient_tpu.privacy import table_noise_std

    cfg = dp_make_cfg(num_rows=4)
    moved = apply_knobs(cfg, key_of(cfg)._replace(rows=16))
    assert moved.dp_noise_mult == pytest.approx(math.sqrt(4 / 16))
    assert table_noise_std(moved) == pytest.approx(
        table_noise_std(cfg))
    # dtype-only move: σ untouched (qdq is free post-processing)
    assert apply_knobs(cfg, key_of(cfg)._replace(
        dtype="int8")).dp_noise_mult == cfg.dp_noise_mult
    # dp off: the knob is inert, never rewritten
    off = make_cfg(num_rows=4)
    assert apply_knobs(off, key_of(off)._replace(
        rows=16)).dp_noise_mult == off.dp_noise_mult


def test_budget_feasible_predicate():
    from commefficient_tpu.autopilot.controller import _budget_feasible

    cfg = dp_make_cfg(dp_epsilon=8.0)
    keep = _budget_feasible(cfg)
    assert keep(key_of(cfg))                        # launch point
    assert keep(key_of(cfg)._replace(rows=1))       # σ grows: slower
    assert not keep(key_of(cfg)._replace(rows=12))  # σ shrinks: faster
    # constraint off (no budget / dp off): everything passes
    assert _budget_feasible(make_cfg())(
        key_of(cfg)._replace(rows=12))
    assert _budget_feasible(dp_make_cfg())(
        key_of(cfg)._replace(rows=12))


def test_controller_never_holds_budget_violating_point():
    """The ladder is pre-filtered: every point the controller can
    ever visit fits at least as many rounds under --dp_epsilon as
    the launch plan; an infeasible pin is a launch error, not a
    silent fallback."""
    from commefficient_tpu.autopilot.controller import _budget_feasible

    cfg = dp_make_cfg(autopilot="on", autopilot_band="0.05:0.6",
                      probe_every=1, dp_epsilon=8.0)
    ctl = build_controller(cfg)
    keep = _budget_feasible(cfg)
    assert ctl is not None and all(keep(k) for k in ctl.ladder)

    bad = key_str(key_of(cfg)._replace(rows=12))
    with pytest.raises(ValueError, match="budget"):
        build_controller(dataclasses.replace(cfg, autopilot_pin=bad))

    good = key_str(key_of(cfg)._replace(rows=1))
    pinned = build_controller(dataclasses.replace(cfg,
                                                  autopilot_pin=good))
    assert pinned.pinned and key_str(pinned.key) == good


# --- FedModel integration ----------------------------------------------


def _fed_loss(params, batch, cfg):
    pred = batch["x"] @ params["w"]
    n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
    l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
    return l, (l * 0.0 + 1.0,)


def _heavy_batch(rng, W, B, d, num_clients):
    # power-law feature scaling makes the gradient heavy-tailed, so
    # the sketch's top-k recovery error sits far below the dense-iid
    # floor and the band has room to hold across the dtype walk
    scale = (np.arange(1, d + 1) ** -1.5).astype(np.float32)
    return {"client_ids": rng.choice(num_clients, W, replace=False)
            .astype(np.int32),
            "x": jnp.asarray(rng.randn(W, B, d).astype(np.float32)
                             * scale),
            "y": jnp.asarray(rng.randn(W, B), jnp.float32),
            "mask": jnp.ones((W, B), jnp.float32)}


def _run_fed(cfg_kw, n_rounds=8, d=512, num_clients=16,
             return_model=False):
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)
    W, B = 4, 2
    base = dict(mode="sketch", error_type="virtual",
                local_momentum=0.0, virtual_momentum=0.9,
                num_workers=W, local_batch_size=B, seed=5,
                num_clients=num_clients, k=64, num_rows=5,
                num_cols=2048)
    base.update(cfg_kw)
    cfg = Config(**base)
    model = FedModel(None, {"w": jnp.zeros((d,), jnp.float32)},
                     _fed_loss, cfg, padded_batch_size=B)
    opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
    rng = np.random.RandomState(5)
    for _ in range(n_rounds):
        model(_heavy_batch(rng, W, B, d, num_clients))
        opt.step()
    ps = np.asarray(model.ps_weights)
    if return_model:
        return ps, model
    model.finalize()
    return ps


def test_autopilot_off_base_variant_is_args_itself():
    """With the autopilot off, the dispatched variant's config must BE
    the model's args object (apply_knobs identity at the base key), so
    the built round program is byte-identical to a build without the
    feature — the object-identity half of the HLO-identity guarantee
    (the audit's program fingerprints pin the other half)."""
    ps, model = _run_fed({}, n_rounds=1, return_model=True)
    var = model._variants.get(model._variant_key)
    assert var.cfg is model.args
    assert model._autopilot is None
    assert model._variants.counters()["size"] == 1
    model.finalize()


def test_autopilot_hlo_invisible_when_off():
    """The autopilot config fields are host-only: flipping them (with
    the controller pinned at the base point) must not change the
    lowered client-round program."""
    from commefficient_tpu.core.rounds import (ClientStates,
                                               build_client_round)

    def lower(cfg, d=8, B=3, W=2):
        ps = jax.ShapeDtypeStruct((d,), jnp.float32)
        cs = jax.eval_shape(
            lambda: ClientStates.init(cfg, 4,
                                      jnp.zeros((d,), jnp.float32)))
        batch = {"x": jax.ShapeDtypeStruct((W, B, d), jnp.float32),
                 "y": jax.ShapeDtypeStruct((W, B), jnp.float32),
                 "mask": jax.ShapeDtypeStruct((W, B), jnp.float32)}
        ids = jax.ShapeDtypeStruct((W,), jnp.int32)
        rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
        lr = jax.ShapeDtypeStruct((), jnp.float32)

        def loss(flat, batch):
            l = jnp.sum((batch["x"] @ flat - batch["y"]) ** 2
                        * batch["mask"])
            return l, (l * 0.0 + 1.0,)

        return jax.jit(build_client_round(cfg, loss, B)) \
            .lower(ps, cs, batch, ids, rng, lr).as_text()

    off = dataclasses.replace(make_cfg(), grad_size=8)
    on = dataclasses.replace(
        make_cfg(autopilot="on", autopilot_band="0.05:0.6",
                 probe_every=1, autopilot_cooldown=5,
                 autopilot_cache_size=2), grad_size=8)
    assert lower(off) == lower(on)


def test_pinned_knob_bit_identical_to_static():
    """A run pinned at a lattice point must be BIT-identical to the
    equivalent static config — the pin dispatches the same program
    from round 0 and the controller never moves."""
    pin = "int8-k64-r5-c2048-re9500"
    static = _run_fed({"sketch_dtype": "int8"})
    pinned = _run_fed({"autopilot": "on",
                       "autopilot_band": "0.05:0.6",
                       "probe_every": 1, "autopilot_pin": pin})
    assert np.array_equal(static, pinned)


def test_autopilot_walk_band_held_and_compile_isolation():
    """The acceptance walk, compact: from an f32 launch the controller
    converges to int8 (>= 2x cheaper uplink), recovery error stays in
    band on every observed round, and the re-jit cache compiled ONLY
    the visited lattice points."""
    ps, model = _run_fed(
        {"autopilot": "on", "autopilot_band": "0.05:0.6",
         "probe_every": 1, "autopilot_cooldown": 1},
        n_rounds=8, return_model=True)
    ctl = model._autopilot
    rec = model.autopilot_record()
    assert rec["final"].startswith("int8")
    assert rec["final_wire_bytes"] * 2 <= rec["initial_wire_bytes"]
    lo, hi = 0.05, 0.6
    observed = [t for t in rec["trajectory"]
                if t["recovery_error"] is not None]
    assert observed, "no recovery observations reached the controller"
    assert all(t["recovery_error"] <= hi for t in observed)
    assert not any(t["action"] == "panic" for t in observed)
    # replay from the record alone is bit-exact
    assert replay_record(rec) == [t["key"] for t in rec["trajectory"]]
    # compile isolation: every cached variant was visited, and each
    # compiled at most one client flavor (+ server) — never the
    # off-cadence flavor jit keeps lazy, never an unvisited point
    visited = {t["key"] for t in rec["trajectory"]}
    visited.add(rec["initial"])
    cached = model._variants.keys()
    assert {key_str(k) for k in cached} <= visited
    for k in cached:
        var = model._variants.peek(k)
        assert var.compiled <= {"probed", "server"}, \
            (key_str(k), var.compiled)
    assert len(cached) <= len(ctl.ladder)
    model.finalize()


def test_warm_ahead_never_compiles_unvisited_point():
    """_switch_variant AOT-compiles only the point the controller just
    committed to; lattice points never visited must stay absent from
    the cache entirely (jit laziness is not enough — they must never
    even be built)."""
    ps, model = _run_fed(
        {"autopilot": "on", "autopilot_band": "0.0:0.6",
         "probe_every": 1},
        n_rounds=3, return_model=True)
    # band LO=0: nothing is ever below the band, controller holds at
    # the base point forever
    rec = model.autopilot_record()
    assert all(t["action"] in ("hold", "blind")
               for t in rec["trajectory"])
    assert model._variants.counters()["size"] == 1
    assert model._variants.counters()["misses"] == 1
    model.finalize()


def test_variant_switch_bit_identical_to_fresh_jit():
    """After a cache switch, the dispatched variant's program must
    produce bit-identical results to a FRESH jax.jit of the same
    build — the cache is a lookup structure, never a semantic layer."""
    ps, model = _run_fed(
        {"autopilot": "on", "autopilot_band": "0.05:0.6",
         "probe_every": 1, "autopilot_cooldown": 1},
        n_rounds=6, return_model=True)
    var = model._variants.get(model._variant_key)
    assert key_str(var.key).startswith("int8"), \
        "walk did not reach int8; test premise broken"

    from commefficient_tpu.core.rounds import (ClientStates,
                                               build_client_round)
    cfg = var.cfg
    d, W, B = 512, 4, 2
    fresh = jax.jit(build_client_round(
        cfg, None, B, mesh=model.mesh,
        tree_loss=lambda p, b: _fed_loss(p, b, cfg),
        unravel=model.unravel, probes=True, probe_recovery=True))

    rng = np.random.RandomState(11)
    batch = _heavy_batch(rng, W, B, d, 16)
    dev_batch = {k: jnp.asarray(v) for k, v in batch.items()
                 if k != "client_ids"}
    ids = jnp.asarray(batch["client_ids"], jnp.int32)
    key = jax.random.PRNGKey(3)
    ps0 = jnp.asarray(np.asarray(model.ps_weights))

    def run(fn):
        cs = ClientStates.init(cfg, 16, ps0)
        return fn(ps0, cs, dev_batch, ids, key, jnp.float32(0.25))

    a = run(var.round_probed)
    b = run(fresh)
    for xa, xb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        assert np.array_equal(np.asarray(xa), np.asarray(xb))
    model.finalize()
