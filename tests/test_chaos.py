"""Chaos harness: deterministic fault/adversary injection
(data/chaos.py) exercised end to end — byzantine attacks vs the
robust folds and the alarm rules that must name them, the correlated
dropout trace, flaky shard reads against the prefetcher's bounded
retry, prefetch-worker death surfacing, and crash-safe ledger /
manifest writers under an injected SIGKILL mid-write.

The attack matrix is the headline: every (attack x fold) cell must
either converge on the honest objective or raise an alarm — silent
>2x degradation is the one outcome the subsystem exists to prevent.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.clientstore import HostClientStore, StorePrefetcher
from commefficient_tpu.clientstore import prefetch as prefetch_mod
from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import (ClientStates,
                                           build_client_round,
                                           build_server_round)
from commefficient_tpu.core.server import ServerState
from commefficient_tpu.data.chaos import (ChaosConfig, ChaosInjector,
                                          FlakyStore,
                                          kill_prefetch_worker)
from commefficient_tpu.telemetry import registry
from commefficient_tpu.telemetry.alarms import (DivergenceAbort,
                                                build_alarm_engine)
from commefficient_tpu.telemetry.sinks import (JSONLSink,
                                               last_round_index,
                                               recover_torn_tail)

from reference_mirror import MirrorFed, np_robust_fold


def linear_loss(params_flat, batch):
    pred = batch["x"] @ params_flat
    sq = (pred - batch["y"]) ** 2
    n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
    loss = jnp.sum(sq * batch["mask"]) / n
    return loss, (loss * 0.0 + 1.0,)


def make_cfg(**kw):
    base = dict(mode="uncompressed", local_momentum=0.0,
                virtual_momentum=0.0, weight_decay=0.0,
                error_type="none", num_workers=2, k=3,
                num_rows=5, num_cols=16, num_blocks=1,
                local_batch_size=2, microbatch_size=-1, seed=21)
    base.update(kw)
    return Config(**base)


def _pad_round(clients, B, d):
    """(W, B, ...) padded arrays from [(cid, X, y), ...]."""
    W = len(clients)
    x = np.zeros((W, B, d), np.float32)
    y = np.zeros((W, B), np.float32)
    mask = np.zeros((W, B), np.float32)
    ids = np.zeros((W,), np.int32)
    for i, (cid, X, Y) in enumerate(clients):
        n = len(Y)
        x[i, :n], y[i, :n], mask[i, :n], ids[i] = X, Y, 1.0, cid
    batch = {"x": jnp.asarray(x), "y": jnp.asarray(y),
             "mask": jnp.asarray(mask)}
    return batch, jnp.asarray(ids)


# --- injector determinism ----------------------------------------------


def test_byzantine_selection_is_seeded():
    cfg = ChaosConfig(seed=9, attack="sign_flip", byzantine_frac=0.25)
    a = ChaosInjector(cfg, 16)
    b = ChaosInjector(cfg, 16)
    np.testing.assert_array_equal(a.byzantine, b.byzantine)
    assert a.byzantine.size == 4
    assert np.array_equal(np.sort(a.byzantine), a.byzantine)
    other = ChaosInjector(dataclasses.replace(cfg, seed=10), 16)
    assert not np.array_equal(a.byzantine, other.byzantine)


def test_byzantine_explicit_ids_override():
    inj = ChaosInjector(
        ChaosConfig(attack="scale", byzantine_ids=(5, 1, 5)), 8)
    np.testing.assert_array_equal(inj.byzantine, [1, 5])
    assert list(inj.is_byzantine([0, 1, 5, 7])) == [False, True, True,
                                                    False]
    # attack "none" without explicit ids never draws a byzantine set
    calm = ChaosInjector(ChaosConfig(seed=9, byzantine_frac=0.5), 8)
    assert calm.byzantine.size == 0


def test_drop_trace_is_replayable():
    cfg = ChaosConfig(seed=2, burst_start_prob=0.3,
                      burst_stop_prob=0.4, burst_drop_frac=0.25)
    a = ChaosInjector(cfg, 8)
    b = ChaosInjector(cfg, 8)
    ta = [a.drop_slots(8) for _ in range(50)]
    tb = [b.drop_slots(8) for _ in range(50)]
    assert any(t is not None for t in ta)  # bursts happen
    assert any(t is None for t in ta)      # calm happens
    for x, y in zip(ta, tb):
        if x is None:
            assert y is None
        else:
            np.testing.assert_array_equal(x, y)


def test_label_flip_poisons_only_byzantine_rows():
    inj = ChaosInjector(ChaosConfig(attack="label_flip",
                                    byzantine_ids=(2,),
                                    num_classes=10), 4)
    batch = {"y": np.array([[1, 9], [3, 4]]),
             "client_ids": np.array([2, 3])}
    out = inj.poison_batch(batch)
    np.testing.assert_array_equal(out["y"], [[8, 0], [3, 4]])
    # the input batch is never mutated
    np.testing.assert_array_equal(batch["y"], [[1, 9], [3, 4]])
    clean = inj.poison_batch({"y": np.array([[1]]),
                              "client_ids": np.array([0])})
    np.testing.assert_array_equal(clean["y"], [[1]])


def test_burst_dropout_is_correlated_across_rounds():
    cfg = ChaosConfig(seed=4, burst_start_prob=1.0,
                      burst_stop_prob=0.0, burst_drop_frac=0.5)
    inj = ChaosInjector(cfg, 6)
    batches = [{"mask": np.ones((6, 3), np.float32),
                "client_ids": np.arange(6)} for _ in range(4)]
    out = list(inj.wrap_loader(iter(batches)))
    dead = set(np.where(out[0]["mask"].sum(1) == 0)[0])
    assert len(dead) == 3
    for b in out[1:]:  # the burst never stops: same slots every round
        assert set(np.where(b["mask"].sum(1) == 0)[0]) == dead
    assert batches[0]["mask"].sum() == 18  # originals untouched
    replay = list(ChaosInjector(cfg, 6).wrap_loader(iter(batches)))
    assert set(np.where(replay[0]["mask"].sum(1) == 0)[0]) == dead


class _FakeLoader:
    B = 7

    def __init__(self, batches):
        self._b = batches

    def __iter__(self):
        return iter(self._b)

    def __len__(self):
        return len(self._b)

    def peek_next_client_ids(self):
        return [1, 2]


def test_chaos_loader_facade_delegates():
    inj = ChaosInjector(ChaosConfig(seed=0), 4)
    fl = _FakeLoader([{"mask": np.ones((2, 2), np.float32)}] * 3)
    w = inj.wrap(fl)
    assert len(w) == 3
    assert w.B == 7
    assert w.peek_next_client_ids() == [1, 2]
    assert len(list(w)) == 3


# --- robust folds vs the NumPy mirror ----------------------------------


FOLD_CONFIGS = [
    dict(robust_agg="median"),
    dict(robust_agg="median", robust_median_groups=2),
    dict(robust_agg="trimmed", robust_trim_frac=0.25),
    dict(robust_agg="clip", robust_clip_norm=0.5),
    dict(robust_agg="clip"),  # robust_clip_norm 0: auto (median) tau
]


@pytest.mark.parametrize(
    "kw", FOLD_CONFIGS,
    ids=["median", "median-g2", "trimmed", "clip-fixed", "clip-auto"])
def test_robust_fold_matches_mirror(kw):
    """Engine robust fold == tests/reference_mirror.np_robust_fold to
    1e-6, including a DEAD slot (all-zero mask: zero transmit, zero
    datapoint weight, excluded from median/trim ranks and from the
    auto clip tau)."""
    d, B, W = 8, 3, 4
    cfg = make_cfg(num_workers=W, weight_decay=0.01, grad_size=d,
                   **kw)
    rng = np.random.default_rng(3)
    w0 = rng.normal(size=d)
    clients = [(cid, rng.normal(size=(n, d)),
                rng.normal(size=(n,))) for cid, n in
               [(0, 3), (1, 2), (2, 3)]]
    padded = clients + [(3, np.zeros((0, d)), np.zeros((0,)))]
    batch, ids = _pad_round(padded, B, d)
    client_round = jax.jit(build_client_round(cfg, linear_loss, B,
                                              probes=True))
    ps = jnp.asarray(w0, jnp.float32)
    cs = ClientStates.init(cfg, W, ps)
    res = client_round(ps, cs, batch, ids, jax.random.PRNGKey(0),
                       jnp.float32(0.3))
    m = MirrorFed(cfg, w0, W)
    transmits = [m._client_transmit(cid, X, Y, B)
                 for cid, X, Y in clients]
    transmits.append(np.zeros(d))  # the dead slot's zero transmit
    agg, rej = np_robust_fold(cfg, transmits, [3, 2, 3, 0])
    np.testing.assert_allclose(np.asarray(res.aggregated), agg,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(res.probes["fold_rejection_rate"]),
                               rej, rtol=1e-5, atol=1e-6)


# --- the attack matrix: converge or alarm ------------------------------


MATRIX_ATTACKS = ("label_flip", "sign_flip", "scale", "noise")
MATRIX_FOLDS = ("none", "median", "trimmed", "clip")
_BYZ_IDS = (1, 5)  # 2 of 8 clients


def _matrix_chaos(attack):
    kw = dict(seed=7, attack=attack, byzantine_ids=_BYZ_IDS,
              attack_scale=50.0, noise_std=30.0)
    if attack == "label_flip":
        # y -> 200 - y on byzantine rows: a data poison loud enough
        # that its gradients breach the norm-ratio alarm (a 2-class
        # flip on a regression target is provably norm-silent)
        kw["num_classes"] = 201
    return ChaosConfig(**kw)


def _run_cell(attack, fold, rounds=40):
    """One matrix cell: W=8 linear-regression clients, 2 byzantine,
    SGD on the round aggregate. Returns (initial honest loss, final
    honest loss, set of fired alarm rules)."""
    W, B, d, lr = 8, 20, 16, 0.25
    kw = dict(robust_trim_frac=0.25) if fold == "trimmed" else {}
    cfg = make_cfg(num_workers=W, local_batch_size=B, grad_size=d,
                   probe_every=1, on_divergence="log",
                   alarm_byzantine_ratio=2.5,
                   alarm_fold_rejection=0.8, robust_agg=fold, **kw)
    inj = ChaosInjector(_matrix_chaos(attack), W)
    transform = inj.transmit_transform()
    if transform is None:
        # identity transform: keeps data-level cells on the
        # per-client path too, so the client-norm probes (and with
        # them the byzantine_suspect rule) exist in EVERY cell
        def transform(transmit, batch, client_ids, rng):
            return transmit
    client_round = jax.jit(build_client_round(
        cfg, linear_loss, B, probes=True,
        transmit_transform=transform))

    rng = np.random.RandomState(11)
    w_true = rng.randn(d)
    X = rng.randn(W, B, d).astype(np.float32)
    Y = (X.reshape(-1, d) @ w_true).reshape(W, B).astype(np.float32)
    ids_np = np.arange(W, dtype=np.int32)
    y_round = Y
    if attack == "label_flip":
        poisoned = inj.poison_batch({"y": Y.astype(np.float64),
                                     "client_ids": ids_np})
        y_round = poisoned["y"].astype(np.float32)
    batch = {"x": jnp.asarray(X), "y": jnp.asarray(y_round),
             "mask": jnp.ones((W, B), jnp.float32)}
    ids = jnp.asarray(ids_np)

    def honest_loss(p):
        r = X.reshape(-1, d) @ np.asarray(p, np.float64) - Y.ravel()
        return float(np.mean(r * r))

    alarm_engine = build_alarm_engine(cfg)
    ps = jnp.zeros((d,), jnp.float32)
    cs = ClientStates.init(cfg, W, ps)
    key = jax.random.PRNGKey(cfg.seed)
    init = honest_loss(ps)
    rules = set()
    for r in range(rounds):
        res = client_round(ps, cs, batch, ids,
                           jax.random.fold_in(key, r),
                           jnp.float32(lr))
        cs = res.client_states
        probes = {k: float(v) for k, v in res.probes.items()}
        rules |= {a["rule"] for a in alarm_engine.check(r, probes)}
        ps = ps - lr * res.aggregated
    return init, honest_loss(ps), rules


_CLEAN_CACHE = {}


def _clean_cell(fold):
    if fold not in _CLEAN_CACHE:
        _CLEAN_CACHE[fold] = _run_cell("none", fold)
    return _CLEAN_CACHE[fold]


@pytest.mark.parametrize("fold", MATRIX_FOLDS)
def test_attack_matrix_clean_baseline(fold):
    """No attack: every fold converges and NO alarm fires — the
    robust estimators and their alarms cost nothing on honest data."""
    init, final, rules = _clean_cell(fold)
    assert final <= 0.05 * init, (fold, final, init)
    assert not rules, (fold, rules)


@pytest.mark.parametrize(
    "attack,fold",
    [(a, f) for a in MATRIX_ATTACKS for f in MATRIX_FOLDS])
def test_attack_matrix_converge_or_alarm(attack, fold):
    """Every attacked cell must converge on the HONEST objective or
    raise an alarm naming the problem; silent >2x degradation (vs the
    fold's clean baseline) is the one forbidden outcome."""
    _, clean_final, _ = _clean_cell(fold)
    init, final, rules = _run_cell(attack, fold)
    converged = final <= max(2.0 * clean_final, 0.05 * init)
    assert converged or rules, (attack, fold, final, init, rules)
    if fold in ("median", "trimmed"):
        # rank-based folds must actually neutralise a 25% adversary,
        # not merely report it
        assert converged, (attack, fold, final, init)
    if attack in ("scale", "noise", "label_flip"):
        # norm-loud attacks must be NAMED whatever the fold does
        assert "byzantine_suspect" in rules, (attack, fold, rules)
    if attack == "sign_flip" and fold in ("median", "trimmed"):
        # sign_flip hides inside the norm distribution; the fold's
        # own rejection-rate probe is what detects it
        assert "fold_rejection_rate" in rules, (attack, fold, rules)


# --- DP x byzantine: privacy noise composes with the robust fold -------


def _run_dp_cell(attack, rounds=40):
    """One DP matrix cell: the sign-flip adversary against a sketch
    round carrying the FULL --dp sketch mechanism (per-client L2 clip
    + seeded Gaussian noise on the aggregated table) folded with the
    robust clip estimator. Same contract as the plain matrix: returns
    (initial honest loss, final honest loss, fired alarm rules)."""
    from commefficient_tpu.privacy import table_noise_std

    W, B, d, lr = 8, 20, 16, 0.25
    cfg = make_cfg(mode="sketch", error_type="virtual", k=8,
                   num_rows=5, num_cols=128, num_workers=W,
                   local_batch_size=B, grad_size=d, probe_every=1,
                   on_divergence="log", alarm_byzantine_ratio=2.5,
                   alarm_fold_rejection=0.8, robust_agg="clip",
                   # DP demands a FIXED clip cap (config.py): the
                   # auto median-of-norms tau would couple every
                   # client's scale to the whole cohort. The fold
                   # norms its per-datapoint table means — here
                   # sqrt(5)·‖clip(g, 20)‖: the transmit's ×B and
                   # the mean's /n cancel — which start ≈ 30 and
                   # decay as the regression converges; 35 sits just
                   # above, so honest clients never clip, the role
                   # the adaptive median tau played pre-DP.
                   robust_clip_norm=35.0,
                   dp="sketch", dp_clip=20.0, dp_noise_mult=0.05)
    assert table_noise_std(cfg) > 0  # the noise leg is really armed
    inj = ChaosInjector(_matrix_chaos(attack), W)
    transform = inj.transmit_transform()
    if transform is None:
        def transform(transmit, batch, client_ids, rng):
            return transmit
    client_round = jax.jit(build_client_round(
        cfg, linear_loss, B, probes=True,
        transmit_transform=transform))
    server_round = jax.jit(build_server_round(cfg))

    rng = np.random.RandomState(11)
    w_true = rng.randn(d)
    X = rng.randn(W, B, d).astype(np.float32)
    Y = (X.reshape(-1, d) @ w_true).reshape(W, B).astype(np.float32)
    batch = {"x": jnp.asarray(X), "y": jnp.asarray(Y),
             "mask": jnp.ones((W, B), jnp.float32)}
    ids = jnp.asarray(np.arange(W, dtype=np.int32))

    def honest_loss(p):
        r = X.reshape(-1, d) @ np.asarray(p, np.float64) - Y.ravel()
        return float(np.mean(r * r))

    alarm_engine = build_alarm_engine(cfg)
    ps = jnp.zeros((d,), jnp.float32)
    cs = ClientStates.init(cfg, W, ps)
    ss = ServerState.init(cfg)
    key = jax.random.PRNGKey(cfg.seed)
    init = honest_loss(ps)
    rules = set()
    for r in range(rounds):
        res = client_round(ps, cs, batch, ids,
                           jax.random.fold_in(key, r),
                           jnp.float32(lr))
        cs = res.client_states
        probes = {k: float(v) for k, v in res.probes.items()}
        rules |= {a["rule"] for a in alarm_engine.check(r, probes)}
        ps, ss, new_vel, _, _ = server_round(
            ps, ss, res.aggregated, jnp.float32(lr),
            cs.velocities, ids)
        if new_vel is not None:
            cs = cs._replace(velocities=new_vel)
    return init, honest_loss(ps), rules


_DP_CLEAN = {}


def _dp_clean_cell():
    if "cell" not in _DP_CLEAN:
        _DP_CLEAN["cell"] = _run_dp_cell("none")
    return _DP_CLEAN["cell"]


def test_dp_clean_round_converges_without_alarm():
    """No attack: the DP mechanism alone (clip + table noise + clip
    fold) converges on the honest objective and trips NO alarm — the
    privacy noise must not read as a byzantine signature."""
    init, final, rules = _dp_clean_cell()
    assert final <= 0.05 * init, (final, init)
    assert not rules, rules


def test_dp_sign_flip_clip_converge_or_alarm():
    """The headline composition cell: sign_flip byzantines inside a
    DP round with the clip fold. Same forbidden outcome as the plain
    matrix — silent >2x degradation vs the DP clean baseline. The
    per-client DP clip must not blunt the fold, and the table noise
    must not mask (or fake) the adversary."""
    _, clean_final, _ = _dp_clean_cell()
    init, final, rules = _run_dp_cell("sign_flip")
    converged = final <= max(2.0 * clean_final, 0.05 * init)
    assert converged or rules, (final, clean_final, init, rules)


# --- alarm rules in isolation ------------------------------------------


def test_byzantine_suspect_rule():
    cfg = make_cfg(probe_every=1, on_divergence="log",
                   alarm_byzantine_ratio=3.0)
    eng = build_alarm_engine(cfg)
    ok = eng.check(0, {"client_norm_max": 2.0,
                       "client_norm_mean": 1.0})
    assert ok == []
    fired = eng.check(1, {"client_norm_max": 10.0,
                          "client_norm_mean": 1.0})
    assert [a["rule"] for a in fired] == ["byzantine_suspect"]
    # zero mean with a nonzero max is an infinite ratio
    fired = eng.check(2, {"client_norm_max": 1.0,
                          "client_norm_mean": 0.0})
    assert fired and fired[0]["rule"] == "byzantine_suspect"


def test_fold_rejection_rule_and_abort():
    cfg = make_cfg(probe_every=1, on_divergence="abort",
                   alarm_fold_rejection=0.5)
    eng = build_alarm_engine(cfg)
    assert eng.check(0, {"fold_rejection_rate": 0.2}) == []
    with pytest.raises(DivergenceAbort) as err:
        eng.check(1, {"fold_rejection_rate": 0.9})
    assert err.value.alarms[0]["rule"] == "fold_rejection_rate"


# --- flaky shard reads vs the prefetcher's bounded retry ---------------


class _DummyStore:
    def gather(self, ids, out=None):
        return {"v": np.zeros((len(ids), 2), np.float32)}, 0

    def row_version(self, cid):
        return 0


def test_flaky_store_schedule_is_seeded():
    cfg = ChaosConfig(seed=5, shard_fail_prob=0.4,
                      shard_fail_streak=2)

    def trace(n=40):
        fs = FlakyStore(_DummyStore(), cfg)
        out = []
        for _ in range(n):
            try:
                fs.gather(np.array([0]))
                out.append(True)
            except OSError:
                out.append(False)
        return out, fs

    t1, f1 = trace()
    t2, _ = trace()
    assert t1 == t2                      # replayable schedule
    assert f1.failures == t1.count(False) > 0
    assert f1.attempts == 40
    # failures arrive as streaks, not isolated hits
    assert any(a is False and b is False for a, b in zip(t1, t1[1:]))


def _store_with_rows(n=8, dim=4):
    st = HostClientStore(n, {"v": ((dim,), None)},
                         budget_bytes=1 << 16)
    ids = np.arange(n, dtype=np.int64)
    st.write(ids, {"v": np.arange(n * dim, dtype=np.float32)
                   .reshape(n, dim)})
    return st, ids


def test_prefetch_retries_transient_shard_failures(monkeypatch):
    """A failure streak shorter than GATHER_TRIES recovers invisibly:
    take() returns the rows and only the retry counters show it."""
    monkeypatch.setattr(prefetch_mod, "GATHER_BACKOFF_S", 1e-4)
    st, ids = _store_with_rows()
    flaky = FlakyStore(st, ChaosConfig())
    flaky._streak_left = prefetch_mod.GATHER_TRIES - 1
    pf = StorePrefetcher(flaky)
    try:
        pf.submit(ids)
        rows = pf.take(ids)
        assert rows is not None
        np.testing.assert_array_equal(
            rows["v"], np.arange(32, dtype=np.float32).reshape(8, 4))
        assert flaky.failures == prefetch_mod.GATHER_TRIES - 1
        assert flaky.attempts == prefetch_mod.GATHER_TRIES
    finally:
        pf.close()


def test_prefetch_surfaces_persistent_shard_failure(monkeypatch):
    """A streak >= GATHER_TRIES exhausts the retry budget; the OSError
    rides the done-queue and take() raises instead of stalling."""
    monkeypatch.setattr(prefetch_mod, "GATHER_BACKOFF_S", 1e-4)
    st, ids = _store_with_rows()
    flaky = FlakyStore(st, ChaosConfig())
    flaky._streak_left = prefetch_mod.GATHER_TRIES
    pf = StorePrefetcher(flaky)
    try:
        pf.submit(ids)
        with pytest.raises(OSError, match="transient shard read"):
            pf.take(ids)
        assert flaky.failures == prefetch_mod.GATHER_TRIES
    finally:
        pf.close()


def test_kill_prefetch_worker_surfaces_death():
    st, ids = _store_with_rows()
    pf = StorePrefetcher(st)
    try:
        kill_prefetch_worker(pf)
        with pytest.raises(RuntimeError,
                           match="prefetch worker died"):
            pf.submit(ids)
        with pytest.raises(RuntimeError,
                           match="prefetch worker died"):
            pf.submit(ids)  # still dead; never half-recovers
    finally:
        pf.close()


def test_kill_prefetch_worker_requires_hook():
    with pytest.raises(RuntimeError, match="no kill hook"):
        kill_prefetch_worker(object())


# --- crash-safe writers ------------------------------------------------


def test_recover_torn_tail(tmp_path):
    p = tmp_path / "led.jsonl"
    good = json.dumps({"kind": "round", "round": 0}) + "\n"
    torn = '{"kind": "round", "rou'
    p.write_text(good + torn)
    assert recover_torn_tail(str(p)) == len(torn)
    assert p.read_text() == good
    assert recover_torn_tail(str(p)) == 0  # idempotent on clean files
    one = tmp_path / "one.jsonl"
    one.write_text('{"half')  # a single torn line: whole file goes
    assert recover_torn_tail(str(one)) == 6
    assert one.read_text() == ""
    assert recover_torn_tail(str(tmp_path / "missing.jsonl")) == 0


def _round_rec(r):
    return {"kind": "round", "round": r, "spans": {}, "counters": {}}


def test_ledger_survives_sigkill_mid_write(tmp_path):
    """A writer SIGKILLed mid-record leaves at most one torn tail;
    the next append-open truncates it and the resumed sink keeps
    round ids monotone and deduplicated."""
    path = tmp_path / "run.jsonl"
    code = (
        "import json, os, signal\n"
        "from commefficient_tpu.telemetry.sinks import JSONLSink\n"
        f"sink = JSONLSink({str(path)!r})\n"
        "for r in range(3):\n"
        "    sink.write({'kind': 'round', 'round': r, 'spans': {},\n"
        "                'counters': {}})\n"
        "line = json.dumps({'kind': 'round', 'round': 3})\n"
        "sink._f.write(line[:17])\n"  # die halfway through round 3
        "sink._f.flush()\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert out.returncode == -signal.SIGKILL, out.stderr[-2000:]
    assert last_round_index(str(path)) == 2  # torn round 3 not counted
    # resume: the open recovers the tail, resume_after dedups replay
    sink = JSONLSink(str(path), resume_after=last_round_index(str(path)))
    for r in range(1, 5):  # replay overlaps rounds 1-2
        sink.write(_round_rec(r))
    sink.close()
    with open(path) as f:
        rounds = [json.loads(line)["round"] for line in f]
    assert rounds == [0, 1, 2, 3, 4]  # monotone, no duplicates


def test_manifest_survives_sigkill_mid_write(tmp_path):
    """A manifest writer SIGKILLed mid-dump leaves only the inert
    .tmp: no torn file at the canonical name, the registry never
    lists it, and later writes are unaffected."""
    runs = str(tmp_path / "runs")
    code = (
        "import json, os, signal\n"
        "from commefficient_tpu.telemetry import registry\n"
        "def dying_dump(rec, f, **kw):\n"
        "    f.write('{\"kind\": \"run_manifest\", \"torn\": tru')\n"
        "    f.flush()\n"
        "    os.fsync(f.fileno())\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        "json.dump = dying_dump\n"
        f"registry.write_manifest({runs!r}, ledger='led.jsonl')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))))
    assert out.returncode == -signal.SIGKILL, out.stderr[-2000:]
    mdir = os.path.join(runs, registry.MANIFEST_DIR)
    names = sorted(os.listdir(mdir))
    assert names and all(n.endswith(".json.tmp") for n in names)
    assert registry.list_manifests(runs) == []
    # the orphaned .tmp never blocks a later healthy write
    written = registry.write_manifest(runs, ledger="led.jsonl")
    found = registry.list_manifests(runs)
    assert [p for p, _ in found] == [written]
    assert found[0][1]["kind"] == "run_manifest"


def test_ledger_resume_is_monotone_and_deduplicated(tmp_path):
    path = tmp_path / "run.jsonl"
    sink = JSONLSink(str(path))
    for r in range(5):
        sink.write(_round_rec(r))
    # crash mid-round-5: torn tail, no close()
    sink._f.write('{"kind": "round", "round": 5, "spa')
    sink._f.flush()
    sink._f.close()
    assert last_round_index(str(path)) == 4
    resumed = JSONLSink(str(path),
                        resume_after=last_round_index(str(path)))
    for r in range(3, 8):  # checkpoint replay re-emits rounds 3-4
        resumed.write(_round_rec(r))
    resumed.close()
    with open(path) as f:
        rounds = [json.loads(line)["round"] for line in f]
    assert rounds == sorted(set(rounds)) == list(range(8))


# --- preemption drill: die mid-round, resume on fewer devices ----------


_DRILL_WORKER = '''
import json, os, sys
import numpy as np
import jax, jax.numpy as jnp
from commefficient_tpu.config import Config
from commefficient_tpu.runtime import FedModel, FedOptimizer
from commefficient_tpu.runtime.checkpoint import (RoundAutosaver,
                                                  checkpoint_file,
                                                  load_checkpoint)

phase, ckdir, seed = sys.argv[1], sys.argv[2], int(sys.argv[3])
W, B, D, ROUNDS = 4, 8, 16, 20
rng = np.random.RandomState(11)
w_true = rng.randn(D).astype(np.float32)
X = rng.randn(W, B, D).astype(np.float32)
Y = (X.reshape(-1, D) @ w_true).reshape(W, B).astype(np.float32)

def loss(p, batch, _cfg):
    pred = batch["x"] @ p["w"]
    n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
    l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
    return l, (l * 0.0 + 1.0,)

cfg = Config(mode="sketch", error_type="virtual", local_momentum=0.0,
             virtual_momentum=0.9, num_workers=W, local_batch_size=B,
             num_clients=W, dataset_name="CIFAR10", seed=4, k=16,
             num_rows=5, num_cols=64)
cfg.checkpoint_path = ckdir
cfg.checkpoint_every_rounds = 1
cfg.checkpoint_keep = 2
model = FedModel(None, {"w": jnp.zeros((D,), jnp.float32)}, loss,
                 cfg, padded_batch_size=B)
opt = FedOptimizer([{"lr": 0.3}], cfg, model=model)
saver = RoundAutosaver(cfg, model, opt, None, None, None, tag="drill")
drill = None
start = 0
if phase == "kill":
    from commefficient_tpu.data.chaos import PreemptionDrill
    drill = PreemptionDrill(seed=seed, min_round=2, max_round=5)
else:
    load_checkpoint(checkpoint_file(ckdir, "drill"), model, opt)
    start = int(model.round_index)

batch = {"x": X, "y": Y, "mask": np.ones((W, B), np.float32),
         "client_ids": np.arange(W, dtype=np.int32)}

def err(m):
    return float(np.linalg.norm(
        np.asarray(jax.device_get(m.ps_weights)) - w_true))

initial = err(model)
for r in range(start, ROUNDS):
    model(batch)
    if drill is not None and drill.should_kill(model.round_index):
        drill.execute()  # never returns on SIGKILL; SIGTERM dies too
    opt.step()
    saver(0)
model.finalize()
print("DRILL " + json.dumps({
    "start": start, "initial": initial, "final": err(model),
    "diverged": bool(getattr(model, "diverged", False))}))
'''


def test_preemption_drill_resume_on_fewer_devices(tmp_path):
    """The elastic drill end to end: a seeded PreemptionDrill kills a
    2-device sketch run mid-round (after the forward, before the fold
    commits), and a 1-device survivor resumes from the round-cadence
    autosave and must keep converging on the honest objective — or
    flag divergence. Silent degradation is the forbidden outcome."""
    worker = tmp_path / "drill_worker.py"
    worker.write_text(_DRILL_WORKER)
    ckdir = str(tmp_path / "ck")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               PYTHONPATH=repo)
    out = subprocess.run(
        [sys.executable, str(worker), "kill", ckdir, "7"], env=env,
        capture_output=True, text=True, timeout=560, cwd=repo)
    assert out.returncode in (-signal.SIGTERM, -signal.SIGKILL), \
        (out.returncode, out.stderr[-2000:])
    # the autosave cadence left a valid resume point behind
    snaps = [n for n in os.listdir(ckdir) if n.endswith(".npz")]
    assert any(n == "ckpt_drill.npz" for n in snaps), snaps

    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    out = subprocess.run(
        [sys.executable, str(worker), "resume", ckdir, "7"], env=env,
        capture_output=True, text=True, timeout=560, cwd=repo)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = next(json.loads(line[len("DRILL "):])
               for line in out.stdout.splitlines()
               if line.startswith("DRILL "))
    assert rec["start"] >= 1, rec  # resumed mid-run, not from scratch
    converged = rec["final"] <= 0.5 * rec["initial"]
    assert converged or rec["diverged"], rec


def test_preemption_drill_is_seeded():
    """Same seed -> same kill round and signal: a failed drill is a
    repro, not a flake."""
    from commefficient_tpu.data.chaos import PreemptionDrill

    a, b = PreemptionDrill(seed=9), PreemptionDrill(seed=9)
    assert (a.kill_round, a.signal) == (b.kill_round, b.signal)
    assert 1 <= a.kill_round <= 4
    assert a.signal in (signal.SIGTERM, signal.SIGKILL)
    assert not a.should_kill(a.kill_round - 1)
    assert a.should_kill(a.kill_round)
    a.fired = True
    assert not a.should_kill(a.kill_round)


# --- config guard rails ------------------------------------------------


def test_robust_agg_rejects_client_chunk():
    cfg = make_cfg(robust_agg="median", client_chunk=1,
                   microbatch_size=1, grad_size=8)
    with pytest.raises(AssertionError, match="client_chunk"):
        cfg.validate_runtime()


def test_median_groups_must_divide_workers():
    cfg = make_cfg(robust_agg="median", robust_median_groups=3,
                   num_workers=4, grad_size=8)
    with pytest.raises(AssertionError, match="robust_median_groups"):
        cfg.validate_runtime()
