"""Differential fuzz of the mode-config lattice vs the NumPy mirror.

The hand-picked configs in test_modes.py cover the lattice's named
corners; this fuzz samples ~50 random VALID configs per run (5 modes x
error types x momenta x weight decay x microbatch x DP clip x
topk_down x client chunking x sketch geometry x dead clients x ragged
batches), executes 3 federated rounds through the JAX engine and
through tests/reference_mirror.py, and asserts trajectory agreement —
weights after every round, plus final per-client velocity/error/
stale-weight state where the mode carries it.

Seeded and deterministic by default (CI-stable); set FUZZ_SEED /
FUZZ_N env vars to explore new corners. Any discrepancy found should
be frozen as a named regression test in test_modes.py.

Deliberately out of scope (mirror models none of these):
- --dropout_prob's RNG-driven drops: the engine decides drops
  internally, so the mirror can't replay them. Dead clients are
  fuzzed DETERMINISTICALLY instead (all-padding batches — the same
  dead-slot path dropout takes, state-untouched semantics asserted).
- approx_topk outside sketch mode: approx_max_k's selection is
  implementation-defined, so only sketch mode (where the mirror
  shares the CountSketch op and therefore the selection) fuzzes it.
"""

import dataclasses
import os
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.core.rounds import (ClientStates, _state_ids,
                                           args2sketch,
                                           build_client_round,
                                           build_server_round)
from commefficient_tpu.core.server import ServerState

from reference_mirror import MirrorFed
from test_modes import linear_loss, make_cfg

FUZZ_N = int(os.environ.get("FUZZ_N", "50"))
FUZZ_SEED = int(os.environ.get("FUZZ_SEED", "1234"))


def sample_config(rng: random.Random):
    """One random valid point of the mode lattice + its federation
    geometry. Returns (cfg, geometry dict)."""
    mode = rng.choice(["uncompressed", "sketch", "true_topk",
                       "local_topk", "fedavg"])
    d = rng.choice([5, 16, 33])
    k = rng.randint(1, min(d, 8))
    kw = dict(mode=mode, k=k, weight_decay=rng.choice([0.0, 0.01]),
              virtual_momentum=rng.choice([0.0, 0.9]),
              local_momentum=0.0, error_type="none",
              client_chunk=rng.choice([0, 0, 2, 3]),
              seed=rng.randint(0, 10000))
    if mode == "uncompressed":
        kw["local_momentum"] = rng.choice([0.0, 0.9])
    elif mode == "sketch":
        kw["error_type"] = "virtual"
        kw["num_rows"] = rng.choice([1, 3, 5])
        kw["num_cols"] = rng.choice([16, 32, 64])
        kw["num_blocks"] = rng.choice([1, 2, 20])
        kw["approx_topk"] = rng.random() < 0.3
        # wire quantization lattice: f32 keeps the exact path hot;
        # the quantized dtypes exercise both wire-crossing spots —
        # sketch-late (one summed table) and, under a robust fold,
        # the per-client-table qdq
        kw["sketch_dtype"] = rng.choice(["f32", "f32", "bf16",
                                         "int8", "fp8"])
        if rng.random() < 0.25:
            kw["robust_agg"] = rng.choice(["median", "trimmed",
                                           "clip"])
            kw["client_chunk"] = 0  # robust needs the full stack
    elif mode == "true_topk":
        kw["error_type"] = "virtual"
        kw["local_momentum"] = rng.choice([0.0, 0.9])
    elif mode == "local_topk":
        kw["error_type"] = rng.choice(["local", "none"])
        kw["local_momentum"] = rng.choice([0.0, 0.9])
    else:  # fedavg
        kw["fedavg_batch_size"] = rng.choice([-1, 2])
        kw["num_fedavg_epochs"] = rng.choice([1, 2])
        kw["fedavg_lr_decay"] = rng.choice([1.0, 0.9])
        kw["local_batch_size"] = -1
    if mode != "fedavg":
        kw["microbatch_size"] = rng.choice([-1, 1, 2, 3])
        if rng.random() < 0.3:
            kw["do_dp"] = True
            kw["l2_norm_clip"] = 0.5
            kw["noise_multiplier"] = 0.0
        # stale top-k weight downloads (needs exact selection: the
        # stale-diff top-k has no shared-op mirror under approx)
        if rng.random() < 0.3 and not kw.get("approx_topk"):
            kw["do_topk_down"] = True

    W = rng.choice([2, 3])
    kw["num_workers"] = W
    num_clients = rng.choice([4, 6])
    B = 4
    geom = {"d": d, "W": W, "num_clients": num_clients, "B": B,
            "rounds": 3, "lr": 0.05}
    return make_cfg(**kw), geom


def sample_rounds(rng: random.Random, geom):
    """Random federation: per round, W distinct clients with ragged
    batch sizes; occasionally one is DEAD (n=0, all-padding slot —
    the dropout/loader-padding path; the engine must leave its state
    untouched and the mirror simply never sees it)."""
    rs = np.random.RandomState(rng.randint(0, 2 ** 31 - 1))
    rounds = []
    for _ in range(geom["rounds"]):
        ids = rs.choice(geom["num_clients"], geom["W"], replace=False)
        dead = (rs.randint(geom["W"])
                if geom["W"] > 1 and rs.rand() < 0.3 else -1)
        clients = []
        for slot, cid in enumerate(ids):
            n = 0 if slot == dead else rs.randint(1, geom["B"] + 1)
            X = rs.randn(n, geom["d"]).astype(np.float32)
            y = rs.randn(n).astype(np.float32)
            clients.append((int(cid), X, y))
        rounds.append(clients)
    return rounds


def run_engine(cfg, w0, rounds, lr, num_clients, B):
    """test_modes.run_engine + (a) static padded batch B shared by all
    rounds (microbatch boundaries depend on it) and (b) final client
    states returned for the state-agreement asserts."""
    d = len(w0)
    cfg = dataclasses.replace(cfg, grad_size=d)
    client_round = jax.jit(build_client_round(cfg, linear_loss, B))
    server_round = jax.jit(build_server_round(cfg))

    ps = jnp.asarray(w0, jnp.float32)
    cs = ClientStates.init(cfg, num_clients, ps)
    ss = ServerState.init(cfg)
    rng = jax.random.PRNGKey(cfg.seed)
    traj = []
    for rnd_i, clients in enumerate(rounds):
        W = len(clients)
        x = np.zeros((W, B, d), np.float32)
        y = np.zeros((W, B), np.float32)
        mask = np.zeros((W, B), np.float32)
        ids = np.zeros((W,), np.int32)
        for i, (cid, X, Y) in enumerate(clients):
            n = len(Y)
            ids[i] = cid
            if n:
                x[i, :n], y[i, :n], mask[i, :n] = X, Y, 1.0
        batch = {"x": jnp.asarray(x), "y": jnp.asarray(y),
                 "mask": jnp.asarray(mask)}
        res = client_round(ps, cs, batch, jnp.asarray(ids),
                           jax.random.fold_in(rng, rnd_i),
                           jnp.float32(lr))
        cs = res.client_states
        # the runtime sentinels dead slots' ids for the server round
        # too (fed_model._call_train): a dead client's velocity must
        # not be masked by true_topk's server-side scatter
        srv_ids = _state_ids(jnp.asarray(ids), batch)
        ps, ss, new_vel, _, _ = server_round(
            ps, ss, res.aggregated, jnp.float32(lr),
            cs.velocities, srv_ids)
        if new_vel is not None:
            cs = cs._replace(velocities=new_vel)
        traj.append(np.asarray(ps, np.float64))
    return traj, cs


def run_mirror(cfg, w0, rounds, lr, num_clients, B):
    d = len(w0)
    cfg = dataclasses.replace(cfg, grad_size=d)
    m = MirrorFed(cfg, w0, num_clients, sketch=args2sketch(cfg))
    traj = []
    for clients in rounds:
        alive = [c for c in clients if len(c[2]) > 0]
        if cfg.mode == "fedavg":
            traj.append(m.round_fedavg(alive, lr))
        else:
            traj.append(m.round(alive, lr, B=B))
    return traj, m


def describe(cfg, geom):
    keys = ["mode", "error_type", "local_momentum", "virtual_momentum",
            "weight_decay", "microbatch_size", "do_dp", "do_topk_down",
            "client_chunk", "k", "approx_topk", "num_rows", "num_cols",
            "num_blocks", "sketch_dtype", "robust_agg",
            "fedavg_batch_size", "num_fedavg_epochs",
            "fedavg_lr_decay", "seed"]
    parts = [f"{k}={getattr(cfg, k, None)}" for k in keys]
    return " ".join(parts) + f" geom={geom}"


@pytest.mark.parametrize("case", range(FUZZ_N))
def test_fuzzed_config_matches_mirror(case):
    rng = random.Random(FUZZ_SEED * 1000003 + case)
    cfg, geom = sample_config(rng)
    rounds = sample_rounds(rng, geom)
    w0 = np.random.RandomState(case).randn(geom["d"]) * 0.1
    label = describe(cfg, geom)

    got, cs = run_engine(cfg, w0, rounds, geom["lr"],
                         geom["num_clients"], geom["B"])
    want, m = run_mirror(cfg, w0, rounds, geom["lr"],
                         geom["num_clients"], geom["B"])
    # quantized wires: the engine and mirror quantize near-identical
    # f32 tables (the algebra is bit-shared), but a sum that lands on
    # a rounding boundary can flip one wire bin between them — the
    # dequantized tables then differ by a bin step, which error
    # feedback carries forward. Measured worst case over the lattice
    # is ~1e-4 (bf16) / ~1e-7 (int8, fp8); atol leaves headroom.
    atol = {"bf16": 2e-3, "int8": 2e-3,
            "fp8": 2e-3}.get(getattr(cfg, "sketch_dtype", "f32"),
                             1e-5)
    for r, (g, w) in enumerate(zip(got, want)):
        np.testing.assert_allclose(
            g, w, rtol=1e-3, atol=atol,
            err_msg=f"weights diverged at round {r}: {label}")

    # final per-client state agreement where the mode carries it
    if cs.velocities is not None:
        np.testing.assert_allclose(
            np.asarray(cs.velocities, np.float64), m.vel,
            rtol=1e-3, atol=1e-5,
            err_msg=f"client velocities diverged: {label}")
    if cs.errors is not None:
        np.testing.assert_allclose(
            np.asarray(cs.errors, np.float64), m.err,
            rtol=1e-3, atol=1e-5,
            err_msg=f"client errors diverged: {label}")
    if cs.weights is not None and m.client_w is not None:
        np.testing.assert_allclose(
            np.asarray(cs.weights, np.float64), m.client_w,
            rtol=1e-3, atol=1e-5,
            err_msg=f"stale topk_down weights diverged: {label}")
