"""Multi-tenant federation service (commefficient_tpu/fedservice).

The daemon's one hard promise: it is CONTROL PLANE ONLY. A job driven
through the scheduler must be bit-identical — per-round ledger records
and final server state — to driving its FedModel directly, with J > 1
tenants interleaved or not. On top of that: admission control rejects
what the pod cannot run (and the ``admission_rejected`` alarm fires),
the deliberately starvable backlog policy trips ``job_starvation``,
per-job ledger shards stay isolated and solo-equivalent, migration is
checkpoint-exact across mesh shapes, and the JSONLSink two-writer
guard refuses a second live writer on one path.
"""

import dataclasses
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import Config
from commefficient_tpu.fedservice import (AdmissionError, FedService,
                                          JobSpec)
from commefficient_tpu.runtime.fed_model import FedModel, FedOptimizer
from commefficient_tpu.telemetry.sinks import JSONLSink

W, B, DIM = 8, 2, 256

#: wall-clock / host-load fields that legitimately differ between a
#: solo run and a daemon-interleaved one; everything else must match
NONDET_KEYS = ("ts", "spans", "counters", "device_time",
               "host_rss_peak_bytes", "hbm_peak_bytes")


def _loss(params, batch, cfg):
    pred = batch["x"] @ params["w"]
    n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
    l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
    return l, (l * 0.0 + 1.0,)


def _job_cfg(seed, ledger="", **kw):
    base = dict(mode="local_topk", error_type="local",
                local_momentum=0.9, virtual_momentum=0.0, k=8,
                num_workers=W, local_batch_size=B, num_clients=64,
                seed=seed, ledger=ledger)
    base.update(kw)
    return Config(**base)


def _builder(cfg, mesh):
    model = FedModel(None, {"w": jnp.zeros((DIM,), jnp.float32)},
                     _loss, cfg, padded_batch_size=B, mesh=mesh)
    opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
    return model, opt


def _batches(seed, n, workers=W):
    rng = np.random.RandomState(seed)
    return [
        {"client_ids": rng.choice(64, workers, replace=False)
         .astype(np.int32),
         "x": jnp.asarray(rng.randn(workers, B, DIM), jnp.float32),
         "y": jnp.asarray(rng.randn(workers, B), jnp.float32),
         "mask": jnp.ones((workers, B), jnp.float32)}
        for _ in range(n)]


def _solo_run(seed, batches, ledger=""):
    model, opt = _builder(_job_cfg(seed, ledger), None)
    for batch in batches:
        model(batch)
        opt.step()
    final = np.array(model.ps_weights)
    model.finalize()
    return final


def _canon(path):
    """Ledger round records minus the wall-clock fields — the part of
    a job ledger that must be bit-identical daemon vs solo."""
    out = []
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("kind") != "round":
                continue
            kept = {k: v for k, v in rec.items()
                    if k not in NONDET_KEYS}
            out.append(kept)
    return out


def _svc_cfg(ledger="", **kw):
    base = dict(num_workers=W, local_batch_size=B, num_clients=64,
                ledger=ledger)
    base.update(kw)
    return Config(**base)


class TestDeterminism:
    def test_two_job_daemon_bit_identical_to_solo(self, tmp_path):
        """Two interleaved tenants: each job's per-round ledger
        records AND final server state are bit-identical to its own
        solo run."""
        R = 4
        solo_leds = [str(tmp_path / "solo_a.jsonl"),
                     str(tmp_path / "solo_b.jsonl")]
        solo = [
            _solo_run(3, _batches(7, R), solo_leds[0]),
            _solo_run(4, _batches(9, R), solo_leds[1]),
        ]

        led = str(tmp_path / "svc.jsonl")
        svc = FedService(_svc_cfg(led))
        bs = [_batches(7, R), _batches(9, R)]
        svc.admit(JobSpec("a", _job_cfg(3), _builder,
                          lambda r: bs[0][r], rounds=R))
        svc.admit(JobSpec("b", _job_cfg(4), _builder,
                          lambda r: bs[1][r], rounds=R))
        svc.run()
        daemon = [svc.job_state("a"), svc.job_state("b")]
        svc.close()

        for j in range(2):
            assert np.array_equal(solo[j], daemon[j]), f"job {j}"
            shard = _canon(f"{led}.job{j}.jsonl")
            ref = _canon(solo_leds[j])
            assert len(shard) == R
            assert shard == ref, f"job {j} ledger diverged"

    def test_single_job_daemon_parity(self, tmp_path):
        """The J=1 daemon adds zero noise — the reason j1 keeps the
        bare perf-gate key."""
        R = 3
        solo = _solo_run(5, _batches(11, R))
        svc = FedService(_svc_cfg())
        bs = _batches(11, R)
        svc.admit(JobSpec("only", _job_cfg(5), _builder,
                          lambda r: bs[r], rounds=R))
        svc.run()
        daemon = svc.job_state("only")
        svc.close()
        assert np.array_equal(solo, daemon)


class TestAdmission:
    def test_capacity_exceeding_spec_rejected(self, tmp_path):
        """A spatial demand beyond the pod's free devices is refused
        at admission and the always-armed admission_rejected alarm
        lands on the service ledger."""
        led = str(tmp_path / "svc.jsonl")
        svc = FedService(_svc_cfg(led))
        bs = _batches(7, 2)
        with pytest.raises(AdmissionError, match="devices"):
            svc.admit(JobSpec("big", _job_cfg(3), _builder,
                              lambda r: bs[r], rounds=2,
                              mesh_demand=(16, 1)))
        svc.close()
        alarms = [a for rec in map(json.loads, open(led))
                  for a in rec.get("alarms") or ()]
        assert any(a["rule"] == "admission_rejected"
                   for a in alarms), alarms

    def test_duplicate_job_id_and_seed_rejected(self):
        svc = FedService(_svc_cfg())
        bs = _batches(7, 2)
        svc.admit(JobSpec("a", _job_cfg(3), _builder,
                          lambda r: bs[r], rounds=2))
        with pytest.raises(AdmissionError, match="already admitted"):
            svc.admit(JobSpec("a", _job_cfg(8), _builder,
                              lambda r: bs[r], rounds=2))
        with pytest.raises(AdmissionError, match="seed"):
            svc.admit(JobSpec("b", _job_cfg(3), _builder,
                              lambda r: bs[r], rounds=2))
        assert svc._rejected == 2
        svc.close()

    def test_spec_validation(self):
        svc = FedService(_svc_cfg())
        with pytest.raises(AdmissionError, match="rounds"):
            svc.admit(JobSpec("z", _job_cfg(3), _builder,
                              lambda r: None, rounds=0))
        svc.close()


class TestFairness:
    def test_starvation_drill_fires_alarm(self, tmp_path):
        """Backlog policy + one huge tenant: the small tenant starves
        past --alarm_job_starvation and the rule fires with its job
        index attached."""
        led = str(tmp_path / "svc.jsonl")
        svc = FedService(_svc_cfg(led, alarm_job_starvation=3),
                         policy="backlog")
        big, small = _batches(7, 30), _batches(9, 30)
        svc.admit(JobSpec("big", _job_cfg(3), _builder,
                          lambda r: big[r], rounds=30))
        svc.admit(JobSpec("small", _job_cfg(4), _builder,
                          lambda r: small[r], rounds=3))
        fired = []
        for _ in range(8):
            fired.extend(svc.tick())
        svc.close()
        starve = [a for a in fired if a["rule"] == "job_starvation"]
        assert starve, fired
        assert starve[0]["job"] == 1.0  # the small tenant
        alarms = [a for rec in map(json.loads, open(led))
                  for a in rec.get("alarms") or ()]
        assert any(a["rule"] == "job_starvation" for a in alarms)

    def test_fair_policy_no_starvation(self):
        svc = FedService(_svc_cfg(alarm_job_starvation=2))
        bs = [_batches(7, 5), _batches(9, 5)]
        svc.admit(JobSpec("a", _job_cfg(3), _builder,
                          lambda r: bs[0][r], rounds=5))
        svc.admit(JobSpec("b", _job_cfg(4), _builder,
                          lambda r: bs[1][r], rounds=5))
        fired = []
        while svc.active_jobs():
            fired.extend(svc.tick())
        svc.close()
        assert not [a for a in fired
                    if a["rule"] == "job_starvation"], fired


class TestSLOPlane:
    def test_starved_tenant_burns_slo_and_flags_admission(
            self, tmp_path, capsys):
        """Backlog policy + one huge tenant: the daemon's starvation
        SLO burns past --alarm_slo_burn (the rule fires through the
        normal tick check), round records carry the schema-v6 slo
        stamp, the summary backfills the fire count, and a job
        admitted while the budget burns is flagged — in the meta
        record and its manifest — but not refused."""
        led = str(tmp_path / "svc.jsonl")
        svc = FedService(_svc_cfg(led, slo_starvation=1.0,
                                  slo_window=4, slo_fast_window=2,
                                  alarm_slo_burn=1.0),
                         policy="backlog")
        big, small = _batches(7, 20), _batches(9, 20)
        svc.admit(JobSpec("big", _job_cfg(3), _builder,
                          lambda r: big[r], rounds=20))
        svc.admit(JobSpec("small", _job_cfg(4), _builder,
                          lambda r: small[r], rounds=3))
        fired = []
        for _ in range(6):
            fired.extend(svc.tick())
        burn = [a for a in fired if a["rule"] == "slo_burn"]
        assert burn, fired
        assert burn[0]["value"] >= 1.0
        assert burn[0]["slo_burn_starvation"] == burn[0]["value"]
        assert svc.slo_burning_jobs() == ["service"]

        late = _batches(11, 2)
        svc.admit(JobSpec("late", _job_cfg(5), _builder,
                          lambda r: late[r], rounds=2))
        assert "burning their SLO error budget" in \
            capsys.readouterr().out
        svc.close()

        recs = [json.loads(x) for x in open(led)]
        stamped = [r["slo"] for r in recs if r.get("kind") == "round"
                   and r.get("slo")]
        assert stamped and "starvation" in stamped[-1]
        assert stamped[-1]["starvation"]["burn"] >= 1.0
        metas = [r for r in recs if r.get("kind") == "meta"
                 and r.get("slo_burning_at_admission")]
        assert metas and metas[0]["admitted_job"] == "late"
        summ = [r for r in recs if r.get("kind") == "summary"]
        assert summ and summ[0]["alarm_fired"]["slo_burn"] >= 1

    def test_daemon_propagates_plane_knobs_to_tenants(self,
                                                      tmp_path):
        """--live_port / --flightrec_rounds on the daemon cfg arm
        every admitted tenant's sink on the shared registry — one
        scrape endpoint carries job=<j> AND job=service series."""
        import socket

        from commefficient_tpu.telemetry.live import (live_registry,
                                                      shutdown_plane)

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        led = str(tmp_path / "svc.jsonl")
        svc = FedService(_svc_cfg(
            led, live_port=port, flightrec_rounds=4,
            postmortem_dir=str(tmp_path / "pm")))
        try:
            bs = _batches(7, 2)
            svc.admit(JobSpec("a", _job_cfg(3), _builder,
                              lambda r: bs[r], rounds=2))
            job = svc._jobs[0]
            assert job.model.live_sink is not None
            assert job.model.live_sink.labels["job"] == "0"
            assert job.model.flightrec is not None
            assert job.model.flightrec.out_dir == \
                str(tmp_path / "pm")
            svc.run()
            snap = live_registry().snapshot()
            rounds = snap["counters"]["commeff_rounds_total"]
            seen = {snap["labels"][k]["job"]: v
                    for k, v in rounds.items()}
            assert seen["0"] == 2.0
            # the newest tick record drains at close(); at least the
            # earlier ticks have streamed by now
            assert seen["service"] >= 1.0
        finally:
            svc.close()
            shutdown_plane()

    def test_clean_service_has_no_slo_stamp(self):
        """SLO knobs unset: no engine, no stamp, no summary record —
        the bit-identity invariant's observability half."""
        svc = FedService(_svc_cfg())
        assert svc._slo is None
        assert svc.slo_burning_jobs() == []
        svc.close()


class TestSpatialAndMigration:
    def test_spatial_partition_and_release(self):
        """Two 4x1 tenants fill the 8-device pod; their devices come
        back when they drain."""
        svc = FedService(_svc_cfg(num_workers=4))
        bs = [_batches(7, 2, workers=4), _batches(9, 2, workers=4)]

        def mk(i):
            return lambda r: bs[i][r]

        for i, seed in enumerate((3, 4)):
            svc.admit(JobSpec(f"j{i}",
                              _job_cfg(seed, num_workers=4), _builder,
                              mk(i), rounds=2, mesh_demand=(4, 1)))
        assert len(svc._free) == 0
        svc.run()
        assert len(svc._free) == 8
        svc.close()

    def test_migration_is_checkpoint_exact(self, tmp_path):
        """4x1 sub-mesh -> 2x1 mid-run: the migrated job finishes
        with exactly the state a never-migrated run reaches (PR 12
        topology-free restore)."""
        R = 4
        cfg = _job_cfg(3, num_workers=4)
        batches = _batches(7, R, workers=4)
        solo = _solo_run_cfg(cfg, batches)

        svc = FedService(_svc_cfg(num_workers=4),
                         ckpt_dir=str(tmp_path / "ckpt"))
        svc.admit(JobSpec("m", cfg, _builder,
                          lambda r: batches[r], rounds=R,
                          mesh_demand=(4, 1)))
        svc.tick()
        svc.tick()
        before = svc.job_state("m")
        svc.migrate("m", mesh_demand=(2, 1))
        # the restore itself is bit-exact across the mesh change
        assert np.array_equal(before, svc.job_state("m"))
        svc.run()
        migrated = svc.job_state("m")
        svc.close()
        # post-migration rounds: cross-placement XLA reduction order
        # injects ~1e-6 noise (same bound as tests/test_elastic.py)
        np.testing.assert_allclose(migrated, solo, rtol=0, atol=1e-4)


def _solo_run_cfg(cfg, batches):
    model, opt = _builder(dataclasses.replace(cfg), None)
    for batch in batches:
        model(batch)
        opt.step()
    final = np.array(model.ps_weights)
    model.finalize()
    return final


class TestRegistryStamping:
    def test_per_job_manifests_and_job_filter(self, tmp_path):
        """Admission stamps one manifest per tenant (job_id +
        service_run lineage) and latest_ledgers(job=...) narrows to
        that tenant's ledger shard."""
        from commefficient_tpu.telemetry import registry

        led = str(tmp_path / "svc.jsonl")
        runs = str(tmp_path / "runs")
        svc = FedService(_svc_cfg(led), runs_dir=runs)
        bs = [_batches(7, 2), _batches(9, 2)]
        svc.admit(JobSpec("a", _job_cfg(3), _builder,
                          lambda r: bs[0][r], rounds=2))
        svc.admit(JobSpec("b", _job_cfg(4), _builder,
                          lambda r: bs[1][r], rounds=2))
        svc.run()
        svc.close()

        hits = registry.latest_ledgers(runs, n=5, job="a")
        assert len(hits) == 1
        _, manifest, ledger = hits[0]
        assert manifest["job_id"] == "a"
        assert manifest["service_run"] is True
        assert ledger.endswith(".job0.jsonl")
        assert len(registry.latest_ledgers(runs, n=5)) == 2


class TestSinkGuard:
    def test_second_writer_on_same_path_refused(self, tmp_path):
        """Regression: two live JSONLSinks on one path would
        interleave torn records — the second open must raise, and
        close() must release the path for a legitimate reopen."""
        path = str(tmp_path / "led.jsonl")
        sink = JSONLSink(path)
        with pytest.raises(RuntimeError, match="already has a live"):
            JSONLSink(path)
        sink.close()
        again = JSONLSink(path)  # reopen after close is fine
        again.close()

    def test_job_shards_are_distinct_paths(self, tmp_path):
        from commefficient_tpu.telemetry import job_ledger_path
        base = str(tmp_path / "led.jsonl")
        a = JSONLSink(job_ledger_path(base, 0))
        b = JSONLSink(job_ledger_path(base, 1))
        c = JSONLSink(base)
        for s in (a, b, c):
            s.close()


class TestSchedulerLocks:
    @pytest.mark.slow  # compiles a 2-job service run (~7 s); the
    # cheap lock regressions stay in tier-1 via test_live_ops
    def test_probe_threads_race_the_tick_loop(self):
        """flowlint lock-confinement regression: an HTTP scrape
        asking ``active_jobs``/``slo_burning_jobs`` while the
        scheduler ticks (and ``admit`` appends) must never hit
        'list/dict mutated during iteration' — every ``_jobs`` /
        ``_by_id`` / ``_free`` touch now goes through the service
        lock."""
        import threading

        svc = FedService(_svc_cfg(), policy="fair")
        errors = []
        stop = threading.Event()

        def scrape():
            while not stop.is_set():
                try:
                    svc.active_jobs()
                    svc.slo_burning_jobs()
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return

        threads = [threading.Thread(target=scrape) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            for j, seed in enumerate((3, 4)):
                svc.admit(JobSpec(f"j{j}", _job_cfg(seed), _builder,
                                  _mk_batch_fn(seed, 1), rounds=1))
            svc.run(max_ticks=4)
        finally:
            stop.set()
            for t in threads:
                t.join()
            svc.close()
        assert errors == []
        assert svc.active_jobs() == 0


def _mk_batch_fn(seed, n):
    batches = _batches(seed, n)
    return lambda r: batches[r] if r < len(batches) else None
