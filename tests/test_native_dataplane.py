"""C++ data-plane vs the Python loader path.

No-augmentation assembly must match FedLoader bit-for-bit; augmented
output must be a member of the enumerable crop/flip candidate set;
prefetch-ring pops must equal one-shot assembly in submission order.
Skipped wholesale when no toolchain is present."""

import numpy as np
import pytest

from commefficient_tpu import native
from commefficient_tpu.data.fed_sampler import FedSampler
from commefficient_tpu.data.loader import (FedLoader, NativeFedLoader,
                                           make_fed_loader)
from commefficient_tpu.data.synthetic import FedSynthetic
from commefficient_tpu.data.transforms import (Compose, Normalize,
                                               RandomCrop,
                                               RandomHorizontalFlip,
                                               ToFloat)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no native toolchain")

MEAN = np.array([0.1, 0.2, 0.3], np.float32)
STD = np.array([1.1, 0.9, 1.3], np.float32)


def _dataset(transform):
    return FedSynthetic("", "Synthetic", transform=transform,
                        num_classes=4, per_class=16, num_val=8,
                        gen_seed=3)


def _sampler(ds, W=2, B=4, seed=0):
    return FedSampler(ds, num_workers=W, local_batch_size=B, seed=seed)


def test_no_aug_matches_python_loader_bitwise():
    tf = Compose([ToFloat(), Normalize(MEAN, STD)])
    ds_py, ds_nat = _dataset(tf), _dataset(tf)
    py = FedLoader(ds_py, _sampler(ds_py))
    nat = NativeFedLoader(ds_nat, _sampler(ds_nat))
    for b_py, b_nat in zip(py, nat):
        np.testing.assert_array_equal(b_py["client_ids"],
                                      b_nat["client_ids"])
        np.testing.assert_array_equal(b_py["y"], b_nat["y"])
        np.testing.assert_array_equal(b_py["mask"], b_nat["mask"])
        np.testing.assert_array_equal(b_py["x"], b_nat["x"])


def test_augmented_output_is_valid_crop_flip():
    p = 2
    tf = Compose([ToFloat(), RandomCrop(32, p),
                  RandomHorizontalFlip(), Normalize(MEAN, STD)])
    ds = _dataset(tf)
    nat = NativeFedLoader(ds, _sampler(ds), seed=11)
    batch = next(iter(nat))
    images, targets = ds.dense_train_view()

    # each emitted sample must equal one of the (2p+1)^2 * 2
    # crop/flip candidates of SOME stored image with its target
    for w in range(batch["x"].shape[0]):
        for b in range(batch["x"].shape[1]):
            if batch["mask"][w, b] == 0:
                continue
            got = batch["x"][w, b]
            rows = np.nonzero(targets == batch["y"][w, b])[0]
            found = False
            for row in rows:
                img = images[row].astype(np.float32)
                padded = np.pad(img, ((p, p), (p, p), (0, 0)),
                                mode="reflect")
                for i in range(2 * p + 1):
                    for j in range(2 * p + 1):
                        crop = padded[i:i + 32, j:j + 32]
                        for flip in (crop, crop[:, ::-1]):
                            cand = (flip - MEAN) / STD
                            if np.array_equal(cand, got):
                                found = True
                                break
                        if found:
                            break
                    if found:
                        break
                if found:
                    break
            assert found, (w, b)


def test_aug_deterministic_per_seed():
    tf = Compose([ToFloat(), RandomCrop(32, 4),
                  RandomHorizontalFlip(), Normalize(MEAN, STD)])
    ds = _dataset(tf)
    a = next(iter(NativeFedLoader(ds, _sampler(ds, seed=5), seed=9)))
    b = next(iter(NativeFedLoader(ds, _sampler(ds, seed=5), seed=9)))
    c = next(iter(NativeFedLoader(ds, _sampler(ds, seed=5), seed=10)))
    np.testing.assert_array_equal(a["x"], b["x"])
    assert not np.array_equal(a["x"], c["x"])


def test_prefetch_matches_oneshot():
    images = np.random.RandomState(0).randint(
        0, 256, (64, 16, 16, 3)).astype(np.uint8)
    targets = np.arange(64, dtype=np.int32) % 7
    plane = native.NativeDataplane(images, targets, slots=3, B=5,
                                   mean=MEAN, std=STD, crop_pad=2,
                                   do_flip=True)
    rng = np.random.RandomState(1)
    specs = [rng.randint(-1, 64, (3, 5)).astype(np.int64)
             for _ in range(12)]
    expected = [plane.assemble(s, seed=100 + i)
                for i, s in enumerate(specs)]
    with native.Prefetcher(plane, depth=3, n_threads=3) as pf:
        for i, s in enumerate(specs[:6]):
            pf.submit(s, 100 + i)
        for i in range(12):
            x, y, m = pf.pop()
            np.testing.assert_array_equal(x, expected[i][0])
            np.testing.assert_array_equal(y, expected[i][1])
            np.testing.assert_array_equal(m, expected[i][2])
            if i + 6 < 12:
                pf.submit(specs[i + 6], 100 + i + 6)


def test_uint8_scaling_matches_tofloat():
    images = np.random.RandomState(2).randint(
        0, 256, (10, 8, 8, 3)).astype(np.uint8)
    targets = np.zeros(10, np.int32)
    plane = native.NativeDataplane(images, targets, slots=1, B=2,
                                   mean=MEAN, std=STD)
    idx = np.array([[3, 7]], np.int64)
    x, _, _ = plane.assemble(idx, seed=0)
    ref = (images[[3, 7]].astype(np.float32) / 255.0 - MEAN) / STD
    np.testing.assert_allclose(x[0], ref, rtol=0, atol=1e-6)


def test_make_fed_loader_fallback_on_unsupported_transform():
    from commefficient_tpu.data.transforms import RandomRotation
    tf = Compose([ToFloat(), RandomRotation(5), Normalize(MEAN, STD)])
    ds = _dataset(tf)
    with pytest.warns(UserWarning, match="native data-plane"):
        loader = make_fed_loader(ds, _sampler(ds))
    assert isinstance(loader, FedLoader)
    tf2 = Compose([ToFloat(), Normalize(MEAN, STD)])
    ds2 = _dataset(tf2)
    loader2 = make_fed_loader(ds2, _sampler(ds2))
    assert isinstance(loader2, NativeFedLoader)


def test_out_of_range_index_raises():
    images = np.zeros((10, 8, 8, 3), np.uint8)
    targets = np.zeros(10, np.int32)
    plane = native.NativeDataplane(images, targets, slots=1, B=2,
                                   mean=MEAN, std=STD)
    with pytest.raises(IndexError):
        plane.assemble(np.array([[3, 10]], np.int64), seed=0)
    with native.Prefetcher(plane, depth=2, n_threads=1) as pf:
        pf.submit(np.array([[99, 0]], np.int64), 0)
        with pytest.raises(IndexError):
            pf.pop()


def test_prefetch_ring_soak():
    """500 rounds through a 4-thread ring: strict submission-order
    delivery and correct content under sustained concurrency."""
    images = np.random.RandomState(0).randint(
        0, 256, (128, 8, 8, 3)).astype(np.uint8)
    targets = (np.arange(128) % 11).astype(np.int32)
    plane = native.NativeDataplane(images, targets, slots=2, B=3,
                                   mean=MEAN, std=STD, crop_pad=1,
                                   do_flip=True)
    rng = np.random.RandomState(1)
    n = 500
    specs = [rng.randint(-1, 128, (2, 3)).astype(np.int64)
             for _ in range(n)]
    # full-content comparison every round (images are tiny): any
    # out-of-order delivery or corruption fails deterministically
    expected = [plane.assemble(s, seed=i) for i, s in enumerate(specs)]
    with native.Prefetcher(plane, depth=4, n_threads=4) as pf:
        inflight = 0
        submitted = 0
        for i in range(n):
            while submitted < n and inflight < 8:
                pf.submit(specs[submitted], submitted)
                submitted += 1
                inflight += 1
            x, y, m = pf.pop()
            inflight -= 1
            np.testing.assert_array_equal(x, expected[i][0])
            np.testing.assert_array_equal(y, expected[i][1])
            np.testing.assert_array_equal(m, expected[i][2])
