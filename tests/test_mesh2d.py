"""2D-mesh (clients x model) correctness: a pod-scale round that
shards the sketch table, momentum and error-feedback state by columns
over the ``model`` axis must reproduce the 1-D clients-only round to
float tolerance (bit-identical where the mode permits) — the sharded
server is an implementation detail, never a semantics change."""

import dataclasses
import re

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import (ClientStates, args2sketch,
                                           build_client_round,
                                           build_server_round)
from commefficient_tpu.core.server import ServerState
from commefficient_tpu.ops.topk import distributed_threshold_mask_1d
from commefficient_tpu.parallel.mesh import (MODEL_AXIS,
                                             client_sharding,
                                             make_mesh2d,
                                             model_axis_size,
                                             server_state_sharding,
                                             shard_map, spec)

from test_modes import linear_loss
from test_sharding import _batch, _setup

import pytest


def _run_rounds(cfg, mesh, n_rounds=3, seed=5, per_client=False,
                ids_fn=None):
    """Drive ``n_rounds`` full rounds; returns final params, server
    momentum/error state and the last round's (globally gathered)
    aggregate. ``mesh=None`` is the 1-D oracle; ``per_client``
    disqualifies the fused path via the microbatch no-op (same trick
    as test_sharding.TestFusedMeshPath)."""
    run_cfg = cfg
    if per_client:
        run_cfg = dataclasses.replace(cfg, microbatch_size=3)
    cr = jax.jit(build_client_round(run_cfg, linear_loss, 3,
                                    mesh=mesh))
    two_d = mesh is not None and model_axis_size(mesh) > 1
    sr = jax.jit(build_server_round(run_cfg,
                                    mesh=mesh if two_d else None))
    d = cfg.grad_size
    ps = jnp.zeros(d, jnp.float32).at[0].set(0.5)
    cs = ClientStates.init(cfg, 16, ps)
    ss = ServerState.init(
        cfg, sharding=(server_state_sharding(mesh, cfg.transmit_shape)
                       if two_d else None))
    agg = None
    for r in range(n_rounds):
        batch, ids = _batch(seed=seed + r)
        if ids_fn is not None:
            batch, ids = ids_fn(batch, ids)
        if mesh is not None:
            sh = client_sharding(mesh)
            batch = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sh), batch)
        res = cr(ps, cs, batch, ids, jax.random.PRNGKey(r), 1.0)
        cs = res.client_states
        agg = res.aggregated
        ps, ss, _, _, _ = sr(ps, ss, res.aggregated, jnp.float32(0.01))
    return (np.asarray(ps), np.asarray(ss.Vvelocity),
            np.asarray(ss.Verror), np.asarray(agg), cs)


def _assert_state_close(a, b, tol=1e-6):
    for x, y in zip(a[:4], b[:4]):
        np.testing.assert_allclose(x, y, rtol=0, atol=tol)


class TestDistributedSelect:
    def test_threshold_mask_matches_topk_with_ties(self, devices):
        """The shard-local candidate extraction + global k-th-key
        agreement must select exactly the lax.top_k set — including
        the lowest-global-index tie-break and a ragged last shard
        (d not divisible by the model axis)."""
        d, k, M = 37, 7, 8
        n_loc = -(-d // M)
        rng = np.random.RandomState(3)
        sq = np.abs(rng.randn(d)).astype(np.float32)
        sq[5] = sq[21] = sq[30] = 1.7  # forced three-way tie
        pad = n_loc * M - d
        sq_p = np.pad(sq, (0, pad))
        valid = (np.arange(n_loc * M) < d)

        mesh = make_mesh2d(1, M)

        def body(sq_loc, valid_loc):
            return distributed_threshold_mask_1d(
                sq_loc, k, MODEL_AXIS, valid=valid_loc)

        mask = shard_map(
            body, mesh=mesh,
            in_specs=(spec(MODEL_AXIS), spec(MODEL_AXIS)),
            out_specs=spec(MODEL_AXIS),
        )(jnp.asarray(sq_p), jnp.asarray(valid))
        got = set(np.nonzero(np.asarray(mask))[0].tolist())
        want = set(np.asarray(
            jax.lax.top_k(jnp.asarray(sq), k)[1]).tolist())
        assert got == want
        assert len(got) == k

    def test_estimates_at_bit_identical(self, devices):
        """Point queries into the gathered table must agree bit-for-
        bit with the rolled full-table estimate — the 2D select sees
        exactly what the 1-D unsketch would."""
        cfg = _setup("sketch")
        sk = args2sketch(cfg)
        rng = np.random.RandomState(11)
        table = jnp.asarray(
            rng.randn(cfg.num_rows, cfg.num_cols).astype(np.float32))
        idx = jnp.arange(cfg.grad_size, dtype=jnp.int32)
        full = np.asarray(sk.estimates(table))[:cfg.grad_size]
        point = np.asarray(sk.estimates_at(table, idx))
        np.testing.assert_array_equal(full, point)


class TestMesh2DParity:
    @pytest.mark.parametrize("shape", [(4, 2), (2, 4)])
    def test_sketch_matches_1d_oracle(self, devices, shape):
        cfg = _setup("sketch", weight_decay=5e-4)
        ref = _run_rounds(cfg, None)
        got = _run_rounds(cfg, make_mesh2d(*shape))
        _assert_state_close(ref, got)

    @pytest.mark.parametrize("shape", [(4, 2), (1, 8)])
    def test_uncompressed_matches_1d_oracle(self, devices, shape):
        cfg = _setup("uncompressed", error_type="none",
                     virtual_momentum=0.9, weight_decay=5e-4)
        ref = _run_rounds(cfg, None)
        got = _run_rounds(cfg, make_mesh2d(*shape))
        _assert_state_close(ref, got)

    def test_mesh_cx1_matches_1d_oracle(self, devices):
        """A Cx1 mesh is the existing 1-D program — the 2D plumbing
        must be a strict superset, not a fork."""
        cfg = _setup("sketch")
        ref = _run_rounds(cfg, None)
        got = _run_rounds(cfg, make_mesh2d(8, 1))
        _assert_state_close(ref, got)

    def test_robust_fold_parity_2d(self, devices):
        """Robust folds run on the per-client early-sketch path; the
        2D server must consume the replicated folded table unchanged."""
        cfg = _setup("sketch", robust_agg="trimmed",
                     robust_trim_frac=0.25)
        ref = _run_rounds(cfg, None, per_client=True)
        got = _run_rounds(cfg, make_mesh2d(4, 2), per_client=True)
        _assert_state_close(ref, got)

    def test_dead_slots_parity_2d(self, devices):
        """Dropout pads (id-0 sentinel slots with an all-zero mask)
        must stay inert on the 2D late-sketch per-client path exactly
        as on 1-D — no state race, no aggregate contribution."""
        def kill_last(batch, ids):
            batch = dict(batch)
            batch["mask"] = batch["mask"].at[-2:].set(0.0)
            ids = ids.at[-2:].set(0)
            return batch, ids

        cfg = _setup("sketch")
        ref = _run_rounds(cfg, None, per_client=True,
                          ids_fn=kill_last)
        got = _run_rounds(cfg, make_mesh2d(4, 2), per_client=True,
                          ids_fn=kill_last)
        _assert_state_close(ref, got)

    def test_server_state_shards_one_over_m(self, devices):
        """The headline memory claim: per-device momentum/EF table
        shards are 1/M of the global table."""
        cfg = _setup("sketch")
        mesh = make_mesh2d(2, 4)
        ss = ServerState.init(
            cfg, sharding=server_state_sharding(mesh,
                                                cfg.transmit_shape))
        r, c = cfg.num_rows, cfg.num_cols
        for buf in (ss.Vvelocity, ss.Verror):
            shapes = {tuple(s.data.shape)
                      for s in buf.addressable_shards}
            assert shapes == {(r, c // 4)}, shapes


class TestCompiled2D:
    def _lowered(self, cfg, mesh, seed=12):
        batch, ids = _batch(seed=seed)
        fused = build_client_round(cfg, linear_loss,
                                   batch["x"].shape[1], mesh=mesh)
        ps = jnp.zeros(cfg.grad_size, jnp.float32)
        cs = ClientStates.init(cfg, 16, ps)
        if mesh is not None and mesh.devices.size > 1:
            sh = client_sharding(mesh)
            batch = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, sh), batch)
        return jax.jit(fused).lower(ps, cs, batch, ids,
                                    jax.random.PRNGKey(0),
                                    jnp.float32(1.0))

    def test_reduce_scatter_and_sharded_allreduce(self, devices):
        """The 2D fused round's table traffic: a reduce-scatter over
        ``model`` (partial tables -> column shards) and a client-axis
        all-reduce of the (r, c/M) SHARD — never of the full (r, c)
        table or a (W, d) gradient buffer."""
        cfg = _setup("sketch")
        txt = self._lowered(cfg, make_mesh2d(4, 2)).compile().as_text()
        assert re.search(r"reduce-scatter(-start)?\(", txt), \
            "2D sketch emission must lower to a real reduce-scatter"
        ars = [l for l in txt.splitlines()
               if re.search(r"all-reduce(-start)?\(", l)]
        r, c = cfg.num_rows, cfg.num_cols
        shard = [l for l in ars if f"f32[{r},{c // 2}]" in l
                 or f"f32[{r * c // 2}]" in l]
        assert len(shard) == 1, "\n".join(ars)
        assert not any(f"f32[{r},{c}]" in l for l in ars)
        assert not any(f"f32[{8 * cfg.grad_size}]" in l or
                       f"f32[8,{cfg.grad_size}]" in l for l in ars)

    def test_server2d_gathers_once(self, devices):
        """The distributed select rebuilds the full table with ONE
        table-sized all-gather; no all-reduce of table-sized buffers."""
        cfg = _setup("sketch")
        mesh = make_mesh2d(4, 2)
        sr = build_server_round(cfg, mesh=mesh)
        ss = ServerState.init(
            cfg, sharding=server_state_sharding(mesh,
                                                cfg.transmit_shape))
        ps = jnp.zeros(cfg.grad_size, jnp.float32)
        agg = jnp.zeros(cfg.transmit_shape, jnp.float32)
        txt = jax.jit(sr).lower(ps, ss, agg,
                                jnp.float32(0.01)).compile().as_text()
        r, c = cfg.num_rows, cfg.num_cols
        ags = [l for l in txt.splitlines()
               if re.search(r"all-gather(-start)?\(", l)
               and f"f32[{r},{c}]" in l]
        assert len(ags) == 1, txt

    def test_mesh_1x1_lowering_identical_to_1d(self, devices):
        """--mesh 1x1 must build the SAME program as the 1-D default
        (loc-stripped StableHLO fingerprint) — no 2D tax on the
        single-device path."""
        from commefficient_tpu.analysis.hlo import fingerprint
        cfg = _setup("sketch")
        one_d = self._lowered(cfg, None).as_text()
        mesh11 = self._lowered(cfg, make_mesh2d(1, 1)).as_text()
        assert fingerprint(one_d) == fingerprint(mesh11)


def test_config_mesh_validation():
    cfg = _setup("sketch", mesh="4x2")
    assert cfg.mesh2d == (4, 2) and cfg.model_axis == 2
    with pytest.raises(AssertionError):
        _setup("true_topk", mesh="4x2").validate_runtime()
    with pytest.raises(AssertionError):
        # 32 cols % 3 != 0
        _setup("sketch", mesh="2x3").validate_runtime()
