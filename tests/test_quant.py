"""Wire-quantization ops (``--sketch_dtype``): quantize/harmonize/
dequantize properties, bit-exact parity with the NumPy reference
mirror, the fused Pallas emit+quantize path vs sketch-then-quantize,
recovery error inside the alarm band, the downlink delta-encoding
byte formula, and the f32 HLO-identity pin (quantization machinery
must leave ZERO trace in the f32 round program)."""

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np
import pytest

from commefficient_tpu import accounting
from commefficient_tpu.ops import quant
from commefficient_tpu.ops.sketch import CountSketch
from tests.reference_mirror import (np_dequantize_table, np_qeff,
                                    np_quantize_table)

SCALED = ["int8", "fp8"]
WIRES = ["bf16", "int8", "fp8"]


def rand_table(r=4, c=64, seed=0, zero_row=True):
    """Rows at wildly different magnitudes (each row carries its own
    scale) plus, by default, one all-zero row for the 0/0 guard."""
    rng = np.random.RandomState(seed)
    t = rng.randn(r, c).astype(np.float32)
    t *= np.power(10.0, rng.randint(-3, 4, (r, 1))).astype(np.float32)
    if zero_row:
        t[1] = 0.0
    return t


class TestQuantizeProperties:
    @pytest.mark.parametrize("wire", SCALED)
    def test_roundtrip_error_bounded(self, wire):
        t = rand_table()
        q, s = jax.jit(lambda x: quant.quantize_table(x, wire))(
            jnp.asarray(t))
        back = np.asarray(quant.dequantize(q, s))
        s = np.asarray(s)
        if wire == "int8":
            # uniform steps of width ``scale``: half-step plus one
            # f32 ULP of the div/mul round trip
            assert np.all(np.abs(back - t) <= 0.5 * s * (1 + 1e-6))
        else:
            # e4m3 relative ulp/2 = 2^-4 (f16 intermediate adds at
            # most one more near-tie ULP -> 2^-3 is safely loose);
            # subnormal floor: half the min subnormal (2^-9) x scale
            assert np.all(np.abs(back - t)
                          <= np.maximum(np.abs(t) * 2.0**-3,
                                        s * 2.0**-10))

    @pytest.mark.parametrize("wire", SCALED)
    def test_zero_row_guard(self, wire):
        """All-zero rows quantize to zeros under scale exactly 1.0 —
        the 0/0 guard in ops/quant._scale."""
        t = rand_table()
        q, s = quant.quantize_table(jnp.asarray(t), wire)
        q, s = np.asarray(q), np.asarray(s)
        assert np.all(np.asarray(q[1], np.float32) == 0.0)
        assert s[1, 0] == 1.0
        assert np.all(np_dequantize_table(q, s)[1] == 0.0)

    def test_qeff_headroom_schedule(self):
        # int8 floors to an integer step and never drops below 1
        assert quant.qeff("int8", 1) == 127.0
        assert quant.qeff("int8", 2) == 63.0
        assert quant.qeff("int8", 8) == 15.0
        assert quant.qeff("int8", 127) == 1.0
        assert quant.qeff("int8", 500) == 1.0
        # fp8 values are not integers: exact division
        assert quant.qeff("fp8", 1) == 448.0
        assert quant.qeff("fp8", 2) == 224.0
        assert quant.qeff("fp8", 7) == 448.0 / 7.0
        # the mirror runs the identical schedule
        for wire in SCALED:
            for n in (1, 2, 7, 8, 127, 500):
                assert np_qeff(wire, n) == quant.qeff(wire, n)

    @pytest.mark.parametrize("wire", SCALED)
    def test_harmonize_identity_single_shard(self, wire):
        """n_addends=1 with global == local rowmax: harmonize must be
        the bit-exact identity (IEEE x/x == 1; re-rounding a value
        the format already holds is itself)."""
        t = rand_table(seed=3)
        q, rowmax = quant.quantize_local(jnp.asarray(t), wire)
        qq, s = quant.harmonize(q, rowmax, rowmax, wire, 1)
        assert (np.asarray(qq).tobytes() == np.asarray(q).tobytes())
        np.testing.assert_array_equal(
            np.asarray(s),
            np.asarray(quant._scale(rowmax, quant.qeff(wire, 1))))

    @pytest.mark.parametrize("wire", SCALED)
    def test_summation_headroom_no_overflow(self, wire):
        """n shards harmonized onto the shared scale: the wire-dtype
        sum can never leave the wire range, and dequantizing the sum
        approximates the true f32 sum within n half-steps."""
        n, r, c = 4, 3, 128
        shards = [rand_table(r, c, seed=10 + i, zero_row=False)
                  for i in range(n)]
        locs = [quant.quantize_local(jnp.asarray(t), wire)
                for t in shards]
        g = jnp.max(jnp.stack([rm for _, rm in locs]), axis=0)
        harm = [quant.harmonize(q, rm, g, wire, n) for q, rm in locs]
        scale = np.asarray(harm[0][1])
        total = sum(np.asarray(q, np.float32) for q, _ in harm)
        assert np.all(np.abs(total) <= quant.QMAX[wire])
        back = total * scale
        true = sum(shards)
        step = scale * (1.0 if wire == "int8" else 2.0**-3
                        * quant.qeff(wire, n))
        tol = n * 0.5 * step + n * np.abs(true) * (
            0.0 if wire == "int8" else 2.0**-3)
        assert np.all(np.abs(back - true) <= tol + 1e-6)

    def test_bf16_is_scale_free_cast(self):
        t = rand_table(seed=4)
        q, s = quant.quantize_table(jnp.asarray(t), "bf16")
        assert s is None
        np.testing.assert_array_equal(
            np.asarray(q), t.astype(ml_dtypes.bfloat16))
        np.testing.assert_array_equal(
            np.asarray(quant.dequantize(q, s)),
            t.astype(ml_dtypes.bfloat16).astype(np.float32))

    def test_fp8_routes_through_explicit_f16(self):
        """The f32->fp8 convert is pinned to double-round via f16
        (ops/quant._to_fp8) so CPU/TPU/NumPy agree bit-for-bit."""
        rng = np.random.RandomState(5)
        x = np.concatenate([
            rng.randn(512).astype(np.float32) * 448.0,
            rng.randn(512).astype(np.float32) * 2.0**-9,
            np.float32([448.0, -448.0, 0.0, 2.0**-9, 2.0**-10]),
        ])
        got = np.asarray(quant._to_fp8(jnp.asarray(x), "fp8"))
        want = x.astype(np.float16).astype(ml_dtypes.float8_e4m3fn)
        assert got.tobytes() == want.tobytes()


class TestMirrorParity:
    """tests/reference_mirror.np_quantize_table is the engine-side
    oracle (used by the mode-vs-mirror suites): it must match the jax
    ops bit-for-bit, including the multi-shard harmonize path."""

    @pytest.mark.parametrize("wire", WIRES)
    @pytest.mark.parametrize("n_addends", [1, 2, 8])
    def test_bitwise(self, wire, n_addends):
        t = rand_table(seed=6)
        # a shared rowmax above the local one exercises the ratio<1
        # harmonize branch the multi-shard collective hits
        g = None if n_addends == 1 else np.max(
            np.abs(t), axis=-1, keepdims=True) * np.float32(2.0)
        qj, sj = quant.quantize_table(
            jnp.asarray(t), wire, n_addends=n_addends,
            global_rowmax=None if g is None else jnp.asarray(g))
        qn, sn = np_quantize_table(t, wire, n_addends=n_addends,
                                   global_rowmax=g)
        assert np.asarray(qj).tobytes() == qn.tobytes()
        if wire == "bf16":
            assert sj is None and sn is None
        else:
            assert np.asarray(sj).tobytes() == sn.tobytes()
            np.testing.assert_array_equal(
                np.asarray(quant.dequantize(qj, sj)),
                np_dequantize_table(qn, sn))


class TestFusedPallas:
    """ops/sketch_pallas.sketch_quant_pallas (emit + quantize in one
    kernel, f32 table confined to VMEM scratch) vs sketch-then-
    quantize over the SAME pallas table: exact agreement."""

    @pytest.mark.parametrize("wire", WIRES)
    @pytest.mark.parametrize("d,c,r", [(5000, 1024, 3), (300, 128, 5)])
    def test_fused_matches_unfused(self, wire, d, c, r):
        cs = CountSketch(d=d, c=c, r=r, seed=7,
                         backend="pallas_interpret")
        v = jnp.asarray(
            np.random.RandomState(0).randn(d).astype(np.float32))
        qf, rmf = cs.sketch_quantized(v, wire)
        qu, rmu = quant.quantize_local(cs.sketch(v), wire)
        assert np.asarray(qf).tobytes() == np.asarray(qu).tobytes()
        if wire == "bf16":
            assert rmf is None and rmu is None
        else:
            np.testing.assert_array_equal(np.asarray(rmf),
                                          np.asarray(rmu))

    @pytest.mark.parametrize("wire", WIRES)
    def test_fused_matches_unfused_per_chunk(self, wire):
        """--overlap_depth emission: the fused kernel's rows=(off,
        cnt) form must reproduce the row slice of the whole-table
        fused result bit for bit (per-row scales: a chunk IS its row
        slice of the table algebra), for every chunk of every depth —
        with VMEM scratch sized to the chunk, not the table."""
        from commefficient_tpu.parallel.wire import row_chunks
        d, c, r = 3000, 256, 5
        cs = CountSketch(d=d, c=c, r=r, seed=7,
                         backend="pallas_interpret")
        v = jnp.asarray(
            np.random.RandomState(1).randn(d).astype(np.float32))
        whole = np.asarray(cs.sketch(v))
        for depth in (2, 4):
            for off, cnt in row_chunks(r, depth):
                qf, rmf = cs.sketch_quantized(v, wire,
                                              rows=(off, cnt))
                qu, rmu = quant.quantize_local(
                    jnp.asarray(whole[off:off + cnt]), wire)
                assert np.asarray(qf).tobytes() == \
                    np.asarray(qu).tobytes(), (depth, off)
                if wire == "bf16":
                    assert rmf is None and rmu is None
                else:
                    np.testing.assert_array_equal(
                        np.asarray(rmf), np.asarray(rmu))


class TestRecoveryBand:
    @pytest.mark.parametrize("wire", SCALED)
    def test_quantized_recovery_stays_in_band(self, wire):
        """Top-k recovery from a quantize->dequantize table stays
        within the alarm band of f32 recovery (the table's own noise
        dominates the wire rounding at sane geometries)."""
        d, c, r, k = 1 << 14, 2048, 3, 100
        cs = CountSketch(d=d, c=c, r=r, seed=11)
        rng = np.random.RandomState(12)
        v = rng.randn(d).astype(np.float32) * 0.01
        hh = rng.choice(d, k, replace=False)
        v[hh] += rng.randn(k).astype(np.float32) * 10.0
        table = cs.sketch(jnp.asarray(v))

        def err(t):
            _, idx, vals = cs.unsketch(t, k, with_support=True)
            rec = np.zeros(d, np.float32)
            rec[np.asarray(idx)] = np.asarray(vals)
            return float(np.linalg.norm(rec - v) / np.linalg.norm(v))

        e32 = err(table)
        eq = err(quant.dequantize(*quant.quantize_table(table, wire)))
        assert eq <= max(2.0 * e32, e32 + 0.05), (wire, e32, eq)


class TestWireByteFormulas:
    def test_uplink_ratio_meets_frontier(self):
        """int8 uplink at the reference geometry (5 x 16384): >= 3.5x
        fewer bytes than f32 — the PR's headline wire saving."""
        f32 = accounting.sketch_wire_bytes(5, 16384, "f32")
        i8 = accounting.sketch_wire_bytes(5, 16384, "int8")
        assert f32 / i8 >= 3.5
        # scaled dtypes carry one f32 scale per row
        assert i8 == 5 * 16384 * 1 + 5 * 4
        assert accounting.sketch_wire_bytes(5, 16384, "bf16") == f32 / 2

    def test_delta_downlink_formula(self):
        f = accounting.delta_downlink_bytes
        # 10 changed, 4 repeat the previous support of 9: 10 int8
        # values + 6 fresh int32 indices + ceil(9/8)=2 bitmap bytes
        assert f(10, 4, 9, "int8") == 10 * 1 + 6 * 4 + 2
        assert f(10, 4, 9, "f32") == 10 * 4 + 6 * 4 + 2
        # a stale client delta-codes nothing: every coord is (idx, val)
        assert f(10, 4, 9, "int8", have_prev=False) == 10 * (1 + 4)
        assert f(0, 0, 0, "int8") == 0.0


class TestF32HloIdentity:
    def test_f32_program_carries_no_quantization(self):
        """sketch_dtype='f32' must compile the EXACT round program a
        config that never mentions the flag compiles (the committed
        audit_baseline.json pins it against the pre-feature program),
        and no wire-dtype tensor may appear anywhere in it."""
        from commefficient_tpu.analysis import hlo, program
        from commefficient_tpu.core.rounds import build_client_round
        from commefficient_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(jax.devices())

        def lower(cfg):
            fn = build_client_round(cfg, program._toy_loss, program.B,
                                    mesh=mesh)
            args = program._client_inputs(cfg, mesh)
            return jax.jit(fn, donate_argnums=(1,)).lower(
                *args).as_text()

        explicit = program.make_cfg(
            "sketch", program.MESH_W, error_type="virtual",
            virtual_momentum=0.9, sketch_dtype="f32")
        silent = program.make_cfg(
            "sketch", program.MESH_W, error_type="virtual",
            virtual_momentum=0.9)
        # the getattr-defaulted form the runtime also tolerates
        del silent.__dict__["sketch_dtype"]
        text = lower(explicit)
        assert hlo.fingerprint(text) == hlo.fingerprint(lower(silent))
        for wire_type in ("xi8>", "f8E4M3", "xbf16>"):
            assert wire_type not in text, wire_type
