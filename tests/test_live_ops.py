"""Live operations plane: metrics exporter, SLO burn rates, flight
recorder.

The plane's contract, tested here end to end: the exporter serves
exactly what the ledger records (one registry, per-job labels, text
exposition that a minimal Prometheus parser round-trips); SLO burn is
the classic multi-window error-budget rate and matches a NumPy mirror
bit-for-bit; the ``slo_burn`` alarm shares the ``--on_divergence``
escalation; the flight recorder's postmortem bundle is atomic (a
SIGKILLed process leaves either a complete bundle or none), bounded,
and rate-limited to one bundle per firing rule; and with every knob
unset the whole plane is never constructed — the telemetry no-op
fast path stays untouched.
"""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import urllib.error
import urllib.request

import numpy as np
import pytest

from commefficient_tpu.config import Config
from commefficient_tpu.telemetry.alarms import (AlarmEngine,
                                                DivergenceAbort)
from commefficient_tpu.telemetry.core import Telemetry
from commefficient_tpu.telemetry.flightrec import (FlightRecorder,
                                                   install_crash_hook,
                                                   load_postmortem)
from commefficient_tpu.telemetry.live import (PREFIX, LiveMetricsSink,
                                              LiveRegistry, LiveServer,
                                              attach_live_plane,
                                              shutdown_plane)
from commefficient_tpu.telemetry.record import make_round_record
from commefficient_tpu.telemetry.sinks import (job_index_of_ledger,
                                               recover_ledger_shards)
from commefficient_tpu.telemetry.slo import (SLOEngine, SLOSpec,
                                             build_slo_engine)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_plane():
    yield
    shutdown_plane()


# --- registry + exposition format --------------------------------------


def test_registry_render_round_trips_through_parser():
    """What the registry renders, the operator console's minimal
    parser reads back — names, label escaping, quantiles, _sum/_count
    — so the two ends of the scrape share one wire contract."""
    fedwatch = _load_script("fedwatch")
    reg = LiveRegistry()
    labels = {"job": 'we"ird\\job', "run": "r1"}
    reg.counter_add("c_total", 2, labels)
    reg.counter_add("c_total", 3, labels)
    reg.gauge_set("g", -1.5, labels)
    for v in (1.0, 2.0, 3.0, 4.0):
        reg.observe("s_seconds", v, labels)
    samples = fedwatch.parse_prometheus(reg.render())
    by_name = {}
    for name, lab, val in samples:
        by_name.setdefault(name, []).append((lab, val))
    assert by_name["c_total"] == [(labels, 5.0)]
    assert by_name["g"] == [(labels, -1.5)]
    qs = {lab["quantile"]: val for lab, val in by_name["s_seconds"]}
    # nearest-rank quantiles over the sorted window [1,2,3,4]:
    # p50 -> index round(0.5*3) = 2 -> 3.0
    assert qs == {"0.5": 3.0, "0.95": 4.0, "1": 4.0}
    assert by_name["s_seconds_sum"] == [(labels, 10.0)]
    assert by_name["s_seconds_count"] == [(labels, 4.0)]


def _round_rec(r, **kw):
    rec = make_round_record(r)
    rec.update(kw)
    return rec


def test_live_sink_derives_series_from_records():
    """The sink derives every exported series from the record stream
    alone — the same records the ledger gets — so a scrape can never
    disagree with the post-hoc ledger."""
    reg = LiveRegistry()
    sink = LiveMetricsSink(reg, labels={"job": "0"})
    sink.write({"kind": "meta", "plan": {"num_workers": 8}})
    sink.write(_round_rec(
        0, spans={"client": 0.75, "server": 0.25},
        uplink_bytes=1000.0, downlink_bytes=2000.0, dp_epsilon=0.25,
        probes={"job_backlog_total": 3.0, "slo_burn_round_latency": 0.5,
                "slo_burn_max": 0.5},
        alarms=[{"rule": "slo_burn", "value": 10.0}]))
    sink.write({"kind": "summary", "alarm_fired": {"slo_burn": 2}})
    snap = reg.snapshot()

    def series(kind, name):
        return {snap["labels"][k]["objective"]
                if "objective" in snap["labels"][k]
                else snap["labels"][k].get("rule", "0"): v
                for k, v in snap[kind][PREFIX + name].items()}

    assert series("counters", "rounds_total") == {"0": 1.0}
    assert series("counters", "uplink_bytes_total") == {"0": 1000.0}
    assert series("counters", "downlink_bytes_total") == {"0": 2000.0}
    assert series("counters", "alarms_total") == {"slo_burn": 1.0}
    assert series("gauges", "clients_per_s") == {"0": 8.0}
    assert series("gauges", "job_backlog_total") == {"0": 3.0}
    assert series("gauges", "dp_epsilon") == {"0": 0.25}
    assert series("gauges", "slo_burn") == {"round_latency": 0.5,
                                            "max": 0.5}
    assert series("gauges", "alarms_run_total") == {"slo_burn": 2.0}
    window, total, count = next(iter(
        snap["summaries"][PREFIX + "round_seconds"].values()))
    assert (window, total, count) == ([1.0], 1.0, 1)


def test_exporter_serves_metrics_and_healthz():
    reg = LiveRegistry()
    reg.counter_add(PREFIX + "rounds_total", 7, {"job": "a"})
    server = LiveServer(reg, port=0)  # ephemeral
    try:
        with urllib.request.urlopen(server.url + "/metrics") as resp:
            assert "version=0.0.4" in resp.headers["Content-Type"]
            body = resp.read().decode()
        assert f'{PREFIX}rounds_total{{job="a"}} 7' in body
        with urllib.request.urlopen(server.url + "/healthz") as resp:
            assert resp.read() == b"ok\n"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(server.url + "/nope")
    finally:
        server.close()


def test_plane_off_is_never_constructed():
    """Both knobs unset: no sink, no recorder, no server thread, and
    the telemetry fan-out keeps its disabled fast path."""
    from commefficient_tpu.telemetry import live

    tel = Telemetry()
    sink, rec = attach_live_plane(tel, Config())
    assert sink is None and rec is None
    assert not tel.enabled
    assert live._PLANE["server"] is None
    assert live._PLANE["registry"] is None


def test_job_index_of_ledger():
    assert job_index_of_ledger("runs/svc.jsonl.job3.jsonl") == 3
    assert job_index_of_ledger(
        "runs/svc.jsonl.job3.jsonl.p1.jsonl") == 3
    assert job_index_of_ledger("runs/svc.jsonl") is None
    assert job_index_of_ledger("") is None


# --- SLO burn-rate math ------------------------------------------------


def test_burn_rate_matches_numpy_mirror():
    """The engine's burn per round equals the NumPy-mirrored
    min(fast, slow) window violation rate over the error budget."""
    spec = SLOSpec(round_p95_s=1.0, error_budget=0.05,
                   window=16, fast_window=4)
    eng = SLOEngine(spec)
    lat = np.random.RandomState(0).uniform(0.5, 1.5, size=64)
    viol = (lat > spec.round_p95_s).astype(float)
    for i, v in enumerate(lat):
        probes = eng.observe(i, round_s=float(v))
        if i + 1 < spec.fast_window:  # warmup: never alarm cold
            assert probes["slo_burn_round_latency"] == 0.0
            continue
        fast = viol[max(0, i + 1 - spec.fast_window):i + 1].mean()
        slow = viol[max(0, i + 1 - spec.window):i + 1].mean()
        want = min(fast, slow) / spec.error_budget
        assert probes["slo_burn_round_latency"] == pytest.approx(want)
        assert probes["slo_burn_max"] == pytest.approx(want)


def test_multiwindow_needs_current_and_sustained():
    """One bad round never pages (slow window dilutes it); a
    sustained burn does; recovery drops the burn immediately (fast
    window clears) even while the slow window is still hot."""
    spec = SLOSpec(round_p95_s=1.0, error_budget=0.05,
                   window=32, fast_window=4)
    eng = SLOEngine(spec)
    for i in range(32):
        eng.observe(i, round_s=0.5)
    p = eng.observe(32, round_s=5.0)  # one blip after a clean run
    assert p["slo_burn_round_latency"] == pytest.approx(
        (1 / 32) / 0.05)
    assert not eng.burning
    for i in range(33, 49):  # sustained: 16 bad rounds
        p = eng.observe(i, round_s=5.0)
    assert p["slo_burn_round_latency"] >= 10.0
    assert eng.burning
    for i in range(49, 53):  # recovery: fast window all clean
        p = eng.observe(i, round_s=0.5)
    assert p["slo_burn_round_latency"] == 0.0
    assert not eng.burning


def test_privacy_burn_linear_schedule():
    """ε spend at or under the linear schedule ε*(n+1)/horizon never
    violates; spending ahead of it burns."""
    spec = SLOSpec(eps_horizon=10, eps_budget=1.0,
                   window=4, fast_window=2)
    eng = SLOEngine(spec)
    for n in range(6):  # strictly under the schedule
        p = eng.observe(n, dp_epsilon=0.05 * (n + 1))
        assert p["slo_burn_privacy_burn"] == 0.0
    for n in range(6, 10):  # overspent from round 6 of 10 on
        p = eng.observe(n, dp_epsilon=1.1)
    assert p["slo_burn_privacy_burn"] == pytest.approx(1.0 / 0.05)
    stamp = eng.stamp()["privacy_burn"]
    assert stamp["seen"] == 10 and stamp["fast_rate"] == 1.0


def test_objectives_advance_independently():
    """An objective with no signal this round does not advance — its
    windows measure its own stream, not wall rounds."""
    spec = SLOSpec(round_p95_s=1.0, staleness_max=2.0,
                   window=8, fast_window=2)
    eng = SLOEngine(spec)
    for i in range(4):
        eng.observe(i, round_s=5.0)  # latency only
    p = eng.observe(4, staleness_max=1.0)  # first staleness sample
    assert eng.stamp()["round_latency"]["seen"] == 4
    assert eng.stamp()["staleness"]["seen"] == 1
    assert p["slo_burn_staleness"] == 0.0  # still in ITS warmup
    assert p["slo_burn_max"] == p["slo_burn_round_latency"] > 1.0


def test_build_slo_engine_gating():
    assert build_slo_engine(Config()) is None  # all targets 0
    eng = build_slo_engine(Config(slo_round_p95=0.5))
    assert eng is not None and not eng.burning
    # privacy objective arms only with a real DP budget
    eng = build_slo_engine(Config(dp="sketch", dp_epsilon=2.0,
                                  dp_noise_mult=1.0,
                                  slo_eps_rounds=10))
    assert eng is not None
    assert "privacy_burn" in eng._objectives
    with pytest.raises(AssertionError):  # ε horizon without DP
        Config(slo_eps_rounds=10)


# --- the slo_burn alarm rule -------------------------------------------


def test_slo_alarm_fires_with_objective_breakdown():
    cfg = Config(alarm_slo_burn=2.0, slo_round_p95=0.1,
                 slo_window=4, slo_fast_window=2)
    engine = AlarmEngine(cfg)
    assert engine.check_slo(0, {}) == []
    assert engine.check_slo(
        0, {"slo_burn_max": 1.9,
            "slo_burn_round_latency": 1.9}) == []
    fired = engine.check_slo(
        3, {"slo_burn_max": 12.0, "slo_burn_round_latency": 12.0,
            "slo_burn_staleness": 0.5})
    assert [a["rule"] for a in fired] == ["slo_burn"]
    assert fired[0]["value"] == 12.0 and fired[0]["threshold"] == 2.0
    # the alarm names WHICH objective burns, not just that one does
    assert fired[0]["slo_burn_round_latency"] == 12.0
    assert fired[0]["slo_burn_staleness"] == 0.5


def test_slo_alarm_abort_escalation():
    cfg = Config(alarm_slo_burn=1.0, slo_round_p95=0.1,
                 on_divergence="abort")
    engine = AlarmEngine(cfg)
    with pytest.raises(DivergenceAbort, match="slo_burn"):
        engine.check_slo(5, {"slo_burn_max": 3.0})


def test_alarm_counts_backfilled_on_summary(tmp_path):
    """Every flagged alarm lands in the close()-time summary record's
    per-rule ``alarm_fired`` totals; clean runs emit no summary."""
    from commefficient_tpu.telemetry.sinks import JSONLSink

    path = str(tmp_path / "led.jsonl")
    tel = Telemetry([JSONLSink(path)])
    tel.begin_round(0)
    tel.flag_alarm(0, {"rule": "slo_burn", "value": 2.0})
    tel.flag_alarm(0, {"rule": "slo_burn", "value": 3.0})
    tel.flag_alarm(0, {"rule": "nan_inf", "value": 1.0})
    tel.close()
    recs = [json.loads(x) for x in open(path)]
    summ = [r for r in recs if r["kind"] == "summary"]
    assert len(summ) == 1
    assert summ[0]["alarm_fired"] == {"nan_inf": 1, "slo_burn": 2}

    clean = str(tmp_path / "clean.jsonl")
    tel = Telemetry([JSONLSink(clean)])
    tel.begin_round(0)
    tel.close()
    kinds = [json.loads(x)["kind"] for x in open(clean)]
    assert "summary" not in kinds  # bit-identity for healthy runs


# --- flight recorder ---------------------------------------------------


def test_flightrec_ring_bound_and_one_bundle_per_rule(tmp_path):
    out = str(tmp_path / "pm")
    fr = FlightRecorder(Config(), 4, labels={"job": "j"}, out_dir=out)
    fr.write({"kind": "meta", "plan": {"num_workers": 2}})
    for r in range(9):
        fr.write(_round_rec(r))
    trip = _round_rec(9, alarms=[{"rule": "slo_burn", "value": 9.0,
                                  "threshold": 1.0}])
    fr.write(trip)  # alarm in-stream -> dump, trigger inside the ring
    first = fr.last_bundle
    assert first and os.path.isfile(first)
    assert not [n for n in os.listdir(out) if n.endswith(".tmp")]
    bundle, problems = load_postmortem(first)
    assert problems == []
    assert [r["round"] for r in bundle["rounds"]] == [6, 7, 8, 9]
    assert bundle["rounds"][-1]["alarms"][0]["rule"] == "slo_burn"
    assert bundle["labels"] == {"job": "j"}
    assert bundle["meta"]["plan"] == {"num_workers": 2}
    assert [e["rule"] for e in bundle["events"]
            if e["kind"] == "alarm"] == ["slo_burn"]

    # same rule keeps firing: same incident, no new bundle
    fr.write(_round_rec(10, alarms=[{"rule": "slo_burn",
                                     "value": 10.0}]))
    assert fr.last_bundle == first
    assert len(os.listdir(out)) == 1
    # a DIFFERENT rule (and a shutdown) are new incidents
    fr.write(_round_rec(11, alarms=[{"rule": "nan_inf",
                                     "value": 1.0}]))
    fr.dump("graceful_shutdown", context={"signal": "SIGTERM"})
    assert len(os.listdir(out)) == 3


def test_flightrec_crash_hook_dumps_before_traceback(tmp_path,
                                                     capsys):
    fr = FlightRecorder(Config(), 2, out_dir=str(tmp_path / "pm"))
    fr.write(_round_rec(0))
    prev = sys.excepthook
    try:
        hook = install_crash_hook(fr)
        hook(ValueError, ValueError("boom"), None)
    finally:
        sys.excepthook = prev
    bundle, problems = load_postmortem(fr.last_bundle)
    assert problems == []
    assert bundle["reason"] == "crash"
    assert "ValueError: boom" in bundle["context"]["exception"]
    assert capsys.readouterr().err  # the traceback still printed


def test_postmortem_survives_sigkill(tmp_path):
    """Trip an alarm (bundle dumps atomically), then SIGKILL the
    process: the parent finds a complete, valid bundle — never a torn
    one — because the write is tmp + fsync + rename."""
    out = str(tmp_path / "pm")
    code = (
        "import os, signal\n"
        "from commefficient_tpu.config import Config\n"
        "from commefficient_tpu.telemetry.flightrec import "
        "FlightRecorder\n"
        "from commefficient_tpu.telemetry.record import "
        "make_round_record\n"
        f"fr = FlightRecorder(Config(), 4, labels={{'job': '0'}},\n"
        f"                    out_dir={out!r})\n"
        "for r in range(6):\n"
        "    rec = make_round_record(r)\n"
        "    if r == 5:\n"
        "        rec['alarms'] = [{'rule': 'slo_burn', 'value': 9.0,\n"
        "                          'threshold': 1.0}]\n"
        "    fr.write(rec)\n"
        "assert fr.last_bundle\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300,
                         cwd=_REPO)
    assert res.returncode == -signal.SIGKILL, res.stderr[-2000:]
    names = sorted(os.listdir(out))
    assert len(names) == 1 and names[0].endswith(".json"), names
    bundle, problems = load_postmortem(os.path.join(out, names[0]))
    assert problems == []
    assert bundle["reason"] == "alarm" and bundle["rule"] == "slo_burn"
    assert [r["round"] for r in bundle["rounds"]] == [2, 3, 4, 5]


# --- lock confinement under real threads (flowlint regression) ---------


def test_flightrec_concurrent_writer_and_dump(tmp_path):
    """The crash-hook/alarm threads dump while the round loop
    appends: the ring snapshot under the lock means no 'deque mutated
    during iteration', and the claim-before-I/O means two racing
    dumps of the SAME incident write exactly one bundle."""
    import threading

    out = str(tmp_path / "pm")
    fr = FlightRecorder(Config(), 4, labels={"job": "j"},
                        out_dir=out)
    fr.write({"kind": "meta", "plan": {}})
    stop = threading.Event()
    errors = []

    def writer():
        r = 0
        while not stop.is_set():
            try:
                fr.write(_round_rec(r))
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return
            r += 1

    def dumper(reason):
        try:
            fr.dump(reason, rule="crash_race")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    w = threading.Thread(target=writer)
    w.start()
    dumpers = [threading.Thread(target=dumper, args=("crash",))
               for _ in range(4)]
    for t in dumpers:
        t.start()
    for t in dumpers:
        t.join()
    stop.set()
    w.join()
    assert errors == []
    bundles = [n for n in os.listdir(out) if n.endswith(".json")]
    assert len(bundles) == 1, bundles  # one incident, one bundle
    _, problems = load_postmortem(os.path.join(out, bundles[0]))
    assert problems == []


def test_live_registry_concurrent_writers():
    """HTTP scrape threads render while round loops publish: every
    label-map write now happens under the registry lock, so N
    hammering threads lose no increments and render() never sees a
    mid-write dict."""
    import threading

    reg = LiveRegistry()
    errors = []

    def pound(j):
        try:
            for i in range(200):
                reg.counter_add("ffl_rounds_total", 1.0,
                                labels={"job": str(j)})
                reg.gauge_set("ffl_loss", float(i),
                              labels={"job": str(j)})
                reg.render()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=pound, args=(j,))
               for j in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    snap = reg.snapshot()
    counts = snap["counters"]["ffl_rounds_total"]
    assert sorted(counts.values()) == [200.0] * 4


def test_jsonl_sink_concurrent_claim_single_winner(tmp_path):
    """Two threads racing to open the same ledger path: the claim is
    taken under ``_live_lock`` BEFORE the file opens, so exactly one
    construction succeeds and the losers get the live-writer error —
    never two writers interleaving on one shard."""
    import threading

    from commefficient_tpu.telemetry.sinks import JSONLSink

    path = str(tmp_path / "led.jsonl")
    results = []

    def construct():
        try:
            results.append(JSONLSink(path))
        except RuntimeError as e:
            results.append(e)

    threads = [threading.Thread(target=construct) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sinks = [r for r in results if isinstance(r, JSONLSink)]
    errs = [r for r in results if isinstance(r, RuntimeError)]
    assert len(sinks) == 1 and len(errs) == 3, results
    sinks[0].close()
    # the claim dies with close(): reopening is legal again
    JSONLSink(path).close()


def test_report_renders_postmortem(tmp_path, capsys):
    out = str(tmp_path / "pm")
    fr = FlightRecorder(Config(), 3, labels={"job": "7"}, out_dir=out)
    fr.write({"kind": "meta", "plan": {"num_workers": 2}})
    for r in range(3):
        rec = _round_rec(r)
        rec["spans"]["server"] = 0.01
        fr.write(rec)
    path = fr.dump("alarm", rule="slo_burn",
                   context={"alarms": [{"rule": "slo_burn"}]})
    report = _load_script("telemetry_report")
    assert report.main(["--postmortem", path]) == 0
    text = capsys.readouterr().out
    assert "incident: alarm rule=slo_burn" in text
    assert "job=7" in text and "3 of last 3 round(s)" in text
    assert report.main(["--postmortem", path, "--json"]) == 0
    blob = json.loads(capsys.readouterr().out)
    assert blob["problems"] == []
    assert blob["bundle"]["rule"] == "slo_burn"
    assert blob["summary"]["rounds"] == 3


# --- shard recovery at daemon restart ----------------------------------


def test_recover_ledger_shards_sweeps_job_and_process_shards(
        tmp_path):
    base = str(tmp_path / "svc.jsonl")
    good = json.dumps({"kind": "round", "round": 0}) + "\n"
    shards = [base, base + ".job0.jsonl", base + ".p1.jsonl",
              base + ".job0.jsonl.p2.jsonl"]
    for p in shards:
        with open(p, "w") as f:
            f.write(good + '{"kind": "round", "rou')  # torn tail
    dropped = recover_ledger_shards(base)
    assert sorted(dropped) == sorted(shards)
    assert all(n > 0 for n in dropped.values())
    for p in shards:
        assert open(p).read() == good
    assert recover_ledger_shards(base) == {}  # idempotent
    assert recover_ledger_shards(
        str(tmp_path / "missing.jsonl")) == {}


# --- fedwatch console --------------------------------------------------


def test_fedwatch_folds_scrape_into_job_table():
    fedwatch = _load_script("fedwatch")
    reg = LiveRegistry()
    sink = LiveMetricsSink(reg, labels={"job": "0", "run": "r"})
    sink.write({"kind": "meta", "plan": {"num_workers": 4}})
    sink.write(_round_rec(
        0, spans={"server": 2.0}, uplink_bytes=4096.0,
        probes={"slo_burn_max": 1.5, "slo_burn_round_latency": 1.5},
        alarms=[{"rule": "slo_burn"}]))
    jobs = fedwatch.job_table(
        fedwatch.parse_prometheus(reg.render()))
    row = jobs["0"]
    assert row["rounds"] == 1.0 and row["p95_s"] == 2.0
    assert row["clients_s"] == 2.0 and row["up"] == 4096.0
    assert row["burn"] == 1.5 and row["alarms"] == 1.0
    table = fedwatch.render_table(jobs)
    assert table.splitlines()[0].split()[:2] == ["job", "rounds"]
    assert "4096" not in table  # bytes render in MiB
    assert "0.00M" in table


def test_fedwatch_ledger_fallback(tmp_path):
    fedwatch = _load_script("fedwatch")
    base = str(tmp_path / "svc.jsonl")
    with open(base, "w") as f:
        f.write(json.dumps({"kind": "round", "round": 0,
                            "spans": {"t": 1.0}}) + "\n")
        f.write(json.dumps({"kind": "summary",
                            "alarm_fired": {"slo_burn": 3}}) + "\n")
    with open(base + ".job1.jsonl", "w") as f:
        for r in range(2):
            f.write(json.dumps({
                "kind": "round", "round": r, "spans": {"t": 0.5},
                "uplink_bytes": 100.0, "dp_epsilon": 0.5,
                "probes": {"slo_burn_max": 2.0}}) + "\n")
    jobs = fedwatch.ledger_table(base)
    assert jobs["service"]["rounds"] == 1
    assert jobs["service"]["alarms"] == 3
    assert jobs["1"]["rounds"] == 2 and jobs["1"]["up"] == 200.0
    assert jobs["1"]["burn"] == 2.0 and jobs["1"]["eps"] == 0.5
    assert "service" in fedwatch.render_table(jobs)
