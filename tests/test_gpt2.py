"""GPT-2 double-heads tests: shapes, loss masking, torch parity,
persona input building, end-to-end smoke."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.models.gpt2 import (GPT2Config, GPT2DoubleHeads,
                                           convert_torch_gpt2,
                                           gpt2_double_heads_loss)


class TestModel:
    def test_shapes(self):
        cfg = GPT2Config.tiny()
        m = GPT2DoubleHeads(cfg)
        B, N, T = 2, 2, 16
        ids = jnp.zeros((B, N, T), jnp.int32)
        mc = jnp.full((B, N), T - 1, jnp.int32)
        params = m.init(jax.random.PRNGKey(0), ids, mc, ids)["params"]
        lm, mcl = m.apply({"params": params}, ids, mc, ids)
        assert lm.shape == (B, N, T, cfg.vocab_size)
        assert mcl.shape == (B, N)

    def test_loss_ignores_masked_labels(self):
        lm = jnp.zeros((1, 1, 4, 8))
        mc = jnp.zeros((1, 1))
        labels_all_ignored = jnp.full((1, 1, 4), -1, jnp.int32)
        loss, lm_loss, _ = gpt2_double_heads_loss(
            lm, mc, labels_all_ignored, jnp.zeros((1,), jnp.int32),
            ignore_index=-1)
        assert float(lm_loss) == 0.0

    def test_causality(self):
        """Changing a future token must not affect past LM logits."""
        cfg = GPT2Config.tiny()
        m = GPT2DoubleHeads(cfg)
        B, N, T = 1, 1, 8
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, N, T)),
                          jnp.int32)
        mc = jnp.full((B, N), T - 1, jnp.int32)
        params = m.init(jax.random.PRNGKey(0), ids, mc, ids)["params"]
        lm1, _ = m.apply({"params": params}, ids, mc, ids)
        ids2 = ids.at[0, 0, -1].set((ids[0, 0, -1] + 1)
                                    % cfg.vocab_size)
        lm2, _ = m.apply({"params": params}, ids2, mc, ids2)
        np.testing.assert_allclose(lm1[0, 0, :-1], lm2[0, 0, :-1],
                                   atol=1e-5)


class TestTorchParity:
    def test_transformer_matches_hf_gpt2(self):
        """Random-init HF torch GPT-2 -> convert -> identical LM
        logits. Proves the checkpoint conversion path and the
        transformer math (layout, LN eps, gelu, causal mask)."""
        torch = pytest.importorskip("torch")
        from transformers import GPT2Config as HFConfig
        from transformers import GPT2LMHeadModel

        hf_cfg = HFConfig(vocab_size=128, n_positions=32, n_embd=16,
                          n_layer=2, n_head=2)
        torch.manual_seed(0)
        hf = GPT2LMHeadModel(hf_cfg).eval()

        cfg = GPT2Config(vocab_size=128, n_positions=32, n_embd=16,
                         n_layer=2, n_head=2)
        sd = {k: v.numpy() for k, v in hf.state_dict().items()}
        params = convert_torch_gpt2(sd, cfg)

        m = GPT2DoubleHeads(cfg)
        rng = np.random.RandomState(1)
        ids_np = rng.randint(0, 128, (2, 1, 16))
        with torch.no_grad():
            want = hf(torch.tensor(ids_np.reshape(2, 16))
                      ).logits.numpy()
        ids = jnp.asarray(ids_np, jnp.int32)
        mc = jnp.full((2, 1), 15, jnp.int32)
        lm, _ = m.apply({"params": {"params": params}["params"]},
                        ids, mc, None)
        got = np.asarray(lm[:, 0])
        np.testing.assert_allclose(got, want.reshape(2, 16, 128),
                                   rtol=2e-3, atol=2e-3)


class TestPersonaInputs:
    def test_build_input_from_segments(self):
        from commefficient_tpu.data.fed_persona import \
            build_input_from_segments
        from commefficient_tpu.data.tokenizer import (ByteTokenizer,
                                                      SPECIAL_TOKENS)
        tok = ByteTokenizer()
        tok.add_special_tokens(SPECIAL_TOKENS)
        bos, eos, s1, s2 = tok.convert_tokens_to_ids(
            SPECIAL_TOKENS[:-1])
        persona = [[10, 11]]
        history = [[20], [21]]
        reply = [30, 31]
        inst = build_input_from_segments(persona, history, reply, tok,
                                         lm_labels=True)
        # layout: [bos p p] [s1 20] [s2 21]... wait — speaker parity:
        # last segment (reply) gets speaker2, alternating backwards
        ids = inst["input_ids"]
        assert ids[0] == bos
        assert ids[-1] == eos
        assert inst["mc_token_ids"] == len(ids) - 1
        # lm labels: -1 everywhere except the reply tokens + eos
        # (reference fed_persona.py:354-357: [-1]*prefix + [-1] +
        # sequence[-1][1:], where sequence[-1] = [spk, *reply, eos])
        labels = inst["lm_labels"]
        n_prefix = len(ids) - (len(reply) + 1)
        assert all(l == -1 for l in labels[:n_prefix])
        assert labels[-(len(reply) + 1):] == [30, 31, eos]

    def test_build_input_golden_streams(self):
        """Hardcoded golden token streams for the serialization
        protocol (generated from the reference algorithm,
        fed_persona.py:330-358): exact ids/types/labels/mc positions,
        covering empty persona, empty history, odd/even history
        lengths (the type-vs-speaker parity quirk) and with_eos."""
        from commefficient_tpu.data.fed_persona import \
            build_input_from_segments
        from commefficient_tpu.data.tokenizer import (ByteTokenizer,
                                                      SPECIAL_TOKENS)
        tok = ByteTokenizer()
        tok.add_special_tokens(SPECIAL_TOKENS)
        golden = [
            (dict(persona=[[10, 11]], history=[[20], [21]],
                  reply=[30, 31], lm_labels=True, with_eos=True),
             [256, 10, 11, 259, 20, 258, 21, 259, 30, 31, 257],
             [258, 258, 258, 259, 259, 258, 258, 259, 259, 259, 259],
             [-1, -1, -1, -1, -1, -1, -1, -1, 30, 31, 257], 10),
            (dict(persona=[[10, 11], [12]],
                  history=[[20], [21], [22]], reply=[30],
                  lm_labels=False, with_eos=True),
             [256, 10, 11, 12, 258, 20, 259, 21, 258, 22, 259, 30,
              257],
             [258, 258, 258, 258, 259, 259, 258, 258, 259, 259, 258,
              258, 258],
             [-1] * 13, 12),
            (dict(persona=[[5]], history=[], reply=[7, 8, 9],
                  lm_labels=True, with_eos=False),
             [256, 5, 259, 7, 8, 9],
             [258, 258, 259, 259, 259, 259],
             [-1, -1, -1, 7, 8, 9], 5),
            (dict(persona=[], history=[[1], [2], [3], [4]],
                  reply=[6], lm_labels=True, with_eos=True),
             [256, 259, 1, 258, 2, 259, 3, 258, 4, 259, 6, 257],
             [258, 259, 259, 258, 258, 259, 259, 258, 258, 259, 259,
              259],
             [-1, -1, -1, -1, -1, -1, -1, -1, -1, -1, 6, 257], 11),
        ]
        for kw, ids, tt, lm, mc in golden:
            inst = build_input_from_segments(
                kw["persona"], kw["history"], kw["reply"], tok,
                lm_labels=kw["lm_labels"], with_eos=kw["with_eos"])
            assert inst["input_ids"] == ids
            assert inst["token_type_ids"] == tt
            assert inst["lm_labels"] == lm
            assert inst["mc_token_ids"] == mc

    def test_synthetic_archive_and_dataset(self, tmp_path):
        from commefficient_tpu.data.fed_persona import (
            FedPERSONA, generate_synthetic_personachat)
        from commefficient_tpu.data.tokenizer import (ByteTokenizer,
                                                      SPECIAL_TOKENS)
        generate_synthetic_personachat(str(tmp_path))
        tok = ByteTokenizer()
        tok.add_special_tokens(SPECIAL_TOKENS)
        ds = FedPERSONA(tok, 2, 2, 1, str(tmp_path), "PERSONA",
                        train=True)
        assert ds.num_clients == 8
        cid, *rest = ds[0]
        assert cid == 0
        assert len(rest) == 5
        val = FedPERSONA(tok, -1, 2, 1, str(tmp_path), "PERSONA",
                         train=False)
        assert val[0][0] == -1


class TestPersonaPrefetch:
    """PersonaFedLoader's background collation must be byte-identical
    to the synchronous path — every RNG stream in submission order
    (round-2 review weak #7: the prefetch BENCHMARKS promised now
    exists)."""

    def _stack(self, root, depth, epochs=2):
        from commefficient_tpu.data.fed_persona import FedPERSONA
        from commefficient_tpu.data.fed_sampler import FedSampler
        from commefficient_tpu.data.loader import PersonaFedLoader
        from commefficient_tpu.data.tokenizer import (ByteTokenizer,
                                                      SPECIAL_TOKENS)
        tok = ByteTokenizer()
        tok.add_special_tokens(SPECIAL_TOKENS)
        ds = FedPERSONA(tok, 2, 2, 1, root, "PERSONA", train=True,
                        seed=3)
        sampler = FedSampler(ds, num_workers=2, local_batch_size=2,
                             seed=3)
        loader = PersonaFedLoader(ds, sampler, 2, 64, 0,
                                  dropout_prob=0.3, dropout_seed=5,
                                  prefetch_depth=depth)
        out = []
        for _ in range(epochs):  # dataset _rng persists across epochs
            out.extend(list(loader))
        return out

    def test_identical_to_synchronous(self, tmp_path):
        from commefficient_tpu.data.fed_persona import (
            generate_synthetic_personachat)
        generate_synthetic_personachat(str(tmp_path))
        sync = self._stack(str(tmp_path), depth=1)
        pre = self._stack(str(tmp_path), depth=3)
        assert len(sync) == len(pre) and len(sync) > 2
        for a, b in zip(sync, pre):
            assert a.keys() == b.keys()
            for k in a:
                np.testing.assert_array_equal(a[k], b[k], err_msg=k)

    def test_abandoned_iteration_is_safe(self, tmp_path):
        """Breaking out mid-epoch (NaN abort) must retire the producer
        without deadlock, and a later fresh iteration still yields."""
        from commefficient_tpu.data.fed_persona import (
            FedPERSONA, generate_synthetic_personachat)
        from commefficient_tpu.data.fed_sampler import FedSampler
        from commefficient_tpu.data.loader import PersonaFedLoader
        from commefficient_tpu.data.tokenizer import (ByteTokenizer,
                                                      SPECIAL_TOKENS)
        generate_synthetic_personachat(str(tmp_path))
        tok = ByteTokenizer()
        tok.add_special_tokens(SPECIAL_TOKENS)
        ds = FedPERSONA(tok, 2, 2, 1, str(tmp_path), "PERSONA",
                        train=True)
        loader = PersonaFedLoader(
            ds, FedSampler(ds, num_workers=2, local_batch_size=2,
                           seed=0), 2, 64, 0, prefetch_depth=2)
        it = iter(loader)
        next(it)
        it.close()  # abandon
        again = list(loader)
        assert len(again) >= 1


class TestGpt2TrainSmoke:
    def test_end_to_end(self, tmp_path):
        from commefficient_tpu.train import gpt2_train
        results = gpt2_train.main([
            "--test", "--dataset_name", "PERSONA",
            "--dataset_dir", str(tmp_path),
            "--mode", "uncompressed", "--error_type", "none",
            "--local_momentum", "0", "--num_workers", "2",
            "--local_batch_size", "2", "--num_epochs", "1",
            "--lr_scale", "0.01",
        ])
        assert len(results) == 1
        assert np.isfinite(results[0]["train_loss"])
        assert np.isfinite(results[0]["val_ppl"])


class TestPretrainedLoadPath:
    """The reference's core GPT-2 story is fine-tuning a *pretrained*
    HF checkpoint (gpt2_train.py:262-285, incl. special-token
    embedding resize). Fabricate a random-weight HF-layout dir
    (pytorch_model.bin + vocab.json/merges.txt) and prove the whole
    disk path: tokenizer load, weight conversion, embedding resize,
    logits parity, and a federated round."""

    def _fabricate(self, d):
        torch = pytest.importorskip("torch")
        import json as _json

        from transformers import GPT2Config as HFConfig
        from transformers import GPT2LMHeadModel

        from commefficient_tpu.data.tokenizer import _bytes_to_unicode
        # byte-level vocab (the real GPT-2 vocab's first 256 entries)
        vocab = {ch: i for i, ch in
                 enumerate(_bytes_to_unicode().values())}
        with open(os.path.join(d, "vocab.json"), "w") as f:
            _json.dump(vocab, f)
        with open(os.path.join(d, "merges.txt"), "w") as f:
            f.write("#version: 0.2\n")
        hf_cfg = HFConfig(vocab_size=256, n_positions=256, n_embd=32,
                          n_layer=2, n_head=2)
        torch.manual_seed(7)
        hf = GPT2LMHeadModel(hf_cfg).eval()
        torch.save(hf.state_dict(),
                   os.path.join(d, "pytorch_model.bin"))
        return hf

    def test_disk_path_resize_and_logits(self, tmp_path):
        torch = pytest.importorskip("torch")
        from commefficient_tpu.config import Config
        from commefficient_tpu.data.tokenizer import GPT2BPETokenizer
        from commefficient_tpu.train.gpt2_train import \
            build_model_and_tokenizer

        hf = self._fabricate(str(tmp_path))
        args = Config(mode="uncompressed", error_type="none",
                      local_momentum=0.0, num_workers=1,
                      local_batch_size=2, num_clients=2,
                      dataset_name="PERSONA", seed=0, do_test=True,
                      model_checkpoint=str(tmp_path))
        module, params, tok = build_model_and_tokenizer(args)

        assert isinstance(tok, GPT2BPETokenizer)
        assert len(tok) == 256 + 5  # 5 special tokens added
        wte = np.asarray(params["transformer"]["wte"])
        assert wte.shape == (261, 32)
        base = hf.state_dict()["transformer.wte.weight"].numpy()
        np.testing.assert_array_equal(wte[:256], base)
        # resized rows are the mean of the base embedding (HF resize)
        np.testing.assert_allclose(
            wte[256:], np.tile(base.mean(0, keepdims=True), (5, 1)),
            rtol=1e-6)

        # logits parity on base-vocab ids through the loaded params
        rng = np.random.RandomState(3)
        ids_np = rng.randint(0, 256, (2, 1, 16))
        with torch.no_grad():
            want = hf(torch.tensor(ids_np.reshape(2, 16))
                      ).logits.numpy()
        lm, _ = module.apply({"params": params},
                             jnp.asarray(ids_np, jnp.int32),
                             jnp.full((2, 1), 15, jnp.int32), None)
        np.testing.assert_allclose(np.asarray(lm[:, 0])[..., :256],
                                   want.reshape(2, 16, 256),
                                   rtol=2e-3, atol=2e-3)

    def test_federated_round_from_pretrained(self, tmp_path):
        """One --test federated round starting from the fabricated HF
        checkpoint (reference gpt2_train.py round loop on a pretrained
        model)."""
        pytest.importorskip("torch")
        from commefficient_tpu.data.fed_persona import \
            generate_synthetic_personachat
        from commefficient_tpu.train import gpt2_train

        ckpt = tmp_path / "ckpt"
        data = tmp_path / "data"
        ckpt.mkdir()
        data.mkdir()
        self._fabricate(str(ckpt))
        generate_synthetic_personachat(str(data))
        results = gpt2_train.main([
            "--test", "--dataset_name", "PERSONA",
            "--dataset_dir", str(data),
            "--model_checkpoint", str(ckpt),
            "--mode", "uncompressed", "--error_type", "none",
            "--local_momentum", "0", "--num_workers", "2",
            "--local_batch_size", "2", "--num_epochs", "1",
            "--lr_scale", "0.01",
        ])
        assert np.isfinite(results[0]["train_loss"])
        assert np.isfinite(results[0]["val_ppl"])


class TestFullCandidateValidation:
    """Reference restricts candidates only when *training*
    (fed_persona.py:251-254): val MC accuracy is measured over the
    item's full candidate list, not num_candidates."""

    def _val_ds(self, tmp_path, n_cands):
        from commefficient_tpu.data.fed_persona import (
            FedPERSONA, generate_synthetic_personachat)
        from commefficient_tpu.data.tokenizer import (ByteTokenizer,
                                                      SPECIAL_TOKENS)
        generate_synthetic_personachat(str(tmp_path),
                                       num_candidates=n_cands)
        tok = ByteTokenizer()
        tok.add_special_tokens(SPECIAL_TOKENS)
        # num_candidates=2 restriction must NOT apply to val items
        return FedPERSONA(tok, 2, 2, 1, str(tmp_path), "PERSONA",
                          train=False)

    def test_val_items_keep_all_candidates(self, tmp_path):
        ds = self._val_ds(tmp_path, n_cands=5)
        cid, input_ids, mc_tok, lm_lab, mc_lab, tt = ds[0]
        assert cid == -1
        assert len(input_ids) == 5          # all candidates kept
        assert mc_lab == 4                  # gold is last

    def test_val_loader_pads_and_masks(self, tmp_path):
        from commefficient_tpu.data.loader import PersonaValLoader
        ds = self._val_ds(tmp_path, n_cands=5)
        loader = PersonaValLoader(ds, 2, 8, 64, pad_id=0,
                                  shards_per_step=1)
        batch = next(iter(loader))
        assert batch["input_ids"].shape[2] == 8
        # real rows: 5 valid candidate slots, 3 padded; gold index 4
        rows = np.nonzero(batch["mask"])
        np.testing.assert_array_equal(
            batch["cand_mask"][rows][:, :5], 1.0)
        np.testing.assert_array_equal(
            batch["cand_mask"][rows][:, 5:], 0.0)
        np.testing.assert_array_equal(batch["mc_labels"][rows], 4)

    def test_mc_argmax_never_picks_padded_slot(self):
        """compute_loss_val masks mc_logits with cand_mask: a padded
        slot carrying the max raw logit must not be predicted."""
        import jax.numpy as jnp

        from commefficient_tpu.config import Config
        from commefficient_tpu.train.gpt2_train import \
            make_compute_loss_val

        from commefficient_tpu.models.gpt2 import GPT2Config

        class StubModule:
            cfg = GPT2Config.tiny()

            def apply(self, variables, input_ids, mc_token_ids,
                      token_type_ids, return_hidden=False):
                assert return_hidden
                B, N, T = input_ids.shape
                h = jnp.zeros((B * N, T, 8), jnp.float32)
                wte = jnp.zeros((16, 8), jnp.float32)
                mc = jnp.zeros((B, N), jnp.float32)
                mc = mc.at[..., -1].set(10.0)  # padded slot: max
                mc = mc.at[..., 1].set(5.0)    # gold slot: runner-up
                return h, wte, mc

        args = Config(mode="uncompressed", error_type="none",
                      local_momentum=0.0, num_workers=1,
                      local_batch_size=2, num_clients=2,
                      dataset_name="PERSONA", seed=0)
        loss_fn = make_compute_loss_val(StubModule(), args)
        B, N, T = 2, 4, 8
        batch = {
            "input_ids": np.zeros((B, N, T), np.int32),
            "token_type_ids": np.zeros((B, N, T), np.int32),
            "lm_labels": np.full((B, N, T), -1, np.int32),
            "mc_token_ids": np.zeros((B, N), np.int32),
            "mc_labels": np.full((B,), 1, np.int32),
            "cand_mask": np.zeros((B, N), np.float32),
            "mask": np.ones((B,), np.float32),
        }
        batch["cand_mask"][..., :2] = 1.0  # only slots 0,1 are real
        _, (acc,) = loss_fn(None, batch, None)
        assert float(acc) == 1.0  # masked argmax lands on gold (1)
        # without the mask the padded slot 3 would win and acc = 0
        del batch["cand_mask"]
        _, (acc_unmasked,) = loss_fn(None, batch, None)
        assert float(acc_unmasked) == 0.0

    def test_end_to_end_full_candidates(self, tmp_path):
        """A random-init --test run over a 5-candidate archive: val MC
        accuracy is measured over all 5 (chance ~1/5, and certainly
        below the 2-candidate chance of 1/2 it used to report)."""
        from commefficient_tpu.data.fed_persona import \
            generate_synthetic_personachat
        from commefficient_tpu.train import gpt2_train
        generate_synthetic_personachat(str(tmp_path), num_candidates=5)
        results = gpt2_train.main([
            "--test", "--dataset_name", "PERSONA",
            "--dataset_dir", str(tmp_path),
            "--mode", "uncompressed", "--error_type", "none",
            "--local_momentum", "0", "--num_workers", "2",
            "--local_batch_size", "2", "--num_epochs", "1",
            "--lr_scale", "0.0",
        ])
        assert 0.0 <= results[0]["val_acc"] <= 0.45


class TestRemat:
    @pytest.mark.xfail(
        strict=False,
        reason="jax 0.4.x remat reschedules the backward: one grad "
               "element lands ~3e-6 off, past the 1e-6 identity "
               "tolerance; exact on current jax")
    def test_remat_identical_outputs_and_grads(self):
        """--remat must not change the math — same forward logits and
        same gradients, only the backward's memory/FLOP schedule."""
        import dataclasses

        import jax
        import jax.numpy as jnp

        from commefficient_tpu.models.gpt2 import (GPT2Config,
                                                   GPT2DoubleHeads)

        cfg = GPT2Config.tiny()
        rng = np.random.RandomState(0)
        B, N, T = 2, 2, 10
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, N, T)),
                          jnp.int32)
        mc = jnp.asarray(rng.randint(0, T, (B, N)), jnp.int32)
        base = GPT2DoubleHeads(cfg)
        remat = GPT2DoubleHeads(dataclasses.replace(cfg, remat=True))
        params = base.init(jax.random.PRNGKey(0), ids, mc)["params"]

        lm0, mc0 = base.apply({"params": params}, ids, mc)
        lm1, mc1 = remat.apply({"params": params}, ids, mc)
        np.testing.assert_array_equal(np.asarray(lm0), np.asarray(lm1))
        np.testing.assert_array_equal(np.asarray(mc0), np.asarray(mc1))

        def loss(module, p):
            lm, _ = module.apply({"params": p}, ids, mc)
            return jnp.sum(lm ** 2)

        g0 = jax.grad(lambda p: loss(base, p))(params)
        g1 = jax.grad(lambda p: loss(remat, p))(params)
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-6)


class TestBatchedTrainLoss:
    def test_matches_per_example_double_heads_loss(self):
        """The batched train loss must equal the mask-weighted mean of
        gpt2_double_heads_loss applied example by example (the
        formulation it replaced for speed)."""
        import jax
        import jax.numpy as jnp

        from commefficient_tpu.config import Config
        from commefficient_tpu.models.gpt2 import (
            GPT2Config, GPT2DoubleHeads, gpt2_double_heads_loss)
        from commefficient_tpu.train.gpt2_train import (
            make_compute_loss_train)

        cfg = Config(mode="uncompressed", error_type="none",
                     local_momentum=0.0, num_workers=2,
                     local_batch_size=2, num_clients=4,
                     dataset_name="PERSONA", seed=0,
                     lm_coef=2.0, mc_coef=0.5)
        gcfg = GPT2Config.tiny()
        module = GPT2DoubleHeads(gcfg)
        rng = np.random.RandomState(0)
        B, N, T = 3, 2, 12
        batch = {
            "input_ids": jnp.asarray(
                rng.randint(0, gcfg.vocab_size, (B, N, T)), jnp.int32),
            "token_type_ids": jnp.asarray(
                rng.randint(0, gcfg.vocab_size, (B, N, T)), jnp.int32),
            "lm_labels": jnp.asarray(np.where(
                rng.rand(B, N, T) < 0.3, -1,
                rng.randint(0, gcfg.vocab_size, (B, N, T))), jnp.int32),
            "mc_token_ids": jnp.asarray(rng.randint(0, T, (B, N)),
                                        jnp.int32),
            "mc_labels": jnp.asarray(rng.randint(0, N, (B,)),
                                     jnp.int32),
            "mask": jnp.asarray([1.0, 1.0, 0.0]),
        }
        params = module.init(jax.random.PRNGKey(0),
                             batch["input_ids"],
                             batch["mc_token_ids"],
                             batch["input_ids"])["params"]
        got, _ = make_compute_loss_train(module, cfg)(params, batch,
                                                      cfg)

        lm_logits, mc_logits = module.apply(
            {"params": params}, batch["input_ids"],
            batch["mc_token_ids"], batch["token_type_ids"])
        per = []
        for i in range(B):
            loss_i, _, _ = gpt2_double_heads_loss(
                lm_logits[i:i + 1], mc_logits[i:i + 1],
                batch["lm_labels"][i:i + 1],
                batch["mc_labels"][i:i + 1],
                lm_coef=cfg.lm_coef, mc_coef=cfg.mc_coef,
                ignore_index=-1)
            per.append(float(loss_i))
        m = np.asarray(batch["mask"])
        want = float(np.sum(np.asarray(per) * m) / m.sum())
        np.testing.assert_allclose(float(got), want, rtol=2e-5)

    def test_chunked_lm_loss_gradients_match_full_logits(self):
        """Gradients through the chunked (scan + checkpoint) LM loss
        must match gradients of the same loss computed from full
        logits — the chunking is a memory schedule, not new math."""
        import jax
        import jax.numpy as jnp

        from commefficient_tpu.models.gpt2 import (
            GPT2Config, GPT2DoubleHeads, lm_nll_sums_chunked,
            token_nll)

        gcfg = GPT2Config.tiny()
        module = GPT2DoubleHeads(gcfg)
        rng = np.random.RandomState(1)
        B, N, T = 2, 2, 14  # T-1=13, tc=2: pad=1 exercises padding
        ids = jnp.asarray(rng.randint(0, gcfg.vocab_size, (B, N, T)),
                          jnp.int32)
        mc = jnp.asarray(rng.randint(0, T, (B, N)), jnp.int32)
        labels = jnp.asarray(np.where(
            rng.rand(B * N, T) < 0.3, -1,
            rng.randint(0, gcfg.vocab_size, (B * N, T))), jnp.int32)
        params = module.init(jax.random.PRNGKey(0), ids, mc,
                             ids)["params"]

        def loss_chunked(p):
            h, wte, _ = module.apply({"params": p}, ids, mc, ids,
                                     return_hidden=True)
            sn, sv = lm_nll_sums_chunked(h[:, :-1], wte,
                                         labels[:, 1:], gcfg.dtype,
                                         ignore_index=-1,
                                         tokens_per_chunk=8)
            return jnp.sum(sn) / jnp.maximum(jnp.sum(sv), 1.0)

        def loss_full(p):
            h, wte, _ = module.apply({"params": p}, ids, mc, ids,
                                     return_hidden=True)
            logits = jnp.einsum("btc,vc->btv",
                                h[:, :-1].astype(gcfg.dtype),
                                wte.astype(gcfg.dtype),
                                preferred_element_type=jnp.float32)
            nll, valid = token_nll(logits, labels[:, 1:], -1)
            return jnp.sum(nll * valid) \
                / jnp.maximum(jnp.sum(valid), 1.0)

        lc, gc = jax.value_and_grad(loss_chunked)(params)
        lf, gf = jax.value_and_grad(loss_full)(params)
        np.testing.assert_allclose(float(lc), float(lf), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(gc),
                        jax.tree_util.tree_leaves(gf)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-6)


class TestFabricatedAssets:
    """The full-size learning-run stand-ins (zero-egress environment):
    the fabricated 50257-entry BPE vocab and the learnable
    persona-correlated corpus. Their invariants are load-bearing for
    the convergence evidence — the NLL floor math assumes every
    synthetic word is ONE token, and MC learnability assumes the gold
    candidate is last and shares the persona's signature."""

    @staticmethod
    def _dialog_signature(dialog):
        # reconstruct the signature: persona, history and gold replies
        # all draw from the SAME signature_size-word set
        words = {w for s in dialog["personality"] for w in s.split()}
        for u in dialog["utterances"]:
            words |= set(u["candidates"][-1].split())
            for h in u["history"]:
                words |= set(h.split())
        return frozenset(words)

    def test_fabricated_vocab_single_token_words(self, tmp_path):
        import random

        from commefficient_tpu.data.tokenizer import (GPT2BPETokenizer,
                                                      SPECIAL_TOKENS,
                                                      fabricate_bpe_vocab)
        words = fabricate_bpe_vocab(str(tmp_path), vocab_size=50257,
                                    num_words=500, seed=3)
        tok = GPT2BPETokenizer(str(tmp_path))
        assert len(tok) == 50257
        assert tok.add_special_tokens(SPECIAL_TOKENS) == 5
        assert len(tok) == 50262  # the reference fine-tune vocab size
        rng = random.Random(0)
        sample = rng.sample(words, 40)
        ids = set()
        for w in sample:
            bare, spaced = tok.encode(w), tok.encode(" " + w)
            assert len(bare) == 1 and len(spaced) == 1, w
            ids.update(bare + spaced)
        assert len(ids) == 80  # distinct tokens, bare != spaced
        # ids spread across the table, not a dense prefix
        assert max(ids) - min(ids) > 25000
        # decode round-trips through the byte table
        s = " ".join(sample[:5])
        assert tok.decode(tok.encode(s)) == s

    def test_learnable_corpus_structure(self, tmp_path):
        import json

        from commefficient_tpu.data.fed_persona import (
            RAW_NAME, generate_learnable_personachat)
        words = [a + b for a in ("ba", "ke", "lu", "mi", "po", "su")
                 for b in ("da", "fe", "go", "ni", "ra", "tu")]
        generate_learnable_personachat(
            str(tmp_path), words, num_personalities=6,
            dialogs_per_personality=2, utterances_per_dialog=3,
            num_candidates=4, signature_size=5, num_val_dialogs=4,
            seed=0)
        with open(tmp_path / RAW_NAME) as f:
            data = json.load(f)
        assert len(data["train"]) == 12 and len(data["valid"]) == 4

        sig_of = self._dialog_signature
        train_sigs, val_sigs = [], []
        for split, sigs in (("train", train_sigs),
                            ("valid", val_sigs)):
            for d in data[split]:
                sig = sig_of(d)
                # everything the persona says fits one signature set
                assert len(sig) <= 5, sorted(sig)
                sigs.append(sig)
                for u in d["utterances"]:
                    cands = u["candidates"]
                    assert len(cands) == 4
                    # gold last, drawn from the persona signature
                    assert set(cands[-1].split()) <= sig
        # val personalities are UNSEEN in training (the rule, not the
        # strings, is what validation measures)
        assert not set(train_sigs) & set(val_sigs)

    def test_seen_persona_val_tier(self, tmp_path):
        """val_from_train_sigs=True: train split byte-identical to the
        default corpus (same seed), val dialogs reuse TRAIN
        signatures — the easier seen-persona evaluation tier."""
        import json

        from commefficient_tpu.data.fed_persona import (
            RAW_NAME, generate_learnable_personachat)
        words = [a + b for a in ("ba", "ke", "lu", "mi")
                 for b in ("da", "fe", "go", "ni")]
        kw = dict(num_personalities=4, dialogs_per_personality=2,
                  utterances_per_dialog=2, num_candidates=3,
                  signature_size=4, num_val_dialogs=4, seed=5)
        generate_learnable_personachat(str(tmp_path / "a"), words,
                                       **kw)
        generate_learnable_personachat(str(tmp_path / "b"), words,
                                       val_from_train_sigs=True, **kw)
        a = json.load(open(tmp_path / "a" / RAW_NAME))
        b = json.load(open(tmp_path / "b" / RAW_NAME))
        assert a["train"] == b["train"]

        train_sigs = [self._dialog_signature(d) for d in b["train"]]
        for d in b["valid"]:
            v = self._dialog_signature(d)
            assert any(v <= t for t in train_sigs), sorted(v)


def test_trainer_losses_thread_tokens_per_chunk(monkeypatch):
    """--tokens_per_chunk reaches the chunked vocab CE from BOTH
    trainer loss closures (0 = the 1024 auto default)."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.config import Config
    from commefficient_tpu.models import gpt2 as gpt2_mod
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads
    from commefficient_tpu.train.gpt2_train import (
        make_compute_loss_train, make_compute_loss_val)

    seen = []
    orig = gpt2_mod.lm_nll_sums_chunked

    def capture(h, wte, labels, dtype, ignore_index=-100,
                tokens_per_chunk=1024):
        seen.append(tokens_per_chunk)
        return orig(h, wte, labels, dtype, ignore_index=ignore_index,
                    tokens_per_chunk=tokens_per_chunk)

    monkeypatch.setattr(gpt2_mod, "lm_nll_sums_chunked", capture)

    gcfg = GPT2Config.tiny()
    module = GPT2DoubleHeads(gcfg)
    rng = np.random.RandomState(0)
    B, N, T = 2, 2, 12
    batch = {
        "input_ids": jnp.asarray(
            rng.randint(0, gcfg.vocab_size, (B, N, T)), jnp.int32),
        "token_type_ids": jnp.zeros((B, N, T), jnp.int32),
        "lm_labels": jnp.asarray(
            rng.randint(0, gcfg.vocab_size, (B, N, T)), jnp.int32),
        "mc_token_ids": jnp.full((B, N), T - 1, jnp.int32),
        "mc_labels": jnp.full((B,), N - 1, jnp.int32),
        "mask": jnp.ones((B,), jnp.float32),
        "cand_mask": jnp.ones((B, N), jnp.float32),
    }
    params = module.init(jax.random.PRNGKey(0), batch["input_ids"],
                         batch["mc_token_ids"],
                         batch["token_type_ids"])["params"]

    base = Config(mode="uncompressed", error_type="none",
                  local_momentum=0.0, num_workers=1,
                  local_batch_size=2, dataset_name="PERSONA")
    ref, _ = make_compute_loss_train(module, base)(params, batch, base)
    assert seen and all(c == 1024 for c in seen)  # 0 -> auto 1024

    import dataclasses
    args = dataclasses.replace(base, tokens_per_chunk=6)
    seen.clear()
    got, _ = make_compute_loss_train(module, args)(params, batch, args)
    assert seen and all(c == 6 for c in seen)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    seen.clear()
    make_compute_loss_val(module, args)(params, batch, args)
    assert seen and all(c == 6 for c in seen)


class TestSavePretrained:
    def test_model_and_tokenizer_roundtrip(self, tmp_path):
        """reference fed_aggregator.py:205-212 / gpt2_train.py:278-283:
        final weights + config + tokenizer written HF-style; weights
        and special-token ids survive a reload."""
        import jax
        import jax.numpy as jnp
        from flax import serialization

        from commefficient_tpu.config import Config
        from commefficient_tpu.data.tokenizer import (ByteTokenizer,
                                                      SPECIAL_TOKENS)
        from commefficient_tpu.models.gpt2 import (GPT2Config,
                                                   GPT2DoubleHeads)
        from commefficient_tpu.runtime import FedModel

        cfg = GPT2Config.tiny()
        module = GPT2DoubleHeads(cfg)
        dummy = jnp.zeros((1, 2, 8), jnp.int32)
        params = module.init(jax.random.PRNGKey(0), dummy,
                             jnp.zeros((1, 2), jnp.int32),
                             dummy)["params"]
        args = Config(mode="uncompressed", error_type="none",
                      local_momentum=0.0, num_workers=2,
                      local_batch_size=2, num_clients=4,
                      dataset_name="PERSONA", seed=0)

        def loss(p, batch, cfg_):
            return jnp.float32(0.0), ()

        model = FedModel(module, params, loss, args)
        out = tmp_path / "saved"
        model.save_pretrained(str(out))
        assert (out / "config.json").exists()
        with open(out / "flax_model.msgpack", "rb") as f:
            restored = serialization.msgpack_restore(f.read())
        flat0 = jax.tree_util.tree_leaves(model.params())
        flat1 = jax.tree_util.tree_leaves(restored)
        assert len(flat0) == len(flat1)
        for a, b in zip(flat0, flat1):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        tok = ByteTokenizer()
        tok.add_special_tokens(SPECIAL_TOKENS)
        tok.save_pretrained(str(out))
        assert (out / "special_tokens.json").exists()

    def test_hf_export_roundtrip_transformers_logits(self, tmp_path):
        """hf_format export (round-2 review missing #2): train a
        federated round, export pytorch_model.bin + HF config, load
        with the real `transformers` GPT2DoubleHeadsModel, and match
        both LM and MC logits — the artifact goes back to the torch/HF
        ecosystem like the reference's save_pretrained
        (fed_aggregator.py:209-212)."""
        torch = pytest.importorskip("torch")
        from transformers import GPT2DoubleHeadsModel

        import jax
        import jax.numpy as jnp

        from commefficient_tpu.config import Config
        from commefficient_tpu.models.gpt2 import (GPT2Config,
                                                   GPT2DoubleHeads)
        from commefficient_tpu.runtime import FedModel, FedOptimizer
        from commefficient_tpu.train.gpt2_train import (
            make_compute_loss_train)

        cfg = GPT2Config(vocab_size=128, n_positions=32, n_embd=16,
                         n_layer=2, n_head=2)
        module = GPT2DoubleHeads(cfg)
        B, N, T = 2, 2, 16
        dummy = jnp.zeros((1, N, 8), jnp.int32)
        params = module.init(jax.random.PRNGKey(0), dummy,
                             jnp.zeros((1, N), jnp.int32),
                             dummy)["params"]
        args = Config(mode="uncompressed", error_type="none",
                      local_momentum=0.0, virtual_momentum=0.9,
                      num_workers=2, local_batch_size=B,
                      num_clients=4, dataset_name="PERSONA", seed=0,
                      num_results_train=1)
        model = FedModel(module, params,
                         make_compute_loss_train(module, args), args)
        opt = FedOptimizer([{"lr": 0.01}], args)

        rng = np.random.RandomState(0)
        ids_np = rng.randint(0, 128, (2, B, N, T)).astype(np.int32)
        batch = {
            "input_ids": ids_np,
            "token_type_ids": rng.randint(
                0, 128, (2, B, N, T)).astype(np.int32),
            "lm_labels": ids_np.copy(),
            "mc_token_ids": np.full((2, B, N), T - 1, np.int32),
            "mc_labels": rng.randint(0, N, (2, B)).astype(np.int32),
            "mask": np.ones((2, B), np.float32),
            "client_ids": np.array([0, 1], np.int32),
        }
        model(batch)
        opt.step()  # weights move: the export is of a TRAINED model

        out = tmp_path / "hf"
        model.save_pretrained(str(out), hf_format=True)
        assert (out / "pytorch_model.bin").exists()

        hf = GPT2DoubleHeadsModel.from_pretrained(str(out)).eval()
        ids2 = rng.randint(0, 128, (B, N, T)).astype(np.int32)
        tt2 = rng.randint(0, 128, (B, N, T)).astype(np.int32)
        mc2 = np.full((B, N), T - 1, np.int32)
        with torch.no_grad():
            hf_out = hf(torch.tensor(ids2.astype(np.int64)),
                        token_type_ids=torch.tensor(
                            tt2.astype(np.int64)),
                        mc_token_ids=torch.tensor(
                            mc2.astype(np.int64)))
        lm, mc = module.apply({"params": model.params()},
                              jnp.asarray(ids2),
                              jnp.asarray(mc2), jnp.asarray(tt2))
        np.testing.assert_allclose(np.asarray(lm),
                                   hf_out.logits.numpy(),
                                   rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(np.asarray(mc),
                                   hf_out.mc_logits.numpy(),
                                   rtol=2e-3, atol=2e-3)

        # and the framework's own reload path reads the same dir
        from commefficient_tpu.models.gpt2 import convert_torch_gpt2
        sd = {k: v.numpy()
              for k, v in torch.load(str(out / "pytorch_model.bin"),
                                     map_location="cpu").items()}
        p2 = convert_torch_gpt2(sd, cfg)
        for a, b in zip(
                jax.tree_util.tree_leaves(
                    model.params()["transformer"]),
                jax.tree_util.tree_leaves(p2["transformer"])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

    def test_bpe_tokenizer_roundtrip(self, tmp_path):
        """Saved vocab/merges/special files reload into an equivalent
        tokenizer (self-contained run dirs)."""
        import json

        from commefficient_tpu.data.tokenizer import (GPT2BPETokenizer,
                                                      SPECIAL_TOKENS)

        vocab = {"l": 0, "o": 1, "w": 2, "lo": 3, "low": 4, "Ġ": 5}
        (tmp_path / "vocab.json").write_text(json.dumps(vocab))
        (tmp_path / "merges.txt").write_text(
            "#version: 0.2\nl o\nlo w")
        tok = GPT2BPETokenizer(str(tmp_path))
        tok.add_special_tokens(SPECIAL_TOKENS)
        out = tmp_path / "saved"
        tok.save_pretrained(str(out))
        tok2 = GPT2BPETokenizer(str(out))
        assert tok2.encoder == tok.encoder
        assert tok2.bpe_ranks == tok.bpe_ranks
        assert tok2.special == tok.special
        assert tok2.encode("low") == tok.encode("low")
        assert len(tok2) == len(tok)
