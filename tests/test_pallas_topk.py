"""Fused take-mask Pallas kernel vs the XLA threshold mask — exactly
k selected, identical sets including lowest-index tie-breaks. On CPU
the kernel runs in interpreter mode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.ops.topk import (_nibble_threshold_key,
                                        _threshold_topk_mask,
                                        threshold_topk_mask_1d)
from commefficient_tpu.ops.topk_pallas import _CHUNK


def _mask_via_kernel(sq, k):
    # the shipped path, with its interpret hook (so the same branch
    # selection and need-computation is under test, not a copy)
    return threshold_topk_mask_1d(sq, k, interpret=True)


@pytest.mark.parametrize("d,k", [(_CHUNK, 100), (_CHUNK + 7, 513),
                                 (3 * _CHUNK + 11, 5000)])
def test_kernel_matches_xla_mask(d, k):
    rng = np.random.RandomState(d % 97)
    x = rng.randn(d).astype(np.float32)
    x[rng.randint(0, d, 200)] = 1.5  # magnitude ties
    x[rng.randint(0, d, 200)] = 0.0
    sq = jnp.square(jnp.asarray(x))
    got = np.asarray(_mask_via_kernel(sq, k))
    want = np.asarray(_threshold_topk_mask(sq, k))
    assert got.sum() == k
    np.testing.assert_array_equal(got, want)


def test_kernel_all_equal_ties():
    """All-equal input: exactly the first k indices, across chunk
    boundaries (the SMEM rank carry)."""
    d, k = 2 * _CHUNK, _CHUNK + 17
    got = np.asarray(_mask_via_kernel(jnp.ones(d, jnp.float32), k))
    assert got.sum() == k
    assert got[:k].all() and not got[k:].any()


def test_kernel_zero_threshold_edge():
    """k exceeds the nonzero count: T == 0, the padded zeros beyond d
    must never be selected over real zeros."""
    d = _CHUNK + 100  # forces padding
    k = d - 3
    rng = np.random.RandomState(9)
    x = np.zeros(d, np.float32)
    nz = rng.choice(d, 50, replace=False)
    x[nz] = rng.randn(50)
    sq = jnp.square(jnp.asarray(x))
    got = np.asarray(_mask_via_kernel(sq, k))
    want = np.asarray(_threshold_topk_mask(sq, k))
    assert got.sum() == k
    np.testing.assert_array_equal(got, want)


@pytest.mark.xfail(
    strict=False,
    reason="jax 0.4.x Mosaic lowering refuses non-interpret "
           "pallas_call when the process backend is CPU, so the "
           "cross-platform lower(lowering_platforms=('tpu',)) probe "
           "cannot run; works on current jax / real TPU")
def test_branch_selected_at_lowering_not_trace():
    """The Pallas-vs-XLA branch is a lax.platform_dependent, decided
    per LOWERING platform — not frozen from jax.default_backend() at
    trace time (round-4 advisor: a jit(..., backend=...) override or
    multi-backend process must not silently trace the wrong branch).
    One trace, lowered for cpu and for tpu: the cpu module must hold
    the XLA mask (no Mosaic custom-call), the tpu module the kernel."""
    d, k = _CHUNK, 100
    sq = jnp.square(jnp.asarray(
        np.random.RandomState(0).randn(d).astype(np.float32)))
    traced = jax.jit(
        lambda v: threshold_topk_mask_1d(v, k)).trace(sq)
    cpu_txt = traced.lower(lowering_platforms=("cpu",)).as_text()
    tpu_txt = traced.lower(lowering_platforms=("tpu",)).as_text()
    assert "tpu_custom_call" not in cpu_txt
    assert "tpu_custom_call" in tpu_txt
    # and the cpu lowering executes correctly end to end
    got = np.asarray(jax.jit(
        lambda v: threshold_topk_mask_1d(v, k), backend="cpu")(sq))
    want = np.asarray(_threshold_topk_mask(sq, k))
    assert got.sum() == k
    np.testing.assert_array_equal(got, want)


def test_nibble_search_matches_bit_search():
    from commefficient_tpu.ops.topk import _blocked_cumsum  # noqa: F401

    rng = np.random.RandomState(3)
    for d, k in ((4096, 17), (100000, 5000), (5000, 4999)):
        x = rng.randn(d).astype(np.float32)
        x[rng.randint(0, d, 60)] = 2.5
        sq = jnp.square(jnp.asarray(x))
        keys = jax.lax.bitcast_convert_type(sq, jnp.uint32)

        def bit32(keys, k):
            def body(i, t):
                bit = jnp.uint32(31) - i.astype(jnp.uint32)
                cand = t | (jnp.uint32(1) << bit)
                cnt = jnp.sum((keys >= cand).astype(jnp.int32))
                return jnp.where(cnt >= k, cand, t)
            return jax.lax.fori_loop(0, 32, body, jnp.uint32(0))

        assert int(_nibble_threshold_key(keys, k)) == int(bit32(keys, k))
