"""Differentially-private sketching (``--dp sketch``) and the ε/δ
accountant (privacy/): the in-round mechanism against the NumPy
mirror, the RDP composition against an independently-restated
reference (exact integer binomials, to 1e-6 over 100+ rounds), and
the runtime lifecycle — per-dispatch charging, schema-v5 ledger
stamping, budget abort at the predicted round, checkpoint
continuity — against closed-form predictions."""

import dataclasses
import json
import math
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import Config
from commefficient_tpu.core.robust import _TINY, clip_factors, robust_fold
from commefficient_tpu.core.rounds import (ClientStates, args2sketch,
                                           build_client_round)
from commefficient_tpu.privacy import (PrivacyAccountant,
                                       add_table_noise, build_accountant,
                                       dp_clip, np_dp_clip, np_dp_noise,
                                       round_noise_key, sample_rate_of,
                                       steps_to_budget, table_noise_std)
from commefficient_tpu.privacy.accountant import DEFAULT_ORDERS
from commefficient_tpu.privacy.mechanism import table_sensitivity

from reference_mirror import MirrorFed, np_clip_factors
from test_modes import linear_loss, make_cfg, run_engine


# ------------------------------------------------------------------ #
# independent accountant mirror: exact integer binomials (math.comb) #
# instead of the accountant's lgamma route, log1p(-1/α) instead of   #
# log((α-1)/α) — same math, different code, so a transcription bug   #
# in either cannot self-verify.                                      #
# ------------------------------------------------------------------ #

def mirror_rdp(q, sigma, alpha):
    if sigma <= 0:
        return math.inf
    if q <= 0:
        return 0.0
    if q >= 1:
        return alpha / (2.0 * sigma * sigma)
    logs = [math.log(math.comb(alpha, k))
            + (alpha - k) * math.log(1.0 - q)
            + (k * math.log(q) if k else 0.0)
            + k * (k - 1) / (2.0 * sigma * sigma)
            for k in range(alpha + 1)]
    m = max(logs)
    return (m + math.log(sum(math.exp(t - m) for t in logs))) \
        / (alpha - 1)


def mirror_epsilon(q, sigma, delta, weights):
    """ε after charging one round per entry of ``weights`` (the fold
    weight scale w: effective noise multiplier σ/w)."""
    best = math.inf
    for a in DEFAULT_ORDERS:
        tot = sum(mirror_rdp(q, sigma / w, a) for w in weights)
        if not math.isfinite(tot):
            continue
        eps = (tot + math.log1p(-1.0 / a)
               - (math.log(delta) + math.log(a)) / (a - 1))
        best = min(best, max(eps, 0.0))
    return best


def dp_cfg(**kw):
    base = dict(mode="sketch", error_type="virtual", k=4,
                num_rows=5, num_cols=64, dp="sketch",
                dp_clip=0.5, dp_noise_mult=0.3)
    base.update(kw)
    return make_cfg(**base)


def rounds_data(seed=0, n_rounds=3, d=8, num_clients=4, W=2, B=3):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n_rounds):
        ids = rng.choice(num_clients, W, replace=False)
        out.append([(int(cid), rng.randn(B, d).astype(np.float32),
                     rng.randn(B).astype(np.float32)) for cid in ids])
    return out


def run_mirror_dp(cfg, w0, rounds, lr, num_clients=4):
    """MirrorFed with the engine's per-round keys threaded in, so the
    mirror's noise draw is the SAME bits as the engine's."""
    cfg = dataclasses.replace(cfg, grad_size=len(w0))
    m = MirrorFed(cfg, w0, num_clients, sketch=args2sketch(cfg))
    rng = jax.random.PRNGKey(cfg.seed)
    return [m.round(r, lr, rng=jax.random.fold_in(rng, i))
            for i, r in enumerate(rounds)]


W0 = [0.0, 0.5, -0.3, 0.1, 0.0, 0.2, -0.1, 0.05]


class TestClipAlgebra:
    """One clip helper for the robust fold AND the DP clip — pinned
    bit-identical to the pre-refactor inline formula."""

    def test_clip_factors_pins_prerefactor_formula(self):
        norms = jnp.asarray([0.0, 1e-13, 0.3, 1.0, 7.5], jnp.float32)
        for tau in (0.1, 1.0, 4.0):
            want = jnp.minimum(1.0, jnp.float32(tau)
                               / jnp.maximum(norms, 1e-12))
            np.testing.assert_array_equal(
                np.asarray(clip_factors(norms, jnp.float32(tau))),
                np.asarray(want))

    def test_robust_clip_fold_bit_identical(self):
        """The full robust clip fold vs the pre-refactor algebra
        restated inline (same jnp ops in the same order) — the
        clip_factors extraction must be invisible at the bit level."""
        cfg = make_cfg(robust_agg="clip", robust_clip_norm=0.5)
        rng = np.random.RandomState(3)
        W, B, d = 4, 2, 6
        transmit = jnp.asarray(rng.randn(W, d).astype(np.float32))
        batch = {"mask": jnp.ones((W, B), jnp.float32)}
        got, _ = jax.jit(lambda t, b: robust_fold(cfg, t, b))(
            transmit, batch)

        def inline(t, b):
            flatT = t.reshape(W, -1).astype(jnp.float32)
            n = jnp.sum(b["mask"], axis=1).astype(jnp.float32)
            total = jnp.maximum(jnp.sum(n), 1.0)
            g = flatT / jnp.maximum(n, 1.0)[:, None]
            norms = jnp.sqrt(jnp.sum(g * g, axis=1))
            tau = jnp.float32(0.5)
            scale = jnp.minimum(1.0, tau / jnp.maximum(norms, 1e-12))
            return jnp.sum(scale[:, None] * flatT, axis=0) / total

        want = jax.jit(inline)(transmit, batch)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want))

    def test_np_mirror_matches_jax(self):
        norms = np.array([0.0, 0.2, 1.0, 9.0], np.float32)
        np.testing.assert_allclose(
            np_clip_factors(norms, 0.7),
            np.asarray(clip_factors(jnp.asarray(norms),
                                    jnp.float32(0.7))),
            rtol=1e-7)

    def test_dp_clip_exact_inside_cap_and_matches_mirror(self):
        g = np.linspace(-1, 1, 16).astype(np.float32)
        inside = np.asarray(dp_clip(jnp.asarray(g), 100.0))
        np.testing.assert_array_equal(inside, g)  # no-op inside cap
        clipped = np.asarray(dp_clip(jnp.asarray(g), 0.5))
        assert abs(np.linalg.norm(clipped) - 0.5) < 1e-6
        np.testing.assert_allclose(clipped, np_dp_clip(g, 0.5),
                                   rtol=1e-6, atol=1e-7)


class TestMechanism:
    def test_noise_replay_bit_exact(self):
        key = round_noise_key(jax.random.PRNGKey(7))
        a = np.asarray(add_table_noise(jnp.zeros((3, 8)), key, 0.25))
        b = np.asarray(add_table_noise(jnp.zeros((3, 8)), key, 0.25))
        np.testing.assert_array_equal(a, b)
        other = round_noise_key(jax.random.PRNGKey(8))
        assert not np.array_equal(
            a, np.asarray(add_table_noise(jnp.zeros((3, 8)),
                                          other, 0.25)))

    def test_noise_key_disjoint_from_client_streams(self):
        rng = jax.random.PRNGKey(11)
        nk = np.asarray(round_noise_key(rng))
        for cid in range(64):
            assert not np.array_equal(
                nk, np.asarray(jax.random.fold_in(rng, cid)))

    def test_table_noise_std_closed_form(self):
        cfg = dp_cfg(dp_clip=0.25, dp_noise_mult=0.8, num_rows=5,
                     num_workers=2)
        assert table_sensitivity(5, 0.25, 2) \
            == math.sqrt(5) * 0.25 / 2
        assert table_noise_std(cfg) == 0.8 * math.sqrt(5) * 0.25 / 2

    def test_np_dp_noise_matches_jitted_draw(self):
        # same key -> same threefry bits; the uniform->normal tail can
        # fuse differently inside the round jit, so ulp-level only
        key = round_noise_key(jax.random.PRNGKey(3))
        jitted = jax.jit(lambda t: add_table_noise(t, key, 0.7))
        got = np.asarray(jitted(jnp.zeros((5, 64), jnp.float32)))
        np.testing.assert_allclose(got, np_dp_noise(key, (5, 64), 0.7),
                                   rtol=1e-6, atol=1e-7)


class TestAccountant:
    def test_subsampled_matches_mirror_120_rounds(self):
        q, sigma, delta = 0.037, 1.1, 1e-5
        acc = PrivacyAccountant(sigma, q, delta)
        for _ in range(120):
            acc.step()
        want = mirror_epsilon(q, sigma, delta, [1.0] * 120)
        assert abs(acc.epsilon() - want) <= 1e-6 * max(1.0, want)

    def test_full_participation_matches_closed_form(self):
        # q=1: per-round RDP is exactly α/(2σ²)
        sigma, delta, n = 2.0, 1e-6, 150
        acc = PrivacyAccountant(sigma, 1.0, delta)
        for _ in range(n):
            acc.step()
        want = mirror_epsilon(1.0, sigma, delta, [1.0] * n)
        assert abs(acc.epsilon() - want) <= 1e-6 * max(1.0, want)

    def test_staleness_weighted_matches_mirror(self):
        q, sigma, delta = 0.25, 0.9, 1e-5
        weights = [1.0, 0.5, 0.25] * 34  # 102 rounds
        acc = PrivacyAccountant(sigma, q, delta)
        for w in weights:
            acc.step(weight_scale=w)
        want = mirror_epsilon(q, sigma, delta, weights)
        assert abs(acc.epsilon() - want) <= 1e-6 * max(1.0, want)

    def test_weight_scale_is_sigma_rescale(self):
        a = PrivacyAccountant(1.0, 0.3, 1e-5)
        b = PrivacyAccountant(2.0, 0.3, 1e-5)
        for _ in range(20):
            a.step(weight_scale=0.5)
            b.step()
        assert a.epsilon() == b.epsilon()

    def test_sigma_override_matches_rebuilt(self):
        a = PrivacyAccountant(1.0, 0.3, 1e-5)
        b = PrivacyAccountant(1.7, 0.3, 1e-5)
        for _ in range(10):
            a.step(sigma=1.7)
            b.step()
        assert a.epsilon() == b.epsilon()

    def test_quantized_wire_is_free_postprocessing(self):
        # the accountant charges the noisy f32 release; the int8 qdq
        # after it must not change the account
        f32 = build_accountant(dp_cfg(dp_noise_mult=1.0))
        int8 = build_accountant(dp_cfg(dp_noise_mult=1.0,
                                       sketch_dtype="int8"))
        for _ in range(5):
            f32.step()
            int8.step()
        assert f32.epsilon() == int8.epsilon()
        assert build_accountant(make_cfg()) is None  # --dp off

    def test_state_roundtrip_bit_exact_through_json(self):
        acc = PrivacyAccountant(1.3, 0.41, 3e-6)
        for w in (1.0, 0.7, 0.7, 1.0, 0.33):
            acc.step(weight_scale=w)
        back = PrivacyAccountant.load_state(
            json.loads(json.dumps(acc.state_dict())))
        assert back.state_dict() == acc.state_dict()
        assert back.epsilon() == acc.epsilon()
        for _ in range(5):  # continuity: both keep composing equally
            acc.step()
            back.step()
        assert back.epsilon() == acc.epsilon()

    def test_epsilon_zero_before_first_step_and_monotone(self):
        acc = PrivacyAccountant(1.0, 0.5, 1e-5)
        assert acc.epsilon() == 0.0
        prev = 0.0
        for _ in range(30):
            acc.step()
            assert acc.epsilon() >= prev
            prev = acc.epsilon()

    def test_sigma_zero_spends_infinite_epsilon(self):
        acc = PrivacyAccountant(0.0, 0.5, 1e-5)
        acc.step()
        assert math.isinf(acc.epsilon())

    def test_steps_to_budget_brackets_the_curve(self):
        sigma, q, delta, budget = 1.0, 0.5, 1e-5, 10.0
        n = steps_to_budget(sigma, q, delta, budget)
        acc = PrivacyAccountant(sigma, q, delta)
        assert acc.epsilon_after(n) <= budget < acc.epsilon_after(n + 1)
        assert acc.rounds_left(budget) == n


class TestDPRound:
    """The compiled DP round against MirrorFed with the same keys."""

    def test_noised_round_matches_mirror(self):
        cfg = dp_cfg()
        rounds = rounds_data(seed=20)
        got = run_engine(cfg, W0, rounds, lr=0.01)
        want = run_mirror_dp(cfg, W0, rounds, lr=0.01)
        for r, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_allclose(g, w, rtol=1e-3, atol=1e-4,
                                       err_msg=f"round {r}")

    def test_noise_before_int8_qdq_matches_mirror(self):
        """int8 wire under DP: ONE qdq on the NOISY aggregated table.
        A wrong order (noise after qdq, or per-client qdq left on)
        diverges from the mirror immediately."""
        cfg = dp_cfg(sketch_dtype="int8", dp_noise_mult=0.5)
        rounds = rounds_data(seed=21)
        got = run_engine(cfg, W0, rounds, lr=0.01)
        want = run_mirror_dp(cfg, W0, rounds, lr=0.01)
        for r, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_allclose(g, w, rtol=5e-3, atol=5e-4,
                                       err_msg=f"round {r}")

    def test_tight_clip_matches_mirror(self):
        cfg = dp_cfg(dp_clip=0.05, dp_noise_mult=0.0)
        rounds = rounds_data(seed=22)
        got = run_engine(cfg, W0, rounds, lr=0.01)
        want = run_mirror_dp(cfg, W0, rounds, lr=0.01)
        for r, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_allclose(g, w, rtol=1e-3, atol=1e-4,
                                       err_msg=f"round {r}")

    def test_seeded_replay_bit_exact(self):
        cfg = dp_cfg(dp_noise_mult=1.0)
        rounds = rounds_data(seed=23)
        a = run_engine(cfg, W0, rounds, lr=0.01)
        b = run_engine(cfg, W0, rounds, lr=0.01)
        for ra, rb in zip(a, b):
            np.testing.assert_array_equal(ra, rb)

    def test_dp_off_program_identical(self):
        """--dp off must trace NOTHING: the lowered round is
        byte-identical whatever the (inert) dp_* knobs say, and a
        --dp sketch build differs."""
        d, B = 8, 3
        base = dataclasses.replace(
            make_cfg(mode="sketch", error_type="virtual", k=4,
                     num_rows=5, num_cols=64), grad_size=d)
        inert = dataclasses.replace(base, dp_clip=7.0,
                                    dp_noise_mult=3.0, dp_delta=1e-7)
        dp = dataclasses.replace(base, dp="sketch")

        def text(cfg):
            fn = build_client_round(cfg, linear_loss, B)
            args = (jnp.zeros(d),
                    ClientStates.init(cfg, 4, jnp.zeros(d)),
                    {"x": jnp.zeros((2, B, d)),
                     "y": jnp.zeros((2, B)),
                     "mask": jnp.ones((2, B))},
                    jnp.zeros(2, jnp.int32), jax.random.PRNGKey(0),
                    jnp.float32(0.01))
            return jax.jit(fn).lower(*args).as_text()

        assert text(base) == text(inert)
        assert text(base) != text(dp)


class TestCapacityDenominator:
    """--dp sketch normalises every fold by the STATIC padded
    capacity W·B: the transmit is the clipped gradient × the real
    datapoint count n_i, so only a data-independent denominator
    keeps one client's share of the released mean within the charged
    sqrt(r)·C/W sensitivity — on padded / mostly-dead rounds AND
    under staleness weights (which would cancel out of a
    weighted-total denominator)."""

    def test_mostly_dead_round_uses_capacity_denominator(self):
        d, B, W = 8, 3, 2
        base = dataclasses.replace(
            make_cfg(mode="sketch", error_type="virtual", k=4,
                     num_rows=5, num_cols=64), grad_size=d)
        # huge clip (exact no-op) + zero noise isolates the fold
        # algebra: the DP round differs from dp-off ONLY by the
        # capacity denominator
        dp = dataclasses.replace(base, dp="sketch", dp_clip=1e6,
                                 dp_noise_mult=0.0)
        rng = np.random.RandomState(5)
        batch = {"x": jnp.asarray(rng.randn(W, B, d), jnp.float32),
                 "y": jnp.asarray(rng.randn(W, B), jnp.float32),
                 "mask": jnp.asarray([[1, 0, 0], [0, 0, 0]],
                                     jnp.float32)}

        def agg(cfg):
            fn = jax.jit(build_client_round(cfg, linear_loss, B))
            res = fn(jnp.zeros(d),
                     ClientStates.init(cfg, W, jnp.zeros(d)), batch,
                     jnp.arange(W, dtype=jnp.int32),
                     jax.random.PRNGKey(0), jnp.float32(0.01))
            return np.asarray(res.aggregated)

        off, got = agg(base), agg(dp)
        assert np.linalg.norm(off) > 0
        # one alive datapoint: dp-off divides by 1, DP divides by
        # the static W·B capacity
        np.testing.assert_allclose(got, off / (W * B), rtol=1e-6,
                                   atol=1e-8)

    def test_full_round_capacity_denominator_is_inert(self):
        """With every slot full the alive total IS W·B, so the DP
        round at huge clip / zero noise equals the dp-off round
        exactly."""
        d, B, W = 8, 3, 2
        base = dataclasses.replace(
            make_cfg(mode="sketch", error_type="virtual", k=4,
                     num_rows=5, num_cols=64), grad_size=d)
        dp = dataclasses.replace(base, dp="sketch", dp_clip=1e6,
                                 dp_noise_mult=0.0)
        rng = np.random.RandomState(6)
        batch = {"x": jnp.asarray(rng.randn(W, B, d), jnp.float32),
                 "y": jnp.asarray(rng.randn(W, B), jnp.float32),
                 "mask": jnp.ones((W, B), jnp.float32)}

        def agg(cfg):
            fn = jax.jit(build_client_round(cfg, linear_loss, B))
            res = fn(jnp.zeros(d),
                     ClientStates.init(cfg, W, jnp.zeros(d)), batch,
                     jnp.arange(W, dtype=jnp.int32),
                     jax.random.PRNGKey(0), jnp.float32(0.01))
            return np.asarray(res.aggregated)

        np.testing.assert_allclose(agg(dp), agg(base), rtol=1e-6,
                                   atol=1e-8)

    def test_robust_clip_fold_capacity_and_mirror_matches(self):
        from reference_mirror import np_robust_fold

        W, B, d = 4, 2, 6
        base = make_cfg(robust_agg="clip", robust_clip_norm=0.5)
        dp = dp_cfg(robust_agg="clip", robust_clip_norm=0.5)
        rng = np.random.RandomState(7)
        transmit = jnp.asarray(rng.randn(W, d).astype(np.float32))
        mask = np.zeros((W, B), np.float32)
        mask[0, 0] = 1.0  # one alive datapoint in a W=4 cohort
        batch = {"mask": jnp.asarray(mask)}
        got_base, _ = robust_fold(base, transmit, batch)
        got_dp, _ = robust_fold(dp, transmit, batch)
        np.testing.assert_allclose(np.asarray(got_dp),
                                   np.asarray(got_base) / (W * B),
                                   rtol=1e-6, atol=1e-8)
        want, _ = np_robust_fold(dp, [np.asarray(t) for t in
                                      transmit],
                                 mask.sum(axis=1), capacity=B)
        np.testing.assert_allclose(np.asarray(got_dp), want,
                                   rtol=1e-6, atol=1e-7)

    def test_dp_robust_composition_guards(self):
        """The accountant's bound only covers folds where a client's
        influence is its own clipped share: median/trimmed releases
        and cohort-derived clip caps are refused at config time."""
        with pytest.raises(AssertionError):
            dp_cfg(robust_agg="median").validate_runtime()
        with pytest.raises(AssertionError):
            dp_cfg(robust_agg="trimmed",
                   robust_trim_frac=0.2).validate_runtime()
        with pytest.raises(AssertionError):
            # auto median-of-norms cap
            dp_cfg(robust_agg="clip").validate_runtime()
        ok = dp_cfg(robust_agg="clip", robust_clip_norm=1.0)
        assert ok.validate_runtime().robust_agg == "clip"


def _lin_model(args):
    import flax.linen as nn

    from commefficient_tpu.runtime import FedModel, FedOptimizer

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4, use_bias=False)(x)

    module = Lin()
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 3)))["params"]

    def loss(p, batch, cfg):
        pred = module.apply({"params": p}, batch["x"])
        per = jnp.sum((pred - batch["y"][..., None]) ** 2, -1)
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        return jnp.sum(per * batch["mask"]) / n, ()

    model = FedModel(module, params, loss, args, padded_batch_size=4)
    opt = FedOptimizer([{"lr": 0.05}], args)
    return model, opt


def _dp_args(**kw):
    base = dict(mode="sketch", error_type="virtual",
                local_momentum=0.0, virtual_momentum=0.9, k=2,
                num_rows=3, num_cols=32, num_blocks=1, num_workers=2,
                local_batch_size=4, num_clients=4,
                dataset_name="CIFAR10", seed=0, dp="sketch",
                dp_clip=1.0, dp_noise_mult=1.0, dp_delta=1e-5)
    base.update(kw)
    return Config(**base)


def _round_batch(rng):
    return {"x": rng.randn(2, 4, 3).astype(np.float32),
            "y": rng.randn(2, 4).astype(np.float32),
            "mask": np.ones((2, 4), np.float32),
            "client_ids": np.array([0, 1], np.int32)}


class TestRuntimeCharge:
    """The accountant's runtime lifecycle through FedModel."""

    def test_charged_once_per_dispatched_round(self):
        args = _dp_args()
        model, opt = _lin_model(args)
        rng = np.random.RandomState(0)
        for _ in range(3):
            model(_round_batch(rng))
            opt.step()
        assert model._accountant.steps == 3
        ref = PrivacyAccountant(1.0, sample_rate_of(args), 1e-5)
        for _ in range(3):
            ref.step()
        assert model._accountant.epsilon() == ref.epsilon()

    def test_budget_abort_at_predicted_round(self):
        from commefficient_tpu.telemetry.alarms import DivergenceAbort

        q = sample_rate_of(_dp_args())
        probe = PrivacyAccountant(1.0, q, 1e-5)
        eps = []
        for _ in range(3):
            probe.step()
            eps.append(probe.epsilon())
        budget = (eps[1] + eps[2]) / 2.0  # 2 rounds fit, 3 don't
        assert steps_to_budget(1.0, q, 1e-5, budget) == 2

        args = _dp_args(dp_epsilon=budget, on_divergence="abort")
        model, opt = _lin_model(args)
        rng = np.random.RandomState(0)
        for _ in range(2):
            model(_round_batch(rng))
            opt.step()
        with pytest.raises(DivergenceAbort):
            model(_round_batch(rng))
            opt.step()

    def test_ledger_round_records_carry_v5_keys(self):
        from commefficient_tpu.telemetry.record import (
            LEDGER_SCHEMA_VERSION, make_round_record, validate_record)

        rec = make_round_record(0)
        assert rec["schema"] == 7 == LEDGER_SCHEMA_VERSION
        assert rec["dp_epsilon"] is None \
            and rec["dp_delta"] is None and rec["dp_sigma"] is None
        assert validate_record(rec) == []
        del rec["dp_epsilon"]
        assert any("dp_epsilon" in p for p in validate_record(rec))

    def test_set_round_privacy_stamps_open_record(self):
        from commefficient_tpu.telemetry.core import Telemetry

        out = []

        class _Sink:
            def write(self, rec):
                out.append(rec)

            def flush(self):
                pass

            def close(self):
                pass

        tel = Telemetry(sinks=[_Sink()])
        tel.begin_round(0)
        tel.set_round_privacy(0, 1.25, 1e-5, 0.8)
        tel.set_round_bytes(0, 10, 20)
        tel.close()
        rounds = [r for r in out if r.get("kind") == "round"]
        assert rounds and rounds[0]["dp_epsilon"] == 1.25
        assert rounds[0]["dp_delta"] == 1e-5
        assert rounds[0]["dp_sigma"] == 0.8

    def test_async_round_charges_largest_alive_weight(self):
        """A staleness-weighted round charges weight_scale =
        (1 + s_min)^{-alpha} over the ALIVE slots only: DP folds
        normalise by the static W·B capacity (core/rounds.py), so a
        client's released contribution is cw_i·t_i/(W·B) — genuinely
        scaled by its fold weight — and the round's worst case is the
        largest alive weight. Dead slots (including one with the
        globally smallest staleness) must not set the charge, and the
        ledger σ is the effective σ/w."""
        from commefficient_tpu.runtime.fed_model import FedModel

        sigmas = []

        class _Tel:
            def set_round_privacy(self, ridx, eps, delta, sigma):
                sigmas.append(sigma)

        fake = SimpleNamespace(
            _accountant=PrivacyAccountant(1.0, 1.0, 1e-5),
            telemetry=_Tel(), alarm_engine=None)
        cfg = SimpleNamespace(dp_noise_mult=1.0,
                              async_staleness_weight=0.5,
                              dp_epsilon=0.0)
        staleness = np.array([3.0, 1.0, 7.0])
        mask = np.array([[1, 1], [0, 0], [1, 0]], np.float32)
        FedModel._charge_privacy(fake, 0, cfg, staleness, mask)
        w = (1.0 + 3.0) ** -0.5  # slot 1 (s=1) is dead: alive min is 3
        ref = PrivacyAccountant(1.0, 1.0, 1e-5)
        ref.step(weight_scale=w)
        assert fake._accountant.epsilon() == ref.epsilon()
        assert sigmas == [1.0 / w]

    def test_sync_and_dead_rounds_charge_full_sensitivity(self):
        """No discount without the async driver (staleness is None)
        and none on a fully-dead fold (pure-noise release; charging 1
        is conservative)."""
        from commefficient_tpu.runtime.fed_model import FedModel

        class _Tel:
            def set_round_privacy(self, *a):
                pass

        fake = SimpleNamespace(
            _accountant=PrivacyAccountant(1.0, 1.0, 1e-5),
            telemetry=_Tel(), alarm_engine=None)
        cfg = SimpleNamespace(dp_noise_mult=1.0,
                              async_staleness_weight=0.5,
                              dp_epsilon=0.0)
        FedModel._charge_privacy(fake, 0, cfg)
        FedModel._charge_privacy(fake, 1, cfg, np.array([2.0, 5.0]),
                                 np.zeros((2, 3), np.float32))
        ref = PrivacyAccountant(1.0, 1.0, 1e-5)
        ref.step()
        ref.step()
        assert fake._accountant.epsilon() == ref.epsilon()

    def test_no_subsampling_amplification_credit(self):
        """FedSampler draws cohorts without replacement until clients
        exhaust their epoch data — not Poisson — so sample_rate_of
        claims q = 1 even for a small cohort of a big federation."""
        assert sample_rate_of(_dp_args(num_clients=1000)) == 1.0
        assert sample_rate_of(_dp_args()) == 1.0
        # the accountant built for such a config prices the plain
        # (unamplified) Gaussian round
        acc = build_accountant(_dp_args(num_clients=1000))
        acc.step()
        ref = PrivacyAccountant(1.0, 1.0, 1e-5)
        ref.step()
        assert acc.epsilon() == ref.epsilon()


class TestCheckpointContinuity:
    def test_accountant_survives_save_load_bit_exact(self, tmp_path):
        from commefficient_tpu.runtime.checkpoint import (
            load_checkpoint, save_checkpoint)

        args = _dp_args()
        model, opt = _lin_model(args)
        rng = np.random.RandomState(1)
        batches = [_round_batch(rng) for _ in range(4)]
        for b in batches[:2]:
            model(b)
            opt.step()
        path = str(tmp_path / "ck.npz")
        save_checkpoint(path, model, opt)
        spent = model._accountant.state_dict()

        model2, opt2 = _lin_model(args)
        load_checkpoint(path, model2, opt2)
        assert model2._accountant.state_dict() == spent

        # continuity: original and resumed runs keep composing equally
        for b in batches[2:]:
            model(b)
            opt.step()
            model2(b)
            opt2.step()
        assert model2._accountant.epsilon() == model._accountant.epsilon()
        assert model2._accountant.steps == 4

    def test_dp_run_refuses_dpless_checkpoint(self, tmp_path):
        from commefficient_tpu.runtime.checkpoint import (
            load_checkpoint, save_checkpoint)

        off = _dp_args(dp="off", dp_noise_mult=0.0)
        model_off, opt_off = _lin_model(off)
        rng = np.random.RandomState(2)
        model_off(_round_batch(rng))
        opt_off.step()
        path = str(tmp_path / "off.npz")
        save_checkpoint(path, model_off, opt_off)

        model_dp, opt_dp = _lin_model(_dp_args())
        with pytest.raises(ValueError, match="privacy accountant"):
            load_checkpoint(path, model_dp, opt_dp)

    def test_dpless_run_warns_on_dp_checkpoint(self, tmp_path):
        from commefficient_tpu.runtime.checkpoint import (
            load_checkpoint, save_checkpoint)

        model_dp, opt_dp = _lin_model(_dp_args())
        rng = np.random.RandomState(3)
        model_dp(_round_batch(rng))
        opt_dp.step()
        path = str(tmp_path / "dp.npz")
        save_checkpoint(path, model_dp, opt_dp)

        off = _dp_args(dp="off", dp_noise_mult=0.0)
        model_off, opt_off = _lin_model(off)
        with pytest.warns(UserWarning, match="privacy accountant"):
            load_checkpoint(path, model_off, opt_off)


# ------------------------------------------------------------------ #
# perf-gate privacy keying: p<eps> topology fragment, no fallback    #
# ------------------------------------------------------------------ #

class TestPerfGatePrivacyKeying:
    def test_privacy_suffix_forms(self):
        from commefficient_tpu.telemetry import gate

        assert gate.privacy_suffix(None) == ""
        # 0.0 is DP with an unlimited budget, NOT an absence
        assert gate.privacy_suffix(0.0) == "p0"
        assert gate.privacy_suffix(3.5) == "p3.5"
        assert gate.privacy_suffix(8) == "p8"
        assert gate.topology_key(8, 1, dp_epsilon=3.5) == "d8p1p3.5"
        assert gate.topology_key(8, 1, wire_dtype="int8",
                                 band="0.05:0.6",
                                 dp_epsilon=2.0) == \
            "d8p1qint8b0.05-0.6p2"
        assert gate.topology_key(dp_epsilon=1.5) == "any-p1.5"

    def test_no_cross_budget_fallback(self):
        from commefficient_tpu.telemetry import gate

        m = {"round_total": {"median": 1.0, "mad": 0.1, "n": 5,
                             "better": "lower"}}
        base = gate.make_baseline(m, device_count=8, process_count=1)
        base = gate.update_baseline(base, m, device_count=8,
                                    process_count=1, dp_epsilon=2.5)
        # a DP run resolves ONLY its own budget's pin
        assert gate.baseline_entry(base, 8, 1,
                                   dp_epsilon=2.5) is not None
        assert gate.baseline_entry(base, 8, 1, dp_epsilon=4.0) is None
        assert gate.baseline_entry(base, 8, 1, dp_epsilon=0.0) is None
        # a DP run never resolves the noiseless pin, and a noiseless
        # run never resolves a DP one
        clean = gate.baseline_entry(base, 8, 1)
        assert clean is not None and "dp_epsilon" not in clean
        only_dp = gate.make_baseline(m, device_count=8,
                                     process_count=1, dp_epsilon=2.5)
        assert gate.baseline_entry(only_dp, 8, 1) is None
        with pytest.raises(ValueError):
            gate.compare(only_dp, m, device_count=8, process_count=1)
        with pytest.raises(ValueError):
            gate.compare(base, m, device_count=8, process_count=1,
                         dp_epsilon=4.0)
        # the budget is recorded on the entry for auditability
        hit = gate.baseline_entry(base, 8, 1, dp_epsilon=2.5)
        assert hit["dp_epsilon"] == 2.5
        # mesh fallback keeps the privacy fragment (mesh is the ONLY
        # fragment with a migration fallback)
        assert gate.baseline_entry(
            base, 8, 1, mesh_shape={"clients": 4, "model": 2},
            dp_epsilon=2.5) is not None
        assert gate.baseline_entry(
            only_dp, 8, 1,
            mesh_shape={"clients": 4, "model": 2}) is None

    def test_registry_run_key_privacy_fragment(self):
        from commefficient_tpu.telemetry import registry

        man = {"config_hash": "abc", "device_count": 8,
               "process_count": 1,
               "config": {"mode": "sketch", "dp": "sketch",
                          "dp_epsilon": 3.5}}
        assert registry.run_dp_epsilon(man) == 3.5
        assert registry.run_key(man) == ("abc", 8, 1, "p3.5")
        # unlimited budget still keys off the noiseless pin
        man["config"]["dp_epsilon"] = 0.0
        assert registry.run_dp_epsilon(man) == 0.0
        assert registry.run_key(man) == ("abc", 8, 1, "p0")
        man["config"]["dp"] = "off"
        assert registry.run_dp_epsilon(man) is None
        assert registry.run_key(man) == ("abc", 8, 1)

    def test_perf_gate_resolves_dp_epsilon(self):
        import os
        import sys

        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "scripts"))
        import perf_gate

        man = {"config": {"mode": "sketch", "dp": "sketch",
                          "dp_epsilon": 3.5},
               "device_count": 2, "process_count": 1}
        assert perf_gate.resolve_topology(man)[7] == 3.5
        # ledger meta plan carries enough to re-derive the key
        recs = [{"kind": "meta", "num_devices": 4,
                 "plan": {"dp": {"mode": "sketch", "clip": 1.0,
                                 "noise_mult": 1.0, "delta": 1e-5,
                                 "epsilon_budget": 2.0}}}]
        assert perf_gate.resolve_topology(None, recs)[7] == 2.0
        # an unlimited budget survives the chain as 0.0, never None
        recs[0]["plan"]["dp"]["epsilon_budget"] = 0.0
        assert perf_gate.resolve_topology(None, recs)[7] == 0.0
        # CLI override wins; noiseless runs resolve to None
        assert perf_gate.resolve_topology(man, dp_epsilon=9.0)[7] \
            == 9.0
        man["config"]["dp"] = "off"
        assert perf_gate.resolve_topology(man)[7] is None

    def test_round_plan_records_dp_block(self):
        from commefficient_tpu.core.rounds import round_plan

        cfg = dataclasses.replace(
            make_cfg(mode="sketch", error_type="virtual", k=8,
                     num_rows=3, num_cols=128, dp="sketch",
                     dp_clip=2.0, dp_noise_mult=0.5, dp_delta=1e-6,
                     dp_epsilon=4.0),
            grad_size=64)
        blk = round_plan(cfg)["dp"]
        assert blk == {"mode": "sketch", "clip": 2.0,
                       "noise_mult": 0.5, "delta": 1e-6,
                       "epsilon_budget": 4.0}
        assert "dp" not in round_plan(
            dataclasses.replace(make_cfg(mode="sketch",
                                         error_type="virtual", k=8,
                                         num_rows=3, num_cols=128),
                                grad_size=64))
