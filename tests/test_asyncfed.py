"""Buffered asynchronous federated rounds (commefficient_tpu/asyncfed).

Four layers of guarantee:

- the seeded ``ArrivalSchedule`` replays bit-identically (golden
  trace) and its ``replay_stats`` summary matches the bench's
  historical inline computation;
- the arrival queue / round driver bookkeeping is exact: arrival
  order, dead-slot padding, staleness accounting, and the
  prefetch-lookahead peek that must be either exactly right or None;
- the DEGENERATE configuration — buffer == cohort, staleness weight
  0, punctual arrivals — is BIT-IDENTICAL to the synchronous round at
  the FedModel level across modes (the async driver adds bookkeeping,
  never math);
- the staleness-weighted fold algebra matches the NumPy mirror to
  1e-6, composed with ``--robust_agg``, a 2-D ``--mesh`` and
  ``--sketch_dtype int8``, under churny and bursty traces.

Plus the observatory surface: the ``async_staleness`` alarm rule, the
``a<K>`` perf-gate topology fragment (no cross-mode fallback), and
the registry run_key fragment.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from commefficient_tpu.asyncfed import ArrivalQueue, AsyncRoundDriver
from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import (ClientStates, args2sketch,
                                           build_client_round)
from commefficient_tpu.data.chaos import ArrivalSchedule
from reference_mirror import (np_qdq_table, np_robust_fold,
                              np_staleness_weights)


def linear_loss(params_flat, batch):
    pred = batch["x"] @ params_flat
    sq = (pred - batch["y"]) ** 2
    n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
    loss = jnp.sum(sq * batch["mask"]) / n
    return loss, (loss * 0.0 + 1.0,)


def make_cfg(**kw):
    base = dict(mode="uncompressed", local_momentum=0.0,
                virtual_momentum=0.0, weight_decay=0.0,
                error_type="none", num_workers=4, k=3,
                num_rows=3, num_cols=64, num_blocks=1,
                local_batch_size=2, microbatch_size=-1, seed=21)
    base.update(kw)
    return Config(**base)


# -- ArrivalSchedule ----------------------------------------------------


def test_arrival_schedule_golden_trace():
    """The seeded schedules are pinned: any change to the draw order
    silently invalidates every replayed experiment."""
    ch = ArrivalSchedule("churny", seed=7, max_delay=3, churn_frac=0.5)
    got = [ch.delays(6).tolist() for _ in range(4)]
    assert got == [[1, 0, 3, 0, 0, 0], [1, 0, 0, 1, 1, 2],
                   [0, 1, 0, 1, 0, 0], [1, 0, 0, 1, 2, 2]], got
    bu = ArrivalSchedule("bursty", seed=7, max_delay=4,
                         burst_start_prob=0.5, burst_stop_prob=0.3,
                         drop_frac=0.5)
    got = [bu.delays(6).tolist() for _ in range(4)]
    assert got == [[4, 0, 4, 0, 0, 4], [4, 0, 4, 0, 0, 4],
                   [4, 0, 4, 0, 0, 4], [0, 0, 0, 0, 0, 0]], got


@pytest.mark.parametrize("kind", ArrivalSchedule.KINDS)
def test_arrival_schedule_replays(kind):
    a = ArrivalSchedule(kind, seed=3)
    b = ArrivalSchedule(kind, seed=3)
    t1 = [a.delays(8).tolist() for _ in range(6)]
    assert [b.delays(8).tolist() for _ in range(6)] == t1
    a.reset()
    assert [a.delays(8).tolist() for _ in range(6)] == t1
    assert (ArrivalSchedule("uniform", seed=0).delays(5) == 0).all()


def test_replay_stats_matches_inline_summary():
    """replay_stats == the summary host_scale_bench historically
    computed inline (satellite: the bench now calls this)."""
    alive = [1.0, 0.5, 0.25, 1.0, 1.0, 0.75, 0.5, 1.0]
    st = ArrivalSchedule.replay_stats(alive, 8)
    assert st == {"burst_count": 2, "burst_rounds": 4,
                  "longest_burst": 2, "alive_frac_min": 0.25,
                  "alive_frac_mean": 0.75,
                  "dropped_client_rounds": 16}
    empty = ArrivalSchedule.replay_stats([], 8)
    assert empty["alive_frac_min"] == 1.0
    assert empty["dropped_client_rounds"] == 0


# -- queue / driver units ----------------------------------------------


def test_arrival_queue_order_and_peek():
    q = ArrivalQueue()
    q.push(2, "late")
    q.push(0, "a")
    q.push(0, "b")
    q.push(1, "mid")
    assert q.peek_arrived(0) == ["a", "b"]  # peek never consumes
    assert len(q) == 4
    assert q.pop_arrived(0, limit=8) == ["a", "b"]
    assert q.pop_arrived(0, limit=8) == []  # "mid" still in flight
    assert q.pop_arrived(2, limit=1) == ["mid"]  # limit respected
    assert q.pop_arrived(2, limit=8) == ["late"]
    assert len(q) == 0


def _host_batch(rng, W, B, d, lo=0, hi=100):
    return {"client_ids": rng.choice(np.arange(lo + 1, hi), W,
                                     replace=False).astype(np.int32),
            "x": rng.randn(W, B, d).astype(np.float32),
            "y": rng.randn(W, B).astype(np.float32),
            "mask": np.ones((W, B), np.float32)}


def test_driver_punctual_identity_and_stats():
    cfg = make_cfg(num_workers=4, async_buffer_size=4)
    drv = AsyncRoundDriver(cfg)
    rng = np.random.RandomState(0)
    b = _host_batch(rng, 4, 2, 3)
    fb, stale = drv.step(b)
    for k in b:
        np.testing.assert_array_equal(fb[k], b[k])
    assert (stale == 0).all() and stale.shape == (4,)
    st = drv.round_stats()
    assert st["async_buffer_occupancy"] == 1.0
    assert st["async_backlog"] == 0.0
    assert st["async_staleness_hist"] == [4]


def test_driver_pads_dead_slots_and_tracks_staleness():
    cfg = make_cfg(num_workers=4, async_buffer_size=4)
    drv = AsyncRoundDriver(cfg)
    # slots 1 and 3 of the first cohort are 2 steps late
    delays = iter([np.array([0, 2, 0, 2])] + [np.zeros(4, np.int64)] * 2)
    drv.attach_arrival_process(lambda r, n: next(delays))
    rng = np.random.RandomState(1)
    b0 = _host_batch(rng, 4, 2, 3)
    fb0, s0 = drv.step(b0)
    # fold 0: only the two punctual slots arrived, rest dead-padded
    np.testing.assert_array_equal(
        fb0["client_ids"][:2], b0["client_ids"][[0, 2]])
    assert (fb0["client_ids"][2:] == 0).all()
    assert (fb0["mask"][2:] == 0).all() and (fb0["mask"][:2] == 1).all()
    assert (s0 == 0).all()
    st = drv.round_stats()
    assert st["async_buffer_occupancy"] == 0.5
    assert st["async_backlog"] == 2.0
    # fold 1: the punctual second cohort fills the buffer first (it
    # arrived at step 1; the stragglers arrive at step 2)
    b1 = _host_batch(rng, 4, 2, 3)
    fb1, s1 = drv.step(b1)
    np.testing.assert_array_equal(fb1["client_ids"], b1["client_ids"])
    assert (s1 == 0).all()
    # fold 2: the stragglers drain with staleness 2
    b2 = _host_batch(rng, 4, 2, 3)
    fb2, s2 = drv.step(b2)
    np.testing.assert_array_equal(
        fb2["client_ids"][:2], b0["client_ids"][[1, 3]])
    assert s2[:2].tolist() == [2.0, 2.0]
    assert drv.round_stats()["async_staleness_max"] == 2.0


def test_driver_peek_next_ids_exact_or_none():
    cfg = make_cfg(num_workers=4, async_buffer_size=2)
    drv = AsyncRoundDriver(cfg)
    rng = np.random.RandomState(2)
    # punctual K=2 < W: after one step the backlog holds 2 arrived
    # entries — the peek must predict the next fold's gather exactly
    b0 = _host_batch(rng, 4, 2, 3)
    drv.step(b0)
    peek = drv.peek_next_ids()
    assert peek is not None
    b1 = _host_batch(rng, 4, 2, 3)
    fb1, _ = drv.step(b1)
    np.testing.assert_array_equal(peek, fb1["client_ids"])
    # drain the backlog below K: the peek must refuse to guess
    drv.step(_host_batch(rng, 4, 2, 3))
    drv.step(_host_batch(rng, 4, 2, 3))
    while len(drv.queue) >= drv.k:
        drv.queue.pop_arrived(drv._fold, 1)
    assert drv.peek_next_ids() is None


def test_driver_stamps_issue_rounds():
    seen = []
    cfg = make_cfg(num_workers=4, async_buffer_size=4)
    drv = AsyncRoundDriver(cfg, stamp=lambda ids, r: seen.append(
        (np.asarray(ids).tolist(), r)))
    rng = np.random.RandomState(3)
    b = _host_batch(rng, 4, 2, 3)
    drv.step(b)
    drv.step(_host_batch(rng, 4, 2, 3))
    assert seen[0] == (b["client_ids"].tolist(), 0)
    assert seen[1][1] == 1


# -- degenerate-sync bit parity at the FedModel level -------------------


def _run_fed(cfg_kw, n_rounds=5, async_k=0, alpha=0.0, sched=None,
             d=64, num_clients=32):
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)
    W, B = 4, 2

    def loss(params, batch, cfg):
        pred = batch["x"] @ params["w"]
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
        return l, (l * 0.0 + 1.0,)

    base = dict(num_workers=W, local_batch_size=B, seed=5,
                num_clients=num_clients, async_buffer_size=async_k,
                async_staleness_weight=alpha)
    base.update(cfg_kw)
    cfg = Config(**base)
    model = FedModel(None, {"w": jnp.zeros((d,), jnp.float32)}, loss,
                     cfg, padded_batch_size=B)
    opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
    if sched is not None:
        model.attach_arrival_process(sched)
    rng = np.random.RandomState(5)
    for _ in range(n_rounds):
        batch = {"client_ids": rng.choice(num_clients, W,
                                          replace=False)
                 .astype(np.int32),
                 "x": jnp.asarray(rng.randn(W, B, d), jnp.float32),
                 "y": jnp.asarray(rng.randn(W, B), jnp.float32),
                 "mask": jnp.ones((W, B), jnp.float32)}
        model(batch)
        opt.step()
    ps = np.asarray(model.ps_weights)
    model.finalize()
    return ps


@pytest.mark.parametrize("mode_kw", [
    dict(mode="sketch", error_type="virtual", local_momentum=0.0,
         virtual_momentum=0.9, k=16, num_rows=3, num_cols=128),
    dict(mode="local_topk", error_type="local", local_momentum=0.9,
         virtual_momentum=0.0, k=16),
    dict(mode="fedavg", error_type="none", local_momentum=0.0,
         local_batch_size=-1),
], ids=["sketch", "local_topk", "fedavg"])
def test_degenerate_buffered_round_is_bit_exact(mode_kw):
    """K == cohort, alpha == 0, punctual arrivals: the buffered round
    must be BIT-IDENTICAL to the synchronous barrier round — the
    subsystem's core invariant (weighting is skipped at trace time,
    the queue pops the issued batch slot for slot)."""
    sync = _run_fed(mode_kw)
    deg = _run_fed(mode_kw, async_k=4, alpha=0.0)
    assert np.array_equal(sync, deg)


def test_churny_buffered_round_diverges_then_stays_finite():
    """Sanity on the non-degenerate path: a churny trace with
    staleness weighting produces a DIFFERENT (but finite) model —
    the async machinery is actually engaged."""
    kw = dict(mode="sketch", error_type="virtual", local_momentum=0.0,
              virtual_momentum=0.9, k=16, num_rows=3, num_cols=128)
    sync = _run_fed(kw)
    churn = _run_fed(kw, async_k=2, alpha=0.5,
                     sched=ArrivalSchedule("churny", seed=9))
    assert np.isfinite(churn).all()
    assert not np.array_equal(sync, churn)


# -- staleness-weighted fold algebra vs the NumPy mirror ----------------


def _pad_round(clients, B, d):
    W = len(clients)
    x = np.zeros((W, B, d), np.float32)
    y = np.zeros((W, B), np.float32)
    mask = np.zeros((W, B), np.float32)
    ids = np.zeros((W,), np.int32)
    for i, (cid, X, Y) in enumerate(clients):
        n = len(Y)
        x[i, :n], y[i, :n], mask[i, :n], ids[i] = X, Y, 1.0, cid
    return ({"x": jnp.asarray(x), "y": jnp.asarray(y),
             "mask": jnp.asarray(mask)},
            jnp.asarray(ids, jnp.int32))


def _staleness_from(kind, W, seed=11):
    sched = ArrivalSchedule(kind, seed=seed, max_delay=4)
    return sched.delays(W).astype(np.float32)


@pytest.mark.parametrize("robust", ["none", "median", "trimmed",
                                    "clip"])
@pytest.mark.parametrize("kind", ["churny", "bursty"])
def test_weighted_fold_matches_mirror(robust, kind):
    """Engine staleness-weighted fold == NumPy mirror to 1e-6: the
    weighted (robust) fold of t_i with weights w_i equals the plain
    (robust) fold of w_i*t_i with w_i*n_i datapoints, including a
    dead pad slot (weight never resurrects it)."""
    d, B, W, alpha = 8, 3, 4, 0.7
    cfg = make_cfg(num_workers=W, grad_size=d, robust_agg=robust,
                   async_buffer_size=W, async_staleness_weight=alpha)
    if kind == "bursty":
        cfg.robust_trim_frac = 0.2
    rng = np.random.default_rng(4)
    w0 = rng.normal(size=d).astype(np.float32)
    clients = [(cid, rng.normal(size=(n, d)).astype(np.float32),
                rng.normal(size=(n,)).astype(np.float32))
               for cid, n in [(1, 3), (2, 2), (3, 3)]]
    padded = clients + [(0, np.zeros((0, d), np.float32),
                         np.zeros((0,), np.float32))]
    batch, ids = _pad_round(padded, B, d)
    stale = _staleness_from(kind, W)
    stale[-1] = 0.0  # pad slots carry staleness 0 by construction

    cr = jax.jit(build_client_round(cfg, linear_loss, B,
                                    client_weights=True))
    ps = jnp.asarray(w0)
    res = cr(ps, ClientStates.init(cfg, W, ps), batch, ids,
             jax.random.PRNGKey(0), jnp.float32(1.0),
             jnp.asarray(stale))

    # mirror: per-client transmit = (masked-mean grad) * n, then the
    # pre-scaled stack through the unweighted mirror fold
    wts = np_staleness_weights(stale, alpha).astype(np.float64)
    transmits, counts = [], []
    for i, (cid, X, Y) in enumerate(padded):
        n = len(Y)
        if n:
            r = X.astype(np.float64) @ w0.astype(np.float64) \
                - Y.astype(np.float64)
            g = X.astype(np.float64).T @ (2.0 * r / n)
        else:
            g = np.zeros(d)
        transmits.append(wts[i] * g * n)
        counts.append(wts[i] * n)
    if robust == "none":
        expect = (np.sum(transmits, axis=0)
                  / max(float(np.sum(counts)), 1.0))
    else:
        expect, _ = np_robust_fold(cfg, transmits, counts)
    np.testing.assert_allclose(np.asarray(res.aggregated), expect,
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("kind", ["churny", "bursty"])
def test_weighted_sketch_int8_fold_matches_mirror(kind):
    """Weighted fold composed with the quantized sketch wire: the
    fused round's aggregate == qdq(sketch(Σ w_i·n_i·g_i / Σ w_i·n_i))
    through the shared CountSketch op + the NumPy quantizer mirror.
    The weighted-mean algebra itself is checked to 1e-6 pre-sketch."""
    d, B, W, alpha = 256, 2, 4, 0.5
    cfg = make_cfg(mode="sketch", error_type="virtual",
                   virtual_momentum=0.9, num_workers=W, grad_size=d,
                   num_rows=3, num_cols=64, sketch_dtype="int8",
                   async_buffer_size=W, async_staleness_weight=alpha)
    rng = np.random.default_rng(6)
    c = rng.normal(size=(W, 1, d)).astype(np.float32)

    def lin_loss(p, b):
        n = jnp.maximum(jnp.sum(b["mask"]), 1.0)
        loss = jnp.sum((b["c"] @ p) * b["mask"]) / n
        return loss, (loss * 0.0,)

    mask = np.ones((W, B), np.float32)
    mask[-1] = 0.0  # a dead pad slot rides along
    batch = {"c": jnp.asarray(np.broadcast_to(c, (W, B, d))),
             "mask": jnp.asarray(mask)}
    stale = _staleness_from(kind, W, seed=13)
    stale[-1] = 0.0
    cr = jax.jit(build_client_round(cfg, lin_loss, B,
                                    client_weights=True))
    flat = jnp.zeros((d,), jnp.float32)
    res = cr(flat, ClientStates.init(cfg, W, flat), batch,
             jnp.arange(W, dtype=jnp.int32), jax.random.PRNGKey(0),
             jnp.float32(1.0), jnp.asarray(stale))

    wts = np_staleness_weights(stale, alpha).astype(np.float64)
    n_per = mask.sum(axis=1).astype(np.float64)
    total = max(float((wts * n_per).sum()), 1.0)
    dense = np.einsum("w,wd->d", wts * n_per,
                      c[:, 0, :].astype(np.float64)) / total
    table = np.asarray(jax.jit(args2sketch(cfg).sketch)(
        jnp.asarray(dense, jnp.float32)), np.float64)
    expect = np_qdq_table(table.astype(np.float32), "int8")
    np.testing.assert_allclose(np.asarray(res.aggregated), expect,
                               rtol=1e-4, atol=1e-5)


def test_weighted_fold_on_2d_mesh_matches_1d():
    """The weighted fused sketch fold on a 2x2 clients x model mesh
    == the single-device weighted fold (and the f32 variant matches
    the dense mirror to 1e-5): staleness weighting composes with the
    partial-sketch reduce-scatter emission."""
    from commefficient_tpu.parallel.mesh import make_mesh2d

    d, B, W, alpha = 512, 2, 4, 0.5
    cfg = make_cfg(mode="sketch", error_type="virtual",
                   virtual_momentum=0.9, num_workers=W, grad_size=d,
                   num_rows=3, num_cols=64, mesh="2x2",
                   async_buffer_size=W, async_staleness_weight=alpha)
    rng = np.random.default_rng(8)
    c = rng.normal(size=(W, 1, d)).astype(np.float32)

    def lin_loss(p, b):
        n = jnp.maximum(jnp.sum(b["mask"]), 1.0)
        loss = jnp.sum((b["c"] @ p) * b["mask"]) / n
        return loss, (loss * 0.0,)

    batch = {"c": jnp.asarray(np.broadcast_to(c, (W, B, d))),
             "mask": jnp.ones((W, B), jnp.float32)}
    stale = _staleness_from("churny", W, seed=17)
    flat = jnp.zeros((d,), jnp.float32)

    def run(mesh):
        cr = jax.jit(build_client_round(cfg, lin_loss, B, mesh=mesh,
                                        client_weights=True))
        res = cr(flat, ClientStates.init(cfg, W, flat), batch,
                 jnp.arange(W, dtype=jnp.int32), jax.random.PRNGKey(0),
                 jnp.float32(1.0), jnp.asarray(stale))
        return np.asarray(jax.device_get(res.aggregated))

    agg2d = run(make_mesh2d(2, 2)).reshape(3, -1)
    agg1d = run(None)
    np.testing.assert_allclose(agg2d, agg1d, rtol=1e-5, atol=1e-5)
    # and the table is the sketch of the weighted dense mean
    wts = np_staleness_weights(stale, alpha).astype(np.float64)
    n_per = np.full((W,), float(B))
    total = max(float((wts * n_per).sum()), 1.0)
    dense = np.einsum("w,wd->d", wts * n_per,
                      c[:, 0, :].astype(np.float64)) / total
    table = np.asarray(jax.jit(args2sketch(cfg).sketch)(
        jnp.asarray(dense, jnp.float32)))
    np.testing.assert_allclose(agg1d, table, rtol=1e-5, atol=1e-5)


# -- observatory surface ------------------------------------------------


def test_async_staleness_alarm_rule():
    from commefficient_tpu.telemetry.alarms import build_alarm_engine

    cfg = make_cfg(async_buffer_size=2, async_staleness_weight=0.5,
                   alarm_async_staleness=3.0)
    eng = build_alarm_engine(cfg)
    assert eng is not None
    assert eng.check(0, {"async_staleness_max": 2.0}) == []
    fired = eng.check(1, {"async_staleness_max": 5.0,
                          "async_buffer_occupancy": 0.5,
                          "async_backlog": 7.0})
    assert [f["rule"] for f in fired] == ["async_staleness"]
    assert fired[0]["value"] == 5.0 and fired[0]["backlog"] == 7.0
    # rule off: nothing fires regardless of staleness
    off = build_alarm_engine(make_cfg(alarm_recovery_error=0.9))
    assert off is None or off.check(0, {"async_staleness_max": 99.0}) \
        == []


def test_gate_async_topology_key_no_fallback():
    from commefficient_tpu.telemetry import gate

    assert gate.async_suffix(None) == ""
    assert gate.async_suffix(0) == ""
    assert gate.async_suffix(4) == "a4"
    assert gate.topology_key(8, 1, None, None, 4) == "d8p1a4"
    assert gate.topology_key(None, None, None, None, 4) == "any-a4"

    base = {}
    base = gate.update_baseline(base, {"round_ms": {"median": 1.0,
                                                    "mad": 0.1}},
                                source="x", device_count=8,
                                process_count=1)
    # a buffered run must NEVER fall back onto the synchronous entry
    assert gate.baseline_entry(base, 8, 1, None, None, 4) is None
    base = gate.update_baseline(base, {"round_ms": {"median": 2.0,
                                                    "mad": 0.1}},
                                source="y", device_count=8,
                                process_count=1, async_k=4)
    e = gate.baseline_entry(base, 8, 1, None, None, 4)
    assert e and e["metrics"]["round_ms"]["median"] == 2.0
    # ...and a synchronous run never reads the buffered entry
    e = gate.baseline_entry(base, 8, 1, None, None, None)
    assert e and e["metrics"]["round_ms"]["median"] == 1.0
    # the mesh-blind fallback drops ONLY the mesh fragment: the a<K>
    # fragment survives it
    base = gate.update_baseline(base, {"round_ms": {"median": 3.0,
                                                    "mad": 0.1}},
                                source="z", device_count=8,
                                process_count=1, async_k=2)
    hit = gate.baseline_entry(base, 8, 1,
                              {"clients": 4, "model": 2}, None, 2)
    assert hit and hit["metrics"]["round_ms"]["median"] == 3.0


def test_registry_run_key_async_fragment():
    from commefficient_tpu.telemetry import registry

    man = {"config_hash": "abc", "device_count": 8,
           "process_count": 1,
           "config": {"mode": "local_topk", "async_buffer_size": 4}}
    assert registry.run_async_k(man) == 4
    assert registry.run_key(man) == ("abc", 8, 1, "a4")
    man["config"]["async_buffer_size"] = 0
    assert registry.run_async_k(man) is None
    assert registry.run_key(man) == ("abc", 8, 1)


def test_perf_gate_resolves_async_k():
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import perf_gate

    man = {"config": {"mode": "sketch", "async_buffer_size": 3},
           "device_count": 2, "process_count": 1}
    assert perf_gate.resolve_topology(man)[4] == 3
    recs = [{"kind": "meta", "num_devices": 4,
             "plan": {"async_buffer_size": 6}}]
    assert perf_gate.resolve_topology(None, recs)[4] == 6
    # CLI override wins; synchronous runs resolve to None
    assert perf_gate.resolve_topology(man, async_k=8)[4] == 8
    man["config"]["async_buffer_size"] = 0
    assert perf_gate.resolve_topology(man)[4] is None


def test_config_validates_async_bounds():
    with pytest.raises(AssertionError):
        make_cfg(async_buffer_size=-1).validate()
    with pytest.raises(AssertionError):
        make_cfg(async_buffer_size=8).validate_runtime()  # > workers
    with pytest.raises(AssertionError):
        make_cfg(async_buffer_size=2, client_chunk=2,
                 num_workers=4).validate_runtime()
    make_cfg(async_buffer_size=2).validate_runtime()
