"""Fused-linear-CE kernels (ops/flce_pallas.py) vs the chunked
tied-head cross-entropy (models/gpt2.py lm_nll_sums_chunked).

The chunked path is the numeric reference: same math, logits
materialised one chunk at a time. The fused kernels must reproduce its
per-example (Σ nll, Σ valid) and its gradients w.r.t. hidden states
and the tied embedding, including ignore_index masking, padding to
tile multiples (token, vocab, both), bf16 compute, and vmap batching
over a client axis. On CPU the kernels run in interpreter mode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.models.gpt2 import lm_nll_sums_chunked
from commefficient_tpu.ops.flce_pallas import (lm_nll_sums_fused,
                                               resolve_fused_ce,
                                               supported)

# (E, Tm, C, V) — all far below one (1024, 2048) tile, so padding of
# both axes is always exercised; V=2500 crosses a vocab-block border
SHAPES = [
    (3, 17, 128, 301),
    (2, 40, 256, 2500),
    (1, 9, 128, 2048),   # V exactly one block
]


def _case(e, tm, c, v, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    h = jnp.asarray(rng.randn(e, tm, c), dtype)
    w = jnp.asarray(rng.randn(v, c) * 0.1, dtype)
    lab = rng.randint(0, v, (e, tm))
    lab[0, : min(5, tm)] = -100            # ignored prefix
    return h, w, jnp.asarray(lab, jnp.int32)


@pytest.mark.parametrize("e,tm,c,v", SHAPES)
def test_forward_matches_chunked(e, tm, c, v):
    h, w, lab = _case(e, tm, c, v)
    sn0, sv0 = lm_nll_sums_chunked(h, w, lab, jnp.float32)
    sn1, sv1 = lm_nll_sums_fused(h, w, lab, jnp.float32,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(sn0), np.asarray(sn1),
                               rtol=1e-5, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(sv0), np.asarray(sv1))


@pytest.mark.parametrize("e,tm,c,v", SHAPES[:2])
def test_gradients_match_chunked(e, tm, c, v):
    h, w, lab = _case(e, tm, c, v, seed=1)
    # per-example weights exercise distinct cotangents per token row
    wt = jnp.asarray(np.random.RandomState(2).randn(e), jnp.float32)

    def loss(fn, kw):
        def f(h, w):
            sn, _ = fn(h, w, lab, jnp.float32, **kw)
            return jnp.sum(sn * wt)
        return f

    g0 = jax.grad(loss(lm_nll_sums_chunked, {}), (0, 1))(h, w)
    g1 = jax.grad(loss(lm_nll_sums_fused, {"interpret": True}),
                  (0, 1))(h, w)
    for a, b in zip(g0, g1):
        scale = max(1e-9, float(jnp.max(jnp.abs(a))))
        np.testing.assert_allclose(np.asarray(a) / scale,
                                   np.asarray(b) / scale,
                                   rtol=0, atol=2e-4)


def test_all_ignored_example_is_zero():
    h, w, lab = _case(2, 12, 128, 301, seed=3)
    lab = lab.at[1].set(-100)
    sn, sv = lm_nll_sums_fused(h, w, lab, jnp.float32, interpret=True)
    assert float(sn[1]) == 0.0 and float(sv[1]) == 0.0


def test_vmap_bf16_matches_chunked():
    # v=2500 spans two vocab blocks, so the backward's dX partials
    # reduction (now accumulated in f32, not bf16) is exercised
    rng = np.random.RandomState(4)
    W_, e, tm, c, v = 2, 2, 30, 128, 2500
    h = jnp.asarray(rng.randn(W_, e, tm, c), jnp.float32)
    w = jnp.asarray(rng.randn(v, c) * 0.1, jnp.float32)
    lab = jnp.asarray(rng.randint(0, v, (W_, e, tm)), jnp.int32)

    def make(fn, kw):
        def per_client(h, lab, w):
            sn, sv = fn(h, w, lab, jnp.bfloat16, **kw)
            return jnp.sum(sn / jnp.maximum(sv, 1.0))
        return lambda h, w: jnp.sum(
            jax.vmap(per_client, (0, 0, None))(h, lab, w))

    l0, (gh0, gw0) = jax.value_and_grad(
        make(lm_nll_sums_chunked, {}), (0, 1))(h, w)
    l1, (gh1, gw1) = jax.value_and_grad(
        make(lm_nll_sums_fused, {"interpret": True}), (0, 1))(h, w)
    # bf16 compute: summation-order differences only. Tolerance is
    # 2x tighter than before the f32 dX-partials accumulation.
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-2)
    for g0, g1 in ((gh0, gh1), (gw0, gw1)):
        scale = float(jnp.max(jnp.abs(g0)))
        np.testing.assert_allclose(np.asarray(g0) / scale,
                                   np.asarray(g1) / scale,
                                   rtol=0, atol=1e-2)


def test_dxp_guard_scales_with_vmap_multiplicity(monkeypatch):
    """The dX-partials OOM guard must account for the vmapped client
    axis: N clients materialise N partials buffers concurrently, so a
    geometry that fits per-call can still blow the cap under vmap
    (ADVICE.md: 8 x 315 MB passing a 512 MB check)."""
    import warnings

    from commefficient_tpu.ops import flce_pallas

    e, tm, c, v = 2, 30, 128, 301
    _, mp, _, _, nv = flce_pallas._tile_geometry(
        e * tm, v, flce_pallas._BLOCK_M, flce_pallas._BLOCK_V)
    one_call = nv * mp * c * jnp.dtype(jnp.float32).itemsize
    # cap between 1x and 8x the per-call buffer
    monkeypatch.setattr(flce_pallas, "_DXP_LIMIT", 4 * one_call)
    assert flce_pallas.fused_fallback_reason(
        e, tm, c, v, jnp.float32, interpret=True, batch_mult=1) is None
    reason = flce_pallas.fused_fallback_reason(
        e, tm, c, v, jnp.float32, interpret=True, batch_mult=8)
    assert reason is not None and "dX partials" in reason

    # the fallback is correct (chunked numbers) and warns, once
    h, w, lab = _case(e, tm, c, v, seed=7)
    monkeypatch.setattr(flce_pallas, "_warned_fallbacks", set())
    sn0, sv0 = lm_nll_sums_chunked(h, w, lab, jnp.float32)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        sn1, sv1 = flce_pallas.lm_nll_sums_fused(
            h, w, lab, jnp.float32, interpret=True, batch_mult=8)
        flce_pallas.lm_nll_sums_fused(
            h, w, lab, jnp.float32, interpret=True, batch_mult=8)
    hits = [r for r in rec if "falling back" in str(r.message)]
    assert len(hits) == 1, "fallback warning must fire exactly once"
    np.testing.assert_allclose(np.asarray(sn0), np.asarray(sn1),
                               rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(sv0), np.asarray(sv1))


def test_unaligned_width_falls_back_to_chunked():
    assert not supported(96)
    h, w, lab = _case(2, 11, 96, 301, seed=5)
    sn0, sv0 = lm_nll_sums_chunked(h, w, lab, jnp.float32)
    sn1, sv1 = lm_nll_sums_fused(h, w, lab, jnp.float32,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(sn0), np.asarray(sn1),
                               rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(sv0), np.asarray(sv1))


def test_resolve_fused_ce():
    assert resolve_fused_ce("on", 768)
    assert not resolve_fused_ce("off", 768)
    # auto follows the default backend: engaged on TPU, off elsewhere
    assert resolve_fused_ce("auto", 768) == (
        jax.default_backend() == "tpu")
    assert not resolve_fused_ce("auto", 96)  # unaligned width
