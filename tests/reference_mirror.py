"""Independent NumPy mirror of the reference's federated-round math.

Implements, in plain NumPy and torch-free, exactly the semantics of
/root/reference/CommEfficient fed_worker.py:142-337 (client side) and
fed_aggregator.py:431-615 (server side), for use as a test oracle
against the JAX engine. Written from the reference's equations, not
its code structure.

NB the reference repo's own unit_test.py traces (w2=0.3808 etc.)
target an *obsolete* API and are unreachable under the current
reference code (e.g. current math gives w2=0.2604 for the 1-param
case); this mirror is the oracle for the *current* semantics.
"""

from __future__ import annotations

import ml_dtypes
import numpy as np


def np_topk(v, k):
    out = np.zeros_like(v)
    if k >= v.size:
        return v.copy()
    idx = np.argsort(v ** 2)[-k:]
    out[idx] = v[idx]
    return out


# robust folds (mirror of core/robust.py) ------------------------------

_TINY = 1e-12


def np_clip_factors(norms, tau):
    """Mirror of core/robust.clip_factors — the ONE per-vector
    norm-clip algebra shared by the ``clip`` robust fold and the DP
    per-client clip (privacy/mechanism.py), restated here in NumPy
    with the same ``_TINY`` guard."""
    return np.minimum(1.0, tau / np.maximum(norms, _TINY))


def np_masked_median(vals, alive):
    """Coordinate-wise median over alive rows; same rank formula as
    core/robust._masked_median (dead rows sort to +inf)."""
    G = vals.shape[0]
    s = np.sort(np.where(alive[:, None], vals, np.inf), axis=0)
    k = int(np.sum(alive))
    if k == 0:
        return np.zeros(vals.shape[1])
    lo = min(max((k - 1) // 2, 0), G - 1)
    hi = min(k // 2, G - 1)
    return 0.5 * (s[lo] + s[hi])


def np_masked_trimmed_mean(vals, alive, trim_frac):
    G = vals.shape[0]
    s = np.sort(np.where(alive[:, None], vals, np.inf), axis=0)
    k = int(np.sum(alive))
    t = int(np.floor(trim_frac * k))
    ranks = np.arange(G)[:, None]
    wm = (ranks >= t) & (ranks < k - t)
    kept = np.where(wm, s, 0.0).sum(axis=0)
    denom = np.maximum(wm.sum(axis=0).astype(np.float64), 1.0)
    return kept / denom


def np_robust_fold(cfg, transmits, counts, capacity=None):
    """Mirror of core/robust.robust_fold over a list of per-client
    transmit arrays (already scaled by batch size) and their
    datapoint counts. ``capacity`` is the engine round's padded
    per-client batch size (needed under --dp sketch, where the fold
    normalises by the static W·capacity). Returns (aggregated,
    fold_rejection_rate)."""
    T = np.stack([np.asarray(t, np.float64).ravel() for t in transmits])
    W = T.shape[0]
    n = np.asarray(counts, np.float64)
    alive = n > 0
    if getattr(cfg, "dp", "off") == "sketch":
        # static capacity denominator (core/robust.py): W·B
        cap = capacity if capacity is not None else max(n.max(), 1.0)
        total = float(W) * float(cap)
    else:
        total = max(float(n.sum()), 1.0)
    plain = T.sum(axis=0) / total
    g = T / np.maximum(n, 1.0)[:, None]

    mode = cfg.robust_agg
    if mode == "median":
        groups = getattr(cfg, "robust_median_groups", 0)
        if 1 < groups < W:
            assert W % groups == 0, (W, groups)
            gsum = T.reshape(groups, W // groups, -1).sum(axis=1)
            gn = n.reshape(groups, W // groups).sum(axis=1)
            galive = alive.reshape(groups, W // groups).any(axis=1)
            gv = gsum / np.maximum(gn, 1.0)[:, None]
        else:
            gv, galive = g, alive
        agg = np_masked_median(gv, galive)
    elif mode == "trimmed":
        agg = np_masked_trimmed_mean(g, alive, cfg.robust_trim_frac)
    elif mode == "clip":
        norms = np.sqrt(np.sum(g * g, axis=1))
        if cfg.robust_clip_norm > 0:
            tau = float(cfg.robust_clip_norm)
        else:
            tau = float(np_masked_median(norms[:, None], alive)[0])
        scale = np_clip_factors(norms, tau)
        agg = np.sum(scale[:, None] * T, axis=0) / total
    else:
        raise ValueError(f"unknown robust_agg {mode!r}")

    rej = (np.linalg.norm(plain - agg)
           / max(np.linalg.norm(plain), _TINY))
    return agg.reshape(np.shape(transmits[0])), float(rej)


def np_staleness_weights(staleness, alpha):
    """Mirror of core/server.staleness_weights: the buffered-async
    fold's per-client down-weight ``1/(1+s)^alpha``, computed in f32
    exactly like the jitted step (the weight multiplies both the
    transmit and its datapoint count before the fold)."""
    s = np.asarray(staleness, np.float32)
    return (1.0 + s) ** np.float32(-float(alpha))


# wire quantization (mirror of ops/quant.py) --------------------------

NP_WIRE_DTYPES = {"bf16": np.dtype(ml_dtypes.bfloat16),
                  "int8": np.dtype(np.int8),
                  "fp8": np.dtype(ml_dtypes.float8_e4m3fn)}
NP_QMAX = {"int8": 127.0, "fp8": 448.0}


def np_qeff(wire, n_addends):
    """Per-addend wire range under summation headroom; identical
    formula to ops/quant.qeff (int8 floors to an integer step)."""
    q = NP_QMAX[wire]
    if wire == "int8":
        return float(max(1, int(q // max(1, n_addends))))
    return q / float(max(1, n_addends))


def np_quantize_table(table, wire, n_addends=1, global_rowmax=None):
    """f32 sketch table -> (wire-dtype table, f32 per-row scale) —
    the local-quantize + harmonize scheme of ops/quant.py, in NumPy.
    All arithmetic is float32 (same dtype the engine traces in) and
    the bf16/fp8 casts share ml_dtypes' conversion code with jax, so
    at ``n_addends=1`` with ``global_rowmax=None`` (the single-shard
    wire crossing the engine's ``_qdq_local`` performs) the result is
    bit-identical to the device path. ``scale`` is None for bf16."""
    t = np.asarray(table, np.float32)
    if wire == "bf16":
        return t.astype(NP_WIRE_DTYPES["bf16"]), None
    qmax = np.float32(NP_QMAX[wire])
    rowmax = np.max(np.abs(t), axis=-1, keepdims=True)
    s_local = np.where(rowmax > 0, rowmax / qmax,
                       np.float32(1.0)).astype(np.float32)
    if wire == "int8":
        q = np.clip(np.round(t / s_local), -qmax, qmax)
    else:
        # fp8 rounds through an EXPLICIT f16 intermediate, exactly as
        # ops/quant._to_fp8 does on device
        q = (t / s_local).astype(np.float16).astype(
            NP_WIRE_DTYPES["fp8"])
    if global_rowmax is None:
        global_rowmax = rowmax
    g = np.asarray(global_rowmax, np.float32).reshape(rowmax.shape)
    qe = np.float32(np_qeff(wire, n_addends))
    s_global = np.where(g > 0, g / qe,
                        np.float32(1.0)).astype(np.float32)
    ratio = (s_local / s_global).astype(np.float32)
    if wire == "int8":
        q = np.clip(np.round(q.astype(np.float32) * ratio),
                    -qmax, qmax).astype(np.int8)
    else:
        q = (q.astype(np.float32) * ratio).astype(
            np.float16).astype(NP_WIRE_DTYPES["fp8"])
    return q, s_global


def np_dequantize_table(q, scale):
    """Wire-dtype table -> f32 (mirror of ops/quant.dequantize)."""
    t = np.asarray(q).astype(np.float32)
    if scale is None:
        return t
    return t * np.asarray(scale, np.float32)


def np_qdq_table(table, wire):
    """Full single-shard wire crossing: quantize at full range and
    dequantize. f32 is a passthrough (no wire crossing exists)."""
    if wire == "f32":
        return np.asarray(table, np.float32)
    return np_dequantize_table(*np_quantize_table(table, wire))


class MirrorFed:
    """Dense-mode mirror (uncompressed / true_topk / local_topk /
    fedavg). Sketch mode is exercised through the shared CountSketch op
    (itself independently property-tested)."""

    def __init__(self, cfg, w0, num_clients, sketch=None):
        self.cfg = cfg
        self.w = np.asarray(w0, np.float64).copy()
        d = self.w.size
        shape = ((cfg.num_rows, cfg.num_cols) if cfg.mode == "sketch"
                 else (d,))
        self.Vvel = np.zeros(shape)
        self.Verr = np.zeros(shape)
        self.vel = np.zeros((num_clients,) + shape)
        self.err = np.zeros((num_clients,) + shape)
        # --topk_down stale per-client weights (fed_worker.py:234-249)
        self.client_w = (np.tile(self.w, (num_clients, 1))
                         if getattr(cfg, "do_topk_down", False)
                         else None)
        self.sketch = sketch
        # schema-v2 probe oracle: round() fills this with the same
        # keys the engine's --probe_every path computes (client
        # aggregate/transmit norms + server state norms/coverage +
        # sketch recovery error). Keys the engine's fast paths omit
        # (e.g. client_norm_* on the fused path) are still computed
        # here; tests compare only the engine's keys.
        self.last_probes = None
        self._dense_tt = []

    # client math ---------------------------------------------------------

    def _grad_mean(self, X, y, w):
        """MSE mean loss: L = mean_i (w.x_i - y_i)^2."""
        r = X @ w - y
        return (2.0 / len(y)) * (X.T @ r)

    def _grad_unit(self, X, y, w, B=None):
        """Masked-mean gradient with the reference's microbatch quirk:
        sum over microbatches of the per-microbatch MEAN gradient
        (fed_worker.py:267-289; core/grad.py). Microbatch boundaries
        run over the round's PADDED batch size ``B`` — a client with
        fewer real samples contributes empty tail chunks that the
        engine skips, exactly as here."""
        mb = getattr(self.cfg, "microbatch_size", -1)
        n = len(y)
        B = n if B is None else B
        if mb is None or mb <= 0 or mb >= B:
            return self._grad_mean(X, y, w)
        g = np.zeros_like(w)
        for s in range(0, B, mb):
            e = min(s + mb, n)
            if e > s:
                g = g + self._grad_mean(X[s:e], y[s:e], w)
        return g

    def _client_transmit(self, cid, X, y, B=None):
        cfg = self.cfg
        w = self.w
        if self.client_w is not None:
            # catch up the stale local weights by the top-k of the
            # difference only, then train (and decay) at those weights
            w = self.client_w[cid] + np_topk(self.w - self.client_w[cid],
                                             cfg.k)
            self.client_w[cid] = w.copy()
        g = self._grad_unit(X, y, w, B)
        if cfg.weight_decay:
            g = g + cfg.weight_decay / cfg.num_workers * w
        if cfg.do_dp:
            # clip to l2_norm_clip (fed_worker.py:306-307); worker-mode
            # noise is tested separately with noise_multiplier=0
            norm = np.linalg.norm(g)
            if norm > cfg.l2_norm_clip:
                g = g * (cfg.l2_norm_clip / norm)
        if getattr(cfg, "dp", "off") == "sketch":
            # --dp sketch per-client clip (privacy/mechanism.dp_clip):
            # the shared clip algebra on the microbatch-accumulated
            # dense gradient (never divided by batch size), before
            # sketching — the transmit then scales it by len(y)
            g = g * np_clip_factors(np.linalg.norm(g), cfg.dp_clip)
        if cfg.mode == "sketch":
            # dense pre-sketch transmit: ground truth for the
            # recovery-error probe (valid when no table-space
            # per-client state exists, matching the engine's gating)
            self._dense_tt.append(np.asarray(g, np.float64) * len(y))
            g = np.asarray(self.sketch.sketch(
                np.asarray(g, np.float32)), np.float64)
        g = g * len(y)  # sum-of-grads semantics (fed_worker.py:192)
        if cfg.local_momentum > 0:
            self.vel[cid] = g + cfg.local_momentum * self.vel[cid]
        if cfg.error_type == "local":
            self.err[cid] += (self.vel[cid] if cfg.local_momentum > 0
                              else g)
            tt = self.err[cid].copy()
        else:
            tt = (self.vel[cid].copy() if cfg.local_momentum > 0
                  else g.copy())
        if cfg.mode == "local_topk":
            tt = np_topk(tt, cfg.k)
            nz = tt != 0
            if cfg.error_type == "local":
                self.err[cid][nz] = 0
            if cfg.local_momentum > 0:
                self.vel[cid][nz] = 0
        return tt

    # server math ---------------------------------------------------------

    def _coverage(self, sel_mass, dense_mass):
        return sel_mass / dense_mass if dense_mass > 0 else 1.0

    def _record_server_probes(self, upd_scaled, extra=None):
        """Same quantities as core/server.py's ``_state_probes``:
        norms of the POST-masking state, plus the lr-scaled update."""
        pr = {"update_norm": np.linalg.norm(upd_scaled),
              "momentum_norm": np.linalg.norm(self.Vvel),
              "residual_norm": np.linalg.norm(self.Verr)}
        if extra:
            pr.update(extra)
        self.last_probes.update(pr)

    def _server(self, agg, lr, participating):
        cfg = self.cfg
        rho = cfg.virtual_momentum
        if cfg.mode in ("uncompressed", "fedavg", "local_topk"):
            self.Vvel = agg + rho * self.Vvel
            eff_lr = 1.0 if cfg.mode == "fedavg" else lr
            upd = self.Vvel * eff_lr
            self._record_server_probes(upd)
            return upd
        if cfg.mode == "true_topk":
            self.Vvel = agg + rho * self.Vvel
            self.Verr = self.Verr + self.Vvel
            dense_mass = float(np.sum(self.Verr ** 2))  # pre-masking
            upd = np_topk(self.Verr, cfg.k)
            nz = upd != 0
            self.Verr[nz] = 0
            self.Vvel[nz] = 0
            if cfg.local_momentum > 0:
                for cid in participating:
                    self.vel[cid][nz] = 0
            self._record_server_probes(
                upd * lr,
                {"mass_coverage": self._coverage(
                    float(np.sum(upd ** 2)), dense_mass)})
            return upd * lr
        if cfg.mode == "sketch":
            self.Vvel = agg + rho * self.Vvel
            if cfg.error_type == "local":
                self.Verr = self.Vvel.copy()
            elif cfg.error_type == "virtual":
                self.Verr = self.Verr + self.Vvel
            # dense residual mass is unknowable in table space: the
            # engine probes the table's own unbiased l2estimate
            dense_mass = float(np.asarray(self.sketch.l2estimate(
                np.asarray(self.Verr, np.float32)))) ** 2
            upd = np.asarray(self.sketch.unsketch(
                np.asarray(self.Verr, np.float32), k=cfg.k), np.float64)
            su = np.asarray(self.sketch.sketch(
                np.asarray(upd, np.float32)), np.float64)
            nz = su != 0
            if cfg.error_type == "virtual":
                self.Verr[nz] = 0
            self.Vvel[nz] = 0
            if cfg.error_type == "local":
                self.Verr = self.Vvel.copy()
            self._record_server_probes(
                upd * lr,
                {"mass_coverage": self._coverage(
                    float(np.sum(upd ** 2)), dense_mass)})
            return upd * lr
        raise ValueError(cfg.mode)

    # round ---------------------------------------------------------------

    def round(self, clients, lr, B=None, rng=None):
        """clients: list of (client_id, X, y). Returns new weights.
        ``B``: the engine round's padded batch size (microbatch
        boundaries depend on it; None = no padding). ``rng``: the
        round's PRNG key as passed to the engine round — required
        under ``--dp sketch`` with ``dp_noise_mult > 0`` (the mirror
        draws the SAME table noise via privacy.round_noise_key)."""
        total = sum(len(y) for _, _, y in clients)
        self._dense_tt = []
        transmits = [self._client_transmit(cid, X, y, B)
                     for cid, X, y in clients]
        robust = getattr(self.cfg, "robust_agg", "none") != "none"
        wire = getattr(self.cfg, "sketch_dtype", "f32")
        quantized = self.cfg.mode == "sketch" and wire != "f32"
        # --dp sketch: the engine disables every pre-noise wire qdq
        # (noise BEFORE quantization — core/rounds.py) and applies one
        # qdq to the noisy aggregated table instead
        dp_on = getattr(self.cfg, "dp", "off") == "sketch"
        dp_qdq = quantized and dp_on
        if dp_on:
            quantized = False
            # static W·B capacity denominator (core/rounds.py): each
            # transmit is bounded by dp_clip·n_i, so only a
            # data-independent denominator keeps every client's share
            # within the charged sqrt(r)·C/W sensitivity
            cap = B if B is not None else max(len(y) for _, _, y in clients)
            total = float(len(clients)) * float(cap)
        # where the table crosses the wire (mirrors the engine's path
        # split in core/rounds.py): clip / robust paths upload
        # per-client tables, so each transmit is quantized BEFORE the
        # fold; the sketch-late paths upload one summed table, so the
        # sum quantizes before the division. (The fused path qdq's
        # after the division — the scheme is scale-invariant up to
        # rounding, so both forms agree; tolerances absorb the ULPs.)
        late = (self.cfg.mode == "sketch"
                and self.cfg.max_grad_norm is None and not robust)
        if quantized and not late:
            transmits = [np_qdq_table(t, wire).astype(np.float64)
                         for t in transmits]
        rej = None
        if robust:
            agg, rej = np_robust_fold(
                self.cfg, transmits, [len(y) for _, _, y in clients],
                capacity=B)
        elif quantized:
            agg = np_qdq_table(
                np.sum(transmits, axis=0), wire).astype(np.float64) \
                / total
        else:
            agg = np.sum(transmits, axis=0) / total
        if dp_on:
            from commefficient_tpu.privacy import (np_dp_noise,
                                                   round_noise_key,
                                                   table_noise_std)
            std = table_noise_std(self.cfg)
            if std > 0:
                assert rng is not None, \
                    "MirrorFed.round needs the engine round's rng " \
                    "under --dp sketch"
                agg = agg + np_dp_noise(round_noise_key(rng),
                                        np.shape(agg),
                                        std).astype(np.float64)
            if dp_qdq:
                agg = np_qdq_table(agg, wire).astype(np.float64)
        # sketch-late engine paths materialise DENSE per-client
        # transmits (the table appears only after the local sum), so
        # the transmit-norm probes are over the dense vectors there;
        # robust folds force per-client sketching, so their norm
        # probes are back over the tables
        norm_src = (self._dense_tt
                    if (self.cfg.mode == "sketch" and self._dense_tt
                        and self.cfg.max_grad_norm is None
                        and not robust)
                    else transmits)
        self.last_probes = self._client_probes(agg, norm_src)
        if rej is not None:
            self.last_probes["fold_rejection_rate"] = rej
        if self.cfg.mode == "sketch" and self._dense_tt:
            dense_agg = np.sum(self._dense_tt, axis=0) / total
            est = np.asarray(self.sketch.unsketch(
                np.asarray(agg, np.float32), k=self.cfg.k), np.float64)
            den = np.linalg.norm(dense_agg)
            self.last_probes["recovery_error"] = (
                np.linalg.norm(est - dense_agg) / den if den > 0
                else 0.0)
        upd = self._server(agg, lr, [cid for cid, _, _ in clients])
        self.w = self.w - upd
        return self.w.copy()

    def _client_probes(self, agg, transmits):
        norms = np.array([np.linalg.norm(t) for t in transmits])
        return {
            "agg_norm": np.linalg.norm(agg),
            "agg_nan": float(np.sum(np.isnan(agg))),
            "agg_inf": float(np.sum(np.isinf(agg))),
            "client_norm_mean": norms.mean(),
            "client_norm_max": norms.max(),
            "client_norm_std": norms.std(),
        }

    def round_fedavg(self, clients, lr):
        """FedAvg local SGD (fed_worker.py:62-114): per client, split
        its data into fedavg_batch_size chunks, run
        num_fedavg_epochs x n_batches decayed-LR SGD steps, transmit
        (w0 - w_final) * |client data|."""
        cfg = self.cfg
        total = sum(len(y) for _, _, y in clients)
        transmits = []
        for cid, X, y in clients:
            w = self.w.copy()
            n = len(y)
            bs = n if cfg.fedavg_batch_size == -1 else cfg.fedavg_batch_size
            step = 0
            for _ in range(cfg.num_fedavg_epochs):
                for s in range(0, n, bs):
                    Xb, yb = X[s:s + bs], y[s:s + bs]
                    g = self._grad_mean(Xb, yb, w)
                    if cfg.weight_decay:
                        g = g + cfg.weight_decay / cfg.num_workers * w
                    w = w - g * lr * (cfg.fedavg_lr_decay ** step)
                    step += 1
            transmits.append((self.w - w) * n)
        agg = np.sum(transmits, axis=0) / total
        self.last_probes = self._client_probes(agg, transmits)
        upd = self._server(agg, 1.0, [c for c, _, _ in clients])
        self.w = self.w - upd
        return self.w.copy()
