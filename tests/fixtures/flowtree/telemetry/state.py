"""Lock-confinement fixtures: _LOCK_MAP-declared state written and
iterated with and without the lock."""

import threading

_LOCK_MAP = {"_items": "_lock"}


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []

    def add_unlocked(self, x):
        self._items.append(x)

    def add_locked(self, x):
        with self._lock:
            self._items.append(x)

    def snapshot(self):
        with self._lock:
            return list(self._items)

    def leak_iter(self):
        return [x for x in self._items]
