"""Thread root: ``Thread(target=self._run)`` makes ``Pump._run`` a
thread entry point (program.thread_roots)."""

import threading


class Pump:
    def __init__(self):
        self.thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        return drain()


def drain():
    return 0
