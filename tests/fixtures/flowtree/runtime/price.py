"""A private wire-width byte table outside the owners — flagged."""

WIDTH = {"int8": 1, "bf16": 2}
