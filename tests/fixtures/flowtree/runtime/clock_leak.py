"""Legacy-tier seeds: raw-clock fires twice, one waived."""

import time


def probe():
    # audit: allow(raw-clock) — fixture waiver
    return time.time()


def stamp():
    return time.time()
