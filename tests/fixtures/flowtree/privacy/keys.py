"""PRNG-key discipline fixtures: one reuse-after-split, one dropped
split stream, and the clean disjoint-stream idiom."""

from jax import random


def bad_reuse(rng):
    k1, k2 = random.split(rng)
    a = random.normal(rng, (4,))
    return a, k1, k2


def bad_drop(rng):
    k1, k2 = random.split(rng)
    return random.normal(k1, (4,))


def good(rng):
    k1, k2 = random.split(rng)
    return random.normal(k1, ()) + random.uniform(k2, ())


def good_fold(rng, t):
    child = random.fold_in(rng, t)
    other = random.fold_in(rng, t + 1)
    return random.normal(child, ()) + random.normal(other, ())
