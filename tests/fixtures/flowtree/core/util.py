"""Helpers called from the traced closure in rounds.py — the
impurity lives HERE, two call-graph hops from the jit root."""

import time


def tick():
    return time.time()


def helper(x):
    return x * 2
