"""Legacy-tier seed: mutable default argument."""


def accumulate(x, out=[]):
    out.append(x)
    return out
