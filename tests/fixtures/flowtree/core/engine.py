"""Method dispatch: the traced closure calls ``eng.run`` on a local
constructed in the enclosing builder scope; ``run`` dispatches
``self.now()`` through the base class, where the clock hides."""

import time

import jax


class Base:
    def now(self):
        return time.time()


class Engine(Base):
    def run(self, x):
        return self.now() + x


def build(cfg):
    eng = Engine()

    def traced(x):
        return eng.run(x)

    return traced


step = jax.jit(build(None))
