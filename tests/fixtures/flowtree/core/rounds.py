"""The builder idiom: ``jax.jit(build_outer(cfg))`` roots every
closure inside build_outer AND — through the sibling-return hop —
inside build_round."""

import time

import jax

import core.util as cu
from core.util import helper as aliased_helper


def build_round(cfg):
    def traced(x):
        cu.tick()
        return aliased_helper(x)

    return traced


def build_outer(cfg):
    return build_round(cfg)


def host_loop(x):
    # unreachable from any jit root: host impurity is fine here
    print(x)
    return time.time()


step = jax.jit(build_outer(None))
