"""Wire-dtype crossing: an unowned int8 cast (flagged) and a waived
bf16 cast (suppressed, recorded)."""

import jax.numpy as jnp


def encode_wrong(x):
    return x.astype(jnp.int8)


def canary(x):
    # audit: allow(wire-dtype-crossing) — fixture waiver
    return x.astype(jnp.bfloat16)
