"""The sanctioned cast owner: the same casts are clean here."""

import jax.numpy as jnp


def encode(x):
    return x.astype(jnp.int8)


WIDTH = {"int8": 1, "bf16": 2, "f32": 4}
