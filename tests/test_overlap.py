"""--overlap_depth latency-hiding pipeline: chunked sketch emission
with compute-overlapped wire collectives must be invisible to the
numbers. Per-row quantization scales make every row chunk's
quantize/harmonize/collective exactly the row slice of the
whole-table algebra, so the folded table is BIT-identical to the
serial program at any depth, on any mesh, for every wire dtype —
asserted here against both the engine's own depth-1 program and the
NumPy reference mirror. Dead dropout slots must stay neutral per
chunk, and the 2D (clients x model) sharded round must keep its 1-D
oracle parity under overlap."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import (ClientStates,
                                           build_client_round)
from commefficient_tpu.core.server import fold_row_chunks
from commefficient_tpu.ops import quant
from commefficient_tpu.parallel.mesh import (client_sharding,
                                             make_mesh, make_mesh2d)
from commefficient_tpu.parallel.wire import row_chunks

from reference_mirror import np_qdq_table, np_quantize_table
from test_modes import linear_loss
from test_mesh2d import _assert_state_close, _run_rounds
from test_sharding import _batch, _setup

WIRES = ["f32", "bf16", "int8", "fp8"]
SCALED = ["bf16", "int8", "fp8"]


def test_row_chunks_cover_rows_disjointly():
    """Ceil-sized chunks (at most min(depth, r) of them), in row
    order, exactly covering [0, r) — the contract every chunked path
    folds on."""
    assert row_chunks(3, 1) == [(0, 3)]
    assert row_chunks(3, 2) == [(0, 2), (2, 1)]
    assert row_chunks(3, 4) == [(0, 1), (1, 1), (2, 1)]
    assert row_chunks(8, 2) == [(0, 4), (4, 4)]
    assert row_chunks(5, 4) == [(0, 2), (2, 2), (4, 1)]
    for r in (1, 3, 5, 8):
        for depth in (1, 2, 3, 4, 7, 16):
            chunks = row_chunks(r, depth)
            assert 1 <= len(chunks) <= min(depth, r)
            assert chunks[0][0] == 0
            assert sum(c for _, c in chunks) == r
            for (o1, c1), (o2, _) in zip(chunks, chunks[1:]):
                assert o1 + c1 == o2


def _wild_table(r=5, c=64, seed=2):
    rng = np.random.RandomState(seed)
    t = rng.randn(r, c).astype(np.float32)
    t *= np.power(10.0, rng.randint(-3, 4, (r, 1))).astype(np.float32)
    t[1] = 0.0  # all-zero row: the 0/0 scale guard, per chunk
    return t


class TestChunkAlgebra:
    """The linearity argument, stated on tables: a chunk's wire
    crossing IS the row slice of the whole table's (scales are
    per-row), in the mirror and in the jax ops, bit for bit."""

    @pytest.mark.parametrize("wire", SCALED)
    def test_mirror_chunk_qdq_is_row_slice_of_whole(self, wire):
        t = _wild_table()
        whole = np_qdq_table(t, wire)
        for depth in (2, 3, 5):
            folded = np.concatenate(
                [np_qdq_table(t[off:off + cnt], wire)
                 for off, cnt in row_chunks(t.shape[0], depth)])
            np.testing.assert_array_equal(folded, whole)

    @pytest.mark.parametrize("wire", SCALED)
    def test_jax_chunk_quantize_matches_mirror_bitwise(self, wire):
        """Per-chunk scales: quantize_table over a row chunk must
        equal np_quantize_table over the same slice bit for bit —
        wire payload AND the per-row scale side-channel."""
        t = _wild_table(seed=9)
        for off, cnt in row_chunks(t.shape[0], 3):
            qj, sj = quant.quantize_table(
                jnp.asarray(t[off:off + cnt]), wire)
            qn, sn = np_quantize_table(t[off:off + cnt], wire)
            assert np.asarray(qj).tobytes() == qn.tobytes()
            if wire == "bf16":
                assert sj is None and sn is None
            else:
                assert np.asarray(sj).tobytes() == sn.tobytes()

    def test_fold_row_chunks_restores_row_order(self):
        t = _wild_table()
        chunks = [jnp.asarray(t[off:off + cnt])
                  for off, cnt in row_chunks(t.shape[0], 3)]
        np.testing.assert_array_equal(
            np.asarray(fold_row_chunks(iter(chunks))), t)


def _aggregated(cfg, mesh=None, shard=False, batch_seed=0,
                mutate=None):
    """One client round's aggregated table for ``cfg`` (optionally on
    a mesh, optionally with the batch mutated first)."""
    batch, ids = _batch(seed=batch_seed)
    if mutate is not None:
        batch = mutate(batch)
    fn = jax.jit(build_client_round(cfg, linear_loss,
                                    batch["x"].shape[1], mesh=mesh))
    ps = jnp.zeros(cfg.grad_size, jnp.float32).at[0].set(0.5)
    cs = ClientStates.init(cfg, 16, ps)
    if shard and mesh is not None:
        sh = client_sharding(mesh)
        batch = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), batch)
        ids = jax.device_put(ids, sh)
    res = fn(ps, cs, batch, ids, jax.random.PRNGKey(0),
             jnp.float32(1.0))
    return np.asarray(res.aggregated)


class TestDepthParity:
    """The acceptance bit: the aggregated table at --overlap_depth
    2/4 must equal the serial depth-1 table BYTE for byte — per wire
    dtype, per mesh topology. A failure here means chunking changed
    the numbers, which the whole design forbids."""

    @pytest.mark.parametrize("wire", WIRES)
    def test_single_device_bitwise(self, wire):
        outs = [_aggregated(_setup(sketch_dtype=wire,
                                   overlap_depth=depth))
                for depth in (1, 2, 4)]
        assert outs[0].tobytes() == outs[1].tobytes(), wire
        assert outs[0].tobytes() == outs[2].tobytes(), wire

    @pytest.mark.parametrize("wire", WIRES)
    def test_mesh1d_bitwise(self, devices, wire):
        outs = [_aggregated(_setup(sketch_dtype=wire,
                                   overlap_depth=depth),
                            mesh=make_mesh(devices), shard=True)
                for depth in (1, 2, 4)]
        assert outs[0].tobytes() == outs[1].tobytes(), wire
        assert outs[0].tobytes() == outs[2].tobytes(), wire

    @pytest.mark.parametrize("wire", ["f32", "int8"])
    def test_mesh2d_bitwise(self, devices, wire):
        outs = [_aggregated(_setup(sketch_dtype=wire,
                                   overlap_depth=depth),
                            mesh=make_mesh2d(4, 2))
                for depth in (1, 2, 4)]
        assert outs[0].tobytes() == outs[1].tobytes(), wire
        assert outs[0].tobytes() == outs[2].tobytes(), wire

    def test_dead_slot_neutral_per_chunk(self, devices):
        """A dead dropout/padding slot (all-zero mask) must stay
        neutral in EVERY chunk: its garbage data cannot perturb any
        chunk's quantize scale or collective payload. Pinned by
        swapping the dead slot's features for different garbage and
        requiring a byte-identical aggregate, at depth 1 and 2."""

        def kill(slot, poison):
            def mutate(batch):
                mask = np.asarray(batch["mask"]).copy()
                mask[slot] = 0.0
                x = np.asarray(batch["x"]).copy()
                x[slot] = poison
                return {"x": jnp.asarray(x), "y": batch["y"],
                        "mask": jnp.asarray(mask)}
            return mutate

        mesh = make_mesh(devices)
        for depth in (1, 2):
            cfg = _setup(sketch_dtype="int8", overlap_depth=depth)
            a = _aggregated(cfg, mesh=mesh, shard=True,
                            mutate=kill(3, 7.5))
            b = _aggregated(cfg, mesh=mesh, shard=True,
                            mutate=kill(3, -123.0))
            assert a.tobytes() == b.tobytes(), depth
            if depth == 1:
                serial = a
        assert serial.tobytes() == a.tobytes()


class TestOverlapEndToEnd:
    """Multi-round state evolution under overlap: the 2D sharded
    round keeps its 1-D oracle parity, and the quantized 2D round is
    byte-identical to its own serial program over full rounds
    (client state, server momentum/error and params included)."""

    def test_2d_overlap_matches_1d_oracle_f32(self, devices):
        cfg = _setup("sketch", weight_decay=5e-4)
        ref = _run_rounds(cfg, None)
        got = _run_rounds(dataclasses.replace(cfg, overlap_depth=2),
                          make_mesh2d(4, 2))
        _assert_state_close(ref, got)

    def test_2d_overlap_int8_bitwise_vs_serial(self, devices):
        cfg = _setup("sketch", sketch_dtype="int8")
        ref = _run_rounds(cfg, make_mesh2d(4, 2))
        got = _run_rounds(dataclasses.replace(cfg, overlap_depth=4),
                          make_mesh2d(4, 2))
        for x, y in zip(ref[:4], got[:4]):
            assert np.asarray(x).tobytes() == np.asarray(y).tobytes()


def test_overlap_depth_validation():
    """depth >= 1 always; depth > 1 is sketch-mode only (the other
    modes have no table to chunk) — enforced at config validation so
    a bad flag dies before tracing."""
    with pytest.raises(Exception):
        Config(mode="sketch", overlap_depth=0).validate()
    cfg = _setup("uncompressed", error_type="none",
                 virtual_momentum=0.9, overlap_depth=2)
    with pytest.raises(Exception):
        cfg.validate_runtime()
    _setup(overlap_depth=2).validate_runtime()  # sketch: fine
