"""flowlint (analysis/flow.py + analysis/checkers/): the call-graph
engine's root finding and resolution (aliased imports, method
dispatch, the builder idiom, thread targets), one positive and one
negative fixture per flow checker, waiver handling, the committed
fixture-tree pin (legacy findings byte-identical to the pre-migration
engine — the migration moved ``analysis/lint.py``'s rules verbatim
into ``checkers/legacy.py`` and this pin keeps them that way), the
lint-report/baseline round trip, and the <10 s engine wall-time
budget on the real package.
"""

import json
import pathlib
import textwrap

import pytest

from commefficient_tpu.analysis import baseline as base_mod
from commefficient_tpu.analysis.flow import build_program, run_flow
from commefficient_tpu.analysis.lint import (FLOW_CHECKERS_BY_NAME,
                                             LEGACY_RULES,
                                             RULES_BY_NAME, lint_report,
                                             run_all, run_lint,
                                             stale_waivers, unwaived)

REPO_ROOT = pathlib.Path(__file__).resolve().parents[1]
FLOWTREE = REPO_ROOT / "tests" / "fixtures" / "flowtree"


def _write_tree(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return tmp_path


def _flow(root, rule):
    return run_flow(root=root,
                    checkers=[FLOW_CHECKERS_BY_NAME[rule]])


# --- the committed fixture tree: one pin for the whole engine ----------


@pytest.fixture(scope="module")
def flowtree_program():
    return build_program(FLOWTREE)


def test_flowtree_findings_match_committed_pin(flowtree_program):
    """Both tiers on the committed fixture tree must reproduce the
    pinned findings exactly — rule, path, line, message, and waived
    bit. This is the migration-identity gate: the legacy rules moved
    verbatim out of lint.py, and any drift in either tier's findings
    shows up here as a diff against the committed JSON."""
    got = [{"rule": v.rule, "path": v.path, "line": v.line,
            "message": v.message, "waived": v.waived}
           for v in run_all(root=FLOWTREE, program=flowtree_program)]
    expected = json.loads(
        (REPO_ROOT / "tests" / "fixtures"
         / "flowtree_expected.json").read_text())
    assert got == expected


def test_legacy_tier_alone_matches_pin_subset(flowtree_program):
    """``run_lint`` (the historical entry point) must produce exactly
    the legacy-rule subset of the pin — same findings the
    pre-migration per-file linter produced."""
    legacy_names = {r.name for r in LEGACY_RULES}
    got = [str(v) for v in run_lint(root=FLOWTREE)]
    expected = [str_of(e) for e in json.loads(
        (REPO_ROOT / "tests" / "fixtures"
         / "flowtree_expected.json").read_text())
        if e["rule"] in legacy_names]
    assert got == expected


def str_of(e):
    w = " [waived]" if e["waived"] else ""
    return f"{e['path']}:{e['line']}: {e['rule']}: {e['message']}{w}"


# --- call-graph resolution ---------------------------------------------


def test_builder_idiom_roots_sibling_closures(flowtree_program):
    """``jax.jit(build_outer(cfg))`` where build_outer returns
    ``build_round(cfg)`` roots the sibling builder's closure too."""
    assert "core/rounds.py::build_round.<locals>.traced" \
        in flowtree_program.jit_roots


def test_aliased_imports_reach_two_hops(flowtree_program):
    """The traced closure calls ``cu.tick()`` (module alias) and
    ``aliased_helper`` (from-import asname) — both helpers must be in
    the traced set; the unrooted host loop must not be."""
    traced = flowtree_program.traced
    assert "core/util.py::tick" in traced
    assert "core/util.py::helper" in traced
    assert "core/rounds.py::host_loop" not in traced


def test_method_dispatch_through_ctor_and_bases(flowtree_program):
    """``eng = Engine()`` in the builder scope, ``eng.run(x)`` in the
    closure, ``self.now()`` found on the base class: three dispatch
    mechanisms chained."""
    traced = flowtree_program.traced
    assert "core/engine.py::Engine.run" in traced
    assert "core/engine.py::Base.now" in traced


def test_thread_target_is_a_root(flowtree_program):
    assert "telemetry/worker.py::Pump._run" \
        in flowtree_program.thread_roots
    assert "telemetry/worker.py::drain" in flowtree_program.threaded


def test_external_module_attrs_never_dispatch(tmp_path):
    """``jnp.take(...)`` must NOT resolve to some in-package class's
    ``take`` method — an alias of an external module contributes no
    edges (the false-positive class that motivated local ctor-type
    inference)."""
    root = _write_tree(tmp_path, {
        "ops/a.py": """
            import jax
            import jax.numpy as jnp
            import time

            class Store:
                def take(self, i):
                    return time.time()

            def build(cfg):
                def traced(x):
                    return jnp.take(x, 0)
                return traced

            step = jax.jit(build(None))
            """,
    })
    p = build_program(root)
    assert "ops/a.py::Store.take" not in p.traced
    assert unwaived(_flow(root, "trace-purity")) == []


# --- per-checker positive/negative fixtures ----------------------------


def test_trace_purity_positive_and_negative(flowtree_program):
    vs = run_flow(root=FLOWTREE, program=flowtree_program,
                  checkers=[FLOW_CHECKERS_BY_NAME["trace-purity"]])
    hit_paths = {(v.path, v.line) for v in vs}
    # positive: the clock two hops from the root
    assert ("core/util.py", 8) in hit_paths
    # negative: the same impurity in the unreachable host loop
    assert not any(v.path == "core/rounds.py" for v in vs)


def test_prng_positive_and_negative(flowtree_program):
    vs = run_flow(root=FLOWTREE, program=flowtree_program,
                  checkers=[FLOW_CHECKERS_BY_NAME["prng-keys"]])
    msgs = [v.message for v in vs]
    assert any("used after split" in m for m in msgs)
    assert any("never consumed" in m for m in msgs)
    # negative: good() and good_fold() produce nothing past line 20
    assert all(v.line < 18 for v in vs), vs


def test_wire_positive_negative_and_waiver(flowtree_program):
    vs = run_flow(root=FLOWTREE, program=flowtree_program,
                  checkers=[
                      FLOW_CHECKERS_BY_NAME["wire-dtype-crossing"]])
    by_path = {}
    for v in vs:
        by_path.setdefault(v.path, []).append(v)
    # positive: the unowned cast and the private byte table
    assert any(not v.waived for v in by_path["ops/leak.py"])
    assert "runtime/price.py" in by_path
    # negative: the owner module is exempt
    assert "ops/quant.py" not in by_path
    # waiver: the bf16 canary is reported but waived
    waived = [v for v in by_path["ops/leak.py"] if v.waived]
    assert len(waived) == 1 and "bfloat16" in waived[0].message


def test_lock_confinement_positive_and_negative(flowtree_program):
    vs = run_flow(root=FLOWTREE, program=flowtree_program,
                  checkers=[
                      FLOW_CHECKERS_BY_NAME["lock-confinement"]])
    kinds = {(v.line, v.message.split(" of ")[0]) for v in vs}
    assert (15, ".append() mutation") in kinds     # add_unlocked
    assert (26, "comprehension iteration") in kinds  # leak_iter
    # negative: locked append, locked snapshot, __init__ stores
    assert len(vs) == 2, vs


def test_lock_map_undeclared_module_is_silent(tmp_path):
    root = _write_tree(tmp_path, {
        "telemetry/free.py": """
            class S:
                def __init__(self):
                    self._items = []

                def add(self, x):
                    self._items.append(x)
            """,
    })
    assert _flow(root, "lock-confinement") == []


# --- waivers and staleness across tiers --------------------------------


def test_flow_waiver_suppresses_and_wrong_rule_does_not(tmp_path):
    root = _write_tree(tmp_path, {
        "core/x.py": """
            import jax.numpy as jnp

            def f(x):
                # audit: allow(wire-dtype-crossing)
                return x.astype(jnp.int8)
            """,
    })
    vs = _flow(root, "wire-dtype-crossing")
    assert len(vs) == 1 and vs[0].waived and unwaived(vs) == []
    root2 = _write_tree(tmp_path / "b", {
        "core/x.py": """
            import jax.numpy as jnp

            def f(x):
                # audit: allow(trace-purity)
                return x.astype(jnp.int8)
            """,
    })
    assert len(unwaived(_flow(root2, "wire-dtype-crossing"))) == 1


def test_stale_flow_waiver_is_flagged(tmp_path):
    root = _write_tree(tmp_path, {
        "core/x.py": """
            def f(x):
                # audit: allow(lock-confinement)
                return x
            """,
    })
    stale = stale_waivers(root=root, violations=run_all(root=root))
    assert len(stale) == 1 and "lock-confinement" in stale[0]
    # restricting staleness to the legacy tier skips (not flags) it
    assert stale_waivers(
        root=root, violations=run_lint(root=root),
        rule_names=[r.name for r in LEGACY_RULES]) == []


def test_fixture_tree_has_no_stale_waivers(flowtree_program):
    assert stale_waivers(
        root=FLOWTREE,
        violations=run_all(root=FLOWTREE,
                           program=flowtree_program)) == []


# --- report / baseline round trip --------------------------------------


def test_lint_report_spans_both_tiers_and_round_trips(
        flowtree_program):
    vs = run_all(root=FLOWTREE, program=flowtree_program)
    report = lint_report(vs)
    # every flow rule is a legal (baseline-visible) rule name
    for rule in ("trace-purity", "prng-keys", "wire-dtype-crossing",
                 "lock-confinement"):
        assert rule in report["rules"]
    # waived findings from BOTH tiers land in the baseline subset
    full = base_mod.build_report({"programs": {}}, report)
    pinned = json.loads(json.dumps(base_mod.to_baseline(full)))
    waived = pinned["lint"]["waived"]
    assert any("wire-dtype-crossing" in w for w in waived)
    assert any("raw-clock" in w for w in waived)
    # unwaived findings are failures and never enter the baseline
    assert full["failures"]
    assert all("[waived]" in w for w in waived)
    # a NEW waiver against this baseline is a visible diff
    report2 = json.loads(json.dumps(full))
    report2["failures"] = []
    report2["lint"]["waived"].append(
        "x.py:1: lock-confinement: new [waived]")
    problems = base_mod.diff_against_baseline(report2, pinned)
    assert any("new lint waiver" in p for p in problems)


def test_telemetry_report_audit_diff(capsys, tmp_path,
                                     package_parse):
    """``telemetry_report.py --audit``: in sync against the committed
    baseline (exit 0), and a doctored baseline renders the extra
    entry as FIXED with exit 1."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "telemetry_report",
        str(REPO_ROOT / "scripts" / "telemetry_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    committed = str(REPO_ROOT / "audit_baseline.json")
    # audit_report is what ``--audit`` dispatches to; driving it
    # directly lets both checks reuse the suite's one engine run
    # instead of paying two cold ones
    assert mod.audit_report(
        committed, as_json=False,
        violations=package_parse["violations"]) == 0
    out = capsys.readouterr().out
    assert "in sync" in out and "wire-dtype-crossing" in out

    doctored = json.loads(pathlib.Path(committed).read_text())
    doctored["lint"]["waived"].append(
        "ghost.py:1: host-sync: long gone [waived]")
    p = tmp_path / "doctored.json"
    p.write_text(json.dumps(doctored))
    assert mod.audit_report(
        str(p), as_json=False,
        violations=package_parse["violations"]) == 1
    out = capsys.readouterr().out
    assert "FIXED ghost.py:1" in out


# --- the real tree -----------------------------------------------------


# ``package_parse`` — the session-scoped single engine run on the
# real package — lives in conftest.py (test_audit shares it).


@pytest.fixture(scope="module")
def package_program(package_parse):
    return package_parse["program"]


def test_package_flow_tier_is_clean(package_parse):
    flow_rules = {"trace-purity", "prng-keys", "wire-dtype-crossing",
                  "lock-confinement"}
    bad = [v for v in unwaived(package_parse["violations"])
           if v.rule in flow_rules]
    assert bad == [], "unwaived flow-tier violations in the package"


def test_package_roots_look_sane(package_program):
    p = package_program
    assert len(p.jit_roots) >= 10
    assert any(fq.startswith("core/rounds.py::") for fq in p.traced)
    assert any(fq.startswith("core/server.py::") for fq in p.traced)
    assert p.thread_roots, "no thread roots found in the package"


def test_engine_wall_time_budget(package_parse):
    """Full cold parse + both tiers on the whole package in under
    10 s — the audit runs this on every CI pass, so the engine
    staying cheap is part of its contract. (Timed around the shared
    module fixture so tier-1 doesn't pay for a second cold run.)"""
    elapsed = package_parse["elapsed"]
    assert elapsed < 10.0, f"engine took {elapsed:.1f}s (budget 10s)"
