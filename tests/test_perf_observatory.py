"""Performance-observatory tests: device-time trace attribution,
roofline FLOP counting, the noise-aware perf gate, the run registry,
the step-time alarm, stale-waiver detection, and the end-to-end
``--profile`` path on a real CPU mesh.

The golden-trace test runs against ``tests/fixtures/mini.trace.json.gz``
— a hand-authored Chrome trace-event dump with two ``fed_round``
markers, overlapping compute/collective device events, a transfer that
straddles the round boundary, and events that attribution must ignore
(phase annotations, host-lane python frames, out-of-window ops). Its
bucket values are computed by hand and asserted exactly.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from commefficient_tpu.telemetry import gate, registry, trace
from commefficient_tpu.telemetry.alarms import (AlarmEngine,
                                                DivergenceAbort)
from commefficient_tpu.telemetry.core import Telemetry
from commefficient_tpu.telemetry.record import (make_bench_record,
                                                make_round_record,
                                                validate_record)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "mini.trace.json.gz")


# --- golden trace parser ----------------------------------------------


class TestTraceAttribution:
    def test_fixture_golden_buckets(self):
        """Hand-computed buckets for the checked-in mini trace.

        Round 0 window [1000, 2000) us: device busy = fusion.1 union
        all-reduce.2 (1100-1400) + copy.3 clipped (1900-2000) = 400 us;
        collective 150, transfer 100 (copy minus collective overlap:
        none), compute 150, host gap 600. Round 1 window [2000, 3500):
        copy.3 tail (2000-2100) + fusion.4 (2200-2500) = 400 busy,
        no collective, transfer 100, compute 300, gap 1100."""
        events = trace.load_trace_events(FIXTURE)
        buckets = trace.attribute_rounds(events)
        assert sorted(buckets) == [0, 1]
        # the v3 aggregate buckets must stay BIT-FOR-BIT what they
        # were before per-device attribution existed (the cross-device
        # union is the same interval set the old pooled path measured)
        agg = ("window_s", "busy_s", "compute_s", "collective_s",
               "transfer_s", "host_gap_s")
        assert {k: buckets[0][k] for k in agg} == {
            "window_s": 0.001, "busy_s": 0.0004,
            "compute_s": 0.00015, "collective_s": 0.00015,
            "transfer_s": 0.0001, "host_gap_s": 0.0006}
        assert {k: buckets[1][k] for k in agg} == {
            "window_s": 0.0015, "busy_s": 0.0004,
            "compute_s": 0.0003, "collective_s": 0.0,
            "transfer_s": 0.0001, "host_gap_s": 0.0011}
        # v4: the same rounds also carry per-device lanes (TPU:0 from
        # the /device: pid, cpu:30 from the tf_XLA thread) and skew
        # stats — the all-reduce here runs on ONE lane, so there is no
        # cross-device group to align and no skew
        assert sorted(buckets[0]["per_device"]) == ["TPU:0", "cpu:30"]
        assert buckets[0]["per_device"]["TPU:0"] == {
            "busy_s": 0.0004, "compute_s": 0.00015,
            "collective_s": 0.00015, "transfer_s": 0.0001,
            "wait_s": 0.0, "wire_s": 0.00015}
        assert buckets[1]["per_device"]["cpu:30"] == {
            "busy_s": 0.0003, "compute_s": 0.0003,
            "collective_s": 0.0, "transfer_s": 0.0,
            "wait_s": 0.0, "wire_s": 0.0}
        for r in (0, 1):
            assert buckets[r]["skew"]["n_collectives"] == 0
            assert buckets[r]["skew"]["straggler_device"] is None

    def test_buckets_partition_each_window(self):
        buckets = trace.attribute_rounds(
            trace.load_trace_events(FIXTURE))
        for b in buckets.values():
            parts = (b["compute_s"] + b["collective_s"]
                     + b["transfer_s"] + b["host_gap_s"])
            assert abs(parts - b["window_s"]) < 1e-9
            assert abs((b["busy_s"] + b["host_gap_s"])
                       - b["window_s"]) < 1e-9

    def test_device_lanes_exclude_host_python(self):
        events = trace.load_trace_events(FIXTURE)
        lanes = trace.device_lanes(events)
        # pid 2 is a /device: process, pid 3 hosts a tf_XLA* thread;
        # pid 1 (host python, where the round markers live) is not a
        # device lane
        assert (2, 20) in lanes and (3, 30) in lanes
        assert all(pid != 1 for pid, _tid in lanes)

    def test_round_windows_from_markers(self):
        events = trace.load_trace_events(FIXTURE)
        windows = trace.round_windows(events)
        assert windows == [(0, 1000.0, 2000.0),
                           (1, 2000.0, 3500.0)]

    def test_attribute_logdir_finds_gz(self, tmp_path):
        sub = tmp_path / "plugins" / "profile" / "x"
        sub.mkdir(parents=True)
        with open(FIXTURE, "rb") as f:
            (sub / "host.trace.json.gz").write_bytes(f.read())
        buckets = trace.attribute_logdir(str(tmp_path))
        assert sorted(buckets) == [0, 1]

    def test_no_markers_no_rounds(self):
        events = [{"ph": "M", "pid": 2, "name": "process_name",
                   "args": {"name": "/device:TPU:0"}},
                  {"ph": "X", "pid": 2, "tid": 1, "name": "fusion.1",
                   "ts": 10, "dur": 5, "args": {}}]
        assert trace.attribute_rounds(events) == {}


# --- roofline FLOP inventory ------------------------------------------


CANNED_STABLEHLO = """
module @round {
  func.func public @main(%arg0: tensor<8x32xf32>, %arg1: tensor<32x16xf32>) -> tensor<8x16xf32> {
    %0 = stablehlo.dot_general %arg0, %arg1, contracting_dims = [1] x [0] : (tensor<8x32xf32>, tensor<32x16xf32>) -> tensor<8x16xf32>
    %1 = stablehlo.convolution(%arg2, %arg3) dim_numbers = [b, 0, 1, f]x[0, 1, i, o]->[b, 0, 1, f], window = {stride = [1, 1]} : (tensor<1x8x8x3xf32>, tensor<3x3x3x16xf32>) -> tensor<1x8x8x16xf32>
    return %0 : tensor<8x16xf32>
  }
}
"""


class TestFlopInventory:
    def test_dot_and_conv_macs(self):
        from commefficient_tpu.analysis.hlo import flop_inventory
        inv = flop_inventory(CANNED_STABLEHLO)
        # dot: 2 x numel(8x16) x K=32; conv: 2 x numel(1x8x8x16) x
        # (numel(3x3x3x16) / O=16) = 2 x 1024 x 27
        assert inv["dot_flops"] == 2 * 8 * 16 * 32
        assert inv["conv_flops"] == 2 * (8 * 8 * 16) * (3 * 3 * 3)
        assert inv["total_flops"] == inv["dot_flops"] + inv["conv_flops"]
        assert inv["dot_count"] == 1 and inv["conv_count"] == 1
        assert inv["by_dtype"] == {"f32": inv["total_flops"]}

    def test_cost_model_floors(self):
        from commefficient_tpu.analysis.cost import build_cost_model
        cost = build_cost_model(
            CANNED_STABLEHLO, backend="cpu", device_kind="cpu",
            n_devices=8, allreduce_payload_bytes=4.0 * 50_000,
            label="test/8dev")
        assert cost["total_flops"] == 2 * 8 * 16 * 32 + 2 * 1024 * 27
        assert cost["expected_round_s"] > 0
        assert cost["expected_round_s"] >= cost["compute_floor_s"]
        assert cost["expected_round_s"] >= cost["collective_floor_s"]


# --- perf-gate math ---------------------------------------------------


def _metric(median, mad=0.0, better="lower", n=8):
    return {"median": median, "mad": mad, "n": n, "p50": median,
            "p95": median, "better": better}


class TestGateMath:
    def test_noise_within_band_passes(self):
        base = gate.make_baseline(
            {"span:round_dispatch:ms": _metric(10.0, mad=0.5)})
        verdict = gate.compare(
            base, {"span:round_dispatch:ms": _metric(12.0)})
        assert verdict["checked"] == 1
        assert verdict["regressions"] == []

    def test_regression_beyond_band_fails(self):
        base = gate.make_baseline(
            {"span:round_dispatch:ms": _metric(10.0, mad=0.5)})
        verdict = gate.compare(
            base, {"span:round_dispatch:ms": _metric(20.0)})
        assert len(verdict["regressions"]) == 1
        r = verdict["regressions"][0]
        assert r["metric"] == "span:round_dispatch:ms"
        # band = max(0.25 * 10, 5 * 0.5) = 2.5ms; delta = 10ms
        assert r["tolerance"] == pytest.approx(2.5)

    def test_mad_band_dominates_when_noisy(self):
        # mad 2ms -> band 10ms: a 9ms jump is still noise
        base = gate.make_baseline(
            {"span:h2d:ms": _metric(10.0, mad=2.0)})
        verdict = gate.compare(base, {"span:h2d:ms": _metric(19.0)})
        assert verdict["regressions"] == []

    def test_higher_is_better_metrics_gate_downward(self):
        base = gate.make_baseline(
            {"bench:clients_per_s": _metric(100.0, better="higher")})
        bad = gate.compare(
            base, {"bench:clients_per_s": _metric(50.0,
                                                  better="higher")})
        good = gate.compare(
            base, {"bench:clients_per_s": _metric(200.0,
                                                  better="higher")})
        assert len(bad["regressions"]) == 1
        assert bad["improvements"] == []
        assert good["regressions"] == []
        assert len(good["improvements"]) == 1

    def test_one_sided_metrics_skip(self):
        base = gate.make_baseline({"span:a:ms": _metric(1.0)})
        verdict = gate.compare(base, {"span:b:ms": _metric(1.0)})
        assert verdict["checked"] == 0
        reasons = {s["metric"]: s["reason"]
                   for s in verdict["skipped"]}
        assert reasons == {"span:a:ms": "not in current run",
                           "span:b:ms": "not in baseline"}

    def test_sub_resolution_baseline_skipped(self):
        # 0.01 ms median is below scheduler resolution: a 100x blowup
        # is not gateable signal
        base = gate.make_baseline({"span:tiny:ms": _metric(0.01)})
        verdict = gate.compare(base, {"span:tiny:ms": _metric(1.0)})
        assert verdict["checked"] == 0
        assert verdict["skipped"][0]["reason"] == \
            "below timing resolution"

    def test_roofline_utilization_never_floored(self):
        base = gate.make_baseline(
            {"device:roofline_utilization": _metric(0.0005,
                                                    better="higher")})
        verdict = gate.compare(
            base, {"device:roofline_utilization": _metric(
                0.0001, better="higher")})
        assert verdict["checked"] == 1
        assert len(verdict["regressions"]) == 1

    def test_schema_mismatch_raises(self):
        with pytest.raises(ValueError, match="schema"):
            gate.compare({"schema": 99, "metrics": {}}, {})

    def test_metrics_from_records_shapes(self):
        rec = make_round_record(0)
        rec["spans"] = {"h2d": 0.002, "server": 0.001}
        rec["device_time"] = {"busy_s": 0.5, "compute_s": 0.4,
                              "roofline_utilization": 0.31}
        bench = make_bench_record("clients_per_s", 120.0, "1/s",
                                  round_times_s=[0.1, 0.11, 0.09])
        metrics = gate.metrics_from_records([rec, bench])
        assert metrics["span:h2d:ms"]["median"] == \
            pytest.approx(2.0)
        assert metrics["span:h2d:ms"]["better"] == "lower"
        assert metrics["device:busy_s"]["better"] == "lower"
        assert metrics["device:roofline_utilization"]["better"] == \
            "higher"
        assert metrics["bench:clients_per_s"]["median"] == 120.0
        assert metrics["bench:clients_per_s"]["better"] == "higher"
        assert metrics["bench:clients_per_s:round_s"]["n"] == 3
        assert metrics["bench:clients_per_s:round_s"]["better"] == \
            "lower"


# --- perf_gate CLI ----------------------------------------------------


def _load_perf_gate():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "perf_gate.py")
    spec = importlib.util.spec_from_file_location("_perf_gate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_ledger(path, round_s):
    """A synthetic ledger whose round_dispatch span is ``round_s``."""
    with open(path, "w") as f:
        for r in range(8):
            rec = make_round_record(r)
            rec["spans"] = {"round_dispatch": round_s}
            rec["uplink_bytes"] = rec["downlink_bytes"] = 1024.0
            rec["device_time"] = {"window_s": round_s,
                                  "busy_s": 0.8 * round_s,
                                  "compute_s": 0.7 * round_s,
                                  "collective_s": 0.1 * round_s,
                                  "transfer_s": 0.0,
                                  "host_gap_s": 0.2 * round_s}
            f.write(json.dumps(rec) + "\n")


class TestPerfGateCLI:
    def test_baseline_check_regress_refuse_cycle(self, tmp_path):
        pg = _load_perf_gate()
        good = str(tmp_path / "good.jsonl")
        slow = str(tmp_path / "slow.jsonl")
        baseline = str(tmp_path / "perf_baseline.json")
        _write_ledger(good, 0.050)
        _write_ledger(slow, 0.200)  # 4x: far outside any noise band

        assert pg.main(["--ledger", good,
                        "--write-baseline", baseline]) == 0
        assert os.path.exists(baseline)
        base = gate.load_baseline(baseline)
        assert base["schema"] == gate.BASELINE_SCHEMA
        # the synthetic ledger carries no topology info, so it lands
        # under the "any" bucket of the schema-2 topology map
        entry = gate.baseline_entry(base, None, None)
        assert "span:round_dispatch:ms" in entry["metrics"]

        # same run gates green against its own baseline
        assert pg.main(["--ledger", good, "--baseline", baseline,
                        "--check"]) == 0
        # the synthetically slowed ledger fails
        assert pg.main(["--ledger", slow, "--baseline", baseline,
                        "--check"]) == 1
        # re-baselining over a regression is refused without --force
        assert pg.main(["--ledger", slow, "--baseline", baseline,
                        "--write-baseline", baseline]) == 1
        assert gate.baseline_entry(
            gate.load_baseline(baseline), None, None)["metrics"][
            "span:round_dispatch:ms"]["median"] == pytest.approx(50.0)
        # --force is the explicit trade-off escape hatch
        assert pg.main(["--ledger", slow, "--baseline", baseline,
                        "--write-baseline", baseline,
                        "--force"]) == 0
        assert gate.baseline_entry(
            gate.load_baseline(baseline), None, None)["metrics"][
            "span:round_dispatch:ms"]["median"] == pytest.approx(200.0)

    def test_empty_ledger_is_an_error(self, tmp_path):
        pg = _load_perf_gate()
        empty = str(tmp_path / "empty.jsonl")
        open(empty, "w").close()
        assert pg.main(["--ledger", empty, "--check"]) == 1

    def test_runs_dir_discovery(self, tmp_path):
        pg = _load_perf_gate()
        ledger = str(tmp_path / "run.jsonl")
        _write_ledger(ledger, 0.050)
        registry.write_manifest(str(tmp_path / "runs"), args=None,
                                ledger=ledger)
        baseline = str(tmp_path / "perf_baseline.json")
        assert pg.main(["--runs_dir", str(tmp_path / "runs"),
                        "--write-baseline", baseline]) == 0
        assert pg.main(["--runs_dir", str(tmp_path / "runs"),
                        "--baseline", baseline, "--check"]) == 0

    def test_runs_dir_without_manifests_errors(self, tmp_path):
        pg = _load_perf_gate()
        assert pg.main(["--runs_dir", str(tmp_path),
                        "--check"]) == 1


# --- run registry -----------------------------------------------------


class _Cfg:
    def __init__(self, **kw):
        self.__dict__.update(kw)


class TestRunRegistry:
    def test_manifest_round_trip(self, tmp_path):
        ledger = str(tmp_path / "a.jsonl")
        open(ledger, "w").close()
        args = _Cfg(mode="sketch", k=16, ledger=ledger,
                    do_profile=True)
        path = registry.write_manifest(
            str(tmp_path / "runs"), args=args, ledger=ledger,
            bench={"clients_per_s": {"value": 10.0}},
            mesh_shape={"data": 8}, extra={"trainer": "test"})
        manifests = registry.list_manifests(str(tmp_path / "runs"))
        assert [p for p, _ in manifests] == [path]
        rec = manifests[0][1]
        assert rec["kind"] == "run_manifest"
        assert rec["schema"] == registry.MANIFEST_SCHEMA
        assert rec["config_hash"] == registry.config_hash(args)
        assert rec["ledger"] == os.path.abspath(ledger)
        assert rec["trainer"] == "test"
        assert rec["mesh_shape"] == {"data": 8}
        hits = registry.latest_ledgers(str(tmp_path / "runs"))
        assert hits == [(path, rec, os.path.abspath(ledger))]

    def test_config_hash_ignores_observability_knobs(self):
        a = _Cfg(mode="sketch", k=16, ledger="x.jsonl",
                 do_profile=True, telemetry_console=True)
        b = _Cfg(mode="sketch", k=16, ledger="y.jsonl",
                 do_profile=False, telemetry_console=False)
        c = _Cfg(mode="sketch", k=32, ledger="x.jsonl",
                 do_profile=True, telemetry_console=True)
        assert registry.config_hash(a) == registry.config_hash(b)
        assert registry.config_hash(a) != registry.config_hash(c)

    def test_latest_ledgers_skips_deleted(self, tmp_path):
        runs = str(tmp_path / "runs")
        led1 = str(tmp_path / "old.jsonl")
        led2 = str(tmp_path / "gone.jsonl")
        open(led1, "w").close()
        open(led2, "w").close()
        registry.write_manifest(runs, args=_Cfg(x=1), ledger=led1)
        registry.write_manifest(runs, args=_Cfg(x=2), ledger=led2)
        os.remove(led2)
        hits = registry.latest_ledgers(runs, n=2)
        assert [h[2] for h in hits] == [os.path.abspath(led1)]

    def test_maybe_write_manifest_gates(self, tmp_path):
        # no ledger -> no manifest; --test smoke -> no manifest
        assert registry.maybe_write_manifest(
            _Cfg(ledger=""), runs_dir=str(tmp_path)) is None
        assert registry.maybe_write_manifest(
            _Cfg(ledger="x.jsonl", do_test=True),
            runs_dir=str(tmp_path)) is None
        assert registry.list_manifests(str(tmp_path)) == []


# --- step-time alarm --------------------------------------------------


class _AlarmCfg:
    on_divergence = "ledger-flag"
    alarm_residual_ratio = 10.0
    alarm_residual_rounds = 3
    alarm_recovery_error = 1.0
    alarm_step_time_ratio = 2.0
    alarm_step_time_window = 8


class TestStepTimeAlarm:
    def test_warmup_then_fire_then_keep_firing(self):
        eng = AlarmEngine(_AlarmCfg())
        for r in range(AlarmEngine.STEP_TIME_WARMUP):
            assert eng.check_step_time(r, 0.1) == []
        # healthy round within ratio x median: no alarm
        assert eng.check_step_time(5, 0.15) == []
        fired = eng.check_step_time(6, 0.5)
        assert fired and fired[0]["rule"] == "step_time_regression"
        assert fired[0]["threshold"] == pytest.approx(0.2)
        assert fired[0]["rolling_median"] == pytest.approx(0.1)
        # firing samples are NOT folded into the window, so a
        # sustained regression keeps firing instead of becoming the
        # new normal
        assert eng.check_step_time(7, 0.5)
        assert eng.check_step_time(8, 0.5)

    def test_flags_ledger_record(self):
        tel = Telemetry(sinks=[_ListSink()])
        tel.begin_round(0)
        eng = AlarmEngine(_AlarmCfg(), telemetry=tel)
        for r in range(AlarmEngine.STEP_TIME_WARMUP):
            eng.check_step_time(r, 0.1)
        eng.check_step_time(0, 0.9)
        rec = tel._records[0]
        assert rec["alarms"] and \
            rec["alarms"][0]["rule"] == "step_time_regression"

    def test_abort_action_raises(self):
        class Abort(_AlarmCfg):
            on_divergence = "abort"
        eng = AlarmEngine(Abort())
        for r in range(AlarmEngine.STEP_TIME_WARMUP):
            eng.check_step_time(r, 0.1)
        with pytest.raises(DivergenceAbort):
            eng.check_step_time(5, 0.9)

    def test_disarmed_when_ratio_zero(self):
        class Off(_AlarmCfg):
            alarm_step_time_ratio = 0.0
        eng = AlarmEngine(Off())
        for r in range(20):
            assert eng.check_step_time(r, 100.0) == []

    def test_build_alarm_engine_arms_on_step_time_alone(self):
        from commefficient_tpu.telemetry.alarms import \
            build_alarm_engine

        class NoProbes(_AlarmCfg):
            probe_period = 0
        assert build_alarm_engine(NoProbes()) is not None

        class Nothing(_AlarmCfg):
            probe_period = 0
            alarm_step_time_ratio = 0.0
        assert build_alarm_engine(Nothing()) is None


# --- stale waivers ----------------------------------------------------


class TestStaleWaivers:
    def test_live_orphan_and_unknown(self, tmp_path):
        from commefficient_tpu.analysis import lint
        (tmp_path / "a.py").write_text(
            "# audit: allow(mutable-default-arg)\n"   # live: covers L2
            "def f(a=[]):\n"
            "    return a\n"
            "\n"
            "# audit: allow(mutable-default-arg)\n"   # orphan
            "x = 1\n"
            "\n"
            "# audit: allow(no-such-rule)\n"          # typo'd rule
            "y = 2\n")
        violations = lint.run_lint(root=tmp_path)
        assert [v.waived for v in violations] == [True]
        stale = lint.stale_waivers(root=tmp_path,
                                   violations=violations)
        assert len(stale) == 2
        assert any("a.py:5" in s and "stale waiver" in s
                   for s in stale)
        assert any("a.py:8" in s and "unknown rule" in s
                   for s in stale)

    def test_repo_has_no_stale_waivers(self):
        from commefficient_tpu.analysis import lint
        assert lint.stale_waivers() == []

    def test_stale_waivers_are_hard_failures(self):
        from commefficient_tpu.analysis import baseline as base_mod
        from commefficient_tpu.analysis import lint
        summary = lint.lint_report(
            [], stale=["a.py:5: stale waiver allow(host-sync) — ..."])
        report = base_mod.build_report(
            {"programs": {}, "failures": []}, summary)
        assert any("stale waiver" in f for f in report["failures"])
        # ...and can never be baselined in: the pinned subset keeps
        # only the waived list
        base = base_mod.to_baseline(
            {"programs": {}, "jax_version": "x", "device_count": 8,
             "lint": summary, "failures": []})
        assert "stale_waivers" not in base["lint"]


# --- telemetry emission hold ------------------------------------------


class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)

    def close(self):
        pass


class TestEmissionHold:
    def test_hold_buffers_then_merges_device_time(self):
        sink = _ListSink()
        tel = Telemetry(sinks=[sink])
        tel.hold_emission(True)
        for r in range(2):
            tel.begin_round(r)
            tel.set_round_bytes(r, 10.0, 20.0)
        tel.begin_round(2)        # closes round 1
        tel.set_round_bytes(2, 10.0, 20.0)
        assert sink.records == []  # everything buffered by the hold
        buckets = {"window_s": 1.0, "busy_s": 0.5, "compute_s": 0.4,
                   "collective_s": 0.1, "transfer_s": 0.0,
                   "host_gap_s": 0.5}
        tel.merge_round_device_time(0, buckets)
        tel.merge_round_device_time(1, buckets)
        tel.hold_emission(False)
        emitted = [r["round"] for r in sink.records
                   if r["kind"] == "round"]
        assert emitted == [0, 1]   # round order preserved
        assert all(r["device_time"] == buckets for r in sink.records
                   if r["kind"] == "round")
        tel.close()
        assert [r["round"] for r in sink.records
                if r["kind"] == "round"] == [0, 1, 2]

    def test_roofline_utilization_derived_from_cost_model(self):
        sink = _ListSink()
        tel = Telemetry(sinks=[sink])
        tel.expected_round_s = 0.25
        tel.begin_round(0)
        tel.merge_round_device_time(0, {"window_s": 1.0,
                                        "busy_s": 0.5})
        rec = tel._records[0]
        assert rec["device_time"]["roofline_utilization"] == \
            pytest.approx(0.5)

    def test_close_overrides_hold(self):
        sink = _ListSink()
        tel = Telemetry(sinks=[sink])
        tel.hold_emission(True)
        tel.begin_round(0)
        tel.close()
        assert [r["round"] for r in sink.records
                if r["kind"] == "round"] == [0]


# --- end-to-end: --profile on the CPU mesh ----------------------------


class TestProfileIntegration:
    def test_profiled_run_attributes_device_time(self, tmp_path):
        """The acceptance criterion: a ``--profile``'d CPU run
        produces a schema-v3 ledger whose per-round device-time
        buckets sum to the round window exactly, and whose windows
        together cover the in-trace wall time to within 10% (+ a
        small absolute epsilon for trace start/stop edges)."""
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        from commefficient_tpu.config import Config
        from commefficient_tpu.runtime import FedModel, FedOptimizer
        from commefficient_tpu.telemetry import clock
        from commefficient_tpu.telemetry.profiler import trace_window

        class Lin(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(64, use_bias=False)(x)

        module = Lin()
        params = module.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 32)))["params"]
        ledger = str(tmp_path / "ledger.jsonl")
        args = Config(mode="sketch", error_type="virtual",
                      local_momentum=0.0, virtual_momentum=0.9,
                      num_workers=2, local_batch_size=4,
                      num_clients=4, dataset_name="CIFAR10", seed=0,
                      k=16, num_rows=3, num_cols=256)
        args.ledger = ledger
        args.do_profile = True

        def loss(p, batch, cfg):
            pred = module.apply({"params": p}, batch["x"])
            n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
            return (jnp.sum(pred ** 2 * batch["mask"][..., None])
                    / n, ())

        model = FedModel(module, params, loss, args,
                         padded_batch_size=4)
        opt = FedOptimizer([{"lr": 0.1}], args)
        rng = np.random.RandomState(0)

        def mk(r):
            return {"x": rng.randn(2, 4, 32).astype(np.float32),
                    "y": rng.randn(2, 4).astype(np.float32),
                    "mask": np.ones((2, 4), np.float32),
                    "client_ids": np.array([r % 4, (r + 1) % 4],
                                           np.int32)}

        # round 0 outside the window carries compile/warmup
        model(mk(0))
        opt.step()
        logdir = str(tmp_path / "trace")
        with trace_window(logdir, telemetry=model.telemetry):
            t0 = clock.tick()
            for r in range(1, 5):
                model(mk(r))
                opt.step()
            jax.block_until_ready(model.ps_weights)
            loop_wall = clock.tick() - t0
        model.finalize()

        recs = [json.loads(line) for line in open(ledger)]
        assert all(not validate_record(r) for r in recs)
        rounds = [r for r in recs if r["kind"] == "round"]
        assert len(rounds) == 5
        assert all(r["schema"] == 7 for r in rounds)

        traced = [r for r in rounds if r.get("device_time")]
        assert [r["round"] for r in traced] == [1, 2, 3, 4]
        total_window = 0.0
        for r in traced:
            dt = r["device_time"]
            parts = (dt["compute_s"] + dt["collective_s"]
                     + dt["transfer_s"] + dt["host_gap_s"])
            assert abs(parts - dt["window_s"]) < 1e-5
            assert dt["busy_s"] > 0
            # v4: real traces carry per-device lanes whose wait+wire
            # split partitions each device's collective bucket exactly
            assert dt["per_device"]
            for lane in dt["per_device"].values():
                assert lane["wait_s"] + lane["wire_s"] == \
                    pytest.approx(lane["collective_s"], abs=1e-9)
            assert dt["skew"]["n_collectives"] >= 0
            # the --profile cost model registered expected_round_s,
            # so every traced round carries a utilization
            assert 0 < dt["roofline_utilization"] <= 1.0
            total_window += dt["window_s"]
        # windows tile the in-trace loop: round 1's window absorbs
        # the one-off cost-model lowering, the last window extends to
        # the trace stop — 10% relative + 50ms absolute covers both
        assert abs(total_window - loop_wall) <= \
            0.1 * loop_wall + 0.05

        cost_meta = [r for r in recs if r["kind"] == "meta"
                     and r.get("cost_model")]
        assert len(cost_meta) == 1
        cm = cost_meta[0]["cost_model"]
        assert cm["expected_round_s"] > 0
        assert cm["total_flops"] > 0

        trace_meta = [r for r in recs if r["kind"] == "meta"
                      and r.get("trace_rounds")]
        assert len(trace_meta) == 1
        assert trace_meta[0]["trace_rounds"] == 4
        assert trace_meta[0]["trace_busy_s"] > 0

        # the ledger gates end-to-end through the perf-gate CLI
        pg = _load_perf_gate()
        baseline = str(tmp_path / "perf_baseline.json")
        assert pg.main(["--ledger", ledger,
                        "--write-baseline", baseline]) == 0
        assert pg.main(["--ledger", ledger, "--baseline", baseline,
                        "--check"]) == 0


# --- v4: per-device attribution + collective skew ---------------------


SKEW_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                            "skew.trace.json.gz")
OVERLAP_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                               "overlap.trace.json.gz")

AGG_KEYS = ("window_s", "busy_s", "compute_s", "collective_s",
            "transfer_s", "host_gap_s")


class TestSkewAttribution:
    """``skew.trace.json.gz``: two TPU device lanes whose all-reduces
    enter at different times. Round 0: TPU:0 enters all-reduce.7 at
    1300, TPU:1 (the straggler — still computing) at 1450, both exit
    1600 — so TPU:0's collective 300 us splits into 150 us *wait* and
    150 us *wire*, TPU:1's 150 us is all wire. Round 1: a 20 us enter
    delta on all-reduce.8 plus a single-participant reduce-scatter on
    TPU:0 (no peer group: all wire, excluded from skew stats)."""

    def test_fixture_golden_per_device_buckets(self):
        buckets = trace.attribute_rounds(
            trace.load_trace_events(SKEW_FIXTURE))
        assert sorted(buckets) == [0, 1]
        b0 = buckets[0]
        assert {k: b0[k] for k in AGG_KEYS} == {
            "window_s": 0.002, "busy_s": 0.0006,
            "compute_s": 0.0002, "collective_s": 0.0003,
            "transfer_s": 0.0001, "host_gap_s": 0.0014}
        assert b0["per_device"] == {
            "TPU:0": {"busy_s": 0.0006, "compute_s": 0.0002,
                      "collective_s": 0.0003, "transfer_s": 0.0001,
                      "wait_s": 0.00015, "wire_s": 0.00015},
            "TPU:1": {"busy_s": 0.0005, "compute_s": 0.00035,
                      "collective_s": 0.00015, "transfer_s": 0.0,
                      "wait_s": 0.0, "wire_s": 0.00015}}
        assert b0["skew"] == {
            "n_collectives": 1, "max_enter_delta_s": 0.00015,
            "p95_enter_delta_s": 0.00015, "straggler_device": "TPU:1"}
        b1 = buckets[1]
        assert {k: b1[k] for k in AGG_KEYS} == {
            "window_s": 0.001, "busy_s": 0.0003,
            "compute_s": 0.0, "collective_s": 0.0003,
            "transfer_s": 0.0, "host_gap_s": 0.0007}
        assert b1["per_device"] == {
            "TPU:0": {"busy_s": 0.0003, "compute_s": 0.0,
                      "collective_s": 0.0003, "transfer_s": 0.0,
                      "wait_s": 2e-05, "wire_s": 0.00028},
            "TPU:1": {"busy_s": 0.00018, "compute_s": 0.0,
                      "collective_s": 0.00018, "transfer_s": 0.0,
                      "wait_s": 0.0, "wire_s": 0.00018}}
        assert b1["skew"] == {
            "n_collectives": 1, "max_enter_delta_s": 2e-05,
            "p95_enter_delta_s": 2e-05, "straggler_device": "TPU:1"}

    def test_wait_plus_wire_partitions_collective_exactly(self):
        """Per device, wait_s + wire_s must reproduce collective_s
        EXACTLY (wire is computed as the rounded difference, so the
        identity survives 6-dp rounding), and each lane's busy time
        must partition into compute + collective + transfer."""
        for fixture in (FIXTURE, SKEW_FIXTURE, OVERLAP_FIXTURE):
            buckets = trace.attribute_rounds(
                trace.load_trace_events(fixture))
            for b in buckets.values():
                for dev, lane in b["per_device"].items():
                    assert lane["wait_s"] + lane["wire_s"] == \
                        pytest.approx(lane["collective_s"],
                                      abs=1e-12), (fixture, dev)
                    assert lane["compute_s"] + lane["collective_s"] \
                        + lane["transfer_s"] == \
                        pytest.approx(lane["busy_s"], abs=1e-12)

    def test_aggregate_never_exceeds_lane_sums(self):
        """The aggregate buckets are the cross-device interval UNION:
        concurrent work on two lanes collapses, so aggregate busy is
        bounded by the per-lane sum and dominated by every single
        lane."""
        buckets = trace.attribute_rounds(
            trace.load_trace_events(SKEW_FIXTURE))
        for b in buckets.values():
            lane_busy = [l["busy_s"] for l in b["per_device"].values()]
            assert max(lane_busy) <= b["busy_s"] + 1e-12
            assert b["busy_s"] <= sum(lane_busy) + 1e-12

    def test_v4_buckets_validate_and_round_trip(self):
        buckets = trace.attribute_rounds(
            trace.load_trace_events(SKEW_FIXTURE))
        rec = make_round_record(7)
        rec["device_time"] = buckets[0]
        assert validate_record(rec) == []
        back = json.loads(json.dumps(rec))
        assert validate_record(back) == []
        assert back["device_time"] == rec["device_time"]

    def test_overlap_fixture_golden_buckets(self):
        """``overlap.trace.json.gz``: two TPU lanes, round 0 in the
        pipelined shape (all-reduce.5 [1400,1600) runs while TPU:1 is
        still inside fusion.3 until 1450 — 50 us of the pooled
        collective union intersects some lane's compute), round 1 the
        serial shape (all-reduce.7 starts only after every fusion has
        ended — zero intersection). All values hand-computed."""
        buckets = trace.attribute_rounds(
            trace.load_trace_events(OVERLAP_FIXTURE))
        assert sorted(buckets) == [0, 1]
        b0 = buckets[0]
        assert {k: b0[k] for k in AGG_KEYS} == {
            "window_s": 0.001, "busy_s": 0.0007,
            "compute_s": 0.0004, "collective_s": 0.0002,
            "transfer_s": 0.0001, "host_gap_s": 0.0003}
        assert b0["overlapped_s"] == 5e-05
        assert b0["per_device"] == {
            "TPU:0": {"busy_s": 0.0007, "compute_s": 0.0005,
                      "collective_s": 0.0002, "transfer_s": 0.0,
                      "wait_s": 5e-05, "wire_s": 0.00015},
            "TPU:1": {"busy_s": 0.0004, "compute_s": 0.00015,
                      "collective_s": 0.00015, "transfer_s": 0.0001,
                      "wait_s": 0.0, "wire_s": 0.00015}}
        assert b0["skew"] == {
            "n_collectives": 1, "max_enter_delta_s": 5e-05,
            "p95_enter_delta_s": 5e-05, "straggler_device": "TPU:1"}
        b1 = buckets[1]
        assert {k: b1[k] for k in AGG_KEYS} == {
            "window_s": 0.001, "busy_s": 0.0004,
            "compute_s": 0.0002, "collective_s": 0.0002,
            "transfer_s": 0.0, "host_gap_s": 0.0006}
        assert b1["overlapped_s"] == 0.0
        assert b1["per_device"] == {
            "TPU:0": {"busy_s": 0.0004, "compute_s": 0.0002,
                      "collective_s": 0.0002, "transfer_s": 0.0,
                      "wait_s": 0.0, "wire_s": 0.0002},
            "TPU:1": {"busy_s": 0.00035, "compute_s": 0.00015,
                      "collective_s": 0.0002, "transfer_s": 0.0,
                      "wait_s": 0.0, "wire_s": 0.0002}}

    def test_overlapped_is_an_overlay_not_a_fifth_bucket(self):
        """``overlapped_s`` bounds and partition exactness on every
        checked-in fixture: 0 <= overlapped <= collective, and the
        four real buckets still sum to the window to 1e-12 — the
        overlay must never perturb the partition."""
        for fixture in (FIXTURE, SKEW_FIXTURE, OVERLAP_FIXTURE):
            buckets = trace.attribute_rounds(
                trace.load_trace_events(fixture))
            for b in buckets.values():
                assert 0.0 <= b["overlapped_s"] <= \
                    b["collective_s"] + 1e-12, fixture
                parts = (b["compute_s"] + b["collective_s"]
                         + b["transfer_s"] + b["host_gap_s"])
                assert parts == pytest.approx(b["window_s"],
                                              abs=1e-12), fixture

    def test_skew_metrics_reach_the_gate(self):
        rec = make_round_record(0)
        rec["device_time"] = {"busy_s": 0.5, "skew": {
            "n_collectives": 3, "max_enter_delta_s": 0.02,
            "p95_enter_delta_s": 0.01, "straggler_device": "TPU:1"}}
        metrics = gate.metrics_from_records([rec])
        assert metrics["device:skew_max_enter_delta_s"]["median"] == \
            pytest.approx(0.02)
        assert metrics["device:skew_max_enter_delta_s"]["better"] == \
            "lower"
        assert metrics["device:skew_p95_enter_delta_s"]["better"] == \
            "lower"


class _SkewAlarmCfg(_AlarmCfg):
    alarm_collective_skew = 0.4


class TestCollectiveSkewAlarm:
    BUCKETS = {"window_s": 1.0, "busy_s": 0.6, "compute_s": 0.5,
               "collective_s": 0.1, "transfer_s": 0.0,
               "host_gap_s": 0.4}

    @staticmethod
    def _with_skew(delta, straggler="TPU:3"):
        b = dict(TestCollectiveSkewAlarm.BUCKETS)
        b["skew"] = {"n_collectives": 2, "max_enter_delta_s": delta,
                     "p95_enter_delta_s": delta,
                     "straggler_device": straggler}
        return b

    def test_fires_above_collective_fraction(self):
        eng = AlarmEngine(_SkewAlarmCfg())
        # threshold = 0.4 x collective_s 0.1 = 0.04 s of skew
        assert eng.check_device_time(0, self._with_skew(0.03)) == []
        fired = eng.check_device_time(1, self._with_skew(0.05))
        assert fired and fired[0]["rule"] == "collective_skew"
        assert fired[0]["straggler_device"] == "TPU:3"
        assert fired[0]["value"] == pytest.approx(0.05)
        assert fired[0]["threshold"] == pytest.approx(0.04)

    def test_no_collective_no_fire(self):
        eng = AlarmEngine(_SkewAlarmCfg())
        b = self._with_skew(0.5)
        b["collective_s"] = 0.0
        assert eng.check_device_time(0, b) == []
        # v3 buckets without skew never fire either
        assert eng.check_device_time(1, dict(self.BUCKETS)) == []

    def test_disarmed_when_zero(self):
        class Off(_AlarmCfg):
            alarm_collective_skew = 0.0
        eng = AlarmEngine(Off())
        assert eng.check_device_time(0, self._with_skew(9.9)) == []

    def test_flags_ledger_record_through_telemetry(self):
        sink = _ListSink()
        tel = Telemetry(sinks=[sink])
        tel.hold_emission(True)
        tel.begin_round(0)
        eng = AlarmEngine(_SkewAlarmCfg(), telemetry=tel)
        tel.on_device_time = eng.check_device_time
        tel.merge_round_device_time(0, self._with_skew(0.09))
        tel.hold_emission(False)
        tel.close()
        rounds = [r for r in sink.records if r["kind"] == "round"]
        assert rounds[0]["alarms"]
        assert rounds[0]["alarms"][0]["rule"] == "collective_skew"

    def test_abort_action_raises_from_merge(self):
        class Abort(_SkewAlarmCfg):
            on_divergence = "abort"
        tel = Telemetry(sinks=[_ListSink()])
        tel.begin_round(0)
        eng = AlarmEngine(Abort(), telemetry=tel)
        tel.on_device_time = eng.check_device_time
        with pytest.raises(DivergenceAbort):
            tel.merge_round_device_time(0, self._with_skew(0.5))

    def test_build_alarm_engine_arms_on_skew_alone(self):
        from commefficient_tpu.telemetry.alarms import \
            build_alarm_engine

        class OnlySkew(_AlarmCfg):
            probe_period = 0
            alarm_step_time_ratio = 0.0
            alarm_collective_skew = 0.5
        assert build_alarm_engine(OnlySkew()) is not None

        class Nothing(OnlySkew):
            alarm_collective_skew = 0.0
        assert build_alarm_engine(Nothing()) is None


# --- cross-host ledger shards -----------------------------------------


def _load_script(name):
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _ShardCfg:
    def __init__(self, ledger, console=False):
        self.ledger = ledger
        self.telemetry_console = console


class TestLedgerShards:
    def test_shard_path_naming(self):
        from commefficient_tpu.telemetry.sinks import shard_ledger_path
        assert shard_ledger_path("/x/a.jsonl", 0) == "/x/a.jsonl"
        assert shard_ledger_path("/x/a.jsonl", 1) == \
            "/x/a.jsonl.p1.jsonl"
        assert shard_ledger_path("/x/a.jsonl", 3) == \
            "/x/a.jsonl.p3.jsonl"

    def test_every_process_writes_its_shard(self, tmp_path, capsys):
        """The old process-0 gate silently dropped every other host's
        telemetry; now process k > 0 writes a process-stamped shard
        and says so once."""
        from commefficient_tpu.telemetry.core import build_telemetry
        ledger = str(tmp_path / "led.jsonl")

        tel0 = build_telemetry(_ShardCfg(ledger), process_index=0,
                               process_count=2)
        tel0.begin_round(0)
        tel0.close()
        tel1 = build_telemetry(_ShardCfg(ledger), process_index=1,
                               process_count=2)
        tel1.begin_round(0)
        tel1.close()

        assert os.path.exists(ledger)
        shard = ledger + ".p1.jsonl"
        assert os.path.exists(shard)
        out = capsys.readouterr().out
        assert "ledger shard" in out and ".p1.jsonl" in out
        canon = [json.loads(l) for l in open(ledger)]
        shrd = [json.loads(l) for l in open(shard)]
        assert all(not validate_record(r) for r in canon + shrd)
        # both sides are process-stamped on a multi-process mesh
        assert {r["process"] for r in canon} == {0}
        assert {r["process"] for r in shrd} == {1}

    def test_single_process_is_unstamped(self, tmp_path):
        from commefficient_tpu.telemetry.core import build_telemetry
        ledger = str(tmp_path / "solo.jsonl")
        tel = build_telemetry(_ShardCfg(ledger), process_index=0,
                              process_count=1)
        tel.begin_round(0)
        tel.close()
        recs = [json.loads(l) for l in open(ledger)]
        assert recs and all("process" not in r for r in recs)

    def _write_shard_fixture(self, tmp_path):
        ledger = str(tmp_path / "fleet.jsonl")
        with open(ledger, "w") as f:
            meta = {"schema": 1, "kind": "meta", "ts": 0.0,
                    "num_devices": 4, "process_count": 2}
            f.write(json.dumps(meta) + "\n")
            for r in range(2):
                rec = make_round_record(r)
                rec["spans"] = {"round_dispatch": 0.05}
                rec["device_time"] = {
                    "window_s": 0.1, "busy_s": 0.08,
                    "compute_s": 0.07, "collective_s": 0.01,
                    "transfer_s": 0.0, "host_gap_s": 0.02}
                f.write(json.dumps(rec) + "\n")
        shard = ledger + ".p1.jsonl"
        with open(shard, "w") as f:
            f.write(json.dumps({"schema": 1, "kind": "meta",
                                "ts": 0.0, "process": 1}) + "\n")
            for r in range(3):  # round 2 exists ONLY on the shard
                rec = make_round_record(r)
                rec["process"] = 1
                rec["spans"] = {"client_feed": 0.01}
                rec["host_rss_peak_bytes"] = 1000.0 + r
                rec["uplink_bytes"] = 64.0
                rec["device_time"] = {
                    "window_s": 0.1, "busy_s": 0.06,
                    "compute_s": 0.05, "collective_s": 0.01,
                    "transfer_s": 0.0, "host_gap_s": 0.04}
                f.write(json.dumps(rec) + "\n")
        return ledger, shard

    def test_merge_joins_shards_on_round_id(self, tmp_path):
        lm = _load_script("ledger_merge")
        ledger, shard = self._write_shard_fixture(tmp_path)
        assert lm.discover_shards(ledger) == [(1, shard)]
        assert lm.main([ledger]) == 0
        merged_path = ledger + ".merged.jsonl"
        assert os.path.exists(merged_path)
        merged = [json.loads(l) for l in open(merged_path)]
        rounds = [r for r in merged if r.get("kind") == "round"]
        assert [r["round"] for r in rounds] == [0, 1, 2]
        for r in rounds[:2]:
            sh = r["shards"]["p1"]
            assert sh["spans"] == {"client_feed": 0.01}
            assert sh["uplink_bytes"] == 64.0
            # per-host host gap: the multi-host straggler scoreboard
            assert r["host_gap_by_process"] == {
                "p0": 0.02, "p1": 0.04}
        # the round only process 1 survived to record is kept, flagged
        assert rounds[2]["shard_only"] is True
        assert rounds[2]["process"] == 1
        # shard meta dropped: only the canonical meta remains
        metas = [r for r in merged if r.get("kind") == "meta"]
        assert len(metas) == 1 and "process" not in metas[0]

    def test_merge_without_shards_is_an_error(self, tmp_path):
        lm = _load_script("ledger_merge")
        ledger = str(tmp_path / "solo.jsonl")
        with open(ledger, "w") as f:
            f.write(json.dumps(make_round_record(0)) + "\n")
        assert lm.main([ledger]) == 1

    def test_report_summarizes_merged_shards(self, tmp_path):
        lm = _load_script("ledger_merge")
        tr = _load_script("telemetry_report")
        ledger, _ = self._write_shard_fixture(tmp_path)
        assert lm.main([ledger]) == 0
        records, problems = tr.load_ledger(ledger + ".merged.jsonl")
        assert problems == []
        summ = tr.summarize(records)
        assert summ["shards"]["p1"]["rounds"] == 2
        assert summ["shards"]["p1"]["host_gap_mean_ms"] == \
            pytest.approx(40.0)
        assert summ["shards"]["p1"]["host_rss_peak_bytes"] == 1001.0
        rendered = tr.render_summary(summ, label="merged")
        assert "shard p1" in rendered


# --- topology-keyed gate ----------------------------------------------


class TestTopologyGate:
    def test_entries_are_isolated_per_topology(self):
        base = gate.make_baseline(
            {"span:round_dispatch:ms": _metric(10.0)},
            device_count=8, process_count=1, config_hash="cafe")
        entry = gate.baseline_entry(base, 8, 1)
        assert entry["device_count"] == 8
        assert entry["config_hash"] == "cafe"
        assert gate.baseline_entry(base, 4, 1) is None
        verdict = gate.compare(
            base, {"span:round_dispatch:ms": _metric(11.0)},
            device_count=8, process_count=1)
        assert verdict["topology"] == "d8p1"
        assert verdict["regressions"] == []
        # an ungated topology point fails LOUDLY, never silently
        with pytest.raises(ValueError, match="d4p1"):
            gate.compare(base,
                         {"span:round_dispatch:ms": _metric(11.0)},
                         device_count=4, process_count=1)

    def test_update_replaces_only_one_topology(self):
        base = gate.make_baseline(
            {"span:a:ms": _metric(10.0)}, device_count=1,
            process_count=1)
        base = gate.update_baseline(
            base, {"span:a:ms": _metric(5.0)}, source="x",
            device_count=8, process_count=1, config_hash="c8")
        assert sorted(base["topologies"]) == ["d1p1", "d8p1"]
        base = gate.update_baseline(
            base, {"span:a:ms": _metric(4.0)}, source="y",
            device_count=8, process_count=1, config_hash="c8")
        assert base["topologies"]["d8p1"]["metrics"][
            "span:a:ms"]["median"] == pytest.approx(4.0)
        assert base["topologies"]["d1p1"]["metrics"][
            "span:a:ms"]["median"] == pytest.approx(10.0)

    def test_v1_baseline_resolves_for_any_topology(self):
        """Legacy topology-blind baselines keep working (their
        historical behaviour) until re-captured."""
        v1 = {"schema": 1, "ts": 0.0, "source": "old",
              "metrics": {"span:a:ms": _metric(10.0)}}
        assert gate.baseline_entry(v1, 8, 1)["metrics"]
        verdict = gate.compare(v1, {"span:a:ms": _metric(11.0)},
                               device_count=8, process_count=1)
        assert verdict["regressions"] == []
        migrated = gate.migrate_baseline(v1)
        assert migrated["schema"] == gate.BASELINE_SCHEMA
        assert migrated["topologies"][gate.ANY_TOPOLOGY][
            "metrics"]["span:a:ms"]["median"] == 10.0

    def test_unreadable_schema_raises(self):
        with pytest.raises(ValueError, match="schema"):
            gate.baseline_entry({"schema": 99}, 1, 1)

    def test_mesh_shape_extends_topology_key(self):
        """2D-mesh runs key separately per shape; 1-D layouts keep
        the historical mesh-less key (v2 pins stay valid)."""
        ms = {"clients": 4, "model": 2}
        assert gate.topology_key(8, 1, ms) == "d8p1m4x2"
        assert gate.topology_key(8, 1, {"clients": 8, "model": 1}) \
            == "d8p1"
        assert gate.topology_key(8, 1, None) == "d8p1"
        assert gate.topology_key(None, None, ms) == gate.ANY_TOPOLOGY
        base = gate.make_baseline(
            {"span:a:ms": _metric(10.0)}, device_count=8,
            process_count=1, mesh_shape=ms)
        assert sorted(base["topologies"]) == ["d8p1m4x2"]
        assert base["topologies"]["d8p1m4x2"]["mesh_shape"] == ms
        # distinct shapes on the same chips are distinct entries
        base = gate.update_baseline(
            base, {"span:a:ms": _metric(7.0)}, device_count=8,
            process_count=1, mesh_shape={"clients": 2, "model": 4})
        assert sorted(base["topologies"]) == ["d8p1m2x4", "d8p1m4x2"]
        verdict = gate.compare(base, {"span:a:ms": _metric(10.5)},
                               device_count=8, process_count=1,
                               mesh_shape=ms)
        assert verdict["topology"] == "d8p1m4x2"
        assert verdict["regressions"] == []

    def test_mesh_run_falls_back_to_meshless_pin(self):
        """A pin captured before mesh keying existed keeps gating a
        2D run (migration), but an exact mesh-keyed entry wins."""
        base = gate.make_baseline(
            {"span:a:ms": _metric(10.0)}, device_count=8,
            process_count=1)
        ms = {"clients": 4, "model": 2}
        entry = gate.baseline_entry(base, 8, 1, ms)
        assert entry is not None and "mesh_shape" not in entry
        base = gate.update_baseline(
            base, {"span:a:ms": _metric(5.0)}, device_count=8,
            process_count=1, mesh_shape=ms)
        assert gate.baseline_entry(base, 8, 1, ms)["metrics"][
            "span:a:ms"]["median"] == pytest.approx(5.0)
        # the 1-D key never sees the mesh entry
        assert gate.baseline_entry(base, 8, 1)["metrics"][
            "span:a:ms"]["median"] == pytest.approx(10.0)

    def test_cli_topology_cycle(self, tmp_path, capsys):
        """One baseline file guards several topology points
        independently: a regression at d4p1 fails ONLY d4p1, and a
        topology with no entry is a loud failure."""
        pg = _load_perf_gate()
        good = str(tmp_path / "good.jsonl")
        slow = str(tmp_path / "slow.jsonl")
        baseline = str(tmp_path / "perf_baseline.json")
        _write_ledger(good, 0.050)
        _write_ledger(slow, 0.200)

        assert pg.main(["--ledger", good, "--write-baseline", baseline,
                        "--device_count", "8",
                        "--process_count", "1"]) == 0
        # no d4p1 entry yet: --check fails loudly...
        assert pg.main(["--ledger", good, "--baseline", baseline,
                        "--check", "--device_count", "4",
                        "--process_count", "1"]) == 1
        assert "no d4p1 entry" in capsys.readouterr().out
        # ...and --write-baseline captures it without gating
        assert pg.main(["--ledger", good, "--write-baseline", baseline,
                        "--device_count", "4",
                        "--process_count", "1"]) == 0
        base = gate.load_baseline(baseline)
        assert sorted(base["topologies"]) == ["d4p1", "d8p1"]
        # a regression at ONE topology point fails that point only
        assert pg.main(["--ledger", slow, "--baseline", baseline,
                        "--check", "--device_count", "4",
                        "--process_count", "1"]) == 1
        assert pg.main(["--ledger", good, "--baseline", baseline,
                        "--check", "--device_count", "8",
                        "--process_count", "1"]) == 0

    def test_cli_reads_topology_from_ledger_meta(self, tmp_path):
        pg = _load_perf_gate()
        ledger = str(tmp_path / "meta.jsonl")
        with open(ledger, "w") as f:
            f.write(json.dumps({"schema": 1, "kind": "meta",
                                "ts": 0.0, "num_devices": 8}) + "\n")
            rec = make_round_record(0)
            rec["spans"] = {"round_dispatch": 0.05}
            f.write(json.dumps(rec) + "\n")
        records = pg.load_ledger_records(ledger)
        # pre-fleet metas never recorded process_count: defaults to 1
        assert pg.resolve_topology(None, records) == \
            (8, 1, None, None, None, None, None, None, None)
        # CLI overrides win
        assert pg.resolve_topology(None, records,
                                   device_count=2,
                                   process_count=2) == \
            (2, 2, None, None, None, None, None, None, None)
        manifest = {"device_count": 16, "process_count": 4}
        assert pg.resolve_topology(manifest, records) == \
            (16, 4, None, None, None, None, None, None, None)

    def test_resolve_mesh_shape_chain(self, tmp_path):
        """Mesh layout resolution: CLI "CxM" wins, then the manifest
        dict, then the ledger meta record; 1-D runs stay None."""
        pg = _load_perf_gate()
        ledger = str(tmp_path / "mesh.jsonl")
        with open(ledger, "w") as f:
            f.write(json.dumps({
                "schema": 1, "kind": "meta", "ts": 0.0,
                "num_devices": 8,
                "mesh_shape": {"clients": 4, "model": 2}}) + "\n")
        records = pg.load_ledger_records(ledger)
        assert pg.resolve_topology(None, records) == \
            (8, 1, {"clients": 4, "model": 2}, None, None, None,
             None, None, None)
        manifest = {"device_count": 8, "process_count": 1,
                    "mesh_shape": {"clients": 2, "model": 4}}
        assert pg.resolve_topology(manifest, records)[2] == \
            {"clients": 2, "model": 4}
        assert pg.resolve_topology(manifest, records,
                                   mesh_shape="8x1")[2] == \
            {"clients": 8, "model": 1}


# --- registry topology keys -------------------------------------------


class TestRegistryTopologyKeys:
    def test_run_topology_and_key(self):
        m = {"config_hash": "c", "device_count": 8, "process_count": 2}
        assert registry.run_topology(m) == (8, 2)
        assert registry.run_key(m) == ("c", 8, 2)
        # pre-fleet manifests: unknown topology, never silently
        # comparable with a counted run
        assert registry.run_topology({}) == (None, None)
        assert registry.run_key({"config_hash": "c"}) != \
            registry.run_key(m)
        # 2D-mesh runs get their own comparability key; 1-D runs
        # keep the historical 3-tuple
        m2 = dict(m, mesh_shape={"clients": 4, "model": 2})
        assert registry.run_key(m2) == ("c", 8, 2, "m4x2")
        m1 = dict(m, mesh_shape={"clients": 8, "model": 1})
        assert registry.run_key(m1) == registry.run_key(m)

    def test_manifest_records_live_topology(self, tmp_path):
        ledger = str(tmp_path / "a.jsonl")
        open(ledger, "w").close()
        registry.write_manifest(str(tmp_path / "runs"),
                                args=_Cfg(x=1), ledger=ledger)
        (_, rec), = registry.list_manifests(str(tmp_path / "runs"))
        assert isinstance(rec["device_count"], int)
        assert isinstance(rec["process_count"], int)
        # single-process run: no shard list
        assert "ledger_shards" not in rec

    def _fake_manifest(self, runs, name, ts, chash, ledger, dc, pc,
                       scaling=None):
        out_dir = os.path.join(runs, registry.MANIFEST_DIR)
        os.makedirs(out_dir, exist_ok=True)
        rec = {"schema": 1, "kind": "run_manifest", "ts": ts,
               "config_hash": chash, "ledger": ledger,
               "device_count": dc, "process_count": pc,
               "git_sha": "", "bench": {}}
        if scaling:
            rec["scaling"] = scaling
        path = os.path.join(out_dir, f"run_{name}.json")
        with open(path, "w") as f:
            json.dump(rec, f)
        return path

    def test_latest_ledgers_key_filter(self, tmp_path):
        runs = str(tmp_path / "runs")
        led = str(tmp_path / "led.jsonl")
        open(led, "w").close()
        self._fake_manifest(runs, "a", 1.0, "cfg", led, 1, 1)
        self._fake_manifest(runs, "b", 2.0, "cfg", led, 8, 1)
        self._fake_manifest(runs, "c", 3.0, "cfg", led, 8, 1)
        hits = registry.latest_ledgers(runs, n=5,
                                       key=("cfg", 8, 1))
        assert len(hits) == 2
        assert all(registry.run_topology(m) == (8, 1)
                   for _, m, _ in hits)
        # newest first
        assert hits[0][1]["ts"] == 3.0
        assert registry.latest_ledgers(runs, n=5,
                                       key=("cfg", 2, 1)) == []


# --- scaling curves in the report -------------------------------------


class TestScalingCurves:
    def _scaling(self, cps, eff, frac=0.1, skew=0.001):
        return {"clients_per_s": cps, "parallel_efficiency": eff,
                "collective_fraction": frac, "max_skew_s": skew}

    def test_groups_by_config_and_orders_by_topology(self):
        tr = _load_script("telemetry_report")
        manifests = [
            ("m4", {"config_hash": "aaaa", "device_count": 4,
                    "process_count": 1,
                    "scaling": self._scaling(300.0, 0.75)}),
            ("m1", {"config_hash": "aaaa", "device_count": 1,
                    "process_count": 1,
                    "scaling": self._scaling(100.0, 1.0)}),
            # a single-point config is not a curve
            ("mx", {"config_hash": "bbbb", "device_count": 1,
                    "process_count": 1,
                    "scaling": self._scaling(50.0, 1.0)}),
            # manifests without a scaling block are ignored
            ("my", {"config_hash": "aaaa", "device_count": 2,
                    "process_count": 1}),
        ]
        curves = tr.scaling_curves(manifests)
        assert len(curves) == 1
        assert curves[0]["config_hash"] == "aaaa"
        assert [(p["device_count"], p["process_count"])
                for p in curves[0]["points"]] == [(1, 1), (4, 1)]
        rendered = tr.render_scaling_curves(curves)
        assert "d1p1" in rendered and "d4p1" in rendered
        assert "eff 0.750" in rendered
        assert "clients/s" in rendered

    def test_newest_manifest_wins_per_topology_point(self):
        tr = _load_script("telemetry_report")
        manifests = [  # list_manifests order: oldest first
            ("old", {"config_hash": "aaaa", "device_count": 2,
                     "process_count": 1,
                     "scaling": self._scaling(10.0, 0.5)}),
            ("new", {"config_hash": "aaaa", "device_count": 2,
                     "process_count": 1,
                     "scaling": self._scaling(20.0, 0.9)}),
            ("one", {"config_hash": "aaaa", "device_count": 1,
                     "process_count": 1,
                     "scaling": self._scaling(11.0, 1.0)}),
        ]
        curves = tr.scaling_curves(manifests)
        (curve,) = curves
        p2 = [p for p in curve["points"]
              if p["device_count"] == 2][0]
        assert p2["clients_per_s"] == 20.0
        assert p2["manifest"] == "new"

    def test_runs_dir_report_renders_curve(self, tmp_path, capsys):
        tr = _load_script("telemetry_report")
        runs = str(tmp_path / "runs")
        out_dir = os.path.join(runs, registry.MANIFEST_DIR)
        os.makedirs(out_dir)
        for i, (dc, cps, eff) in enumerate(
                [(1, 100.0, 1.0), (2, 180.0, 0.9)]):
            ledger = str(tmp_path / f"led{dc}.jsonl")
            _write_ledger(ledger, 0.05)
            rec = {"schema": 1, "kind": "run_manifest",
                   "ts": float(i + 1), "config_hash": "aaaa",
                   "ledger": ledger, "device_count": dc,
                   "process_count": 1, "git_sha": "", "bench": {},
                   "scaling": self._scaling(cps, eff)}
            with open(os.path.join(out_dir,
                                   f"run_{i}.json"), "w") as f:
                json.dump(rec, f)
        assert tr.runs_dir_report(runs, as_json=False) == 0
        out = capsys.readouterr().out
        assert "scaling curve" in out
        assert "d1p1" in out and "d2p1" in out
        # the two runs differ in topology: no cross-topology diff
        assert "no previous run with this config+topology" in out
