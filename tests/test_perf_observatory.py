"""Performance-observatory tests: device-time trace attribution,
roofline FLOP counting, the noise-aware perf gate, the run registry,
the step-time alarm, stale-waiver detection, and the end-to-end
``--profile`` path on a real CPU mesh.

The golden-trace test runs against ``tests/fixtures/mini.trace.json.gz``
— a hand-authored Chrome trace-event dump with two ``fed_round``
markers, overlapping compute/collective device events, a transfer that
straddles the round boundary, and events that attribution must ignore
(phase annotations, host-lane python frames, out-of-window ops). Its
bucket values are computed by hand and asserted exactly.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from commefficient_tpu.telemetry import gate, registry, trace
from commefficient_tpu.telemetry.alarms import (AlarmEngine,
                                                DivergenceAbort)
from commefficient_tpu.telemetry.core import Telemetry
from commefficient_tpu.telemetry.record import (make_bench_record,
                                                make_round_record,
                                                validate_record)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "mini.trace.json.gz")


# --- golden trace parser ----------------------------------------------


class TestTraceAttribution:
    def test_fixture_golden_buckets(self):
        """Hand-computed buckets for the checked-in mini trace.

        Round 0 window [1000, 2000) us: device busy = fusion.1 union
        all-reduce.2 (1100-1400) + copy.3 clipped (1900-2000) = 400 us;
        collective 150, transfer 100 (copy minus collective overlap:
        none), compute 150, host gap 600. Round 1 window [2000, 3500):
        copy.3 tail (2000-2100) + fusion.4 (2200-2500) = 400 busy,
        no collective, transfer 100, compute 300, gap 1100."""
        events = trace.load_trace_events(FIXTURE)
        buckets = trace.attribute_rounds(events)
        assert sorted(buckets) == [0, 1]
        assert buckets[0] == {
            "window_s": 0.001, "busy_s": 0.0004,
            "compute_s": 0.00015, "collective_s": 0.00015,
            "transfer_s": 0.0001, "host_gap_s": 0.0006}
        assert buckets[1] == {
            "window_s": 0.0015, "busy_s": 0.0004,
            "compute_s": 0.0003, "collective_s": 0.0,
            "transfer_s": 0.0001, "host_gap_s": 0.0011}

    def test_buckets_partition_each_window(self):
        buckets = trace.attribute_rounds(
            trace.load_trace_events(FIXTURE))
        for b in buckets.values():
            parts = (b["compute_s"] + b["collective_s"]
                     + b["transfer_s"] + b["host_gap_s"])
            assert abs(parts - b["window_s"]) < 1e-9
            assert abs((b["busy_s"] + b["host_gap_s"])
                       - b["window_s"]) < 1e-9

    def test_device_lanes_exclude_host_python(self):
        events = trace.load_trace_events(FIXTURE)
        lanes = trace.device_lanes(events)
        # pid 2 is a /device: process, pid 3 hosts a tf_XLA* thread;
        # pid 1 (host python, where the round markers live) is not a
        # device lane
        assert (2, 20) in lanes and (3, 30) in lanes
        assert all(pid != 1 for pid, _tid in lanes)

    def test_round_windows_from_markers(self):
        events = trace.load_trace_events(FIXTURE)
        windows = trace.round_windows(events)
        assert windows == [(0, 1000.0, 2000.0),
                           (1, 2000.0, 3500.0)]

    def test_attribute_logdir_finds_gz(self, tmp_path):
        sub = tmp_path / "plugins" / "profile" / "x"
        sub.mkdir(parents=True)
        with open(FIXTURE, "rb") as f:
            (sub / "host.trace.json.gz").write_bytes(f.read())
        buckets = trace.attribute_logdir(str(tmp_path))
        assert sorted(buckets) == [0, 1]

    def test_no_markers_no_rounds(self):
        events = [{"ph": "M", "pid": 2, "name": "process_name",
                   "args": {"name": "/device:TPU:0"}},
                  {"ph": "X", "pid": 2, "tid": 1, "name": "fusion.1",
                   "ts": 10, "dur": 5, "args": {}}]
        assert trace.attribute_rounds(events) == {}


# --- roofline FLOP inventory ------------------------------------------


CANNED_STABLEHLO = """
module @round {
  func.func public @main(%arg0: tensor<8x32xf32>, %arg1: tensor<32x16xf32>) -> tensor<8x16xf32> {
    %0 = stablehlo.dot_general %arg0, %arg1, contracting_dims = [1] x [0] : (tensor<8x32xf32>, tensor<32x16xf32>) -> tensor<8x16xf32>
    %1 = stablehlo.convolution(%arg2, %arg3) dim_numbers = [b, 0, 1, f]x[0, 1, i, o]->[b, 0, 1, f], window = {stride = [1, 1]} : (tensor<1x8x8x3xf32>, tensor<3x3x3x16xf32>) -> tensor<1x8x8x16xf32>
    return %0 : tensor<8x16xf32>
  }
}
"""


class TestFlopInventory:
    def test_dot_and_conv_macs(self):
        from commefficient_tpu.analysis.hlo import flop_inventory
        inv = flop_inventory(CANNED_STABLEHLO)
        # dot: 2 x numel(8x16) x K=32; conv: 2 x numel(1x8x8x16) x
        # (numel(3x3x3x16) / O=16) = 2 x 1024 x 27
        assert inv["dot_flops"] == 2 * 8 * 16 * 32
        assert inv["conv_flops"] == 2 * (8 * 8 * 16) * (3 * 3 * 3)
        assert inv["total_flops"] == inv["dot_flops"] + inv["conv_flops"]
        assert inv["dot_count"] == 1 and inv["conv_count"] == 1
        assert inv["by_dtype"] == {"f32": inv["total_flops"]}

    def test_cost_model_floors(self):
        from commefficient_tpu.analysis.cost import build_cost_model
        cost = build_cost_model(
            CANNED_STABLEHLO, backend="cpu", device_kind="cpu",
            n_devices=8, allreduce_payload_bytes=4.0 * 50_000,
            label="test/8dev")
        assert cost["total_flops"] == 2 * 8 * 16 * 32 + 2 * 1024 * 27
        assert cost["expected_round_s"] > 0
        assert cost["expected_round_s"] >= cost["compute_floor_s"]
        assert cost["expected_round_s"] >= cost["collective_floor_s"]


# --- perf-gate math ---------------------------------------------------


def _metric(median, mad=0.0, better="lower", n=8):
    return {"median": median, "mad": mad, "n": n, "p50": median,
            "p95": median, "better": better}


class TestGateMath:
    def test_noise_within_band_passes(self):
        base = gate.make_baseline(
            {"span:round_dispatch:ms": _metric(10.0, mad=0.5)})
        verdict = gate.compare(
            base, {"span:round_dispatch:ms": _metric(12.0)})
        assert verdict["checked"] == 1
        assert verdict["regressions"] == []

    def test_regression_beyond_band_fails(self):
        base = gate.make_baseline(
            {"span:round_dispatch:ms": _metric(10.0, mad=0.5)})
        verdict = gate.compare(
            base, {"span:round_dispatch:ms": _metric(20.0)})
        assert len(verdict["regressions"]) == 1
        r = verdict["regressions"][0]
        assert r["metric"] == "span:round_dispatch:ms"
        # band = max(0.25 * 10, 5 * 0.5) = 2.5ms; delta = 10ms
        assert r["tolerance"] == pytest.approx(2.5)

    def test_mad_band_dominates_when_noisy(self):
        # mad 2ms -> band 10ms: a 9ms jump is still noise
        base = gate.make_baseline(
            {"span:h2d:ms": _metric(10.0, mad=2.0)})
        verdict = gate.compare(base, {"span:h2d:ms": _metric(19.0)})
        assert verdict["regressions"] == []

    def test_higher_is_better_metrics_gate_downward(self):
        base = gate.make_baseline(
            {"bench:clients_per_s": _metric(100.0, better="higher")})
        bad = gate.compare(
            base, {"bench:clients_per_s": _metric(50.0,
                                                  better="higher")})
        good = gate.compare(
            base, {"bench:clients_per_s": _metric(200.0,
                                                  better="higher")})
        assert len(bad["regressions"]) == 1
        assert bad["improvements"] == []
        assert good["regressions"] == []
        assert len(good["improvements"]) == 1

    def test_one_sided_metrics_skip(self):
        base = gate.make_baseline({"span:a:ms": _metric(1.0)})
        verdict = gate.compare(base, {"span:b:ms": _metric(1.0)})
        assert verdict["checked"] == 0
        reasons = {s["metric"]: s["reason"]
                   for s in verdict["skipped"]}
        assert reasons == {"span:a:ms": "not in current run",
                           "span:b:ms": "not in baseline"}

    def test_sub_resolution_baseline_skipped(self):
        # 0.01 ms median is below scheduler resolution: a 100x blowup
        # is not gateable signal
        base = gate.make_baseline({"span:tiny:ms": _metric(0.01)})
        verdict = gate.compare(base, {"span:tiny:ms": _metric(1.0)})
        assert verdict["checked"] == 0
        assert verdict["skipped"][0]["reason"] == \
            "below timing resolution"

    def test_roofline_utilization_never_floored(self):
        base = gate.make_baseline(
            {"device:roofline_utilization": _metric(0.0005,
                                                    better="higher")})
        verdict = gate.compare(
            base, {"device:roofline_utilization": _metric(
                0.0001, better="higher")})
        assert verdict["checked"] == 1
        assert len(verdict["regressions"]) == 1

    def test_schema_mismatch_raises(self):
        with pytest.raises(ValueError, match="schema"):
            gate.compare({"schema": 99, "metrics": {}}, {})

    def test_metrics_from_records_shapes(self):
        rec = make_round_record(0)
        rec["spans"] = {"h2d": 0.002, "server": 0.001}
        rec["device_time"] = {"busy_s": 0.5, "compute_s": 0.4,
                              "roofline_utilization": 0.31}
        bench = make_bench_record("clients_per_s", 120.0, "1/s",
                                  round_times_s=[0.1, 0.11, 0.09])
        metrics = gate.metrics_from_records([rec, bench])
        assert metrics["span:h2d:ms"]["median"] == \
            pytest.approx(2.0)
        assert metrics["span:h2d:ms"]["better"] == "lower"
        assert metrics["device:busy_s"]["better"] == "lower"
        assert metrics["device:roofline_utilization"]["better"] == \
            "higher"
        assert metrics["bench:clients_per_s"]["median"] == 120.0
        assert metrics["bench:clients_per_s"]["better"] == "higher"
        assert metrics["bench:clients_per_s:round_s"]["n"] == 3
        assert metrics["bench:clients_per_s:round_s"]["better"] == \
            "lower"


# --- perf_gate CLI ----------------------------------------------------


def _load_perf_gate():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "perf_gate.py")
    spec = importlib.util.spec_from_file_location("_perf_gate", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_ledger(path, round_s):
    """A synthetic ledger whose round_dispatch span is ``round_s``."""
    with open(path, "w") as f:
        for r in range(8):
            rec = make_round_record(r)
            rec["spans"] = {"round_dispatch": round_s}
            rec["uplink_bytes"] = rec["downlink_bytes"] = 1024.0
            rec["device_time"] = {"window_s": round_s,
                                  "busy_s": 0.8 * round_s,
                                  "compute_s": 0.7 * round_s,
                                  "collective_s": 0.1 * round_s,
                                  "transfer_s": 0.0,
                                  "host_gap_s": 0.2 * round_s}
            f.write(json.dumps(rec) + "\n")


class TestPerfGateCLI:
    def test_baseline_check_regress_refuse_cycle(self, tmp_path):
        pg = _load_perf_gate()
        good = str(tmp_path / "good.jsonl")
        slow = str(tmp_path / "slow.jsonl")
        baseline = str(tmp_path / "perf_baseline.json")
        _write_ledger(good, 0.050)
        _write_ledger(slow, 0.200)  # 4x: far outside any noise band

        assert pg.main(["--ledger", good,
                        "--write-baseline", baseline]) == 0
        assert os.path.exists(baseline)
        base = gate.load_baseline(baseline)
        assert base["schema"] == gate.BASELINE_SCHEMA
        assert "span:round_dispatch:ms" in base["metrics"]

        # same run gates green against its own baseline
        assert pg.main(["--ledger", good, "--baseline", baseline,
                        "--check"]) == 0
        # the synthetically slowed ledger fails
        assert pg.main(["--ledger", slow, "--baseline", baseline,
                        "--check"]) == 1
        # re-baselining over a regression is refused without --force
        assert pg.main(["--ledger", slow, "--baseline", baseline,
                        "--write-baseline", baseline]) == 1
        assert gate.load_baseline(baseline)["metrics"][
            "span:round_dispatch:ms"]["median"] == pytest.approx(50.0)
        # --force is the explicit trade-off escape hatch
        assert pg.main(["--ledger", slow, "--baseline", baseline,
                        "--write-baseline", baseline,
                        "--force"]) == 0
        assert gate.load_baseline(baseline)["metrics"][
            "span:round_dispatch:ms"]["median"] == pytest.approx(200.0)

    def test_empty_ledger_is_an_error(self, tmp_path):
        pg = _load_perf_gate()
        empty = str(tmp_path / "empty.jsonl")
        open(empty, "w").close()
        assert pg.main(["--ledger", empty, "--check"]) == 1

    def test_runs_dir_discovery(self, tmp_path):
        pg = _load_perf_gate()
        ledger = str(tmp_path / "run.jsonl")
        _write_ledger(ledger, 0.050)
        registry.write_manifest(str(tmp_path / "runs"), args=None,
                                ledger=ledger)
        baseline = str(tmp_path / "perf_baseline.json")
        assert pg.main(["--runs_dir", str(tmp_path / "runs"),
                        "--write-baseline", baseline]) == 0
        assert pg.main(["--runs_dir", str(tmp_path / "runs"),
                        "--baseline", baseline, "--check"]) == 0

    def test_runs_dir_without_manifests_errors(self, tmp_path):
        pg = _load_perf_gate()
        assert pg.main(["--runs_dir", str(tmp_path),
                        "--check"]) == 1


# --- run registry -----------------------------------------------------


class _Cfg:
    def __init__(self, **kw):
        self.__dict__.update(kw)


class TestRunRegistry:
    def test_manifest_round_trip(self, tmp_path):
        ledger = str(tmp_path / "a.jsonl")
        open(ledger, "w").close()
        args = _Cfg(mode="sketch", k=16, ledger=ledger,
                    do_profile=True)
        path = registry.write_manifest(
            str(tmp_path / "runs"), args=args, ledger=ledger,
            bench={"clients_per_s": {"value": 10.0}},
            mesh_shape={"data": 8}, extra={"trainer": "test"})
        manifests = registry.list_manifests(str(tmp_path / "runs"))
        assert [p for p, _ in manifests] == [path]
        rec = manifests[0][1]
        assert rec["kind"] == "run_manifest"
        assert rec["schema"] == registry.MANIFEST_SCHEMA
        assert rec["config_hash"] == registry.config_hash(args)
        assert rec["ledger"] == os.path.abspath(ledger)
        assert rec["trainer"] == "test"
        assert rec["mesh_shape"] == {"data": 8}
        hits = registry.latest_ledgers(str(tmp_path / "runs"))
        assert hits == [(path, rec, os.path.abspath(ledger))]

    def test_config_hash_ignores_observability_knobs(self):
        a = _Cfg(mode="sketch", k=16, ledger="x.jsonl",
                 do_profile=True, telemetry_console=True)
        b = _Cfg(mode="sketch", k=16, ledger="y.jsonl",
                 do_profile=False, telemetry_console=False)
        c = _Cfg(mode="sketch", k=32, ledger="x.jsonl",
                 do_profile=True, telemetry_console=True)
        assert registry.config_hash(a) == registry.config_hash(b)
        assert registry.config_hash(a) != registry.config_hash(c)

    def test_latest_ledgers_skips_deleted(self, tmp_path):
        runs = str(tmp_path / "runs")
        led1 = str(tmp_path / "old.jsonl")
        led2 = str(tmp_path / "gone.jsonl")
        open(led1, "w").close()
        open(led2, "w").close()
        registry.write_manifest(runs, args=_Cfg(x=1), ledger=led1)
        registry.write_manifest(runs, args=_Cfg(x=2), ledger=led2)
        os.remove(led2)
        hits = registry.latest_ledgers(runs, n=2)
        assert [h[2] for h in hits] == [os.path.abspath(led1)]

    def test_maybe_write_manifest_gates(self, tmp_path):
        # no ledger -> no manifest; --test smoke -> no manifest
        assert registry.maybe_write_manifest(
            _Cfg(ledger=""), runs_dir=str(tmp_path)) is None
        assert registry.maybe_write_manifest(
            _Cfg(ledger="x.jsonl", do_test=True),
            runs_dir=str(tmp_path)) is None
        assert registry.list_manifests(str(tmp_path)) == []


# --- step-time alarm --------------------------------------------------


class _AlarmCfg:
    on_divergence = "ledger-flag"
    alarm_residual_ratio = 10.0
    alarm_residual_rounds = 3
    alarm_recovery_error = 1.0
    alarm_step_time_ratio = 2.0
    alarm_step_time_window = 8


class TestStepTimeAlarm:
    def test_warmup_then_fire_then_keep_firing(self):
        eng = AlarmEngine(_AlarmCfg())
        for r in range(AlarmEngine.STEP_TIME_WARMUP):
            assert eng.check_step_time(r, 0.1) == []
        # healthy round within ratio x median: no alarm
        assert eng.check_step_time(5, 0.15) == []
        fired = eng.check_step_time(6, 0.5)
        assert fired and fired[0]["rule"] == "step_time_regression"
        assert fired[0]["threshold"] == pytest.approx(0.2)
        assert fired[0]["rolling_median"] == pytest.approx(0.1)
        # firing samples are NOT folded into the window, so a
        # sustained regression keeps firing instead of becoming the
        # new normal
        assert eng.check_step_time(7, 0.5)
        assert eng.check_step_time(8, 0.5)

    def test_flags_ledger_record(self):
        tel = Telemetry(sinks=[_ListSink()])
        tel.begin_round(0)
        eng = AlarmEngine(_AlarmCfg(), telemetry=tel)
        for r in range(AlarmEngine.STEP_TIME_WARMUP):
            eng.check_step_time(r, 0.1)
        eng.check_step_time(0, 0.9)
        rec = tel._records[0]
        assert rec["alarms"] and \
            rec["alarms"][0]["rule"] == "step_time_regression"

    def test_abort_action_raises(self):
        class Abort(_AlarmCfg):
            on_divergence = "abort"
        eng = AlarmEngine(Abort())
        for r in range(AlarmEngine.STEP_TIME_WARMUP):
            eng.check_step_time(r, 0.1)
        with pytest.raises(DivergenceAbort):
            eng.check_step_time(5, 0.9)

    def test_disarmed_when_ratio_zero(self):
        class Off(_AlarmCfg):
            alarm_step_time_ratio = 0.0
        eng = AlarmEngine(Off())
        for r in range(20):
            assert eng.check_step_time(r, 100.0) == []

    def test_build_alarm_engine_arms_on_step_time_alone(self):
        from commefficient_tpu.telemetry.alarms import \
            build_alarm_engine

        class NoProbes(_AlarmCfg):
            probe_period = 0
        assert build_alarm_engine(NoProbes()) is not None

        class Nothing(_AlarmCfg):
            probe_period = 0
            alarm_step_time_ratio = 0.0
        assert build_alarm_engine(Nothing()) is None


# --- stale waivers ----------------------------------------------------


class TestStaleWaivers:
    def test_live_orphan_and_unknown(self, tmp_path):
        from commefficient_tpu.analysis import lint
        (tmp_path / "a.py").write_text(
            "# audit: allow(mutable-default-arg)\n"   # live: covers L2
            "def f(a=[]):\n"
            "    return a\n"
            "\n"
            "# audit: allow(mutable-default-arg)\n"   # orphan
            "x = 1\n"
            "\n"
            "# audit: allow(no-such-rule)\n"          # typo'd rule
            "y = 2\n")
        violations = lint.run_lint(root=tmp_path)
        assert [v.waived for v in violations] == [True]
        stale = lint.stale_waivers(root=tmp_path,
                                   violations=violations)
        assert len(stale) == 2
        assert any("a.py:5" in s and "stale waiver" in s
                   for s in stale)
        assert any("a.py:8" in s and "unknown rule" in s
                   for s in stale)

    def test_repo_has_no_stale_waivers(self):
        from commefficient_tpu.analysis import lint
        assert lint.stale_waivers() == []

    def test_stale_waivers_are_hard_failures(self):
        from commefficient_tpu.analysis import baseline as base_mod
        from commefficient_tpu.analysis import lint
        summary = lint.lint_report(
            [], stale=["a.py:5: stale waiver allow(host-sync) — ..."])
        report = base_mod.build_report(
            {"programs": {}, "failures": []}, summary)
        assert any("stale waiver" in f for f in report["failures"])
        # ...and can never be baselined in: the pinned subset keeps
        # only the waived list
        base = base_mod.to_baseline(
            {"programs": {}, "jax_version": "x", "device_count": 8,
             "lint": summary, "failures": []})
        assert "stale_waivers" not in base["lint"]


# --- telemetry emission hold ------------------------------------------


class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(rec)

    def close(self):
        pass


class TestEmissionHold:
    def test_hold_buffers_then_merges_device_time(self):
        sink = _ListSink()
        tel = Telemetry(sinks=[sink])
        tel.hold_emission(True)
        for r in range(2):
            tel.begin_round(r)
            tel.set_round_bytes(r, 10.0, 20.0)
        tel.begin_round(2)        # closes round 1
        tel.set_round_bytes(2, 10.0, 20.0)
        assert sink.records == []  # everything buffered by the hold
        buckets = {"window_s": 1.0, "busy_s": 0.5, "compute_s": 0.4,
                   "collective_s": 0.1, "transfer_s": 0.0,
                   "host_gap_s": 0.5}
        tel.merge_round_device_time(0, buckets)
        tel.merge_round_device_time(1, buckets)
        tel.hold_emission(False)
        emitted = [r["round"] for r in sink.records
                   if r["kind"] == "round"]
        assert emitted == [0, 1]   # round order preserved
        assert all(r["device_time"] == buckets for r in sink.records
                   if r["kind"] == "round")
        tel.close()
        assert [r["round"] for r in sink.records
                if r["kind"] == "round"] == [0, 1, 2]

    def test_roofline_utilization_derived_from_cost_model(self):
        sink = _ListSink()
        tel = Telemetry(sinks=[sink])
        tel.expected_round_s = 0.25
        tel.begin_round(0)
        tel.merge_round_device_time(0, {"window_s": 1.0,
                                        "busy_s": 0.5})
        rec = tel._records[0]
        assert rec["device_time"]["roofline_utilization"] == \
            pytest.approx(0.5)

    def test_close_overrides_hold(self):
        sink = _ListSink()
        tel = Telemetry(sinks=[sink])
        tel.hold_emission(True)
        tel.begin_round(0)
        tel.close()
        assert [r["round"] for r in sink.records
                if r["kind"] == "round"] == [0]


# --- end-to-end: --profile on the CPU mesh ----------------------------


class TestProfileIntegration:
    def test_profiled_run_attributes_device_time(self, tmp_path):
        """The acceptance criterion: a ``--profile``'d CPU run
        produces a schema-v3 ledger whose per-round device-time
        buckets sum to the round window exactly, and whose windows
        together cover the in-trace wall time to within 10% (+ a
        small absolute epsilon for trace start/stop edges)."""
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        from commefficient_tpu.config import Config
        from commefficient_tpu.runtime import FedModel, FedOptimizer
        from commefficient_tpu.telemetry import clock
        from commefficient_tpu.telemetry.profiler import trace_window

        class Lin(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(64, use_bias=False)(x)

        module = Lin()
        params = module.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 32)))["params"]
        ledger = str(tmp_path / "ledger.jsonl")
        args = Config(mode="sketch", error_type="virtual",
                      local_momentum=0.0, virtual_momentum=0.9,
                      num_workers=2, local_batch_size=4,
                      num_clients=4, dataset_name="CIFAR10", seed=0,
                      k=16, num_rows=3, num_cols=256)
        args.ledger = ledger
        args.do_profile = True

        def loss(p, batch, cfg):
            pred = module.apply({"params": p}, batch["x"])
            n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
            return (jnp.sum(pred ** 2 * batch["mask"][..., None])
                    / n, ())

        model = FedModel(module, params, loss, args,
                         padded_batch_size=4)
        opt = FedOptimizer([{"lr": 0.1}], args)
        rng = np.random.RandomState(0)

        def mk(r):
            return {"x": rng.randn(2, 4, 32).astype(np.float32),
                    "y": rng.randn(2, 4).astype(np.float32),
                    "mask": np.ones((2, 4), np.float32),
                    "client_ids": np.array([r % 4, (r + 1) % 4],
                                           np.int32)}

        # round 0 outside the window carries compile/warmup
        model(mk(0))
        opt.step()
        logdir = str(tmp_path / "trace")
        with trace_window(logdir, telemetry=model.telemetry):
            t0 = clock.tick()
            for r in range(1, 5):
                model(mk(r))
                opt.step()
            jax.block_until_ready(model.ps_weights)
            loop_wall = clock.tick() - t0
        model.finalize()

        recs = [json.loads(line) for line in open(ledger)]
        assert all(not validate_record(r) for r in recs)
        rounds = [r for r in recs if r["kind"] == "round"]
        assert len(rounds) == 5
        assert all(r["schema"] == 3 for r in rounds)

        traced = [r for r in rounds if r.get("device_time")]
        assert [r["round"] for r in traced] == [1, 2, 3, 4]
        total_window = 0.0
        for r in traced:
            dt = r["device_time"]
            parts = (dt["compute_s"] + dt["collective_s"]
                     + dt["transfer_s"] + dt["host_gap_s"])
            assert abs(parts - dt["window_s"]) < 1e-5
            assert dt["busy_s"] > 0
            # the --profile cost model registered expected_round_s,
            # so every traced round carries a utilization
            assert 0 < dt["roofline_utilization"] <= 1.0
            total_window += dt["window_s"]
        # windows tile the in-trace loop: round 1's window absorbs
        # the one-off cost-model lowering, the last window extends to
        # the trace stop — 10% relative + 50ms absolute covers both
        assert abs(total_window - loop_wall) <= \
            0.1 * loop_wall + 0.05

        cost_meta = [r for r in recs if r["kind"] == "meta"
                     and r.get("cost_model")]
        assert len(cost_meta) == 1
        cm = cost_meta[0]["cost_model"]
        assert cm["expected_round_s"] > 0
        assert cm["total_flops"] > 0

        trace_meta = [r for r in recs if r["kind"] == "meta"
                      and r.get("trace_rounds")]
        assert len(trace_meta) == 1
        assert trace_meta[0]["trace_rounds"] == 4
        assert trace_meta[0]["trace_busy_s"] > 0

        # the ledger gates end-to-end through the perf-gate CLI
        pg = _load_perf_gate()
        baseline = str(tmp_path / "perf_baseline.json")
        assert pg.main(["--ledger", ledger,
                        "--write-baseline", baseline]) == 0
        assert pg.main(["--ledger", ledger, "--baseline", baseline,
                        "--check"]) == 0
