"""Torch-format CV export (models/torch_export.py): reference key
names, correct tensor layouts, lossless round-trip. The image has no
torchvision, so layout correctness is proven op-by-op against torch
functional ops and structurally by schema + round-trip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from commefficient_tpu.models import get_model
from commefficient_tpu.models.torch_export import (build_name_map,
                                                   cv_load_state_dict,
                                                   cv_state_dict,
                                                   supports_torch_export)


def _init(module, shape=(1, 32, 32, 3)):
    return module.init(jax.random.PRNGKey(0),
                       jnp.zeros(shape))["params"]


class TestLayouts:
    """Exported tensors compute the same op in torch."""

    def test_conv_kernel_layout(self):
        import flax.linen as nn
        conv = nn.Conv(4, (3, 3), padding=1, use_bias=False)
        x = np.random.RandomState(0).randn(2, 8, 8, 3).astype(
            np.float32)
        params = conv.init(jax.random.PRNGKey(1),
                           jnp.asarray(x))["params"]
        want = np.asarray(conv.apply({"params": params},
                                     jnp.asarray(x)))
        w = np.transpose(np.asarray(params["kernel"]), (3, 2, 0, 1))
        got = torch.nn.functional.conv2d(
            torch.from_numpy(np.transpose(x, (0, 3, 1, 2))),
            torch.from_numpy(w), padding=1).numpy()
        np.testing.assert_allclose(np.transpose(got, (0, 2, 3, 1)),
                                   want, rtol=1e-4, atol=1e-5)

    def test_dense_kernel_layout(self):
        import flax.linen as nn
        dense = nn.Dense(5)
        x = np.random.RandomState(0).randn(3, 7).astype(np.float32)
        params = dense.init(jax.random.PRNGKey(1),
                            jnp.asarray(x))["params"]
        want = np.asarray(dense.apply({"params": params},
                                      jnp.asarray(x)))
        got = torch.nn.functional.linear(
            torch.from_numpy(x),
            torch.from_numpy(np.asarray(params["kernel"]).T),
            torch.from_numpy(np.asarray(params["bias"]))).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_layernorm_affine_layout(self):
        """flax LN over (H, W, C) == torch LayerNorm((C, h, w)) on the
        channels-first activation (the reference resnets fork's LN
        sites, resnets.py:79-97)."""
        import flax.linen as nn
        ln = nn.LayerNorm(reduction_axes=(-3, -2, -1),
                          feature_axes=(-3, -2, -1))
        rng = np.random.RandomState(2)
        x = rng.randn(2, 4, 4, 3).astype(np.float32)
        params = ln.init(jax.random.PRNGKey(1),
                         jnp.asarray(x))["params"]
        # non-trivial affine
        params = {"scale": jnp.asarray(
                      rng.randn(4, 4, 3).astype(np.float32)),
                  "bias": jnp.asarray(
                      rng.randn(4, 4, 3).astype(np.float32))}
        want = np.asarray(ln.apply({"params": params},
                                   jnp.asarray(x)))
        tln = torch.nn.LayerNorm((3, 4, 4))
        with torch.no_grad():
            tln.weight.copy_(torch.from_numpy(np.transpose(
                np.asarray(params["scale"]), (2, 0, 1))))
            tln.bias.copy_(torch.from_numpy(np.transpose(
                np.asarray(params["bias"]), (2, 0, 1))))
            got = tln(torch.from_numpy(
                np.transpose(x, (0, 3, 1, 2)))).numpy()
        np.testing.assert_allclose(np.transpose(got, (0, 2, 3, 1)),
                                   want, rtol=1e-4, atol=1e-4)


class TestSchemas:
    """Exported key sets match the reference torch modules' names."""

    def test_resnet9_keys(self):
        module = get_model("ResNet9")(
            num_classes=10, channels={"prep": 2, "layer1": 2,
                                      "layer2": 2, "layer3": 2})
        sd = cv_state_dict(module, _init(module))
        want = {f"n.{m}.conv.weight" for m in
                ("prep", "layer1", "layer2", "layer3",
                 "res1.res1", "res1.res2", "res3.res1", "res3.res2")}
        want.add("n.linear.weight")
        assert set(sd) == want  # reference resnet9.py:74-124
        assert sd["n.prep.conv.weight"].shape == (2, 3, 3, 3)
        # head input = layer3 channels x 2x2 remaining spatial
        assert sd["n.linear.weight"].shape == (10, 8)

    def test_resnet9_batchnorm_keys_and_stats(self):
        module = get_model("ResNet9")(
            num_classes=10, do_batchnorm=True,
            channels={"prep": 2, "layer1": 2, "layer2": 2,
                      "layer3": 2})
        variables = module.init(jax.random.PRNGKey(0),
                                jnp.zeros((1, 32, 32, 3)))
        params, stats = variables["params"], variables["batch_stats"]
        sd = cv_state_dict(module, params, stats)
        for site in ("n.prep.bn", "n.res1.res1.bn"):
            for leaf in ("weight", "bias", "running_mean",
                         "running_var", "num_batches_tracked"):
                assert f"{site}.{leaf}" in sd, site + "." + leaf
        assert sd["n.prep.bn.running_var"].shape == (2,)
        assert sd["n.prep.bn.num_batches_tracked"].dtype == np.int64

    def test_fixup_resnet9_keys(self):
        module = get_model("FixupResNet9")(
            channels={"prep": 2, "layer1": 2, "layer2": 2,
                      "layer3": 2})
        sd = cv_state_dict(module, _init(module))
        # reference fixup_resnet9.py:33-56 naming
        for k in ("conv1.weight", "bias1a", "bias1b", "scale",
                  "bias2", "linear.weight", "linear.bias",
                  "layer1.conv.weight", "layer1.bias1a",
                  "layer1.blocks.0.conv1.weight",
                  "layer1.blocks.0.bias2b",
                  "layer2.conv.weight", "layer3.blocks.0.scale"):
            assert k in sd, k
        # layer2 has 0 residual blocks (reference plan 1/0/1)
        assert not any(k.startswith("layer2.blocks") for k in sd)

    def test_fixup_resnet50_keys(self):
        module = get_model("FixupResNet50")(num_classes=3,
                                            stage_sizes=(1, 1, 1, 1))
        sd = cv_state_dict(module, _init(module, (1, 64, 64, 3)))
        for k in ("conv1.weight", "bias1", "bias2", "fc.weight",
                  "fc.bias", "layer1.0.conv1.weight",
                  "layer1.0.conv3.weight", "layer1.0.downsample.weight",
                  "layer4.0.conv2.weight", "layer4.0.bias3b"):
            assert k in sd, k

    def test_resnet18_families_keys(self):
        m1 = get_model("ResNet18")(num_classes=10,
                                   num_blocks=(1, 1, 1, 1))
        # batch-stat BN (no tracked stats): identity running buffers
        # are synthesized so the artifact strict-loads in torch
        sd = cv_state_dict(m1, _init(m1))
        # reference fixup_resnet18.py:168-216: prep Sequential, flat
        # ``layers`` over all blocks, avg+max head -> classifier
        for k in ("prep.0.weight", "layers.0.conv1.weight",
                  "layers.0.bn1.weight", "layers.0.bn1.running_mean",
                  "layers.1.shortcut.0.weight", "classifier.weight",
                  "classifier.bias"):
            assert k in sd, k
        assert not any(k.startswith("layers.0.shortcut")
                       for k in sd)  # stride-1 same-width: no proj

        m2 = get_model("FixupResNet18")(num_classes=10,
                                        num_blocks=(1, 1, 1, 1))
        sd2 = cv_state_dict(m2, _init(m2))
        for k in ("prep.weight", "layers.0.conv1.weight",
                  "layers.0.add1a.bias", "layers.0.mul.scale",
                  "layers.1.shortcut.weight", "classifier.weight"):
            assert k in sd2, k

    def test_resnets_family_keys(self):
        from commefficient_tpu.models.resnets import (BasicBlock,
                                                      Bottleneck,
                                                      ResNet)
        m = ResNet(block=BasicBlock, layers=(1, 1, 1, 1),
                   num_classes=5, norm="batch")
        sd = cv_state_dict(m, _init(m, (1, 28, 28, 1)))
        # torchvision naming (the reference forked it, resnets.py)
        for k in ("conv1.weight", "bn1.weight", "bn1.running_mean",
                  "layer1.0.conv1.weight", "layer1.0.bn2.weight",
                  "layer2.0.downsample.0.weight",
                  "layer2.0.downsample.1.running_var", "fc.weight",
                  "fc.bias"):
            assert k in sd, k
        assert sd["conv1.weight"].shape == (64, 1, 7, 7)

        ml = ResNet(block=Bottleneck, layers=(1, 1, 1, 1),
                    num_classes=5, norm="layer")
        sd = cv_state_dict(ml, _init(ml, (1, 28, 28, 1)))
        for k in ("bn1.weight", "layer1.0.bn3.bias",
                  "layer1.0.downsample.1.weight"):
            assert k in sd, k
        assert not any("running" in k for k in sd)  # LN: no stats


class TestRoundTrip:
    """Export -> torch.save -> torch.load -> import into a different
    init == original forward. Proves the name map bijective and every
    layout transform self-inverse-consistent."""

    @pytest.mark.parametrize("name,kw,shape", [
        ("ResNet9", dict(channels={"prep": 2, "layer1": 2,
                                   "layer2": 2, "layer3": 2}), 32),
        ("FixupResNet9", dict(channels={"prep": 2, "layer1": 2,
                                        "layer2": 2, "layer3": 2}), 32),
        ("FixupResNet18", dict(num_blocks=(1, 1, 1, 1)), 32),
    ])
    def test_roundtrip_forward(self, tmp_path, name, kw, shape):
        module = get_model(name)(num_classes=10, **kw)
        x = jnp.asarray(np.random.RandomState(0).randn(
            2, shape, shape, 3).astype(np.float32))
        p_src = module.init(jax.random.PRNGKey(0), x)["params"]
        p_dst = module.init(jax.random.PRNGKey(7), x)["params"]
        want = np.asarray(module.apply({"params": p_src}, x))

        sd = cv_state_dict(module, p_src)
        path = tmp_path / "m.pt"
        torch.save({k: torch.from_numpy(np.array(v, copy=True))
                    for k, v in sd.items()}, str(path))
        loaded = {k: v.numpy()
                  for k, v in torch.load(str(path)).items()}
        p_back = cv_load_state_dict(module, p_dst, loaded)
        got = np.asarray(module.apply({"params": p_back}, x))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_roundtrip_with_batch_stats(self, tmp_path):
        module = get_model("ResNet9")(
            num_classes=10, do_batchnorm=True,
            channels={"prep": 2, "layer1": 2, "layer2": 2,
                      "layer3": 2})
        x = jnp.asarray(np.random.RandomState(3).randn(
            2, 32, 32, 3).astype(np.float32))
        v = module.init(jax.random.PRNGKey(0), x)
        p_src, s_src = v["params"], v["batch_stats"]
        # non-trivial running stats
        s_src = jax.tree_util.tree_map(
            lambda a: a + np.random.RandomState(5).rand(
                *a.shape).astype(np.float32), s_src)
        want = np.asarray(module.apply(
            {"params": p_src, "batch_stats": s_src}, x, train=False))

        sd = cv_state_dict(module, p_src, s_src)
        v2 = module.init(jax.random.PRNGKey(9), x)
        p_back, s_back = cv_load_state_dict(module, v2["params"], sd,
                                            v2["batch_stats"])
        got = np.asarray(module.apply(
            {"params": p_back, "batch_stats": s_back}, x,
            train=False))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_fed_model_save_pretrained_torch_format(tmp_path):
    """FedModel.save_pretrained(..., torch_format=True) writes the
    reference's artifact (state_dict.pt) next to the flax blob."""
    from commefficient_tpu.config import Config
    from commefficient_tpu.runtime import FedModel
    from commefficient_tpu.train.cv_train import make_compute_loss

    module = get_model("ResNet9")(
        num_classes=10, channels={"prep": 1, "layer1": 1,
                                  "layer2": 1, "layer3": 1})
    params = _init(module)
    args = Config(mode="uncompressed", error_type="none",
                  local_momentum=0.0, num_workers=1,
                  local_batch_size=2, num_clients=2,
                  dataset_name="CIFAR10", k=10, seed=0)
    model = FedModel(module, params, make_compute_loss(module), args)
    model.save_pretrained(str(tmp_path), torch_format=True)
    sd = torch.load(str(tmp_path / "state_dict.pt"))
    assert "n.prep.conv.weight" in sd
    np.testing.assert_allclose(
        sd["n.linear.weight"].numpy(),
        np.asarray(model.params()["Dense_0"]["kernel"]).T)


def test_supports_torch_export():
    assert supports_torch_export(get_model("ResNet9")())
    assert not supports_torch_export(object())
