"""Topology-changing checkpoint restore (elastic resume).

The elasticity contract (runtime/checkpoint.py): a run checkpointed
on a ``CxM`` mesh restores onto a DIFFERENT ``C'xM'`` mesh with
bit-identical state — sketches are linear objects, so resharding is
pure placement migration — and the continued trajectory matches an
unresized oracle over the same seeded schedule (allclose; XLA
reduction order across placements injects ~1e-6 float noise, the
same bound tests/test_mesh2d.py pins).

Also covered here: asyncfed backlog survival across a resize, the
crafted multi-process clientstore shard merge, the sync-restore-of-
pending-async refusal, and the perf gate's refusal to resolve a
baseline pin for a ledger that spans topologies.
"""

import json
import os
import sys

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
import jax  # noqa: E402

from commefficient_tpu.config import Config  # noqa: E402
from commefficient_tpu.runtime.checkpoint import (  # noqa: E402
    load_checkpoint, save_checkpoint)
from commefficient_tpu.runtime.fed_model import (  # noqa: E402
    FedModel, FedOptimizer)

W, B, D, NC = 4, 2, 256, 8

SKETCH = dict(mode="sketch", error_type="virtual", local_momentum=0.0,
              virtual_momentum=0.9, k=16, num_rows=3, num_cols=128)
TOPK = dict(mode="local_topk", error_type="local", local_momentum=0.9,
            virtual_momentum=0.0, k=16)
FEDAVG = dict(mode="fedavg", error_type="none", local_momentum=0.0,
              local_batch_size=-1)


def _loss(params, batch, cfg):
    pred = batch["x"] @ params["w"]
    n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
    l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
    return l, (l * 0.0 + 1.0,)


def _mk_cfg(mode_kw, mesh="", async_k=0, **kw):
    base = dict(num_workers=W, local_batch_size=B, seed=5,
                num_clients=NC, mesh=mesh, async_buffer_size=async_k)
    base.update(mode_kw)
    base.update(kw)
    return Config(**base)


def _build(cfg):
    model = FedModel(None, {"w": jnp.zeros((D,), jnp.float32)}, _loss,
                     cfg, padded_batch_size=B)
    opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
    return model, opt


def _batch(r):
    rng = np.random.RandomState(1000 + r)
    return {"client_ids": rng.choice(NC, W, replace=False)
            .astype(np.int32),
            "x": jnp.asarray(rng.randn(W, B, D), jnp.float32),
            "y": jnp.asarray(rng.randn(W, B), jnp.float32),
            "mask": jnp.ones((W, B), jnp.float32)}


def _run(model, opt, r0, r1):
    for r in range(r0, r1):
        model(_batch(r))
        opt.step()


def _archive_arrays(path):
    with np.load(path, allow_pickle=False) as z:
        return {k: np.asarray(z[k]) for k in z.files if k != "meta"}, \
            json.loads(str(z["meta"]))


def _assert_archives_bit_equal(path_a, path_b):
    arrs_a, _ = _archive_arrays(path_a)
    arrs_b, _ = _archive_arrays(path_b)
    assert set(arrs_a) == set(arrs_b)
    for k in sorted(arrs_a):
        a, b = arrs_a[k], arrs_b[k]
        assert a.dtype == b.dtype, f"{k}: {a.dtype} != {b.dtype}"
        assert np.array_equal(a, b), f"{k} not bit-equal after resize"


# -- restored state is bit-exact across the mesh change -----------------


@pytest.mark.parametrize("mode_kw,mesh_a,mesh_b", [
    (SKETCH, "2x1", "1x2"),
    (TOPK, "2x1", "1x1"),
    (FEDAVG, "2x1", "1x1"),
], ids=["sketch", "local_topk", "fedavg"])
def test_resize_restores_state_bit_exact(tmp_path, mode_kw, mesh_a,
                                         mesh_b):
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    ck_a = str(tmp_path / "a.npz")
    ck_b = str(tmp_path / "b.npz")
    model, opt = _build(_mk_cfg(mode_kw, mesh=mesh_a))
    _run(model, opt, 0, 3)
    save_checkpoint(ck_a, model, opt)
    model.finalize()

    model2, opt2 = _build(_mk_cfg(mode_kw, mesh=mesh_b))
    load_checkpoint(ck_a, model2, opt2)
    assert int(model2.round_index) == 3
    save_checkpoint(ck_b, model2, opt2)
    model2.finalize()

    _assert_archives_bit_equal(ck_a, ck_b)
    _, meta_b = _archive_arrays(ck_b)
    # the resized save extends the lineage: old topology + new one
    segs = meta_b.get("segments") or []
    assert len(segs) >= 2
    assert segs[-1]["mesh_shape"] != segs[0]["mesh_shape"] or \
        mesh_a == mesh_b


@pytest.mark.parametrize("mode_kw,mesh_a,mesh_b", [
    (SKETCH, "2x1", "1x2"),
    (TOPK, "2x1", "1x1"),
], ids=["sketch", "local_topk"])
def test_resized_trajectory_matches_unresized_oracle(tmp_path, mode_kw,
                                                     mesh_a, mesh_b):
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    ck = str(tmp_path / "ck.npz")
    model, opt = _build(_mk_cfg(mode_kw, mesh=mesh_a))
    _run(model, opt, 0, 3)
    save_checkpoint(ck, model, opt)
    model.finalize()

    # oracle: same topology resume, same seeded schedule
    om, oo = _build(_mk_cfg(mode_kw, mesh=mesh_a))
    load_checkpoint(ck, om, oo)
    _run(om, oo, 3, 6)
    ps_oracle = np.asarray(jax.device_get(om.ps_weights))
    om.finalize()

    rm, ro = _build(_mk_cfg(mode_kw, mesh=mesh_b))
    load_checkpoint(ck, rm, ro)
    _run(rm, ro, 3, 6)
    ps_resized = np.asarray(jax.device_get(rm.ps_weights))
    rm.finalize()

    # cross-placement XLA reduction order injects ~1e-6 noise (same
    # bound as tests/test_mesh2d.py); state itself is bit-exact above
    np.testing.assert_allclose(ps_resized, ps_oracle, rtol=0,
                               atol=1e-4)


# -- asyncfed backlog survives the resize -------------------------------


def _lag(r, n):
    # pure function of (round, cohort size): the schedule replays
    # identically on both sides of the resume with no hidden RNG
    return (np.arange(n) + r) % 3


def test_async_backlog_survives_resize(tmp_path):
    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    ck_a = str(tmp_path / "a.npz")
    ck_b = str(tmp_path / "b.npz")
    model, opt = _build(_mk_cfg(SKETCH, mesh="2x1", async_k=2))
    model.attach_arrival_process(_lag)
    _run(model, opt, 0, 3)
    save_checkpoint(ck_a, model, opt)
    ps_mid = np.asarray(jax.device_get(model.ps_weights))
    model.finalize()

    _, meta = _archive_arrays(ck_a)
    assert int(meta["asyncfed"]["pending"]) > 0, \
        "drill needs in-flight arrivals at the save point"

    model2, opt2 = _build(_mk_cfg(SKETCH, mesh="1x2", async_k=2))
    model2.attach_arrival_process(_lag)
    load_checkpoint(ck_a, model2, opt2)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(model2.ps_weights)), ps_mid)
    save_checkpoint(ck_b, model2, opt2)
    _assert_archives_bit_equal(ck_a, ck_b)

    # the rebuilt heap folds the same backlog: continue and compare
    # against an unresized oracle resumed from the same checkpoint
    om, oo = _build(_mk_cfg(SKETCH, mesh="2x1", async_k=2))
    om.attach_arrival_process(_lag)
    load_checkpoint(ck_a, om, oo)
    _run(om, oo, 3, 6)
    ps_oracle = np.asarray(jax.device_get(om.ps_weights))
    om.finalize()

    _run(model2, opt2, 3, 6)
    ps_resized = np.asarray(jax.device_get(model2.ps_weights))
    model2.finalize()
    np.testing.assert_allclose(ps_resized, ps_oracle, rtol=0,
                               atol=1e-4)


def test_sync_restore_of_pending_async_refuses(tmp_path):
    ck = str(tmp_path / "ck.npz")
    model, opt = _build(_mk_cfg(SKETCH, async_k=2))
    model.attach_arrival_process(_lag)
    _run(model, opt, 0, 3)
    save_checkpoint(ck, model, opt)
    model.finalize()
    _, meta = _archive_arrays(ck)
    assert int(meta["asyncfed"]["pending"]) > 0

    model2, opt2 = _build(_mk_cfg(SKETCH, async_k=0))
    with pytest.raises(ValueError, match="async_buffer_size"):
        load_checkpoint(ck, model2, opt2)
    model2.finalize()


# -- multi-process clientstore shard migration --------------------------


def test_multiprocess_store_shards_merge_on_restore(tmp_path):
    """A 2-process host-store checkpoint (main archive + side shard)
    restores onto a single process: the ownership split of the OLD
    topology merges, then re-splits under the new one. The 2-process
    layout is crafted by rewriting a real archive — in-process jax
    can't run two processes."""
    ck = str(tmp_path / "ck.npz")
    cfg = _mk_cfg(TOPK, clientstore="host")
    model, opt = _build(cfg)
    _run(model, opt, 0, 3)
    save_checkpoint(ck, model, opt)
    model.finalize()

    with np.load(ck, allow_pickle=False) as z:
        arrays = {k: np.asarray(z[k]) for k in z.files if k != "meta"}
        meta = json.loads(str(z["meta"]))
    ids = arrays["store:ids"]
    assert len(ids) >= 2, "need written rows to split across shards"
    fields = [k[len("store:"):] for k in arrays
              if k.startswith("store:") and k != "store:ids"
              and not k.startswith("store:init:")]
    # split the sparse rows into two contiguous ownership halves
    cut = NC // 2
    lo, hi = ids < cut, ids >= cut
    assert lo.any() and hi.any()
    side = {"ids": ids[hi]}
    for f in fields:
        side[f] = arrays["store:" + f][hi]
        arrays["store:" + f] = arrays["store:" + f][lo]
    for k in list(arrays):
        if k.startswith("store:init:"):
            side[k[len("store:"):]] = arrays[k]
    arrays["store:ids"] = ids[lo]
    meta["clientstore"]["processes"] = 2
    np.savez_compressed(ck, meta=json.dumps(meta), **arrays)
    np.savez_compressed(f"{ck}.shard1.npz", **side)

    model2, opt2 = _build(_mk_cfg(TOPK, clientstore="host"))
    load_checkpoint(ck, model2, opt2)
    # every pre-craft row survives the merge bit-exactly: gather in
    # shard-concatenation order and compare against the split halves
    merged_ids = np.concatenate([ids[lo], ids[hi]])
    got, _ = model2.client_store.gather(merged_ids)
    for f in fields:
        want = np.concatenate([arrays["store:" + f], side[f]])
        np.testing.assert_array_equal(got[f], want)
    # and the next save re-splits under the NEW (single-process)
    # topology: one shard holding the full id set
    ck2 = str(tmp_path / "ck2.npz")
    save_checkpoint(ck2, model2, opt2)
    with np.load(ck2, allow_pickle=False) as z2:
        meta2 = json.loads(str(z2["meta"]))
        ids2 = np.asarray(z2["store:ids"])
    assert int(meta2["clientstore"]["processes"]) == 1
    np.testing.assert_array_equal(np.sort(ids2), np.sort(ids))
    assert not os.path.exists(f"{ck2}.shard1.npz")
    model2.finalize()


# -- perf gate refuses a cross-topology ledger --------------------------


def _round_rec(r):
    return {"schema": 1, "kind": "round", "ts": 1000.0 + r, "round": r,
            "spans": {"round": 0.01 + 0.001 * r}, "counters": {},
            "uplink_bytes": None, "downlink_bytes": None,
            "host_rss_peak_bytes": None, "hbm_peak_bytes": None}


def _write_runs_dir(tmp_path, segments):
    runs = tmp_path / "runs"
    (runs / "manifests").mkdir(parents=True)
    ledger = runs / "led.jsonl"
    with open(ledger, "w") as f:
        for r in range(4):
            f.write(json.dumps(_round_rec(r)) + "\n")
    manifest = {
        "schema": 1, "kind": "run_manifest", "ts": 1, "git_sha": "",
        "config_hash": "cafe" * 10, "config": {}, "argv": [],
        "ledger": str(ledger), "bench": {}, "mesh_shape": None,
        "device_count": 8, "process_count": 1,
        "topology_segments": segments,
    }
    with open(runs / "manifests" / "run_1_cafecafe.json", "w") as f:
        json.dump(manifest, f)
    return str(runs)


def test_perf_gate_refuses_cross_topology_ledger(tmp_path, capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import perf_gate
    segs = [
        {"device_count": 8, "process_count": 2,
         "mesh_shape": {"clients": 4, "model": 2}, "round_index": 3},
        {"device_count": 4, "process_count": 1,
         "mesh_shape": {"clients": 2, "model": 2}, "round_index": 6},
    ]
    runs = _write_runs_dir(tmp_path, segs)
    rc = perf_gate.main(["--runs_dir", runs, "--check",
                         "--baseline", str(tmp_path / "missing.json")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REFUSED" in out
    assert "2 segments" in out
    # the refusal blocks re-baselining too: a mixed ledger must never
    # become anyone's pin
    rc = perf_gate.main(["--runs_dir", runs, "--write-baseline",
                         str(tmp_path / "new.json")])
    assert rc == 1
    assert not os.path.exists(tmp_path / "new.json")


def test_perf_gate_accepts_unresized_resume(tmp_path, capsys):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    import perf_gate
    # resumed WITHOUT a topology change: same topology in every
    # segment — this is one comparable run, the gate pins it normally
    segs = [
        {"device_count": 8, "process_count": 1,
         "mesh_shape": {"clients": 8, "model": 1}, "round_index": 3},
        {"device_count": 8, "process_count": 1,
         "mesh_shape": {"clients": 8, "model": 1}, "round_index": 6},
    ]
    runs = _write_runs_dir(tmp_path, segs)
    rc = perf_gate.main(["--runs_dir", runs, "--write-baseline",
                         str(tmp_path / "base.json")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "REFUSED" not in out
    assert os.path.exists(tmp_path / "base.json")


def test_run_topology_changed_semantics():
    from commefficient_tpu.telemetry import registry
    assert not registry.run_topology_changed({})
    one = {"topology_segments": [
        {"device_count": 8, "process_count": 1,
         "mesh_shape": {"clients": 8, "model": 1}}]}
    assert not registry.run_topology_changed(one)
    same = {"topology_segments": one["topology_segments"] * 2}
    assert not registry.run_topology_changed(same)
    changed = {"topology_segments": [
        {"device_count": 8, "process_count": 1,
         "mesh_shape": {"clients": 8, "model": 1}},
        {"device_count": 4, "process_count": 1,
         "mesh_shape": {"clients": 4, "model": 1}}]}
    assert registry.run_topology_changed(changed)
