"""Pallas sketch kernels vs the XLA rotation-sketch path.

The contract is hash-identity: identical rotation/sign streams, so
Pallas- and XLA-sketched tables may be psum-mixed. Chunk summation
order differs between the two (sequential grid accumulation vs XLA's
tree reduce), so sketch tables match to ULP-level tolerance; recovery
from a given table is a pure permutation + median and matches
bit-for-bit. On CPU the kernels run in interpreter mode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.ops.sketch import CountSketch
from commefficient_tpu.ops.sketch_pallas import supported

GEOMS = [
    # (d, c, r) — c lane-aligned (multiple of 128), table VMEM-sized
    (5000, 1024, 3),
    (300, 128, 5),      # d > padded? no: m=3 chunks of 128
    (4096, 4096, 1),    # single chunk, single row
    (70000, 2048, 4),   # even r -> median averages two middles
]


def _pair(d, c, r):
    xla = CountSketch(d=d, c=c, r=r, seed=7, backend="xla")
    pal = CountSketch(d=d, c=c, r=r, seed=7, backend="pallas_interpret")
    return xla, pal


@pytest.mark.parametrize("d,c,r", GEOMS)
def test_sketch_table_matches(d, c, r):
    assert supported(d, c, r)
    xla, pal = _pair(d, c, r)
    v = jnp.asarray(np.random.RandomState(0).randn(d).astype(np.float32))
    tx, tp = np.asarray(xla.sketch(v)), np.asarray(pal.sketch(v))
    # same hash streams; only chunk-sum order differs (ULP-level)
    np.testing.assert_allclose(tx, tp, rtol=1e-6, atol=1e-5)


@pytest.mark.parametrize("d,c,r", GEOMS)
def test_estimates_bit_exact(d, c, r):
    xla, pal = _pair(d, c, r)
    rng = np.random.RandomState(1)
    table = jnp.asarray(rng.randn(r, c).astype(np.float32))
    np.testing.assert_array_equal(np.asarray(xla.estimates(table)),
                                  np.asarray(pal.estimates(table)))


def test_unsketch_from_shared_table_bit_exact():
    d, c, r, k = 5000, 1024, 3, 20
    xla, pal = _pair(d, c, r)
    rng = np.random.RandomState(2)
    v = np.zeros(d, np.float32)
    hh = rng.choice(d, k, replace=False)
    v[hh] = rng.randn(k).astype(np.float32) * 100
    v += rng.randn(d).astype(np.float32) * 0.01
    table = xla.sketch(jnp.asarray(v))  # one table, both recoveries
    out_x = xla.unsketch(table, k)
    out_p = pal.unsketch(table, k)
    np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_p))
    # and the heavy hitters were actually recovered
    recovered = set(np.nonzero(np.asarray(out_p))[0])
    assert len(recovered & set(hh.tolist())) >= int(0.9 * k)


def test_unsupported_geometry_falls_back():
    # the reference default c=500000 is not lane-aligned -> XLA path
    assert not supported(6_500_000, 500_000, 5)
    cs = CountSketch(d=1000, c=500, r=3, backend="auto")
    assert cs._resolve_backend() == "xla"  # c % 128 != 0


def test_pallas_linearity():
    d, c, r = 5000, 1024, 3
    _, pal = _pair(d, c, r)
    rng = np.random.RandomState(3)
    a = jnp.asarray(rng.randn(d).astype(np.float32))
    b = jnp.asarray(rng.randn(d).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(pal.sketch(a) + pal.sketch(b)),
        np.asarray(pal.sketch(a + b)), rtol=1e-5, atol=1e-5)
