"""Pallas sketch kernels vs the XLA rotation-sketch path.

The contract is hash-identity: identical rotation/sign streams, so
Pallas- and XLA-sketched tables may be psum-mixed. Chunk summation
order differs between the two (sequential grid accumulation vs XLA's
tree reduce), so sketch tables match to ULP-level tolerance; recovery
from a given table is a pure permutation + median and matches
bit-for-bit. On CPU the kernels run in interpreter mode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.ops.sketch import CountSketch
from commefficient_tpu.ops.sketch_pallas import supported

GEOMS = [
    # (d, c, r) — c lane-aligned (multiple of 128), table VMEM-sized
    (5000, 1024, 3),
    (300, 128, 5),      # d > padded? no: m=3 chunks of 128
    (4096, 4096, 1),    # single chunk, single row
    (70000, 2048, 4),   # even r -> median averages two middles
]


def _pair(d, c, r):
    xla = CountSketch(d=d, c=c, r=r, seed=7, backend="xla")
    pal = CountSketch(d=d, c=c, r=r, seed=7, backend="pallas_interpret")
    return xla, pal


@pytest.mark.parametrize("d,c,r", GEOMS)
def test_sketch_table_matches(d, c, r):
    assert supported(d, c, r)
    xla, pal = _pair(d, c, r)
    v = jnp.asarray(np.random.RandomState(0).randn(d).astype(np.float32))
    tx, tp = np.asarray(xla.sketch(v)), np.asarray(pal.sketch(v))
    # same hash streams; only chunk-sum order differs (ULP-level)
    np.testing.assert_allclose(tx, tp, rtol=1e-6, atol=1e-5)


@pytest.mark.parametrize("d,c,r", GEOMS)
def test_estimates_bit_exact(d, c, r):
    xla, pal = _pair(d, c, r)
    rng = np.random.RandomState(1)
    table = jnp.asarray(rng.randn(r, c).astype(np.float32))
    np.testing.assert_array_equal(np.asarray(xla.estimates(table)),
                                  np.asarray(pal.estimates(table)))


def test_unsketch_from_shared_table_bit_exact():
    d, c, r, k = 5000, 1024, 3, 20
    xla, pal = _pair(d, c, r)
    rng = np.random.RandomState(2)
    v = np.zeros(d, np.float32)
    hh = rng.choice(d, k, replace=False)
    v[hh] = rng.randn(k).astype(np.float32) * 100
    v += rng.randn(d).astype(np.float32) * 0.01
    table = xla.sketch(jnp.asarray(v))  # one table, both recoveries
    out_x = xla.unsketch(table, k)
    out_p = pal.unsketch(table, k)
    np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_p))
    # and the heavy hitters were actually recovered
    recovered = set(np.nonzero(np.asarray(out_p))[0])
    assert len(recovered & set(hh.tolist())) >= int(0.9 * k)


def test_unsupported_geometry_falls_back():
    # the reference default c=500000 is not lane-aligned -> XLA path
    assert not supported(6_500_000, 500_000, 5)
    cs = CountSketch(d=1000, c=500, r=3, backend="auto")
    assert cs._resolve_backend() == "xla"  # c % 128 != 0


def test_pallas_linearity():
    d, c, r = 5000, 1024, 3
    _, pal = _pair(d, c, r)
    rng = np.random.RandomState(3)
    a = jnp.asarray(rng.randn(d).astype(np.float32))
    b = jnp.asarray(rng.randn(d).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(pal.sketch(a) + pal.sketch(b)),
        np.asarray(pal.sketch(a + b)), rtol=1e-5, atol=1e-5)


class TestSublaneRotations:
    """rot_lanes > 0: quantized rotations, single-sublane-roll kernel
    fast path. Backend equivalence must hold exactly as for the
    full-granularity operator."""

    def test_rotations_are_quantized(self):
        cs = CountSketch(d=5000, c=1024, r=3, seed=7, rot_lanes=128)
        rot = cs._rotations()
        assert (rot % 128 == 0).all()
        assert rot.max() < 1024

    def test_degenerate_granularity_rejected(self):
        import pytest as _pytest
        cs = CountSketch(d=5000, c=1024, r=3, seed=7, rot_lanes=1024)
        with _pytest.raises(AssertionError):
            cs._rotations()

    def test_sketch_backends_match(self):
        d, c, r = 5000, 1024, 3
        xla = CountSketch(d=d, c=c, r=r, seed=7, backend="xla",
                          rot_lanes=128)
        pal = CountSketch(d=d, c=c, r=r, seed=7,
                          backend="pallas_interpret", rot_lanes=128)
        v = jnp.asarray(np.random.RandomState(3).randn(d)
                        .astype(np.float32))
        np.testing.assert_allclose(np.asarray(xla.sketch(v)),
                                   np.asarray(pal.sketch(v)),
                                   rtol=1e-6, atol=1e-5)

    def test_estimates_backends_bit_exact(self):
        d, c, r = 5000, 1024, 3
        xla = CountSketch(d=d, c=c, r=r, seed=7, backend="xla",
                          rot_lanes=128)
        pal = CountSketch(d=d, c=c, r=r, seed=7,
                          backend="pallas_interpret", rot_lanes=128)
        table = jnp.asarray(np.random.RandomState(4).randn(r, c)
                            .astype(np.float32))
        np.testing.assert_array_equal(np.asarray(xla.estimates(table)),
                                      np.asarray(pal.estimates(table)))

    def test_linearity_and_recovery_still_work(self):
        # c/rot_lanes = 512 — the flagship ratio (c=2^19, lanes 1024);
        # coarse ratios (say 8) measurably hurt recovery and are not
        # what the knob is for
        d, c, r, k = 200000, 65536, 5, 30
        cs = CountSketch(d=d, c=c, r=r, seed=9, backend="xla",
                         rot_lanes=128)
        rng = np.random.RandomState(5)
        v = np.zeros(d, np.float32)
        hh = rng.choice(d, k, replace=False)
        v[hh] = rng.randn(k).astype(np.float32) * 100
        a = jnp.asarray(v)
        b = jnp.asarray(rng.randn(d).astype(np.float32) * 0.01)
        np.testing.assert_allclose(
            np.asarray(cs.sketch(a) + cs.sketch(b)),
            np.asarray(cs.sketch(a + b)), rtol=2e-5, atol=2e-4)
        dense = cs.unsketch(cs.sketch(a), k)
        got = set(np.nonzero(np.asarray(dense))[0].tolist())
        assert len(got & set(hh.tolist())) >= int(0.9 * k)

    def test_sparse_resketch_matches_dense(self):
        # hashes() must agree with the quantized rotation stream
        d, c, r = 5000, 1024, 3
        cs = CountSketch(d=d, c=c, r=r, seed=11, backend="xla",
                         rot_lanes=128)
        rng = np.random.RandomState(6)
        idx = jnp.asarray(np.sort(rng.choice(d, 40, replace=False))
                          .astype(np.int32))
        vals = jnp.asarray(rng.randn(40).astype(np.float32))
        dense = jnp.zeros(d, jnp.float32).at[idx].set(vals)
        np.testing.assert_allclose(np.asarray(cs.sketch_sparse(idx, vals)),
                                   np.asarray(cs.sketch(dense)),
                                   rtol=1e-6, atol=1e-5)


class TestPackedSigns:
    """Packed-sign streaming (CountSketch.packed_signs) must be a pure
    perf lever: identical sign VALUES to in-kernel hashing, so tables
    and recoveries are bit-identical between the two kernel modes."""

    @pytest.mark.parametrize("d,c,r", GEOMS)
    def test_packed_vs_hashed_bit_identical(self, d, c, r):
        packed = CountSketch(d=d, c=c, r=r, seed=7,
                             backend="pallas_interpret")
        hashed = CountSketch(d=d, c=c, r=r, seed=7,
                             backend="pallas_interpret",
                             packed_signs=False)
        assert packed._packed_sign_kernels
        assert not hashed._packed_sign_kernels
        v = jnp.asarray(np.random.RandomState(3).randn(d)
                        .astype(np.float32))
        tp, th = packed.sketch(v), hashed.sketch(v)
        assert jnp.array_equal(tp, th), "sketch tables differ"
        ep = packed.estimates(tp, padded=True)
        eh = hashed.estimates(tp, padded=True)
        assert jnp.array_equal(ep, eh), "estimates differ"

    def test_packed_bits_match_signs_row(self):
        cs = CountSketch(d=4096, c=1024, r=5, seed=11)
        bits = np.asarray(jax.jit(cs._packed_signs_traced)())
        for row in range(cs.r):
            want = np.asarray(cs._signs_row(row))
            got = 1.0 - 2.0 * ((bits >> row) & 1).astype(np.float32)
            np.testing.assert_array_equal(got, want)

    def test_r9_falls_back_to_hashing(self):
        cs = CountSketch(d=2048, c=512, r=9, seed=7,
                         backend="pallas_interpret")
        assert not cs._packed_sign_kernels  # u8 holds 8 row bits
        t = cs.sketch(jnp.ones(2048, jnp.float32))
        assert t.shape == (9, 512)


def test_r17_per_row_mix_path():
    """r > 16 leaves the one-mix scheme: the kernels hash once per
    (row, coord) via _flip_chunk. Pin that branch of the flip-mask
    formulation against the XLA path (it is outside GEOMS and the
    packed-sign eligibility, so nothing else executes it)."""
    d, c, r = 2048, 512, 17
    xla = CountSketch(d=d, c=c, r=r, seed=7, backend="xla")
    pal = CountSketch(d=d, c=c, r=r, seed=7,
                      backend="pallas_interpret")
    assert not pal._one_mix_signs and not pal._packed_sign_kernels
    v = jnp.asarray(np.random.RandomState(5).randn(d)
                    .astype(np.float32))
    tx, tp = xla.sketch(v), pal.sketch(v)
    np.testing.assert_allclose(np.asarray(tx), np.asarray(tp),
                               rtol=1e-6, atol=1e-5)
    assert jnp.array_equal(xla.estimates(tx), pal.estimates(tx))
