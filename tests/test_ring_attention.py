"""Ring / Ulysses sequence-parallel attention vs the dense oracle,
on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from commefficient_tpu.parallel.ring_attention import (
    dense_reference, ring_attention, ulysses_attention)

from commefficient_tpu.parallel.mesh import shard_map


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


def _qkv(B, T, H, D, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_ring_matches_dense(causal, n_dev):
    B, T, H, D = 2, 64, 4, 16
    q, k, v = _qkv(B, T, H, D)
    mesh = _mesh(n_dev)
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=causal),
        mesh=mesh, in_specs=P(None, "seq", None, None),
        out_specs=P(None, "seq", None, None))
    out = jax.jit(fn)(q, k, v)
    ref = dense_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_dense(causal):
    B, T, H, D = 2, 64, 8, 16  # H divisible by 8 devices
    q, k, v = _qkv(B, T, H, D, seed=1)
    mesh = _mesh(8)
    fn = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, "seq",
                                          causal=causal),
        mesh=mesh, in_specs=P(None, "seq", None, None),
        out_specs=P(None, "seq", None, None))
    out = jax.jit(fn)(q, k, v)
    ref = dense_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_long_sequence_scales():
    """T larger than any single shard would see: just correctness at
    a longer length (memory scaling is structural: each device only
    materialises (T_local, T_local) score blocks)."""
    B, T, H, D = 1, 512, 2, 8
    q, k, v = _qkv(B, T, H, D, seed=2)
    mesh = _mesh(8)
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=True),
        mesh=mesh, in_specs=P(None, "seq", None, None),
        out_specs=P(None, "seq", None, None))
    out = jax.jit(fn)(q, k, v)
    ref = dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_gpt2_sequence_parallel_matches_dense(impl):
    """Full GPT2DoubleHeads forward under sequence parallelism ==
    dense single-device forward (positions, ring attention, and the
    cross-shard MC gather all exercised)."""
    import dataclasses
    from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads

    cfg = GPT2Config.tiny()  # n_head=2 -> use 2 devices for ulysses
    n_dev = 2
    B, N, T = 2, 2, 32
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, N, T)),
                      jnp.int32)
    mc_ids = jnp.asarray(rng.randint(0, T, (B, N)), jnp.int32)

    dense = GPT2DoubleHeads(cfg)
    params = dense.init(jax.random.PRNGKey(0), ids, mc_ids)["params"]
    lm_ref, mc_ref = dense.apply({"params": params}, ids, mc_ids)

    sp_cfg = dataclasses.replace(cfg, seq_axis="seq", seq_impl=impl)
    sp = GPT2DoubleHeads(sp_cfg)
    mesh = _mesh(n_dev)
    fn = shard_map(
        lambda p, i, m: sp.apply({"params": p}, i, m),
        mesh=mesh,
        in_specs=(P(), P(None, None, "seq"), P()),
        out_specs=(P(None, None, "seq", None), P()))
    lm_sp, mc_sp = jax.jit(fn)(params, ids, mc_ids)
    np.testing.assert_allclose(np.asarray(lm_sp), np.asarray(lm_ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(mc_sp), np.asarray(mc_ref),
                               rtol=2e-5, atol=2e-5)
