"""Full-state checkpoint/resume: an interrupted-and-resumed run must
reproduce the uninterrupted run bit-for-bit (weights, server momentum/
error, client states, data order) — including runs interrupted
MID-EPOCH by a SIGTERM or an exception, which resume from the
round-cadence autosave (``--checkpoint_every_rounds``)."""

import glob
import json
import os
import signal

import numpy as np
import pytest

from commefficient_tpu.train import cv_train


def _argv(tmpdir, epochs, extra=()):
    return [
        "--test", "--dataset_name", "Synthetic",
        "--mode", "sketch", "--error_type", "virtual",
        "--local_momentum", "0", "--virtual_momentum", "0.9",
        "--num_clients", "10", "--num_workers", "2",
        "--local_batch_size", "4", "--num_epochs", str(epochs),
        "--lr_scale", "0.1", "--pivot_epoch", "1",
        "--checkpoint", "--checkpoint_path", str(tmpdir),
        "--checkpoint_every", "1", *extra,
    ]


def _load_state(tmpdir):
    import json
    import os
    path = os.path.join(str(tmpdir), "ckpt_ResNet9.npz")
    with np.load(path) as z:
        return ({k: np.array(z[k]) for k in z.files if k != "meta"},
                json.loads(str(z["meta"])))


@pytest.mark.parametrize("mode_extra", [
    (),                                           # sketch + virtual
    ("--mode", "true_topk", "--k", "10"),         # topk + virtual
])
def test_resume_bit_exact(tmp_path, mode_extra):
    cont_dir = tmp_path / "cont"
    resume_dir = tmp_path / "resume"

    # uninterrupted 3-epoch run
    cv_train.main(_argv(cont_dir, 3, mode_extra))
    cont_state, cont_meta = _load_state(cont_dir)

    # 1 epoch, stop, then resume for the remaining 2 (schedule decays
    # over the full 3-epoch horizon in both invocations)
    cv_train.main(_argv(resume_dir, 1,
                        (*mode_extra, "--schedule_epochs", "3")))
    cv_train.main(_argv(resume_dir, 3, (*mode_extra, "--resume")))
    res_state, res_meta = _load_state(resume_dir)

    assert cont_meta["epoch"] == res_meta["epoch"] == 3
    assert cont_meta["round_index"] == res_meta["round_index"]
    assert cont_meta["opt_step_count"] == res_meta["opt_step_count"]
    assert set(cont_state) == set(res_state)
    for k in cont_state:
        np.testing.assert_array_equal(cont_state[k], res_state[k],
                                      err_msg=k)


def test_resume_rejects_mismatched_config(tmp_path):
    cv_train.main(_argv(tmp_path, 1))
    with pytest.raises(ValueError):
        # different mode -> different transmit shape: must refuse
        cv_train.main(_argv(tmp_path, 2,
                            ("--mode", "uncompressed", "--resume",
                             "--error_type", "none",
                             "--virtual_momentum", "0")))


def test_resume_rejects_rot_lanes_mismatch(tmp_path):
    """A sketch checkpoint records its RESOLVED rotation granularity:
    resuming under a different one would decode the saved sketch-space
    error state against the wrong rotation stream — silent corruption,
    so it must refuse (runtime/checkpoint.py rot_lanes check; the
    cross-platform risk is the auto default re-resolving per
    backend)."""
    cv_train.main(_argv(tmp_path, 1))  # auto -> 0 on the CPU backend
    with pytest.raises(ValueError, match="rot_lanes"):
        # 1 is the only granularity the tiny --test sketch (c=10)
        # admits; any resolved value != the checkpoint's 0 must refuse
        cv_train.main(_argv(tmp_path, 2,
                            ("--resume", "--sketch_rot_lanes", "1")))


def test_resume_requires_existing_checkpoint(tmp_path):
    with pytest.raises(FileNotFoundError):
        cv_train.main(_argv(tmp_path / "empty", 1, ("--resume",)))


def test_global_np_rng_and_loader_counter_roundtrip(tmp_path):
    """Augmentation RNG state (global numpy) and the native loader's
    round counter survive save/load."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.config import Config
    from commefficient_tpu.models import get_model
    from commefficient_tpu.ops.vec import flatten_params
    from commefficient_tpu.runtime.checkpoint import (load_checkpoint,
                                                      save_checkpoint)
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)

    cfg = Config(mode="uncompressed", error_type="none",
                 local_momentum=0.0, virtual_momentum=0.9,
                 num_workers=2, local_batch_size=2, num_clients=4,
                 dataset_name="CIFAR10", seed=0)
    module = get_model("ResNet9")(
        num_classes=10,
        channels={"prep": 2, "layer1": 2, "layer2": 2, "layer3": 2})
    params = module.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, 32, 32, 3)))["params"]

    def loss(p, batch, args):
        return (jnp.float32(0.0), jnp.float32(0.0))

    model = FedModel(module, params, loss, cfg)
    opt = FedOptimizer([{"lr": 1.0}], cfg)

    class FakeLoader:
        _round_counter = 7
        sampler = None

    path = str(tmp_path / "s.npz")
    np.random.seed(123)
    np.random.rand(5)  # advance the global stream
    save_checkpoint(path, model, opt, loader=FakeLoader(), epoch=1)
    after_save = np.random.rand(3)

    np.random.seed(999)  # scramble
    fresh = FakeLoader()
    fresh._round_counter = 0
    load_checkpoint(path, model, opt, loader=fresh)
    np.testing.assert_array_equal(np.random.rand(3), after_save)
    assert fresh._round_counter == 7


def _midrun_argv(d, epochs, extra=()):
    """1 round per epoch (num_clients == num_workers), so ``--test``'s
    one-round-per-epoch break coincides with the true epoch boundary
    and a mid-run kill/resume replays whole rounds."""
    return [
        "--test", "--dataset_name", "Synthetic", "--iid",
        "--mode", "sketch", "--error_type", "virtual",
        "--local_momentum", "0", "--virtual_momentum", "0.9",
        "--num_clients", "2", "--num_workers", "2",
        "--local_batch_size", "4", "--num_epochs", str(epochs),
        "--lr_scale", "0.1", "--pivot_epoch", "1",
        "--checkpoint", "--checkpoint_path", str(d),
        "--checkpoint_every", "1",
        "--checkpoint_every_rounds", "2", "--checkpoint_keep", "2",
        *extra,
    ]


# killed BETWEEN autosaves (cadence 2, autosave at round 2): the
# resume replays round 3, exercising the ledger's replay dedup
_KILL_ROUND = 3


def _inject_round_failure(monkeypatch, kill_round, action):
    """Wrap RoundAutosaver.__call__: run the real autosave logic,
    then — once per process — kill the run at ``kill_round`` (either
    a real SIGTERM to ourselves, which the trainer's sigterm_raises
    turns into GracefulShutdown, or a raised exception)."""
    from commefficient_tpu.runtime import checkpoint as ckpt
    real = ckpt.RoundAutosaver.__call__
    state = {"fired": False}

    def wrapped(self, epoch):
        real(self, epoch)
        if not state["fired"] \
                and int(self.model.round_index) >= kill_round:
            state["fired"] = True
            if action == "sigterm":
                os.kill(os.getpid(), signal.SIGTERM)
            else:
                raise RuntimeError("chaos: injected round failure")

    monkeypatch.setattr(ckpt.RoundAutosaver, "__call__", wrapped)
    return state


@pytest.fixture(scope="module")
def _uninterrupted_run(tmp_path_factory):
    d = tmp_path_factory.mktemp("cont")
    cv_train.main(_midrun_argv(d, 6))
    return _load_state(d), d


def test_round_autosave_retention(_uninterrupted_run):
    """--checkpoint_keep prunes round-stamped history snapshots to
    the budget (newest kept)."""
    _, d = _uninterrupted_run
    snaps = sorted(os.path.basename(p) for p in
                   glob.glob(os.path.join(str(d), "ckpt_ResNet9_r*.npz")))
    assert len(snaps) == 2, snaps
    rounds = [int(n.split("_r")[1].split(".")[0]) for n in snaps]
    assert rounds == [4, 6]  # cadence-2 autosaves, oldest pruned


@pytest.mark.parametrize("failure", ["sigterm", "exception"])
def test_resume_after_midrun_failure_bit_exact(
        tmp_path, monkeypatch, failure, _uninterrupted_run):
    """Kill a run mid-epoch (SIGTERM or raised exception between
    rounds); the last round-cadence autosave must be a consistent
    resume point and the resumed run bit-exact vs uninterrupted,
    with ledger round ids monotone and deduplicated."""
    (cont_state, cont_meta), _ = _uninterrupted_run
    crash_dir = tmp_path / "crash"
    ledger = str(crash_dir / "led.jsonl")
    extra = ("--ledger", ledger)

    state = _inject_round_failure(monkeypatch, _KILL_ROUND, failure)
    if failure == "sigterm":
        # GracefulShutdown is caught inside main(): clean exit
        cv_train.main(_midrun_argv(crash_dir, 6, extra))
    else:
        with pytest.raises(RuntimeError, match="injected round"):
            cv_train.main(_midrun_argv(crash_dir, 6, extra))
    assert state["fired"]
    monkeypatch.undo()

    # crash saved NOTHING past the last cadence autosave: no final
    # model artifact, checkpoint meta at the autosaved round
    assert not os.path.exists(str(crash_dir / "ResNet9.pkl"))
    crash_meta = _load_state(crash_dir)[1]
    assert crash_meta["round_index"] == _KILL_ROUND - 1

    cv_train.main(_midrun_argv(crash_dir, 6, (*extra, "--resume")))
    res_state, res_meta = _load_state(crash_dir)
    assert res_meta["epoch"] == cont_meta["epoch"] == 6
    assert res_meta["round_index"] == cont_meta["round_index"]
    assert res_meta["opt_step_count"] == cont_meta["opt_step_count"]
    assert set(cont_state) == set(res_state)
    for k in cont_state:
        np.testing.assert_array_equal(cont_state[k], res_state[k],
                                      err_msg=k)
    # the resumed run appended to the SAME ledger; replayed rounds
    # were deduplicated (JSONLSink resume_after), ids stay monotone
    with open(ledger) as f:
        rounds = [rec["round"] for rec in map(json.loads, f)
                  if rec.get("kind") == "round"
                  and rec.get("round") is not None]
    assert rounds == sorted(set(rounds)), rounds
    assert rounds == list(range(rounds[0], rounds[-1] + 1))
    assert rounds[-1] >= cont_meta["round_index"] - 1


def test_gpt2_resume_round_trip(tmp_path):
    """GPT-2 trainer: resumed run continues from the saved epoch and
    reproduces the uninterrupted final state exactly."""
    import json
    import os

    from commefficient_tpu.train import gpt2_train

    def argv(d, epochs, extra=()):
        return [
            "--test", "--dataset_name", "PERSONA",
            "--dataset_dir", str(d / "data"),
            "--mode", "sketch", "--error_type", "virtual",
            "--local_momentum", "0", "--virtual_momentum", "0.9",
            "--num_workers", "2", "--local_batch_size", "2",
            "--num_epochs", str(epochs), "--lr_scale", "0.01",
            "--checkpoint", "--checkpoint_path", str(d),
            "--checkpoint_every", "1", *extra,
        ]

    def state(d):
        with np.load(os.path.join(str(d), "ckpt_gpt2.npz")) as z:
            return ({k: np.array(z[k]) for k in z.files if k != "meta"},
                    json.loads(str(z["meta"])))

    cont, resume = tmp_path / "c", tmp_path / "r"
    gpt2_train.main(argv(cont, 2))
    # interrupted run: 1 epoch now, but decay over the full horizon
    gpt2_train.main(argv(resume, 1, ("--schedule_epochs", "2")))
    gpt2_train.main(argv(resume, 2, ("--resume",)))
    s1, m1 = state(cont)
    s2, m2 = state(resume)
    assert m1["epoch"] == m2["epoch"] == 2
    for k in s1:
        np.testing.assert_array_equal(s1[k], s2[k], err_msg=k)


# -- torn-shard detection and the retained-autosave fallback ------------


def _truncate(path):
    with open(path, "rb") as f:
        blob = f.read()
    with open(path, "wb") as f:
        f.write(blob[: len(blob) // 2])


def test_validate_names_missing_side_shard(tmp_path):
    """A multi-process checkpoint whose side shard vanished (dead
    process, partial copy) must refuse by NAME before any state is
    touched."""
    from commefficient_tpu.runtime.checkpoint import (
        TornCheckpointError, validate_checkpoint)

    path = str(tmp_path / "ck.npz")
    meta = {"format": 1, "clientstore": {"fields": ["velocities"],
                                         "processes": 2}}
    np.savez_compressed(path, meta=json.dumps(meta))
    with pytest.raises(TornCheckpointError, match=r"ck\.npz\.shard1"):
        validate_checkpoint(path)
    # a present-but-torn side shard is named the same way
    _truncate_target = path + ".shard1.npz"
    np.savez_compressed(_truncate_target, ids=np.zeros(1, np.int64))
    _truncate(_truncate_target)
    with pytest.raises(TornCheckpointError, match=r"shard1\.npz"):
        validate_checkpoint(path)


def test_torn_canonical_falls_back_to_retained_autosave(
        tmp_path, capsys):
    """A torn canonical checkpoint costs at most the autosave cadence:
    --resume restores the newest retained round snapshot instead of
    crashing, and the run completes."""
    d = tmp_path / "run"
    cv_train.main(_midrun_argv(d, 4))
    _truncate(os.path.join(str(d), "ckpt_ResNet9.npz"))
    cv_train.main(_midrun_argv(d, 6, ("--resume",)))
    out = capsys.readouterr().out
    assert "falling back to retained autosave" in out
    assert "_r00000004.npz" in out
    _, meta = _load_state(d)  # canonical rewritten by the resumed run
    assert meta["epoch"] == 6


def test_torn_canonical_without_fallback_raises(tmp_path):
    """No retained snapshot to fall back to: the original error —
    naming the torn file — propagates instead of silently training
    from scratch."""
    from commefficient_tpu.runtime.checkpoint import TornCheckpointError

    cv_train.main(_argv(tmp_path, 1))
    _truncate(os.path.join(str(tmp_path), "ckpt_ResNet9.npz"))
    with pytest.raises(TornCheckpointError, match=r"ckpt_ResNet9\.npz"):
        cv_train.main(_argv(tmp_path, 2, ("--resume",)))


def test_round_autosave_retention_across_resume_boundary(tmp_path):
    """--checkpoint_keep keeps pruning across a stop/resume: the
    resumed run's autosaves displace the pre-resume snapshots instead
    of accumulating beside them."""
    d = tmp_path / "run"
    cv_train.main(_midrun_argv(d, 4))
    snaps = sorted(glob.glob(os.path.join(str(d), "ckpt_ResNet9_r*.npz")))
    rounds = [int(os.path.basename(n).split("_r")[1].split(".")[0])
              for n in snaps]
    assert rounds == [2, 4]
    cv_train.main(_midrun_argv(d, 8, ("--resume",)))
    snaps = sorted(glob.glob(os.path.join(str(d), "ckpt_ResNet9_r*.npz")))
    rounds = [int(os.path.basename(n).split("_r")[1].split(".")[0])
              for n in snaps]
    assert rounds == [6, 8], rounds
    _, meta = _load_state(d)
    assert meta["round_index"] == 8
