"""Model-family coverage: every registered model initializes, runs a
forward pass with the right output shape, and the Fixup inits satisfy
their defining invariants (SURVEY.md §2.6)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.models import get_model, model_names


def _fwd(module, shape, num_classes):
    x = jnp.asarray(np.random.RandomState(0).randn(*shape), jnp.float32)
    variables = module.init(jax.random.PRNGKey(0), x)
    out = module.apply(variables, x)
    assert out.shape == (shape[0], num_classes)
    assert np.isfinite(np.asarray(out)).all()
    return variables, out


class TestRegistry:
    def test_expected_models_registered(self):
        names = model_names()
        for expect in ["ResNet9", "FixupResNet9", "FixupResNet50",
                       "ResNet18", "FixupResNet18", "ResNet101LN"]:
            assert expect in names, names


class TestCifarModels:
    @pytest.mark.parametrize("name", ["FixupResNet9", "ResNet18",
                                      "FixupResNet18"])
    def test_forward_shape(self, name):
        cls = get_model(name)
        if name == "FixupResNet9":
            module = cls(**cls.test_config())
        else:
            module = cls(num_classes=10, num_blocks=(1, 1, 1, 1))
        _fwd(module, (2, 32, 32, 3), 10)

    def test_fixup_zero_head_at_init(self):
        """Fixup nets zero-init the classifier (reference
        fixup_resnet9.py:79-81) => logits are exactly 0 at init."""
        cls = get_model("FixupResNet9")
        module = cls(**cls.test_config())
        _, out = _fwd(module, (2, 32, 32, 3), 10)
        assert np.allclose(np.asarray(out), 0.0)


class TestEmnistFamily:
    def test_resnet101ln_1channel(self):
        module = get_model("ResNet101LN")()
        # EMNIST: 28x28 grayscale, 62 classes (reference resnets.py:155,
        # resnet101ln.py:8)
        _fwd(module, (2, 28, 28, 1), 62)

    def test_layernorm_no_batch_mixing(self):
        """LayerNorm output for a sample must not depend on the other
        samples in the batch (the point of LN for federated EMNIST)."""
        module = get_model("ResNet101LN")()
        rng = np.random.RandomState(1)
        x2 = jnp.asarray(rng.randn(2, 28, 28, 1), jnp.float32)
        variables = module.init(jax.random.PRNGKey(0), x2)
        out2 = module.apply(variables, x2)
        out1 = module.apply(variables, x2[:1])
        np.testing.assert_allclose(np.asarray(out2[0]),
                                   np.asarray(out1[0]), atol=1e-4)


class TestImagenetModels:
    def test_fixup_resnet50_tiny(self):
        module = get_model("FixupResNet50")(num_classes=5,
                                            stage_sizes=(1, 1, 1, 1))
        _fwd(module, (1, 64, 64, 3), 5)

    def test_generic_resnet_factories(self):
        from commefficient_tpu.models.resnets import resnet18
        module = resnet18(num_classes=7)
        _fwd(module, (1, 28, 28, 1), 7)

    def test_resnext_grouped_conv(self):
        """resnext50_32x4d (reference resnets.py:309-321): grouped 3x3
        conv — kernel input-channel dim is width/groups."""
        from commefficient_tpu.models.resnets import resnext50_32x4d
        module = resnext50_32x4d(num_classes=4)
        variables, _ = _fwd(module, (1, 28, 28, 1), 4)
        # first bottleneck: planes=64, base_width=4, groups=32 =>
        # width=128; grouped conv kernel is (3, 3, 128/32, 128)
        k = variables["params"]["Bottleneck_0"]["Conv_1"]["kernel"]
        assert k.shape == (3, 3, 4, 128), k.shape


class TestFixupBf16:
    """--bf16 for the Fixup family: compute must actually run in
    bfloat16 (the scalar fixup biases/scales are f32 params and would
    silently promote activations back to f32 if not cast at use),
    while params and the returned logits stay float32."""

    @pytest.mark.parametrize("name,shape", [
        ("FixupResNet9", (2, 32, 32, 3)),
        ("FixupResNet18", (2, 32, 32, 3)),
        ("FixupResNet50", (1, 64, 64, 3)),
    ])
    def test_bf16_compute_dtype(self, name, shape):
        cls = get_model(name)
        kw = {"num_classes": 4, "dtype": jnp.bfloat16}
        if name == "FixupResNet9":
            kw.update(cls.test_config(4))
        elif name == "FixupResNet50":
            kw["stage_sizes"] = (1, 1, 1, 1)
        else:
            kw["num_blocks"] = (1, 1, 1, 1)
        module = cls(**kw)
        x = jnp.asarray(np.random.RandomState(0).randn(*shape),
                        jnp.float32)
        variables = module.init(jax.random.PRNGKey(0), x)
        for leaf in jax.tree_util.tree_leaves(variables["params"]):
            assert leaf.dtype == jnp.float32, leaf.dtype
        # intercept an intermediate activation to prove bf16 engaged
        _, state = module.apply(variables, x, capture_intermediates=True)
        inter = jax.tree_util.tree_leaves(state["intermediates"])
        assert any(getattr(a, "dtype", None) == jnp.bfloat16
                   for a in inter), \
            "no bfloat16 intermediate found — promotion undid --bf16"
        out = module.apply(variables, x)
        assert out.dtype == jnp.float32


class TestBatchNormUnderClientVmap:
    """SURVEY §7 hard part: with --batchnorm, batch statistics must
    stay per-client under the vmapped round — sync-BN-style mixing
    across the client axis would break the federated semantics. If
    stats never mix, client contributions are additive: the two-client
    round's aggregated gradient equals the weighted sum of the two
    single-client rounds'."""

    def test_per_client_batch_stats_additivity(self):
        import jax
        import jax.numpy as jnp
        from commefficient_tpu.config import Config
        from commefficient_tpu.core.rounds import (ClientStates,
                                                   build_client_round)
        from commefficient_tpu.models import get_model
        from commefficient_tpu.ops.vec import flatten_params
        from commefficient_tpu.train.cv_train import make_compute_loss

        cfg = Config(mode="uncompressed", error_type="none",
                     local_momentum=0.0, virtual_momentum=0.0,
                     weight_decay=0.0, num_workers=2,
                     local_batch_size=4, num_clients=4,
                     dataset_name="CIFAR10", seed=0)
        module = get_model("ResNet9")(
            num_classes=10, do_batchnorm=True,
            channels={"prep": 2, "layer1": 2, "layer2": 2,
                      "layer3": 2})
        variables = module.init(jax.random.PRNGKey(0),
                                jnp.zeros((1, 32, 32, 3)), train=True)
        flat, unravel = flatten_params(variables["params"])
        cfg.grad_size = int(flat.size)
        loss = make_compute_loss(module, variables.get("batch_stats"))

        def loss_flat(p, batch):
            return loss(unravel(p), batch, cfg)

        rng = np.random.RandomState(0)
        xa = rng.randn(1, 4, 32, 32, 3).astype(np.float32)
        xb = rng.randn(1, 4, 32, 32, 3).astype(np.float32)
        ya = rng.randint(0, 10, (1, 4)).astype(np.int32)
        yb = rng.randint(0, 10, (1, 4)).astype(np.int32)
        ones = np.ones((1, 4), np.float32)

        def agg(x, y, m, W):
            c = Config(**{**cfg.__dict__, "num_workers": W})
            fn = jax.jit(build_client_round(c, loss_flat, 4))
            cs = ClientStates.init(c, 4)
            res = fn(flat, cs,
                     {"x": jnp.asarray(x), "y": jnp.asarray(y),
                      "mask": jnp.asarray(m)},
                     jnp.arange(W, dtype=jnp.int32),
                     jax.random.PRNGKey(0), 1.0)
            return np.asarray(res.aggregated)

        both = agg(np.concatenate([xa, xb]), np.concatenate([ya, yb]),
                   np.concatenate([ones, ones]), 2)
        solo_a = agg(xa, ya, ones, 1)
        solo_b = agg(xb, yb, ones, 1)
        # each solo agg = g_sum/4; both = (gA_sum + gB_sum)/8
        np.testing.assert_allclose(both, (solo_a + solo_b) / 2.0,
                                   rtol=2e-4, atol=2e-5)
