"""Model-family coverage: every registered model initializes, runs a
forward pass with the right output shape, and the Fixup inits satisfy
their defining invariants (SURVEY.md §2.6)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.models import get_model, model_names


def _fwd(module, shape, num_classes):
    x = jnp.asarray(np.random.RandomState(0).randn(*shape), jnp.float32)
    variables = module.init(jax.random.PRNGKey(0), x)
    out = module.apply(variables, x)
    assert out.shape == (shape[0], num_classes)
    assert np.isfinite(np.asarray(out)).all()
    return variables, out


class TestRegistry:
    def test_expected_models_registered(self):
        names = model_names()
        for expect in ["ResNet9", "FixupResNet9", "FixupResNet50",
                       "ResNet18", "FixupResNet18", "ResNet101LN"]:
            assert expect in names, names


class TestCifarModels:
    @pytest.mark.parametrize("name", ["FixupResNet9", "ResNet18",
                                      "FixupResNet18"])
    def test_forward_shape(self, name):
        cls = get_model(name)
        if name == "FixupResNet9":
            module = cls(**cls.test_config())
        else:
            module = cls(num_classes=10, num_blocks=(1, 1, 1, 1))
        _fwd(module, (2, 32, 32, 3), 10)

    def test_fixup_zero_head_at_init(self):
        """Fixup nets zero-init the classifier (reference
        fixup_resnet9.py:79-81) => logits are exactly 0 at init."""
        cls = get_model("FixupResNet9")
        module = cls(**cls.test_config())
        _, out = _fwd(module, (2, 32, 32, 3), 10)
        assert np.allclose(np.asarray(out), 0.0)


class TestEmnistFamily:
    def test_resnet101ln_1channel(self):
        module = get_model("ResNet101LN")()
        # EMNIST: 28x28 grayscale, 62 classes (reference resnets.py:155,
        # resnet101ln.py:8)
        _fwd(module, (2, 28, 28, 1), 62)

    def test_layernorm_no_batch_mixing(self):
        """LayerNorm output for a sample must not depend on the other
        samples in the batch (the point of LN for federated EMNIST)."""
        module = get_model("ResNet101LN")()
        rng = np.random.RandomState(1)
        x2 = jnp.asarray(rng.randn(2, 28, 28, 1), jnp.float32)
        variables = module.init(jax.random.PRNGKey(0), x2)
        out2 = module.apply(variables, x2)
        out1 = module.apply(variables, x2[:1])
        np.testing.assert_allclose(np.asarray(out2[0]),
                                   np.asarray(out1[0]), atol=1e-4)


class TestImagenetModels:
    def test_fixup_resnet50_tiny(self):
        module = get_model("FixupResNet50")(num_classes=5,
                                            stage_sizes=(1, 1, 1, 1))
        _fwd(module, (1, 64, 64, 3), 5)

    def test_generic_resnet_factories(self):
        from commefficient_tpu.models.resnets import resnet18
        module = resnet18(num_classes=7)
        _fwd(module, (1, 28, 28, 1), 7)
