"""FedEMNIST (LEAF format) + FedImageNet + new transform stacks,
driven off synthetic on-disk fixtures (SURVEY.md §2.5)."""

import json
import os

import numpy as np
import pytest

from commefficient_tpu.data import get_dataset_cls
from commefficient_tpu.data.fed_sampler import FedSampler
from commefficient_tpu.data.loader import FedLoader


def make_leaf_dir(root, n_clients=4, per_client=(3, 5, 2, 7),
                  n_test=6, seed=0):
    rng = np.random.RandomState(seed)
    for split, counts in (("train", per_client),
                          ("test", [n_test // 2, n_test - n_test // 2])):
        d = os.path.join(root, split)
        os.makedirs(d, exist_ok=True)
        user_data = {}
        for u, n in enumerate(counts):
            user_data[f"writer{u}"] = {
                "x": rng.rand(n, 784).tolist(),
                "y": rng.randint(0, 62, n).tolist(),
            }
        with open(os.path.join(d, "shard0.json"), "w") as f:
            json.dump({"users": list(user_data),
                       "user_data": user_data}, f)


class TestFedEMNIST:
    @pytest.fixture()
    def ds_dir(self, tmp_path):
        make_leaf_dir(str(tmp_path))
        return str(tmp_path)

    def test_natural_partition(self, ds_dir):
        cls = get_dataset_cls("EMNIST")
        ds = cls(ds_dir, "EMNIST", train=True)
        assert ds.num_clients == 4
        assert list(ds.images_per_client) == [3, 5, 2, 7]
        assert len(ds) == 17
        cid, img, target = ds[3]  # first item of client 1
        assert cid == 1
        assert img.shape == (28, 28, 1)
        assert 0 <= target < 62

    def test_val_items(self, ds_dir):
        cls = get_dataset_cls("EMNIST")
        ds = cls(ds_dir, "EMNIST", train=False)
        assert len(ds) == 6
        cid, img, target = ds[0]
        assert cid == -1 and img.shape == (28, 28, 1)

    def test_round_batches_flow(self, ds_dir):
        cls = get_dataset_cls("EMNIST")
        ds = cls(ds_dir, "EMNIST", train=True)
        sampler = FedSampler(ds, num_workers=2, local_batch_size=2,
                             seed=0)
        loader = FedLoader(ds, sampler)
        batch = next(iter(loader))
        assert batch["x"].shape[:2] == (2, 2)
        assert batch["x"].shape[2:] == (28, 28, 1)

    def test_iid_resplit(self, ds_dir):
        cls = get_dataset_cls("EMNIST")
        ds = cls(ds_dir, "EMNIST", train=True, do_iid=True,
                 num_clients=3, seed=1)
        assert ds.num_clients == 3
        ids = sorted({ds[i][0] for i in range(len(ds))})
        assert ids == [0, 1, 2]


class TestFedImageNet:
    @pytest.fixture()
    def ds_dir(self, tmp_path):
        from PIL import Image
        rng = np.random.RandomState(0)
        for split, counts in (("train", (3, 2)), ("val", (1, 1))):
            for ci, wnid in enumerate(["n01440764", "n01443537"]):
                d = tmp_path / split / wnid
                d.mkdir(parents=True)
                for i in range(counts[ci]):
                    arr = rng.randint(0, 255, (32, 40, 3), np.uint8)
                    Image.fromarray(arr).save(d / f"img{i}.JPEG")
        return str(tmp_path)

    def test_stats_only_prep_and_items(self, ds_dir):
        cls = get_dataset_cls("ImageNet")
        ds = cls(ds_dir, "ImageNet", train=True)
        assert list(ds.images_per_client) == [3, 2]
        cid, img, target = ds[4]  # second image of wnid 1
        assert cid == 1 and target == 1
        assert img.shape == (32, 40, 3)
        with open(os.path.join(ds_dir, "stats.json")) as f:
            stats = json.load(f)
        assert stats["num_val_images"] == 2

    def test_refuses_overwrite(self, ds_dir):
        cls = get_dataset_cls("ImageNet")
        ds = cls(ds_dir, "ImageNet", train=True)
        with pytest.raises(RuntimeError):
            ds.prepare_datasets()

    def test_val_transform_pipeline(self, ds_dir):
        from commefficient_tpu.data import transforms as T
        cls = get_dataset_cls("ImageNet")
        ds = cls(ds_dir, "ImageNet", train=False,
                 transform=T.imagenet_val_transform())
        cid, img, target = ds[0]
        assert img.shape == (224, 224, 3)
        assert img.dtype == np.float32


def make_cifar10_dir(root, per_batch=8, n_test=10, seed=0):
    """Fabricate ``cifar-10-batches-py/`` in the exact upstream layout:
    five pickled train batches + ``test_batch``, each a dict with
    b"data" (N, 3072) uint8 rows in channels-first order and b"labels"
    a plain list (the layout FedCIFAR10.prepare_datasets reads;
    reference fed_cifar.py:13-100)."""
    import pickle

    rng = np.random.RandomState(seed)
    d = os.path.join(root, "cifar-10-batches-py")
    os.makedirs(d, exist_ok=True)
    for bi in range(1, 6):
        data = rng.randint(0, 256, (per_batch, 3072), np.uint8)
        labels = rng.randint(0, 10, per_batch).tolist()
        with open(os.path.join(d, f"data_batch_{bi}"), "wb") as f:
            pickle.dump({b"data": data, b"labels": labels,
                         b"batch_label": b"training batch"}, f)
    data = rng.randint(0, 256, (n_test, 3072), np.uint8)
    with open(os.path.join(d, "test_batch"), "wb") as f:
        pickle.dump({b"data": data,
                     b"labels": rng.randint(0, 10, n_test).tolist()}, f)
    return d


def make_cifar100_dir(root, n_train=40, n_test=10, seed=0):
    """``cifar-100-python/`` upstream layout: single ``train`` pickle
    with b"fine_labels" + ``test``."""
    import pickle

    rng = np.random.RandomState(seed)
    d = os.path.join(root, "cifar-100-python")
    os.makedirs(d, exist_ok=True)
    # guarantee every fine label appears at least... not needed: the
    # partition only needs counts per class (possibly zero)
    with open(os.path.join(d, "train"), "wb") as f:
        pickle.dump({b"data": rng.randint(0, 256, (n_train, 3072),
                                          np.uint8),
                     b"fine_labels": rng.randint(
                         0, 100, n_train).tolist()}, f)
    with open(os.path.join(d, "test"), "wb") as f:
        pickle.dump({b"data": rng.randint(0, 256, (n_test, 3072),
                                          np.uint8),
                     b"fine_labels": rng.randint(
                         0, 100, n_test).tolist()}, f)
    return d


class TestFedCIFARPrep:
    """prepare_datasets against real-format pickle archives (round-2
    review weak #3: this path must not first run on real data)."""

    def test_cifar10_prep_items_and_partition(self, tmp_path):
        import pickle

        root = str(tmp_path)
        src = make_cifar10_dir(root)
        cls = get_dataset_cls("CIFAR10")
        ds = cls(root, "CIFAR10", train=True)  # triggers prep

        # counts per class match the archive contents
        ys = []
        for bi in range(1, 6):
            with open(os.path.join(src, f"data_batch_{bi}"), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            ys.append(np.asarray(d[b"labels"]))
        y = np.concatenate(ys)
        want_counts = [int((y == c).sum()) for c in range(10)]
        assert list(ds.images_per_client) == want_counts
        assert len(ds) == 40

        # one class per natural client: label == client id everywhere
        for i in range(len(ds)):
            cid, img, target = ds[i]
            assert target == cid
            assert img.shape == (32, 32, 3) and img.dtype == np.uint8

        # pixel content survives the channels-first -> NHWC reshape:
        # first item of class y[0]'s client is the first archive row
        # with that label
        with open(os.path.join(src, "data_batch_1"), "rb") as f:
            d0 = pickle.load(f, encoding="bytes")
        row = np.asarray(d0[b"data"][0])
        first_cls = int(d0[b"labels"][0])
        # position of row 0 within its class = #earlier rows of cls
        start = int(np.concatenate([[0], np.cumsum(
            ds.images_per_client)])[first_cls])
        pos = 0  # row 0 is the first occurrence of its class
        _, img, _ = ds[start + pos]
        np.testing.assert_array_equal(
            img, row.reshape(3, 32, 32).transpose(1, 2, 0))

    def test_cifar10_val_items(self, tmp_path):
        root = str(tmp_path)
        make_cifar10_dir(root)
        cls = get_dataset_cls("CIFAR10")
        ds = cls(root, "CIFAR10", train=False)
        assert len(ds) == 10
        cid, img, target = ds[0]
        assert cid == -1 and img.shape == (32, 32, 3)

    def test_cifar10_noniid_resplit_and_round(self, tmp_path):
        """num_clients > 10 subdivides each class's shard
        (fed_dataset.data_per_client); a full --test federated round
        runs off the prepared archive through cv_train."""
        from commefficient_tpu.train import cv_train

        root = str(tmp_path)
        make_cifar10_dir(root, per_batch=20)  # 100 imgs
        cls = get_dataset_cls("CIFAR10")
        ds = cls(root, "CIFAR10", train=True, num_clients=20)
        assert ds.num_clients == 20
        # every reported client holds exactly one class
        by_client = {}
        for i in range(len(ds)):
            cid, _, target = ds[i]
            by_client.setdefault(cid, set()).add(target)
        assert all(len(v) == 1 for v in by_client.values())

        results = cv_train.main([
            "--test", "--dataset_name", "CIFAR10",
            "--dataset_dir", root, "--num_clients", "20",
            "--mode", "sketch", "--error_type", "virtual",
            "--local_momentum", "0", "--virtual_momentum", "0.9",
            "--num_workers", "2", "--local_batch_size", "4",
            "--num_epochs", "1",
        ])
        assert len(results) == 1
        assert np.isfinite(results[0]["train_loss"])

    def test_cifar100_prep_and_items(self, tmp_path):
        root = str(tmp_path)
        make_cifar100_dir(root)
        cls = get_dataset_cls("CIFAR100")
        ds = cls(root, "CIFAR100", train=True)
        assert len(ds.images_per_client) == 100
        assert sum(ds.images_per_client) == 40
        for i in range(len(ds)):
            cid, img, target = ds[i]
            assert target == cid
            assert img.shape == (32, 32, 3)
        val = cls(root, "CIFAR100", train=False)
        assert len(val) == 10

    def test_missing_archive_raises(self, tmp_path):
        cls = get_dataset_cls("CIFAR10")
        with pytest.raises(FileNotFoundError):
            cls(str(tmp_path), "CIFAR10", train=True)


class TestTransforms:
    def test_femnist_train_shapes(self):
        from commefficient_tpu.data import transforms as T
        rng = np.random.RandomState(0)
        t = T.femnist_train_transform(rng=np.random.RandomState(1))
        x = rng.rand(28, 28, 1).astype(np.float32)
        out = t(x)
        assert out.shape == (28, 28, 1)
        assert np.isfinite(out).all()

    def test_resize_center_crop(self):
        from commefficient_tpu.data import transforms as T
        x = np.zeros((100, 60, 3), np.uint8)
        out = T.Resize(50)(x)
        assert min(out.shape[:2]) == 50
        out = T.CenterCrop(40)(out)
        assert out.shape[:2] == (40, 40)


class TestSyntheticSeparation:
    """--synthetic_separation: the class-overlap dial behind the
    discriminating convergence anchor (scripts/anchor24.py)."""

    def _ds(self, sep, **kw):
        from commefficient_tpu.data.synthetic import FedSynthetic
        return FedSynthetic("", "Synthetic", train=False, do_iid=False,
                            num_clients=None, per_class=8,
                            num_val=400, separation=sep, seed=0,
                            **kw)

    def test_default_separable_small_overlapping(self):
        assert self._ds(1.0).bayes_accuracy() == 1.0
        acc = self._ds(0.025).bayes_accuracy()
        assert 0.5 < acc < 0.95  # genuinely sub-1.0 ceiling

    def test_means_scale_with_separation(self):
        import numpy as np
        a, b = self._ds(1.0), self._ds(0.5)
        np.testing.assert_allclose(b._means, 0.5 * a._means,
                                   rtol=1e-6)

    def test_flags_reach_dataset(self):
        """--synthetic_separation/--synthetic_num_val thread from the
        CLI through cv_train's dataset construction."""
        from commefficient_tpu.config import parse_args
        a = parse_args(default_lr=0.1, argv=[
            "--dataset_name", "Synthetic", "--mode", "uncompressed",
            "--error_type", "none", "--local_momentum", "0",
            "--synthetic_separation", "0.025",
            "--synthetic_num_val", "2000"])
        assert a.synthetic_separation == 0.025
        assert a.synthetic_num_val == 2000


class TestClientDropout:
    """--dropout_prob fault injection: dropped clients' mask rows are
    zeroed so the engine excludes them; fully-dropped rounds are
    skipped (Python loader); deterministic per seed."""

    def _loader(self, p, seed=3):
        from commefficient_tpu.data.fed_sampler import FedSampler
        from commefficient_tpu.data.loader import FedLoader
        from commefficient_tpu.data.synthetic import FedSynthetic
        from commefficient_tpu.data.transforms import (Compose,
                                                       Normalize,
                                                       ToFloat)
        import numpy as np
        tf = Compose([ToFloat(), Normalize(np.zeros(3, np.float32),
                                           np.ones(3, np.float32))])
        ds = FedSynthetic("", "Synthetic", transform=tf, num_classes=4,
                          per_class=16, num_val=8, gen_seed=1)
        return FedLoader(ds, FedSampler(ds, num_workers=2,
                                        local_batch_size=4, seed=0),
                         dropout_prob=p, dropout_seed=seed)

    def test_some_clients_dropped(self):
        import numpy as np
        batches = list(self._loader(0.5))
        per_client = np.concatenate(
            [b["mask"].sum(axis=1) for b in batches])
        assert (per_client == 0).any(), "expected some dropouts"
        assert (per_client > 0).any(), "expected some survivors"

    def test_no_dropout_by_default(self):
        import numpy as np
        batches = list(self._loader(0.0))
        assert all((b["mask"].sum(axis=1) > 0).all() for b in batches)

    def test_deterministic_per_seed(self):
        import numpy as np
        a = [b["mask"] for b in self._loader(0.5, seed=7)]
        b = [b["mask"] for b in self._loader(0.5, seed=7)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_dropped_client_excluded_from_aggregate(self):
        """Engine semantics: zero-mask client contributes nothing and
        the denominator renormalises over survivors."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from commefficient_tpu.config import Config
        from commefficient_tpu.core.rounds import (ClientStates,
                                                   build_client_round)

        d = 6
        cfg = Config(mode="uncompressed", error_type="none",
                     local_momentum=0.0, num_workers=2,
                     local_batch_size=2, num_clients=4,
                     dataset_name="CIFAR10", seed=0)
        cfg.grad_size = d

        def loss(p, batch):
            m = batch["mask"]
            per = jnp.sum(batch["x"] * p[None, :], axis=1)
            return (jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0),
                    (jnp.float32(0.0),))

        fn = jax.jit(build_client_round(cfg, loss, 2))
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 2, d).astype(np.float32))
        p0 = jnp.zeros(d, jnp.float32)
        cs = ClientStates.init(cfg, 4)
        mask_full = jnp.ones((2, 2), jnp.float32)
        mask_drop = jnp.asarray([[1, 1], [0, 0]], jnp.float32)

        agg_drop = fn(p0, cs, {"x": x, "mask": mask_full * mask_drop},
                      jnp.asarray([0, 1], jnp.int32),
                      jax.random.PRNGKey(0), 1.0).aggregated
        agg_solo = fn(p0, cs, {"x": x[:1].repeat(2, 0),
                               "mask": jnp.asarray([[1, 1], [0, 0]],
                                                   jnp.float32)},
                      jnp.asarray([0, 1], jnp.int32),
                      jax.random.PRNGKey(0), 1.0).aggregated
        np.testing.assert_allclose(np.asarray(agg_drop),
                                   np.asarray(agg_solo),
                                   rtol=1e-6, atol=1e-7)

    def test_dropped_client_state_untouched_in_stateful_modes(self):
        """local_topk with momentum+error: a dropped client transmits
        nothing and its velocity/error rows stay exactly as they
        were (without the engine guard it would upload rho*velocity)."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        from commefficient_tpu.config import Config
        from commefficient_tpu.core.rounds import (ClientStates,
                                                   build_client_round)

        d = 6
        cfg = Config(mode="local_topk", error_type="local",
                     local_momentum=0.9, num_workers=2,
                     local_batch_size=2, num_clients=4, k=2,
                     dataset_name="CIFAR10", seed=0)
        cfg.grad_size = d

        def loss(p, batch):
            m = batch["mask"]
            per = jnp.sum(batch["x"] * p[None, :], axis=1)
            return (jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0),
                    (jnp.float32(0.0),))

        fn = jax.jit(build_client_round(cfg, loss, 2))
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 2, d).astype(np.float32))
        cs = ClientStates(
            velocities=jnp.asarray(
                rng.randn(4, d).astype(np.float32)),
            errors=jnp.asarray(rng.randn(4, d).astype(np.float32)),
            weights=None)
        mask = jnp.asarray([[1, 1], [0, 0]], jnp.float32)  # 1 dropped
        res = fn(jnp.zeros(d, jnp.float32), cs,
                 {"x": x, "mask": mask},
                 jnp.asarray([0, 1], jnp.int32),
                 jax.random.PRNGKey(0), 1.0)
        new = res.client_states
        # dropped client 1: state rows bit-identical
        np.testing.assert_array_equal(np.asarray(new.velocities[1]),
                                      np.asarray(cs.velocities[1]))
        np.testing.assert_array_equal(np.asarray(new.errors[1]),
                                      np.asarray(cs.errors[1]))
        # survivor's state DID change
        assert not np.array_equal(np.asarray(new.velocities[0]),
                                  np.asarray(cs.velocities[0]))
        # aggregated == survivor's own top-k transmit / its datapoints
        solo = fn(jnp.zeros(d, jnp.float32), cs,
                  {"x": x, "mask": jnp.asarray([[1, 1], [0, 0]],
                                               jnp.float32)},
                  jnp.asarray([0, 3], jnp.int32),
                  jax.random.PRNGKey(0), 1.0)
        np.testing.assert_allclose(np.asarray(res.aggregated),
                                   np.asarray(solo.aggregated),
                                   rtol=1e-6, atol=1e-7)


class TestNonIidResplitValidation:
    def test_indivisible_client_count_rejected(self):
        """The non-iid re-split divides clients evenly over natural
        partitions; an indivisible --num_clients used to produce a
        short images-per-client vector and crash the sampler with a
        broadcast error mid-epoch (data_per_client is consulted
        lazily, so trigger it directly)."""
        from commefficient_tpu.data.synthetic import FedSynthetic
        ds = FedSynthetic("", "Synthetic", train=True, do_iid=False,
                          num_clients=16, per_class=8, seed=0)
        with pytest.raises(ValueError, match="multiple of 10"):
            ds.data_per_client  # property: the split is computed here

    def test_divisible_count_and_iid_still_work(self):
        from commefficient_tpu.data.synthetic import FedSynthetic
        ds = FedSynthetic("", "Synthetic", train=True, do_iid=False,
                          num_clients=20, per_class=8, seed=0)
        assert len(ds.data_per_client) == 20
        ds_iid = FedSynthetic("", "Synthetic", train=True, do_iid=True,
                              num_clients=16, per_class=8, seed=0)
        assert len(ds_iid.data_per_client) == 16
