"""2-D mesh (clients x seq) federated GPT-2 round vs the dense
single-device oracle: aggregated gradient and loss must match."""

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.core.rounds_sp import (build_sp_gpt2_round,
                                              make_sp_mesh,
                                              shift_lm_labels)
from commefficient_tpu.models.gpt2 import GPT2Config, GPT2DoubleHeads
from commefficient_tpu.ops.vec import flatten_params

IGNORE = -1


def _batch(rng, W, B, N, T, vocab):
    ids = rng.randint(0, vocab, (W, B, N, T)).astype(np.int32)
    tt = rng.randint(0, vocab, (W, B, N, T)).astype(np.int32)
    labels = ids.copy()
    labels[..., : T // 4] = IGNORE  # some ignored context positions
    mc_ids = rng.randint(0, T, (W, B, N)).astype(np.int32)
    mc_labels = rng.randint(0, N, (W, B)).astype(np.int32)
    return {
        "input_ids": jnp.asarray(ids),
        "token_type_ids": jnp.asarray(tt),
        "shifted_labels": shift_lm_labels(jnp.asarray(labels)),
        "mc_token_ids": jnp.asarray(mc_ids),
        "mc_labels": jnp.asarray(mc_labels),
        "mask": jnp.ones((W, B), jnp.float32),
    }


def _dense_oracle(cfg, params, flat, unravel, batch, lm_coef, mc_coef):
    model = GPT2DoubleHeads(cfg)

    def client_loss(f, ids, tt, labels, mc_ids, mc_labels):
        lm_logits, mc_logits = model.apply({"params": unravel(f)},
                                           ids, mc_ids, tt)
        valid = labels != IGNORE
        safe = jnp.where(valid, labels, 0)
        logp = jax.nn.log_softmax(lm_logits)
        nll = -jnp.take_along_axis(logp, safe[..., None],
                                   axis=-1)[..., 0]
        lm = jnp.sum(nll * valid) / jnp.maximum(
            jnp.sum(valid).astype(jnp.float32), 1.0)
        mc_logp = jax.nn.log_softmax(mc_logits, axis=-1)
        mc = jnp.mean(-jnp.take_along_axis(
            mc_logp, mc_labels[..., None], axis=-1)[..., 0])
        return lm_coef * lm + mc_coef * mc

    losses, grads = [], []
    W = batch["input_ids"].shape[0]
    for w in range(W):
        loss, g = jax.value_and_grad(client_loss)(
            flat, batch["input_ids"][w], batch["token_type_ids"][w],
            batch["shifted_labels"][w], batch["mc_token_ids"][w],
            batch["mc_labels"][w])
        losses.append(loss)
        grads.append(g)
    agg = sum(grads) / W
    return agg, sum(losses) / W


def test_sp_round_matches_dense_oracle():
    cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=32,
                     n_layer=2, n_head=2)
    W, B, N, T = 2, 1, 2, 32
    mesh = make_sp_mesh(2, 4)

    dense = GPT2DoubleHeads(cfg)
    rng = np.random.RandomState(0)
    ids0 = jnp.zeros((B, N, T), jnp.int32)
    params = dense.init(jax.random.PRNGKey(0), ids0,
                        jnp.zeros((B, N), jnp.int32), ids0)["params"]
    flat, unravel = flatten_params(params)
    batch = _batch(rng, W, B, N, T, cfg.vocab_size)

    round_fn = jax.jit(build_sp_gpt2_round(cfg, mesh, unravel))
    agg_sp, per_client_sp = round_fn(flat, batch)
    assert per_client_sp.shape == (W,)
    loss_sp = np.asarray(per_client_sp).sum() / W

    agg_ref, loss_ref = _dense_oracle(cfg, params, flat, unravel,
                                      batch, 1.0, 1.0)
    np.testing.assert_allclose(float(loss_sp), float(loss_ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(agg_sp), np.asarray(agg_ref),
                               rtol=5e-4, atol=2e-5)


def test_sp_per_client_losses_match_oracle():
    """Each client's reported loss equals its own dense-oracle loss —
    not a replicated round mean (round-2 review weak #6)."""
    cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=32,
                     n_layer=2, n_head=2)
    W, B, N, T = 2, 1, 2, 32
    mesh = make_sp_mesh(2, 4)
    dense = GPT2DoubleHeads(cfg)
    rng = np.random.RandomState(7)
    ids0 = jnp.zeros((B, N, T), jnp.int32)
    params = dense.init(jax.random.PRNGKey(0), ids0,
                        jnp.zeros((B, N), jnp.int32), ids0)["params"]
    flat, unravel = flatten_params(params)
    batch = _batch(rng, W, B, N, T, cfg.vocab_size)

    round_fn = jax.jit(build_sp_gpt2_round(cfg, mesh, unravel))
    _, per_client_sp = round_fn(flat, batch)
    for w in range(W):
        _, loss_w = _dense_oracle(
            cfg, params, flat, unravel,
            {k: v[w:w + 1] for k, v in batch.items()}, 1.0, 1.0)
        np.testing.assert_allclose(float(per_client_sp[w]),
                                   float(loss_w), rtol=1e-5,
                                   atol=1e-5)


def test_sp_no_full_vocab_logits_buffer():
    """The compiled SP round must not contain the (B·N, T_local, V)
    LM logits tensor: the chunked vocab CE caps the vocab-head buffer
    at one token chunk (round-2 review weak #6)."""
    import re

    cfg = GPT2Config(vocab_size=512, n_positions=64, n_embd=32,
                     n_layer=2, n_head=2)
    W, B, N, T = 2, 1, 2, 64
    mesh = make_sp_mesh(2, 4)
    T_local = T // 4
    dense = GPT2DoubleHeads(cfg)
    rng = np.random.RandomState(5)
    ids0 = jnp.zeros((B, N, T), jnp.int32)
    params = dense.init(jax.random.PRNGKey(0), ids0,
                        jnp.zeros((B, N), jnp.int32), ids0)["params"]
    flat, unravel = flatten_params(params)
    batch = _batch(rng, W, B, N, T, cfg.vocab_size)

    # chunk = 4 tokens per example: any f32 buffer of V columns must
    # have token dim <= 4, never the full local shard of 16
    round_fn = jax.jit(build_sp_gpt2_round(cfg, mesh, unravel,
                                           tokens_per_chunk=4 * B * N))
    text = round_fn.lower(flat, batch).compile().as_text()
    full = re.findall(rf"f32\[[0-9,]*{T_local},{cfg.vocab_size}\]",
                      text)
    assert not full, f"full-shard vocab logits present: {full[:3]}"


def test_sp_tokens_per_chunk_threading(monkeypatch):
    """--tokens_per_chunk reaches the chunked vocab CE: 0 resolves to
    the auto default (256 — the measured memory knee, BENCHMARKS.md SP
    table), an explicit value passes through unchanged (round-3 review
    weak #3: the knee was hard-coded out of reach)."""
    from commefficient_tpu.core import rounds_sp
    from commefficient_tpu.models.gpt2 import lm_nll_sums_chunked

    seen = []

    def capture(h, wte, labels, dtype, ignore_index=-100,
                tokens_per_chunk=1024):
        seen.append(tokens_per_chunk)
        return lm_nll_sums_chunked(h, wte, labels, dtype,
                                   ignore_index=ignore_index,
                                   tokens_per_chunk=tokens_per_chunk)

    monkeypatch.setattr(rounds_sp, "lm_nll_sums_chunked", capture)

    cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=32,
                     n_layer=2, n_head=2)
    W, B, N, T = 2, 1, 2, 32
    mesh = make_sp_mesh(2, 4)
    dense = GPT2DoubleHeads(cfg)
    rng = np.random.RandomState(0)
    ids0 = jnp.zeros((B, N, T), jnp.int32)
    params = dense.init(jax.random.PRNGKey(0), ids0,
                        jnp.zeros((B, N), jnp.int32), ids0)["params"]
    flat, unravel = flatten_params(params)
    batch = _batch(rng, W, B, N, T, cfg.vocab_size)

    ref, _ = jax.jit(build_sp_gpt2_round(cfg, mesh, unravel))(
        flat, batch)
    assert seen and all(c == 256 for c in seen)  # 0 -> auto 256

    seen.clear()
    out, _ = jax.jit(build_sp_gpt2_round(cfg, mesh, unravel,
                                         tokens_per_chunk=8))(
        flat, batch)
    assert seen and all(c == 8 for c in seen)
    # chunking is an evaluation order, not a different objective
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=5e-4, atol=2e-5)


def test_sp_round_ragged_examples():
    """Padded example rows are excluded from loss and gradient."""
    cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=32,
                     n_layer=2, n_head=2)
    W, B, N, T = 2, 2, 2, 32
    mesh = make_sp_mesh(2, 4)
    dense = GPT2DoubleHeads(cfg)
    rng = np.random.RandomState(2)
    ids0 = jnp.zeros((B, N, T), jnp.int32)
    params = dense.init(jax.random.PRNGKey(0), ids0,
                        jnp.zeros((B, N), jnp.int32), ids0)["params"]
    flat, unravel = flatten_params(params)
    batch = _batch(rng, W, B, N, T, cfg.vocab_size)
    # client 1's second example is padding
    batch["mask"] = jnp.asarray([[1.0, 1.0], [1.0, 0.0]], jnp.float32)

    round_fn = jax.jit(build_sp_gpt2_round(cfg, mesh, unravel))
    agg_sp, per_client_sp = round_fn(flat, batch)
    loss_sp = np.asarray(per_client_sp).sum() / W

    # oracle: slice client 1 down to its single real example
    trimmed = {
        "input_ids": [batch["input_ids"][0], batch["input_ids"][1, :1]],
        "token_type_ids": [batch["token_type_ids"][0],
                           batch["token_type_ids"][1, :1]],
        "shifted_labels": [batch["shifted_labels"][0],
                           batch["shifted_labels"][1, :1]],
        "mc_token_ids": [batch["mc_token_ids"][0],
                         batch["mc_token_ids"][1, :1]],
        "mc_labels": [batch["mc_labels"][0], batch["mc_labels"][1, :1]],
    }
    losses, grads = [], []
    for w in range(W):
        one = {k: jnp.asarray(v[w])[None] for k, v in trimmed.items()}
        one["mask"] = jnp.ones((1, one["input_ids"].shape[1]),
                               jnp.float32)
        a, l = _dense_oracle(cfg, params, flat, unravel, one, 1.0, 1.0)
        grads.append(a)
        losses.append(l)
    agg_ref = sum(grads) / W
    np.testing.assert_allclose(float(loss_sp),
                               float(sum(losses) / W),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(agg_sp), np.asarray(agg_ref),
                               rtol=5e-4, atol=2e-5)


def test_sp_round_client_mask():
    """A masked-out client contributes nothing."""
    cfg = GPT2Config(vocab_size=64, n_positions=64, n_embd=32,
                     n_layer=2, n_head=2)
    W, B, N, T = 2, 1, 2, 32
    mesh = make_sp_mesh(2, 4)
    dense = GPT2DoubleHeads(cfg)
    rng = np.random.RandomState(1)
    ids0 = jnp.zeros((B, N, T), jnp.int32)
    params = dense.init(jax.random.PRNGKey(0), ids0,
                        jnp.zeros((B, N), jnp.int32), ids0)["params"]
    flat, unravel = flatten_params(params)
    batch = _batch(rng, W, B, N, T, cfg.vocab_size)
    batch["mask"] = jnp.asarray([[1.0], [0.0]], jnp.float32)

    round_fn = jax.jit(build_sp_gpt2_round(cfg, mesh, unravel))
    agg_sp, per_client_sp = round_fn(flat, batch)
    assert float(per_client_sp[1]) == 0.0  # masked client reports 0

    agg_ref, _ = _dense_oracle(
        cfg, params, flat, unravel,
        {k: v[:1] for k, v in batch.items()}, 1.0, 1.0)
    np.testing.assert_allclose(np.asarray(agg_sp), np.asarray(agg_ref),
                               rtol=5e-4, atol=2e-5)


def test_gpt2_train_cli_seq_devices(tmp_path):
    """Full trainer path with --seq_devices: sequence-parallel client
    rounds feeding the sketch-mode server step."""
    from commefficient_tpu.train import gpt2_train

    results = gpt2_train.main([
        "--test", "--dataset_name", "PERSONA",
        "--dataset_dir", str(tmp_path / "data"),
        "--mode", "sketch", "--error_type", "virtual",
        "--local_momentum", "0", "--virtual_momentum", "0.9",
        "--num_workers", "2", "--local_batch_size", "2",
        "--num_epochs", "1", "--seq_devices", "4",
    ])
    assert len(results) == 1
    assert np.isfinite(results[0]["train_loss"])
    assert np.isfinite(results[0]["val_ppl"])


def test_seq_devices_rejects_local_state_modes(tmp_path):
    from commefficient_tpu.train import gpt2_train
    import pytest as _pytest

    with _pytest.raises(ValueError):
        gpt2_train.main([
            "--test", "--dataset_name", "PERSONA",
            "--dataset_dir", str(tmp_path / "data"),
            "--mode", "local_topk", "--error_type", "local",
            "--num_workers", "2", "--local_batch_size", "2",
            "--num_epochs", "1", "--seq_devices", "4",
        ])
