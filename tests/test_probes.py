"""Schema-v2 probe layer: in-compile diagnostics vs the NumPy mirror
(all five modes, fused / per-client / chunked paths), the alarm
engine's rules and actions, probes-off program identity (the emitted
HLO must not change when probes are off), and the end-to-end ledger
round-trip including the pipelined deferred-attach path."""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import (ClientStates, args2sketch,
                                           build_client_round,
                                           build_server_round)
from commefficient_tpu.core.server import ServerState
from commefficient_tpu.telemetry import Telemetry
from commefficient_tpu.telemetry.alarms import (AlarmEngine,
                                                DivergenceAbort,
                                                build_alarm_engine)

from reference_mirror import MirrorFed


def linear_loss(params_flat, batch):
    pred = batch["x"] @ params_flat
    sq = (pred - batch["y"]) ** 2
    n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
    loss = jnp.sum(sq * batch["mask"]) / n
    return loss, (loss * 0.0 + 1.0,)


def make_cfg(**kw):
    base = dict(mode="uncompressed", local_momentum=0.0,
                virtual_momentum=0.0, weight_decay=0.0,
                error_type="none", num_workers=2, k=3,
                num_rows=5, num_cols=16, num_blocks=1,
                local_batch_size=2, microbatch_size=-1, seed=21)
    base.update(kw)
    return Config(**base)


def _round_data(rng, d, n_per_client=(3, 2)):
    return [(cid, rng.normal(size=(n, d)).astype(np.float64),
             rng.normal(size=(n,)).astype(np.float64))
            for cid, n in enumerate(n_per_client)]


def run_engine_probes(cfg, w0, rounds, lr, num_clients=4):
    """test_modes.run_engine, but with the probed program variants;
    returns one merged client+server probe dict per round."""
    d = len(w0)
    cfg = dataclasses.replace(cfg, grad_size=d)
    B = max(len(y) for rnd in rounds for _, _, y in rnd)
    client_round = jax.jit(build_client_round(
        cfg, linear_loss, B, probes=True, probe_recovery=True))
    server_round = jax.jit(build_server_round(cfg, probes=True))

    ps = jnp.asarray(w0, jnp.float32)
    cs = ClientStates.init(cfg, num_clients, ps)
    ss = ServerState.init(cfg)
    rng = jax.random.PRNGKey(cfg.seed)
    out = []
    for rnd_i, clients in enumerate(rounds):
        W = len(clients)
        x = np.zeros((W, B, d), np.float32)
        y = np.zeros((W, B), np.float32)
        mask = np.zeros((W, B), np.float32)
        ids = np.zeros((W,), np.int32)
        for i, (cid, X, Y) in enumerate(clients):
            n = len(Y)
            x[i, :n], y[i, :n], mask[i, :n], ids[i] = X, Y, 1.0, cid
        batch = {"x": jnp.asarray(x), "y": jnp.asarray(y),
                 "mask": jnp.asarray(mask)}
        res = client_round(ps, cs, batch, jnp.asarray(ids),
                           jax.random.fold_in(rng, rnd_i),
                           jnp.float32(lr))
        cs = res.client_states
        ps, ss, new_vel, _, _, sprobes = server_round(
            ps, ss, res.aggregated, jnp.float32(lr),
            cs.velocities, jnp.asarray(ids))
        if new_vel is not None:
            cs = cs._replace(velocities=new_vel)
        probes = {k: float(v) for k, v in res.probes.items()}
        probes.update({k: float(v) for k, v in sprobes.items()})
        out.append(probes)
    return out


def run_mirror_probes(cfg, w0, rounds, lr, num_clients=4, B=None):
    d = len(w0)
    cfg = dataclasses.replace(cfg, grad_size=d)
    m = MirrorFed(cfg, w0, num_clients, sketch=args2sketch(cfg))
    out = []
    for rnd in rounds:
        if cfg.mode == "fedavg":
            m.round_fedavg(rnd, lr)
        else:
            m.round(rnd, lr, B)
        out.append(dict(m.last_probes))
    return out


# --- probe values vs the NumPy mirror ----------------------------------


FUSED_KEYS = {"agg_norm", "agg_nan", "agg_inf"}
CLIENT_KEYS = FUSED_KEYS | {"client_norm_mean", "client_norm_max",
                            "client_norm_std"}
SERVER_KEYS = {"update_norm", "momentum_norm", "residual_norm"}


@pytest.mark.parametrize("cfg_kw,client_keys,extra", [
    # fused fast path (no per-client transmits): agg probes only
    (dict(mode="sketch", error_type="virtual", virtual_momentum=0.9),
     FUSED_KEYS | {"recovery_error"}, {"mass_coverage"}),
    (dict(mode="true_topk", error_type="virtual",
          virtual_momentum=0.9), FUSED_KEYS, {"mass_coverage"}),
    (dict(mode="uncompressed", virtual_momentum=0.9), FUSED_KEYS,
     set()),
    # per-client vmap path: transmit-norm stats appear
    (dict(mode="uncompressed", local_momentum=0.9), CLIENT_KEYS,
     set()),
    (dict(mode="local_topk", error_type="local", k=2), CLIENT_KEYS,
     set()),
    (dict(mode="fedavg", local_batch_size=-1, fedavg_batch_size=2,
          num_fedavg_epochs=1), CLIENT_KEYS, set()),
    # chunked scan path (sketch-late; microbatching defeats the fused
    # fast path so --client_chunk engages): dense accumulator + one
    # end-of-scan sketch, transmit norms ride the scan outputs
    (dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
          microbatch_size=1, client_chunk=1),
     CLIENT_KEYS | {"recovery_error"}, {"mass_coverage"}),
])
def test_probe_values_match_mirror(cfg_kw, client_keys, extra):
    cfg = make_cfg(**cfg_kw)
    rng = np.random.default_rng(7)
    d = 8
    w0 = rng.normal(size=d)
    rounds = [_round_data(rng, d) for _ in range(3)]
    lr = 0.3
    B = max(len(y) for rnd in rounds for _, _, y in rnd)
    eng = run_engine_probes(cfg, w0, rounds, lr)
    mir = run_mirror_probes(cfg, w0, rounds, lr, B=B)
    for e, m in zip(eng, mir):
        assert set(e) == client_keys | SERVER_KEYS | extra, sorted(e)
        for key in sorted(e):
            np.testing.assert_allclose(
                e[key], m[key], rtol=5e-4, atol=1e-5,
                err_msg=f"probe {key}")


DROPOUT_MODES = [
    # fused fast path under dropout: the WD share must follow the
    # alive-datapoint fraction (core/rounds.py _fused_local)
    (dict(mode="sketch", error_type="virtual", virtual_momentum=0.9),
     FUSED_KEYS | {"recovery_error"}, {"mass_coverage"}),
    (dict(mode="true_topk", error_type="virtual",
          virtual_momentum=0.9), FUSED_KEYS, {"mass_coverage"}),
    (dict(mode="uncompressed", local_momentum=0.9), CLIENT_KEYS,
     set()),
    (dict(mode="local_topk", error_type="local", k=2), CLIENT_KEYS,
     set()),
    (dict(mode="fedavg", local_batch_size=-1, fedavg_batch_size=2,
          num_fedavg_epochs=1), CLIENT_KEYS, set()),
]


@pytest.mark.parametrize("cfg_kw,client_keys,extra", DROPOUT_MODES)
def test_dropout_round_probes_match_mirror(cfg_kw, client_keys, extra):
    """Satellite of the chaos harness: a round with a DEAD slot
    (dropout / loader padding, all-zero mask) must produce the same
    probes as the mirror run over the alive clients only — the dead
    slot contributes nothing to the aggregate (weight decay included)
    and is excluded from the client-norm statistics. All five modes,
    with weight_decay nonzero so the WD share is pinned too."""
    cfg = make_cfg(weight_decay=0.01, dropout_prob=0.5, **cfg_kw)
    rng = np.random.default_rng(11)
    d = 8
    w0 = rng.normal(size=d)
    lr = 0.3
    full = [_round_data(rng, d, (3, 2)) for _ in range(3)]
    # round 1: client 1 is dropped (zero real samples -> all-zero mask)
    dead_cid, dead_X, dead_Y = full[1][1]
    full[1][1] = (dead_cid, dead_X[:0], dead_Y[:0])
    alive_only = [[(c, X, Y) for c, X, Y in rnd if len(Y)]
                  for rnd in full]
    B = max(len(y) for rnd in full for _, _, y in rnd)
    eng = run_engine_probes(cfg, w0, full, lr)
    mir = run_mirror_probes(cfg, w0, alive_only, lr, B=B)
    for e, m in zip(eng, mir):
        assert set(e) == client_keys | SERVER_KEYS | extra, sorted(e)
        for key in sorted(e):
            np.testing.assert_allclose(
                e[key], m[key], rtol=5e-4, atol=1e-5,
                err_msg=f"probe {key}")


@pytest.mark.parametrize("cfg_kw,client_keys,extra", DROPOUT_MODES)
def test_fully_dropped_round_aggregate_is_zero(cfg_kw, client_keys,
                                               extra):
    """Zero-averaging semantics on a FULLY-dropped round: nobody
    trained, so the aggregate must be exactly zero — in particular the
    fused path's analytic weight-decay term must not keep decaying the
    weights when every client's mask is zero."""
    cfg = dataclasses.replace(
        make_cfg(weight_decay=0.01, dropout_prob=0.5, **cfg_kw),
        grad_size=8)
    W, B, d = 2, 3, 8
    client_round = jax.jit(build_client_round(cfg, linear_loss, B))
    rng = np.random.default_rng(13)
    batch = {"x": jnp.asarray(rng.normal(size=(W, B, d)), jnp.float32),
             "y": jnp.asarray(rng.normal(size=(W, B)), jnp.float32),
             "mask": jnp.zeros((W, B), jnp.float32)}
    ps = jnp.asarray(rng.normal(size=d), jnp.float32)
    res = client_round(ps, ClientStates.init(cfg, 4, ps), batch,
                       jnp.arange(W, dtype=jnp.int32),
                       jax.random.PRNGKey(0), jnp.float32(0.3))
    np.testing.assert_array_equal(np.asarray(res.aggregated), 0.0)


def test_recovery_error_is_zero_for_lossless_sketch():
    """A sketch with more bucket capacity than coordinates and
    k >= d recovers exactly -> recovery_error == 0 (up to fp32)."""
    cfg = make_cfg(mode="sketch", error_type="virtual", k=8,
                   num_rows=7, num_cols=64)
    rng = np.random.default_rng(3)
    d = 6
    eng = run_engine_probes(cfg, rng.normal(size=d),
                            [_round_data(rng, d)], 0.3)
    assert eng[0]["recovery_error"] < 1e-5


def test_nan_counts_surface_in_probes():
    cfg = make_cfg(mode="uncompressed")
    rng = np.random.default_rng(5)
    d = 4
    rounds = [_round_data(rng, d)]
    # poison one client's labels: the gradient (hence the aggregate)
    # goes NaN and the probe must count it
    rounds[0][0][2][0] = np.nan
    eng = run_engine_probes(cfg, rng.normal(size=d), rounds, 0.1)
    assert eng[0]["agg_nan"] > 0


# --- probes-off program identity ---------------------------------------


def _lower_text(fn, cfg, d=8, B=3, W=2):
    ps = jax.ShapeDtypeStruct((d,), jnp.float32)
    cs = jax.eval_shape(
        lambda: ClientStates.init(cfg, 4, jnp.zeros((d,), jnp.float32)))
    batch = {"x": jax.ShapeDtypeStruct((W, B, d), jnp.float32),
             "y": jax.ShapeDtypeStruct((W, B), jnp.float32),
             "mask": jax.ShapeDtypeStruct((W, B), jnp.float32)}
    ids = jax.ShapeDtypeStruct((W,), jnp.int32)
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    lr = jax.ShapeDtypeStruct((), jnp.float32)
    return jax.jit(fn).lower(ps, cs, batch, ids, rng, lr).as_text()


@pytest.mark.parametrize("mode,error_type", [
    ("sketch", "virtual"), ("true_topk", "virtual"),
    ("uncompressed", "none")])
def test_probes_off_program_identical(mode, error_type):
    """probes/probe_recovery are trace-time flags: a build without
    them must emit EXACTLY the program of a default build (the no-op
    overhead guarantee), while the probed build differs."""
    cfg = dataclasses.replace(
        make_cfg(mode=mode, error_type=error_type,
                 virtual_momentum=0.9), grad_size=8)
    default = _lower_text(build_client_round(cfg, linear_loss, 3), cfg)
    explicit_off = _lower_text(
        build_client_round(cfg, linear_loss, 3, probes=False,
                           probe_recovery=False), cfg)
    probed = _lower_text(
        build_client_round(cfg, linear_loss, 3, probes=True,
                           probe_recovery=True), cfg)
    assert default == explicit_off
    assert probed != default

    # observability knobs that live entirely on the host — the skew
    # alarm threshold reads trace-derived buckets, never the program —
    # must be invisible to the lowered HLO
    skew_cfg = dataclasses.replace(cfg, alarm_collective_skew=0.5)
    assert _lower_text(build_client_round(skew_cfg, linear_loss, 3),
                       skew_cfg) == default

    # robust-aggregation / chaos-harness knobs at their inert values
    # must be invisible too: --robust_agg none is a trace-time gate,
    # transmit_transform=None (chaos off) is the identical build path,
    # and the checkpoint/alarm cadences are host-only
    inert_cfg = dataclasses.replace(
        cfg, robust_agg="none", robust_trim_frac=0.2,
        robust_clip_norm=5.0, robust_median_groups=2,
        alarm_byzantine_ratio=4.0, alarm_fold_rejection=0.5,
        checkpoint_every_rounds=3, checkpoint_keep=2,
        # asyncfed knobs without --async_buffer_size: the staleness
        # weight and alarm threshold are host/trace-gated and must
        # not perturb a synchronous build
        async_staleness_weight=0.7, alarm_async_staleness=4.0,
        # --overlap_depth 1 is the serial program by construction:
        # none of the chunked-emission branches trace (the HLO
        # fingerprint identity every audit baseline pins on)
        overlap_depth=1,
        # live-operations plane: exporter port, flight-recorder ring,
        # SLO targets, and the burn-rate alarm are all host-side —
        # they observe the round stream, never enter the program
        live_port=1, flightrec_rounds=4, slo_round_p95=0.5,
        slo_staleness_max=2.0, slo_starvation=1.0,
        slo_window=16, slo_fast_window=4, alarm_slo_burn=2.0,
        # causal round tracing is host-side span bookkeeping: the
        # tracer hooks live in telemetry/_Span, never in a traced
        # body (the causal-confinement flowlint rule pins this
        # structurally; this pins the emitted program)
        causal_trace=True)
    assert _lower_text(
        build_client_round(inert_cfg, linear_loss, 3,
                           transmit_transform=None),
        inert_cfg) == default
    # alpha == 0 keeps even a client_weights build's WEIGHTING
    # branch untraced (the staleness arg itself is appended, so the
    # signature — not the fold math — is what differs)
    assert _lower_text(
        build_client_round(cfg, linear_loss, 3, client_weights=False),
        cfg) == default
    # ...while an ACTIVE overlap pipeline (sketch only, and only
    # once a quantized wire gives the chunks something to trace on a
    # single shard) changes the program: per-chunk qdq vs one
    # whole-table qdq
    if mode == "sketch":
        q1_cfg = dataclasses.replace(cfg, sketch_dtype="int8")
        q2_cfg = dataclasses.replace(q1_cfg, overlap_depth=2)
        assert _lower_text(build_client_round(q2_cfg, linear_loss, 3),
                           q2_cfg) != \
            _lower_text(build_client_round(q1_cfg, linear_loss, 3),
                        q1_cfg)
    # an ACTIVE robust fold, by contrast, changes the program
    med_cfg = dataclasses.replace(cfg, robust_agg="median")
    assert _lower_text(build_client_round(med_cfg, linear_loss, 3),
                       med_cfg) != default
    # ...and so does an active staleness-weighted fold
    aw_cfg = dataclasses.replace(cfg, async_buffer_size=2,
                                 async_staleness_weight=0.7)
    aw_round = build_client_round(aw_cfg, linear_loss, 3,
                                  client_weights=True)
    d, B, W = 8, 3, 2
    ps = jax.ShapeDtypeStruct((d,), jnp.float32)
    cs = jax.eval_shape(
        lambda: ClientStates.init(aw_cfg, 4, jnp.zeros((d,),
                                                       jnp.float32)))
    batch = {"x": jax.ShapeDtypeStruct((W, B, d), jnp.float32),
             "y": jax.ShapeDtypeStruct((W, B), jnp.float32),
             "mask": jax.ShapeDtypeStruct((W, B), jnp.float32)}
    assert jax.jit(aw_round).lower(
        ps, cs, batch, jax.ShapeDtypeStruct((W,), jnp.int32),
        jax.ShapeDtypeStruct((2,), jnp.uint32),
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((W,), jnp.float32)).as_text() != default

    def _server_text(sr):
        ps = jax.ShapeDtypeStruct((8,), jnp.float32)
        ss = jax.eval_shape(lambda: ServerState.init(cfg))
        agg = ss.Verror if mode == "sketch" else ps
        lr = jax.ShapeDtypeStruct((), jnp.float32)
        ids = jax.ShapeDtypeStruct((2,), jnp.int32)
        return jax.jit(sr).lower(ps, ss, agg, lr, None, ids).as_text()

    s_default = _server_text(build_server_round(cfg))
    s_off = _server_text(build_server_round(cfg, probes=False))
    s_on = _server_text(build_server_round(cfg, probes=True))
    assert s_default == s_off
    assert s_on != s_off


# --- alarm engine ------------------------------------------------------


def _cfg_alarms(**kw):
    base = dict(probe_every=1, on_divergence="log",
                alarm_residual_ratio=2.0, alarm_residual_rounds=2,
                alarm_recovery_error=0.9)
    base.update(kw)
    return make_cfg(**base)


def test_alarm_nan_inf_fires():
    eng = build_alarm_engine(_cfg_alarms())
    fired = eng.check(0, {"agg_nan": 0.0, "agg_inf": 0.0})
    assert fired == []
    fired = eng.check(1, {"agg_nan": 2.0, "agg_inf": 0.0})
    assert [a["rule"] for a in fired] == ["nan_inf"]


def test_alarm_residual_growth_needs_consecutive_rounds():
    eng = build_alarm_engine(_cfg_alarms())
    assert eng.check(0, {"residual_growth": 3.0}) == []  # 1st breach
    fired = eng.check(1, {"residual_growth": 3.0})      # 2nd: fires
    assert [a["rule"] for a in fired] == ["residual_growth"]
    # a healthy round resets the streak
    eng2 = build_alarm_engine(_cfg_alarms())
    eng2.check(0, {"residual_growth": 3.0})
    eng2.check(1, {"residual_growth": 1.0})
    assert eng2.check(2, {"residual_growth": 3.0}) == []


def test_alarm_recovery_error_fires():
    eng = build_alarm_engine(_cfg_alarms())
    assert eng.check(0, {"recovery_error": 0.5}) == []
    fired = eng.check(1, {"recovery_error": 0.95})
    assert [a["rule"] for a in fired] == ["recovery_error"]


def test_alarm_abort_raises_after_flagging():
    eng = build_alarm_engine(_cfg_alarms(on_divergence="abort"))
    with pytest.raises(DivergenceAbort) as exc:
        eng.check(4, {"agg_nan": 1.0})
    assert exc.value.round_index == 4
    assert "nan_inf" in str(exc.value)


def test_alarm_flags_ledger_record(tmp_path):
    from commefficient_tpu.telemetry.sinks import JSONLSink
    path = str(tmp_path / "run.jsonl")
    tel = Telemetry([JSONLSink(path)])
    tel.begin_round(0)
    eng = AlarmEngine(_cfg_alarms(on_divergence="ledger-flag"),
                      telemetry=tel)
    eng.check(0, {"agg_inf": 3.0})
    tel.merge_round_probes(0, {"agg_inf": 3.0})
    tel.set_round_bytes(0, 1.0, 1.0)
    tel.close()
    with open(path) as f:
        rec = json.loads(f.readline())
    assert rec["alarms"] and rec["alarms"][0]["rule"] == "nan_inf"
    assert rec["probes"]["agg_inf"] == 3.0


def test_alarm_engine_off_without_probes():
    assert build_alarm_engine(make_cfg(probe_every=0)) is None


# --- disabled-telemetry fast path covers the new v2 calls --------------


def test_disabled_telemetry_probe_calls_are_noop():
    tel = Telemetry()
    assert not tel.enabled
    tel.merge_round_probes(0, {"agg_norm": 1.0})
    tel.flag_alarm(0, {"rule": "nan_inf"})
    assert not tel._records and tel._current is None


# --- end-to-end ledger round-trip (cv trainer) -------------------------


def _probe_rounds(path):
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    return [r for r in recs if r["kind"] == "round"]


def _cv_args(**kw):
    args = ["--test", "--dataset_name", "Synthetic",
            "--mode", "sketch", "--error_type", "virtual",
            "--local_momentum", "0", "--virtual_momentum", "0.9",
            "--num_clients", "10", "--num_workers", "2",
            "--local_batch_size", "4", "--num_epochs", "2",
            "--lr_scale", "0.1", "--pivot_epoch", "1", "--seed", "5"]
    for key, val in kw.items():
        args += [f"--{key}"] + ([] if val is None else [str(val)])
    return args


def test_probed_run_emits_v2_ledger(tmp_path):
    """Probe fields (introduced in schema v2) on a live ledger —
    records are stamped with the current schema (v3 since the
    device-time fields landed)."""
    from commefficient_tpu.telemetry.record import \
        LEDGER_SCHEMA_VERSION
    from commefficient_tpu.train import cv_train
    path = str(tmp_path / "run.jsonl")
    cv_train.main(_cv_args(probe_every=1, ledger=path))
    rounds = _probe_rounds(path)
    assert rounds
    for r in rounds:
        assert r["schema"] == LEDGER_SCHEMA_VERSION
        assert r["schema"] >= 2
        pr = r["probes"]
        for key in ("agg_norm", "agg_nan", "agg_inf", "update_norm",
                    "momentum_norm", "residual_norm", "mass_coverage",
                    "recovery_error"):
            assert np.isfinite(pr[key]), key
    # residual growth ratio needs two rounds of history
    assert "residual_growth" in rounds[-1]["probes"]


def test_pipelined_probes_match_sync(tmp_path):
    """--pipeline_depth defers probe materialisation to the flush
    replay (device arrays parked in _probe_log); the attached values
    must equal the synchronous run's bit for bit."""
    from commefficient_tpu.train import cv_train
    a, b = str(tmp_path / "sync.jsonl"), str(tmp_path / "piped.jsonl")
    cv_train.main(_cv_args(probe_every=1, ledger=a))
    cv_train.main(_cv_args(probe_every=1, ledger=b,
                           pipeline_depth=4))
    ra, rb = _probe_rounds(a), _probe_rounds(b)
    assert len(ra) == len(rb) and len(ra) > 0
    for x, y in zip(ra, rb):
        assert x["probes"] == y["probes"]


def test_divergence_abort_stops_run_and_flags_ledger(tmp_path):
    """A diverging run (astronomical lr -> NaN aggregate) under
    --on_divergence abort must stop at the offending round, and that
    round's record must carry the nan_inf alarm."""
    from commefficient_tpu.train import cv_train
    path = str(tmp_path / "abort.jsonl")
    results = cv_train.main(
        _cv_args(mode="uncompressed", error_type="none",
                 num_epochs="3", lr_scale="1e18",
                 probe_every=1, on_divergence="abort", ledger=path))
    # epoch 3 aborted mid-flight: its row never lands
    assert len(results) < 3
    rounds = _probe_rounds(path)
    last = rounds[-1]
    assert last["alarms"], "aborting round must be ledger-flagged"
    assert last["alarms"][-1]["rule"] == "nan_inf"
    assert last["alarms"][-1]["action"] == "abort"
    assert last["probes"]["agg_nan"] + last["probes"]["agg_inf"] > 0
