"""Tree-space sketching: the fused sketch round without the flat
gradient (VERDICT round-3 task #3 — attack the d-bound flat-vector
constant).

Contract under test: ``CountSketch.sketch_from_leaves(leaves)`` is
bit-identical to ``sketch(ravel-concat(leaves))``, and the tree-primal
fused client round (build_client_round with tree_loss/unravel) produces
the same aggregated table, metrics, and server trajectory as the
flat-primal path it replaces.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import Config
from commefficient_tpu.ops.sketch import CountSketch
from commefficient_tpu.ops.vec import flatten_params


def _leaf_tree(seed, shapes):
    rng = np.random.RandomState(seed)
    return {f"l{i}": jnp.asarray(rng.randn(*s), jnp.float32)
            for i, s in enumerate(shapes)}


SHAPES = [(7, 13), (64,), (3, 5, 11), (257,), (2, 2)]


class TestSketchFromLeaves:
    def test_matches_flat_sketch_exactly(self):
        tree = _leaf_tree(0, SHAPES)
        flat, _ = flatten_params(tree)
        cs = CountSketch(d=int(flat.size), c=128, r=3, backend="xla")
        t_flat = cs.sketch(flat)
        t_tree = cs.sketch_from_leaves(jax.tree_util.tree_leaves(tree))
        np.testing.assert_array_equal(np.asarray(t_flat),
                                      np.asarray(t_tree))

    def test_matches_under_pallas_interpret(self):
        tree = _leaf_tree(1, SHAPES)
        flat, _ = flatten_params(tree)
        cs = CountSketch(d=int(flat.size), c=256, r=3,
                         backend="pallas_interpret")
        t_flat = cs.sketch(flat)
        t_tree = cs.sketch_from_leaves(jax.tree_util.tree_leaves(tree))
        np.testing.assert_array_equal(np.asarray(t_flat),
                                      np.asarray(t_tree))

    def test_matches_on_scan_path(self):
        # m = ceil(d/c) > _UNROLL_LIMIT takes the chunk-scan kernel;
        # the leaf assembly must agree there too
        from commefficient_tpu.ops.sketch import _UNROLL_LIMIT
        c = 32
        d = (_UNROLL_LIMIT + 5) * c + 7
        rng = np.random.RandomState(9)
        sizes = []
        left = d
        while left > 0:
            n = min(left, int(rng.randint(1, 4000)))
            sizes.append((n,))
            left -= n
        tree = _leaf_tree(9, sizes)
        flat, _ = flatten_params(tree)
        assert flat.size == d
        cs = CountSketch(d=d, c=c, r=3, backend="xla")
        np.testing.assert_array_equal(
            np.asarray(cs.sketch(flat)),
            np.asarray(cs.sketch_from_leaves(
                jax.tree_util.tree_leaves(tree))))

    def test_wrong_total_size_raises(self):
        tree = _leaf_tree(2, [(4, 4)])
        cs = CountSketch(d=99, c=64, r=2, backend="xla")
        with pytest.raises(AssertionError):
            cs.sketch_from_leaves(jax.tree_util.tree_leaves(tree))


class TestPaddedEstimates:
    @pytest.mark.parametrize("backend", ["xla", "pallas_interpret"])
    def test_padded_estimates_zero_tail_same_head(self, backend):
        d, c, r = 1000, 128, 3  # padded_d = 1024 > d
        rng = np.random.RandomState(3)
        v = jnp.asarray(rng.randn(d), jnp.float32)
        cs = CountSketch(d=d, c=c, r=r, backend=backend)
        table = cs.sketch(v)
        est = cs.estimates(table)
        est_p = cs.estimates(table, padded=True)
        assert est_p.shape == (cs._padded_d,)
        np.testing.assert_array_equal(np.asarray(est_p[:d]),
                                      np.asarray(est))
        np.testing.assert_array_equal(np.asarray(est_p[d:]),
                                      np.zeros(cs._padded_d - d))

    def test_unsketch_selection_unchanged_by_padding(self):
        # big_d gate is 1<<20 — too big for a unit test, so check the
        # invariant directly: selection over the tail-zeroed padded
        # estimates equals selection over the sliced estimates
        from commefficient_tpu.ops.topk import threshold_topk_indices
        d, c, r, k = 1000, 128, 3, 25
        rng = np.random.RandomState(4)
        v = np.zeros(d, np.float32)
        hot = rng.choice(d, 40, replace=False)
        v[hot] = rng.randn(40) * 10
        cs = CountSketch(d=d, c=c, r=r, backend="xla")
        table = cs.sketch(jnp.asarray(v))
        est = cs.estimates(table)
        est_p = cs.estimates(table, padded=True)
        idx = threshold_topk_indices(jax.lax.square(est), k)
        idx_p = threshold_topk_indices(jax.lax.square(est_p), k)
        np.testing.assert_array_equal(np.sort(np.asarray(idx)),
                                      np.sort(np.asarray(idx_p)))


def _round_pair(cfg, W=4, B=3, D=40):
    """Build flat-primal and tree-primal fused client rounds over the
    same tiny linear model and batch."""
    rng = np.random.RandomState(7)
    tree = {"w": jnp.asarray(rng.randn(D, 4), jnp.float32),
            "b": jnp.asarray(rng.randn(4), jnp.float32)}
    flat, unravel = flatten_params(tree)
    cfg.grad_size = int(flat.size)

    def tree_loss(p, batch):
        logits = batch["x"] @ p["w"] + p["b"]
        per = jnp.sum((logits - batch["y"]) ** 2, axis=-1)
        loss = jnp.sum(per * batch["mask"]) / jnp.maximum(
            jnp.sum(batch["mask"]), 1.0)
        return loss, (loss * 0.5,)

    def flat_loss(p, batch):
        return tree_loss(unravel(p), batch)

    batch = {
        "x": jnp.asarray(rng.randn(W, B, D), jnp.float32),
        "y": jnp.asarray(rng.randn(W, B, 4), jnp.float32),
        "mask": jnp.ones((W, B), jnp.float32),
    }
    return flat, unravel, flat_loss, tree_loss, batch


class TestTreePrimalFusedRound:
    def test_same_table_and_metrics(self):
        from commefficient_tpu.core.rounds import (ClientStates,
                                                   build_client_round)
        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9,
                     weight_decay=5e-4, num_workers=4,
                     local_batch_size=3, k=10, num_cols=64, num_rows=3,
                     dataset_name="CIFAR10", seed=0)
        flat, unravel, flat_loss, tree_loss, batch = _round_pair(cfg)
        cs = ClientStates(None, None, None)
        ids = jnp.arange(4, dtype=jnp.int32)
        key = jax.random.PRNGKey(0)

        r_flat = build_client_round(cfg, flat_loss, 3)(
            flat, cs, batch, ids, key)
        r_tree = build_client_round(cfg, flat_loss, 3,
                                    tree_loss=tree_loss,
                                    unravel=unravel)(
            flat, cs, batch, ids, key)
        np.testing.assert_allclose(np.asarray(r_flat.aggregated),
                                   np.asarray(r_tree.aggregated),
                                   rtol=1e-6, atol=1e-7)
        for mf, mt in zip(r_flat.metrics, r_tree.metrics):
            np.testing.assert_allclose(np.asarray(mf), np.asarray(mt),
                                       rtol=1e-6)

    def test_same_trajectory_through_server(self):
        from commefficient_tpu.core.rounds import (ClientStates,
                                                   build_client_round,
                                                   build_server_round)
        from commefficient_tpu.core.server import ServerState
        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9,
                     weight_decay=0.0, num_workers=4,
                     local_batch_size=3, k=10, num_cols=64, num_rows=3,
                     dataset_name="CIFAR10", seed=0)
        flat, unravel, flat_loss, tree_loss, batch = _round_pair(cfg)
        cs = ClientStates(None, None, None)
        ids = jnp.arange(4, dtype=jnp.int32)

        def run(client_round):
            ps = flat
            ss = ServerState.init(cfg)
            server = build_server_round(cfg)
            for r in range(3):
                res = client_round(ps, cs, batch, ids,
                                   jax.random.PRNGKey(r))
                ps, ss, _, _, _ = server(ps, ss, res.aggregated,
                                         jnp.float32(0.05))
            return np.asarray(ps)

        ps_flat = run(build_client_round(cfg, flat_loss, 3))
        ps_tree = run(build_client_round(cfg, flat_loss, 3,
                                         tree_loss=tree_loss,
                                         unravel=unravel))
        np.testing.assert_allclose(ps_flat, ps_tree,
                                   rtol=1e-6, atol=1e-7)

    def test_mesh_tree_path_matches_single_device(self, devices):
        from jax.sharding import Mesh
        from commefficient_tpu.core.rounds import (ClientStates,
                                                   build_client_round)
        from commefficient_tpu.parallel.mesh import CLIENT_AXIS
        cfg = Config(mode="sketch", error_type="virtual",
                     local_momentum=0.0, virtual_momentum=0.9,
                     weight_decay=5e-4, num_workers=8,
                     local_batch_size=3, k=10, num_cols=64, num_rows=3,
                     dataset_name="CIFAR10", seed=0)
        flat, unravel, flat_loss, tree_loss, batch = _round_pair(
            cfg, W=8)
        cs = ClientStates(None, None, None)
        ids = jnp.arange(8, dtype=jnp.int32)
        key = jax.random.PRNGKey(0)
        mesh = Mesh(np.asarray(devices), (CLIENT_AXIS,))

        r_one = build_client_round(cfg, flat_loss, 3,
                                   tree_loss=tree_loss,
                                   unravel=unravel)(
            flat, cs, batch, ids, key)
        r_mesh = build_client_round(cfg, flat_loss, 3, mesh=mesh,
                                    tree_loss=tree_loss,
                                    unravel=unravel)(
            flat, cs, batch, ids, key)
        np.testing.assert_allclose(np.asarray(r_one.aggregated),
                                   np.asarray(r_mesh.aggregated),
                                   rtol=1e-5, atol=1e-6)
