"""Two-process multi-controller smoke (see scripts/multihost_smoke.py).

Five scenarios over a mesh spanning two localhost CPU processes, the
multi-controller runtime joined through the trainers' own pod CLI
flags: (1) cv_train sketch with the per-round psum crossing the
process boundary, (2) local_topk with per-client state rows sharded
ACROSS processes, (3) a save→kill→resume checkpoint round-trip
asserting bit-equal metrics against the uninterrupted run, (4) the
GPT-2 trainer (sketch round + sharded validation), (5) the GPT-2
trainer with --seq_devices spanning BOTH processes — ring attention's
ppermute crosses the process boundary (the pod user's DCN sequence
sharding). Cross-process metric identity is asserted for every
scenario — the moral equivalent of the reference's localhost NCCL
topology (fed_aggregator.py:161-165).
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_two_process_trainer_smoke():
    script = os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "multihost_smoke.py")
    env = dict(os.environ)
    # the launcher sets JAX_PLATFORMS/XLA_FLAGS for its workers; it
    # needs no devices itself
    out = subprocess.run(
        [sys.executable, os.path.abspath(script)], env=env,
        capture_output=True, text=True, timeout=1500)
    assert out.returncode == 0, out.stderr[-4000:]
    assert "MULTIHOST_OK" in out.stdout


@pytest.mark.slow
def test_two_process_clientstore_shards():
    """Shard-per-process client store: ownership by client-id block,
    allgather-sum row exchange, bit-equality with the device placement
    on the spanning mesh, and the side-shard checkpoint round-trip
    (see scripts/clientstore_multihost.py)."""
    script = os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "clientstore_multihost.py")
    out = subprocess.run(
        [sys.executable, os.path.abspath(script)],
        env=dict(os.environ), capture_output=True, text=True,
        timeout=900)
    if out.returncode == 3:
        pytest.skip("CPU backend lacks multiprocess computations")
    assert out.returncode == 0, out.stderr[-4000:]
    assert "CLIENTSTORE_MULTIHOST_OK" in out.stdout
