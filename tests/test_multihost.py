"""Two-process multi-controller smoke (see scripts/multihost_smoke.py).

Exercises ``initialize_multihost`` for real: two localhost CPU
processes join one JAX runtime, the mesh spans both, and a short
synthetic ``cv_train`` runs one-round-per-epoch SPMD with the
per-round psum crossing the process boundary — the moral equivalent of
the reference's localhost NCCL topology (fed_aggregator.py:161-165).
"""

import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_two_process_trainer_smoke():
    script = os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "multihost_smoke.py")
    env = dict(os.environ)
    # the launcher sets JAX_PLATFORMS/XLA_FLAGS for its workers; it
    # needs no devices itself
    out = subprocess.run(
        [sys.executable, os.path.abspath(script)], env=env,
        capture_output=True, text=True, timeout=1500)
    assert out.returncode == 0, out.stderr[-4000:]
    assert "MULTIHOST_OK" in out.stdout
