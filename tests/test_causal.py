"""Causal round tracing (telemetry/causal.py + critpath.py): the
deterministic id scheme, golden-DAG critical-path attribution (the
buckets-sum-to-wall invariant is exact by construction), the tracer
lifecycle on a real CPU FedModel run (and the zero-ledger-field off
mode), cross-process/cross-job stitching through ledger_merge
including torn-tail shards, the flight recorder's critical-path diff
on latency alarms + the postmortem render, fedwatch's crit column on
both the scrape and ledger paths, the --critpath report, and the
causal-confinement flowlint rule."""

import dataclasses
import importlib.util
import json
import os
import textwrap
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.config import Config
from commefficient_tpu.telemetry.causal import (BUCKETS, SEQ_GRANT,
                                                SEQ_ROOT,
                                                CausalTracer,
                                                assemble_traces,
                                                bucket_of,
                                                build_causal_tracer,
                                                span_id, trace_id)
from commefficient_tpu.telemetry.critpath import (CLOCK_TOLERANCE,
                                                  critical_path,
                                                  critpath_diff,
                                                  dominant_bucket,
                                                  median_buckets)
from commefficient_tpu.telemetry.record import (make_round_record,
                                                validate_record)

W, B, DIM = 8, 2, 64


def _load_script(name):
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- deterministic ids -------------------------------------------------


def test_ids_are_pure_functions_of_job_round_seq():
    """Both sides of a process boundary mint the same ids with no
    handshake — the whole stitch protocol."""
    assert trace_id(None, 3) == "jsolo.r3"
    assert trace_id(0, 3) == "j0.r3"
    assert trace_id("service", 12) == "jservice.r12"
    assert span_id(2, 5, SEQ_GRANT) == "j2.r5.s2"
    assert span_id(None, 0, SEQ_ROOT) == "jsolo.r0.s0"
    # stability across calls (no clock / RNG component)
    assert trace_id(7, 9) == trace_id(7, 9)


def test_bucket_map_is_total():
    assert bucket_of("h2d") == "h2d"
    assert bucket_of("sched_grant") == "sched_wait"
    assert bucket_of("checkpoint") == "flush"
    # unknown span names can never silently inflate a named bucket
    assert bucket_of("brand_new_phase") == "host_other"
    assert BUCKETS[-1] == "host_other"


# --- golden DAGs: the attribution invariant ----------------------------


def _gspan(seq, name, b, e, parent_seq=SEQ_ROOT, job=None, r=0):
    return {"id": span_id(job, r, seq),
            "parent": None if parent_seq is None
            else span_id(job, r, parent_seq),
            "name": name, "bucket": bucket_of(name),
            "b": float(b), "e": float(e)}


def _golden_stamp():
    """Root [0,10] with gather [1,3], h2d [3,4], dispatch [4,8]
    (nesting a collective [6,7]), flush [8,9.5]. Hand-computed:
    host_gather 2, h2d 1, compute 3 (dispatch minus its collective),
    collective_exposed 1, flush 1.5, host_other 1.5 (root gaps
    [0,1] + [9.5,10])."""
    spans = [_gspan(SEQ_ROOT, "round", 0, 10, parent_seq=None),
             _gspan(8, "gather", 1, 3),
             _gspan(9, "h2d", 3, 4),
             _gspan(10, "round_dispatch", 4, 8),
             _gspan(11, "collective", 6, 7, parent_seq=10),
             _gspan(12, "flush", 8, 9.5)]
    spans[0]["bucket"] = "host_other"
    return {"trace": trace_id(None, 0), "job": None, "round": 0,
            "wall": 10.0, "spans": spans}


def test_golden_dag_attribution_is_exact():
    crit = critical_path(_golden_stamp())
    assert crit["round"] == 0 and crit["wall"] == 10.0
    want = {"sched_wait": 0.0, "arrival_wait": 0.0,
            "host_gather": 2.0, "h2d": 1.0, "compute": 3.0,
            "collective_exposed": 1.0, "writeback": 0.0,
            "flush": 1.5, "host_other": 1.5}
    assert crit["buckets"] == pytest.approx(want)
    # the invariant is exact, not approximate
    assert sum(crit["buckets"].values()) == crit["wall"]
    assert dominant_bucket(crit) == ("compute", pytest.approx(0.3))


def test_golden_dag_clips_overlap_and_overrun():
    """A sibling overlapping an earlier child is clipped to the
    uncovered remainder; a child overrunning the root is clipped to
    the root end — the invariant survives dirty timestamps."""
    spans = [_gspan(SEQ_ROOT, "round", 0, 10, parent_seq=None),
             _gspan(8, "gather", 1, 6),
             _gspan(9, "h2d", 4, 5),       # fully inside gather
             _gspan(10, "flush", 8, 12)]   # overruns the root
    spans[0]["bucket"] = "host_other"
    crit = critical_path({"trace": "jsolo.r0", "round": 0,
                          "wall": 10.0, "spans": spans})
    assert crit["buckets"]["host_gather"] == pytest.approx(5.0)
    assert crit["buckets"]["h2d"] == pytest.approx(0.0)
    assert crit["buckets"]["flush"] == pytest.approx(2.0)
    assert sum(crit["buckets"].values()) == crit["wall"] == 10.0


def test_device_time_overlay_moves_only_exposed_collective():
    """per_device collective minus overlapped, clipped to the compute
    bucket, migrates compute -> collective_exposed; totals hold."""
    dt = {"per_device": [{"collective_s": 2.0, "overlapped_s": 1.5}]}
    crit = critical_path(_golden_stamp(), dt)
    assert crit["buckets"]["compute"] == pytest.approx(2.5)
    assert crit["buckets"]["collective_exposed"] == pytest.approx(1.5)
    assert sum(crit["buckets"].values()) == crit["wall"]
    # fully-hidden collective moves nothing
    dt = {"per_device": [{"collective_s": 1.0, "overlapped_s": 3.0}]}
    crit = critical_path(_golden_stamp(), dt)
    assert crit["buckets"]["compute"] == pytest.approx(3.0)
    # exposure can never exceed what compute actually covered
    dt = {"per_device": {"collective_s": 99.0, "overlapped_s": 0.0}}
    crit = critical_path(_golden_stamp(), dt)
    assert crit["buckets"]["compute"] == pytest.approx(0.0)
    assert crit["buckets"]["collective_exposed"] == pytest.approx(4.0)
    assert sum(crit["buckets"].values()) == crit["wall"]


def test_critpath_diff_and_median():
    def crit(compute, h2d, r):
        b = {k: 0.0 for k in BUCKETS}
        b["compute"], b["h2d"] = compute, h2d
        return {"round": r, "wall": compute + h2d, "buckets": b}

    base = median_buckets([crit(1.0, 0.1, 0), crit(1.2, 0.1, 1),
                           crit(1.4, 0.3, 2)])
    assert base["compute"] == pytest.approx(1.2)
    assert base["h2d"] == pytest.approx(0.1)
    d = critpath_diff(crit(3.0, 0.1, 3), base)
    assert d["round"] == 3 and d["wall"] == pytest.approx(3.1)
    assert d["base_wall"] == pytest.approx(1.3)
    # rows sorted by absolute growth; ratio None when the median is 0
    assert d["rows"][0]["bucket"] == "compute"
    assert d["rows"][0]["delta_s"] == pytest.approx(1.8)
    assert d["rows"][0]["ratio"] == pytest.approx(2.5)
    flush_row = next(r for r in d["rows"] if r["bucket"] == "flush")
    assert flush_row["ratio"] is None
    assert median_buckets([]) is None
    assert critpath_diff(None, base) is None


def test_critical_path_rejects_unusable_stamps():
    assert critical_path(None) is None
    assert critical_path({"spans": []}) is None
    # a foreign span (trace override) is never picked as the root
    grant = _gspan(SEQ_GRANT, "sched_grant", 0, 1, parent_seq=None)
    grant["trace"] = "j0.r0"
    assert critical_path({"spans": [grant]}) is None


# --- tracer lifecycle --------------------------------------------------


def test_tracer_nests_and_stamps():
    t = CausalTracer(job=4)
    assert t.end_round() is None    # no round open
    t.begin_round(2)
    with t.span("gather"):
        pass
    with t.span("round_dispatch"):
        with t.span("collective"):
            pass
    stamp = t.end_round()
    assert stamp["trace"] == "j4.r2" and stamp["round"] == 2
    by_name = {s["name"]: s for s in stamp["spans"]}
    root = by_name["round"]
    assert root["id"] == span_id(4, 2, SEQ_ROOT)
    assert root["parent"] is None
    assert by_name["gather"]["parent"] == root["id"]
    # nesting: the inner span's parent is the enclosing span
    assert by_name["collective"]["parent"] == \
        by_name["round_dispatch"]["id"]
    crit = critical_path(stamp)
    assert abs(sum(crit["buckets"].values()) - crit["wall"]) \
        <= CLOCK_TOLERANCE
    # the stamp validates as a v7 causal payload on a round record
    rec = make_round_record(2)
    rec["causal"] = stamp
    assert validate_record(rec) == []


def test_tracer_ignores_non_owner_threads():
    """Prefetch workers can't corrupt the owner's open stack — spans
    from other threads are dropped, not misfiled."""
    t = CausalTracer()
    t.begin_round(0)
    worker = threading.Thread(target=lambda: t.open("gather"))
    worker.start()
    worker.join()
    stamp = t.end_round()
    assert [s["name"] for s in stamp["spans"]] == ["round"]


def test_foreign_spans_ride_next_round_and_stitch():
    """A daemon-minted grant buffers until the daemon's next round
    record and lands in the TENANT trace at stitch time, parented
    onto the tenant's deterministic root id — zero orphans."""
    svc = CausalTracer(job="service")
    svc.begin_round(0)
    svc.add_event("sched_grant", 1.0, 2.0, trace=trace_id(0, 5),
                  sid=span_id(0, 5, SEQ_GRANT),
                  parent=span_id(0, 5, SEQ_ROOT))
    svc_stamp = svc.end_round()

    tenant = CausalTracer(job=0)
    tenant.begin_round(5)
    with tenant.span("h2d"):
        pass
    ten_stamp = tenant.end_round()

    traces = assemble_traces([{"kind": "round", "causal": svc_stamp},
                              {"kind": "round",
                               "causal": ten_stamp}])
    t = traces["j0.r5"]
    assert t["orphans"] == []
    assert span_id(0, 5, SEQ_GRANT) in t["spans"]
    assert t["round"] == 5
    # a genuinely missing parent IS reported
    lone = {"trace": "j9.r9", "round": 9, "wall": 0.0,
            "spans": [_gspan(8, "h2d", 0, 1, job=9, r=9)]}
    orphan = assemble_traces([{"kind": "round", "causal": lone}])
    assert orphan["j9.r9"]["orphans"] == [span_id(9, 9, 8)]


def test_build_causal_tracer_gates_on_flag():
    assert build_causal_tracer(Config()) is None
    t = build_causal_tracer(Config(causal_trace=True), job=3)
    assert isinstance(t, CausalTracer) and t.job == 3


def test_schema_v7_validation():
    rec = make_round_record(0)
    assert "causal" not in rec      # off mode adds ZERO fields
    assert validate_record(rec) == []
    rec["causal"] = {"trace": "jsolo.r0", "round": 0, "wall": 1.0,
                     "spans": "nope"}
    assert any("spans" in p for p in validate_record(rec))
    rec["causal"] = {"trace": "jsolo.r0", "round": 0, "wall": 1.0,
                     "spans": [{"id": "x"}]}
    assert validate_record(rec) != []


# --- real CPU runs: solo on/off and the daemon stitch ------------------


def _loss(params, batch, cfg):
    pred = batch["x"] @ params["w"]
    n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
    l = jnp.sum((pred - batch["y"]) ** 2 * batch["mask"]) / n
    return l, (l * 0.0 + 1.0,)


def _job_cfg(seed, ledger="", **kw):
    base = dict(mode="local_topk", error_type="local",
                local_momentum=0.9, virtual_momentum=0.0, k=8,
                num_workers=W, local_batch_size=B, num_clients=64,
                seed=seed, ledger=ledger)
    base.update(kw)
    return Config(**base)


def _builder(cfg, mesh):
    from commefficient_tpu.runtime.fed_model import (FedModel,
                                                     FedOptimizer)
    model = FedModel(None, {"w": jnp.zeros((DIM,), jnp.float32)},
                     _loss, cfg, padded_batch_size=B, mesh=mesh)
    opt = FedOptimizer([{"lr": 0.25}], cfg, model=model)
    return model, opt


def _batches(seed, n):
    rng = np.random.RandomState(seed)
    return [
        {"client_ids": rng.choice(64, W, replace=False)
         .astype(np.int32),
         "x": jnp.asarray(rng.randn(W, B, DIM), jnp.float32),
         "y": jnp.asarray(rng.randn(W, B), jnp.float32),
         "mask": jnp.ones((W, B), jnp.float32)}
        for _ in range(n)]


def _solo(seed, rounds, ledger, **cfg_kw):
    model, opt = _builder(_job_cfg(seed, ledger, **cfg_kw), None)
    for batch in _batches(7, rounds):
        model(batch)
        opt.step()
    model.finalize()
    return [json.loads(line) for line in open(ledger)]


class TestRealRuns:
    def test_traced_solo_run_stamps_every_round(self, tmp_path):
        recs = _solo(3, 3, str(tmp_path / "on.jsonl"),
                     causal_trace=True)
        rounds = [r for r in recs if r.get("kind") == "round"]
        assert len(rounds) == 3
        for rec in rounds:
            assert validate_record(rec) == []
            crit = critical_path(rec["causal"],
                                 rec.get("device_time"))
            assert crit["round"] == rec["round"]
            assert abs(sum(crit["buckets"].values())
                       - crit["wall"]) <= CLOCK_TOLERANCE
        traces = assemble_traces(recs)
        assert sorted(traces) == ["jsolo.r0", "jsolo.r1", "jsolo.r2"]
        assert all(not t["orphans"] for t in traces.values())

    def test_off_mode_adds_zero_ledger_fields(self, tmp_path):
        on = _solo(3, 2, str(tmp_path / "on.jsonl"),
                   causal_trace=True)
        off = _solo(3, 2, str(tmp_path / "off.jsonl"))
        for rec in off:
            assert "causal" not in rec
        # on-mode adds EXACTLY the one stamp, nothing else
        on_r = [r for r in on if r.get("kind") == "round"]
        off_r = [r for r in off if r.get("kind") == "round"]
        assert [set(a) - set(b) for a, b in zip(on_r, off_r)] \
            == [{"causal"}, {"causal"}]

    def test_daemon_grants_stitch_into_tenant_traces(self, tmp_path):
        from commefficient_tpu.fedservice import FedService, JobSpec
        R = 2
        led = str(tmp_path / "svc.jsonl")
        svc = FedService(Config(num_workers=W, local_batch_size=B,
                                num_clients=64, ledger=led,
                                causal_trace=True))
        bs = [_batches(7, R), _batches(9, R)]
        svc.admit(JobSpec("a", _job_cfg(3, causal_trace=True),
                          _builder, lambda r: bs[0][r], rounds=R))
        svc.admit(JobSpec("b", _job_cfg(4, causal_trace=True),
                          _builder, lambda r: bs[1][r], rounds=R))
        svc.run()
        svc.close()
        recs = []
        for p in (led, f"{led}.job0.jsonl", f"{led}.job1.jsonl"):
            recs += [json.loads(line) for line in open(p)]
        traces = assemble_traces(recs)
        for j in range(2):
            for r in range(R):
                t = traces[trace_id(j, r)]
                assert t["orphans"] == [], (j, r, t["orphans"])
                names = {s["name"] for s in t["spans"].values()}
                assert "sched_grant" in names, (j, r, names)
        # admission lands in each tenant's round-0 trace
        assert any(s["name"] == "admission"
                   for s in traces["j0.r0"]["spans"].values())
        assert any(s["name"] == "admission"
                   for s in traces["j1.r0"]["spans"].values())


# --- ledger_merge: the shard matrix + torn tails -----------------------


def _stamp(job, r, extra_spans=()):
    root = _gspan(SEQ_ROOT, "round", 0, 10, parent_seq=None,
                  job=job, r=r)
    root["bucket"] = "host_other"
    return {"trace": trace_id(job, r), "job": job, "round": r,
            "wall": 10.0, "spans": [root] + list(extra_spans)}


class TestLedgerMergeStitch:
    def _write(self, path, recs, torn=False):
        with open(path, "w") as f:
            for rec in recs:
                f.write(json.dumps(rec) + "\n")
            if torn:
                f.write('{"kind": "round", "rou')   # SIGKILL tail

    def test_job_process_matrix_stitches_without_orphans(
            self, tmp_path, capsys):
        lm = _load_script("ledger_merge")
        base = str(tmp_path / "svc.jsonl")
        # canonical service ledger: one round carrying the foreign
        # grant spans for both tenants' round 0
        svc_rec = make_round_record(0)
        grants = []
        for j in range(2):
            g = _gspan(SEQ_GRANT, "sched_grant", 0.0, 0.5,
                       job=j, r=0)
            g["trace"] = trace_id(j, 0)
            grants.append(g)
        svc_rec["causal"] = _stamp("service", 0, grants)
        self._write(base, [svc_rec])
        # job shards: process-0 view has the root + an h2d child;
        # the .p1 sub-shard contributes a gather span for the SAME
        # round (dedup by id must union, not duplicate)
        for j in range(2):
            rec = make_round_record(0)
            rec["causal"] = _stamp(
                j, 0, [_gspan(8, "h2d", 1, 2, job=j, r=0)])
            self._write(f"{base}.job{j}.jsonl", [rec])
            sub = make_round_record(0)
            sub["causal"] = _stamp(
                j, 0, [_gspan(9, "gather", 2, 3, job=j, r=0)])
            # job 1's sub-shard is torn mid-record (host died): the
            # valid prefix must still merge
            self._write(f"{base}.job{j}.jsonl.p1.jsonl", [sub],
                        torn=(j == 1))
        assert lm.main([base]) == 0
        merged = [json.loads(line)
                  for line in open(base + ".merged.jsonl")]
        out = capsys.readouterr()
        assert "causal:" in out.out and " 0 orphan(s)" in out.out
        assert "not JSON" in out.err           # the torn tail warned
        for j in range(2):
            jr = next(r for r in merged if r.get("job") == j
                      and r.get("kind") == "round")
            names = sorted(s["name"]
                           for s in jr["causal"]["spans"])
            assert names == ["gather", "h2d", "round"], names
            # dedup by id: both shards carried the root exactly once
            ids = [s["id"] for s in jr["causal"]["spans"]]
            assert len(ids) == len(set(ids))
        traces = assemble_traces(merged)
        assert sorted(traces) == ["j0.r0", "j1.r0", "jservice.r0"]
        assert all(not t["orphans"] for t in traces.values())
        for j in range(2):
            assert span_id(j, 0, SEQ_GRANT) \
                in traces[trace_id(j, 0)]["spans"]

    def test_orphan_spans_are_warned_not_fatal(self, tmp_path,
                                               capsys):
        lm = _load_script("ledger_merge")
        base = str(tmp_path / "svc.jsonl")
        rec = make_round_record(0)
        # child span whose parent id no shard ever supplies
        lost = _gspan(8, "h2d", 1, 2)
        lost["parent"] = "jsolo.r0.s99"
        rec["causal"] = {"trace": "jsolo.r0", "round": 0,
                         "wall": 10.0, "spans": [lost]}
        self._write(base, [rec])
        self._write(base + ".p1.jsonl", [make_round_record(0)])
        assert lm.main([base]) == 0
        out = capsys.readouterr()
        assert "1 orphan(s)" in out.out
        assert "orphan span(s)" in out.err


# --- flight recorder: critical-path diff on latency alarms -------------


class TestFlightRecorderDiff:
    def _recorder(self, tmp_path, rounds, alarm_rule):
        from commefficient_tpu.telemetry.flightrec import \
            FlightRecorder
        fr = FlightRecorder(Config(), ring_rounds=8,
                            out_dir=str(tmp_path / "pm"))
        for r in range(rounds):
            rec = make_round_record(r)
            slow = 10.0 if r == rounds - 1 else 1.0
            root = _gspan(SEQ_ROOT, "round", 0, slow,
                          parent_seq=None, r=r)
            root["bucket"] = "host_other"
            rec["causal"] = {
                "trace": trace_id(None, r), "job": None, "round": r,
                "wall": slow,
                "spans": [root, _gspan(8, "h2d", 0, 0.5 * slow,
                                       r=r)]}
            if r == rounds - 1:
                rec["alarms"] = [{"rule": alarm_rule, "round": r,
                                  "value": slow, "threshold": 2.0}]
            fr.write(rec)
        return fr

    def test_latency_alarm_bundle_carries_critpath_diff(
            self, tmp_path):
        from commefficient_tpu.telemetry.flightrec import \
            load_postmortem
        fr = self._recorder(tmp_path, 5, "step_time_regression")
        bundle, problems = load_postmortem(fr.last_bundle)
        assert problems == []
        diff = bundle["context"]["critpath_diff"]
        assert diff["round"] == 4
        assert diff["wall"] == pytest.approx(10.0)
        assert diff["base_wall"] == pytest.approx(1.0)
        top = diff["rows"][0]
        assert top["bucket"] in ("h2d", "host_other")
        assert top["delta_s"] == pytest.approx(4.5)
        # the postmortem report renders the diff section
        tr = _load_script("telemetry_report")
        import io
        from contextlib import redirect_stdout
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert tr.postmortem_report(fr.last_bundle, False) == 0
        assert "critical-path diff" in buf.getvalue()

    def test_non_latency_rule_attaches_no_diff(self, tmp_path):
        from commefficient_tpu.telemetry.flightrec import \
            load_postmortem
        fr = self._recorder(tmp_path, 5, "divergence")
        bundle, _ = load_postmortem(fr.last_bundle)
        assert "critpath_diff" not in bundle["context"]

    def test_pre_v7_bundle_renders_graceful_note(self, tmp_path):
        from commefficient_tpu.telemetry.flightrec import (
            FlightRecorder, load_postmortem)
        fr = FlightRecorder(Config(), ring_rounds=4,
                            out_dir=str(tmp_path / "pm"))
        rec = make_round_record(0)   # no causal stamp at all
        rec["alarms"] = [{"rule": "slo_burn", "round": 0,
                          "value": 3.0, "threshold": 1.0}]
        fr.write(rec)
        bundle, _ = load_postmortem(fr.last_bundle)
        assert "critpath_diff" not in bundle["context"]
        tr = _load_script("telemetry_report")
        import io
        from contextlib import redirect_stdout
        buf = io.StringIO()
        with redirect_stdout(buf):
            assert tr.postmortem_report(fr.last_bundle, False) == 0
        assert "no causal data" in buf.getvalue()


# --- consumers: fedwatch + --critpath report ---------------------------


class TestConsumers:
    def test_fedwatch_scrape_path_derives_crit_column(self):
        fw = _load_script("fedwatch")
        jobs = fw.job_table([
            ("commeff_rounds_total", {"job": "0"}, 3.0),
            ("commeff_critpath_seconds",
             {"job": "0", "bucket": "h2d"}, 0.6),
            ("commeff_critpath_seconds",
             {"job": "0", "bucket": "compute"}, 0.4),
            ("commeff_rounds_total", {"job": "1"}, 2.0),
        ])
        assert jobs["0"]["crit"] == "h2d 60%"
        assert "crit" not in jobs["1"]      # untraced job: no column
        assert fw._fmt(jobs["0"]["crit"]) == "h2d 60%"
        table = fw.render_table(jobs)
        assert "crit" in table and "h2d 60%" in table

    def test_fedwatch_ledger_path_derives_crit_column(self, tmp_path):
        fw = _load_script("fedwatch")
        led = str(tmp_path / "svc.jsonl")
        with open(led, "w") as f:
            f.write("\n")
        rec = make_round_record(0)
        rec["causal"] = _stamp(0, 0,
                               [_gspan(8, "h2d", 1, 9, job=0, r=0)])
        with open(f"{led}.job0.jsonl", "w") as f:
            f.write(json.dumps(rec) + "\n")
        jobs = fw.ledger_table(led)
        assert jobs["0"]["crit"] == "h2d 80%"

    def test_report_critpath_explains_and_degrades(self, tmp_path,
                                                   capsys):
        tr = _load_script("telemetry_report")
        recs = []
        for r in range(3):
            rec = make_round_record(r)
            rec["causal"] = _stamp(
                None, r, [_gspan(8, "h2d", 1, 3, r=r)])
            recs.append(rec)
        assert tr.critpath_report(recs, as_json=False) == 0
        out = capsys.readouterr().out
        assert "critical path (3 traced round(s))" in out
        assert "aggregate bucket shares" in out
        # JSON mode round-trips
        assert tr.critpath_report(recs, as_json=True) == 0
        payload = json.loads(capsys.readouterr().out)
        assert len(payload["rounds"]) == 3
        assert payload["aggregate"]["wall_s"] == pytest.approx(30.0)
        # pre-v7 ledger (or off run): graceful note, exit 1
        assert tr.critpath_report([make_round_record(0)],
                                  as_json=False) == 1
        assert "no causal data" in capsys.readouterr().out


# --- the flowlint confinement rule -------------------------------------


class TestConfinement:
    def test_rule_is_registered(self):
        from commefficient_tpu.analysis.lint import \
            FLOW_CHECKERS_BY_NAME
        assert "causal-confinement" in FLOW_CHECKERS_BY_NAME

    def test_jit_reachable_causal_code_flagged(self, tmp_path):
        from commefficient_tpu.analysis.flow import run_flow
        from commefficient_tpu.analysis.lint import \
            FLOW_CHECKERS_BY_NAME

        def tree(files):
            for rel, src in files.items():
                p = tmp_path / rel
                p.parent.mkdir(parents=True, exist_ok=True)
                p.write_text(textwrap.dedent(src))
            return tmp_path

        root = tree({
            "telemetry/causal.py": """
                def mark(x):
                    return x
                """,
            "core/r.py": """
                import jax

                from telemetry.causal import mark

                def build(cfg):
                    def traced(x):
                        return mark(x)
                    return traced

                step = jax.jit(build(None))
                """,
        })
        vs = run_flow(root=root, checkers=[
            FLOW_CHECKERS_BY_NAME["causal-confinement"]])
        assert len(vs) == 1
        assert vs[0].path == "telemetry/causal.py"
        assert "host-side only" in vs[0].message

    def test_host_side_causal_code_is_clean(self, tmp_path):
        from commefficient_tpu.analysis.flow import run_flow
        from commefficient_tpu.analysis.lint import \
            FLOW_CHECKERS_BY_NAME
        p = tmp_path / "telemetry" / "causal.py"
        p.parent.mkdir(parents=True)
        p.write_text(textwrap.dedent("""
            def mark(x):
                return x

            def host_loop():
                return mark(1)
            """))
        (tmp_path / "core").mkdir()
        (tmp_path / "core" / "r.py").write_text(textwrap.dedent("""
            import jax
            import jax.numpy as jnp

            def build(cfg):
                def traced(x):
                    return jnp.sum(x)
                return traced

            step = jax.jit(build(None))
            """))
        vs = run_flow(root=tmp_path, checkers=[
            FLOW_CHECKERS_BY_NAME["causal-confinement"]])
        assert vs == []
