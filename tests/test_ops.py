"""Unit tests for compression ops: top-k, clipping, count-sketch.

Covers what the reference never tested (SURVEY.md §4): sketch
linearity, unbiased recovery, heavy-hitter top-k accuracy, l2estimate.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.ops import CountSketch, clip_by_l2, topk
from commefficient_tpu.ops.sketch import clip_record
from commefficient_tpu.ops.topk import topk_values_indices


class TestTopk:
    def test_1d_keeps_largest_magnitude(self):
        v = jnp.array([1.0, -5.0, 3.0, 0.5, -2.0])
        out = topk(v, 2)
        np.testing.assert_allclose(out, [0.0, -5.0, 3.0, 0.0, 0.0])

    def test_1d_preserves_values_exactly(self):
        rng = np.random.RandomState(0)
        v = jnp.asarray(rng.randn(1000).astype(np.float32))
        out = np.asarray(topk(v, 100))
        nz = np.nonzero(out)[0]
        assert len(nz) == 100
        np.testing.assert_array_equal(out[nz], np.asarray(v)[nz])
        # the kept set is exactly the 100 largest |v|
        thresh = np.sort(np.abs(np.asarray(v)))[-100]
        assert np.all(np.abs(out[nz]) >= thresh)

    def test_2d_rowwise(self):
        v = jnp.array([[1.0, -5.0, 3.0], [0.1, 0.2, -0.3]])
        out = topk(v, 1)
        np.testing.assert_allclose(out, [[0, -5, 0], [0, 0, -0.3]])

    def test_values_indices(self):
        v = jnp.array([1.0, -5.0, 3.0])
        vals, idx = topk_values_indices(v, 2)
        assert set(np.asarray(idx).tolist()) == {1, 2}

    def test_jit_compatible(self):
        f = jax.jit(lambda v: topk(v, 3))
        v = jnp.arange(10.0)
        np.testing.assert_allclose(f(v), topk(v, 3))


class TestThresholdSelect:
    """The exact large-d selection path (_threshold_topk_idx, engaged
    above _THRESHOLD_SELECT_MIN_D): 32 masked count-reductions instead
    of a full sort, same selected SET as lax.top_k including the
    lowest-index tie-break."""

    def test_matches_lax_top_k_set(self):
        from commefficient_tpu.ops.topk import _threshold_topk_idx
        rng = np.random.RandomState(1)
        for d, k in ((4096, 1), (4096, 64), (4096, 4095),
                     (50000, 2000)):
            x = rng.randn(d).astype(np.float32)
            x[rng.randint(0, d, 32)] = 2.5  # magnitude ties
            x[rng.randint(0, d, 32)] = 0.0
            sq = jnp.square(jnp.asarray(x))
            want = set(np.asarray(jax.lax.top_k(sq, k)[1]).tolist())
            got = np.asarray(_threshold_topk_idx(sq, k))
            assert len(set(got.tolist())) == k
            assert set(got.tolist()) == want, (d, k)

    def test_batched_and_vmapped(self):
        from commefficient_tpu.ops.topk import _threshold_topk_idx
        rng = np.random.RandomState(2)
        sq = jnp.square(jnp.asarray(
            rng.randn(3, 8192).astype(np.float32)))
        want = np.asarray(jax.lax.top_k(sq, 100)[1])
        for got in (np.asarray(_threshold_topk_idx(sq, 100)),
                    np.asarray(jax.vmap(
                        lambda s: _threshold_topk_idx(s, 100))(sq))):
            for r in range(3):
                assert set(got[r]) == set(want[r]), r

    def test_all_equal_ties_pick_lowest_indices(self):
        from commefficient_tpu.ops.topk import _threshold_topk_idx
        idx = np.asarray(_threshold_topk_idx(
            jnp.ones(5000, jnp.float32), 7))
        assert idx.tolist() == list(range(7))

    def test_hierarchical_indices_match_lax_top_k(self):
        """threshold_topk_indices (blocked-cumsum compaction, the
        sortless exact selection behind large-d unsketch recovery):
        same selected set as lax.top_k, ascending order, exact k."""
        from commefficient_tpu.ops.topk import threshold_topk_indices
        rng = np.random.RandomState(6)
        for d, k in ((5000, 17), (5000, 1), (100000, 5000),
                     (3000, 2999)):
            x = rng.randn(d).astype(np.float32)
            x[rng.randint(0, d, 60)] = 1.5  # ties
            x[rng.randint(0, d, 60)] = 0.0
            sq = jnp.square(jnp.asarray(x))
            got = np.asarray(threshold_topk_indices(sq, k))
            want = set(np.asarray(jax.lax.top_k(sq, k)[1]).tolist())
            assert len(set(got.tolist())) == k
            assert set(got.tolist()) == want, (d, k)
            assert (np.diff(got) > 0).all()
        # all-equal ties: lowest k indices
        gi = np.asarray(threshold_topk_indices(
            jnp.ones(5000, jnp.float32), 7))
        assert gi.tolist() == list(range(7))

    def test_blocked_cumsum_exact_on_ints(self):
        from commefficient_tpu.ops.topk import _blocked_cumsum
        rng = np.random.RandomState(7)
        x = rng.randint(0, 3, (3, 5000)).astype(np.int32)
        np.testing.assert_array_equal(
            np.asarray(_blocked_cumsum(jnp.asarray(x))),
            np.cumsum(x, -1))

    def test_unsketch_exact_uses_threshold_path(self, monkeypatch):
        """CountSketch.unsketch's exact selection at large d (here
        forced via the threshold override) recovers the same support
        as lax.top_k of the estimates — compared directly against
        lax.top_k, not against a second unsketch call (jit would
        serve the first trace from cache and make that vacuous)."""
        import importlib

        from commefficient_tpu.ops.sketch import CountSketch
        topk_mod = importlib.import_module(
            "commefficient_tpu.ops.topk")

        cs = CountSketch(d=4096, c=256, r=3)
        rng = np.random.RandomState(8)
        table = jnp.asarray(rng.randn(3, 256).astype(np.float32))

        monkeypatch.setattr(topk_mod, "_THRESHOLD_SELECT_MIN_D", 1)
        dense_t, idx_t, vals_t = cs.unsketch(table, 16,
                                             with_support=True)
        est = cs.estimates(table)
        _, idx_want = jax.lax.top_k(jnp.square(est), 16)
        assert set(np.asarray(idx_t).tolist()) \
            == set(np.asarray(idx_want).tolist())
        np.testing.assert_allclose(
            np.asarray(vals_t),
            np.asarray(est)[np.asarray(idx_t)], rtol=1e-6)
        nz = np.nonzero(np.asarray(dense_t))[0]
        assert set(nz.tolist()) <= set(np.asarray(idx_t).tolist())

    def test_engaged_above_threshold_d(self):
        """topk at d >= _THRESHOLD_SELECT_MIN_D goes through the
        threshold path and still keeps exactly the k largest."""
        from commefficient_tpu.ops.topk import _THRESHOLD_SELECT_MIN_D
        d = _THRESHOLD_SELECT_MIN_D
        rng = np.random.RandomState(3)
        v = rng.randn(d).astype(np.float32)
        out = np.asarray(topk(jnp.asarray(v), 500))
        nz = np.nonzero(out)[0]
        assert len(nz) == 500
        np.testing.assert_array_equal(out[nz], v[nz])
        thresh = np.partition(np.abs(v), -500)[-500]
        assert np.all(np.abs(out[nz]) >= thresh)


class TestClip:
    def test_noop_below_clip(self):
        v = jnp.array([0.3, 0.4])  # norm 0.5
        np.testing.assert_allclose(clip_by_l2(v, 1.0), v)

    def test_clips_above(self):
        v = jnp.array([3.0, 4.0])  # norm 5
        out = clip_by_l2(v, 1.0)
        np.testing.assert_allclose(np.linalg.norm(out), 1.0, rtol=1e-6)


class TestCountSketch:
    @pytest.fixture
    def cs(self):
        return CountSketch(d=2048, c=512, r=5, num_blocks=4)

    def test_linearity(self, cs):
        """sketch(a) + sketch(b) == sketch(a + b): required for
        psum-of-sketches to equal the sketch of the summed gradient."""
        rng = np.random.RandomState(1)
        a = jnp.asarray(rng.randn(cs.d).astype(np.float32))
        b = jnp.asarray(rng.randn(cs.d).astype(np.float32))
        np.testing.assert_allclose(
            cs.sketch(a) + cs.sketch(b), cs.sketch(a + b),
            rtol=1e-4, atol=1e-4)

    def test_determinism_across_calls(self, cs):
        v = jnp.asarray(np.random.RandomState(2).randn(cs.d).astype(np.float32))
        np.testing.assert_array_equal(cs.sketch(v), cs.sketch(v))

    def test_scaling(self, cs):
        v = jnp.asarray(np.random.RandomState(3).randn(cs.d).astype(np.float32))
        np.testing.assert_allclose(cs.sketch(2.5 * v), 2.5 * cs.sketch(v),
                                   rtol=1e-4, atol=1e-4)

    def test_heavy_hitter_recovery(self):
        """A sparse signal much larger than the noise floor must be
        recovered at the right coordinates with ~right values."""
        cs = CountSketch(d=10000, c=2000, r=5, num_blocks=5)
        rng = np.random.RandomState(4)
        v = np.zeros(cs.d, np.float32)
        hh_idx = rng.choice(cs.d, 20, replace=False)
        hh_val = rng.randn(20).astype(np.float32) * 100
        v[hh_idx] = hh_val
        v += rng.randn(cs.d).astype(np.float32) * 0.01
        rec = np.asarray(cs.unsketch(cs.sketch(jnp.asarray(v)), k=20))
        assert set(np.nonzero(rec)[0]) == set(hh_idx.tolist())
        np.testing.assert_allclose(rec[hh_idx], hh_val, rtol=0.05, atol=1.0)

    def test_unsketch_exact_when_wide(self):
        """With c >> d and no collisions likely, recovery is exact."""
        cs = CountSketch(d=50, c=4096, r=5, num_blocks=1)
        v = jnp.asarray(np.random.RandomState(5).randn(50).astype(np.float32))
        rec = cs.unsketch(cs.sketch(v), k=50)
        np.testing.assert_allclose(rec, v, rtol=1e-4, atol=1e-4)

    def test_unsketch_k_sparsity(self, cs):
        v = jnp.asarray(np.random.RandomState(6).randn(cs.d).astype(np.float32))
        rec = np.asarray(cs.unsketch(cs.sketch(v), k=64))
        assert np.count_nonzero(rec) <= 64

    def test_estimates_unbiased(self):
        """Mean estimate error across many random seeds ~ 0."""
        rng = np.random.RandomState(7)
        v = np.zeros(500, np.float32)
        v[7] = 10.0
        errs = []
        for seed in range(20):
            cs = CountSketch(d=500, c=50, r=3, num_blocks=1, seed=seed)
            est = np.asarray(cs.estimates(cs.sketch(jnp.asarray(v))))
            errs.append(est[7] - 10.0)
        assert abs(np.mean(errs)) < 1.5

    def test_l2estimate(self):
        cs = CountSketch(d=5000, c=2500, r=5, num_blocks=2)
        v = jnp.asarray(np.random.RandomState(8).randn(cs.d).astype(np.float32))
        true = float(jnp.linalg.norm(v))
        est = float(cs.l2estimate(cs.sketch(v)))
        assert abs(est - true) / true < 0.15

    def test_clip_record_sketch(self):
        cs = CountSketch(d=5000, c=2500, r=5, num_blocks=2)
        v = jnp.asarray(np.random.RandomState(9).randn(cs.d).astype(np.float32))
        table = cs.sketch(v)
        clipped = clip_record(table, 1.0, is_sketch=True)
        assert float(cs.l2estimate(clipped)) <= 1.01

    def test_table_shape_and_jit(self, cs):
        v = jnp.zeros(cs.d)
        f = jax.jit(cs.sketch)
        assert f(v).shape == (cs.r, cs.c)

    def test_hash_quality_uniform(self, cs):
        """Buckets should be near-uniform: chi-square sanity bound."""
        idx = jnp.arange(cs.d, dtype=jnp.int32)
        buckets, signs = cs.hashes(idx)
        counts = np.bincount(np.asarray(buckets[0]), minlength=cs.c)
        expected = cs.d / cs.c
        chi2 = np.sum((counts - expected) ** 2 / expected)
        # dof = c-1; mean c, sd sqrt(2c): allow 5 sd
        assert chi2 < cs.c + 5 * np.sqrt(2 * cs.c)
        assert abs(float(jnp.mean(signs))) < 0.05

    def test_sketch_sparse_matches_dense(self, cs):
        """sketch_sparse(idx, vals) must equal sketch of the dense
        scatter — it replaces the server's O(d) re-sketch of the
        k-sparse recovered update at large d."""
        rng = np.random.RandomState(5)
        idx = rng.choice(cs.d, 64, replace=False).astype(np.int32)
        vals = rng.randn(64).astype(np.float32)
        dense = np.zeros(cs.d, np.float32)
        dense[idx] = vals
        t_dense = np.asarray(cs.sketch(jnp.asarray(dense)))
        t_sparse = np.asarray(cs.sketch_sparse(jnp.asarray(idx),
                                               jnp.asarray(vals)))
        np.testing.assert_allclose(t_dense, t_sparse, rtol=1e-5,
                                   atol=1e-6)

    def test_sketch_sparse_matches_dense_many_rows(self):
        """r > 16 exercises the per-(row, coord) sign fallback in
        hashes()."""
        cs = CountSketch(d=1024, c=128, r=17)
        rng = np.random.RandomState(6)
        idx = rng.choice(cs.d, 32, replace=False).astype(np.int32)
        vals = rng.randn(32).astype(np.float32)
        dense = np.zeros(cs.d, np.float32)
        dense[idx] = vals
        np.testing.assert_allclose(
            np.asarray(cs.sketch(jnp.asarray(dense))),
            np.asarray(cs.sketch_sparse(jnp.asarray(idx),
                                        jnp.asarray(vals))),
            rtol=1e-5, atol=1e-6)

    def test_prefer_sparse_resketch_heuristic(self):
        # GPT-2 flagship geometry: sparse wins
        assert CountSketch(d=124_000_000, c=524288, r=5) \
            .prefer_sparse_resketch(50000)
        # ResNet9 geometry: dense kernel wins
        assert not CountSketch(d=6_600_000, c=524288, r=5) \
            .prefer_sparse_resketch(50000)


class TestKExceedingD:
    def test_topk_k_exceeding_d_is_total(self):
        import jax.numpy as jnp
        from commefficient_tpu.ops.topk import topk

        v = jnp.array([3.0, -1.0, 2.0], jnp.float32)
        np.testing.assert_array_equal(np.asarray(topk(v, k=10)),
                                      np.asarray(v))

    def test_unsketch_k_exceeding_d(self):
        from commefficient_tpu.ops.sketch import CountSketch

        cs = CountSketch(d=50, c=32, r=3, backend="xla")
        v = np.random.RandomState(0).randn(50).astype(np.float32)
        out = cs.unsketch(cs.sketch(v), k=100)  # k > d
        assert out.shape == (50,)


class TestApproxTopk:
    def test_approx_selects_heavy_hitters(self):
        """approx topk keeps ~recall of the true top-k set; selected
        values are preserved exactly and output stays k-sparse."""
        rng = np.random.RandomState(0)
        v = jnp.asarray(rng.randn(100_000).astype(np.float32))
        out = np.asarray(topk(v, 1000, approx=True, recall=0.95))
        nz = np.nonzero(out)[0]
        assert len(nz) <= 1000
        np.testing.assert_array_equal(out[nz], np.asarray(v)[nz])
        true_set = set(np.argsort(np.abs(np.asarray(v)))[-1000:])
        hit = len(true_set & set(nz.tolist())) / 1000
        assert hit >= 0.90  # recall target 0.95 with slack

    def test_approx_2d_rowwise(self):
        rng = np.random.RandomState(1)
        v = jnp.asarray(rng.randn(2, 50_000).astype(np.float32))
        out = np.asarray(topk(v, 500, approx=True))
        assert out.shape == v.shape
        assert all(np.count_nonzero(out[i]) <= 500 for i in range(2))

    def test_approx_with_support_consistent(self):
        from commefficient_tpu.ops.topk import topk_with_support
        rng = np.random.RandomState(2)
        v = jnp.asarray(rng.randn(50_000).astype(np.float32))
        dense, idx, vals = topk_with_support(v, 500, approx=True)
        np.testing.assert_array_equal(
            np.asarray(dense)[np.asarray(idx)], np.asarray(vals))
        np.testing.assert_array_equal(
            np.asarray(vals), np.asarray(v)[np.asarray(idx)])

    def test_exact_default_unchanged(self):
        v = jnp.array([1.0, -5.0, 3.0, 0.5, -2.0])
        np.testing.assert_allclose(topk(v, 2),
                                   [0.0, -5.0, 3.0, 0.0, 0.0])
