"""SPMD correctness: a federated round must produce identical results
whether the client axis is sharded over 8 devices or run on one —
the moral equivalent of the reference's NCCL-vs-single-process
degradation guarantee (fed_aggregator.py:163-169, SURVEY.md §4)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import (ClientStates,
                                           build_client_round,
                                           build_server_round)
from commefficient_tpu.core.server import ServerState
from commefficient_tpu.parallel import client_sharding, make_mesh

from test_modes import linear_loss


def _setup(mode="sketch", **kw):
    base = dict(mode=mode, local_momentum=0.0, virtual_momentum=0.9,
                weight_decay=0.0, error_type="virtual", num_workers=8,
                k=4, num_rows=3, num_cols=32, num_blocks=1,
                grad_size=16, seed=21)
    base.update(kw)
    return Config(**base)


def _batch(W=8, B=3, d=16, seed=0):
    rng = np.random.RandomState(seed)
    return (
        {"x": jnp.asarray(rng.randn(W, B, d).astype(np.float32)),
         "y": jnp.asarray(rng.randn(W, B).astype(np.float32)),
         "mask": jnp.ones((W, B), jnp.float32)},
        jnp.arange(W, dtype=jnp.int32),
    )


def _run_round(cfg, batch, ids, shard=False):
    d = cfg.grad_size
    client_round = jax.jit(build_client_round(cfg, linear_loss,
                                              batch["x"].shape[1]))
    server_round = jax.jit(build_server_round(cfg))
    ps = jnp.zeros(d, jnp.float32).at[0].set(0.5)
    cs = ClientStates.init(cfg, 16, ps)
    ss = ServerState.init(cfg)
    if shard:
        mesh = make_mesh()
        sh = client_sharding(mesh)
        batch = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), batch)
        ids = jax.device_put(ids, sh)
    res = client_round(ps, cs, batch, ids, jax.random.PRNGKey(0), 1.0)
    ps2, ss2, _, upd, _ = server_round(ps, ss, res.aggregated,
                                    jnp.float32(0.01))
    return np.asarray(res.aggregated), np.asarray(ps2)


class TestShardingInvariance:
    def test_sketch_late_shard_map_equals_per_client(self, devices):
        """The device-local-sum-then-sketch fast path (shard_map +
        psum of tables) must equal per-client sketching exactly (the
        FetchSGD linearity identity)."""
        cfg = _setup("sketch")
        batch, ids = _batch(seed=7)
        mesh = make_mesh()

        # fast path with mesh
        fast = jax.jit(build_client_round(
            cfg, linear_loss, batch["x"].shape[1], mesh=mesh))
        # slow path: max_grad_norm forces per-client sketching (its
        # huge value makes the per-sketch clip a no-op)
        slow_cfg = _setup("sketch", max_grad_norm=1e9)
        slow = jax.jit(build_client_round(
            slow_cfg, linear_loss, batch["x"].shape[1]))

        ps = jnp.zeros(cfg.grad_size, jnp.float32).at[0].set(0.5)
        cs = ClientStates.init(cfg, 16, ps)
        sh = client_sharding(mesh)
        sharded = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), batch)
        r_fast = fast(ps, cs, sharded, ids, jax.random.PRNGKey(0), 1.0)
        r_slow = slow(ps, cs, batch, ids, jax.random.PRNGKey(0), 1.0)
        np.testing.assert_allclose(np.asarray(r_fast.aggregated),
                                   np.asarray(r_slow.aggregated),
                                   rtol=1e-4, atol=1e-5)

    def test_sketch_mode(self, devices):
        cfg = _setup("sketch")
        batch, ids = _batch()
        agg_1, ps_1 = _run_round(cfg, batch, ids, shard=False)
        agg_8, ps_8 = _run_round(cfg, batch, ids, shard=True)
        np.testing.assert_allclose(agg_1, agg_8, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(ps_1, ps_8, rtol=1e-5, atol=1e-6)

    def test_true_topk_mode(self, devices):
        cfg = _setup("true_topk", virtual_momentum=0.0)
        batch, ids = _batch(seed=1)
        agg_1, ps_1 = _run_round(cfg, batch, ids, shard=False)
        agg_8, ps_8 = _run_round(cfg, batch, ids, shard=True)
        np.testing.assert_allclose(agg_1, agg_8, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(ps_1, ps_8, rtol=1e-5, atol=1e-6)

    def test_uneven_clients_over_devices(self, devices):
        """W=6 over 8 devices: shard_batch must fall back to
        replication (XLA requires divisibility) and stay exact."""
        from commefficient_tpu.parallel.mesh import shard_batch
        cfg = _setup("uncompressed", error_type="none",
                     num_workers=6)
        batch, ids = _batch(W=6, seed=2)
        agg_1, _ = _run_round(cfg, batch, ids, shard=False)
        mesh = make_mesh()
        batch_r = shard_batch(mesh, batch)
        agg_8, _ = _run_round(cfg, batch_r, ids, shard=False)
        np.testing.assert_allclose(agg_1, agg_8, rtol=1e-5, atol=1e-6)

    def test_client_state_sharded_rows_update(self, devices):
        """Per-client momentum rows sharded over the mesh must update
        exactly as the single-device run (the reference's shared-memory
        client_velocities, fed_aggregator.py:127-129)."""
        cfg = _setup("local_topk", error_type="local",
                     local_momentum=0.9, virtual_momentum=0.0)
        batch, ids = _batch(seed=3)

        def run(shard):
            client_round = jax.jit(
                build_client_round(cfg, linear_loss, 3))
            ps = jnp.zeros(16, jnp.float32)
            cs = ClientStates.init(cfg, 16, ps)
            b, i = batch, ids
            if shard:
                mesh = make_mesh()
                sh = client_sharding(mesh)
                b = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, sh), b)
                cs = ClientStates(
                    jax.device_put(cs.velocities, sh),
                    jax.device_put(cs.errors, sh), None)
            res = client_round(ps, cs, b, i, jax.random.PRNGKey(0), 1.0)
            return (np.asarray(res.client_states.velocities),
                    np.asarray(res.client_states.errors))

        v1, e1 = run(False)
        v8, e8 = run(True)
        np.testing.assert_allclose(v1, v8, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(e1, e8, rtol=1e-5, atol=1e-6)
