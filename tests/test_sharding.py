"""SPMD correctness: a federated round must produce identical results
whether the client axis is sharded over 8 devices or run on one —
the moral equivalent of the reference's NCCL-vs-single-process
degradation guarantee (fed_aggregator.py:163-169, SURVEY.md §4)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import Config
from commefficient_tpu.core.rounds import (ClientStates,
                                           build_client_round,
                                           build_server_round)
from commefficient_tpu.core.server import ServerState
from commefficient_tpu.parallel import client_sharding, make_mesh

from test_modes import linear_loss


def _setup(mode="sketch", **kw):
    base = dict(mode=mode, local_momentum=0.0, virtual_momentum=0.9,
                weight_decay=0.0, error_type="virtual", num_workers=8,
                k=4, num_rows=3, num_cols=32, num_blocks=1,
                grad_size=16, seed=21)
    base.update(kw)
    return Config(**base)


def _batch(W=8, B=3, d=16, seed=0):
    rng = np.random.RandomState(seed)
    return (
        {"x": jnp.asarray(rng.randn(W, B, d).astype(np.float32)),
         "y": jnp.asarray(rng.randn(W, B).astype(np.float32)),
         "mask": jnp.ones((W, B), jnp.float32)},
        jnp.arange(W, dtype=jnp.int32),
    )


def _run_round(cfg, batch, ids, shard=False):
    d = cfg.grad_size
    client_round = jax.jit(build_client_round(cfg, linear_loss,
                                              batch["x"].shape[1]))
    server_round = jax.jit(build_server_round(cfg))
    ps = jnp.zeros(d, jnp.float32).at[0].set(0.5)
    cs = ClientStates.init(cfg, 16, ps)
    ss = ServerState.init(cfg)
    if shard:
        mesh = make_mesh()
        sh = client_sharding(mesh)
        batch = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), batch)
        ids = jax.device_put(ids, sh)
    res = client_round(ps, cs, batch, ids, jax.random.PRNGKey(0), 1.0)
    ps2, ss2, _, upd, _ = server_round(ps, ss, res.aggregated,
                                    jnp.float32(0.01))
    return np.asarray(res.aggregated), np.asarray(ps2)


class TestShardingInvariance:
    def test_sketch_late_shard_map_equals_per_client(self, devices):
        """The device-local-sum-then-sketch fast path (shard_map +
        psum of tables) must equal per-client sketching exactly (the
        FetchSGD linearity identity)."""
        cfg = _setup("sketch")
        batch, ids = _batch(seed=7)
        mesh = make_mesh()

        # fast path with mesh
        fast = jax.jit(build_client_round(
            cfg, linear_loss, batch["x"].shape[1], mesh=mesh))
        # slow path: max_grad_norm forces per-client sketching (its
        # huge value makes the per-sketch clip a no-op)
        slow_cfg = _setup("sketch", max_grad_norm=1e9)
        slow = jax.jit(build_client_round(
            slow_cfg, linear_loss, batch["x"].shape[1]))

        ps = jnp.zeros(cfg.grad_size, jnp.float32).at[0].set(0.5)
        cs = ClientStates.init(cfg, 16, ps)
        sh = client_sharding(mesh)
        sharded = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), batch)
        r_fast = fast(ps, cs, sharded, ids, jax.random.PRNGKey(0), 1.0)
        r_slow = slow(ps, cs, batch, ids, jax.random.PRNGKey(0), 1.0)
        np.testing.assert_allclose(np.asarray(r_fast.aggregated),
                                   np.asarray(r_slow.aggregated),
                                   rtol=1e-4, atol=1e-5)

    def test_sketch_mode(self, devices):
        cfg = _setup("sketch")
        batch, ids = _batch()
        agg_1, ps_1 = _run_round(cfg, batch, ids, shard=False)
        agg_8, ps_8 = _run_round(cfg, batch, ids, shard=True)
        np.testing.assert_allclose(agg_1, agg_8, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(ps_1, ps_8, rtol=1e-5, atol=1e-6)

    def test_true_topk_mode(self, devices):
        cfg = _setup("true_topk", virtual_momentum=0.0)
        batch, ids = _batch(seed=1)
        agg_1, ps_1 = _run_round(cfg, batch, ids, shard=False)
        agg_8, ps_8 = _run_round(cfg, batch, ids, shard=True)
        np.testing.assert_allclose(agg_1, agg_8, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(ps_1, ps_8, rtol=1e-5, atol=1e-6)

    def test_uneven_clients_over_devices(self, devices):
        """W=6 over 8 devices: shard_batch must fall back to
        replication (XLA requires divisibility) and stay exact."""
        from commefficient_tpu.parallel.mesh import shard_batch
        cfg = _setup("uncompressed", error_type="none",
                     num_workers=6)
        batch, ids = _batch(W=6, seed=2)
        agg_1, _ = _run_round(cfg, batch, ids, shard=False)
        mesh = make_mesh()
        batch_r = shard_batch(mesh, batch)
        agg_8, _ = _run_round(cfg, batch_r, ids, shard=False)
        np.testing.assert_allclose(agg_1, agg_8, rtol=1e-5, atol=1e-6)

    def test_client_state_sharded_rows_update(self, devices):
        """Per-client momentum rows sharded over the mesh must update
        exactly as the single-device run (the reference's shared-memory
        client_velocities, fed_aggregator.py:127-129)."""
        cfg = _setup("local_topk", error_type="local",
                     local_momentum=0.9, virtual_momentum=0.0)
        batch, ids = _batch(seed=3)

        def run(shard):
            client_round = jax.jit(
                build_client_round(cfg, linear_loss, 3))
            ps = jnp.zeros(16, jnp.float32)
            cs = ClientStates.init(cfg, 16, ps)
            b, i = batch, ids
            if shard:
                mesh = make_mesh()
                sh = client_sharding(mesh)
                b = jax.tree_util.tree_map(
                    lambda x: jax.device_put(x, sh), b)
                cs = ClientStates(
                    jax.device_put(cs.velocities, sh),
                    jax.device_put(cs.errors, sh), None)
            res = client_round(ps, cs, b, i, jax.random.PRNGKey(0), 1.0)
            return (np.asarray(res.client_states.velocities),
                    np.asarray(res.client_states.errors))

        v1, e1 = run(False)
        v8, e8 = run(True)
        np.testing.assert_allclose(v1, v8, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(e1, e8, rtol=1e-5, atol=1e-6)


class TestFusedMeshPath:
    """Round-1 review item: the fused-gradient fast path must engage
    on multi-device meshes — per-device fused backward over local
    clients + ONE psum (of sketch tables in sketch mode), equal to the
    per-client path."""

    def _compare(self, mode, **kw):
        cfg = _setup(mode, **kw)
        batch, ids = _batch(seed=11)
        B = batch["x"].shape[1]
        mesh = make_mesh()
        fused = jax.jit(build_client_round(cfg, linear_loss, B,
                                           mesh=mesh))
        # microbatch_size=B is a semantic no-op (1 microbatch) that
        # disqualifies the fused path -> per-client reference
        pc_cfg = dataclasses.replace(cfg, microbatch_size=B)
        per_client = jax.jit(build_client_round(pc_cfg, linear_loss,
                                                B))
        ps = jnp.zeros(cfg.grad_size, jnp.float32).at[0].set(0.5)
        cs = ClientStates.init(cfg, 16, ps)
        sh = client_sharding(mesh)
        sharded = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), batch)
        r_f = fused(ps, cs, sharded, ids, jax.random.PRNGKey(0), 1.0)
        r_p = per_client(ps, cs, batch, ids, jax.random.PRNGKey(0),
                         1.0)
        np.testing.assert_allclose(np.asarray(r_f.aggregated),
                                   np.asarray(r_p.aggregated),
                                   rtol=1e-4, atol=1e-6)
        for mf, mp in zip(r_f.metrics, r_p.metrics):
            np.testing.assert_allclose(np.asarray(mf), np.asarray(mp),
                                       rtol=1e-5, atol=1e-6)

    def test_uncompressed_fused_mesh_equals_per_client(self, devices):
        self._compare("uncompressed", error_type="none",
                      weight_decay=5e-4)

    def test_sketch_fused_mesh_equals_per_client(self, devices):
        self._compare("sketch", weight_decay=5e-4)

    def test_true_topk_fused_mesh_equals_per_client(self, devices):
        self._compare("true_topk")

    def test_one_tensor_allreduce_in_compiled_round(self, devices):
        """The compiled fused-mesh round crosses the ICI with exactly
        one tensor all-reduce — of the (r, c) sketch table, not a
        (W, d) gradient buffer (reference one-NCCL-reduce-per-round,
        fed_worker.py:139-140). A second scalar all-reduce (the global
        datapoint total) is allowed."""
        cfg = _setup("sketch")
        batch, ids = _batch(seed=12)
        mesh = make_mesh()
        fused = build_client_round(cfg, linear_loss,
                                   batch["x"].shape[1], mesh=mesh)
        ps = jnp.zeros(cfg.grad_size, jnp.float32)
        cs = ClientStates.init(cfg, 16, ps)
        sh = client_sharding(mesh)
        sharded = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sh), batch)
        txt = jax.jit(fused).lower(
            ps, cs, sharded, ids, jax.random.PRNGKey(0),
            jnp.float32(1.0)).compile().as_text()
        import re
        ars = [l for l in txt.splitlines()
               if re.search(r"all-reduce(-start)?\(", l)]
        table_elems = cfg.num_rows * cfg.num_cols
        big = [l for l in ars if f"f32[{cfg.num_rows},{cfg.num_cols}]"
               in l or f"f32[{table_elems}]" in l]
        assert len(big) == 1, f"want 1 table all-reduce, got:\n" + \
            "\n".join(ars)
        # nothing W*d-sized crosses the interconnect
        assert not any(f"f32[{8 * cfg.grad_size}]" in l or
                       f"f32[8,{cfg.grad_size}]" in l for l in ars)


def test_unsharded_fallback_warns(devices):
    """W % n_devices != 0 must warn (the replication fallback is
    correct but quietly unbalanced — round-1 review)."""
    import warnings

    from commefficient_tpu.parallel import mesh as mesh_mod
    mesh = make_mesh()
    mesh_mod._WARNED_UNSHARDED.clear()
    batch = {"x": jnp.zeros((6, 2))}
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        mesh_mod.shard_batch(mesh, batch)
    assert any("does not divide" in str(x.message) for x in w)
    # divisible batches stay silent
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        mesh_mod.shard_batch(mesh, {"x": jnp.zeros((8, 2))})
    assert not any("does not divide" in str(x.message) for x in w)
