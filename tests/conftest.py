"""Test harness: force an 8-device virtual CPU mesh.

The moral equivalent of the reference's "distributed degrades to
localhost" strategy (SURVEY.md §4): multi-chip sharding is validated on
N virtual CPU devices via --xla_force_host_platform_device_count, no
real pod required. Must run before JAX initialises its backends.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# the container's sitecustomize pre-registers a TPU plugin; this
# overrides it even though the env var was set too late for it.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, devs
    return devs


@pytest.fixture(scope="session")
def package_parse():
    """One timed cold flowlint run (parse + both lint tiers) on the
    real package, shared by test_audit and test_flowlint — the suite
    pays for exactly one engine run. ``elapsed`` is the cold wall
    time, used by the <10 s engine-budget assertion."""
    import time

    from commefficient_tpu.analysis.flow import build_program
    from commefficient_tpu.analysis.lint import run_all

    t0 = time.monotonic()
    program = build_program(None)
    violations = run_all(program=program)
    elapsed = time.monotonic() - t0
    return {"program": program, "violations": violations,
            "elapsed": elapsed}


# --- fast/slow tiers -----------------------------------------------------
# ``pytest -m fast`` is the <2-minute oracle tier: compression-op math,
# server-mode oracles, sharding invariance, accounting, data-layer
# units. The full (unmarked) suite adds the compile-heavy trainer
# end-to-ends; ``-m "not slow"`` skips only the multi-process smokes.

FAST_MODULES = {
    "test_ops",
    "test_accounting",
    "test_audit",
    "test_mesh2d",
    "test_sharding",
    "test_data_breadth",
    "test_telemetry",
}
FAST_CLASSES = {
    "TestHandDerived",        # reference unit_test.py oracle traces
    "TestSparseServerUpdate",
    "TestPersonaInputs",
    "TestFixupLrGroups",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        mod = item.module.__name__.rsplit(".", 1)[-1]
        cls = item.cls.__name__ if item.cls is not None else ""
        if mod in FAST_MODULES or cls in FAST_CLASSES:
            item.add_marker(pytest.mark.fast)
