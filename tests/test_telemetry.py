"""Telemetry subsystem: no-op fast path, record lifecycle/ordering,
JSONL schema, the report script's round-trip + diff, and the
raw-clock grep guard (all host-side timing flows through
``telemetry.clock`` so the ledger is the one source of truth)."""

import importlib.util
import json
import os

import numpy as np
import pytest

from commefficient_tpu.telemetry import (NULL_TELEMETRY, Telemetry,
                                         validate_record)
from commefficient_tpu.telemetry.core import NULL_SPAN
from commefficient_tpu.telemetry.sinks import ConsoleSink, JSONLSink

# --- clock + probe-span guards (now linter rules) ---------------------
# The original grep guards were promoted to first-class rules in the
# analysis/lint.py AST engine (PR 4); these thin wrappers keep the
# guards in tier-1 while leaving one source of truth for each rule.


def _run_rule(name):
    from commefficient_tpu.analysis.lint import (RULES_BY_NAME,
                                                 run_lint, unwaived)
    return unwaived(run_lint(rules=[RULES_BY_NAME[name]]))


def test_no_raw_clocks_outside_telemetry():
    """``time.time()`` / ``perf_counter`` may appear ONLY under
    telemetry/ (clock.py is the one place raw clocks live); everything
    else must go through ``telemetry.clock`` so spans, Timer and the
    ledger agree on what a second is."""
    offenders = _run_rule("raw-clock")
    assert not offenders, (
        "raw clock calls outside commefficient_tpu/telemetry/ "
        "(use telemetry.clock.wall/tick):\n"
        + "\n".join(map(str, offenders)))


def test_probe_host_transfers_only_inside_metrics_host_span():
    """Probe values are materialised (``_host`` / ``jax.device_get``)
    ONLY inside a ``span(\"metrics_host\")`` block: the sync point is
    the probes' entire runtime cost, so it must be ledger-attributed —
    an unspanned transfer would both hide that cost and add a second
    blocking device round-trip per round."""
    offenders = _run_rule("probe-transfer-span")
    assert not offenders, (
        "probe values crossed to the host outside a "
        'span("metrics_host") block:\n'
        + "\n".join(map(str, offenders)))


# --- disabled fast path -----------------------------------------------


def test_disabled_telemetry_is_noop():
    tel = Telemetry()
    assert not tel.enabled
    assert tel.begin_round(0) is None
    # the no-op span is ONE shared object — no per-call allocation
    assert tel.span("h2d") is NULL_SPAN
    assert tel.span("server") is tel.span("gather")
    with tel.span("x"):
        pass
    tel.count("prefetch_hit")
    tel.set_round_bytes(0, 1.0, 2.0)
    tel.epoch({"epoch": 1}, 1)
    tel.close()
    assert NULL_TELEMETRY.span("anything") is NULL_SPAN


def test_disabled_round_retains_nothing():
    tel = Telemetry()
    for r in range(100):
        tel.begin_round(r)
        tel.count("c")
    assert not tel._records and tel._current is None


# --- record lifecycle + JSONL sink ------------------------------------


def test_jsonl_ledger_schema_and_order(tmp_path):
    path = str(tmp_path / "run.jsonl")
    tel = Telemetry([JSONLSink(path)])
    tel.emit_meta(num_clients=4, plan={"mode": "sketch"})
    for r in range(3):
        tel.begin_round(r)
        with tel.span("h2d"):
            pass
        with tel.span("h2d"):  # accumulates, same key
            pass
        tel.count("prefetch_hit")
        tel.set_round_bytes(r, downlink=10.0 * r, uplink=4.0)
    tel.epoch({"epoch": 1, "train_loss": 0.5}, 1)
    tel.close()

    with open(path) as f:
        records = [json.loads(line) for line in f]
    for rec in records:
        assert validate_record(rec) == [], rec
    kinds = [r["kind"] for r in records]
    assert kinds[0] == "meta"
    rounds = [r for r in records if r["kind"] == "round"]
    assert [r["round"] for r in rounds] == [0, 1, 2]
    for r in rounds:
        assert r["spans"]["h2d"] >= 0.0
        assert r["counters"]["prefetch_hit"] == 1
        assert "compile_events" in r["counters"]
        assert r["uplink_bytes"] == 4.0
    assert any(r["kind"] == "epoch" for r in records)


def test_deferred_bytes_preserve_round_order(tmp_path):
    """Pipelined shape: rounds close before their bytes arrive (the
    flush replay attaches them later). Emission must wait and stay in
    round order."""
    path = str(tmp_path / "run.jsonl")
    sink = JSONLSink(path)
    tel = Telemetry([sink])
    tel.begin_round(0)
    tel.begin_round(1)   # closes 0 — but 0 has no bytes yet
    tel.begin_round(2)   # closes 1
    with open(path) as f:
        assert f.read() == ""  # nothing emitted yet
    # bytes arrive out of order: 1 before 0
    tel.set_round_bytes(1, 0.0, 1.0)
    with open(path) as f:
        assert f.read() == ""  # 0 still blocks the front
    tel.set_round_bytes(0, 0.0, 1.0)
    with open(path) as f:
        emitted = [json.loads(x) for x in f]
    assert [r["round"] for r in emitted] == [0, 1]
    tel.set_round_bytes(2, 0.0, 1.0)
    tel.close()
    with open(path) as f:
        emitted = [json.loads(x) for x in f]
    assert [r["round"] for r in emitted] == [0, 1, 2]


def test_close_flushes_byteless_rounds(tmp_path):
    """An aborted run (divergence) never attaches bytes to the last
    rounds; close() must still emit them (bytes stay null) rather
    than dropping the tail."""
    path = str(tmp_path / "run.jsonl")
    tel = Telemetry([JSONLSink(path)])
    tel.begin_round(0)
    tel.close()
    with open(path) as f:
        recs = [json.loads(x) for x in f]
    assert len(recs) == 1 and recs[0]["round"] == 0
    assert recs[0]["uplink_bytes"] is None
    assert validate_record(recs[0]) == []


def test_schema_v4_device_time_round_trip(tmp_path):
    """A fresh round record is schema v4 with ``device_time: None``;
    a populated bucket dict — numeric aggregates plus the v4
    ``per_device``/``skew`` sub-dicts — validates and survives the
    JSONL sink; malformed device_time is caught; v1/v2 (no
    device_time key) and v3 (numeric-only buckets) ledgers stay
    readable."""
    from commefficient_tpu.telemetry.record import (
        READABLE_SCHEMA_VERSIONS, make_round_record)

    assert READABLE_SCHEMA_VERSIONS == (1, 2, 3, 4, 5, 6, 7)
    rec = make_round_record(0)
    assert rec["schema"] == 7 and rec["device_time"] is None
    assert rec["slo"] is None  # v6: the SLO stamp, None unless armed
    assert "causal" not in rec  # v7: OPTIONAL — absent unless traced
    assert validate_record(rec) == []

    rec["device_time"] = {"window_s": 0.01, "busy_s": 0.004,
                          "compute_s": 0.003, "collective_s": 0.0005,
                          "transfer_s": 0.0005, "host_gap_s": 0.006,
                          "roofline_utilization": 0.2,
                          "per_device": {"TPU:0": {
                              "busy_s": 0.004, "wait_s": 0.0001,
                              "wire_s": 0.0004}},
                          "skew": {"n_collectives": 2,
                                   "max_enter_delta_s": 0.0001,
                                   "p95_enter_delta_s": 0.0001,
                                   "straggler_device": "TPU:0"}}
    assert validate_record(rec) == []
    # dict values are allowed ONLY under the v4 sub-dict keys
    bad_dict = dict(rec, device_time={"window_s": {"oops": 1.0}})
    assert any("device_time" in p for p in validate_record(bad_dict))
    # shard records may stamp their process index; it must be an int
    stamped = dict(rec, process=1)
    assert validate_record(stamped) == []
    assert any("process" in p
               for p in validate_record(dict(rec, process="p1")))
    path = str(tmp_path / "v4.jsonl")
    sink = JSONLSink(path)
    sink.write(rec)
    sink.close()
    with open(path) as f:
        back = json.loads(f.read())
    assert validate_record(back) == []
    assert back["device_time"] == rec["device_time"]

    bad = dict(rec, device_time=[1, 2])
    assert any("device_time" in p for p in validate_record(bad))
    bad = dict(rec, device_time={"busy_s": "fast"})
    assert any("device_time" in p for p in validate_record(bad))

    # pre-v3 records never carried the key — still valid
    v2 = {k: v for k, v in make_round_record(1).items()
          if k != "device_time"}
    v2["schema"] = 2
    assert validate_record(v2) == []
    v1 = {k: v for k, v in v2.items()
          if k not in ("probes", "alarms")}
    v1["schema"] = 1
    assert validate_record(v1) == []
    # v3 ledgers (numeric-only buckets, no per_device/skew) read back
    v3 = dict(make_round_record(1), schema=3)
    v3["device_time"] = {"window_s": 0.01, "busy_s": 0.004,
                         "compute_s": 0.003, "collective_s": 0.0005,
                         "transfer_s": 0.0005, "host_gap_s": 0.006}
    assert validate_record(v3) == []
    # ...but a v3+/v4 record MUST carry the key
    v4_missing = {k: v for k, v in make_round_record(2).items()
                  if k != "device_time"}
    assert any("device_time" in p
               for p in validate_record(v4_missing))


def test_console_sink_aggregates(capsys):
    tel = Telemetry([ConsoleSink()])
    for r in range(2):
        tel.begin_round(r)
        with tel.span("server"):
            pass
        tel.set_round_bytes(r, downlink=2 ** 20, uplink=2 ** 20)
    tel.close()
    out = capsys.readouterr().out
    assert "telemetry summary (2 rounds)" in out
    assert "span server" in out
    assert "up 2.0 MiB" in out


def test_json_default_handles_numpy(tmp_path):
    path = str(tmp_path / "np.jsonl")
    sink = JSONLSink(path)
    sink.write({"schema": 1, "kind": "bench", "ts": 0.0,
                "metric": "m", "unit": "u",
                "value": np.float32(1.5), "n": np.int64(3),
                "arr": np.arange(2)})
    sink.close()
    with open(path) as f:
        rec = json.load(f)
    assert rec["value"] == 1.5 and rec["n"] == 3 and rec["arr"] == [0, 1]


# --- report script round-trip -----------------------------------------


def _load_report_module():
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "telemetry_report.py")
    spec = importlib.util.spec_from_file_location("telemetry_report",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_ledger(path, n_rounds, ms_per_round, bytes_per_round):
    tel = Telemetry([JSONLSink(str(path))])
    tel.emit_meta(num_clients=4,
                  plan={"mode": "sketch", "grad_size": 10,
                        "num_workers": 2})
    for r in range(n_rounds):
        rec = tel.begin_round(r)
        rec["spans"]["server"] = ms_per_round / 1e3
        tel.set_round_bytes(r, bytes_per_round, bytes_per_round)
    tel.close()


def test_report_summarize_round_trips(tmp_path):
    report = _load_report_module()
    path = tmp_path / "a.jsonl"
    _write_ledger(path, n_rounds=3, ms_per_round=10.0,
                  bytes_per_round=100.0)
    records, problems = report.load_ledger(str(path))
    assert problems == []
    s = report.summarize(records)
    assert s["rounds"] == 3
    assert s["uplink_bytes"] == 300.0
    assert s["spans"]["server"]["mean_ms"] == 10.0
    text = report.render_summary(s)
    assert "rounds: 3" in text and "span server" in text


def test_report_diff(tmp_path):
    report = _load_report_module()
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write_ledger(a, n_rounds=2, ms_per_round=10.0,
                  bytes_per_round=100.0)
    _write_ledger(b, n_rounds=2, ms_per_round=20.0,
                  bytes_per_round=50.0)
    sa = report.summarize(report.load_ledger(str(a))[0])
    sb = report.summarize(report.load_ledger(str(b))[0])
    d = report.diff_summaries(sa, sb)
    assert d["spans"]["server"]["ratio"] == 2.0
    assert d["uplink_bytes"]["ratio"] == 0.5
    text = report.render_diff(d, "a", "b")
    assert "span server" in text


def test_report_privacy_section(tmp_path):
    """DP runs: the report carries the ε trajectory and the
    noise-vs-recovery-error pairing; diff shows ε spent a -> b."""
    report = _load_report_module()

    def write(path, sigma, eps_per_round, err):
        tel = Telemetry([JSONLSink(str(path))])
        for r in range(3):
            tel.begin_round(r)
            tel.merge_round_probes(r, {"recovery_error": err})
            tel.set_round_privacy(r, eps_per_round * (r + 1), 1e-5,
                                  sigma)
            tel.set_round_bytes(r, 10.0, 10.0)
        tel.close()

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    write(a, sigma=0.5, eps_per_round=0.1, err=0.2)
    write(b, sigma=1.0, eps_per_round=0.05, err=0.4)
    sa = report.summarize(report.load_ledger(str(a))[0])
    pv = sa["privacy"]
    assert pv["rounds"] == 3
    assert pv["eps_first"] == pytest.approx(0.1)
    assert pv["eps_last"] == pytest.approx(0.3)
    assert pv["delta"] == pytest.approx(1e-5)
    assert pv["noise_vs_recovery"] == [
        {"dp_sigma": 0.5, "rounds": 3,
         "recovery_err_mean": pytest.approx(0.2),
         "recovery_err_max": pytest.approx(0.2)}]
    text = report.render_summary(sa)
    assert "privacy: eps 0.1 -> 0.3" in text
    assert "privacy sigma 0.5" in text
    sb = report.summarize(report.load_ledger(str(b))[0])
    d = report.diff_summaries(sa, sb)
    assert d["privacy"]["a_eps_last"] == pytest.approx(0.3)
    assert d["privacy"]["b_eps_last"] == pytest.approx(0.15)
    assert "privacy eps spent" in report.render_diff(d, "a", "b")
    # dp-less ledgers: no privacy section, no diff entry
    c = tmp_path / "c.jsonl"
    _write_ledger(c, n_rounds=2, ms_per_round=1.0,
                  bytes_per_round=1.0)
    sc = report.summarize(report.load_ledger(str(c))[0])
    assert sc["privacy"] is None
    assert "privacy" not in report.diff_summaries(sc, sc)


def test_report_flags_invalid_lines(tmp_path):
    report = _load_report_module()
    path = tmp_path / "bad.jsonl"
    path.write_text('not json\n{"schema": 99, "kind": "round"}\n'
                    + json.dumps({"schema": 1, "kind": "meta",
                                  "ts": 0.0}) + "\n")
    records, problems = report.load_ledger(str(path))
    assert len(records) == 1
    assert len(problems) == 2


# --- prefetch worker-death surfacing ----------------------------------


def test_prefetch_worker_death_surfaces():
    """An exception that escapes the worker LOOP (not a per-job
    gather error) must raise on the main thread at the next take(),
    not stall the round out to the take timeout."""
    import pytest

    from commefficient_tpu.clientstore.prefetch import StorePrefetcher

    class EvilStore:
        def gather(self, ids, out=None):
            raise MemoryError("host arena exhausted")

    pf = StorePrefetcher(EvilStore())
    try:
        # malformed job: unpack fails OUTSIDE the per-job try
        pf._jobs.put("not-a-tuple")
        pf._pending += 1
        pf._thread.join(timeout=5.0)
        assert not pf._thread.is_alive()
        with pytest.raises(RuntimeError, match="prefetch worker died"):
            pf.take(np.array([0], np.int64), timeout=5.0)
        with pytest.raises(RuntimeError, match="prefetch worker died"):
            pf.submit(np.array([1], np.int64))
    finally:
        pf.close(timeout=1.0)


def test_prefetch_per_job_error_still_raises_via_take():
    """Per-job store errors keep the existing surfacing path: the
    exception rides the done-queue and re-raises in take()."""
    import pytest

    from commefficient_tpu.clientstore.prefetch import StorePrefetcher

    class EvilStore:
        def gather(self, ids, out=None):
            raise MemoryError("host arena exhausted")

        def row_version(self, cid):
            return 0

    pf = StorePrefetcher(EvilStore())
    try:
        pf.submit(np.array([0, 1], np.int64))
        with pytest.raises(MemoryError, match="arena exhausted"):
            pf.take(np.array([0, 1], np.int64), timeout=5.0)
    finally:
        pf.close(timeout=1.0)
