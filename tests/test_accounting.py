"""Download/upload byte accounting: the round-histogram structure vs
a brute-force ``last_updated > last_seen`` compare (the semantics of
reference fed_aggregator.py:171-196, 240-300 under this framework's
last-updated-round simplification — see runtime/fed_model.py module
docstring)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu import accounting
from commefficient_tpu.config import Config
from commefficient_tpu.runtime import FedModel

# downloads ship values as f32 under the dense encoding
VAL_BYTES = accounting.bytes_of(1, "f32")


def make_model(grad_size=50, num_clients=6):
    import flax.linen as nn

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(grad_size // 2, use_bias=False)(x)

    module = Lin()
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 2)))[
        "params"]
    args = Config(mode="uncompressed", error_type="none",
                  local_momentum=0.0, num_workers=2,
                  local_batch_size=2, num_clients=num_clients,
                  dataset_name="CIFAR10", seed=0)

    def loss(p, batch, cfg):
        return jnp.float32(0.0), ()

    return FedModel(module, params, loss, args)


class BruteForce:
    """Reference implementation: dense last_updated compare."""

    def __init__(self, grad_size, num_clients):
        self.last_updated = np.full(grad_size, -1, np.int64)
        self.last_seen = np.full(num_clients, -1, np.int64)
        self.round = 0

    def note(self, changed_idx):
        self.round += 1
        self.last_updated[changed_idx] = self.round

    def download(self, ids):
        out = np.array([VAL_BYTES * np.sum(self.last_updated
                                           > self.last_seen[c])
                        for c in ids])
        self.last_seen[ids] = self.round
        return out


def test_sparse_support_matches_brute_force():
    rng = np.random.RandomState(0)
    m = make_model()
    d = m.args.grad_size
    bf = BruteForce(d, m.num_clients)
    for _ in range(40):
        k = rng.randint(1, 10)
        idx = rng.choice(d, k, replace=False)
        vals = rng.randn(k)
        vals[rng.rand(k) < 0.3] = 0.0  # zero values don't count
        m.note_update((idx, vals))
        bf.note(idx[vals != 0])
        ids = rng.choice(m.num_clients, 2, replace=False)
        got, _ = m._account_bytes(ids)
        want = bf.download(ids)
        np.testing.assert_array_equal(got[ids], want)


def test_dense_none_marks_everything():
    m = make_model()
    d = m.args.grad_size
    m.note_update(None)
    got, _ = m._account_bytes(np.array([0, 3]))
    np.testing.assert_array_equal(got[[0, 3]], [4.0 * d, 4.0 * d])
    # same clients sync again with no new update: nothing to download
    got2, _ = m._account_bytes(np.array([0, 3]))
    np.testing.assert_array_equal(got2[[0, 3]], [0.0, 0.0])


def test_dense_array_host_compare():
    m = make_model()
    d = m.args.grad_size
    upd = np.zeros(d, np.float32)
    upd[[2, 5, 7]] = 1.0
    m.note_update(upd)
    got, _ = m._account_bytes(np.array([1]))
    assert got[1] == 4.0 * 3


def test_bitmap_support_matches_dense_compare():
    """The packed-bitmap support form (what local_topk ships instead
    of the dense f32 update) must mark exactly the nonzero coords."""
    m = make_model()
    d = m.args.grad_size
    upd = np.zeros(d, np.float32)
    upd[[2, 5, 7, 31]] = 1.0
    m.note_update({"bitmap": jnp.packbits(jnp.asarray(upd) != 0)})
    got, _ = m._account_bytes(np.array([1]))
    assert got[1] == 4.0 * 4

    m2 = make_model()
    m2.note_update(upd)
    got2, _ = m2._account_bytes(np.array([1]))
    assert got2[1] == got[1]


def test_empty_support_changes_nothing():
    m = make_model()
    m.note_update((np.zeros(0, np.int64), np.zeros(0)))
    got, _ = m._account_bytes(np.array([2]))
    assert got[2] == 0.0


def test_rebuild_round_counts_is_lossless():
    rng = np.random.RandomState(1)
    m = make_model()
    d = m.args.grad_size
    for _ in range(10):
        idx = rng.choice(d, 5, replace=False)
        m.note_update((idx, rng.randn(5)))
        m._account_bytes(rng.choice(m.num_clients, 2, replace=False))
    counts_before = m._round_counts[:m._update_round + 2].copy()
    m._rebuild_round_counts()  # what checkpoint restore runs
    np.testing.assert_array_equal(
        counts_before, m._round_counts[:m._update_round + 2])


def test_local_topk_virtual_momentum_sparse_download():
    """local_topk with virtual_momentum > 0 must still account
    downloads by value-comparing the dense update (reference compares
    weight_update != 0, fed_aggregator.py:240-300): the update support
    is only the union of past top-k selections, so a first-round
    download is ~W*k coords, not grad_size."""
    import flax.linen as nn

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(64, use_bias=False)(x)

    module = Lin()
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 32)))[
        "params"]
    args = Config(mode="local_topk", error_type="local", k=5,
                  local_momentum=0.9, virtual_momentum=0.9,
                  num_workers=2, local_batch_size=2, num_clients=6,
                  dataset_name="CIFAR10", seed=0)

    def loss(p, batch, cfg):
        pred = module.apply({"params": p}, batch["x"])
        per = jnp.sum((pred - batch["y"][..., None]) ** 2, -1)
        n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
        return jnp.sum(per * batch["mask"]) / n, ()

    from commefficient_tpu.runtime import FedOptimizer
    m = FedModel(module, params, loss, args)
    opt = FedOptimizer([{"lr": 0.1}], args)
    d = args.grad_size
    rng = np.random.RandomState(0)
    batch = {"x": rng.randn(2, 2, 32).astype(np.float32),
             "y": rng.randn(2, 2).astype(np.float32),
             "mask": np.ones((2, 2), np.float32),
             "client_ids": np.array([0, 1], np.int32)}
    m(batch)
    opt.step()
    got, _ = m._account_bytes(np.array([5]))
    # support after one round is at most num_workers * k coords
    assert 0 < got[5] <= 4.0 * args.num_workers * args.k
    assert got[5] < 4.0 * d


def make_delta_model(wire="int8", grad_size=64, num_clients=6):
    import flax.linen as nn

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(grad_size // 2, use_bias=False)(x)

    module = Lin()
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 2)))[
        "params"]
    args = Config(mode="sketch", error_type="virtual",
                  local_momentum=0.0, virtual_momentum=0.9,
                  num_rows=2, num_cols=16, num_blocks=1, k=3,
                  num_workers=2, local_batch_size=2,
                  num_clients=num_clients, dataset_name="CIFAR10",
                  seed=0, sketch_dtype=wire,
                  downlink_encoding="delta")

    def loss(p, batch, cfg):
        return jnp.float32(0.0), ()

    return FedModel(module, params, loss, args)


@pytest.mark.parametrize("wire", ["f32", "int8"])
def test_delta_downlink_matches_brute_force(wire):
    """--downlink_encoding delta vs a dense-history brute force: per
    client, changed values ship at the wire width, indices (int32)
    only for coords NOT in the previous broadcast's support, repeats
    as one bitmap bit per previous-support coord — and only clients
    that saw the previous broadcast get to delta-code at all."""
    rng = np.random.RandomState(3)
    m = make_delta_model(wire=wire)
    d = m.args.grad_size
    wb = accounting.dtype_bytes(wire)
    idx_b = accounting.dtype_bytes(np.int32)

    last_updated = np.full(d, -1, np.int64)
    last_seen = np.full(m.num_clients, -1, np.int64)
    prev_vec = np.zeros(d, bool)  # previous update's support
    repeated = 0
    bitmap_bits = 0
    rnd = 0
    for _ in range(40):
        if rng.rand() < 0.15:
            sup = None  # dense update
            vec = np.ones(d, bool)
            m.note_update(None)
        else:
            k = rng.randint(1, 10)
            sup = np.sort(rng.choice(d, k, replace=False))
            vec = np.zeros(d, bool)
            vec[sup] = True
            m.note_update((sup, np.ones(len(sup))))
        rnd += 1
        repeated = int((vec & prev_vec).sum())
        bitmap_bits = int(prev_vec.sum())
        prev_vec = vec
        last_updated[vec] = rnd

        ids = rng.choice(m.num_clients, 2, replace=False)
        got, _ = m._account_bytes(ids)
        for c in ids:
            changed = int(np.sum(last_updated > last_seen[c]))
            if last_seen[c] == rnd - 1:  # saw the previous broadcast
                want = (changed * wb
                        + (changed - repeated) * idx_b
                        + int(np.ceil(bitmap_bits / 8)))
            else:
                want = changed * (wb + idx_b)
            assert got[c] == want, (wire, rnd, c, got[c], want)
            last_seen[c] = rnd


def test_delta_downlink_stale_client_pays_full_indices():
    """A client that skipped a broadcast cannot delta-code: every
    changed coord ships (idx, val) with no bitmap."""
    m = make_delta_model(wire="int8")
    d = m.args.grad_size
    idx = np.arange(5)
    m.note_update((idx, np.ones(5)))
    # client 0 syncs at round 1; client 1 stays stale
    m._account_bytes(np.array([0]))
    m.note_update((idx, np.ones(5)))  # identical support: all repeats
    got, _ = m._account_bytes(np.array([0, 1]))
    # fresh client: 5 values + 0 fresh indices + ceil(5/8)=1 bitmap
    assert got[0] == 5 * 1 + 0 * 4 + 1
    # stale client: both rounds' union is still those 5 coords, but
    # nothing delta-codes — 5 x (idx + val)
    assert got[1] == 5 * (4 + 1)


class TestLedgerMatchesBruteForce:
    """Full-stack mode matrix: run a real FedModel + FedOptimizer for
    3 rounds with the JSONL ledger sink attached, and assert each
    round record's uplink/downlink totals equal (a) the accounting
    arrays model(batch) returned and (b) an independent brute-force
    compare of the server weights before/after each step (the
    reference's value-compare semantics). Covers every compression
    mode, not just uncompressed."""

    MODES = {
        "uncompressed": dict(mode="uncompressed", error_type="none",
                             local_momentum=0.0,
                             virtual_momentum=0.9),
        "sketch": dict(mode="sketch", error_type="virtual",
                       local_momentum=0.0, virtual_momentum=0.9,
                       num_rows=2, num_cols=16, num_blocks=1, k=3),
        # quantized wire lattice: the ledger's uplink total must price
        # the table at the wire width plus the f32 row scales, never
        # at a hardcoded 4 bytes/element
        "sketch_bf16": dict(mode="sketch", error_type="virtual",
                            local_momentum=0.0, virtual_momentum=0.9,
                            num_rows=2, num_cols=16, num_blocks=1,
                            k=3, sketch_dtype="bf16"),
        "sketch_int8": dict(mode="sketch", error_type="virtual",
                            local_momentum=0.0, virtual_momentum=0.9,
                            num_rows=2, num_cols=16, num_blocks=1,
                            k=3, sketch_dtype="int8"),
        "sketch_fp8": dict(mode="sketch", error_type="virtual",
                           local_momentum=0.0, virtual_momentum=0.9,
                           num_rows=2, num_cols=16, num_blocks=1,
                           k=3, sketch_dtype="fp8"),
        "true_topk": dict(mode="true_topk", error_type="virtual",
                          local_momentum=0.0, virtual_momentum=0.9,
                          k=3),
        "local_topk": dict(mode="local_topk", error_type="local",
                           local_momentum=0.9, virtual_momentum=0.9,
                           k=3),
        "fedavg": dict(mode="fedavg", error_type="none",
                       local_momentum=0.0, local_batch_size=-1),
    }

    @pytest.mark.parametrize("mode", sorted(MODES))
    def test_round_bytes_match(self, mode, tmp_path):
        import flax.linen as nn

        from commefficient_tpu.runtime import FedOptimizer
        from commefficient_tpu.telemetry.record import validate_record

        class Lin(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(4, use_bias=False)(x)

        module = Lin()
        params = module.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 3)))["params"]
        ledger = str(tmp_path / "ledger.jsonl")
        kw = dict(self.MODES[mode])
        kw.setdefault("local_batch_size", 2)
        args = Config(num_workers=2, num_clients=5,
                      dataset_name="CIFAR10", seed=0, ledger=ledger,
                      **kw)

        def loss(p, batch, cfg):
            pred = module.apply({"params": p}, batch["x"])
            per = jnp.sum((pred - batch["y"][..., None]) ** 2, -1)
            n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
            return jnp.sum(per * batch["mask"]) / n, ()

        model = FedModel(module, params, loss, args,
                         padded_batch_size=2)
        opt = FedOptimizer([{"lr": 0.1}], args)
        bf = BruteForce(args.grad_size, args.num_clients)
        rng = np.random.RandomState(7)
        returned = []  # (down_total, up_total) per round
        for _ in range(3):
            ids = rng.choice(5, 2, replace=False).astype(np.int32)
            batch = {"x": rng.randn(2, 2, 3).astype(np.float32),
                     "y": rng.randn(2, 2).astype(np.float32),
                     "mask": np.ones((2, 2), np.float32),
                     "client_ids": ids}
            w_before = np.asarray(model.ps_weights)
            out = model(batch)
            down, up = out[-2], out[-1]
            # the model accounts the download BEFORE this round's
            # server update lands (end of the client pass) — mirror
            want_down = bf.download(ids)
            np.testing.assert_array_equal(down[ids], want_down)
            # dtype-aware uplink: wire-width table (+ f32 row scales
            # for the scaled dtypes), f32 floats everywhere else
            assert up.sum() == 2 * args.upload_wire_bytes_per_client
            if mode == "sketch_int8":
                assert args.upload_wire_bytes_per_client == \
                    accounting.sketch_wire_bytes(2, 16, "int8")
                assert up.sum() < \
                    VAL_BYTES * 2 * args.upload_floats_per_client
            opt.step()
            w_after = np.asarray(model.ps_weights)
            bf.note(np.nonzero(w_before != w_after)[0])
            returned.append((float(down.sum()), float(up.sum())))
        model.finalize()

        with open(ledger) as f:
            records = [json.loads(line) for line in f]
        for rec in records:
            assert validate_record(rec) == [], rec
        rounds = [r for r in records if r["kind"] == "round"]
        assert [r["round"] for r in rounds] == [0, 1, 2]
        for rec, (down_total, up_total) in zip(rounds, returned):
            assert rec["downlink_bytes"] == down_total
            assert rec["uplink_bytes"] == up_total


class TestPipelinedFlush:
    """Multi-round pipeline replay: interleaved account/note ops and
    pending alignment across several rounds of a real FedModel, vs a
    synchronous twin (the --test CLI path only ever runs one round per
    epoch, so the replay machinery is exercised here)."""

    def _run(self, depth, n_rounds=7, seed=3):
        import flax.linen as nn

        class Lin(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(4, use_bias=False)(x)

        module = Lin()
        params = module.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 3)))["params"]
        args = Config(mode="true_topk", error_type="virtual", k=3,
                      local_momentum=0.0, virtual_momentum=0.9,
                      num_workers=2, local_batch_size=2,
                      num_clients=5, dataset_name="CIFAR10", seed=0,
                      pipeline_depth=depth)

        def loss(p, batch, cfg):
            pred = module.apply({"params": p}, batch["x"])
            per = jnp.sum((pred - batch["y"][..., None]) ** 2, -1)
            n = jnp.maximum(jnp.sum(batch["mask"]), 1.0)
            return jnp.sum(per * batch["mask"]) / n, ()

        from commefficient_tpu.runtime import (FedOptimizer,
                                               drain_rounds)
        model = FedModel(module, params, loss, args)
        opt = FedOptimizer([{"lr": 0.1}], args)
        rng = np.random.RandomState(seed)
        outputs = []

        def process(metrics, i):
            outputs.append((i, [np.asarray(m) for m in metrics]))
            return True

        pending = []
        for i in range(n_rounds):
            batch = {
                "x": rng.randn(2, 2, 3).astype(np.float32),
                "y": rng.randn(2, 2).astype(np.float32),
                "mask": np.ones((2, 2), np.float32),
                "client_ids": rng.choice(5, 2,
                                         replace=False).astype(np.int32),
            }
            out = model(batch)
            opt.step()
            if out is None:
                pending.append((i,))
                assert drain_rounds(model, pending, process,
                                    force=False)
            else:
                process(out, i)
        assert drain_rounds(model, pending, process, force=True)
        assert not pending
        return outputs, np.asarray(model.ps_weights)

    def test_depth3_matches_sync(self):
        sync, w_sync = self._run(depth=1)
        piped, w_piped = self._run(depth=3)
        assert [i for i, _ in sync] == [i for i, _ in piped]
        np.testing.assert_array_equal(w_sync, w_piped)
        for (i, ms), (j, mp) in zip(sync, piped):
            for a, b in zip(ms, mp):
                np.testing.assert_array_equal(a, b)

    def test_checkpoint_refuses_inflight(self, tmp_path):
        import pytest as _pytest

        from commefficient_tpu.runtime.checkpoint import save_checkpoint
        # a model with one round inflight at depth 2
        import flax.linen as nn

        class Lin(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(4, use_bias=False)(x)

        module = Lin()
        params = module.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 3)))["params"]
        args = Config(mode="uncompressed", error_type="none",
                      local_momentum=0.0, num_workers=2,
                      local_batch_size=2, num_clients=5,
                      dataset_name="CIFAR10", seed=0,
                      pipeline_depth=2)

        def loss(p, batch, cfg):
            return jnp.float32(0.0), ()

        from commefficient_tpu.runtime import FedOptimizer
        model = FedModel(module, params, loss, args)
        opt = FedOptimizer([{"lr": 0.1}], args)
        batch = {"x": np.zeros((2, 2, 3), np.float32),
                 "y": np.zeros((2, 2), np.float32),
                 "mask": np.ones((2, 2), np.float32),
                 "client_ids": np.array([0, 1], np.int32)}
        assert model(batch) is None
        opt.step()
        with _pytest.raises(RuntimeError, match="inflight"):
            save_checkpoint(str(tmp_path / "c.npz"), model, opt)
        model.flush(force=True)
        save_checkpoint(str(tmp_path / "c.npz"), model, opt)  # now ok
