"""Download/upload byte accounting: the round-histogram structure vs
a brute-force ``last_updated > last_seen`` compare (the semantics of
reference fed_aggregator.py:171-196, 240-300 under this framework's
last-updated-round simplification — see runtime/fed_model.py module
docstring)."""

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.config import Config
from commefficient_tpu.runtime import FedModel


def make_model(grad_size=50, num_clients=6):
    import flax.linen as nn

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(grad_size // 2, use_bias=False)(x)

    module = Lin()
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 2)))[
        "params"]
    args = Config(mode="uncompressed", error_type="none",
                  local_momentum=0.0, num_workers=2,
                  local_batch_size=2, num_clients=num_clients,
                  dataset_name="CIFAR10", seed=0)

    def loss(p, batch, cfg):
        return jnp.float32(0.0), ()

    return FedModel(module, params, loss, args)


class BruteForce:
    """Reference implementation: dense last_updated compare."""

    def __init__(self, grad_size, num_clients):
        self.last_updated = np.full(grad_size, -1, np.int64)
        self.last_seen = np.full(num_clients, -1, np.int64)
        self.round = 0

    def note(self, changed_idx):
        self.round += 1
        self.last_updated[changed_idx] = self.round

    def download(self, ids):
        out = np.array([4.0 * np.sum(self.last_updated
                                     > self.last_seen[c])
                        for c in ids])
        self.last_seen[ids] = self.round
        return out


def test_sparse_support_matches_brute_force():
    rng = np.random.RandomState(0)
    m = make_model()
    d = m.args.grad_size
    bf = BruteForce(d, m.num_clients)
    for _ in range(40):
        k = rng.randint(1, 10)
        idx = rng.choice(d, k, replace=False)
        vals = rng.randn(k)
        vals[rng.rand(k) < 0.3] = 0.0  # zero values don't count
        m.note_update((idx, vals))
        bf.note(idx[vals != 0])
        ids = rng.choice(m.num_clients, 2, replace=False)
        got, _ = m._account_bytes(ids)
        want = bf.download(ids)
        np.testing.assert_array_equal(got[ids], want)


def test_dense_none_marks_everything():
    m = make_model()
    d = m.args.grad_size
    m.note_update(None)
    got, _ = m._account_bytes(np.array([0, 3]))
    np.testing.assert_array_equal(got[[0, 3]], [4.0 * d, 4.0 * d])
    # same clients sync again with no new update: nothing to download
    got2, _ = m._account_bytes(np.array([0, 3]))
    np.testing.assert_array_equal(got2[[0, 3]], [0.0, 0.0])


def test_dense_array_host_compare():
    m = make_model()
    d = m.args.grad_size
    upd = np.zeros(d, np.float32)
    upd[[2, 5, 7]] = 1.0
    m.note_update(upd)
    got, _ = m._account_bytes(np.array([1]))
    assert got[1] == 4.0 * 3


def test_empty_support_changes_nothing():
    m = make_model()
    m.note_update((np.zeros(0, np.int64), np.zeros(0)))
    got, _ = m._account_bytes(np.array([2]))
    assert got[2] == 0.0


def test_rebuild_round_counts_is_lossless():
    rng = np.random.RandomState(1)
    m = make_model()
    d = m.args.grad_size
    for _ in range(10):
        idx = rng.choice(d, 5, replace=False)
        m.note_update((idx, rng.randn(5)))
        m._account_bytes(rng.choice(m.num_clients, 2, replace=False))
    counts_before = m._round_counts[:m._update_round + 2].copy()
    m._rebuild_round_counts()  # what checkpoint restore runs
    np.testing.assert_array_equal(
        counts_before, m._round_counts[:m._update_round + 2])
